"""simtwin: cross-plane protocol-equivalence static analysis.

The simulator's protocol logic exists three times: the Python modules
(authoritative), the hand-transcribed native C data plane, and the
JAX/numpy kernel family.  Runtime digest tests keep them honest — hours
into a run.  simtwin fails the drift at LINT time instead: three
extractors (Python AST, cspec's regex+brace C reader, the kernel dtype
pass) feed one table-driven IR, and the SIM2xx rules diff the planes:

=======  ========  ====================================================
SIM201   error     protocol constant / threshold drift between twins
SIM202   error     TCP state-transition table drift
SIM203   error     twin missing a mapped counterpart surface
                   ([tool.simtwin.map] in pyproject.toml)
SIM204   error     dtype/overflow hazard in a device kernel
SIM205   error     simgen-generated region hand-edited or stale
                   (vs spec/protocol_spec.json; see analysis/simgen.py)
=======  ========  ====================================================

Usage::

    python -m shadow_tpu.analysis.simtwin [paths...] [--json]
        [--list-rules] [--config pyproject.toml] [--diff BASE]
        [--emit-spec [PATH]]

Exit status: 0 = no unsuppressed findings, 1 = findings, 2 = usage error.

Everything else is shared with simlint/simrace: severity model, JSON
schema (``"tool": "simtwin"``), ``[tool.simlint.allow]`` allowlists, and
the pragma vocabulary — ``# simtwin: disable=SIM2xx -- <why>`` in Python
files, ``// simtwin: disable=SIM2xx -- <why>`` in C files (the
``simlint:`` spelling works too; each tool judges staleness only for the
rules it runs, so a SIM2xx pragma is never "stale" to simlint or simrace
and vice versa).  ``--diff BASE`` keeps the ANALYSIS whole-model (a
constant changed in an untouched twin still has to agree with the edited
one) and filters only the report, exactly like simrace.

``--emit-spec`` serializes the extracted IR to ``spec/protocol.json`` —
checked in, byte-stable across regeneration and PYTHONHASHSEED values
(everything sorted, no ids, no timestamps).  Since the simgen cut-over
the AUTHORITATIVE table is ``spec/protocol_spec.json`` (the planes are
generated from it; `make gen`); the extracted IR is the read-back
artifact that proves the generated planes still mean what the spec says.
``--emit-spec`` refuses to clobber uncommitted hand edits to the target
(they belong in the authoritative spec) unless ``--force``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional, Set

from . import twin_rules
from .simlint import (Config, Finding, LintResult, _toml_section,
                      apply_pragmas, changed_py_files, load_config)
from .twin_rules import CATALOG, MapEntry, TwinModel, build_spec, parse_map

TWIN_EXTS = (".py", ".cc", ".cpp", ".h")


def default_rules() -> List[twin_rules.TwinRule]:
    return list(CATALOG)


def active_ids(rules: Optional[List] = None) -> Set[str]:
    return {r.id for r in (rules or default_rules())} | {"SIM000"}


def load_map(config_path: Optional[str], config: Config
             ) -> Dict[str, List[MapEntry]]:
    """[tool.simtwin.map] from the same pyproject the Config came from."""
    path = config_path
    if path is None:
        cand = os.path.join(config.root, "pyproject.toml")
        path = cand if os.path.isfile(cand) else None
    if path is None:
        return {}
    try:
        with open(path, encoding="utf-8") as f:
            text = f.read()
    except OSError:
        return {}
    return parse_map(_toml_section(text, "tool.simtwin.map"))


def _apply_c_pragmas(path: str, source: str, findings: List[Finding],
                     ids: Set[str]) -> List[Finding]:
    """C-file counterpart of simlint.apply_pragmas: // pragma comments,
    reason required, rule-scoped ownership, stale pragma = SIM000."""
    pragmas, malformed = cspec_pragmas(source)
    bad = [Finding("SIM000", "error", path, ln, col, msg)
           for ln, col, msg in malformed]
    pragmas = [p for p in pragmas if p.rule in ids]
    index = {(p.target, p.rule): p for p in pragmas}
    for f in findings:
        p = index.get((f.line, f.rule))
        if p is not None:
            f.suppressed, f.reason = True, p.reason
            p.used = True
    for p in pragmas:
        if not p.used:
            bad.append(Finding(
                "SIM000", "error", path, p.line, p.col,
                f"suppression pragma for {p.rule} matched no finding — "
                "remove the stale pragma (or fix its rule id)"))
    return sorted(findings + bad, key=Finding.sort_key)


def cspec_pragmas(source: str):
    from . import cspec
    from .simlint import known_rule_ids
    return cspec.collect_c_pragmas(source, known_rule_ids())


def twin_sources(sources: Dict[str, str],
                 config: Optional[Config] = None,
                 surface_map: Optional[Dict[str, List[MapEntry]]] = None,
                 rules: Optional[List] = None) -> List[Finding]:
    """Analyze in-memory planes ({relpath: source}) — the fixture entry
    point (the cross-plane analog of simlint.lint_source)."""
    config = config or Config()
    rules = rules if rules is not None else default_rules()
    surface_map = surface_map or {}
    twin = TwinModel(sources, surface_map)
    per_file: Dict[str, List[Finding]] = {}
    for rule in rules:
        for f in rule.run(twin):
            if not config.is_allowed(f.rule, f.path):
                per_file.setdefault(f.path, []).append(f)
    ids = {r.id for r in rules} | {"SIM000"}
    out: List[Finding] = list(twin.parse_errors)
    handled: Set[str] = set()
    for rel, ctx in twin.py_ctx.items():
        out.extend(apply_pragmas(ctx, per_file.get(rel, []), ids))
        handled.add(rel)
    for rel in twin.c_extracts:
        out.extend(_apply_c_pragmas(rel, sources[rel],
                                    per_file.get(rel, []), ids))
        handled.add(rel)
    for rel, fs in per_file.items():        # e.g. pyproject-anchored SIM203
        if rel not in handled:
            out.extend(fs)
    return sorted(out, key=Finding.sort_key)


def _load_mapped_sources(config: Config,
                         surface_map: Dict[str, List[MapEntry]]
                         ) -> Dict[str, str]:
    sources: Dict[str, str] = {}
    for entries in surface_map.values():
        for e in entries:
            if e.path in sources:
                continue
            abspath = os.path.join(config.root, e.path)
            try:
                with open(abspath, encoding="utf-8") as f:
                    sources[e.path] = f.read()
            except (OSError, UnicodeDecodeError):
                pass                  # SurfaceMapRule reports the absence
    return sources


def twin_paths(paths: List[str], config: Optional[Config] = None,
               surface_map: Optional[Dict[str, List[MapEntry]]] = None,
               rules: Optional[List] = None,
               only: Optional[Set[str]] = None) -> LintResult:
    """Analyze the mapped twin files under the config root.  ``paths``
    and ``only`` restrict REPORTING (the model is cross-plane: every
    mapped file participates in extraction regardless)."""
    config = config or load_config(None, start=paths[0] if paths else ".")
    if surface_map is None:
        surface_map = load_map(None, config)
    sources = _load_mapped_sources(config, surface_map)
    # the authoritative spec rides along (not a mapped plane): SIM205
    # judges generated-region staleness against its digest.  Read BINARY
    # and decode: a text-mode read would normalize \r\n and make this
    # digest disagree with simgen's raw-bytes spec= markers.
    from .genmark import SPEC_RELPATH
    try:
        with open(os.path.join(config.root, SPEC_RELPATH), "rb") as f:
            sources.setdefault(SPEC_RELPATH, f.read().decode("utf-8"))
    except (OSError, UnicodeDecodeError):
        pass
    findings = twin_sources(sources, config, surface_map, rules)

    scoped: Set[str] = set()
    for p in paths:
        rel = os.path.relpath(os.path.abspath(p), config.root)
        rel = rel.replace(os.sep, "/")
        prefix = "" if rel == "." else rel.rstrip("/") + "/"
        for rel_file in sources:
            if prefix == "" or rel_file.startswith(prefix) \
                    or rel_file == rel:
                scoped.add(rel_file)
    # pyproject-anchored findings (missing mapped file) always report
    scoped.add("pyproject.toml")
    findings = [f for f in findings if f.path in scoped]
    if only is not None:
        # pyproject-anchored findings (a map entry whose file is gone)
        # survive the --diff filter too: .toml never enters the changed
        # set, and a broken map must fail the incremental gate as well
        findings = [f for f in findings
                    if f.path in only or f.path == "pyproject.toml"]
    findings.sort(key=Finding.sort_key)
    n_files = len([s for s in sources if s in scoped])
    return LintResult(findings, n_files, tool="simtwin")


def spec_blob(config: Config,
              surface_map: Dict[str, List[MapEntry]]) -> bytes:
    """The exact bytes --emit-spec would write, without writing them."""
    sources = _load_mapped_sources(config, surface_map)
    twin = TwinModel(sources, surface_map)
    spec = build_spec(twin)
    return (json.dumps(spec, indent=2, sort_keys=True) + "\n").encode()


def emit_spec(out_path: str, config: Config,
              surface_map: Dict[str, List[MapEntry]],
              blob: Optional[bytes] = None) -> bytes:
    """Serialize the IR; returns the exact bytes written.  ``blob``
    lets a caller that already ran spec_blob (the overwrite guard)
    skip a second full extraction."""
    if blob is None:
        blob = spec_blob(config, surface_map)
    os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
    with open(out_path, "wb") as f:
        f.write(blob)
    return blob


def _uncommitted_edits(path: str, root: str) -> bool:
    """True when git sees uncommitted working-tree changes to ``path``.
    Not-a-repo / no-git / untracked-file all report False — the guard
    only protects edits that would be silently destroyed."""
    import subprocess
    try:
        run = subprocess.run(
            ["git", "-C", root, "status", "--porcelain", "--", path],
            capture_output=True, text=True, timeout=30)
    except (OSError, subprocess.SubprocessError):
        return False
    if run.returncode != 0:
        return False
    status = run.stdout.strip()[:2] if run.stdout.strip() else ""
    return bool(status) and status != "??"


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="simtwin",
        description="cross-plane protocol-equivalence static analysis "
                    "(shadow-tpu)")
    ap.add_argument("paths", nargs="*", default=None,
                    help="files or directories to report on "
                         "(default: shadow_tpu/ native/)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable JSON on stdout")
    ap.add_argument("--config", default=None,
                    help="pyproject.toml carrying [tool.simlint] + "
                         "[tool.simtwin.map]")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    ap.add_argument("--diff", metavar="BASE", default=None,
                    help="report only findings in files changed since git "
                         "ref BASE (analysis stays cross-plane)")
    ap.add_argument("--emit-spec", nargs="?", const="", default=None,
                    metavar="PATH",
                    help="write the extracted protocol IR to PATH "
                         "(default: spec/protocol.json under the config "
                         "root) and exit")
    ap.add_argument("--force", action="store_true",
                    help="with --emit-spec: overwrite the target even if "
                         "it carries uncommitted hand edits (the spec is "
                         "authoritative; refused otherwise)")
    args = ap.parse_args(argv)
    rules = default_rules()
    if args.list_rules:
        for r in rules:
            print(f"{r.id}  {r.severity:<7}  {r.short}")
        return 0
    paths = args.paths or ["shadow_tpu", "native"]
    missing = [p for p in paths if not os.path.exists(p)]
    if missing and args.emit_spec is None:
        print(f"simtwin: no such path: {', '.join(missing)}",
              file=sys.stderr)
        return 2
    config = load_config(args.config, start=paths[0] if not missing else ".")
    surface_map = load_map(args.config, config)
    if args.emit_spec is not None:
        out_path = args.emit_spec or os.path.join(config.root, "spec",
                                                  "protocol.json")
        blob = None
        if not args.force and os.path.exists(out_path):
            blob = spec_blob(config, surface_map)
            try:
                with open(out_path, "rb") as f:
                    existing = f.read()
            except OSError:
                existing = None
            if existing is not None and existing != blob \
                    and _uncommitted_edits(out_path, config.root):
                print(f"simtwin: refusing to overwrite {out_path}: it has "
                      f"uncommitted edits that differ from the "
                      f"regenerated IR.  The extracted spec is derived — "
                      f"hand edits belong in spec/protocol_spec.json "
                      f"(then `make gen`).  Commit or discard the edits, "
                      f"or rerun with --force.", file=sys.stderr)
                return 1
        blob = emit_spec(out_path, config, surface_map, blob=blob)
        print(f"simtwin: wrote {out_path} ({len(blob)} bytes)")
        return 0
    only = None
    if args.diff is not None:
        try:
            only = changed_py_files(args.diff, config.root, exts=TWIN_EXTS)
        except RuntimeError as e:
            print(f"simtwin: --diff {args.diff}: {e}", file=sys.stderr)
            return 2
    result = twin_paths(paths, config, surface_map, rules, only=only)
    if args.json:
        json.dump(result.to_json(), sys.stdout, indent=2, sort_keys=True)
        print()
    else:
        for f in result.unsuppressed:
            print(f.render())
        print(f"simtwin: {len(result.unsuppressed)} finding(s), "
              f"{len(result.suppressed)} suppressed, "
              f"{result.files} file(s)")
    return 1 if result.unsuppressed else 0


if __name__ == "__main__":
    sys.exit(main())
