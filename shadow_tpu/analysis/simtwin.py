"""simtwin: cross-plane protocol-equivalence static analysis.

The simulator's protocol logic exists three times: the Python modules
(authoritative), the hand-transcribed native C data plane, and the
JAX/numpy kernel family.  Runtime digest tests keep them honest — hours
into a run.  simtwin fails the drift at LINT time instead: three
extractors (Python AST, cspec's regex+brace C reader, the kernel dtype
pass) feed one table-driven IR, and the SIM2xx rules diff the planes:

=======  ========  ====================================================
SIM201   error     protocol constant / threshold drift between twins
SIM202   error     TCP state-transition table drift
SIM203   error     twin missing a mapped counterpart surface
                   ([tool.simtwin.map] in pyproject.toml)
SIM204   error     dtype/overflow hazard in a device kernel
=======  ========  ====================================================

Usage::

    python -m shadow_tpu.analysis.simtwin [paths...] [--json]
        [--list-rules] [--config pyproject.toml] [--diff BASE]
        [--emit-spec [PATH]]

Exit status: 0 = no unsuppressed findings, 1 = findings, 2 = usage error.

Everything else is shared with simlint/simrace: severity model, JSON
schema (``"tool": "simtwin"``), ``[tool.simlint.allow]`` allowlists, and
the pragma vocabulary — ``# simtwin: disable=SIM2xx -- <why>`` in Python
files, ``// simtwin: disable=SIM2xx -- <why>`` in C files (the
``simlint:`` spelling works too; each tool judges staleness only for the
rules it runs, so a SIM2xx pragma is never "stale" to simlint or simrace
and vice versa).  ``--diff BASE`` keeps the ANALYSIS whole-model (a
constant changed in an untouched twin still has to agree with the edited
one) and filters only the report, exactly like simrace.

``--emit-spec`` serializes the extracted IR to ``spec/protocol.json`` —
checked in, byte-stable across regeneration and PYTHONHASHSEED values
(everything sorted, no ids, no timestamps).  That file is the seed
artifact for ROADMAP item 4's single-source protocol spec: the planes are
diffed against ONE table today so they can be *generated* from one table
tomorrow.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional, Set

from . import twin_rules
from .simlint import (Config, Finding, LintResult, _toml_section,
                      apply_pragmas, changed_py_files, load_config)
from .twin_rules import CATALOG, MapEntry, TwinModel, build_spec, parse_map

TWIN_EXTS = (".py", ".cc", ".cpp", ".h")


def default_rules() -> List[twin_rules.TwinRule]:
    return list(CATALOG)


def active_ids(rules: Optional[List] = None) -> Set[str]:
    return {r.id for r in (rules or default_rules())} | {"SIM000"}


def load_map(config_path: Optional[str], config: Config
             ) -> Dict[str, List[MapEntry]]:
    """[tool.simtwin.map] from the same pyproject the Config came from."""
    path = config_path
    if path is None:
        cand = os.path.join(config.root, "pyproject.toml")
        path = cand if os.path.isfile(cand) else None
    if path is None:
        return {}
    try:
        with open(path, encoding="utf-8") as f:
            text = f.read()
    except OSError:
        return {}
    return parse_map(_toml_section(text, "tool.simtwin.map"))


def _apply_c_pragmas(path: str, source: str, findings: List[Finding],
                     ids: Set[str]) -> List[Finding]:
    """C-file counterpart of simlint.apply_pragmas: // pragma comments,
    reason required, rule-scoped ownership, stale pragma = SIM000."""
    pragmas, malformed = cspec_pragmas(source)
    bad = [Finding("SIM000", "error", path, ln, col, msg)
           for ln, col, msg in malformed]
    pragmas = [p for p in pragmas if p.rule in ids]
    index = {(p.target, p.rule): p for p in pragmas}
    for f in findings:
        p = index.get((f.line, f.rule))
        if p is not None:
            f.suppressed, f.reason = True, p.reason
            p.used = True
    for p in pragmas:
        if not p.used:
            bad.append(Finding(
                "SIM000", "error", path, p.line, p.col,
                f"suppression pragma for {p.rule} matched no finding — "
                "remove the stale pragma (or fix its rule id)"))
    return sorted(findings + bad, key=Finding.sort_key)


def cspec_pragmas(source: str):
    from . import cspec
    from .simlint import known_rule_ids
    return cspec.collect_c_pragmas(source, known_rule_ids())


def twin_sources(sources: Dict[str, str],
                 config: Optional[Config] = None,
                 surface_map: Optional[Dict[str, List[MapEntry]]] = None,
                 rules: Optional[List] = None) -> List[Finding]:
    """Analyze in-memory planes ({relpath: source}) — the fixture entry
    point (the cross-plane analog of simlint.lint_source)."""
    config = config or Config()
    rules = rules if rules is not None else default_rules()
    surface_map = surface_map or {}
    twin = TwinModel(sources, surface_map)
    per_file: Dict[str, List[Finding]] = {}
    for rule in rules:
        for f in rule.run(twin):
            if not config.is_allowed(f.rule, f.path):
                per_file.setdefault(f.path, []).append(f)
    ids = {r.id for r in rules} | {"SIM000"}
    out: List[Finding] = list(twin.parse_errors)
    handled: Set[str] = set()
    for rel, ctx in twin.py_ctx.items():
        out.extend(apply_pragmas(ctx, per_file.get(rel, []), ids))
        handled.add(rel)
    for rel in twin.c_extracts:
        out.extend(_apply_c_pragmas(rel, sources[rel],
                                    per_file.get(rel, []), ids))
        handled.add(rel)
    for rel, fs in per_file.items():        # e.g. pyproject-anchored SIM203
        if rel not in handled:
            out.extend(fs)
    return sorted(out, key=Finding.sort_key)


def _load_mapped_sources(config: Config,
                         surface_map: Dict[str, List[MapEntry]]
                         ) -> Dict[str, str]:
    sources: Dict[str, str] = {}
    for entries in surface_map.values():
        for e in entries:
            if e.path in sources:
                continue
            abspath = os.path.join(config.root, e.path)
            try:
                with open(abspath, encoding="utf-8") as f:
                    sources[e.path] = f.read()
            except (OSError, UnicodeDecodeError):
                pass                  # SurfaceMapRule reports the absence
    return sources


def twin_paths(paths: List[str], config: Optional[Config] = None,
               surface_map: Optional[Dict[str, List[MapEntry]]] = None,
               rules: Optional[List] = None,
               only: Optional[Set[str]] = None) -> LintResult:
    """Analyze the mapped twin files under the config root.  ``paths``
    and ``only`` restrict REPORTING (the model is cross-plane: every
    mapped file participates in extraction regardless)."""
    config = config or load_config(None, start=paths[0] if paths else ".")
    if surface_map is None:
        surface_map = load_map(None, config)
    sources = _load_mapped_sources(config, surface_map)
    findings = twin_sources(sources, config, surface_map, rules)

    scoped: Set[str] = set()
    for p in paths:
        rel = os.path.relpath(os.path.abspath(p), config.root)
        rel = rel.replace(os.sep, "/")
        prefix = "" if rel == "." else rel.rstrip("/") + "/"
        for rel_file in sources:
            if prefix == "" or rel_file.startswith(prefix) \
                    or rel_file == rel:
                scoped.add(rel_file)
    # pyproject-anchored findings (missing mapped file) always report
    scoped.add("pyproject.toml")
    findings = [f for f in findings if f.path in scoped]
    if only is not None:
        # pyproject-anchored findings (a map entry whose file is gone)
        # survive the --diff filter too: .toml never enters the changed
        # set, and a broken map must fail the incremental gate as well
        findings = [f for f in findings
                    if f.path in only or f.path == "pyproject.toml"]
    findings.sort(key=Finding.sort_key)
    n_files = len([s for s in sources if s in scoped])
    return LintResult(findings, n_files, tool="simtwin")


def emit_spec(out_path: str, config: Config,
              surface_map: Dict[str, List[MapEntry]]) -> bytes:
    """Serialize the IR; returns the exact bytes written."""
    sources = _load_mapped_sources(config, surface_map)
    twin = TwinModel(sources, surface_map)
    spec = build_spec(twin)
    blob = (json.dumps(spec, indent=2, sort_keys=True) + "\n").encode()
    os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
    with open(out_path, "wb") as f:
        f.write(blob)
    return blob


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="simtwin",
        description="cross-plane protocol-equivalence static analysis "
                    "(shadow-tpu)")
    ap.add_argument("paths", nargs="*", default=None,
                    help="files or directories to report on "
                         "(default: shadow_tpu/ native/)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable JSON on stdout")
    ap.add_argument("--config", default=None,
                    help="pyproject.toml carrying [tool.simlint] + "
                         "[tool.simtwin.map]")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    ap.add_argument("--diff", metavar="BASE", default=None,
                    help="report only findings in files changed since git "
                         "ref BASE (analysis stays cross-plane)")
    ap.add_argument("--emit-spec", nargs="?", const="", default=None,
                    metavar="PATH",
                    help="write the extracted protocol IR to PATH "
                         "(default: spec/protocol.json under the config "
                         "root) and exit")
    args = ap.parse_args(argv)
    rules = default_rules()
    if args.list_rules:
        for r in rules:
            print(f"{r.id}  {r.severity:<7}  {r.short}")
        return 0
    paths = args.paths or ["shadow_tpu", "native"]
    missing = [p for p in paths if not os.path.exists(p)]
    if missing and args.emit_spec is None:
        print(f"simtwin: no such path: {', '.join(missing)}",
              file=sys.stderr)
        return 2
    config = load_config(args.config, start=paths[0] if not missing else ".")
    surface_map = load_map(args.config, config)
    if args.emit_spec is not None:
        out_path = args.emit_spec or os.path.join(config.root, "spec",
                                                  "protocol.json")
        blob = emit_spec(out_path, config, surface_map)
        print(f"simtwin: wrote {out_path} ({len(blob)} bytes)")
        return 0
    only = None
    if args.diff is not None:
        try:
            only = changed_py_files(args.diff, config.root, exts=TWIN_EXTS)
        except RuntimeError as e:
            print(f"simtwin: --diff {args.diff}: {e}", file=sys.stderr)
            return 2
    result = twin_paths(paths, config, surface_map, rules, only=only)
    if args.json:
        json.dump(result.to_json(), sys.stdout, indent=2, sort_keys=True)
        print()
    else:
        for f in result.unsuppressed:
            print(f.render())
        print(f"simtwin: {len(result.unsuppressed)} finding(s), "
              f"{len(result.suppressed)} suppressed, "
              f"{result.files} file(s)")
    return 1 if result.unsuppressed else 0


if __name__ == "__main__":
    sys.exit(main())
