"""twin_rules: cross-plane protocol-equivalence model + the SIM2xx catalog.

The protocol logic of this simulator exists three times — the Python
modules (authoritative), the hand-transcribed C data plane, and the
JAX/numpy kernel family — kept bit-identical by discipline and runtime
digest tests.  simtwin turns that discipline into lint: three extractors
feed ONE table-driven IR (constants, update coefficients, TCP transition
tables, surface symbols, kernel dtypes) and the rules diff the planes.

=======  ========  ====================================================
SIM201   error     protocol constant / threshold drift between twins
SIM202   error     TCP state-transition table drift (missing / extra
                   transition or state per plane)
SIM203   error     a twin is missing a mapped counterpart surface
                   ([tool.simtwin.map] in pyproject.toml)
SIM204   error     dtype/overflow hazard in a device kernel (sim-ns
                   value narrowed to a 32-bit lane)
SIM205   error     simgen-generated region hand-edited (body digest
                   drift) or stale vs spec/protocol_spec.json
SIM206   error     emitted protocol-logic expression drifted from the
                   spec's expression IR (read-back: the plane's parsed
                   tree differs structurally from the resolved spec
                   tree)
=======  ========  ====================================================

The extracted IR serializes to ``spec/protocol.json`` (``simtwin
--emit-spec``): byte-stable, sorted, hash-seed independent.  Since the
simgen cut-over (ROADMAP item 3) the direction is INVERTED:
``spec/protocol_spec.json`` is authoritative, the planes carry generated
fenced regions (``make gen``), and this extracted IR is the read-back
verification artifact.  Constant sources are anchored to SYMBOL names
(``path#symbol``), never raw line offsets, so generated regions growing
or shrinking cannot churn the spec.

The surface map (``[tool.simtwin.map]``) is the comparator's scope: each
key names a protocol surface, each value lists ``plane:path[:symbol]``
entries (plane in {py, c, kernel}).  ``py``/``kernel`` files go through
the AST extractor (kernel files additionally run the dtype pass);
``c`` files go through cspec.  The surface named ``tcp-state-machine``
selects the files whose transition tables are compared.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from . import cspec
from .simlint import Config, Finding, ModuleContext

# ---------------------------------------------------------------------------
# canonical constant names: per-plane surface spellings -> one comparator key

CANON: Dict[str, str] = {
    # wire framing (core/defs.py <-> dataplane.cc constants)
    "CONFIG_MTU": "MTU", "MTU": "MTU",
    "CONFIG_HEADER_SIZE_TCPIPETH": "HDR_TCP", "HDR_TCP": "HDR_TCP",
    "CONFIG_HEADER_SIZE_UDPIPETH": "HDR_UDP", "HDR_UDP": "HDR_UDP",
    "CONFIG_DATAGRAM_MAX_SIZE": "DGRAM_MAX", "DGRAM_MAX": "DGRAM_MAX",
    "CONFIG_TCP_MAX_SEGMENT_SIZE": "MSS", "MSS": "MSS",
    # TCP buffers / timers
    "CONFIG_TCP_RMEM_MAX": "RMEM_MAX", "RMEM_MAX": "RMEM_MAX",
    "CONFIG_TCP_WMEM_MAX": "WMEM_MAX", "WMEM_MAX": "WMEM_MAX",
    "RTO_INIT_NS": "RTO_INIT_NS", "RTO_INIT": "RTO_INIT_NS",
    "RTO_MIN_NS": "RTO_MIN_NS", "RTO_MIN": "RTO_MIN_NS",
    "RTO_MAX_NS": "RTO_MAX_NS", "RTO_MAX": "RTO_MAX_NS",
    "TIME_WAIT_NS": "TIME_WAIT_NS",
    "MAX_SYN_RETRIES": "MAX_SYN_RETRIES",
    "MAX_RETRIES": "MAX_RETRIES",
    "MAX_SACK_BLOCKS": "MAX_SACK_BLOCKS",
    # interface token buckets
    "INTERFACE_REFILL_INTERVAL_NS": "REFILL_INTERVAL_NS",
    "REFILL_INTERVAL": "REFILL_INTERVAL_NS",
    "REFILL_NS": "REFILL_INTERVAL_NS",
    "REFILL_INTERVAL_NS": "REFILL_INTERVAL_NS",
    "INTERFACE_CAPACITY_FACTOR": "CAPACITY_FACTOR",
    "CAPACITY_FACTOR": "CAPACITY_FACTOR",
    # router AQM
    "CoDelQueue.TARGET_NS": "CODEL_TARGET_NS",
    "CODEL_TARGET": "CODEL_TARGET_NS",
    "CoDelQueue.INTERVAL_NS": "CODEL_INTERVAL_NS",
    "CODEL_INTERVAL": "CODEL_INTERVAL_NS",
    "CoDelQueue.HARD_LIMIT": "CODEL_HARD_LIMIT",
    "CODEL_HARD_LIMIT": "CODEL_HARD_LIMIT",
    "STATIC_CAPACITY": "STATIC_CAPACITY",
    # clock
    "SIM_TIME_MS": "SIM_TIME_MS", "SIM_MS": "SIM_TIME_MS",
    "SIM_TIME_SEC": "SIM_TIME_SEC", "SIM_SEC": "SIM_TIME_SEC",
    # drop RNG (core/rng.py threefry <-> dataplane.cc mirror)
    "_PARITY": "THREEFRY_PARITY", "TF_PARITY": "THREEFRY_PARITY",
    "_ROTATIONS": "THREEFRY_ROTATIONS", "TF_ROT": "THREEFRY_ROTATIONS",
    # TCP header flags (routing/packet.py <-> dataplane.cc enum)
    "TCP_RST": "FLAG_RST", "F_RST": "FLAG_RST",
    "TCP_SYN": "FLAG_SYN", "F_SYN": "FLAG_SYN",
    "TCP_ACK": "FLAG_ACK", "F_ACK": "FLAG_ACK",
    "TCP_FIN": "FLAG_FIN", "F_FIN": "FLAG_FIN",
    # descriptor status bits (descriptor/base.py <-> dataplane.cc enum)
    "S_ACTIVE": "S_ACTIVE", "S_READABLE": "S_READABLE",
    "S_WRITABLE": "S_WRITABLE", "S_CLOSED": "S_CLOSED",
    # epoll readiness bits (descriptor/epoll.py <-> dataplane.cc enum):
    # the C-side readiness cache (ISSUE 12) computes revents natively, so
    # the bit values are a two-plane surface
    "EPOLLIN": "EPOLLIN", "EPOLLOUT": "EPOLLOUT",
    "EPOLLERR": "EPOLLERR", "EPOLLHUP": "EPOLLHUP",
    # port allocation (host/host.py <-> dataplane.cc)
    "MIN_EPHEMERAL_PORT": "MIN_EPHEMERAL_PORT", "MAX_PORT": "MAX_PORT",
    # congestion control: the coefficient families are NAMED constants on
    # all three planes since the simgen cut-over (generated regions in
    # tcp_cong.py / dataplane.cc / ops/protocol_tables.py)
    "Cubic.C": "CUBIC_C", "Cubic.BETA": "CUBIC_BETA",
    "CUBIC_C": "CUBIC_C", "CUBIC_BETA": "CUBIC_BETA",
    "CubicX.C": "CUBICX_C", "CubicX.BETA": "CUBICX_BETA",
    "CUBICX_C": "CUBICX_C", "CUBICX_BETA": "CUBICX_BETA",
    # bbrx estimator parameters: named identically on all three planes
    # (generated logic regions, ISSUE 19)
    "BBRX_BETA_DEN": "BBRX_BETA_DEN", "BBRX_BETA_NUM": "BBRX_BETA_NUM",
    "BBRX_BW_CAP_BPS": "BBRX_BW_CAP_BPS", "BBRX_CYCLE_LEN": "BBRX_CYCLE_LEN",
    "BBRX_CYCLE_NS": "BBRX_CYCLE_NS",
    "BBRX_GAIN_CRUISE_NUM": "BBRX_GAIN_CRUISE_NUM",
    "BBRX_GAIN_DEN": "BBRX_GAIN_DEN",
    "BBRX_GAIN_DOWN_NUM": "BBRX_GAIN_DOWN_NUM",
    "BBRX_GAIN_UP_NUM": "BBRX_GAIN_UP_NUM",
    "BBRX_MIN_CWND_SEGMENTS": "BBRX_MIN_CWND_SEGMENTS",
    "BBRX_RTT_CAP_NS": "BBRX_RTT_CAP_NS",
    "BBRX_RTT_FLOOR_NS": "BBRX_RTT_FLOOR_NS",
}

# C-side regex probes for coefficients spelled inline (see cspec._run_probe)
C_PROBES: Dict[str, Tuple[str, str]] = {
    "MAX_RETRIES": (r"rtx_count\s*>=\s*(MAX_RETRIES)", "one"),
    "DUP_ACK_THRESHOLD": (r"\bcount\s*==\s*(\d+)", "one"),
    "QUICK_ACKS_LIMIT": (r"quick_acks\s*<\s*(\d+)", "one"),
    "DELACK_DELAYS_NS": (r"\bdelay\s*=\s*([^;]+);", "set"),
    # CUBIC_C / CUBIC_BETA left the probe set at the simgen cut-over: the
    # C plane now spells them as named constexpr constants (generated
    # region c-congestion-params), extracted like any other constant.
    # SRTT_GAIN / RTTVAR_GAIN / RTO_VAR_MULT / SSTHRESH_RULE /
    # RECOVERY_INFLATE_SEGMENTS left at the logic-surface cut-over
    # (ISSUE 19): the update expressions are generated from the spec's
    # logic IR and SIM206 compares the parsed trees structurally —
    # strictly stronger than a per-coefficient regex.
}

# sim-time-ish identifiers for the SIM204 dtype pass
_TIMEY_RE = re.compile(
    r"(?:^|_)(?:ns|time|times|deliver|arrive|admit|barrier|expiry|deadline)"
    r"(?:_|$)|_ns$|time")
_NARROW_DTYPES = {"int32", "uint32", "int16", "uint16", "int8", "uint8"}


def _is_timey(name: str) -> bool:
    return bool(_TIMEY_RE.search(name.lower()))


# ---------------------------------------------------------------------------
# python constant folding

def _fold(node: ast.AST, env: Dict[str, object],
          modules: Dict[str, Dict[str, object]]) -> Optional[object]:
    """Fold a module-level constant expression.  ``env`` is the module's
    own names; ``modules`` maps import basenames (defs, stime, ...) to the
    envs of other analyzed modules so ``defs.CONFIG_MTU`` resolves."""
    if isinstance(node, ast.Constant) and isinstance(
            node.value, (int, float, str)):
        return node.value
    if isinstance(node, ast.Name):
        return env.get(node.id)
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name):
        mod_env = modules.get(node.value.id)
        if mod_env is not None:
            return mod_env.get(node.attr)
        return None
    if isinstance(node, ast.Tuple):
        vals = [_fold(e, env, modules) for e in node.elts]
        if any(v is None for v in vals):
            return None
        return list(vals)
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        v = _fold(node.operand, env, modules)
        return -v if isinstance(v, (int, float)) else None
    if isinstance(node, ast.BinOp):
        a = _fold(node.left, env, modules)
        b = _fold(node.right, env, modules)
        if not isinstance(a, (int, float)) or not isinstance(b, (int, float)):
            return None
        try:
            if isinstance(node.op, ast.Add):
                return a + b
            if isinstance(node.op, ast.Sub):
                return a - b
            if isinstance(node.op, ast.Mult):
                return a * b
            if isinstance(node.op, ast.FloorDiv):
                return a // b
            if isinstance(node.op, ast.Div):
                return a / b
            if isinstance(node.op, ast.Pow):
                return a ** b
            if isinstance(node.op, ast.LShift):
                return a << b
            if isinstance(node.op, ast.RShift):
                return a >> b
            if isinstance(node.op, ast.BitOr):
                return a | b
        except (ZeroDivisionError, TypeError, ValueError, OverflowError):
            return None
    return None


@dataclass
class PyExtract:
    path: str
    constants: Dict[str, Tuple[object, int]] = field(default_factory=dict)
    symbols: Dict[str, int] = field(default_factory=dict)
    transitions: List[Tuple[str, str, int]] = field(default_factory=list)
    probes: Dict[str, Tuple[object, int]] = field(default_factory=dict)
    states: List[str] = field(default_factory=list)
    env: Dict[str, object] = field(default_factory=dict)


def fold_module_env(ctx: ModuleContext,
                    modules: Dict[str, Dict[str, object]]
                    ) -> Dict[str, object]:
    """Module-level (and Class.attr) constant values for one module."""
    env: Dict[str, object] = {}
    for stmt in ctx.tree.body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name):
            v = _fold(stmt.value, env, modules)
            if v is not None:
                env[stmt.targets[0].id] = v
        elif isinstance(stmt, ast.ClassDef):
            for sub in stmt.body:
                if isinstance(sub, ast.Assign) and len(sub.targets) == 1 \
                        and isinstance(sub.targets[0], ast.Name):
                    v = _fold(sub.value, env, modules)
                    if v is not None:
                        env[f"{stmt.name}.{sub.targets[0].id}"] = v
    return env


def _const_lines(ctx: ModuleContext) -> Dict[str, int]:
    lines: Dict[str, int] = {}
    for stmt in ctx.tree.body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name):
            lines[stmt.targets[0].id] = stmt.lineno
        elif isinstance(stmt, ast.ClassDef):
            for sub in stmt.body:
                if isinstance(sub, ast.Assign) and len(sub.targets) == 1 \
                        and isinstance(sub.targets[0], ast.Name):
                    lines[f"{stmt.name}.{sub.targets[0].id}"] = sub.lineno
    return lines


def _py_symbols(ctx: ModuleContext) -> Dict[str, int]:
    syms: Dict[str, int] = {}
    for node in ctx.tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            syms[node.name] = node.lineno
        elif isinstance(node, ast.ClassDef):
            syms[node.name] = node.lineno
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    syms[f"{node.name}.{sub.name}"] = sub.lineno
    return syms


# -- transition extraction (python side) ------------------------------------

def _guard_states(test: ast.AST, env: Dict[str, object]) -> Set[str]:
    """States named positively (== / in) by an if-condition."""
    out: Set[str] = set()
    for node in ast.walk(test):
        if not isinstance(node, ast.Compare) or len(node.ops) != 1:
            continue
        left = node.left
        is_state = (isinstance(left, ast.Attribute) and left.attr == "state") \
            or (isinstance(left, ast.Name) and left.id == "state")
        if not is_state:
            continue
        op = node.ops[0]
        comp = node.comparators[0]
        if isinstance(op, ast.Eq):
            v = _fold(comp, env, {})
            if isinstance(v, str):
                out.add(v)
        elif isinstance(op, ast.In) and isinstance(comp, (ast.Tuple, ast.List)):
            for e in comp.elts:
                v = _fold(e, env, {})
                if isinstance(v, str):
                    out.add(v)
    return out


def _py_transitions(ctx: ModuleContext, env: Dict[str, object]
                    ) -> List[Tuple[str, str, int]]:
    """(from|'?', to, line) for every ``<obj>.state = STATE`` assignment,
    guards attributed only through if-*bodies* (never else branches) —
    the AST mirror of cspec._extract_transitions."""
    out: List[Tuple[str, str, int]] = []
    for node in ctx.walk(ast.Assign):
        if len(node.targets) != 1:
            continue
        tgt = node.targets[0]
        if not (isinstance(tgt, ast.Attribute) and tgt.attr == "state"):
            continue
        values = [node.value]
        if isinstance(node.value, ast.IfExp):
            values = [node.value.body, node.value.orelse]
        targets: List[str] = []
        for v in values:
            folded = _fold(v, env, {})
            if isinstance(folded, str):
                targets.append(folded)
        if not targets:
            continue
        guards: Set[str] = set()
        child: ast.AST = node
        cur = ctx.parent(node)
        while cur is not None:
            if isinstance(cur, ast.If) and child in cur.body:
                guards |= _guard_states(cur.test, env)
            child = cur
            cur = ctx.parent(cur)
        for to in targets:
            if guards:
                for g in sorted(guards):
                    out.append((g, to, node.lineno))
            else:
                out.append(("?", to, node.lineno))
    return out


def _py_states(transitions: List[Tuple[str, str, int]]) -> List[str]:
    s = {t for _, t, _ in transitions} | \
        {f for f, _, _ in transitions if f != "?"}
    return sorted(s)


# -- python coefficient probes ----------------------------------------------

def _attr_name(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _py_probes(ctx: ModuleContext, env: Dict[str, object],
               modules: Dict[str, Dict[str, object]]
               ) -> Dict[str, Tuple[object, int]]:
    """The Python spellings of the C_PROBES coefficients."""
    out: Dict[str, Tuple[object, int]] = {}
    delack: List[object] = []
    delack_line = None
    for node in ast.walk(ctx.tree):
        ln = getattr(node, "lineno", 0)
        # rtx_count >= <int literal>  ->  MAX_RETRIES (tcp_retries2)
        if isinstance(node, ast.Compare) and len(node.ops) == 1 \
                and isinstance(node.ops[0], ast.GtE) \
                and _attr_name(node.left) == "rtx_count" \
                and isinstance(node.comparators[0], ast.Constant) \
                and isinstance(node.comparators[0].value, int):
            prev = out.get("MAX_RETRIES")
            if prev is None or node.comparators[0].value > prev[0]:
                out["MAX_RETRIES"] = (node.comparators[0].value, ln)
        # count == N  ->  DUP_ACK_THRESHOLD
        if isinstance(node, ast.Compare) and len(node.ops) == 1 \
                and isinstance(node.ops[0], ast.Eq) \
                and _attr_name(node.left) == "count" \
                and isinstance(node.comparators[0], ast.Constant):
            out.setdefault("DUP_ACK_THRESHOLD",
                           (node.comparators[0].value, ln))
        # _quick_acks < N  ->  QUICK_ACKS_LIMIT
        if isinstance(node, ast.Compare) and len(node.ops) == 1 \
                and isinstance(node.ops[0], ast.Lt) \
                and (_attr_name(node.left) or "").lstrip("_") == "quick_acks" \
                and isinstance(node.comparators[0], ast.Constant):
            out.setdefault("QUICK_ACKS_LIMIT",
                           (node.comparators[0].value, ln))
        # delay = <expr>  ->  DELACK_DELAYS_NS (set of folded values)
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == "delay":
            v = _fold(node.value, env, modules)
            if isinstance(v, (int, float)):
                delack.append(v)
                delack_line = delack_line or ln
        # SRTT/RTTVAR/RTO/ssthresh/recovery coefficient probes retired at
        # the logic-surface cut-over (ISSUE 19): SIM206 structurally
        # compares the generated update expressions instead.
        # def __init__(..., capacity_packets: int = N)  ->  STATIC_CAPACITY
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            args = node.args.args
            for arg, default in zip(args[len(args) - len(node.args.defaults):],
                                    node.args.defaults):
                if arg.arg == "capacity_packets" \
                        and isinstance(default, ast.Constant):
                    out.setdefault("STATIC_CAPACITY",
                                   (default.value, node.lineno))
    if delack:
        out["DELACK_DELAYS_NS"] = (sorted(set(delack)), delack_line or 0)
    return out


def extract_py(ctx: ModuleContext, modules: Dict[str, Dict[str, object]],
               with_transitions: bool) -> PyExtract:
    env = fold_module_env(ctx, modules)
    out = PyExtract(ctx.relpath, env=env)
    lines = _const_lines(ctx)
    for name, val in env.items():
        if isinstance(val, (int, float, list)):
            out.constants[name] = (val, lines.get(name, 1))
    out.symbols = _py_symbols(ctx)
    out.probes = _py_probes(ctx, env, modules)
    if with_transitions:
        out.transitions = _py_transitions(ctx, env)
        out.states = _py_states(out.transitions)
    return out


# ---------------------------------------------------------------------------
# SIM204: kernel dtype/overflow pass

def _dtype_of(node: ast.AST) -> Optional[str]:
    """'int32' for jnp.int32 / np.uint32 / "int32" etc., else None."""
    if isinstance(node, ast.Attribute) and node.attr in _NARROW_DTYPES:
        return node.attr
    if isinstance(node, ast.Name) and node.id in _NARROW_DTYPES:
        return node.id
    if isinstance(node, ast.Constant) and isinstance(node.value, str) \
            and node.value in _NARROW_DTYPES:
        return node.value
    return None


def _expr_names(node: ast.AST) -> Set[str]:
    names: Set[str] = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Name):
            names.add(n.id)
        elif isinstance(n, ast.Attribute):
            names.add(n.attr)
        elif isinstance(n, ast.arg):
            names.add(n.arg)
    return names


def kernel_dtype_findings(ctx: ModuleContext) -> List[Finding]:
    """SIM204: a sim-time value narrowed to a 32-bit lane inside a kernel
    module.  Two shapes are findings:

    * a direct cast — ``deliver_ns.astype(jnp.int32)`` or
      ``jnp.int32(send_times)`` — of an expression whose identifiers look
      sim-time-ish (``*_ns``, ``*time*``, deliver/arrive/admit/barrier/
      expiry);
    * a 32-bit carrier (``jnp.zeros(..., dtype=jnp.int32)`` or a tracked
      ``.astype(32-bit)`` binding — the arrival-ring shape, donate-aware
      in the sense that the carried buffer keeps its identity across
      ``.at[...].set/add``) receiving a sim-time expression.
    """
    findings: List[Finding] = []
    narrow_vars: Set[str] = set()
    rule_id, sev = "SIM204", "error"

    def timey(expr: ast.AST) -> Optional[str]:
        for nm in sorted(_expr_names(expr)):
            if _is_timey(nm):
                return nm
        return None

    for node in ast.walk(ctx.tree):
        # x = jnp.zeros(..., dtype=<32>)  /  x = <expr>.astype(<32>)
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            v = node.value
            if isinstance(v, ast.Call):
                for kw in v.keywords:
                    if kw.arg == "dtype" and _dtype_of(kw.value):
                        narrow_vars.add(node.targets[0].id)
                if isinstance(v.func, ast.Attribute) \
                        and v.func.attr == "astype" and v.args \
                        and _dtype_of(v.args[0]):
                    narrow_vars.add(node.targets[0].id)
        if not isinstance(node, ast.Call):
            continue
        # direct cast of a time-ish expression
        if isinstance(node.func, ast.Attribute) and node.func.attr == "astype" \
                and node.args and _dtype_of(node.args[0]):
            nm = timey(node.func.value)
            if nm:
                findings.append(Finding(
                    rule_id, sev, ctx.relpath, node.lineno, node.col_offset,
                    f"sim-time value `{nm}` narrowed to "
                    f"{_dtype_of(node.args[0])} — int64 ns arithmetic "
                    f"wraps silently in a 32-bit lane"))
            continue
        dt = _dtype_of(node.func)
        if dt and node.args:
            nm = timey(node.args[0])
            if nm:
                findings.append(Finding(
                    rule_id, sev, ctx.relpath, node.lineno, node.col_offset,
                    f"sim-time value `{nm}` narrowed to {dt} — int64 ns "
                    f"arithmetic wraps silently in a 32-bit lane"))
            continue
        # ring.at[i].set(time_expr) on a tracked 32-bit carrier
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr in ("set", "add") and node.args:
            base = node.func.value
            root = None
            while isinstance(base, (ast.Subscript, ast.Attribute)):
                base = base.value
            if isinstance(base, ast.Name):
                root = base.id
            if root in narrow_vars:
                nm = timey(node.args[0])
                if nm:
                    findings.append(Finding(
                        rule_id, sev, ctx.relpath, node.lineno,
                        node.col_offset,
                        f"sim-time value `{nm}` stored into 32-bit carrier "
                        f"`{root}` — ns timestamps overflow int32"))
    return findings


# ---------------------------------------------------------------------------
# the surface map + twin model

STATE_SURFACE = "tcp-state-machine"
_ENTRY_RE = re.compile(r"^(py|c|kernel):([^:]+)(?::(.+))?$")


@dataclass
class MapEntry:
    plane: str      # py | c | kernel
    path: str       # relpath from the config root
    symbol: Optional[str]


def parse_map(raw: Dict[str, List[str]]) -> Dict[str, List[MapEntry]]:
    out: Dict[str, List[MapEntry]] = {}
    for surface, entries in raw.items():
        parsed = []
        for e in entries:
            m = _ENTRY_RE.match(e.strip())
            if m:
                parsed.append(MapEntry(m.group(1), m.group(2), m.group(3)))
        out[surface] = parsed
    return out


def _nearest_symbol(symbols: Dict[str, int], line: int) -> Optional[str]:
    """The defined symbol whose start line is nearest above ``line`` —
    the stable anchor for a value spelled inside a function body.
    Deterministic: ties (same start line) break alphabetically."""
    best: Optional[str] = None
    best_line = -1
    for name in sorted(symbols):
        ln = symbols[name]
        if ln <= line and ln > best_line:
            best, best_line = name, ln
    return best


class TwinModel:
    """All three planes extracted from one source set, per the map."""

    def __init__(self, sources: Dict[str, str],
                 surface_map: Dict[str, List[MapEntry]],
                 spec_text: Optional[str] = None):
        self.sources = sources
        self.map = surface_map
        # authoritative-spec digest for the SIM205 staleness check; the
        # fixture path passes spec_text (or puts the spec file in
        # ``sources``), twin_paths loads it from the config root
        from .genmark import SPEC_RELPATH, sha12
        if spec_text is None:
            spec_text = sources.get(SPEC_RELPATH)
        self.spec_text = spec_text
        self.spec_digest = sha12(spec_text) if spec_text is not None else None
        self.parse_errors: List[Finding] = []
        self.py_ctx: Dict[str, ModuleContext] = {}
        self.py_extracts: Dict[str, PyExtract] = {}
        self.c_extracts: Dict[str, cspec.CExtract] = {}
        self.kernel_paths: List[str] = []
        state_paths = {e.path for e in surface_map.get(STATE_SURFACE, ())}

        py_paths: List[str] = []
        c_paths: List[str] = []
        for entries in surface_map.values():
            for e in entries:
                if e.path not in sources:
                    continue
                if e.plane == "c":
                    if e.path not in c_paths:
                        c_paths.append(e.path)
                else:
                    if e.path not in py_paths:
                        py_paths.append(e.path)
                    if e.plane == "kernel" \
                            and e.path not in self.kernel_paths:
                        self.kernel_paths.append(e.path)

        for rel in sorted(py_paths):
            try:
                self.py_ctx[rel] = ModuleContext(rel, sources[rel])
            except SyntaxError as exc:
                self.parse_errors.append(Finding(
                    "SIM000", "error", rel, exc.lineno or 1,
                    (exc.offset or 1) - 1,
                    f"file does not parse: {exc.msg}"))
        # two folding passes so cross-module references (tcp.py -> defs,
        # stime) settle regardless of iteration order
        module_envs: Dict[str, Dict[str, object]] = {}
        for _ in range(2):
            for rel, ctx in self.py_ctx.items():
                base = rel.rsplit("/", 1)[-1][:-3]
                module_envs[base] = fold_module_env(ctx, module_envs)
        for rel, ctx in sorted(self.py_ctx.items()):
            self.py_extracts[rel] = extract_py(
                ctx, module_envs, with_transitions=rel in state_paths)
        for rel in sorted(c_paths):
            self.c_extracts[rel] = cspec.extract(rel, sources[rel], C_PROBES)

    # -- plane-tagged views ------------------------------------------------
    def plane_of(self, path: str) -> str:
        if path in self.c_extracts:
            return "c"
        if path in self.kernel_paths:
            return "kernel"
        return "python"

    def constants_by_canonical(
            self) -> Dict[str, List[Tuple[str, object, int, str]]]:
        """canonical -> [(path, value, line, anchor)], python plane first,
        then kernel, then C — sorted within a plane by path.  ``anchor``
        is the SYMBOL the value is attributed to (its own name for a
        named constant/enum member, the enclosing function for an inline
        coefficient probe): spec sources cite anchors, never raw line
        offsets, so a generated region growing or shrinking above a value
        cannot churn the emitted spec."""
        merged: Dict[str, List[Tuple[str, object, int, str]]] = {}

        def add(canon: str, path: str, value: object, line: int,
                anchor: str) -> None:
            merged.setdefault(canon, []).append((path, value, line, anchor))

        order = ([(rel, ext) for rel, ext in sorted(self.py_extracts.items())
                  if rel not in self.kernel_paths]
                 + [(rel, ext) for rel, ext in sorted(
                     self.py_extracts.items()) if rel in self.kernel_paths])
        for rel, ext in order:
            for name, (val, line) in sorted(ext.constants.items()):
                canon = CANON.get(name)
                if canon:
                    add(canon, rel, val, line, name)
            for canon, (val, line) in sorted(ext.probes.items()):
                add(canon, rel, val, line,
                    _nearest_symbol(ext.symbols, line) or "module")
        for rel, ext in sorted(self.c_extracts.items()):
            for name, (val, line) in sorted(ext.constants.items()):
                canon = CANON.get(name)
                if canon:
                    add(canon, rel, val, line, name)
            for members in ext.enums.values():
                for name, val, line in members:
                    canon = CANON.get(name)
                    if canon:
                        add(canon, rel, val, line, name)
            for canon, (val, line) in sorted(ext.probes.items()):
                add(canon, rel, val, line,
                    _nearest_symbol(ext.symbols, line) or "unit")
        return merged

    def _region_bodies(self, rel: str) -> List[Tuple[int, str]]:
        """(line_offset, body_text) for each simgen region in a mapped
        file.  The logic surface lives only inside generated regions, so
        the SIM206 read-back parses nothing else — a hand-written
        ``*_np`` kernel helper is not a logic function."""
        from .genmark import scan_regions
        regions, _ = scan_regions(self.sources[rel])
        return [(r.begin_line, r.body) for r in regions]

    def logic_functions_by_plane(
            self) -> Dict[str, Dict[str, Tuple[List[str], object, int, str]]]:
        """plane -> {logic_name: (args, ir_or_None, line, path)} parsed
        from the generated regions of every mapped source — the SIM206
        read-back input.  Functions are recognized by the naming
        convention logic_ir owns (``_g_*``, ``gen_*`` free functions,
        ``*_np``); body line numbers are offset back to file lines."""
        from . import logic_ir
        out: Dict[str, Dict[str, Tuple[List[str], object, int, str]]] = {
            "py": {}, "c": {}, "kernel": {}}
        for rel in sorted(self.c_extracts):
            for off, body in self._region_bodies(rel):
                parsed = cspec.parse_c_logic_functions(body)
                for name, (args, ir, line) in sorted(parsed.items()):
                    out["c"][name] = (args, ir, off + line, rel)
        for rel in sorted(self.py_extracts):
            plane = "kernel" if rel in self.kernel_paths else "py"
            for off, body in self._region_bodies(rel):
                parsed = logic_ir.parse_py_functions(body, plane)
                for name, (args, ir, line) in sorted(parsed.items()):
                    out[plane][name] = (args, ir, off + line, rel)
        return out

    def transition_tables(self) -> Dict[str, Dict]:
        """path -> {'pairs': {(from, to): line}, 'states': [..]} for every
        plane in the tcp-state-machine surface."""
        out: Dict[str, Dict] = {}
        for e in self.map.get(STATE_SURFACE, ()):
            ext = self.py_extracts.get(e.path) if e.plane != "c" \
                else self.c_extracts.get(e.path)
            if ext is None:
                continue
            pairs: Dict[Tuple[str, str], int] = {}
            for f, t, line in ext.transitions:
                pairs.setdefault((f, t), line)
            out[e.path] = {"pairs": pairs, "states": list(ext.states)}
        return out


# ---------------------------------------------------------------------------
# the rule catalog

class TwinRule:
    id = "SIM200"
    severity = "error"
    short = ""

    def run(self, twin: TwinModel) -> List[Finding]:  # pragma: no cover
        raise NotImplementedError


def _fmt(v: object) -> str:
    return repr(v)


class ConstantDriftRule(TwinRule):
    id = "SIM201"
    severity = "error"
    short = "protocol constant/threshold drift between twins"

    def run(self, twin: TwinModel) -> List[Finding]:
        findings: List[Finding] = []
        for canon, sites in sorted(twin.constants_by_canonical().items()):
            if len(sites) < 2:
                continue
            ref_path, ref_val, _ref_line, ref_anchor = sites[0]
            for path, val, line, _anchor in sites[1:]:
                if _values_equal(val, ref_val):
                    continue
                findings.append(Finding(
                    self.id, self.severity, path, line, 0,
                    f"protocol constant {canon} = {_fmt(val)} here but the "
                    f"{twin.plane_of(ref_path)} plane has {_fmt(ref_val)} "
                    f"({ref_path}#{ref_anchor}) — twins must agree or carry "
                    f"a reasoned pragma"))
        return findings


def _values_equal(a: object, b: object) -> bool:
    if isinstance(a, list) and isinstance(b, list):
        return len(a) == len(b) and all(
            _values_equal(x, y) for x, y in zip(a, b))
    if isinstance(a, (int, float)) and isinstance(b, (int, float)):
        return float(a) == float(b)
    return a == b


class TransitionDriftRule(TwinRule):
    id = "SIM202"
    severity = "error"
    short = "TCP state-transition table drift between twins"

    def run(self, twin: TwinModel) -> List[Finding]:
        tables = twin.transition_tables()
        if len(tables) < 2:
            return []
        paths = sorted(tables, key=lambda p: (twin.plane_of(p) != "python", p))
        ref_path = paths[0]
        ref = tables[ref_path]
        findings: List[Finding] = []
        for path in paths[1:]:
            cur = tables[path]
            for st in sorted(set(ref["states"]) - set(cur["states"])):
                findings.append(Finding(
                    self.id, self.severity, path, 1, 0,
                    f"TCP state {st!r} exists in {ref_path} but not in "
                    f"this twin's state table"))
            for st in sorted(set(cur["states"]) - set(ref["states"])):
                findings.append(Finding(
                    self.id, self.severity, path, 1, 0,
                    f"TCP state {st!r} exists only in this twin — "
                    f"{ref_path} has no such state"))
            missing = sorted(set(ref["pairs"]) - set(cur["pairs"]))
            for f, t in missing:
                ref_line = ref["pairs"][(f, t)]
                findings.append(Finding(
                    self.id, self.severity, path, 1, 0,
                    f"transition {f} -> {t} ({ref_path}:{ref_line}) has no "
                    f"counterpart in this twin"))
            extra = sorted(set(cur["pairs"]) - set(ref["pairs"]))
            for f, t in extra:
                findings.append(Finding(
                    self.id, self.severity, path, cur["pairs"][(f, t)], 0,
                    f"transition {f} -> {t} exists only in this twin — "
                    f"{ref_path} never makes it"))
        return findings


class SurfaceMapRule(TwinRule):
    id = "SIM203"
    severity = "error"
    short = "twin missing a mapped counterpart surface"

    def run(self, twin: TwinModel) -> List[Finding]:
        findings: List[Finding] = []
        for surface, entries in sorted(twin.map.items()):
            for e in entries:
                if e.path not in twin.sources:
                    findings.append(Finding(
                        self.id, self.severity, "pyproject.toml", 1, 0,
                        f"surface {surface!r} maps {e.plane}:{e.path} but "
                        f"the file does not exist"))
                    continue
                if not e.symbol:
                    continue
                if e.plane == "c":
                    ext = twin.c_extracts.get(e.path)
                    found = ext is not None and e.symbol in ext.symbols
                else:
                    ext2 = twin.py_extracts.get(e.path)
                    found = ext2 is not None and e.symbol in ext2.symbols
                if not found:
                    findings.append(Finding(
                        self.id, self.severity, e.path, 1, 0,
                        f"surface {surface!r} expects symbol `{e.symbol}` "
                        f"in this {e.plane} twin but it is not defined — "
                        f"unmapped or renamed counterpart"))
        return findings


class KernelDtypeRule(TwinRule):
    id = "SIM204"
    severity = "error"
    short = "dtype/overflow hazard in a device kernel"

    def run(self, twin: TwinModel) -> List[Finding]:
        findings: List[Finding] = []
        for rel in sorted(twin.kernel_paths):
            ctx = twin.py_ctx.get(rel)
            if ctx is not None:
                findings.extend(kernel_dtype_findings(ctx))
        return findings


class GeneratedRegionRule(TwinRule):
    id = "SIM205"
    severity = "error"
    short = "hand-edited or stale simgen-generated region"

    def run(self, twin: TwinModel) -> List[Finding]:
        from .genmark import scan_regions, sha12
        findings: List[Finding] = []
        for rel in sorted(twin.sources):
            if not rel.endswith((".py", ".cc", ".cpp", ".h")):
                continue
            regions, problems = scan_regions(twin.sources[rel])
            for line, msg in problems:
                findings.append(Finding(
                    self.id, self.severity, rel, line, 0, msg))
            for reg in regions:
                if sha12(reg.body) != reg.body_hash:
                    findings.append(Finding(
                        self.id, self.severity, rel, reg.begin_line, 0,
                        f"generated region {reg.name!r} was edited by "
                        f"hand (body digest drift) — the spec is "
                        f"authoritative: edit spec/protocol_spec.json "
                        f"and run `make gen`"))
                elif twin.spec_digest is not None \
                        and reg.spec_hash != twin.spec_digest:
                    findings.append(Finding(
                        self.id, self.severity, rel, reg.begin_line, 0,
                        f"generated region {reg.name!r} is stale: emitted "
                        f"from spec {reg.spec_hash}, current spec is "
                        f"{twin.spec_digest} — run `make gen`"))
        return findings


class LogicDriftRule(TwinRule):
    id = "SIM206"
    severity = "error"
    short = "emitted logic expression drifted from the spec IR"

    def run(self, twin: TwinModel) -> List[Finding]:
        import json

        from . import logic_ir
        if twin.spec_text is None:
            return []
        try:
            spec = json.loads(twin.spec_text)
        except ValueError:
            return []
        fns = spec.get("logic", {}).get("functions", {})
        constants = spec.get("constants", {})
        if not fns:
            return []
        findings: List[Finding] = []
        resolved: Dict[str, object] = {}
        for name, fn in sorted(fns.items()):
            try:
                resolved[name] = logic_ir.resolve(fn["expr"], constants)
            except logic_ir.IRError as exc:
                findings.append(Finding(
                    self.id, self.severity, "spec/protocol_spec.json", 1, 0,
                    f"logic fn {name}: spec expression does not resolve: "
                    f"{exc}"))
        for plane, got in sorted(twin.logic_functions_by_plane().items()):
            if not got:
                # a source set without a logic surface on this plane
                # (fixtures, partial maps) is not drift
                continue
            anchor = sorted(g[3] for g in got.values())[0]
            for name in sorted(set(fns) - set(got)):
                findings.append(Finding(
                    self.id, self.severity, anchor, 1, 0,
                    f"spec logic fn {name} has no "
                    f"`{logic_ir.plane_symbol(name, plane)}` on the "
                    f"{plane} plane — run `make gen`"))
            for name, (args, ir, line, rel) in sorted(got.items()):
                sym = logic_ir.plane_symbol(name, plane)
                fn = fns.get(name)
                if fn is None:
                    findings.append(Finding(
                        self.id, self.severity, rel, line, 0,
                        f"`{sym}` matches the generated-logic naming "
                        f"convention but the spec has no logic fn "
                        f"{name!r}"))
                    continue
                if list(args) != list(fn["args"]):
                    findings.append(Finding(
                        self.id, self.severity, rel, line, 0,
                        f"`{sym}` takes {list(args)} but the spec says "
                        f"{list(fn['args'])}"))
                    continue
                if ir is None:
                    findings.append(Finding(
                        self.id, self.severity, rel, line, 0,
                        f"`{sym}` body is not a single expression of the "
                        f"portable logic vocabulary — the spec is "
                        f"authoritative: edit spec/protocol_spec.json "
                        f"and run `make gen`"))
                    continue
                want = resolved.get(name)
                if want is None:
                    continue    # unresolvable spec expr already reported
                diff = logic_ir.structural_diff(want, ir)
                if diff:
                    findings.append(Finding(
                        self.id, self.severity, rel, line, 0,
                        f"`{sym}` drifted from the spec logic IR: {diff} "
                        f"— the spec is authoritative: edit "
                        f"spec/protocol_spec.json and run `make gen`"))
        return findings


CATALOG: List[TwinRule] = [
    ConstantDriftRule(),
    TransitionDriftRule(),
    SurfaceMapRule(),
    KernelDtypeRule(),
    GeneratedRegionRule(),
    LogicDriftRule(),
]


# ---------------------------------------------------------------------------
# spec serialization (simtwin --emit-spec)

SPEC_VERSION = 1


def build_spec(twin: TwinModel) -> Dict:
    """The cross-plane protocol IR as one JSON-stable dict: every mapping
    sorted, every value a plain int/float/str/list — byte-identical across
    runs and PYTHONHASHSEED values."""
    constants: Dict[str, Dict] = {}
    for canon, sites in sorted(twin.constants_by_canonical().items()):
        per_plane: Dict[str, Dict] = {}
        for path, val, _line, anchor in sites:
            plane = twin.plane_of(path)
            # symbol-anchored source attribution: a generated region
            # changing the file's length must not churn the spec
            per_plane.setdefault(plane, {
                "value": val, "source": f"{path}#{anchor}"})
        constants[canon] = per_plane
    transitions: Dict[str, Dict] = {}
    for path, table in sorted(twin.transition_tables().items()):
        transitions[path] = {
            "plane": twin.plane_of(path),
            "states": sorted(table["states"]),
            "pairs": sorted(f"{f} -> {t}" for f, t in table["pairs"]),
        }
    surfaces: Dict[str, Dict] = {}
    for surface, entries in sorted(twin.map.items()):
        per_file: Dict[str, List[str]] = {}
        for e in sorted(entries,
                        key=lambda x: (x.plane, x.path, x.symbol or "")):
            per_file.setdefault(e.plane + ":" + e.path, []).append(
                e.symbol or "*")
        surfaces[surface] = per_file
    # the logic surface as read back from the authoritative python plane:
    # parsed (literal) expression trees, one entry per emitted function
    from . import logic_ir
    logic: Dict[str, Dict] = {}
    for name, (args, ir, _line, rel) in sorted(
            twin.logic_functions_by_plane()["py"].items()):
        if ir is None:
            continue
        logic[name] = {
            "args": list(args), "expr": ir,
            "source": f"{rel}#{logic_ir.plane_symbol(name, 'py')}",
        }
    return {
        "version": SPEC_VERSION,
        "generator": "simtwin --emit-spec",
        "constants": constants,
        "transitions": transitions,
        "surfaces": surfaces,
        "logic": logic,
    }
