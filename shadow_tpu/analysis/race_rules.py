"""The simrace rule catalog (SIM101-SIM110): package-wide concurrency
analysis over the whole-module model in :class:`PackageContext`.

Where simlint (rules.py) proves per-file determinism contracts, these
rules prove the *threading* contracts the simulator grew in PRs 2-4: the
threaded scheduler's lock hierarchy, the watchdog helper threads on every
fault seam, the lock-guarded trace ring and logger, and the tag-based
shard protocol.  Ordering bugs here surface as silent nondeterminism that
digest-parity tests catch only probabilistically — these rules catch them
at analysis time.

=======  ========  ====================================================
rule     severity  invariant guarded
=======  ========  ====================================================
SIM101   error     no lock-order inversion: two locks never acquired in
                   opposite nesting orders anywhere in the package
SIM102   error     state shared with a ``threading.Thread`` target is
                   mutated/read on both sides under one lock (or the
                   ordering is justified with a pragma)
SIM103   warning   no blocking call (Connection recv/send, sendall,
                   sleep, unbounded join/wait/subprocess) while holding
                   a lock
SIM110   error     the tag-based parent<->child shard protocol round-
                   trips: every sent tag has a handler, arities match,
                   no reachable mutual-wait (see protocol.py)
=======  ========  ====================================================

The model is deliberately scoped to stay sound-ish without whole-program
dataflow: lock identities resolve through ``self`` attributes assigned a
``threading.Lock()``-family factory (collections of locks —
``self._host_locks[hid]`` — collapse to one identity per collection, so
hierarchical per-host locking is not a false inversion), local aliases
(``lk = self._exec_locks[hid]``), and lock-ish attribute names as a
fallback; thread reachability is same-module (a target plus the local
functions/methods it calls), which covers every helper-thread idiom this
codebase uses without dragging the whole engine into the thread set.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .simlint import Config, Finding, ModuleContext

LOCK_FACTORIES = {
    "threading.Lock", "threading.RLock", "threading.Condition",
    "threading.Semaphore", "threading.BoundedSemaphore",
    "multiprocessing.Lock", "multiprocessing.RLock",
    "multiprocessing.Condition",
}

# attribute-name fallback: `with self.foo:` counts as a lock when the
# name says so, even if the assignment lives in another module
_LOCKISH = ("lock", "cond", "mutex", "sem")

# method names that mutate their receiver in place (shared with SIM006's
# closure-mutation logic, duplicated here to keep the catalogs decoupled)
MUTATORS = {"append", "extend", "insert", "remove", "clear", "add",
            "update", "setdefault", "pop", "popitem", "discard"}


def _is_lockish_name(name: str) -> bool:
    low = name.lower()
    return any(part in low for part in _LOCKISH)


# ---------------------------------------------------------------------------
# lock identities


class LockId(tuple):
    """Hashable lock identity: (kind, owner, name).

    kind 'attr'     — ``self.X`` where X was assigned a lock factory (or
                      is lock-ish by name); owner = class qualname
    kind 'attrcoll' — ``self.X[k]`` collection of locks; ONE identity per
                      collection (members are unordered peers, so a
                      nested acquire within one collection is not a
                      statically decidable inversion and is skipped)
    kind 'local'    — function-local ``x = threading.Lock()``; owner =
                      function qualname (closures included)
    kind 'global'   — module-level lock; owner = relpath
    """

    def __new__(cls, kind: str, owner: str, name: str):
        return super().__new__(cls, (kind, owner, name))

    @property
    def kind(self) -> str:
        return self[0]

    def label(self) -> str:
        return f"{self[1]}.{self[2]}" if self[1] else self[2]


# ---------------------------------------------------------------------------
# per-function concurrency summary


class FuncInfo:
    __slots__ = ("ctx", "node", "qual", "cls_qual", "self_name",
                 "local_locks", "locals_")

    def __init__(self, ctx: ModuleContext, node: ast.AST, qual: str,
                 cls_qual: Optional[str]):
        self.ctx = ctx
        self.node = node
        self.qual = qual
        self.cls_qual = cls_qual
        args = node.args
        self.self_name = (args.args[0].arg
                          if cls_qual and args.args else None)
        self.local_locks: Dict[str, LockId] = {}
        self.locals_ = _own_locals(node)


def _own_locals(fn: ast.AST) -> Set[str]:
    """Names bound in ``fn``'s own scope (params, bare-Name stores,
    nested def names) — NOT descending into nested function bodies."""
    a = fn.args
    names = {x.arg for x in a.args + a.kwonlyargs + a.posonlyargs}
    if a.vararg:
        names.add(a.vararg.arg)
    if a.kwarg:
        names.add(a.kwarg.arg)
    for node in _walk_scope(fn):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
            names.add(node.id)
        elif node is not fn and isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            names.add(node.name)
    return names


def _walk_scope(node: ast.AST) -> Iterable[ast.AST]:
    """ast.walk that stays inside one function scope: nested function /
    class / lambda nodes are yielded (their NAMES are scope facts) but
    never descended into (their bodies are separate scopes)."""
    stack = [node]
    while stack:
        cur = stack.pop()
        yield cur
        if cur is not node and isinstance(
                cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                      ast.ClassDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(cur))


class ModuleConcurrency:
    """One module's concurrency facts: functions, lock bindings, and the
    per-function event streams (acquisitions, calls, mutations, loads)
    recorded with the lock set held at each point."""

    def __init__(self, ctx: ModuleContext):
        self.ctx = ctx
        self.funcs: Dict[str, FuncInfo] = {}
        self.class_lock_attrs: Dict[Tuple[str, str], str] = {}  # -> kind
        self.module_locks: Dict[str, LockId] = {}
        # per function qualname:
        self.acquisitions: Dict[str, List[Tuple[Tuple, LockId, ast.AST]]] = {}
        self.calls: Dict[str, List[Tuple[Tuple, ast.Call]]] = {}
        self.mutations: Dict[str, List[Tuple[Tuple, str, str, ast.AST]]] = {}
        self.loads: Dict[str, List[Tuple[Tuple, str, str, ast.AST]]] = {}
        self.callees: Dict[str, Set[str]] = {}
        self.thread_spawns: List[Tuple[str, ast.Call, Optional[str]]] = []
        self._index()
        for qual in self.funcs:
            self._summarize(qual)

    # -- indexing ----------------------------------------------------------
    def _index(self) -> None:
        ctx = self.ctx
        for node in ctx.walk(ast.FunctionDef, ast.AsyncFunctionDef):
            qual, cls_qual = self._qualify(node)
            self.funcs[qual] = FuncInfo(ctx, node, qual, cls_qual)
        # lock-factory bindings: self.X = Lock() / self.X[k] = Lock() /
        # module-level N = Lock() / function-local n = Lock()
        for node in ctx.walk(ast.Assign):
            if len(node.targets) != 1 or not isinstance(node.value, ast.Call):
                continue
            r = ctx.resolve(node.value.func)
            if r is None or r[0] not in LOCK_FACTORIES:
                continue
            t = node.targets[0]
            owner = self._enclosing_func(node)
            if isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name):
                cls = self._enclosing_class_qual(node)
                if cls is not None:
                    self.class_lock_attrs.setdefault((cls, t.attr), "attr")
            elif isinstance(t, ast.Subscript) and \
                    isinstance(t.value, ast.Attribute) and \
                    isinstance(t.value.value, ast.Name):
                cls = self._enclosing_class_qual(node)
                if cls is not None:
                    self.class_lock_attrs[(cls, t.value.attr)] = "attrcoll"
            elif isinstance(t, ast.Name):
                if owner is None:
                    self.module_locks[t.id] = LockId(
                        "global", ctx.relpath, t.id)
                else:
                    owner.local_locks[t.id] = LockId(
                        "local", owner.qual, t.id)

    def _qualify(self, node: ast.AST) -> Tuple[str, Optional[str]]:
        names = [node.name]
        cur = self.ctx.parent(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                names.append(cur.name)
            cur = self.ctx.parent(cur)
        parts = list(reversed(names))
        cls_qual = ".".join(parts[:-1]) if isinstance(
            self.ctx.parent(node), ast.ClassDef) else None
        return ".".join(parts), cls_qual

    def _enclosing_func(self, node: ast.AST) -> Optional[FuncInfo]:
        fn = self.ctx.enclosing_function(node)
        if fn is None:
            return None
        return self.funcs.get(self._qualify(fn)[0])

    def _enclosing_class_qual(self, node: ast.AST) -> Optional[str]:
        cur = self.ctx.parent(node)
        parts: List[str] = []
        cls = None
        while cur is not None:
            if isinstance(cur, ast.ClassDef) and cls is None:
                cls = cur
                parts.append(cur.name)
            elif cls is not None and isinstance(
                    cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef)):
                parts.append(cur.name)
            cur = self.ctx.parent(cur)
        return ".".join(reversed(parts)) if cls is not None else None

    # -- lock resolution ---------------------------------------------------
    def resolve_lock(self, fi: FuncInfo, expr: ast.AST) -> Optional[LockId]:
        if isinstance(expr, ast.Name):
            if expr.id in fi.local_locks:
                return fi.local_locks[expr.id]
            # closure lock: a local lock of any enclosing function
            cur = self.ctx.enclosing_function(fi.node)
            while cur is not None:
                outer = self.funcs.get(self._qualify(cur)[0])
                if outer and expr.id in outer.local_locks:
                    return outer.local_locks[expr.id]
                cur = self.ctx.enclosing_function(cur)
            return self.module_locks.get(expr.id)
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name):
            base, attr = expr.value.id, expr.attr
            if fi.self_name and base == fi.self_name and fi.cls_qual:
                kind = self.class_lock_attrs.get((fi.cls_qual, attr))
                if kind == "attr":
                    return LockId("attr", fi.cls_qual, attr)
                if kind is None and _is_lockish_name(attr):
                    return LockId("attr", fi.cls_qual, attr)
            elif _is_lockish_name(attr):
                return LockId("attr", f"{self.ctx.relpath}:{base}", attr)
            return None
        if isinstance(expr, ast.Subscript):
            coll = self._lock_collection(fi, expr.value)
            return coll
        if isinstance(expr, ast.Call) and \
                isinstance(expr.func, ast.Attribute) and \
                expr.func.attr == "get":
            return self._lock_collection(fi, expr.func.value)
        return None

    def _lock_collection(self, fi: FuncInfo,
                         base: ast.AST) -> Optional[LockId]:
        if isinstance(base, ast.Attribute) and \
                isinstance(base.value, ast.Name) and fi.self_name and \
                base.value.id == fi.self_name and fi.cls_qual:
            kind = self.class_lock_attrs.get((fi.cls_qual, base.attr))
            if kind == "attrcoll":
                return LockId("attrcoll", fi.cls_qual, base.attr)
        return None

    # -- the region/event walker ------------------------------------------
    def _summarize(self, qual: str) -> None:
        fi = self.funcs[qual]
        acqs: List[Tuple[Tuple, LockId, ast.AST]] = []
        calls: List[Tuple[Tuple, ast.Call]] = []
        muts: List[Tuple[Tuple, str, str, ast.AST]] = []
        loads: List[Tuple[Tuple, str, str, ast.AST]] = []
        callees: Set[str] = set()
        acquired: List[LockId] = []      # open .acquire() regions

        def selfattr(e: ast.AST) -> Optional[str]:
            if isinstance(e, ast.Attribute) and \
                    isinstance(e.value, ast.Name) and fi.self_name and \
                    e.value.id == fi.self_name and fi.cls_qual:
                return f"{fi.cls_qual}.{e.attr}"
            return None

        def record_mut(target: ast.AST, held: Tuple, node: ast.AST) -> None:
            base = target
            if isinstance(base, ast.Subscript):
                base = base.value
            sa = selfattr(base)
            if sa is not None:
                muts.append((held, "selfattr", sa, node))
            elif isinstance(base, ast.Name):
                muts.append((held, "name", base.id, node))

        def visit(node: ast.AST, held: Tuple) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef, ast.Lambda)):
                return                     # separate scope
            if isinstance(node, (ast.With, ast.AsyncWith)):
                new = held
                for item in node.items:
                    lk = self.resolve_lock(fi, item.context_expr)
                    if lk is not None:
                        acqs.append((new + tuple(acquired), lk, node))
                        new = new + (lk,)
                    else:
                        visit(item.context_expr, new)
                for s in node.body:
                    visit(s, new)
                return
            if isinstance(node, ast.Call):
                eff = held + tuple(acquired)
                f = node.func
                if isinstance(f, ast.Attribute):
                    lk = self.resolve_lock(fi, f.value)
                    if lk is not None and f.attr == "acquire":
                        acqs.append((eff, lk, node))
                        acquired.append(lk)
                    elif lk is not None and f.attr == "release":
                        if lk in acquired:
                            acquired.remove(lk)
                    if f.attr in MUTATORS:
                        record_mut(f.value, eff, node)
                calls.append((eff, node))
                if isinstance(f, ast.Name):
                    callees.add(f.id)
                elif isinstance(f, ast.Attribute) and \
                        isinstance(f.value, ast.Name) and fi.self_name and \
                        f.value.id == fi.self_name:
                    callees.add(f.attr)
                for child in ast.iter_child_nodes(node):
                    visit(child, held)
                return
            if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                eff = held + tuple(acquired)
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    if isinstance(t, (ast.Subscript, ast.Attribute)) or \
                            isinstance(node, ast.AugAssign):
                        record_mut(t, eff, node)
                if isinstance(node, ast.Assign) and \
                        isinstance(node.value, (ast.Subscript, ast.Call)) \
                        and len(node.targets) == 1 and \
                        isinstance(node.targets[0], ast.Name):
                    # local alias of a lock (exec_lock = self._locks[hid])
                    lk = self.resolve_lock(fi, node.value)
                    if lk is not None:
                        fi.local_locks[node.targets[0].id] = lk
                value = node.value
                if value is not None:
                    visit(value, held)
                return
            if isinstance(node, ast.Name):
                eff = held + tuple(acquired)
                loads.append((eff, "name", node.id, node))
                return
            if isinstance(node, ast.Attribute):
                sa = selfattr(node)
                if sa is not None:
                    loads.append((held + tuple(acquired), "selfattr", sa,
                                  node))
                for child in ast.iter_child_nodes(node):
                    visit(child, held)
                return
            for child in ast.iter_child_nodes(node):
                visit(child, held)

        for stmt in fi.node.body:
            visit(stmt, ())
        self.acquisitions[qual] = acqs
        self.calls[qual] = calls
        self.mutations[qual] = muts
        self.loads[qual] = loads
        self.callees[qual] = callees
        # threading.Thread(target=...) spawns
        for _, call in calls:
            r = self.ctx.resolve(call.func)
            if r is None or r[0] != "threading.Thread":
                continue
            target = next((kw.value for kw in call.keywords
                           if kw.arg == "target"), None)
            tq = self._target_qual(fi, target)
            self.thread_spawns.append((qual, call, tq))

    def _target_qual(self, fi: FuncInfo,
                     target: Optional[ast.AST]) -> Optional[str]:
        if isinstance(target, ast.Name):
            # nearest def: nested in the spawning function, else module
            for cand in (f"{fi.qual}.{target.id}", target.id):
                if cand in self.funcs:
                    return cand
            # method referenced without self (rare) or sibling nested def
            for qual in self.funcs:
                if qual.endswith(f".{target.id}"):
                    return qual
        elif isinstance(target, ast.Attribute) and \
                isinstance(target.value, ast.Name) and fi.self_name and \
                target.value.id == fi.self_name and fi.cls_qual:
            cand = f"{fi.cls_qual}.{target.attr}"
            if cand in self.funcs:
                return cand
        return None

    def thread_reachable(self, root: str) -> Set[str]:
        """Same-module functions reachable from thread target ``root``
        through bare-name and self-method calls."""
        seen = {root}
        frontier = [root]
        while frontier:
            cur = frontier.pop()
            fi = self.funcs.get(cur)
            for name in self.callees.get(cur, ()):
                cands = [f"{cur}.{name}", name]
                if fi is not None and fi.cls_qual:
                    cands.append(f"{fi.cls_qual}.{name}")
                for cand in cands:
                    if cand in self.funcs and cand not in seen:
                        seen.add(cand)
                        frontier.append(cand)
        return seen


# ---------------------------------------------------------------------------
# package context


class PackageContext:
    """All parsed modules + the lazily-built concurrency model."""

    def __init__(self, contexts: List[ModuleContext],
                 config: Optional[Config] = None):
        self.contexts = {c.relpath: c for c in contexts}
        self.config = config or Config()
        self.concurrency: Dict[str, ModuleConcurrency] = {}
        for rel, ctx in sorted(self.contexts.items()):
            self.concurrency[rel] = ModuleConcurrency(ctx)

    def locks_acquired_closure(self, rel: str, qual: str,
                               _seen: Optional[Set] = None) -> Set[LockId]:
        """Every lock ``qual`` (or a same-module callee) may acquire."""
        _seen = _seen if _seen is not None else set()
        key = (rel, qual)
        if key in _seen:
            return set()
        _seen.add(key)
        mc = self.concurrency.get(rel)
        if mc is None or qual not in mc.funcs:
            return set()
        out = {lk for _, lk, _ in mc.acquisitions.get(qual, ())}
        fi = mc.funcs[qual]
        for name in mc.callees.get(qual, ()):
            for cand in (f"{qual}.{name}", name,
                         f"{fi.cls_qual}.{name}" if fi.cls_qual else None):
                if cand and cand in mc.funcs:
                    out |= self.locks_acquired_closure(rel, cand, _seen)
                    break
        return out


class PackageRule:
    """One concurrency invariant checked over the whole package."""

    id: str = "SIM100"
    severity: str = "error"
    short: str = ""

    def run(self, pkg: PackageContext) -> List[Finding]:  # pragma: no cover
        raise NotImplementedError

    def finding(self, relpath: str, node: ast.AST, message: str) -> Finding:
        return Finding(self.id, self.severity, relpath,
                       getattr(node, "lineno", 1),
                       getattr(node, "col_offset", 0), message)


# ---------------------------------------------------------------------------
# SIM101 — lock-order inversion


class LockOrderRule(PackageRule):
    """Two locks acquired in opposite nesting orders anywhere in the
    package can deadlock the moment two threads interleave — the classic
    inversion the reference avoids with its ordered dual-locking
    (scheduler_policy_host_steal.c:366-416).  Edges propagate one call
    level deep (acquiring inside a helper called under a lock counts);
    acquisitions within ONE lock collection (``self._host_locks[a]`` then
    ``[b]``) are skipped — member order is not statically decidable."""

    id = "SIM101"
    severity = "error"
    short = ("lock-order inversion: locks acquired in opposite nesting "
             "orders (deadlock hazard)")

    def run(self, pkg: PackageContext) -> List[Finding]:
        edges: Dict[Tuple[LockId, LockId], Tuple[str, ast.AST]] = {}
        for rel, mc in pkg.concurrency.items():
            for qual in mc.funcs:
                for held, lk, node in mc.acquisitions.get(qual, ()):
                    for h in held:
                        if h != lk:
                            edges.setdefault((h, lk), (rel, node))
                for held, call in mc.calls.get(qual, ()):
                    if not held:
                        continue
                    f = call.func
                    fi = mc.funcs[qual]
                    # propagate through local functions and SELF methods
                    # only — `q.pop()` on an arbitrary receiver must not
                    # resolve to a same-named method of this class
                    name = None
                    if isinstance(f, ast.Name):
                        name = f.id
                    elif isinstance(f, ast.Attribute) and \
                            isinstance(f.value, ast.Name) and \
                            fi.self_name and f.value.id == fi.self_name:
                        name = f.attr
                    if name is None:
                        continue
                    for cand in (f"{qual}.{name}", name,
                                 f"{fi.cls_qual}.{name}"
                                 if fi.cls_qual else None):
                        if cand and cand in mc.funcs:
                            for lk in pkg.locks_acquired_closure(rel, cand):
                                for h in held:
                                    if h != lk:
                                        edges.setdefault((h, lk),
                                                         (rel, call))
                            break
        # reachability over the edge graph: an edge is part of a cycle iff
        # its head can reach its tail
        adj: Dict[LockId, Set[LockId]] = {}
        for (a, b) in edges:
            adj.setdefault(a, set()).add(b)

        def reaches(src: LockId, dst: LockId) -> bool:
            seen = {src}
            frontier = [src]
            while frontier:
                cur = frontier.pop()
                for nxt in adj.get(cur, ()):
                    if nxt == dst:
                        return True
                    if nxt not in seen:
                        seen.add(nxt)
                        frontier.append(nxt)
            return False

        out: List[Finding] = []
        for (a, b), (rel, node) in sorted(
                edges.items(), key=lambda kv: (kv[1][0],
                                               kv[1][1].lineno)):
            if reaches(b, a):
                out.append(self.finding(
                    rel, node,
                    f"lock-order inversion: `{b.label()}` acquired while "
                    f"holding `{a.label()}`, but the opposite order also "
                    "exists — pick one global order (deadlock hazard)"))
        return out


# ---------------------------------------------------------------------------
# SIM102 — unsynchronized thread-shared state


class ThreadSharedStateRule(PackageRule):
    """State a ``threading.Thread`` target mutates is only safe to touch
    from the spawning side under the SAME lock (or after a join that the
    analysis cannot see — justify THAT with a pragma naming the barrier).
    Covers closure variables (the watchdog-helper idiom: a nested
    ``_work`` writing its result box) and ``self`` attributes mutated by
    thread-target methods; accesses before the Thread construction are
    ordered by the start() happens-before edge and ignored."""

    id = "SIM102"
    severity = "error"
    short = ("thread-shared state mutated/read without a shared lock "
             "(silent race)")

    def run(self, pkg: PackageContext) -> List[Finding]:
        out: List[Finding] = []
        for rel, mc in sorted(pkg.concurrency.items()):
            for spawner_qual, call, target_qual in mc.thread_spawns:
                if target_qual is None:
                    continue
                out.extend(self._check_target(mc, rel, spawner_qual, call,
                                              target_qual))
        return out

    def _check_target(self, mc: ModuleConcurrency, rel: str,
                      spawner_qual: str, spawn_call: ast.Call,
                      target_qual: str) -> List[Finding]:
        out: List[Finding] = []
        thread_funcs = mc.thread_reachable(target_qual)
        spawner = mc.funcs.get(spawner_qual)
        target = mc.funcs.get(target_qual)
        if spawner is None or target is None:
            return out
        spawn_line = spawn_call.lineno
        # shared CLOSURE names: used in the thread set, local to the
        # spawner (the enclosing scope the closure captures)
        spawner_locals = spawner.locals_
        reported: Set[Tuple[str, str]] = set()
        for tq in sorted(thread_funcs):
            tfi = mc.funcs[tq]
            for held, kind, name, node in mc.mutations.get(tq, ()):
                if kind == "name":
                    if name in tfi.locals_ or name not in spawner_locals:
                        continue
                    main = self._main_accesses(mc, spawner_qual, "name",
                                               name, spawn_line)
                elif kind == "selfattr":
                    main = self._class_accesses(mc, name, thread_funcs)
                else:
                    continue
                if not main:
                    continue
                key = (tq, name)
                if key in reported:
                    continue
                unlocked_main = [n for h, n in main if not h]
                if held and not unlocked_main:
                    continue               # both sides locked
                reported.add(key)
                anchor, side = (node, "thread") if not held \
                    else (unlocked_main[0], "main")
                label = name.split(".")[-1]
                other = ("the spawning scope" if kind == "name"
                         else "another method")
                out.append(self.finding(
                    rel, anchor,
                    f"`{label}` is shared with thread target "
                    f"`{target.node.name}` (started near line "
                    f"{spawn_line}) and the {side}-side access holds no "
                    f"lock while {other} touches it too — guard both "
                    "sides with one threading.Lock, or justify the "
                    "ordering (join/barrier) with a pragma"))
        return out

    @staticmethod
    def _main_accesses(mc: ModuleConcurrency, spawner_qual: str,
                       kind: str, name: str,
                       spawn_line: int) -> List[Tuple[Tuple, ast.AST]]:
        got: List[Tuple[Tuple, ast.AST]] = []
        for held, k, n, node in (list(mc.loads.get(spawner_qual, ())) +
                                 list(mc.mutations.get(spawner_qual, ()))):
            if k == kind and n == name and node.lineno > spawn_line:
                got.append((held, node))
        return got

    @staticmethod
    def _class_accesses(mc: ModuleConcurrency, attr: str,
                        thread_funcs: Set[str]
                        ) -> List[Tuple[Tuple, ast.AST]]:
        got: List[Tuple[Tuple, ast.AST]] = []
        for qual, fi in mc.funcs.items():
            if qual in thread_funcs or fi.node.name == "__init__":
                continue
            for held, k, n, node in (list(mc.loads.get(qual, ())) +
                                     list(mc.mutations.get(qual, ()))):
                if k == "selfattr" and n == attr:
                    got.append((held, node))
        return got


# ---------------------------------------------------------------------------
# SIM103 — blocking calls under a lock


class BlockingUnderLockRule(PackageRule):
    """A blocking call made while holding a lock turns one slow peer into
    a stalled simulator: every other thread wanting the lock parks behind
    a wait the supervision watchdogs (PR 2) cannot preempt.  Condition
    waits on the HELD lock are exempt (wait releases it)."""

    id = "SIM103"
    severity = "warning"
    short = ("blocking call (recv/send/sleep/unbounded join/wait) while "
             "holding a lock")

    BLOCKING_ATTRS = {"recv", "recv_bytes", "send", "sendall", "send_bytes"}
    SUBPROCESS_FNS = {"subprocess.run", "subprocess.call",
                      "subprocess.check_call", "subprocess.check_output"}

    def run(self, pkg: PackageContext) -> List[Finding]:
        out: List[Finding] = []
        for rel, mc in sorted(pkg.concurrency.items()):
            for qual in mc.funcs:
                fi = mc.funcs[qual]
                for held, call in mc.calls.get(qual, ()):
                    if not held:
                        continue
                    msg = self._blocking_reason(mc, fi, call, held)
                    if msg is not None:
                        out.append(self.finding(
                            rel, call,
                            f"{msg} while holding "
                            f"`{held[-1].label()}` — blocking under a "
                            "lock stalls every thread contending for it; "
                            "move the wait outside the critical section"))
        return out

    def _blocking_reason(self, mc: ModuleConcurrency, fi: FuncInfo,
                         call: ast.Call, held: Tuple) -> Optional[str]:
        r = mc.ctx.resolve(call.func)
        canon = r[0] if r is not None else None
        if canon == "time.sleep":
            return "`time.sleep`"
        if canon in self.SUBPROCESS_FNS and \
                not any(kw.arg == "timeout" for kw in call.keywords):
            return f"unbounded `{canon}`"
        if canon == "select.select" and len(call.args) < 4:
            return "unbounded `select.select`"
        f = call.func
        if not isinstance(f, ast.Attribute):
            return None
        if f.attr in self.BLOCKING_ATTRS:
            return f"pipe/socket `.{f.attr}()`"
        bounded = bool(call.args) or \
            any(kw.arg == "timeout" for kw in call.keywords)
        if f.attr in ("join", "wait") and not bounded:
            if f.attr == "wait":
                lk = mc.resolve_lock(fi, f.value)
                if lk is not None and lk in held:
                    return None     # Condition.wait on the held lock
            return f"unbounded `.{f.attr}()`"
        return None


CATALOG: List[PackageRule] = [
    LockOrderRule(),
    ThreadSharedStateRule(),
    BlockingUnderLockRule(),
]


def _install_protocol_rule() -> None:
    # deferred: protocol.py imports PackageRule from this module, so the
    # SIM110 instance joins the catalog after both modules exist
    from .protocol import ShardProtocolRule
    if not any(r.id == "SIM110" for r in CATALOG):
        CATALOG.append(ShardProtocolRule())


_install_protocol_rule()
