"""The simlint rule catalog (SIM001-SIM006).

Each rule guards one real invariant of this codebase — the docstrings name
the contract and the module(s) that own it.  Rules see through import
renames via ModuleContext.resolve (the shared alias tracker), so
``import time as _t; _t.monotonic()`` is caught, while the established
``_walltime`` / ``_wt`` aliases mark DELIBERATE wall-time (perf
telemetry — obs/, engine heartbeats, watchdogs).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .simlint import Finding, ModuleContext, Rule

# the alias names that declare "this is wall-clock time, on purpose":
# telemetry code imports `time as _walltime` (module scope) or `time as
# _wt` (function scope) and the digest never sees the values
WALLTIME_ALIASES = ("_walltime", "_wt")


def _uses_convention_alias(root: str) -> bool:
    return root in WALLTIME_ALIASES


# ---------------------------------------------------------------------------
# SIM001 — wall-clock access


class WallClockRule(Rule):
    """Sim code must take time from the virtual clock (core/stime.py, the
    reference's SimulationTime) — a wall-clock read on a sim path makes
    event timing depend on host speed and breaks run-to-run digest parity.
    Wall-time for telemetry is declared via the ``_walltime``/``_wt``
    import alias or a [tool.simlint.allow] SIM001 module pattern."""

    id = "SIM001"
    severity = "error"
    short = ("wall-clock access in sim code (use core.stime / "
             "SimulationTime, or the _walltime/_wt alias for telemetry)")

    WALL_TIME_ATTRS = {
        "time", "time_ns", "monotonic", "monotonic_ns", "perf_counter",
        "perf_counter_ns", "process_time", "process_time_ns",
        "thread_time", "thread_time_ns", "clock_gettime",
        "clock_gettime_ns", "localtime", "gmtime",
    }
    WALL_DATETIME = {
        "datetime.datetime.now", "datetime.datetime.utcnow",
        "datetime.datetime.today", "datetime.date.today",
    }

    def run(self, ctx: ModuleContext) -> List[Finding]:
        out: List[Finding] = []
        for node in ctx.walk(ast.Attribute, ast.Name):
            if isinstance(node, ast.Attribute) and isinstance(
                    ctx.parent(node), ast.Attribute):
                continue                 # only the full chain, once
            r = ctx.resolve(node)
            if r is None:
                continue
            canon, root = r
            hit = None
            if canon.startswith("time.") and \
                    canon.split(".", 1)[1] in self.WALL_TIME_ATTRS:
                hit = canon
            elif canon in self.WALL_DATETIME:
                # resolve() canonicalizes every real import form
                # (`import datetime`, `from datetime import datetime/date`)
                # to these full dotted paths
                hit = canon
            if hit is None or _uses_convention_alias(root):
                continue
            out.append(self.finding(
                ctx, node,
                f"wall-clock access `{hit}` — sim code must use the "
                "virtual clock (core.stime); if this is deliberate "
                "telemetry, alias the import as "
                "`import time as _walltime` (or `_wt`)"))
        return out


# ---------------------------------------------------------------------------
# SIM002 — nondeterministic randomness


class NondetRandomRule(Rule):
    """Every random draw must derive from the master seed via the per-host
    stream tree (core/rng.py: master -> slave -> per-host, the reference's
    utility/random.c + master.c:417) or an explicitly seeded
    ``np.random.default_rng(seed)``.  Module-global RNG state, os.urandom
    and uuid4 give a different run every time."""

    id = "SIM002"
    severity = "error"
    short = ("nondeterministic randomness (use host.random streams or "
             "np.random.default_rng(seed))")

    # np.random attrs that are NOT the legacy global state
    NP_OK = {"default_rng", "Generator", "SeedSequence", "PCG64",
             "Philox", "MT19937", "SFC64", "BitGenerator", "RandomState"}
    # stdlib random attrs that construct seeded instances (fine)
    PY_OK = {"Random", "getstate", "setstate"}
    FLAT = {"os.urandom": "os.urandom",
            "uuid.uuid4": "uuid.uuid4 (random UUID)",
            "uuid.uuid1": "uuid.uuid1 (clock/MAC UUID)"}

    def run(self, ctx: ModuleContext) -> List[Finding]:
        out: List[Finding] = []
        for node in ctx.walk(ast.Attribute, ast.Name):
            if isinstance(node, ast.Attribute) and isinstance(
                    ctx.parent(node), ast.Attribute):
                continue
            r = ctx.resolve(node)
            if r is None:
                continue
            canon, _root = r
            msg = None
            if canon in self.FLAT:
                msg = (f"`{self.FLAT[canon]}` is nondeterministic — derive "
                       "ids/bytes from a host.random stream")
            elif canon.startswith("secrets."):
                msg = (f"`{canon}` draws from the OS entropy pool — "
                       "sim code must use seeded streams")
            elif canon.startswith("numpy.random."):
                attr = canon.split(".", 2)[2].split(".")[0]
                if attr not in self.NP_OK:
                    msg = (f"`np.random.{attr}` uses numpy's legacy global "
                           "RNG state — use np.random.default_rng(seed)")
            elif canon.startswith("random.") and not canon.startswith(
                    "random.Random."):
                attr = canon.split(".", 1)[1].split(".")[0]
                if attr not in self.PY_OK:
                    msg = (f"`random.{attr}` uses the module-global RNG — "
                           "use a host.random stream "
                           "(core/rng.py) or random.Random(seed)")
            if msg is not None:
                out.append(self.finding(ctx, node, msg))
        return out


# ---------------------------------------------------------------------------
# SIM003 — unordered iteration


def _set_env_for_scope(scope: ast.AST) -> Set[str]:
    """Names assigned (once, directly) a set-typed expression in ``scope``
    — a one-level local type inference, enough for the codebase idiom
    ``pending = set(...) ... for x in pending``."""
    env: Set[str] = set()
    unsafe: Set[str] = set()
    for node in ast.walk(scope):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            name = node.targets[0].id
            if _is_set_expr(node.value, env):
                env.add(name)
            else:
                unsafe.add(name)
    return env - unsafe


def _is_set_expr(node: ast.AST, env: Set[str]) -> bool:
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) and \
            node.func.id in ("set", "frozenset"):
        return True
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
        if node.func.attr in ("union", "intersection", "difference",
                              "symmetric_difference") and \
                _is_set_expr(node.func.value, env):
            return True
    if isinstance(node, ast.BinOp) and isinstance(
            node.op, (ast.BitOr, ast.BitAnd, ast.Sub, ast.BitXor)):
        return _is_set_expr(node.left, env) or _is_set_expr(node.right, env)
    if isinstance(node, ast.Name):
        return node.id in env
    return False


def _is_keys_call(node: ast.AST) -> bool:
    return (isinstance(node, ast.Call) and not node.args and
            isinstance(node.func, ast.Attribute) and
            node.func.attr == "keys")


class UnorderedIterRule(Rule):
    """Set iteration order depends on PYTHONHASHSEED (fresh per process):
    anything it feeds — digests, event scheduling, shard/host assignment,
    user-visible reports — differs run to run.  Wrap in ``sorted(...)``
    or keep an insertion-ordered dict (``dict.fromkeys`` dedupes
    deterministically).  ``.keys()`` loops are flagged in the same
    contexts: iterate the dict itself (insertion-ordered) or sort."""

    id = "SIM003"
    severity = "warning"
    short = ("iteration over an unordered set / dict.keys() — wrap in "
             "sorted(...) where order matters")

    ORDER_SENSITIVE_CALLS = {"list", "tuple", "iter", "enumerate"}

    def run(self, ctx: ModuleContext) -> List[Finding]:
        out: List[Finding] = []
        # functions first (precise local env), the module tree last for
        # module-level code; `seen` dedupes the overlap
        scopes = [n for n in ctx.walk(ast.FunctionDef,
                                      ast.AsyncFunctionDef)] + [ctx.tree]
        seen: Set[Tuple[int, int]] = set()
        for scope in scopes:
            env = _set_env_for_scope(scope)
            for node in ast.walk(scope):
                iters: List[ast.AST] = []
                if isinstance(node, ast.For):
                    iters.append(node.iter)
                elif isinstance(node, (ast.ListComp, ast.SetComp,
                                       ast.DictComp, ast.GeneratorExp)):
                    iters.extend(g.iter for g in node.generators)
                elif isinstance(node, ast.Call):
                    fn = node.func
                    if isinstance(fn, ast.Name) and \
                            fn.id in self.ORDER_SENSITIVE_CALLS:
                        iters.extend(node.args)
                    elif isinstance(fn, ast.Attribute) and fn.attr == "join":
                        iters.extend(node.args)
                for it in iters:
                    key = (getattr(it, "lineno", 0),
                           getattr(it, "col_offset", 0))
                    if key in seen:
                        continue
                    if _is_set_expr(it, env):
                        seen.add(key)
                        out.append(self.finding(
                            ctx, it,
                            "iteration over an unordered set — order "
                            "varies with PYTHONHASHSEED; wrap in "
                            "sorted(...) (or dedupe with dict.fromkeys "
                            "to keep insertion order)"))
                    elif _is_keys_call(it):
                        seen.add(key)
                        out.append(self.finding(
                            ctx, it,
                            "iteration over .keys() — iterate the dict "
                            "itself (insertion-ordered) or sorted(...) "
                            "when the order feeds output or digests"))
        return out


# ---------------------------------------------------------------------------
# SIM004 — donated-buffer reuse


def _donate_positions(call: ast.Call) -> Optional[Set[int]]:
    """The donate_argnums literal from a jax.jit(...) call node, if any."""
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            v = kw.value
            if isinstance(v, ast.Tuple):
                pos = set()
                for elt in v.elts:
                    if isinstance(elt, ast.Constant) and \
                            isinstance(elt.value, int):
                        pos.add(elt.value)
                return pos
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                return {v.value}
            return set()        # dynamic expression: unknown positions
    return None


def _jit_call_info(node: ast.AST, ctx: ModuleContext
                   ) -> Optional[Set[int]]:
    """If ``node`` is a jax.jit(...)-or-partial(jax.jit, ...) expression,
    return its donated positions (empty set when donate_argnums absent)."""
    if not isinstance(node, ast.Call):
        return None
    r = ctx.resolve(node.func)
    if r is not None and r[0] in ("jax.jit", "jax.api.jit"):
        return _donate_positions(node) or set()
    if r is not None and r[0] in ("functools.partial", "partial") or (
            isinstance(node.func, ast.Name) and node.func.id == "partial"):
        if node.args:
            inner = ctx.resolve(node.args[0])
            if inner is not None and inner[0] in ("jax.jit", "jax.api.jit"):
                return _donate_positions(node) or set()
    return None


class DonatedReuseRule(Rule):
    """``donate_argnums`` hands the argument's device buffer to XLA: after
    the call the buffer may alias the OUTPUT (the device plane's dispatch
    path donates all 8 state tensors — ops/torcells_device.py).  Reading
    the Python variable afterwards observes undefined device memory on
    accelerators; jax only warns on some backends.  The variable must be
    rebound before any later read."""

    id = "SIM004"
    severity = "error"
    short = ("variable read after being donated to a jitted call "
             "(donate_argnums)")

    def _donated_names(self, ctx: ModuleContext) -> Dict[str, Set[int]]:
        donated: Dict[str, Set[int]] = {}
        for node in ctx.walk(ast.FunctionDef, ast.AsyncFunctionDef):
            for dec in node.decorator_list:
                pos = _jit_call_info(dec, ctx)
                if pos:
                    donated[node.name] = pos
        for node in ctx.walk(ast.Assign):
            if len(node.targets) == 1 and isinstance(node.targets[0],
                                                     ast.Name):
                val = node.value
                pos = _jit_call_info(val, ctx)
                # name = partial(jax.jit, donate_argnums=...)(fn) form
                if pos is None and isinstance(val, ast.Call):
                    pos = _jit_call_info(val.func, ctx)
                if pos:
                    donated[node.targets[0].id] = pos
        return donated

    @staticmethod
    def _call_donated_vars(call: ast.Call, pos: Set[int]) -> List[str]:
        names = []
        for i, arg in enumerate(call.args):
            if isinstance(arg, ast.Starred):
                # *state at or before a donated position: the unpacked
                # tuple covers donated slots — the tuple variable itself
                # must not be read afterwards
                if isinstance(arg.value, ast.Name) and any(p >= i
                                                           for p in pos):
                    names.append(arg.value.id)
            elif i in pos and isinstance(arg, ast.Name):
                names.append(arg.id)
        return names

    def run(self, ctx: ModuleContext) -> List[Finding]:
        donated = self._donated_names(ctx)
        if not donated:
            return []
        out: List[Finding] = []
        # module-level code (driver scripts) AND every function body, each
        # as its own scope — _check_body never descends into nested defs,
        # so names are tracked per scope and nothing is visited twice
        out.extend(self._check_body(ctx, ctx.tree.body, donated))
        for fn in ctx.walk(ast.FunctionDef, ast.AsyncFunctionDef):
            out.extend(self._check_body(ctx, fn.body, donated))
        return out

    @staticmethod
    def _walk_scope(node: ast.AST):
        """ast.walk that does not descend into nested function/class
        bodies — those are separate scopes checked on their own, and a
        donation of an inner `s` must not kill the outer `s`."""
        stack = [node]
        while stack:
            cur = stack.pop()
            yield cur
            for child in ast.iter_child_nodes(cur):
                if not isinstance(child, (ast.FunctionDef,
                                          ast.AsyncFunctionDef,
                                          ast.ClassDef, ast.Lambda)):
                    stack.append(child)

    def _check_body(self, ctx, body: List[ast.stmt],
                    donated: Dict[str, Set[int]], loop: bool = False
                    ) -> List[Finding]:
        out: List[Finding] = []
        for idx, stmt in enumerate(body):
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue                  # separate scope, checked by run()
            for call in self._walk_scope(stmt):
                if not (isinstance(call, ast.Call) and
                        isinstance(call.func, ast.Name) and
                        call.func.id in donated):
                    continue
                victims = self._call_donated_vars(call,
                                                  donated[call.func.id])
                if not victims:
                    continue
                dead = set(victims)
                # a same-statement rebind (out = f(state) with state in
                # targets) revives the name immediately — find the call's
                # NEAREST enclosing statement (the call may sit inside a
                # loop/if nested under `stmt`), not `stmt` itself
                near = ctx.parent(call)
                while near is not None and not isinstance(near, ast.stmt):
                    near = ctx.parent(near)
                if isinstance(near, ast.Assign):
                    for t in near.targets:
                        for n in ast.walk(t):
                            if isinstance(n, ast.Name):
                                dead.discard(n.id)
                self._scan_reads(ctx, body[idx + 1:], dead, call, out)
                if loop and dead:
                    # loop back edge: the next iteration re-executes the
                    # body from the top, so `for _ in r: out = step(s)`
                    # re-reads donated `s` — scan up to and INCLUDING the
                    # call statement (its value re-reads the donated arg;
                    # `s = step(s)` is safe because iteration N's targets
                    # already revived `s` above)
                    self._scan_reads(ctx, body[:idx + 1], dead, call, out)
            # recurse into nested suites so a donation inside an if-branch
            # is tracked within that branch; For/While bodies re-execute,
            # so their scans wrap around the back edge
            stmt_loops = isinstance(stmt, (ast.For, ast.AsyncFor,
                                           ast.While))
            for sub in (getattr(stmt, "body", None),
                        getattr(stmt, "orelse", None),
                        getattr(stmt, "finalbody", None)):
                if sub:
                    out.extend(self._check_body(
                        ctx, sub, donated,
                        loop=loop or (stmt_loops and sub is stmt.body)))
        return out

    def _scan_reads(self, ctx, stmts: List[ast.stmt], dead: Set[str],
                    call: ast.Call, out: List[Finding]) -> None:
        """Flag Loads of donated names over ``stmts`` in execution order,
        reviving a name at its first rebind (Store)."""
        for later in stmts:
            if not dead:
                return
            if isinstance(later, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                continue
            # an Assign evaluates its value BEFORE binding targets: walk
            # in that order so `state = g(state)` flags the read, not the
            # rebind
            if isinstance(later, ast.Assign):
                nodes = list(self._walk_scope(later.value)) + \
                    [n for t in later.targets
                     for n in self._walk_scope(t)]
            else:
                nodes = list(self._walk_scope(later))
            for n in nodes:
                if not isinstance(n, ast.Name) or n.id not in dead:
                    continue
                if isinstance(n.ctx, ast.Load):
                    out.append(self.finding(
                        ctx, n,
                        f"`{n.id}` was donated to jitted call "
                        f"`{call.func.id}` (donate_argnums) on "
                        f"line {call.lineno} and is read here — "
                        "the device buffer may be invalidated; "
                        "rebind it from the call's output or "
                        "copy before donating"))
                    dead.discard(n.id)
                else:
                    dead.discard(n.id)      # rebound: safe again


# ---------------------------------------------------------------------------
# SIM005 — blocking wall-time operations


class BlockingOpRule(Rule):
    """The engine's round loop, green threads (process/process.py) and
    plugin RPC serve loops are cooperative: one real ``time.sleep`` or an
    unbounded subprocess wait stalls EVERY simulated host, and the
    supervision watchdogs (ISSUE 2) exist precisely because such stalls
    froze runs.  Blocking calls must be bounded (timeout=) or live in
    allowlisted/justified telemetry code."""

    id = "SIM005"
    severity = "warning"
    short = ("blocking wall-time operation on a sim path (sleep / "
             "subprocess without timeout)")

    SUBPROCESS_FNS = {"subprocess.run", "subprocess.call",
                      "subprocess.check_call", "subprocess.check_output"}

    def run(self, ctx: ModuleContext) -> List[Finding]:
        out: List[Finding] = []
        for node in ctx.walk(ast.Call):
            r = ctx.resolve(node.func)
            if r is None:
                continue
            canon, _root = r
            if canon == "time.sleep":
                out.append(self.finding(
                    ctx, node,
                    "`time.sleep` blocks the whole sim process — "
                    "schedule a sim-time event (api.sleep / Task) "
                    "instead, or justify with a pragma"))
            elif canon in self.SUBPROCESS_FNS:
                if not any(kw.arg == "timeout" for kw in node.keywords):
                    out.append(self.finding(
                        ctx, node,
                        f"`{canon}` without timeout= can block the run "
                        "forever — every external wait must be bounded "
                        "(the plugin/pool watchdogs depend on it)"))
            elif canon == "socket.create_connection":
                if not (len(node.args) >= 2 or
                        any(kw.arg == "timeout" for kw in node.keywords)):
                    out.append(self.finding(
                        ctx, node,
                        "`socket.create_connection` without a timeout "
                        "can block the run forever — pass timeout="))
        return out


# ---------------------------------------------------------------------------
# SIM006 — side effects inside jit-traced functions


class JitSideEffectRule(Rule):
    """A jit-traced function's Python body runs ONCE at trace time; a
    print/log fires once (or never, on cache hit) and a closure mutation
    bakes stale state into the compiled program — both classic silent
    divergences between the device kernels (ops/) and their numpy twins.
    Tracing-time effects belong outside the jitted function."""

    id = "SIM006"
    severity = "error"
    short = ("side effect (print/logging/closure mutation) inside a "
             "jit-traced function")

    MUTATORS = {"append", "extend", "insert", "remove", "clear", "add",
                "update", "setdefault", "pop", "popitem"}

    def _jit_functions(self, ctx: ModuleContext) -> List[ast.FunctionDef]:
        jitted: List[ast.FunctionDef] = []
        wrapped_names: Set[str] = set()
        for node in ctx.walk(ast.Assign, ast.Call):
            call = node.value if isinstance(node, ast.Assign) else node
            if not isinstance(call, ast.Call):
                continue
            if _jit_call_info(call, ctx) is not None:
                # jax.jit(fn, ...) / partial(jax.jit, ...) — positional
                # function args are traced
                for arg in call.args:
                    if isinstance(arg, ast.Name):
                        wrapped_names.add(arg.id)
            elif _jit_call_info(call.func, ctx) is not None:
                # partial(jax.jit, ...)(fn): the ops/ idiom — the OUTER
                # call's args are the traced functions
                for arg in call.args:
                    if isinstance(arg, ast.Name):
                        wrapped_names.add(arg.id)
        for fn in ctx.walk(ast.FunctionDef, ast.AsyncFunctionDef):
            if fn.name in wrapped_names or any(
                    _jit_call_info(d, ctx) is not None or
                    (ctx.resolve(d) or ("",))[0] in ("jax.jit",)
                    for d in fn.decorator_list):
                jitted.append(fn)
        return jitted

    @staticmethod
    def _local_names(fn: ast.FunctionDef) -> Set[str]:
        local = {a.arg for a in fn.args.args + fn.args.kwonlyargs +
                 fn.args.posonlyargs}
        if fn.args.vararg:
            local.add(fn.args.vararg.arg)
        if fn.args.kwarg:
            local.add(fn.args.kwarg.arg)
        for node in ast.walk(fn):
            if isinstance(node, ast.Name) and isinstance(node.ctx,
                                                         ast.Store):
                local.add(node.id)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node is not fn:
                local.add(node.name)
        return local

    def run(self, ctx: ModuleContext) -> List[Finding]:
        out: List[Finding] = []
        for fn in self._jit_functions(ctx):
            local = self._local_names(fn)
            for node in ast.walk(fn):
                if node is fn:
                    continue
                if isinstance(node, ast.Call):
                    f = node.func
                    if isinstance(f, ast.Name) and f.id == "print":
                        out.append(self.finding(
                            ctx, node,
                            f"print() inside jit-traced `{fn.name}` runs "
                            "at trace time only — use jax.debug.print or "
                            "move it outside"))
                        continue
                    r = ctx.resolve(f)
                    if r is not None and (
                            r[0].startswith("logging.") or
                            r[0].endswith("logger.get_logger")):
                        out.append(self.finding(
                            ctx, node,
                            f"logging inside jit-traced `{fn.name}` fires "
                            "at trace time only — log at the call site"))
                        continue
                    if isinstance(f, ast.Attribute) and \
                            f.attr in self.MUTATORS and \
                            isinstance(f.value, ast.Name) and \
                            f.value.id not in local and \
                            f.value.id not in ctx.aliases:
                        out.append(self.finding(
                            ctx, node,
                            f"mutation of closed-over `{f.value.id}` "
                            f"inside jit-traced `{fn.name}` bakes "
                            "trace-time state into the compiled program"))
                elif isinstance(node, (ast.Assign, ast.AugAssign)):
                    targets = node.targets if isinstance(
                        node, ast.Assign) else [node.target]
                    for t in targets:
                        if isinstance(t, ast.Subscript) and \
                                isinstance(t.value, ast.Name) and \
                                t.value.id not in local:
                            out.append(self.finding(
                                ctx, t,
                                f"subscript assignment to closed-over "
                                f"`{t.value.id}` inside jit-traced "
                                f"`{fn.name}` is a trace-time side "
                                "effect (use .at[...].set())"))
        return out


CATALOG: List[Rule] = [
    WallClockRule(),
    NondetRandomRule(),
    UnorderedIterRule(),
    DonatedReuseRule(),
    BlockingOpRule(),
    JitSideEffectRule(),
]
