"""simlint: the determinism & device-safety rule engine.

An AST-based linter with pluggable rules, a severity model, per-line
suppression pragmas that REQUIRE a reason, a per-rule path allowlist read
from ``pyproject.toml``, and machine-readable JSON output.

Usage::

    python -m shadow_tpu.analysis.simlint [paths...] [--json] [--list-rules]
                                          [--config pyproject.toml]

Exit status: 0 = no unsuppressed findings, 1 = findings, 2 = usage error.

Rules (see rules.py for the catalog):

=======  ========  ====================================================
rule     severity  invariant guarded
=======  ========  ====================================================
SIM001   error     no wall-clock reads in sim code (core/stime.py owns
                   the clock); ``import time as _walltime`` / ``_wt``
                   declares deliberate wall-time (perf telemetry)
SIM002   error     no nondeterministic randomness — derive from
                   ``host.random`` streams or np.random.default_rng(seed)
SIM003   warning   no iteration over unordered sets / dict.keys() where
                   order can reach digests, events, or output
SIM004   error     a buffer donated to a jitted call (donate_argnums)
                   must not be read after the call site
SIM005   warning   no unbounded blocking (sleep, subprocess without
                   timeout) on sim execution paths
SIM006   error     no side effects (print/logging/closure mutation)
                   inside jit-traced functions
SIM000   error     simlint's own hygiene: unparsable/unreadable file,
                   malformed, reasonless, or stale (matched-no-finding)
                   suppression pragma
=======  ========  ====================================================

Suppression: a finding is justified IN the code, never silently::

    t.sleep(30.0)  # simlint: disable=SIM005 -- fault harness: bounded stall

The ``-- <why>`` reason is mandatory; a pragma without one is itself a
finding (SIM000), as is a stale pragma that no longer matches anything.
A pragma on any physical line of a multi-line statement covers the whole
statement; a standalone pragma comment line covers the line below it.
Pragma syntax quoted inside strings/docstrings is inert (comments are
found by tokenizing, not line-scanning).  Allowlisting whole modules
(wall-time-legitimate code like obs/) lives in ``[tool.simlint.allow]``
in pyproject.toml, keyed by rule id with fnmatch path patterns.

Adding a rule: subclass :class:`Rule` in rules.py, set ``id`` /
``severity`` / ``short``, implement ``run(ctx)`` returning findings, and
append it to ``rules.CATALOG``.  ``ctx`` (:class:`ModuleContext`) gives
every rule the shared scope/alias tracker — ``ctx.resolve(node)`` sees
through ``import time as _t`` renames — plus parent links and per-function
symbol tables, so rules stay small.
"""

from __future__ import annotations

import argparse
import ast
import fnmatch
import io
import json
import os
import re
import subprocess
import sys
import tokenize
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Set, Tuple

SCHEMA_VERSION = 1

# ---------------------------------------------------------------------------
# findings


@dataclass
class Finding:
    rule: str
    severity: str            # "error" | "warning"
    path: str                # posix relpath from the lint root
    line: int
    col: int
    message: str
    suppressed: bool = False
    reason: Optional[str] = None   # the pragma's justification, when suppressed

    def sort_key(self) -> Tuple:
        return (self.path, self.line, self.col, self.rule)

    def to_json(self) -> Dict:
        out = {"rule": self.rule, "severity": self.severity,
               "path": self.path, "line": self.line, "col": self.col,
               "message": self.message}
        if self.suppressed:
            out["suppressed"] = True
            out["reason"] = self.reason
        return out

    def render(self) -> str:
        tag = " (suppressed)" if self.suppressed else ""
        return (f"{self.path}:{self.line}:{self.col}: {self.rule} "
                f"{self.severity}: {self.message}{tag}")


class Rule:
    """Base class: one invariant, one ``run`` over a module context."""

    id: str = "SIM000"
    severity: str = "error"
    short: str = ""

    def run(self, ctx: "ModuleContext") -> List[Finding]:  # pragma: no cover
        raise NotImplementedError

    def finding(self, ctx: "ModuleContext", node: ast.AST,
                message: str) -> Finding:
        return Finding(self.id, self.severity, ctx.relpath,
                       getattr(node, "lineno", 1),
                       getattr(node, "col_offset", 0), message)


# ---------------------------------------------------------------------------
# module context: the shared scope/alias tracker every rule sees through


class ModuleContext:
    """One parsed module + the symbol information rules share.

    ``aliases`` maps every locally-bound import name to its canonical
    dotted module path — ``import time as _t`` yields ``{"_t": "time"}``,
    ``from numpy import random as npr`` yields ``{"npr": "numpy.random"}``
    — so a rule matching ``time.monotonic`` fires on ``_t.monotonic()``
    too.  ``resolve`` turns an Attribute/Name chain into
    ``(canonical_dotted_path, surface_root_name)`` or None when the chain
    does not start at an imported module."""

    def __init__(self, relpath: str, source: str):
        self.relpath = relpath
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=relpath)
        self.aliases = self._collect_aliases(self.tree)
        self._parents: Dict[ast.AST, ast.AST] = {}
        self._anchor_map: Optional[Dict[int, int]] = None
        for parent in ast.walk(self.tree):
            for child in ast.iter_child_nodes(parent):
                self._parents[child] = parent

    def stmt_anchor(self, line: int) -> int:
        """First line of the innermost statement covering ``line`` — a
        pragma anywhere on a multi-line statement covers the whole
        statement, wherever a rule anchored its finding."""
        if self._anchor_map is None:
            m: Dict[int, int] = {}
            for node in ast.walk(self.tree):
                end = getattr(node, "end_lineno", None)
                if isinstance(node, ast.stmt) and end:
                    for ln in range(node.lineno, end + 1):
                        # innermost statement = the latest-starting one
                        if node.lineno > m.get(ln, 0):
                            m[ln] = node.lineno
            self._anchor_map = m
        return self._anchor_map.get(line, line)

    @staticmethod
    def _collect_aliases(tree: ast.AST) -> Dict[str, str]:
        aliases: Dict[str, str] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.asname:
                        aliases[a.asname] = a.name
                    else:
                        top = a.name.split(".")[0]
                        aliases[top] = top
            elif isinstance(node, ast.ImportFrom):
                mod = ("." * node.level) + (node.module or "")
                for a in node.names:
                    if a.name == "*":
                        continue
                    aliases[a.asname or a.name] = f"{mod}.{a.name}"
        return aliases

    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self._parents.get(node)

    def enclosing_function(self, node: ast.AST) -> Optional[ast.AST]:
        cur = self._parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return cur
            cur = self._parents.get(cur)
        return None

    def resolve(self, node: ast.AST) -> Optional[Tuple[str, str]]:
        """(canonical dotted path, surface root name) for a Name/Attribute
        chain rooted at an imported module binding, else None."""
        parts: List[str] = []
        cur = node
        while isinstance(cur, ast.Attribute):
            parts.append(cur.attr)
            cur = cur.value
        if not isinstance(cur, ast.Name):
            return None
        root = cur.id
        base = self.aliases.get(root)
        if base is None:
            return None
        parts.append(base)
        return ".".join(reversed(parts)), root

    def walk(self, *types) -> Iterable[ast.AST]:
        for node in ast.walk(self.tree):
            if not types or isinstance(node, types):
                yield node


# ---------------------------------------------------------------------------
# suppression pragmas

# one pragma vocabulary for the whole analysis family: the introducer may
# be spelled simlint:/simrace:/simtwin:/simjit: (all equivalent), and the
# rule ids scope ownership — each tool judges staleness only for rules it
# runs
PRAGMA_RE = re.compile(
    r"#\s*sim(?:lint|race|twin|jit):\s*disable=([A-Za-z0-9_,\s]*?)"
    r"\s*(?:--\s*(.*))?$")
_KNOWN_RULES_CACHE: Optional[set] = None


def known_rule_ids() -> set:
    """Every rule id any tool in this package owns: simlint's SIM00x
    catalog, simrace's SIM1xx concurrency catalog, simtwin's SIM2xx
    cross-plane catalog, and simjit's SIM3xx compile-surface catalog.
    Pragmas may name any of them; each TOOL only judges staleness for
    the rules it RUNS (a ``disable=SIM103`` pragma is invisible to
    simlint, not stale)."""
    global _KNOWN_RULES_CACHE
    if _KNOWN_RULES_CACHE is None:
        ids = {r.id for r in default_rules()} | {"SIM000"}
        from . import jit_rules, race_rules, twin_rules
        ids |= {r.id for r in race_rules.CATALOG}
        ids |= {r.id for r in twin_rules.CATALOG}
        ids |= {r.id for r in jit_rules.CATALOG}
        _KNOWN_RULES_CACHE = ids
    return _KNOWN_RULES_CACHE


def _comment_tokens(source: str) -> List[Tuple[int, int, str]]:
    """(line, col, text) for every real COMMENT token — tokenizing rather
    than scanning lines so pragma syntax quoted inside a string literal or
    docstring (this module's own docs, rule messages) is never mistaken
    for a live pragma."""
    out = []
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                out.append((tok.start[0], tok.start[1], tok.string))
    except (tokenize.TokenError, IndentationError):
        pass                   # unparsable files already yield SIM000
    return out


@dataclass
class Pragma:
    """One (rule, reason) pair from a suppression comment."""
    rule: str
    reason: str
    target: int              # the line the pragma covers
    line: int                # the pragma comment's own position
    col: int
    used: bool = False


def collect_pragmas(relpath: str, source: str, lines: List[str]
                    ) -> Tuple[List[Pragma], List[Finding]]:
    """Pragma entries + SIM000 findings for malformed ones.  A pragma
    covers its own line (lint_source widens that to the whole enclosing
    statement); a line that is ONLY a pragma comment covers the next line
    instead."""
    pragmas: List[Pragma] = []
    bad: List[Finding] = []
    for i, col0, text in _comment_tokens(source):
        m = PRAGMA_RE.search(text)
        if not m:
            continue
        ids = [s.strip().upper() for s in m.group(1).split(",") if s.strip()]
        reason = (m.group(2) or "").strip()
        col = col0 + m.start()
        if not ids:
            bad.append(Finding("SIM000", "error", relpath, i, col,
                               "suppression pragma names no rule ids "
                               "(expected disable=<RULE>[,<RULE>] "
                               "-- <why>)"))
            continue
        unknown = [r for r in ids if r not in known_rule_ids()]
        if unknown:
            bad.append(Finding("SIM000", "error", relpath, i, col,
                               f"suppression pragma names unknown rule(s) "
                               f"{', '.join(unknown)}"))
        if not reason:
            bad.append(Finding("SIM000", "error", relpath, i, col,
                               "suppression pragma is missing its reason — "
                               "justify it: # simlint: disable="
                               f"{','.join(ids)} -- <why>"))
            continue
        # a comment with no code before it on its line covers the NEXT line
        standalone = not lines[i - 1][:col0].strip() if i <= len(lines) \
            else True
        target = i + 1 if standalone else i
        for rid in ids:
            if rid in known_rule_ids():
                pragmas.append(Pragma(rid, reason, target, i, col))
    return pragmas, bad


# ---------------------------------------------------------------------------
# configuration ([tool.simlint] in pyproject.toml; python 3.10 has no
# tomllib, so a deliberately tiny parser covers the subset we emit)


@dataclass
class Config:
    root: str = "."                      # directory patterns are relative to
    allow: Dict[str, List[str]] = None   # rule id -> fnmatch path patterns
    exclude: List[str] = None            # path patterns skipped entirely

    def __post_init__(self):
        self.allow = self.allow or {}
        self.exclude = self.exclude or []

    def is_excluded(self, relpath: str) -> bool:
        return any(fnmatch.fnmatch(relpath, p) for p in self.exclude)

    def is_allowed(self, rule_id: str, relpath: str) -> bool:
        pats = self.allow.get(rule_id, ())
        return any(fnmatch.fnmatch(relpath, p) for p in pats)


_ARRAY_ITEM_RE = re.compile(r'"((?:[^"\\]|\\.)*)"')


def _toml_section(text: str, header: str) -> Dict[str, List[str]]:
    """Extract ``key = ["a", "b"]`` pairs from one [header] section of a
    TOML document (multiline arrays supported; just enough for simlint's
    own config — NOT a general TOML parser)."""
    out: Dict[str, List[str]] = {}
    lines = text.splitlines()
    in_section = False
    buf = ""
    key = None
    for raw in lines:
        line = raw.strip()
        if line.startswith("["):
            if key is not None:     # unterminated array at section end
                out[key] = _ARRAY_ITEM_RE.findall(buf)
                key = None
            in_section = line == f"[{header}]"
            continue
        if not in_section or not line or line.startswith("#"):
            continue
        if key is not None:
            buf += line
            if buf.count("[") <= buf.count("]"):
                out[key] = _ARRAY_ITEM_RE.findall(buf)
                key = None
            continue
        m = re.match(r"([A-Za-z0-9_-]+)\s*=\s*(.*)$", line)
        if not m:
            continue
        k, v = m.group(1), m.group(2)
        if v.count("[") > v.count("]"):
            key, buf = k, v
        else:
            out[k] = _ARRAY_ITEM_RE.findall(v)
    if key is not None:
        out[key] = _ARRAY_ITEM_RE.findall(buf)
    return out


def load_config(path: Optional[str], start: Optional[str] = None) -> Config:
    """Load [tool.simlint] from ``path``, or search pyproject.toml upward
    from ``start``.  Missing file/section yields the empty config."""
    if path is None:
        cur = os.path.abspath(start or ".")
        if os.path.isfile(cur):
            cur = os.path.dirname(cur)
        while True:
            cand = os.path.join(cur, "pyproject.toml")
            if os.path.isfile(cand):
                path = cand
                break
            nxt = os.path.dirname(cur)
            if nxt == cur:
                return Config()
            cur = nxt
    try:
        with open(path, encoding="utf-8") as f:
            text = f.read()
    except OSError:
        return Config()
    top = _toml_section(text, "tool.simlint")
    allow = _toml_section(text, "tool.simlint.allow")
    return Config(root=os.path.dirname(os.path.abspath(path)) or ".",
                  allow={k.upper(): v for k, v in allow.items()},
                  exclude=top.get("exclude", []))


# ---------------------------------------------------------------------------
# engine


def default_rules() -> List[Rule]:
    from . import rules
    return list(rules.CATALOG)


def apply_pragmas(ctx: ModuleContext, findings: List[Finding],
                  active_ids: Set[str]) -> List[Finding]:
    """Match suppression pragmas against ``findings`` and return the
    combined (suppressed + SIM000) list for one module.

    ``active_ids`` scopes ownership: only pragmas naming a rule in it can
    suppress here, and only THOSE pragmas can be stale — a pragma for a
    rule another tool runs (simrace's SIM1xx from simlint's point of view,
    and vice versa) is simply not this tool's business.  Malformed pragmas
    (reasonless, unknown rule id) are every tool's business."""
    pragmas, bad = collect_pragmas(ctx.relpath, ctx.source, ctx.lines)
    pragmas = [p for p in pragmas if p.rule in active_ids]
    # a pragma covers the whole statement its target line belongs to, so
    # wrapped calls can carry the pragma on any of their physical lines
    index: Dict[Tuple[int, str], Pragma] = {}
    for p in pragmas:
        index[(ctx.stmt_anchor(p.target), p.rule)] = p
        index[(p.target, p.rule)] = p
    for f in findings:
        p = index.get((f.line, f.rule)) or \
            index.get((ctx.stmt_anchor(f.line), f.rule))
        if p is not None:
            f.suppressed, f.reason = True, p.reason
            p.used = True
    # a pragma that suppressed nothing is stale (the code was fixed, or
    # the rule id is wrong for the finding on that line) — keeping it
    # would misdocument the code, so it is its own finding
    for p in pragmas:
        if not p.used:
            bad.append(Finding(
                "SIM000", "error", ctx.relpath, p.line, p.col,
                f"suppression pragma for {p.rule} matched no finding — "
                "remove the stale pragma (or fix its rule id)"))
    findings = findings + bad            # SIM000 is never suppressible
    return sorted(findings, key=Finding.sort_key)


def lint_source(source: str, relpath: str = "<snippet>",
                config: Optional[Config] = None,
                rules: Optional[List[Rule]] = None) -> List[Finding]:
    """Lint one module's source text (the test-fixture entry point)."""
    config = config or Config()
    rules = rules if rules is not None else default_rules()
    try:
        ctx = ModuleContext(relpath, source)
    except SyntaxError as e:
        return [Finding("SIM000", "error", relpath, e.lineno or 1,
                        (e.offset or 1) - 1,
                        f"file does not parse: {e.msg}")]
    findings: List[Finding] = []
    for rule in rules:
        if config.is_allowed(rule.id, relpath):
            continue
        findings.extend(rule.run(ctx))
    return apply_pragmas(ctx, findings, {r.id for r in rules} | {"SIM000"})


def iter_py_files(paths: List[str], config: Config) -> List[Tuple[str, str]]:
    """[(abspath, relpath-from-config-root)] for every .py under paths,
    sorted, exclusions applied."""
    out = []
    for p in paths:
        p = os.path.abspath(p)
        if os.path.isfile(p):
            files = [p]
        else:
            files = []
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames.sort()
                dirnames[:] = [d for d in dirnames if d != "__pycache__"]
                files.extend(os.path.join(dirpath, fn)
                             for fn in sorted(filenames)
                             if fn.endswith(".py"))
        for fp in files:
            rel = os.path.relpath(fp, config.root).replace(os.sep, "/")
            if not config.is_excluded(rel):
                out.append((fp, rel))
    return sorted(set(out))


def changed_py_files(base: str, root: str,
                     exts: Tuple[str, ...] = (".py",)) -> Set[str]:
    """Relpaths (from ``root``, posix) of files with an ``exts`` suffix
    changed since git ref ``base``, plus untracked ones — the ``--diff
    BASE`` incremental-lint set (simtwin passes C suffixes too).  Raises
    RuntimeError when git can't answer (bad ref, not a repo), so the CLI
    can exit 2 instead of silently linting nothing.

    Path bases differ between the two git commands: ``git diff
    --name-only`` prints toplevel-relative paths while ``git ls-files``
    (run with cwd=root) prints cwd-relative ones — so the diff output is
    re-based onto ``root`` via ``--show-prefix`` (when root is nested in
    the repo, a toplevel path outside root can never match the lint set
    and is dropped)."""

    def _git(args: List[str]) -> str:
        try:
            run = subprocess.run(["git"] + args, cwd=root,
                                 capture_output=True, text=True,
                                 timeout=60)
        except (OSError, subprocess.TimeoutExpired) as e:
            raise RuntimeError(f"git failed: {e!r}")
        if run.returncode != 0:
            raise RuntimeError(
                f"`git {' '.join(args)}` failed: {run.stderr.strip()}")
        return run.stdout

    prefix = _git(["rev-parse", "--show-prefix"]).strip()
    out: Set[str] = set()
    for p in _git(["diff", "--name-only", "-z", base, "--"]).split("\0"):
        if not p.endswith(exts):
            continue
        if prefix:
            if not p.startswith(prefix):
                continue             # changed outside the lint root
            p = p[len(prefix):]
        out.add(p)
    out.update(p for p in _git(["ls-files", "--others",
                                "--exclude-standard", "-z"]).split("\0")
               if p.endswith(exts))
    return out


@dataclass
class LintResult:
    findings: List[Finding]
    files: int
    tool: str = "simlint"

    @property
    def unsuppressed(self) -> List[Finding]:
        return [f for f in self.findings if not f.suppressed]

    @property
    def suppressed(self) -> List[Finding]:
        return [f for f in self.findings if f.suppressed]

    def to_json(self) -> Dict:
        by_rule: Dict[str, int] = {}
        for f in self.unsuppressed:
            by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
        return {
            "version": SCHEMA_VERSION,
            "tool": self.tool,
            "files": self.files,
            "findings": [f.to_json() for f in self.unsuppressed],
            "suppressed": [f.to_json() for f in self.suppressed],
            "summary": {
                "findings": len(self.unsuppressed),
                "suppressed": len(self.suppressed),
                "by_rule": dict(sorted(by_rule.items())),
            },
        }


def lint_paths(paths: List[str], config: Optional[Config] = None,
               rules: Optional[List[Rule]] = None,
               only: Optional[Set[str]] = None) -> LintResult:
    """``only`` (when not None) restricts linting to those relpaths — the
    ``--diff BASE`` incremental mode; an empty set lints nothing."""
    config = config or load_config(None, start=paths[0] if paths else ".")
    rules = rules if rules is not None else default_rules()
    findings: List[Finding] = []
    files = iter_py_files(paths, config)
    if only is not None:
        files = [(a, r) for a, r in files if r in only]
    for abspath, rel in files:
        try:
            with open(abspath, encoding="utf-8") as f:
                source = f.read()
        except (OSError, UnicodeDecodeError) as e:
            # one unreadable/non-UTF8 file must surface as a finding, not
            # crash the whole gate with a traceback
            findings.append(Finding("SIM000", "error", rel, 1, 0,
                                    f"file is unreadable: {e}"))
            continue
        findings.extend(lint_source(source, rel, config, rules))
    findings.sort(key=Finding.sort_key)
    return LintResult(findings, len(files))


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="simlint",
        description="determinism & device-safety static analysis "
                    "(shadow-tpu)")
    ap.add_argument("paths", nargs="*", default=None,
                    help="files or directories (default: shadow_tpu/)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable JSON on stdout")
    ap.add_argument("--config", default=None,
                    help="pyproject.toml carrying [tool.simlint] "
                         "(default: nearest to the first path)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    ap.add_argument("--diff", metavar="BASE", default=None,
                    help="lint only .py files changed since git ref BASE "
                         "(plus untracked files)")
    args = ap.parse_args(argv)
    rules = default_rules()
    if args.list_rules:
        for r in rules:
            print(f"{r.id}  {r.severity:<7}  {r.short}")
        return 0
    paths = args.paths or ["shadow_tpu"]
    missing = [p for p in paths if not os.path.exists(p)]
    if missing:
        print(f"simlint: no such path: {', '.join(missing)}",
              file=sys.stderr)
        return 2
    config = load_config(args.config, start=paths[0])
    only = None
    if args.diff is not None:
        try:
            only = changed_py_files(args.diff, config.root)
        except RuntimeError as e:
            print(f"simlint: --diff {args.diff}: {e}", file=sys.stderr)
            return 2
    result = lint_paths(paths, config, rules, only=only)
    if args.json:
        json.dump(result.to_json(), sys.stdout, indent=2, sort_keys=True)
        print()
    else:
        for f in result.unsuppressed:
            print(f.render())
        print(f"simlint: {len(result.unsuppressed)} finding(s), "
              f"{len(result.suppressed)} suppressed, "
              f"{result.files} file(s)")
    return 1 if result.unsuppressed else 0


if __name__ == "__main__":
    sys.exit(main())
