"""The protocol-logic expression IR (ISSUE 19): one typed arithmetic AST
in ``spec/protocol_spec.json``, three generated materializations.

PR 11 (simgen) made protocol *constants and tables* spec-authoritative;
this module does the same for the protocol *update expressions* (SRTT/
RTTVAR gains, RTO clamp/backoff, ssthresh, recovery inflation, and the
spec-defined ``bbrx`` congestion family).  The IR is deliberately tiny —
int64 arithmetic over named arguments and spec-constant references —
because every node must emit AND parse back on all three planes:

- Python plane  — ``def _g_<name>(args): return <expr>``
- C plane       — ``static inline int64_t gen_<name>(...) { return <expr>; }``
- kernel plane  — ``def <name>_np(args): return <expr>`` (numpy ops)

Node grammar (JSON lists, so the spec stays byte-stable under
``sort_keys``)::

    <expr> ::= <int>                      integer literal
             | "<arg>"                    argument reference
             | ["ref", "NAME"]            spec-constant reference
             | ["ref", "NAME", <idx>]     element of a pair constant
             | ["add"|"sub"|"mul"|"floordiv"|"mod"|"min"|"max"
                |"shl"|"shr", <expr>, <expr>]
             | ["select", <cond>, <expr>, <expr>]
    <cond> ::= ["eq"|"ne"|"lt"|"le"|"gt"|"ge", <expr>, <expr>]

Arithmetic contract (what makes cross-plane digest parity possible):
every operand is a non-negative int64 and every intermediate stays below
2**63, so Python's arbitrary-precision ``//``/``%``, C's truncating
``/``/``%`` and numpy's int64 ops agree exactly.  The spec's job is to
respect that envelope (the bbrx expressions clamp before multiplying).

Emission resolves ``ref`` nodes to literals — the generated expression
carries the VALUE, the spec carries the meaning — and read-back compares
the parsed (literal) tree against the spec tree resolved the same way,
so a drifted coefficient on any one plane is a structural mismatch, not
a regex miss.  ``simtwin``'s SIM206 rule and ``simgen``'s readback diff
both go through :func:`structural_diff`.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Tuple, Union

IR = Union[int, str, list]

_BINOPS = ("add", "sub", "mul", "floordiv", "mod", "min", "max",
           "shl", "shr")
_CMPS = ("eq", "ne", "lt", "le", "gt", "ge")

# one naming convention, owned here, consumed by simgen (emit) and
# twin_rules/cspec (read-back)
PY_PREFIX = "_g_"
C_PREFIX = "gen_"
NP_SUFFIX = "_np"

PLANES = ("py", "c", "kernel")


def plane_symbol(name: str, plane: str) -> str:
    """The emitted function name for logic function ``name`` on a plane."""
    if plane == "py":
        return PY_PREFIX + name
    if plane == "c":
        return C_PREFIX + name
    if plane == "kernel":
        return name + NP_SUFFIX
    raise ValueError(f"unknown plane {plane!r}")


# ---------------------------------------------------------------------------
# validation / resolution

class IRError(ValueError):
    pass


def _const_value(spec_constants: Dict, name: str,
                 elem: Optional[int]) -> int:
    if name not in spec_constants:
        raise IRError(f"logic IR references unknown constant {name!r}")
    v = spec_constants[name]
    if elem is not None:
        if not isinstance(v, (list, tuple)) or elem >= len(v):
            raise IRError(f"constant {name!r} has no element [{elem}]")
        v = v[elem]
    if not isinstance(v, int) or isinstance(v, bool):
        raise IRError(f"logic IR constant {name!r} must be an int, "
                      f"got {v!r}")
    return v


def validate(ir: IR, args: Sequence[str], spec_constants: Dict,
             _cond_ok: bool = False) -> None:
    """Raise :class:`IRError` on any malformed node."""
    if isinstance(ir, bool):
        raise IRError(f"boolean literal {ir!r} is not an IR node")
    if isinstance(ir, int):
        return
    if isinstance(ir, str):
        if ir not in args:
            raise IRError(f"unknown argument reference {ir!r} "
                          f"(args: {list(args)})")
        return
    if not isinstance(ir, list) or not ir:
        raise IRError(f"malformed IR node {ir!r}")
    op = ir[0]
    if op == "ref":
        if len(ir) == 2:
            _const_value(spec_constants, ir[1], None)
        elif len(ir) == 3:
            _const_value(spec_constants, ir[1], ir[2])
        else:
            raise IRError(f"malformed ref node {ir!r}")
        return
    if op in _BINOPS:
        if len(ir) != 3:
            raise IRError(f"{op} node wants 2 operands: {ir!r}")
        validate(ir[1], args, spec_constants)
        validate(ir[2], args, spec_constants)
        return
    if op == "select":
        if len(ir) != 4:
            raise IRError(f"select node wants (cond, t, f): {ir!r}")
        cond = ir[1]
        if (not isinstance(cond, list) or len(cond) != 3
                or cond[0] not in _CMPS):
            raise IRError(f"select condition must be a comparison: {cond!r}")
        validate(cond[1], args, spec_constants)
        validate(cond[2], args, spec_constants)
        validate(ir[2], args, spec_constants)
        validate(ir[3], args, spec_constants)
        return
    raise IRError(f"unknown IR op {op!r}")


def resolve(ir: IR, spec_constants: Dict) -> IR:
    """Replace every ``ref`` node with its spec value (the canonical
    compare form — read-back trees are literal by construction)."""
    if isinstance(ir, (int, str)):
        return ir
    if ir[0] == "ref":
        return _const_value(spec_constants, ir[1],
                            ir[2] if len(ir) == 3 else None)
    return [ir[0]] + [resolve(x, spec_constants) for x in ir[1:]]


def referenced_constants(ir: IR) -> List[str]:
    if isinstance(ir, (int, str)):
        return []
    if ir[0] == "ref":
        return [ir[1]]
    out: List[str] = []
    for x in ir[1:]:
        out.extend(referenced_constants(x))
    return out


def structural_diff(want: IR, got: IR, path: str = "") -> Optional[str]:
    """First structural difference between two RESOLVED trees, or None.
    The message names the diverging path so a SIM206 finding reads like
    a diff, not a shrug."""
    at = path or "<root>"
    if isinstance(want, (int, str)) or isinstance(got, (int, str)):
        if want != got:
            return f"at {at}: spec has {want!r}, plane has {got!r}"
        return None
    if want[0] != got[0]:
        return f"at {at}: spec op {want[0]!r}, plane op {got[0]!r}"
    if len(want) != len(got):
        return (f"at {at}: {want[0]} arity {len(want) - 1} != "
                f"{len(got) - 1}")
    for i, (w, g) in enumerate(zip(want[1:], got[1:])):
        d = structural_diff(w, g, f"{path}/{want[0]}[{i}]")
        if d:
            return d
    return None


def evaluate(ir: IR, env: Dict[str, int]) -> int:
    """Reference interpreter (tests pin the emitted planes against it)."""
    if isinstance(ir, int):
        return ir
    if isinstance(ir, str):
        return env[ir]
    op = ir[0]
    if op == "ref":
        raise IRError("evaluate() wants a resolved tree")
    if op in _CMPS:
        a, b = evaluate(ir[1], env), evaluate(ir[2], env)
        return {"eq": a == b, "ne": a != b, "lt": a < b, "le": a <= b,
                "gt": a > b, "ge": a >= b}[op]
    if op == "select":
        return (evaluate(ir[2], env) if evaluate(ir[1], env)
                else evaluate(ir[3], env))
    a, b = evaluate(ir[1], env), evaluate(ir[2], env)
    if op == "add":
        return a + b
    if op == "sub":
        return a - b
    if op == "mul":
        return a * b
    if op == "floordiv":
        return a // b
    if op == "mod":
        return a % b
    if op == "min":
        return min(a, b)
    if op == "max":
        return max(a, b)
    if op == "shl":
        return a << b
    if op == "shr":
        return a >> b
    raise IRError(f"unknown op {op!r}")


# ---------------------------------------------------------------------------
# emitters (always over a RESOLVED tree)

_PY_BINOP = {"add": "+", "sub": "-", "mul": "*", "floordiv": "//",
             "mod": "%", "shl": "<<", "shr": ">>"}
_C_BINOP = {"add": "+", "sub": "-", "mul": "*", "floordiv": "/",
            "mod": "%", "shl": "<<", "shr": ">>"}
_CMP_TOK = {"eq": "==", "ne": "!=", "lt": "<", "le": "<=",
            "gt": ">", "ge": ">="}


def emit_py(ir: IR) -> str:
    if isinstance(ir, int):
        return str(ir)
    if isinstance(ir, str):
        return ir
    op = ir[0]
    if op in _PY_BINOP:
        return f"({emit_py(ir[1])} {_PY_BINOP[op]} {emit_py(ir[2])})"
    if op in ("min", "max"):
        return f"{op}({emit_py(ir[1])}, {emit_py(ir[2])})"
    if op in _CMPS:
        return f"({emit_py(ir[1])} {_CMP_TOK[op]} {emit_py(ir[2])})"
    if op == "select":
        return (f"({emit_py(ir[2])} if {emit_py(ir[1])} "
                f"else {emit_py(ir[3])})")
    raise IRError(f"emit_py: unknown op {op!r}")


def emit_c(ir: IR) -> str:
    if isinstance(ir, int):
        # int64 literals: suffix anything outside the int32 envelope
        return f"{ir}LL" if ir > 2147483647 else str(ir)
    if isinstance(ir, str):
        return ir
    op = ir[0]
    if op in _C_BINOP:
        return f"({emit_c(ir[1])} {_C_BINOP[op]} {emit_c(ir[2])})"
    if op in ("min", "max"):
        return f"gen_i64_{op}({emit_c(ir[1])}, {emit_c(ir[2])})"
    if op in _CMPS:
        return f"({emit_c(ir[1])} {_CMP_TOK[op]} {emit_c(ir[2])})"
    if op == "select":
        return (f"({emit_c(ir[1])} ? {emit_c(ir[2])} "
                f": {emit_c(ir[3])})")
    raise IRError(f"emit_c: unknown op {op!r}")


def emit_np(ir: IR) -> str:
    if isinstance(ir, int):
        return str(ir)
    if isinstance(ir, str):
        return ir
    op = ir[0]
    if op in _PY_BINOP:
        return f"({emit_np(ir[1])} {_PY_BINOP[op]} {emit_np(ir[2])})"
    if op == "min":
        return f"np.minimum({emit_np(ir[1])}, {emit_np(ir[2])})"
    if op == "max":
        return f"np.maximum({emit_np(ir[1])}, {emit_np(ir[2])})"
    if op in _CMPS:
        return f"({emit_np(ir[1])} {_CMP_TOK[op]} {emit_np(ir[2])})"
    if op == "select":
        return (f"np.where({emit_np(ir[1])}, {emit_np(ir[2])}, "
                f"{emit_np(ir[3])})")
    raise IRError(f"emit_np: unknown op {op!r}")


# ---------------------------------------------------------------------------
# Python / numpy read-back (ast -> IR); the C side lives in cspec.py

_AST_BINOP = {ast.Add: "add", ast.Sub: "sub", ast.Mult: "mul",
              ast.FloorDiv: "floordiv", ast.Mod: "mod",
              ast.LShift: "shl", ast.RShift: "shr"}
_AST_CMP = {ast.Eq: "eq", ast.NotEq: "ne", ast.Lt: "lt", ast.LtE: "le",
            ast.Gt: "gt", ast.GtE: "ge"}
# numpy spellings of the portable ops
_NP_CALLS = {"minimum": "min", "maximum": "max"}


class ParseError(ValueError):
    pass


def _from_pyast(node: ast.AST) -> IR:
    if isinstance(node, ast.Constant):
        if isinstance(node.value, bool) or not isinstance(node.value, int):
            raise ParseError(f"non-integer literal {node.value!r}")
        return node.value
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.BinOp):
        op = _AST_BINOP.get(type(node.op))
        if op is None:
            raise ParseError(f"unsupported operator {node.op!r}")
        return [op, _from_pyast(node.left), _from_pyast(node.right)]
    if isinstance(node, ast.Compare):
        if len(node.ops) != 1:
            raise ParseError("chained comparison")
        op = _AST_CMP.get(type(node.ops[0]))
        if op is None:
            raise ParseError(f"unsupported comparison {node.ops[0]!r}")
        return [op, _from_pyast(node.left), _from_pyast(node.comparators[0])]
    if isinstance(node, ast.IfExp):
        cond = _from_pyast(node.test)
        if not (isinstance(cond, list) and cond[0] in _CMPS):
            raise ParseError("select condition must be a comparison")
        return ["select", cond, _from_pyast(node.body),
                _from_pyast(node.orelse)]
    if isinstance(node, ast.Call):
        fn = node.func
        if isinstance(fn, ast.Name) and fn.id in ("min", "max"):
            name = fn.id
        elif (isinstance(fn, ast.Attribute)
              and isinstance(fn.value, ast.Name) and fn.value.id == "np"):
            if fn.attr == "where":
                if len(node.args) != 3:
                    raise ParseError("np.where wants 3 args")
                cond = _from_pyast(node.args[0])
                if not (isinstance(cond, list) and cond[0] in _CMPS):
                    raise ParseError("np.where condition must be a "
                                     "comparison")
                return ["select", cond, _from_pyast(node.args[1]),
                        _from_pyast(node.args[2])]
            name = _NP_CALLS.get(fn.attr)
            if name is None:
                raise ParseError(f"unsupported numpy call np.{fn.attr}")
        else:
            raise ParseError(f"unsupported call {ast.dump(fn)}")
        if len(node.args) != 2:
            raise ParseError(f"{name} wants 2 args")
        return [name, _from_pyast(node.args[0]), _from_pyast(node.args[1])]
    raise ParseError(f"unsupported syntax {type(node).__name__}")


def parse_py_functions(source: str, plane: str
                       ) -> Dict[str, Tuple[List[str], IR, int]]:
    """Extract every emitted logic function from Python-plane source:
    ``{logic_name: (arg_names, ir, def_lineno)}``.  A function matching
    the naming convention whose body is not a single ``return <expr>`` of
    the portable vocabulary maps to ``(args, None, lineno)`` — the caller
    turns that into a finding rather than a crash."""
    out: Dict[str, Tuple[List[str], IR, int]] = {}
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return out
    for node in ast.walk(tree):
        if not isinstance(node, ast.FunctionDef):
            continue
        if plane == "py":
            if not node.name.startswith(PY_PREFIX):
                continue
            logic = node.name[len(PY_PREFIX):]
        else:
            if not node.name.endswith(NP_SUFFIX):
                continue
            logic = node.name[:-len(NP_SUFFIX)]
        args = [a.arg for a in node.args.args]
        body = [s for s in node.body
                if not isinstance(s, ast.Expr)  # docstring
                or not isinstance(s.value, ast.Constant)]
        ir: Optional[IR] = None
        if (len(body) == 1 and isinstance(body[0], ast.Return)
                and body[0].value is not None):
            try:
                ir = _from_pyast(body[0].value)
            except ParseError:
                ir = None
        out[logic] = (args, ir, node.lineno)
    return out
