"""genmark: the fenced-region marker grammar shared by simgen and SIM205.

A *generated region* is a span of a plane source file materialized from
the authoritative protocol spec (``spec/protocol_spec.json``) by
``simgen`` (`make gen`).  Each region is fenced by two marker lines:

    # >>> simgen:begin region=<name> spec=<sha12> body=<sha12>
    ... generated lines ...
    # <<< simgen:end region=<name>

(C files use ``//`` in place of ``#``.)  The ``spec=`` field is the
first 12 hex chars of the SHA-256 of the authoritative spec bytes at
generation time; ``body=`` is the same digest of the region body (the
lines strictly between the markers, including their newlines).  Both
tools — the generator's ``--check`` and the SIM205 lint rule — verify
the same two invariants from the same parse:

* ``body`` mismatch  -> the region was edited BY HAND after generation;
* ``spec`` mismatch  -> the spec changed after the region was emitted
  (the region is STALE; run ``make gen``).

The grammar lives here, below both simgen and twin_rules, so the two
verifiers can never drift on what a marker means.
"""

from __future__ import annotations

import hashlib
import re
from dataclasses import dataclass
from typing import List, Optional, Tuple

SPEC_RELPATH = "spec/protocol_spec.json"

BEGIN_RE = re.compile(
    r"^(?P<indent>\s*)(?P<lead>#|//)\s*>>> simgen:begin"
    r"\s+region=(?P<name>[A-Za-z0-9_.-]+)"
    r"\s+spec=(?P<spec>[0-9a-f]{12})"
    r"\s+body=(?P<body>[0-9a-f]{12})\s*$")
END_RE = re.compile(
    r"^(?P<indent>\s*)(?P<lead>#|//)\s*<<< simgen:end"
    r"\s+region=(?P<name>[A-Za-z0-9_.-]+)\s*$")
# anything that LOOKS like a marker but doesn't parse is a finding, not
# silence — a typo'd fence must not demote a region to "unguarded"
LOOSE_RE = re.compile(r"^\s*(#|//)\s*(>>>|<<<) simgen:")


def sha12(data) -> str:
    if isinstance(data, str):
        data = data.encode("utf-8")
    return hashlib.sha256(data).hexdigest()[:12]


@dataclass
class Region:
    name: str
    lead: str            # "#" or "//"
    indent: str
    begin_line: int      # 1-based line of the begin marker
    end_line: int        # 1-based line of the end marker
    spec_hash: str
    body_hash: str
    body: str            # lines strictly between the markers


def begin_marker(name: str, lead: str, spec_hash: str, body_hash: str,
                 indent: str = "") -> str:
    return (f"{indent}{lead} >>> simgen:begin region={name} "
            f"spec={spec_hash} body={body_hash}")


def end_marker(name: str, lead: str, indent: str = "") -> str:
    return f"{indent}{lead} <<< simgen:end region={name}"


def scan_regions(text: str) -> Tuple[List[Region], List[Tuple[int, str]]]:
    """Parse every fenced region out of one source file.

    Returns (regions, problems) where each problem is (line, message):
    malformed marker lines, begin without end, end without begin, and
    mismatched region names on a begin/end pair.
    """
    regions: List[Region] = []
    problems: List[Tuple[int, str]] = []
    lines = text.splitlines()
    open_m: Optional[re.Match] = None
    open_line = 0
    body_lines: List[str] = []
    for i, line in enumerate(lines, start=1):
        b = BEGIN_RE.match(line)
        e = END_RE.match(line)
        if b is None and e is None:
            if LOOSE_RE.match(line):
                problems.append((i, "malformed simgen region marker — "
                                    "regenerate with `make gen`"))
            elif open_m is not None:
                body_lines.append(line)
            continue
        if b is not None:
            if open_m is not None:
                problems.append((open_line,
                                 f"simgen region "
                                 f"{open_m.group('name')!r} is never "
                                 f"closed before the next begin marker"))
            open_m, open_line, body_lines = b, i, []
            continue
        assert e is not None
        if open_m is None:
            problems.append((i, f"simgen end marker for region "
                                f"{e.group('name')!r} has no begin"))
            continue
        if e.group("name") != open_m.group("name"):
            problems.append((i, f"simgen end marker names region "
                                f"{e.group('name')!r} but the open region "
                                f"is {open_m.group('name')!r}"))
            open_m = None
            continue
        body = "".join(ln + "\n" for ln in body_lines)
        regions.append(Region(
            name=open_m.group("name"), lead=open_m.group("lead"),
            indent=open_m.group("indent"), begin_line=open_line,
            end_line=i, spec_hash=open_m.group("spec"),
            body_hash=open_m.group("body"), body=body))
        open_m = None
    if open_m is not None:
        problems.append((open_line,
                         f"simgen region {open_m.group('name')!r} is "
                         f"never closed"))
    return regions, problems
