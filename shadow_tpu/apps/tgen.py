"""tgen-like traffic generator (capability analog of the tgen plugin the
reference's example configs drive: resource/examples/shadow.config.xml).

Supported behaviors:
    server: ["server", port]                        — accepts streams, sinks
                                                      and/or serves bytes
    client: ["client", server, port, stream_spec...]
        stream_spec: "<send_bytes>:<recv_bytes>" per stream, executed
        sequentially (e.g. "1024:1048576" uploads 1 KiB then downloads 1 MiB
        — the classic tgen web-ish pattern).

Protocol: 16-byte header (8B send count from client, 8B requested bytes from
server), then raw bytes each way.
"""

from __future__ import annotations

from .registry import register


@register("tgen")
def main(api, args):
    role = args[0] if args else "server"
    if role == "server":
        port = int(args[1]) if len(args) > 1 else 80
        yield from _server(api, port)
        return 0
    device_mode = "device" in args
    if device_mode:
        args = [a for a in args if a != "device"]
    server = args[1]
    port = int(args[2]) if len(args) > 2 else 80
    specs = args[3:] if len(args) > 3 else ["1024:65536"]
    if device_mode:
        ok = yield from _client_device(api, server, port, specs)
    else:
        ok = yield from _client(api, server, port, specs)
    return 0 if ok else 1


def _server(api, port):
    lfd = api.socket("tcp")
    api.bind(lfd, ("0.0.0.0", port))
    api.listen(lfd)
    api.log(f"tgen server on :{port}")
    while True:
        cfd, _ = yield from api.accept(lfd)
        api.spawn(_serve_stream, api, cfd)


def _serve_stream(api, fd):
    hdr = b""
    while len(hdr) < 16:
        chunk = yield from api.recv(fd, 16 - len(hdr))
        if not chunk:
            api.close(fd)
            return
        hdr += chunk
    upload = int.from_bytes(hdr[:8], "big")
    download = int.from_bytes(hdr[8:], "big")
    got = 0
    while got < upload:
        chunk = yield from api.recv(fd, 65536)
        if not chunk:
            api.close(fd)
            return
        got += len(chunk)
    sent = 0
    while sent < download:
        n = min(65536, download - sent)
        yield from api.send(fd, b"d" * n)
        sent += n
    api.close(fd)


def _client_device(api, server, port, specs):
    """Device-plane bulk: the control plane still runs — a real TCP
    connect + the tgen header handshake (0:0, so the server serves nothing
    and closes) — then the bulk bytes advance in HBM
    (parallel/device_plane.py) and the client blocks until the plane
    reports completion."""
    fd = api.socket("tcp")
    yield from api.connect(fd, (server, port))
    yield from api.send(fd, (0).to_bytes(8, "big") + (0).to_bytes(8, "big"))
    api.close(fd)
    handle = api.device_flow_start(route=[server])
    done_ns = yield from api.device_flow_join(handle)
    total_down = sum(int(s.partition(":")[2] or 0) for s in specs)
    api.log(f"tgen client device flow complete at {done_ns / 1e9:.3f}s "
            f"({total_down}B down, {len(specs)} streams)")
    return True


def _client(api, server, port, specs):
    ok = True
    for spec in specs:
        up_s, _, down_s = spec.partition(":")
        upload, download = int(up_s), int(down_s or 0)
        fd = api.socket("tcp")
        yield from api.connect(fd, (server, port))
        yield from api.send(fd, upload.to_bytes(8, "big") + download.to_bytes(8, "big"))
        sent = 0
        while sent < upload:
            n = min(65536, upload - sent)
            yield from api.send(fd, b"u" * n)
            sent += n
        got = 0
        while got < download:
            chunk = yield from api.recv(fd, 65536)
            if not chunk:
                break
            got += len(chunk)
        if got != download:
            ok = False
        api.close(fd)
    api.log(f"tgen client finished {len(specs)} streams ok={ok}")
    return ok
