"""Minimal deterministic HTTP/1.0 server — the in-sim peer for real HTTP
clients (wget/curl) running on the native plugin plane.

Args: [port, content_bytes]

Every GET is answered with ``content_bytes`` of a deterministic pattern and
``Connection: close`` framing, which is all wget/curl need to complete a
download whose byte count the test can assert against a native-run transfer
(the reference CI proves its interposition on real tgen/Tor the same way —
an unmodified binary moving real bytes through the simulated network).
"""

from __future__ import annotations

from .registry import register


def _body(n: int) -> bytes:
    pat = b"0123456789abcdef" * 64   # 1 KiB deterministic block
    reps = n // len(pat) + 1
    return (pat * reps)[:n]


@register("httpd")
def main(api, args):
    port = int(args[0]) if args else 80
    nbytes = int(args[1]) if len(args) > 1 else 65536
    body = _body(nbytes)
    head = (b"HTTP/1.0 200 OK\r\n"
            b"Content-Type: application/octet-stream\r\n"
            b"Content-Length: " + str(nbytes).encode() + b"\r\n"
            b"Connection: close\r\n\r\n")
    lfd = api.socket("tcp")
    api.bind(lfd, ("0.0.0.0", port))
    api.listen(lfd, 16)
    api.log(f"httpd on :{port}, {nbytes}B per GET")
    served = 0
    while True:
        cfd, _addr = yield from api.accept(lfd)
        # read until the blank line ending the request head
        req = b""
        while b"\r\n\r\n" not in req and len(req) < 65536:
            chunk = yield from api.recv(cfd, 4096)
            if not chunk:
                break
            req += chunk
        if req.startswith(b"GET") or req.startswith(b"HEAD"):
            payload = head if req.startswith(b"HEAD") else head + body
            yield from api.send(cfd, payload)
        api.shutdown(cfd, 1)
        # drain the client's half-close so TIME_WAIT bookkeeping is clean
        while True:
            tail = yield from api.recv(cfd, 4096)
            if not tail:
                break
        api.close(cfd)
        served += 1
        api.log(f"httpd served request #{served}")
