"""Built-in application plugins (the Python plugin plane).

Each app is a generator function ``main(api, args)`` speaking the
SyscallAPI.  The registry resolves config ``<plugin path>`` strings of the
form ``python:<name>`` (or a bare name) to the app callable; native ``.so``
paths are handled by the native plugin plane (later rounds).
"""

from . import registry  # noqa: F401
