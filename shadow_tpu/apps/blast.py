"""One-way traffic source/sink pair (loss-tolerant, unlike echo).

Used by the CPU<->TPU parity tests and benchmarks: the source sends N
datagrams at a fixed interval without waiting for replies; the sink counts
arrivals and records their virtual timestamps, so two runs can be compared
for exact delivery parity even on lossy links.

Args:
    source: ["udp", dst_name, port, n, size, interval_sec]
    sink:   ["udp", port]
"""

from __future__ import annotations

from .registry import register


class SinkState:
    __slots__ = ("received", "bytes", "arrival_times")

    def __init__(self):
        self.received = 0
        self.bytes = 0
        self.arrival_times = []


@register("sink")
def sink_main(api, args):
    port = int(args[1]) if len(args) > 1 else 8000
    state = SinkState()
    api.process.app_state = state
    fd = api.socket("udp")
    api.bind(fd, ("0.0.0.0", port))
    api.log(f"sink listening on :{port}")
    while True:
        data, _src = yield from api.recvfrom(fd)
        if not data:
            return 0
        state.received += 1
        state.bytes += len(data)
        state.arrival_times.append(api.now_ns())


@register("source")
def source_main(api, args):
    dst = args[1] if len(args) > 1 else "server"
    port = int(args[2]) if len(args) > 2 else 8000
    n = int(args[3]) if len(args) > 3 else 10
    size = int(args[4]) if len(args) > 4 else 512
    interval = float(args[5]) if len(args) > 5 else 0.01
    fd = api.socket("udp")
    for i in range(n):
        api.sendto(fd, bytes([i % 256]) * size, (dst, port))
        if interval > 0:
            yield from api.sleep(interval)
    api.log(f"source done: {n} x {size}B to {dst}:{port}")
    api.close(fd)
    return 0
