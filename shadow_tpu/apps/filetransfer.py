"""File-transfer app: N clients download a payload from a server over TCP.

Mirrors the reference's built-in ``--test`` workload (examples.c: 1000
clients x 10 downloads of /bin/ls served by a filetransfer plugin).

Args:
    server: ["server", port, file_size_bytes]
    client: ["client", server_name, port, n_downloads]
"""

from __future__ import annotations

from .registry import register


@register("filetransfer")
def main(api, args):
    role = args[0] if args else "server"
    if role == "server":
        port = int(args[1]) if len(args) > 1 else 80
        size = int(args[2]) if len(args) > 2 else 16384
        yield from _server(api, port, size)
        return 0
    server = args[1] if len(args) > 1 else "server"
    port = int(args[2]) if len(args) > 2 else 80
    n = int(args[3]) if len(args) > 3 else 1
    ok = yield from _client(api, server, port, n)
    return 0 if ok else 1


def _server(api, port, size):
    lfd = api.socket("tcp")
    api.bind(lfd, ("0.0.0.0", port))
    api.listen(lfd)
    api.log(f"filetransfer server on :{port}, file size {size}")
    while True:
        cfd, _peer = yield from api.accept(lfd)
        api.spawn(_serve_one, api, cfd, size)


def _serve_one(api, fd, size):
    # request = one line; response = 8-byte big-endian length + payload
    req = yield from api.recv(fd, 4096)
    if not req:
        api.close(fd)
        return
    payload = b"x" * size
    yield from api.send(fd, len(payload).to_bytes(8, "big") + payload)
    api.close(fd)


def _client(api, server, port, n):
    total_ok = 0
    for i in range(n):
        fd = api.socket("tcp")
        yield from api.connect(fd, (server, port))
        yield from api.send(fd, b"GET\n")
        hdr = b""
        while len(hdr) < 8:
            chunk = yield from api.recv(fd, 8 - len(hdr))
            if not chunk:
                break
            hdr += chunk
        if len(hdr) < 8:
            api.close(fd)
            continue
        want = int.from_bytes(hdr, "big")
        got = 0
        while got < want:
            chunk = yield from api.recv(fd, 65536)
            if not chunk:
                break
            got += len(chunk)
        if got == want:
            total_ok += 1
        api.close(fd)
    api.log(f"filetransfer client: {total_ok}/{n} downloads ok")
    return total_ok == n
