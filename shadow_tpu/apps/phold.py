"""PHOLD: the classic PDES scheduler benchmark (reference
src/test/phold/test_phold.c): every host runs one phold process; each
process repeatedly sends a small UDP message to a random peer, which
triggers the peer to send onward.  Stresses the scheduler/event pipeline
with uniform all-to-all traffic.

Args: ["<n_hosts>", "<msgs_in_flight>", "<port>"] — peers are named
``phold1..pholdN`` (quantity-expanded host names).
"""

from __future__ import annotations

from .registry import register


@register("phold")
def main(api, args):
    n_hosts = int(args[0]) if args else 2
    seed_msgs = int(args[1]) if len(args) > 1 else 1
    port = int(args[2]) if len(args) > 2 else 9000
    fd = api.socket("udp")
    api.bind(fd, ("0.0.0.0", port))

    def pick_peer():
        # deterministic per-host random peer (host-seeded RNG)
        k = api.rand() % n_hosts
        return f"phold{k + 1}"

    me = api.gethostname()
    for _ in range(seed_msgs):
        peer = pick_peer()
        if peer != me:
            api.sendto(fd, b"phold", (peer, port))
    while True:
        data, _src = yield from api.recvfrom(fd)
        if not data:
            return 0
        peer = pick_peer()
        if peer != me:
            api.sendto(fd, b"phold", (peer, port))
