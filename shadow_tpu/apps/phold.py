"""PHOLD: the classic PDES scheduler benchmark (reference
src/test/phold/test_phold.c): every host runs one phold process; each
process repeatedly sends a small UDP message to a random peer, which
triggers the peer to send onward.  Stresses the scheduler/event pipeline
with uniform all-to-all traffic.

Args: ["<n_hosts>", "<msgs_in_flight>", "<port>"] — peers are named
``phold1..pholdN`` (quantity-expanded host names).
"""

from __future__ import annotations

from .registry import register


@register("phold")
def main(api, args):
    n_hosts = int(args[0]) if args else 2
    seed_msgs = int(args[1]) if len(args) > 1 else 1
    port = int(args[2]) if len(args) > 2 else 9000
    fd = api.socket("udp")
    api.bind(fd, ("0.0.0.0", port))

    name = api.gethostname()
    try:
        # quantity-expanded names are phold1..pholdN; quantity=1 gives bare
        # "phold"; traffic injectors may have unrelated names
        me_idx = int(name[5:]) - 1 if name.startswith("phold") and name[5:] else -1
    except ValueError:
        me_idx = -1

    def pick_peer():
        # deterministic per-host random peer, never self (classic PHOLD:
        # every hop forwards the message, keeping the population constant)
        if n_hosts <= 1 or me_idx < 0:
            k = api.rand() % n_hosts if n_hosts > 0 else 0
        else:
            k = api.rand() % (n_hosts - 1)
            if k >= me_idx:
                k += 1
        return f"phold{k + 1}"

    me = name
    for _ in range(seed_msgs):
        peer = pick_peer()
        if peer != me:
            api.sendto(fd, b"phold", (peer, port))
    while True:
        data, _src = yield from api.recvfrom(fd)
        if not data:
            return 0
        peer = pick_peer()
        if peer != me:
            api.sendto(fd, b"phold", (peer, port))
