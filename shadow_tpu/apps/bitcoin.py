"""Bitcoin-like P2P block-gossip workload (capability analog of
shadow-plugin-bitcoin, BASELINE.md config #5: 5k-node gossip).

Models the network behavior of Bitcoin's block relay: every node keeps
long-lived TCP connections to a set of peers, miners periodically announce
new blocks via ``inv`` messages, peers that haven't seen a block request it
with ``getdata``, receive the full ``block`` bytes, and re-announce to their
own peers — the classic epidemic broadcast whose propagation latency is the
headline metric for this workload family.

Role:
    node <peer1,peer2,...|-> [mine <interval_sec> <block_bytes> <count>]
                             [txgen <interval_sec> <tx_bytes> <count>]
        Connects out to the listed peers (``-`` = none; inbound only) on
        port 8333 and serves inbound connections.  With ``mine``, creates
        <count> blocks every <interval_sec> seconds and announces them;
        with ``txgen``, originates <count> transactions the same way into
        every mempool.  The keywords combine: a node can both mine and
        originate transactions.

Wire format: length-prefixed messages ``u32 len | u8 type | payload``.
Types: INV (u64 block id), GETDATA (u64 block id), BLOCK (u64 id + bytes),
and the transaction-relay triple TXINV/GETTX/TX — the tx gossip that
dominates message counts on the real network (every node relays every
transaction into its peers' mempools the same epidemic way blocks travel).

``process.app_state`` exposes per-node stats (blocks known, bytes relayed,
per-block first-seen virtual time) for tests and benchmark reporting.
"""

from __future__ import annotations

import struct

from .registry import register

PORT = 8333
MSG_HDR = struct.Struct(">IB")
INV = 1
GETDATA = 2
BLOCK = 3
TXINV = 4
GETTX = 5
TX = 6


class NodeState:
    def __init__(self):
        self.blocks = {}            # block_id -> size
        self.requested = set()      # getdata in flight (bitcoind tracks
                                    # in-flight blocks per peer the same way)
        self.first_seen_ns = {}     # block_id -> virtual ns
        self.peers = []             # connected peer fds
        self.bytes_relayed = 0
        self.mined = 0
        # transaction relay (mempool)
        self.mempool = {}           # tx_id -> size
        self.tx_requested = set()
        self.tx_first_seen_ns = {}
        self.txs_originated = 0


def _pack(msg_type: int, payload: bytes) -> bytes:
    return MSG_HDR.pack(len(payload) + 1, msg_type) + payload


def recv_msg(api, fd):
    hdr = yield from api.recv_exact(fd, MSG_HDR.size)
    if hdr is None:
        return None
    length, msg_type = MSG_HDR.unpack(hdr)
    payload = b""
    if length > 1:
        payload = yield from api.recv_exact(fd, length - 1)
        if payload is None:
            return None
    return msg_type, payload


@register("bitcoin")
def main(api, args):
    st = NodeState()
    api.process.app_state = st
    peers = [] if not args or args[0] in ("-", "") else args[0].split(",")
    mine_every = mine_size = mine_count = 0
    tx_every = tx_size = tx_count = 0
    rest = list(args[1:])
    while rest:
        kw = rest.pop(0)
        if kw in ("mine", "txgen"):
            if len(rest) < 2:
                raise ValueError(f"bitcoin: {kw} needs <interval> <bytes>")
            every = float(rest.pop(0))
            size = int(rest.pop(0))
            count = int(rest.pop(0)) if rest and rest[0].isdigit() else 1
            if kw == "mine":
                mine_every, mine_size, mine_count = every, size, count
            else:
                tx_every, tx_size, tx_count = every, size, count
        else:
            raise ValueError(f"bitcoin: unknown argument {kw!r}")

    lfd = api.socket("tcp")
    api.bind(lfd, ("0.0.0.0", PORT))
    api.listen(lfd, 125)  # bitcoind's default max connections
    api.spawn(_accept_loop, api, st, lfd)

    for peer in peers:
        api.spawn(_dial, api, st, peer)

    if mine_every > 0:
        api.spawn(_miner, api, st, mine_every, mine_size, mine_count)
    if tx_every > 0:
        api.spawn(_txgen, api, st, tx_every, tx_size, tx_count)

    # the node runs until the simulation stops it
    while True:
        yield from api.sleep(3600)


def _accept_loop(api, st, lfd):
    while True:
        cfd, _ = yield from api.accept(lfd)
        st.peers.append(cfd)
        api.spawn(_inbound_peer, api, st, cfd)


def _inbound_peer(api, st, fd):
    # exchange must be two-way: a late joiner's inbound link is its only
    # path to blocks/txs known before the link formed
    for block_id in list(st.blocks):
        yield from api.send(fd, _pack(INV, struct.pack(">Q", block_id)))
    for tx_id in list(st.mempool):
        yield from api.send(fd, _pack(TXINV, struct.pack(">Q", tx_id)))
    yield from _peer_loop(api, st, fd)


def _dial(api, st, peer):
    """Dial with retry: peers boot in staggered waves, so the first attempts
    can hit a not-yet-listening node (bitcoind retries its addrman the same
    way); give up only after the overlay has clearly had time to form."""
    fd = None
    for attempt in range(12):
        fd = api.socket("tcp")
        try:
            yield from api.connect(fd, (peer, PORT))
            break
        except OSError:
            api.close(fd)
            fd = None
            yield from api.sleep(5 * (attempt + 1))
    if fd is None:
        api.log(f"bitcoin: dial {peer} failed permanently")
        return
    st.peers.append(fd)
    # announce everything we already know (block + tx exchange on connect)
    for block_id in list(st.blocks):
        yield from api.send(fd, _pack(INV, struct.pack(">Q", block_id)))
    for tx_id in list(st.mempool):
        yield from api.send(fd, _pack(TXINV, struct.pack(">Q", tx_id)))
    yield from _peer_loop(api, st, fd)


def _peer_loop(api, st, fd):
    inflight = set()  # getdata sent on THIS connection, block not yet seen
    tx_inflight = set()
    while True:
        msg = yield from recv_msg(api, fd)
        if msg is None:
            break
        msg_type, payload = msg
        if msg_type == INV:
            (block_id,) = struct.unpack(">Q", payload)
            if block_id not in st.blocks and block_id not in st.requested:
                st.requested.add(block_id)
                inflight.add(block_id)
                yield from api.send(fd, _pack(GETDATA, payload))
        elif msg_type == GETDATA:
            (block_id,) = struct.unpack(">Q", payload)
            size = st.blocks.get(block_id)
            if size is not None:
                body = struct.pack(">Q", block_id) + b"\0" * size
                st.bytes_relayed += len(body)
                yield from api.send(fd, _pack(BLOCK, body))
        elif msg_type == BLOCK:
            (block_id,) = struct.unpack(">Q", payload[:8])
            st.requested.discard(block_id)
            inflight.discard(block_id)
            if block_id not in st.blocks:
                _learn_block(api, st, block_id, len(payload) - 8)
                yield from _announce(api, st, block_id, exclude=fd)
        elif msg_type == TXINV:
            (tx_id,) = struct.unpack(">Q", payload)
            if tx_id not in st.mempool and tx_id not in st.tx_requested:
                st.tx_requested.add(tx_id)
                tx_inflight.add(tx_id)
                yield from api.send(fd, _pack(GETTX, payload))
        elif msg_type == GETTX:
            (tx_id,) = struct.unpack(">Q", payload)
            size = st.mempool.get(tx_id)
            if size is not None:
                body = struct.pack(">Q", tx_id) + b"\0" * size
                st.bytes_relayed += len(body)
                yield from api.send(fd, _pack(TX, body))
        elif msg_type == TX:
            (tx_id,) = struct.unpack(">Q", payload[:8])
            st.tx_requested.discard(tx_id)
            tx_inflight.discard(tx_id)
            if tx_id not in st.mempool:
                st.mempool[tx_id] = len(payload) - 8
                st.tx_first_seen_ns[tx_id] = api.now_ns()
                yield from _announce_tx(api, st, tx_id, exclude=fd)
    # a dead peer's undelivered getdata/gettx must not black-hole those
    # items: clear them so another peer's inv re-triggers the request
    # (sorted: set iteration order is hash-seed-dependent — SIM003)
    for block_id in sorted(inflight):
        if block_id not in st.blocks:
            st.requested.discard(block_id)
    for tx_id in sorted(tx_inflight):
        if tx_id not in st.mempool:
            st.tx_requested.discard(tx_id)
    if fd in st.peers:
        st.peers.remove(fd)
    api.close(fd)


def _learn_block(api, st, block_id, size):
    st.blocks[block_id] = size
    st.first_seen_ns[block_id] = api.now_ns()


def _broadcast(api, st, msg, exclude=None):
    """Send an announcement to every live peer but the one it came from
    (send failures mean the peer loop is tearing that fd down)."""
    for peer_fd in list(st.peers):
        if peer_fd == exclude:
            continue
        try:
            yield from api.send(peer_fd, msg)
        except OSError:
            pass


def _announce(api, st, block_id, exclude=None):
    yield from _broadcast(api, st, _pack(INV, struct.pack(">Q", block_id)),
                          exclude)


def _announce_tx(api, st, tx_id, exclude=None):
    yield from _broadcast(api, st, _pack(TXINV, struct.pack(">Q", tx_id)),
                          exclude)


def _txgen(api, st, every_sec, tx_size, count):
    """Originates transactions with globally-unique ids in a disjoint id
    space from blocks: (1 << 56) | (host_id << 20) | seq."""
    host_id = api.host.id
    for seq in range(count):
        yield from api.sleep(every_sec)
        tx_id = (1 << 56) | (host_id << 20) | seq
        st.mempool[tx_id] = tx_size
        st.tx_first_seen_ns[tx_id] = api.now_ns()
        st.txs_originated += 1
        yield from _announce_tx(api, st, tx_id)


def _miner(api, st, every_sec, block_size, count):
    """Creates blocks with globally-unique ids: (host_id << 20) | seq."""
    host_id = api.host.id
    for seq in range(count):
        yield from api.sleep(every_sec)
        block_id = (host_id << 20) | seq
        _learn_block(api, st, block_id, block_size)
        st.mined += 1
        api.log(f"bitcoin: mined block {block_id:#x} ({block_size}B)")
        yield from _announce(api, st, block_id)
