"""UDP/TCP echo pair — the smallest end-to-end traffic app.

Args:
    server:  ["udp"|"tcp", "server", port]
    client:  ["udp"|"tcp", "client", server_name, port, n_messages, msg_size]

The client sends n messages and validates each echo; exits 0 on success.
Used by the 2-host smoke workload (BASELINE.md config #1 analog).
"""

from __future__ import annotations

from .registry import register


@register("echo")
def main(api, args):
    proto = args[0] if args else "udp"
    role = args[1] if len(args) > 1 else "server"
    if role == "server":
        port = int(args[2]) if len(args) > 2 else 8000
        if proto == "udp":
            yield from _udp_server(api, port)
        else:
            yield from _tcp_server(api, port)
        return 0
    server = args[2] if len(args) > 2 else "server"
    port = int(args[3]) if len(args) > 3 else 8000
    n = int(args[4]) if len(args) > 4 else 10
    size = int(args[5]) if len(args) > 5 else 1024
    if proto == "udp":
        ok = yield from _udp_client(api, server, port, n, size)
    else:
        ok = yield from _tcp_client(api, server, port, n, size)
    return 0 if ok else 1


def _udp_server(api, port):
    fd = api.socket("udp")
    api.bind(fd, ("0.0.0.0", port))
    api.log(f"udp echo server on :{port}")
    while True:
        data, src = yield from api.recvfrom(fd)
        if not data:
            return
        api.sendto(fd, data, src)


def _udp_client(api, server, port, n, size):
    fd = api.socket("udp")
    ok = True
    for i in range(n):
        msg = bytes([i % 256]) * size
        api.sendto(fd, msg, (server, port))
        data, _ = yield from api.recvfrom(fd)
        if data != msg:
            api.log(f"echo mismatch on message {i}: got {len(data)} bytes")
            ok = False
    api.log(f"udp client done: {n} messages of {size}B echoed ok={ok}")
    api.close(fd)
    return ok


def _tcp_server(api, port):
    lfd = api.socket("tcp")
    api.bind(lfd, ("0.0.0.0", port))
    api.listen(lfd)
    api.log(f"tcp echo server on :{port}")
    while True:
        cfd, peer = yield from api.accept(lfd)
        api.spawn(_tcp_echo_conn, api, cfd)


def _tcp_echo_conn(api, fd):
    while True:
        data = yield from api.recv(fd, 65536)
        if not data:
            api.close(fd)
            return
        yield from api.send(fd, data)


def _tcp_client(api, server, port, n, size):
    fd = api.socket("tcp")
    yield from api.connect(fd, (server, port))
    ok = True
    for i in range(n):
        msg = bytes([i % 256]) * size
        yield from api.send(fd, msg)
        got = b""
        while len(got) < size:
            chunk = yield from api.recv(fd, size - len(got))
            if not chunk:
                ok = False
                break
            got += chunk
        if got != msg:
            ok = False
    api.log(f"tcp client done: {n} messages of {size}B echoed ok={ok}")
    api.close(fd)
    return ok
