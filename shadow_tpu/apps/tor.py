"""Tor-like onion-relay workload (capability analog of shadow-plugin-tor,
the reference's flagship workload: BASELINE.md configs #3/#4).

Models the *network behavior* of a Tor overlay — fixed 512-byte cells,
multi-hop circuits built with EXTEND handshakes, stream multiplexing over
circuits, exit-side TCP to the destination — without the cryptography
(the reference's plugin runs real Tor; what the simulator measures is the
traffic pattern, which this reproduces: per-hop store-and-forward of cells
over long-lived TCP connections).

Roles:
    relay <orport>
        Accepts OR connections, creates/extends circuits, relays cells.
    client <socksport> <path> <dest> <destport> <nstreams> <up:down> [...]
        <path> = comma-separated relay hostnames (guard,middle,exit).
        Builds one circuit through <path>, then runs <nstreams> sequential
        streams to <dest>:<destport>, each uploading `up` bytes and
        downloading `down` bytes (tgen-style).
    server <port>
        Destination: tgen-protocol byte sink/source.

Cell format (fixed CELL_SIZE bytes on the wire):
    u32 circ_id | u8 cmd | u16 len | payload (padded)

Commands: CREATE/CREATED (one-hop handshake), EXTEND/EXTENDED (grow the
circuit by one hop), BEGIN/CONNECTED (open exit stream), DATA, END.
"""

from __future__ import annotations

import struct

from .registry import register

CELL_SIZE = 512
HDR = struct.Struct(">IBH")
PAYLOAD_MAX = CELL_SIZE - HDR.size

CREATE = 1
CREATED = 2
EXTEND = 3
EXTENDED = 4
BEGIN = 5
CONNECTED = 6
DATA = 7
END = 8


def make_cell(circ_id: int, cmd: int, payload: bytes = b"") -> bytes:
    assert len(payload) <= PAYLOAD_MAX
    return HDR.pack(circ_id, cmd, len(payload)) + payload.ljust(PAYLOAD_MAX, b"\0")


def parse_cell(cell: bytes):
    circ_id, cmd, plen = HDR.unpack(cell[:HDR.size])
    return circ_id, cmd, cell[HDR.size:HDR.size + plen]


def recv_cell(api, fd):
    cell = yield from api.recv_exact(fd, CELL_SIZE)
    if cell is None:
        return None
    return parse_cell(cell)


@register("tor")
def main(api, args):
    role = args[0] if args else "relay"
    if role == "relay":
        # relay <orport> [<dirauth_host:port> <bw_weight>]: with a dirauth,
        # the relay publishes its descriptor after opening the ORPort (real
        # Tor also listens before uploading its descriptor, so the
        # consensus never advertises a closed port)
        orport = int(args[1]) if len(args) > 1 else 9001
        dirspec = args[2] if len(args) > 2 else None
        bw = int(args[3]) if len(args) > 3 else 100
        yield from relay_main(api, orport, dirspec, bw)
        return 0
    if role == "server":
        yield from server_main(api, int(args[1]) if len(args) > 1 else 80)
        return 0
    if role == "client":
        ok = yield from client_main(api, args[1:])
        return 0 if ok else 1
    if role == "dirauth":
        yield from dirauth_main(api, int(args[1]) if len(args) > 1 else 9030)
        return 0
    raise ValueError(f"tor: unknown role {role!r}")


# ---------------------------------------------------------------------------
# directory authority (v3 dirauth network behavior: relays upload
# descriptors, clients fetch the consensus and weight their path selection
# by advertised bandwidth — the bootstrap phase real Tor networks start
# with; the crypto/voting among authorities is out of model scope)
# ---------------------------------------------------------------------------

def publish_descriptor(api, dirspec, orport, bw_weight):
    host, _, port = dirspec.partition(":")
    fd = api.socket("tcp")
    yield from api.connect(fd, (host, int(port or 9030)))
    line = f"r {api.host.name} {orport} {bw_weight}\n".encode()
    yield from api.send(fd, line)
    api.close(fd)


def dirauth_main(api, port):
    relays = {}          # name -> (orport, bw)
    api.process.app_state = relays
    lfd = api.socket("tcp")
    api.bind(lfd, ("0.0.0.0", port))
    api.listen(lfd, 64)
    api.log(f"tor dirauth on :{port}")
    while True:
        cfd, _ = yield from api.accept(lfd)
        api.spawn(_dirauth_conn, api, relays, cfd)


def _dirauth_conn(api, relays, fd):
    buf = b""
    while b"\n" not in buf:
        data = yield from api.recv(fd, 4096)
        if not data:
            api.close(fd)
            return
        buf += data
    line = buf.split(b"\n", 1)[0].decode()
    if line.startswith("r "):
        _, name, orport, bw = line.split()
        relays[name] = (int(orport), int(bw))
    elif line.startswith("GETCONS"):
        # deterministic consensus: sorted by relay name
        doc = "".join(f"r {n} {p} {w}\n"
                      for n, (p, w) in sorted(relays.items()))
        yield from api.send(fd, doc.encode() + b".\n")
    api.close(fd)


def fetch_consensus(api, dirspec):
    """Client-side bootstrap: fetch and parse the consensus."""
    host, _, port = dirspec.partition(":")
    fd = api.socket("tcp")
    yield from api.connect(fd, (host, int(port or 9030)))
    yield from api.send(fd, b"GETCONS\n")
    buf = b""
    complete = False
    while True:
        if buf.endswith(b".\n"):
            complete = True
            break
        data = yield from api.recv(fd, 65536)
        if not data:
            break
        buf += data
    api.close(fd)
    if not complete:
        # truncated document (authority died mid-send): fail the bootstrap
        # loudly rather than route over a silently partial consensus
        return []
    relays = []
    for line in buf.decode().splitlines():
        parts = line.split()
        if len(parts) == 4 and parts[0] == "r":
            relays.append((parts[1], int(parts[2]), int(parts[3])))
    return relays


def pick_weighted(rng, relays, n_hops=3):
    """Bandwidth-weighted selection without replacement from an explicit
    RandomSource.  Shared by the runtime client AND the device plane's
    startup path prediction (parallel/device_plane.py replays the same
    draws from the same derived stream)."""
    pool = list(relays)
    path = []
    for _ in range(min(n_hops, len(pool))):
        total = sum(w for _n, _p, w in pool)
        draw = rng.next_int(max(total, 1))
        acc = 0
        for i, (name, orport, w) in enumerate(pool):
            acc += w
            if draw < acc:
                path.append((name, orport))
                pool.pop(i)
                break
        else:
            path.append(pool[-1][:2])
            pool.pop()
    return path


def pick_path(api, relays, n_hops=3):
    """Bandwidth-weighted path selection without replacement, drawn from
    the HOST's deterministic RNG (per-host stream: identical across
    scheduler policies, so digests stay parity-comparable)."""
    return pick_weighted(api.host.random, relays, n_hops)


# ---------------------------------------------------------------------------
# relay
# ---------------------------------------------------------------------------

class _RelayState:
    """Per-relay circuit switchboard.

    circuits maps (conn_fd, circ_id) -> ("fwd", out_fd, out_circ_id) for a
    spliced middle hop, or ("exit", stream_fd) once the exit stream is open.
    """

    def __init__(self):
        self.circuits = {}
        self.next_circ_id = 1
        self.cells_relayed = 0


def relay_main(api, orport, dirspec=None, bw_weight=100):
    st = _RelayState()
    api.process.app_state = st
    lfd = api.socket("tcp")
    api.bind(lfd, ("0.0.0.0", orport))
    api.listen(lfd, 64)
    api.log(f"tor relay on :{orport}")
    if dirspec:
        yield from publish_descriptor(api, dirspec, orport, bw_weight)
    while True:
        cfd, _ = yield from api.accept(lfd)
        api.spawn(_relay_conn, api, st, cfd)


def _relay_conn(api, st, fd):
    """Serve one inbound OR connection: each cell either manages a circuit
    or is relayed to the next hop / exit stream."""
    while True:
        parsed = yield from recv_cell(api, fd)
        if parsed is None:
            break
        circ_id, cmd, payload = parsed
        key = (fd, circ_id)
        if cmd == CREATE:
            st.circuits[key] = None  # endpoint of the circuit so far
            yield from api.send(fd, make_cell(circ_id, CREATED))
        elif cmd == EXTEND:
            route = st.circuits.get(key)
            if route is not None and route[0] == "fwd":
                # already spliced: the EXTEND is for a later hop — relay it
                # down the circuit (real Tor extends end-to-end the same way)
                _, out, out_circ = route
                yield from api.send(out,
                                    make_cell(out_circ, EXTEND, payload))
                continue
            # we are the current endpoint: connect onward, splice
            target = payload.decode()
            host, _, port = target.partition(":")
            out = api.socket("tcp")
            try:
                yield from api.connect(out, (host, int(port)))
            except OSError:
                yield from api.send(fd, make_cell(circ_id, END))
                continue
            out_circ = st.next_circ_id
            st.next_circ_id += 1
            yield from api.send(out, make_cell(out_circ, CREATE))
            reply = yield from recv_cell(api, out)
            if reply is None or reply[1] != CREATED:
                yield from api.send(fd, make_cell(circ_id, END))
                continue
            st.circuits[key] = ("fwd", out, out_circ)
            api.spawn(_relay_backward, api, st, out, out_circ, fd, circ_id)
            yield from api.send(fd, make_cell(circ_id, EXTENDED))
        elif cmd in (BEGIN, DATA, END):
            route = st.circuits.get(key)
            if cmd == BEGIN and (route is None or route[0] == "exit"):
                # we are the exit: open (or reopen, for the next sequential
                # stream on this circuit) the destination stream
                target = payload.decode()
                host, _, port = target.partition(":")
                sfd = api.socket("tcp")
                try:
                    yield from api.connect(sfd, (host, int(port)))
                except OSError:
                    yield from api.send(fd, make_cell(circ_id, END))
                    continue
                st.circuits[key] = ("exit", sfd)
                api.spawn(_exit_backward, api, st, key, sfd, fd, circ_id)
                yield from api.send(fd, make_cell(circ_id, CONNECTED))
            elif route is not None and route[0] == "fwd":
                _, out, out_circ = route
                st.cells_relayed += 1
                yield from api.send(out, make_cell(out_circ, cmd, payload))
            elif route is not None and route[0] == "exit":
                _, sfd = route
                if cmd == DATA:
                    st.cells_relayed += 1
                    yield from api.send(sfd, payload)
                elif cmd == END:
                    api.close(sfd)
                    st.circuits.pop(key, None)
    api.close(fd)


def _relay_backward(api, st, out, out_circ, fd, circ_id):
    """Pump cells arriving from the next hop back down the circuit."""
    while True:
        parsed = yield from recv_cell(api, out)
        if parsed is None:
            break
        in_circ, cmd, payload = parsed
        if in_circ != out_circ:
            continue
        st.cells_relayed += 1
        yield from api.send(fd, make_cell(circ_id, cmd, payload))


def _exit_backward(api, st, key, sfd, fd, circ_id):
    """Exit side: wrap destination bytes into DATA cells toward the client."""
    while True:
        data = yield from api.recv(sfd, PAYLOAD_MAX)
        if not data:
            break
        st.cells_relayed += 1
        yield from api.send(fd, make_cell(circ_id, DATA, data))
    # destination closed: clear the route so the next BEGIN can reopen
    if st.circuits.get(key) == ("exit", sfd):
        st.circuits[key] = None
    api.close(sfd)
    yield from api.send(fd, make_cell(circ_id, END))


# ---------------------------------------------------------------------------
# destination server (tgen protocol: 16B header, raw bytes both ways)
# ---------------------------------------------------------------------------

def server_main(api, port):
    lfd = api.socket("tcp")
    api.bind(lfd, ("0.0.0.0", port))
    api.listen(lfd, 64)
    api.log(f"tor destination server on :{port}")
    while True:
        cfd, _ = yield from api.accept(lfd)
        api.spawn(_serve_one, api, cfd)


def _serve_one(api, fd):
    hdr = yield from api.recv_exact(fd, 16)
    if hdr is None:
        api.close(fd)
        return
    upload = int.from_bytes(hdr[:8], "big")
    download = int.from_bytes(hdr[8:], "big")
    got = 0
    while got < upload:
        chunk = yield from api.recv(fd, 65536)
        if not chunk:
            api.close(fd)
            return
        got += len(chunk)
    sent = 0
    blob = b"x" * 65536
    while sent < download:
        n = min(len(blob), download - sent)
        yield from api.send(fd, blob[:n])
        sent += n
    api.close(fd)


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------

class _ClientStats:
    def __init__(self):
        self.streams_ok = 0
        self.bytes_up = 0
        self.bytes_down = 0


def client_main(api, args):
    # args: <socksport> <path> <dest> <destport> <nstreams> <spec...>
    #       [device]
    # path entries are "relayhost" or "relayhost:orport" (default 9001,
    # matching the relay role's default), OR "auto:<dirhost>:<dirport>" to
    # bootstrap like real Tor: fetch the consensus from the directory
    # authority and pick a bandwidth-weighted 3-hop path.
    # The trailing "device" flag promotes the DATA phase to the
    # device-resident traffic plane: the circuit is still built through the
    # real engine (TCP + CREATE/EXTEND through real relays), then the bulk
    # download advances in HBM (parallel/device_plane.py) and the client
    # blocks until the plane reports completion.
    device_mode = "device" in args
    if device_mode:
        args = [a for a in args if a != "device"]
    if args[1].startswith("auto:"):
        # "auto:<dirhost>" or "auto:<dirhost>:<dirport>" (default 9030,
        # same optional-port convention as relay specs)
        consensus = yield from fetch_consensus(api, args[1][len("auto:"):])
        if not consensus:
            api.log("tor client: empty consensus")
            return False
        if device_mode:
            # device plane: draw from a DERIVED stream (order-independent)
            # so the plane can predict this exact path at startup from the
            # config-determined consensus (parallel/device_plane.py); the
            # consensus fetch above still exercised the real TCP bootstrap
            path = pick_weighted(api.host.random.spawn("device-circuit"),
                                 consensus)
        else:
            path = pick_path(api, consensus)
        api.log(f"tor client: consensus has {len(consensus)} relays, "
                f"picked {'->'.join(h for h, _ in path)}")
    else:
        path = [(h.partition(":")[0], int(h.partition(":")[2] or 9001))
                for h in args[1].split(",")]
    dest, destport = args[2], int(args[3])
    nstreams = int(args[4]) if len(args) > 4 else 1
    specs = args[5:] if len(args) > 5 else ["100:10000"]
    stats = _ClientStats()
    api.process.app_state = stats

    # build the circuit: connect to the guard, CREATE, then EXTEND per hop
    guard, guard_port = path[0]
    fd = api.socket("tcp")
    yield from api.connect(fd, (guard, guard_port))
    circ = 1
    yield from api.send(fd, make_cell(circ, CREATE))
    reply = yield from recv_cell(api, fd)
    if reply is None or reply[1] != CREATED:
        api.log("tor client: CREATE failed")
        return False
    for hop, hop_port in path[1:]:
        yield from api.send(fd,
                            make_cell(circ, EXTEND, f"{hop}:{hop_port}".encode()))
        reply = yield from recv_cell(api, fd)
        if reply is None or reply[1] != EXTENDED:
            api.log(f"tor client: EXTEND to {hop} failed")
            return False
    api.log(f"tor client: circuit built through {'->'.join(h for h, _ in path)}")

    if device_mode:
        # control plane done — hand the bulk transfer to the device plane
        # (the route cross-check catches a consensus-prediction divergence
        # for auto: clients; static paths trivially match)
        handle = api.device_flow_start(route=[h for h, _p in path])
        done_ns = yield from api.device_flow_join(handle)
        for i in range(nstreams):
            spec = specs[i % len(specs)]
            up, down = (int(x) for x in spec.split(":"))
            stats.streams_ok += 1
            stats.bytes_up += up
            stats.bytes_down += down
        yield from api.send(fd, make_cell(circ, END))
        api.close(fd)
        api.log(f"tor client: device flow complete at "
                f"{done_ns / 1e9:.3f}s ({stats.bytes_down}B down, "
                f"{stats.streams_ok} streams)")
        return True

    for i in range(nstreams):
        spec = specs[i % len(specs)]
        up, down = (int(x) for x in spec.split(":"))
        ok = yield from _run_stream(api, fd, circ, dest, destport, up, down)
        if not ok:
            return False
        stats.streams_ok += 1
        stats.bytes_up += up
        stats.bytes_down += down
    yield from api.send(fd, make_cell(circ, END))
    api.close(fd)
    api.log(f"tor client: {stats.streams_ok} streams OK "
            f"({stats.bytes_up}B up, {stats.bytes_down}B down)")
    return True


def _run_stream(api, fd, circ, dest, destport, up, down):
    yield from api.send(fd,
                        make_cell(circ, BEGIN, f"{dest}:{destport}".encode()))
    reply = yield from recv_cell(api, fd)
    if reply is None or reply[1] != CONNECTED:
        return False
    # tgen header through the tunnel
    hdr = up.to_bytes(8, "big") + down.to_bytes(8, "big")
    body = hdr + b"u" * up
    for off in range(0, len(body), PAYLOAD_MAX):
        yield from api.send(fd,
                            make_cell(circ, DATA, body[off:off + PAYLOAD_MAX]))
    got = 0
    ended = False
    while got < down:
        reply = yield from recv_cell(api, fd)
        if reply is None:
            return False
        _, cmd, payload = reply
        if cmd == END:
            ended = True
            break
        if cmd == DATA:
            got += len(payload)
    # drain the exit's END so it can't be mistaken for the next stream's
    # CONNECTED reply (streams run sequentially on one circuit)
    while not ended:
        reply = yield from recv_cell(api, fd)
        if reply is None:
            return False
        if reply[1] == END:
            ended = True
    return got >= down
