"""App registry: plugin-path strings -> app callables."""

from __future__ import annotations

import importlib
from typing import Callable, Dict

_APPS: Dict[str, Callable] = {}


def register(name: str):
    def deco(fn):
        _APPS[name] = fn
        return fn
    return deco


def resolve(path: str) -> Callable:
    """``python:echo`` / ``echo`` -> registered app; ``pkg.mod:fn`` -> import;
    ``exec:/path/to/bin`` or a path to a real executable -> native plugin
    (runs the unmodified binary under the LD_PRELOAD interposer,
    process/native.py — the reference's `<plugin path=...>` equivalent)."""
    import os
    if path.startswith("exec:"):
        from ..process.native import make_native_app
        return make_native_app(path[5:])
    if path.startswith("pool:") or (path.endswith(".so")
                                    and os.path.isfile(path)):
        # shared-object plugins are pooled: many dlmopen namespaces per
        # helper process (the reference's elf-loader model)
        from ..process.native import make_pooled_app
        return make_pooled_app(path[5:] if path.startswith("pool:") else path)
    name = path[7:] if path.startswith("python:") else path
    _ensure_builtins()
    if name in _APPS:
        return _APPS[name]
    if os.path.sep in path and os.path.isfile(path) and os.access(path, os.X_OK):
        from ..process.native import make_native_app
        return make_native_app(path)
    if ":" in name:
        mod, _, fn = name.partition(":")
        return getattr(importlib.import_module(mod), fn)
    raise ValueError(f"unknown program {path!r}; registered: {sorted(_APPS)}")


def _ensure_builtins() -> None:
    from . import (echo, filetransfer, tgen, phold, blast,  # noqa: F401
                   tor, bitcoin, httpd)  # noqa: F401
