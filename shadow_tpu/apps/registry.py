"""App registry: plugin-path strings -> app callables."""

from __future__ import annotations

import importlib
from typing import Callable, Dict

_APPS: Dict[str, Callable] = {}


def register(name: str):
    def deco(fn):
        _APPS[name] = fn
        return fn
    return deco


def resolve(path: str) -> Callable:
    """``python:echo`` / ``echo`` -> registered app; ``pkg.mod:fn`` -> import."""
    name = path[7:] if path.startswith("python:") else path
    _ensure_builtins()
    if name in _APPS:
        return _APPS[name]
    if ":" in name:
        mod, _, fn = name.partition(":")
        return getattr(importlib.import_module(mod), fn)
    raise ValueError(f"unknown program {path!r}; registered: {sorted(_APPS)}")


def _ensure_builtins() -> None:
    from . import echo, filetransfer, tgen, phold, blast  # noqa: F401
