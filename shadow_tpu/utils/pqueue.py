"""Priority queues for events.

The reference wraps GLib heaps with a membership hash for O(1) find/remove
(utility/priority_queue.c) and a mutexed variant
(utility/async_priority_queue.c).  We build on ``heapq`` with lazy deletion —
removal marks the entry dead; dead entries are skipped on pop.
"""

from __future__ import annotations

import heapq
import threading
from typing import Any, Generic, List, Optional, Tuple, TypeVar

T = TypeVar("T")


class PriorityQueue(Generic[T]):
    """Min-heap keyed by item.order_key() (or the item itself), with lazy
    removal."""

    __slots__ = ("_heap", "_entries", "_count")

    def __init__(self):
        self._heap: List[Tuple[Any, int, list]] = []
        self._entries = {}  # id(item) -> entry
        self._count = 0     # insertion tiebreak for identical keys

    def __len__(self) -> int:
        return len(self._entries)

    def push(self, item: T, key=None) -> None:
        if key is None:
            key = item.order_key()
        if id(item) in self._entries:
            # Re-push = reschedule: drop the stale heap entry so one item
            # never has two live entries (the membership hash the reference's
            # priority_queue.c maintains for the same reason).  Calls the
            # unlocked helper so AsyncPriorityQueue.push doesn't self-deadlock.
            self._remove_impl(item)
        entry = [key, self._count, item, True]
        self._count += 1
        self._entries[id(item)] = entry
        heapq.heappush(self._heap, entry)

    def _remove_impl(self, item: T) -> bool:
        entry = self._entries.pop(id(item), None)
        if entry is None:
            return False
        entry[3] = False
        entry[2] = None
        return True

    def remove(self, item: T) -> bool:
        return self._remove_impl(item)

    def __contains__(self, item: T) -> bool:
        return id(item) in self._entries

    def _prune(self) -> None:
        while self._heap and not self._heap[0][3]:
            heapq.heappop(self._heap)

    def peek(self) -> Optional[T]:
        self._prune()
        return self._heap[0][2] if self._heap else None

    def peek_key(self):
        self._prune()
        return self._heap[0][0] if self._heap else None

    def pop(self) -> Optional[T]:
        self._prune()
        if not self._heap:
            return None
        entry = heapq.heappop(self._heap)
        del self._entries[id(entry[2])]
        return entry[2]


class AsyncPriorityQueue(PriorityQueue[T]):
    """Mutex-protected variant (reference utility/async_priority_queue.c)."""

    __slots__ = ("_lock",)

    def __init__(self):
        super().__init__()
        self._lock = threading.Lock()

    def __len__(self) -> int:
        with self._lock:
            return super().__len__()

    def push(self, item: T, key=None) -> None:
        with self._lock:
            super().push(item, key)

    def remove(self, item: T) -> bool:
        with self._lock:
            return self._remove_impl(item)

    def peek(self) -> Optional[T]:
        with self._lock:
            return super().peek()

    def peek_key(self):
        with self._lock:
            return super().peek_key()

    def pop(self) -> Optional[T]:
        with self._lock:
            return super().pop()
