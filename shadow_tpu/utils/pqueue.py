"""Priority queues for events.

The reference wraps GLib heaps with a membership hash for O(1) find/remove
(utility/priority_queue.c) and a mutexed variant
(utility/async_priority_queue.c).  We build on ``heapq`` with lazy deletion —
removal marks the entry dead; dead entries are skipped on pop.

Hot-path design: the membership hash is replaced by an intrusive slot on the
item itself (``item.pq_entry`` — Event reserves it).  A push that reschedules
an already-queued item invalidates its live entry through the slot instead of
a dict lookup; pops clear the entry's live bit as it leaves the heap.  One
item is in at most one queue at a time (the scheduler policies' invariant —
steal migration pops before re-pushing), which is what makes the single slot
sufficient.
"""

from __future__ import annotations

import heapq
import threading
from typing import Any, Generic, List, Optional, TypeVar

T = TypeVar("T")


class PriorityQueue(Generic[T]):
    """Min-heap keyed by item.order_key() (or an explicit key), with lazy
    removal.  Items must expose a writable ``pq_entry`` attribute."""

    __slots__ = ("_heap", "_count", "_len")

    def __init__(self):
        self._heap: List[list] = []   # [key, tiebreak, item, live, owner]
        self._count = 0               # insertion tiebreak for identical keys
        self._len = 0                 # live entries

    def __len__(self) -> int:
        return self._len

    def push(self, item: T, key=None) -> None:
        if key is None:
            key = item.order_key()
        old = item.pq_entry
        if old is not None and old[3]:
            # re-push = reschedule: kill the stale live entry so one item
            # never has two live entries (the membership hash the
            # reference's priority_queue.c maintains for the same reason).
            # A live entry in ANOTHER queue would mean the one-queue-at-a-
            # time invariant broke upstream; mutating that queue from here
            # would race its lock, so fail loudly instead.  Unconditional
            # (not assert): under python -O a silent violation would corrupt
            # the other queue's _len and skew state digests.
            if old[4] is not self:
                raise RuntimeError("item is live in another queue")
            old[3] = False
            old[2] = None
            self._len -= 1
        entry = [key, self._count, item, True, self]
        self._count += 1
        item.pq_entry = entry
        heapq.heappush(self._heap, entry)
        self._len += 1

    def remove(self, item: T) -> bool:
        entry = getattr(item, "pq_entry", None)
        if entry is None or not entry[3] or entry[4] is not self:
            return False
        entry[3] = False
        entry[2] = None
        self._len -= 1
        return True

    def __contains__(self, item: T) -> bool:
        entry = getattr(item, "pq_entry", None)
        return entry is not None and entry[3] and entry[4] is self

    def _prune(self) -> None:
        heap = self._heap
        while heap and not heap[0][3]:
            heapq.heappop(heap)

    def peek(self) -> Optional[T]:
        self._prune()
        return self._heap[0][2] if self._heap else None

    def peek_key(self):
        self._prune()
        return self._heap[0][0] if self._heap else None

    def pop(self) -> Optional[T]:
        heap = self._heap
        while heap:
            entry = heapq.heappop(heap)
            if entry[3]:
                entry[3] = False
                item = entry[2]
                # Clear both directions of the entry<->item link: the engine
                # runs with cyclic GC disabled, and a dead [.., item, ..] cell
                # still referenced by item.pq_entry is an uncollectable cycle
                # that would pin every executed Event until shutdown.
                entry[2] = None
                item.pq_entry = None
                self._len -= 1
                return item
        return None

    def pop_before(self, time_limit) -> Optional[T]:
        """Pop the min item iff its key's time field (key[0]) < time_limit —
        the scheduler's window-bounded pop in ONE heap pass."""
        heap = self._heap
        while heap:
            entry = heap[0]
            if not entry[3]:
                heapq.heappop(heap)
                continue
            if entry[0][0] >= time_limit:
                return None
            heapq.heappop(heap)
            entry[3] = False
            item = entry[2]
            entry[2] = None          # break the cycle (see pop())
            item.pq_entry = None
            self._len -= 1
            return item
        return None


class AsyncPriorityQueue(PriorityQueue[T]):
    """Mutex-protected variant (reference utility/async_priority_queue.c)."""

    __slots__ = ("_lock",)

    def __init__(self):
        super().__init__()
        self._lock = threading.Lock()

    def __len__(self) -> int:
        with self._lock:
            return super().__len__()

    def push(self, item: T, key=None) -> None:
        with self._lock:
            super().push(item, key)

    def remove(self, item: T) -> bool:
        with self._lock:
            return super().remove(item)

    def peek(self) -> Optional[T]:
        with self._lock:
            return super().peek()

    def peek_key(self):
        with self._lock:
            return super().peek_key()

    def pop(self) -> Optional[T]:
        with self._lock:
            return super().pop()

    def pop_before(self, time_limit) -> Optional[T]:
        with self._lock:
            return super().pop_before(time_limit)
