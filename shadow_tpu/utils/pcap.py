"""PCAP capture of simulated traffic (reference utility/pcap_writer.c +
network_interface.c:337-373 hook): standard pcap format with synthetic
Ethernet/IP/UDP/TCP headers so Wireshark opens the files."""

from __future__ import annotations

import os
import struct
from typing import Optional

PCAP_MAGIC = 0xA1B2C3D4
LINKTYPE_ETHERNET = 1


class PcapWriter:
    def __init__(self, path: str):
        self.path = path
        self._f = open(path, "wb")
        # global header: magic, v2.4, tz 0, sigfigs 0, snaplen 65535, ethernet
        self._f.write(struct.pack("<IHHiIII", PCAP_MAGIC, 2, 4, 0, 0, 65535,
                                  LINKTYPE_ETHERNET))

    @classmethod
    def for_host(cls, directory: str, hostname: str) -> "PcapWriter":
        os.makedirs(directory, exist_ok=True)
        return cls(os.path.join(directory, f"{hostname}.pcap"))

    def write_packet(self, sim_time_ns: int, packet) -> None:
        eth = b"\x02" * 6 + b"\x02" * 6 + b"\x08\x00"  # dst, src mac, IPv4
        if packet.is_tcp():
            proto = 6
            l4 = struct.pack(">HHIIBBHHH", packet.src_port & 0xFFFF,
                             packet.dst_port & 0xFFFF,
                             packet.header.sequence & 0xFFFFFFFF,
                             packet.header.acknowledgment & 0xFFFFFFFF,
                             5 << 4, packet.header.flags & 0xFF,
                             packet.header.window & 0xFFFF, 0, 0)
        else:
            proto = 17
            l4 = struct.pack(">HHHH", packet.src_port & 0xFFFF,
                             packet.dst_port & 0xFFFF,
                             (8 + packet.payload_size) & 0xFFFF, 0)
        total_len = 20 + len(l4) + packet.payload_size
        ip = struct.pack(">BBHHHBBHII", 0x45, 0, total_len, packet.uid & 0xFFFF,
                         0, 64, proto, 0, packet.src_ip & 0xFFFFFFFF,
                         packet.dst_ip & 0xFFFFFFFF)
        frame = eth + ip + l4 + packet.payload
        sec, ns = divmod(sim_time_ns, 1_000_000_000)
        self._f.write(struct.pack("<IIII", sec, ns // 1000, len(frame), len(frame)))
        self._f.write(frame)

    def close(self) -> None:
        try:
            self._f.close()
        except Exception:
            pass
