"""CountDownLatch — the round-barrier primitive.

Same semantics as the reference's mutex+condvar latch
(utility/count_down_latch.c:12-17): N parties count down; waiters release
when the count hits zero; the latch is then reset for the next round by the
coordinator.
"""

from __future__ import annotations

import threading


class CountDownLatch:
    def __init__(self, count: int):
        self._initial = count
        self._count = count
        self._generation = 0
        self._cond = threading.Condition()

    def count_down(self) -> None:
        with self._cond:
            self._count -= 1
            if self._count == 0:
                self._cond.notify_all()

    def await_(self) -> None:
        with self._cond:
            gen = self._generation
            while self._count > 0 and self._generation == gen:
                self._cond.wait()

    def count_down_await(self) -> None:
        with self._cond:
            self._count -= 1
            if self._count == 0:
                self._cond.notify_all()
                return
            gen = self._generation
            while self._count > 0 and self._generation == gen:
                self._cond.wait()

    def reset(self) -> None:
        with self._cond:
            self._count = self._initial
            self._generation += 1
