"""Force jax onto the CPU backend, immune to dead accelerator plugins.

An accelerator plugin registered by a sitecustomize at interpreter start
gets INITIALIZED by jax's ``backends()`` even under ``JAX_PLATFORMS=cpu``
(the registration may also override the platform-list config, e.g. to
"axon,cpu"); if the plugin's device tunnel is down, that init hangs
forever.  Callers that are cpu-only BY DESIGN (the test suite, the
multichip dryrun on a virtual mesh, an explicitly cpu-pinned bench) call
:func:`force_cpu_backend` before their first jax use.

Single definition on purpose: the workaround touches a private jax attr
(``_backend_factories``) that can reshape across jax versions — one place
to fix, three call sites (tests/conftest.py, __graft_entry__.py,
bench.py).  Best-effort: failures fall through to jax's normal behavior.
"""

from __future__ import annotations

import os


def force_cpu_backend() -> None:
    os.environ["JAX_PLATFORMS"] = "cpu"
    try:
        import jax
        import jax._src.xla_bridge as xb

        for name in [n for n in getattr(xb, "_backend_factories", {})
                     if n != "cpu"]:
            xb._backend_factories.pop(name, None)
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
