"""Chunked FIFO byte buffer (reference utility/byte_queue.c)."""

from __future__ import annotations

from collections import deque


class ByteQueue:
    def __init__(self):
        self._chunks: deque = deque()
        self._len = 0

    def __len__(self) -> int:
        return self._len

    def push(self, data: bytes) -> None:
        if data:
            self._chunks.append(bytes(data))
            self._len += len(data)

    def pop(self, nbytes: int) -> bytes:
        if nbytes <= 0 or self._len == 0:
            return b""
        out = bytearray()
        while self._chunks and len(out) < nbytes:
            chunk = self._chunks[0]
            take = nbytes - len(out)
            if len(chunk) <= take:
                out += chunk
                self._chunks.popleft()
            else:
                out += chunk[:take]
                self._chunks[0] = chunk[take:]
        self._len -= len(out)
        return bytes(out)

    def peek(self, nbytes: int) -> bytes:
        out = bytearray()
        for chunk in self._chunks:
            if len(out) >= nbytes:
                break
            out += chunk[:nbytes - len(out)]
        return bytes(out)
