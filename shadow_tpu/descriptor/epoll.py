"""Simulated epoll.

Capability of the reference's Epoll (host/descriptor/epoll.c): watches
descriptor status bits via the listener mechanism, maintains a ready set,
and — crucially — is the glue that resumes virtual processes: when a watched
descriptor becomes ready, the owning process gets a ``process_continue``
wakeup (epoll.c drives this in the reference; here the Process registers a
wakeup callback).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from .base import Descriptor, S_CLOSED, S_READABLE, S_WRITABLE

EPOLLIN = 0x001
EPOLLOUT = 0x004
EPOLLERR = 0x008
EPOLLHUP = 0x010
EPOLLET = 1 << 31


class Epoll(Descriptor):
    def __init__(self, host, handle: int):
        super().__init__(host, handle, "epoll")
        self._watches: Dict[int, Tuple[Descriptor, int, object]] = {}  # fd -> (desc, events, data)
        self._ready: Dict[int, int] = {}  # fd -> revents
        # edge-trigger bookkeeping (reference epoll.c EWF_EDGETRIGGER,
        # :275-305): an ET watch reports a condition only when it BECOMES
        # true; collecting it re-arms the edge
        self._prev: Dict[int, int] = {}   # fd -> last observed revents
        self._wakeup_callbacks: List = []

    # -- control -----------------------------------------------------------
    def ctl_add(self, desc: Descriptor, events: int, data=None) -> None:
        if desc.handle in self._watches:
            raise FileExistsError("EEXIST")
        self._watches[desc.handle] = (desc, events, data)
        desc.add_listener(self._on_status)
        self._refresh(desc)

    def ctl_mod(self, desc: Descriptor, events: int, data=None) -> None:
        if desc.handle not in self._watches:
            raise FileNotFoundError("ENOENT")
        self._watches[desc.handle] = (desc, events, data)
        self._refresh(desc)

    def ctl_del(self, desc: Descriptor) -> None:
        if desc.handle not in self._watches:
            raise FileNotFoundError("ENOENT")
        del self._watches[desc.handle]
        desc.remove_listener(self._on_status)
        self._ready.pop(desc.handle, None)
        self._prev.pop(desc.handle, None)
        self._update_own_status()

    # -- status tracking ---------------------------------------------------
    def _revents_for(self, desc: Descriptor, want: int) -> int:
        r = 0
        if (want & EPOLLIN) and desc.has_status(S_READABLE):
            r |= EPOLLIN
        if (want & EPOLLOUT) and desc.has_status(S_WRITABLE):
            r |= EPOLLOUT
        if desc.has_status(S_CLOSED):
            r |= EPOLLHUP
        return r

    def _refresh(self, desc: Descriptor) -> None:
        entry = self._watches.get(desc.handle)
        if entry is None:
            return
        _, want, _ = entry
        r = self._revents_for(desc, want)
        if want & EPOLLET:
            # edge-triggered: only 0->1 transitions become reportable; the
            # pending set accumulates until wait() collects (and re-arms)
            prev = self._prev.get(desc.handle, 0)
            self._prev[desc.handle] = r
            edges = r & ~prev
            if edges:
                newly = desc.handle not in self._ready
                self._ready[desc.handle] = self._ready.get(desc.handle, 0) | edges
                if newly:
                    self._notify_wakeups()
        elif r:
            newly = desc.handle not in self._ready
            self._ready[desc.handle] = r
            if newly:
                self._notify_wakeups()
        else:
            self._ready.pop(desc.handle, None)
        self._update_own_status()

    def _on_status(self, desc: Descriptor, changed_bits: int) -> None:
        self._refresh(desc)

    def _update_own_status(self) -> None:
        # an epoll fd is itself readable when it has ready events (epoll
        # nesting works in the reference too)
        self.adjust_status(S_READABLE, bool(self._ready))

    # -- wakeup integration ------------------------------------------------
    def add_wakeup_callback(self, cb) -> None:
        if cb not in self._wakeup_callbacks:
            self._wakeup_callbacks.append(cb)

    def remove_wakeup_callback(self, cb) -> None:
        if cb in self._wakeup_callbacks:
            self._wakeup_callbacks.remove(cb)

    def _notify_wakeups(self) -> None:
        for cb in list(self._wakeup_callbacks):
            cb()

    # -- wait --------------------------------------------------------------
    def wait(self, max_events: int = 64) -> List[Tuple[object, int]]:
        """Non-blocking collect of (data, revents); blocking semantics are
        provided by the process layer (green thread suspends until the
        wakeup callback fires)."""
        out = []
        for fd, revents in list(self._ready.items())[:max_events]:
            desc, want, data = self._watches[fd]
            out.append((data if data is not None else fd, revents))
            if want & EPOLLET:
                # collected: the edge is consumed until the next transition
                del self._ready[fd]
        if out:
            self._update_own_status()
        return out

    def has_ready(self) -> bool:
        return bool(self._ready)
