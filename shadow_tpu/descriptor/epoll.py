"""Simulated epoll.

Capability of the reference's Epoll (host/descriptor/epoll.c): watches
descriptor status bits via the listener mechanism, maintains a ready set,
and — crucially — is the glue that resumes virtual processes: when a watched
descriptor becomes ready, the owning process gets a ``process_continue``
wakeup (epoll.c drives this in the reference; here the Process registers a
wakeup callback).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from .base import Descriptor, S_CLOSED, S_READABLE, S_WRITABLE

# >>> simgen:begin region=epoll-bits spec=293c930bb679 body=d97e3afb8d41
EPOLLIN = 0x001
EPOLLOUT = 0x004
EPOLLERR = 0x008
EPOLLHUP = 0x010
# <<< simgen:end region=epoll-bits
EPOLLET = 1 << 31


def _revents_from_status(status: int, want: int) -> int:
    """The readiness computation, from an already-read status word — ONE
    status read per refresh (the native-plane mirror of this function is
    dataplane.cc ep_revents; the two are a simtwin epoll-readiness
    surface)."""
    r = 0
    if (want & EPOLLIN) and (status & S_READABLE):
        r |= EPOLLIN
    if (want & EPOLLOUT) and (status & S_WRITABLE):
        r |= EPOLLOUT
    if status & S_CLOSED:
        r |= EPOLLHUP
    return r


class Epoll(Descriptor):
    def __init__(self, host, handle: int):
        super().__init__(host, handle, "epoll")
        self._watches: Dict[int, Tuple[Descriptor, int, object]] = {}  # fd -> (desc, events, data)
        self._ready: Dict[int, int] = {}  # fd -> revents
        # edge-trigger bookkeeping (reference epoll.c EWF_EDGETRIGGER,
        # :275-305): an ET watch reports a condition only when it BECOMES
        # true; collecting it re-arms the edge
        self._prev: Dict[int, int] = {}   # fd -> last observed revents
        self._wakeup_callbacks: List = []

    # -- control -----------------------------------------------------------
    @staticmethod
    def _native_plane_of(desc) -> Optional[object]:
        """The C plane when ``desc`` is a C-plane socket (its status bits
        and this epoll's readiness computation then live natively —
        ISSUE 12 C-side readiness cache), else None."""
        return getattr(desc, "plane", None)

    def ctl_add(self, desc: Descriptor, events: int, data=None) -> None:
        if desc.handle in self._watches:
            raise FileExistsError("EEXIST")
        self._watches[desc.handle] = (desc, events, data)
        plane = self._native_plane_of(desc)
        if plane is not None:
            # the watch registers in C: revents are computed at
            # status-change time natively and delivered (CB_EPOLL) only
            # when the epoll-visible outcome changes — no per-change
            # Python recompute, no listener
            tok = plane.ep_token(self)
            r = plane.c.ep_add(tok, desc.sid, events & 0xFFFFFFFF)
            self._apply_native_revents(desc.handle, r)
            return
        desc.add_listener(self._on_status)
        self._refresh(desc)

    def ctl_mod(self, desc: Descriptor, events: int, data=None) -> None:
        if desc.handle not in self._watches:
            raise FileNotFoundError("ENOENT")
        self._watches[desc.handle] = (desc, events, data)
        plane = self._native_plane_of(desc)
        if plane is not None:
            tok = plane.ep_token(self)
            r = plane.c.ep_mod(tok, desc.sid, events & 0xFFFFFFFF)
            self._apply_native_revents(desc.handle, r)
            return
        self._refresh(desc)

    def ctl_del(self, desc: Descriptor) -> None:
        if desc.handle not in self._watches:
            raise FileNotFoundError("ENOENT")
        del self._watches[desc.handle]
        plane = self._native_plane_of(desc)
        if plane is not None:
            plane.c.ep_del(plane.ep_token(self), desc.sid)
        else:
            desc.remove_listener(self._on_status)
        self._ready.pop(desc.handle, None)
        self._prev.pop(desc.handle, None)
        self._update_own_status()

    # -- status tracking ---------------------------------------------------
    def _revents_for(self, desc: Descriptor, want: int) -> int:
        return _revents_from_status(desc.status, want)

    def _refresh(self, desc: Descriptor) -> None:
        entry = self._watches.get(desc.handle)
        if entry is None:
            return
        _, want, _ = entry
        r = self._revents_for(desc, want)
        if want & EPOLLET:
            # edge-triggered: only 0->1 transitions become reportable; the
            # pending set accumulates until wait() collects (and re-arms)
            prev = self._prev.get(desc.handle, 0)
            self._prev[desc.handle] = r
            edges = r & ~prev
            if edges:
                newly = desc.handle not in self._ready
                self._ready[desc.handle] = self._ready.get(desc.handle, 0) | edges
                if newly:
                    self._notify_wakeups()
        elif r:
            newly = desc.handle not in self._ready
            self._ready[desc.handle] = r
            if newly:
                self._notify_wakeups()
        else:
            self._ready.pop(desc.handle, None)
        self._update_own_status()

    def _on_status(self, desc: Descriptor, changed_bits: int) -> None:
        self._refresh(desc)

    def _apply_native_revents(self, fd: int, r: int) -> None:
        """Apply a C-computed readiness delivery for a native-socket watch:
        the dict bookkeeping of _refresh with the revents already decided
        (LT: the full current set; ET: the fresh edges).  Transition order
        across Python and native watches is preserved naturally — the
        delivery arrives synchronously at the status change, and _ready is
        ONE insertion-ordered dict for both kinds."""
        entry = self._watches.get(fd)
        if entry is None:
            return
        _, want, _ = entry
        if want & EPOLLET:
            if r:
                newly = fd not in self._ready
                self._ready[fd] = self._ready.get(fd, 0) | r
                if newly:
                    self._notify_wakeups()
        elif r:
            newly = fd not in self._ready
            self._ready[fd] = r
            if newly:
                self._notify_wakeups()
        else:
            self._ready.pop(fd, None)
        self._update_own_status()

    def _update_own_status(self) -> None:
        # an epoll fd is itself readable when it has ready events (epoll
        # nesting works in the reference too)
        self.adjust_status(S_READABLE, bool(self._ready))

    # -- wakeup integration ------------------------------------------------
    def add_wakeup_callback(self, cb) -> None:
        if cb not in self._wakeup_callbacks:
            self._wakeup_callbacks.append(cb)

    def remove_wakeup_callback(self, cb) -> None:
        if cb in self._wakeup_callbacks:
            self._wakeup_callbacks.remove(cb)

    def _notify_wakeups(self) -> None:
        for cb in list(self._wakeup_callbacks):
            cb()

    # -- wait --------------------------------------------------------------
    def wait(self, max_events: int = 64) -> List[Tuple[object, int]]:
        """Non-blocking collect of (data, revents); blocking semantics are
        provided by the process layer (green thread suspends until the
        wakeup callback fires).

        Native-socket entries are cross-checked against the LIVE C status
        at collect time: a desynced readiness cache (the poison drill —
        and the failure mode of any future C-side bug) fails loudly here
        instead of handing the app a wake for data that is not there."""
        out = []
        for fd, revents in list(self._ready.items())[:max_events]:
            desc, want, data = self._watches[fd]
            if not (want & EPOLLET) \
                    and self._native_plane_of(desc) is not None:
                live = _revents_from_status(desc.status, want)
                if live != revents:
                    raise RuntimeError(
                        f"epoll readiness cache desync on fd {fd}: C cache "
                        f"delivered revents {revents:#x} but live status "
                        f"computes {live:#x} — refusing to deliver a wrong "
                        "wake")
            out.append((data if data is not None else fd, revents))
            if want & EPOLLET:
                # collected: the edge is consumed until the next transition
                del self._ready[fd]
        if out:
            self._update_own_status()
        return out

    def has_ready(self) -> bool:
        return bool(self._ready)
