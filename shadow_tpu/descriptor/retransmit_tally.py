"""Retransmit tally: sacked/retransmitted/lost sequence-range bookkeeping.

Capability parity with the reference's C++ ``shadow-remora`` library
(host/descriptor/tcp_retransmit_tally.cc/.h): sorted disjoint interval sets
over the TCP sequence space with a dup-ACK-threshold-3 loss rule
(reference header :68).  Two interchangeable backends:

* :class:`NativeTally` — ctypes binding to ``libshadow_tally.so`` built from
  ``native/retransmit_tally.cc`` (``make -C native``), mirroring the
  reference's native implementation choice;
* :class:`PyTally` — pure-Python fallback with identical semantics, used
  when the shared library has not been built.

``make_tally()`` picks the native backend when available.
"""

from __future__ import annotations

import ctypes
import os
from typing import List, Tuple

_LIB = None
_LIB_TRIED = False


def _load_native():
    global _LIB, _LIB_TRIED
    if _LIB_TRIED:
        return _LIB
    _LIB_TRIED = True
    path = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                        "native", "libshadow_tally.so")
    if not os.path.exists(path):
        return None
    try:
        lib = ctypes.CDLL(path)
    except OSError:
        return None
    lib.tally_new.restype = ctypes.c_void_p
    lib.tally_free.argtypes = [ctypes.c_void_p]
    for name in ("tally_mark_sacked", "tally_mark_retransmitted", "tally_mark_lost"):
        getattr(lib, name).argtypes = [ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64]
    lib.tally_advance_una.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.tally_update_lost.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                      ctypes.c_int64, ctypes.c_int]
    lib.tally_lost_count.argtypes = [ctypes.c_void_p]
    lib.tally_lost_count.restype = ctypes.c_int
    lib.tally_get_lost.argtypes = [ctypes.c_void_p,
                                   ctypes.POINTER(ctypes.c_int64), ctypes.c_int]
    lib.tally_get_lost.restype = ctypes.c_int
    lib.tally_clear_lost.argtypes = [ctypes.c_void_p]
    lib.tally_total_sacked.argtypes = [ctypes.c_void_p]
    lib.tally_total_sacked.restype = ctypes.c_int64
    lib.tally_total_lost.argtypes = [ctypes.c_void_p]
    lib.tally_total_lost.restype = ctypes.c_int64
    lib.tally_is_sacked.argtypes = [ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64]
    lib.tally_is_sacked.restype = ctypes.c_int
    lib.tally_highest_sacked.argtypes = [ctypes.c_void_p]
    lib.tally_highest_sacked.restype = ctypes.c_int64
    _LIB = lib
    return lib


Range = Tuple[int, int]


def _insert(ranges: List[Range], b: int, e: int) -> None:
    """Merge [b,e) into a sorted disjoint list in place."""
    if b >= e:
        return
    out: List[Range] = []
    i, n = 0, len(ranges)
    while i < n and ranges[i][1] < b:
        out.append(ranges[i])
        i += 1
    while i < n and ranges[i][0] <= e:
        b = min(b, ranges[i][0])
        e = max(e, ranges[i][1])
        i += 1
    out.append((b, e))
    out.extend(ranges[i:])
    ranges[:] = out


def _subtract(ranges: List[Range], b: int, e: int) -> None:
    if b >= e:
        return
    out: List[Range] = []
    for rb, re_ in ranges:
        if re_ <= b or rb >= e:
            out.append((rb, re_))
            continue
        if rb < b:
            out.append((rb, b))
        if re_ > e:
            out.append((e, re_))
    ranges[:] = out


class PyTally:
    """Pure-Python interval-set tally (semantics == native backend)."""

    def __init__(self):
        self.sacked: List[Range] = []
        self.retransmitted: List[Range] = []
        self.lost: List[Range] = []

    def close(self) -> None:
        pass

    def mark_sacked(self, b: int, e: int) -> None:
        _insert(self.sacked, b, e)
        _subtract(self.lost, b, e)
        _subtract(self.retransmitted, b, e)

    def mark_retransmitted(self, b: int, e: int) -> None:
        _insert(self.retransmitted, b, e)
        _subtract(self.lost, b, e)

    def mark_lost(self, b: int, e: int) -> None:
        _insert(self.lost, b, e)
        _subtract(self.retransmitted, b, e)
        for rb, re_ in self.sacked:
            _subtract(self.lost, rb, re_)

    def advance_una(self, una: int) -> None:
        lo = -(1 << 62)
        _subtract(self.sacked, lo, una)
        _subtract(self.retransmitted, lo, una)
        _subtract(self.lost, lo, una)

    def update_lost(self, una: int, nxt: int, dup_acks: int) -> None:
        """Dup-ACK >= 3: [una, highest_sacked) minus sacked minus
        retransmitted becomes lost (reference tally semantics, threshold
        tcp_retransmit_tally.h:68)."""
        if dup_acks < 3 or not self.sacked:
            return
        hi = self.sacked[-1][1]
        if hi <= una:
            return
        gap: List[Range] = [(una, hi)]
        for rb, re_ in self.sacked:
            _subtract(gap, rb, re_)
        for rb, re_ in self.retransmitted:
            _subtract(gap, rb, re_)
        for rb, re_ in gap:
            _insert(self.lost, rb, re_)

    def lost_ranges(self) -> List[Range]:
        return list(self.lost)

    def clear_lost(self) -> None:
        self.lost = []

    def total_sacked(self) -> int:
        return sum(e - b for b, e in self.sacked)

    def total_lost(self) -> int:
        return sum(e - b for b, e in self.lost)

    def is_sacked(self, b: int, e: int) -> bool:
        return any(rb <= b and e <= re_ for rb, re_ in self.sacked)

    def highest_sacked(self) -> int:
        return self.sacked[-1][1] if self.sacked else -1


class NativeTally:
    """ctypes front-end to native/retransmit_tally.cc."""

    __slots__ = ("_h", "_lib")

    def __init__(self, lib):
        self._lib = lib
        self._h = lib.tally_new()

    def close(self) -> None:
        if self._h:
            self._lib.tally_free(self._h)
            self._h = None

    __del__ = close

    # All entry points are no-ops on a closed handle: teardown can race with
    # late ACK processing in the same event (use-after-free guard).
    def mark_sacked(self, b: int, e: int) -> None:
        if self._h:
            self._lib.tally_mark_sacked(self._h, b, e)

    def mark_retransmitted(self, b: int, e: int) -> None:
        if self._h:
            self._lib.tally_mark_retransmitted(self._h, b, e)

    def mark_lost(self, b: int, e: int) -> None:
        if self._h:
            self._lib.tally_mark_lost(self._h, b, e)

    def advance_una(self, una: int) -> None:
        if self._h:
            self._lib.tally_advance_una(self._h, una)

    def update_lost(self, una: int, nxt: int, dup_acks: int) -> None:
        if self._h:
            self._lib.tally_update_lost(self._h, una, nxt, dup_acks)

    def lost_ranges(self) -> List[Range]:
        if not self._h:
            return []
        n = self._lib.tally_lost_count(self._h)
        if n == 0:
            return []
        buf = (ctypes.c_int64 * (2 * n))()
        got = self._lib.tally_get_lost(self._h, buf, n)
        return [(buf[2 * i], buf[2 * i + 1]) for i in range(got)]

    def clear_lost(self) -> None:
        if self._h:
            self._lib.tally_clear_lost(self._h)

    def total_sacked(self) -> int:
        return self._lib.tally_total_sacked(self._h) if self._h else 0

    def total_lost(self) -> int:
        return self._lib.tally_total_lost(self._h) if self._h else 0

    def is_sacked(self, b: int, e: int) -> bool:
        return bool(self._lib.tally_is_sacked(self._h, b, e)) if self._h else False

    def highest_sacked(self) -> int:
        return self._lib.tally_highest_sacked(self._h) if self._h else -1


def make_tally():
    lib = _load_native()
    if lib is not None:
        return NativeTally(lib)
    return PyTally()


def native_available() -> bool:
    return _load_native() is not None
