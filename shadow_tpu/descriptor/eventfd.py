"""eventfd emulation: a 64-bit counter descriptor.

Reference analog: the reference's interposer forwards eventfd to the
kernel because its plugins share one OS process; in the split-process
design every fd an app can epoll on must be a simulator descriptor, so
eventfd is modeled here with kernel semantics (eventfd(2)):

* the object is a uint64 counter;
* write(8 bytes LE) adds to the counter; blocks (or EAGAIN) if the sum
  would exceed 0xFFFFFFFFFFFFFFFE;
* read(8 bytes) returns-and-resets the counter (or decrements by one in
  EFD_SEMAPHORE mode); blocks (or EAGAIN) at zero;
* readable iff counter > 0; writable iff counter < max.

This is the thread-pool wakeup primitive Tor-class binaries (libevent)
put in their epoll sets — the dual-execution torserver scenario drives it
(tests/native_src/testapp.c).
"""

from __future__ import annotations

from typing import Optional

from .base import Descriptor, S_READABLE, S_WRITABLE

EFD_MAX = 0xFFFFFFFFFFFFFFFE


class EventFD(Descriptor):
    def __init__(self, host, handle: int, initval: int = 0,
                 semaphore: bool = False):
        super().__init__(host, handle, "eventfd")
        self.counter = int(initval) & 0xFFFFFFFFFFFFFFFF
        self.semaphore = semaphore
        self.adjust_status(S_WRITABLE, True)
        if self.counter > 0:
            self.adjust_status(S_READABLE, True)

    def read_value(self) -> Optional[int]:
        """One read(2): the value to return, or None if it would block."""
        if self.counter == 0:
            return None
        val = 1 if self.semaphore else self.counter
        self.counter -= val
        if self.counter == 0:
            self.adjust_status(S_READABLE, False)
        self.adjust_status(S_WRITABLE, True)
        return val

    def write_value(self, val: int):
        """One write(2): True if accepted, False if it would block,
        None for EINVAL (value 0xFFFFFFFFFFFFFFFF is never writable —
        eventfd(2)).  S_WRITABLE stays asserted while counter < max
        (kernel POLLOUT semantics: a write of 1 would succeed) — a
        blocked LARGE write therefore can't wait on the status bit; the
        RPC layer retries it on a virtual-time tick instead."""
        val = int(val)
        if val < 0 or val > EFD_MAX:
            return None
        if self.counter + val > EFD_MAX:
            return False
        if val:
            self.counter += val
            self.adjust_status(S_READABLE, True)
            if self.counter >= EFD_MAX:
                self.adjust_status(S_WRITABLE, False)
        return True
