"""Userspace TCP: full state machine, windows, SACK, retransmits, autotuning.

Capability parity with the reference's tcp.c (2520 LoC; SURVEY.md §2.5):

* connection state machine (tcp.c enum :42-47; CLOSED/LISTEN/SYN_SENT/
  SYN_RECEIVED/ESTABLISHED/FIN_WAIT_*/CLOSING/TIME_WAIT/CLOSE_WAIT/LAST_ACK);
* child/server multiplexing — a LISTEN socket spawns one child socket per
  SYN and queues established children for accept() (tcp.c :91-113);
* sequence/ack windows with peer-advertised flow control and pluggable
  congestion control (tcp_cong.py: reno/aimd/cubic);
* ``_flush``-style send pipeline (tcp.c:1121-1278): retransmit marked-lost
  ranges first, then segmentize buffered user data within
  min(cwnd, peer window), hand packets to the interface qdisc;
* SACK generation from the reorder buffer and SACK processing through the
  retransmit tally (native C++ lib, retransmit_tally.py; reference's
  shadow-remora, dup-ACK threshold 3);
* RTT estimation (RFC 6298 SRTT/RTTVAR via header timestamps, tcp.c:991)
  driving the RTO timer with exponential backoff
  (CONFIG_TCP_RTO_* definitions.h:115-131);
* per-RTT receive/send buffer autotuning toward 2x the measured
  bandwidth-delay product (tcp.c:441-600), clamped to
  CONFIG_TCP_{R,W}MEM_MAX;
* FIN/RST teardown with TIME_WAIT.

Design deltas from the reference (deliberate, simulation-idiomatic):
sequence numbers are unbounded Python ints (no u32 wraparound handling
needed); the initial sequence number is 0 for reproducible traces.

Delayed ACKs follow the reference exactly (tcp.c:2047-2088): a pure ACK in
response to in-order data is coalesced behind a per-socket timer — 1 ms for
the first 1000 "quick ACKs" of a connection, 5 ms after — so all packets
received within the window produce ONE ACK.  DUPACKs and any packet that
already carries an ACK flag (data, FIN) are sent immediately and clear the
pending delayed-ACK counter (tcp.c:1106-1107).
"""

from __future__ import annotations

from collections import deque
from typing import Deque, Dict, List, Optional, Tuple

from ..core import defs, stime
from ..core.task import Task
from ..routing.packet import (TCP_ACK, TCP_FIN, TCP_RST, TCP_SYN, Packet,
                              TCPHeader)
from .base import S_ACTIVE, S_CLOSED, S_READABLE, S_WRITABLE, Socket
from .retransmit_tally import make_tally
from .tcp_cong import make_congestion_control
from ..core.worker import current_worker

# >>> simgen:begin region=tcp-states spec=293c930bb679 body=c91ef6656a5d
# states (reference tcp.c enum TCPState :42-47)
CLOSED = "closed"
LISTEN = "listen"
SYN_SENT = "syn_sent"
SYN_RECEIVED = "syn_received"
ESTABLISHED = "established"
FIN_WAIT_1 = "fin_wait_1"
FIN_WAIT_2 = "fin_wait_2"
CLOSING = "closing"
TIME_WAIT = "time_wait"
CLOSE_WAIT = "close_wait"
LAST_ACK = "last_ack"

# The spec's legal (from, to) transition pairs; "?" = an
# assignment no state guard encloses.
TCP_TRANSITIONS = (
    ("?", "closed"),
    ("?", "established"),
    ("?", "listen"),
    ("?", "syn_received"),
    ("?", "syn_sent"),
    ("?", "time_wait"),
    ("close_wait", "last_ack"),
    ("established", "close_wait"),
    ("established", "fin_wait_1"),
    ("fin_wait_1", "closing"),
    ("fin_wait_1", "fin_wait_2"),
    ("fin_wait_1", "time_wait"),
    ("syn_received", "established"),
    ("syn_received", "fin_wait_1"),
)
# <<< simgen:end region=tcp-states

MSS = defs.CONFIG_TCP_MAX_SEGMENT_SIZE

# >>> simgen:begin region=tcp-timers spec=293c930bb679 body=21bb9e099dc9
RTO_INIT_NS = 1000000000
RTO_MIN_NS = 200000000
RTO_MAX_NS = 120000000000
TIME_WAIT_NS = 60000000000        # 2*MSL teardown hold
MAX_SYN_RETRIES = 6                           # Linux tcp_syn_retries default
MAX_RETRIES = 15                              # Linux tcp_retries2
MAX_SACK_BLOCKS = 4
# <<< simgen:end region=tcp-timers

# >>> simgen:begin region=tcp-logic spec=293c930bb679 body=cc99e04c0aa5
# RTT/RTO update logic, generated from the spec's expression IR
# (SIM206 parses these bodies back and compares them to the spec).

def _g_rto_backoff(rto_ns):
    """exponential backoff on retransmission timeout"""
    return min((rto_ns * 2), 120000000000)


def _g_rto_from_estimate(srtt_ns, rttvar_ns):
    """RTO = clamp(srtt + 4*rttvar) into [RTO_MIN, RTO_MAX]"""
    return max(200000000, min((srtt_ns + (4 * rttvar_ns)), 120000000000))


def _g_rttvar_update(srtt_ns, rttvar_ns, sample_ns):
    """RFC 6298 RTT variance over the PRE-update srtt; |err| spelled max-min so every plane stays in non-negative int64"""
    return ((sample_ns // 2) if (srtt_ns == 0) else (((3 * rttvar_ns) + (max(sample_ns, srtt_ns) - min(sample_ns, srtt_ns))) // 4))


def _g_srtt_update(srtt_ns, sample_ns):
    """RFC 6298 smoothed RTT; first sample seeds the filter"""
    return (sample_ns if (srtt_ns == 0) else (((7 * srtt_ns) + sample_ns) // 8))
# <<< simgen:end region=tcp-logic


class _Segment:
    """One in-flight segment awaiting cumulative ACK."""

    __slots__ = ("seq", "end", "payload", "flags", "send_time_ns", "rtx_count")

    def __init__(self, seq: int, end: int, payload: bytes, flags: int,
                 send_time_ns: int):
        self.seq = seq
        self.end = end                 # seq + len + (1 if SYN or FIN)
        self.payload = payload
        self.flags = flags
        self.send_time_ns = send_time_ns
        self.rtx_count = 0


class TCPSocket(Socket):
    def __init__(self, host, handle: int, recv_buf_size: int,
                 send_buf_size: int, parent: Optional["TCPSocket"] = None):
        super().__init__(host, handle, "tcp", recv_buf_size, send_buf_size)
        self.state = CLOSED
        self.parent = parent
        self.accepted = False  # delivered to the app via accept()
        self.error: Optional[str] = None
        # --- listener side ---
        self.backlog = 0
        self.accept_queue: Deque["TCPSocket"] = deque()
        self.children: Dict[Tuple[int, int], "TCPSocket"] = {}
        # --- sequence space (tcp.c struct :117-243) ---
        self.snd_una = 0          # oldest unacked
        self.snd_nxt = 0          # next seq to send
        self.snd_wnd = MSS        # peer-advertised window
        self.rcv_nxt = 0          # next expected seq
        self.iss = 0
        self.irs = 0
        # --- buffers ---
        self.send_pending: Deque[bytes] = deque()   # user bytes not yet segmentized
        self.send_pending_bytes = 0
        self.unacked: Dict[int, _Segment] = {}      # seq -> segment
        self.reorder: Dict[int, Packet] = {}        # out-of-order arrivals
        self.reorder_bytes = 0
        self.read_queue: Deque[bytes] = deque()     # in-order user bytes
        self.read_bytes = 0
        # --- congestion / loss state ---
        self.cong = None
        self.tally = make_tally()
        self._tally_dirty = False
        self.dup_ack_count = 0
        self.last_ack_rcvd = 0
        # --- RTT / RTO (RFC 6298; tcp.c:991) ---
        self.srtt_ns = 0
        self.rttvar_ns = 0
        self.rto_ns = RTO_INIT_NS
        self.rto_expiry = 0
        self._rto_generation = 0
        self._rto_scheduled = False
        # --- teardown ---
        self.fin_pending = False       # close() requested; FIN not yet sent
        self.fin_seq: Optional[int] = None
        self.eof_received = False      # peer FIN consumed by reader
        self.fin_acked = False
        self.app_closed = False
        self.write_shutdown = False    # shutdown(SHUT_WR) called
        self._persist_scheduled = False
        # --- delayed ACK (tcp.c:2047-2088) ---
        self._delack_scheduled = False
        self._delack_counter = 0
        self._quick_acks = 0
        # --- autotuning (tcp.c:441-600) ---
        self.autotune_recv = host.params.autotune_recv
        self.autotune_send = host.params.autotune_send
        self._rtt_bytes_in = 0
        self._rtt_window_start = 0
        # last advertised window; 0->+ transitions trigger a window update
        self._last_adv_window = recv_buf_size

    # ------------------------------------------------------------------
    # helpers
    # ------------------------------------------------------------------
    def _now(self) -> int:
        # the executing worker mirrors the clock onto the host (event.py);
        # one attribute read instead of a thread-local lookup
        return self.host.now

    def _engine_options(self):
        eng = self.host.engine
        return getattr(eng, "options", None)

    def _make_cong(self):
        from .tcp_cong import INIT_CWND_SEGMENTS
        opts = self._engine_options()
        kind = getattr(opts, "tcp_congestion_control", "reno") if opts else "reno"
        # per-host override (<host tcpcc="...">) beats the engine-wide flag
        host_kind = getattr(getattr(self.host, "params", None),
                            "tcp_cc", None)
        if host_kind:
            kind = host_kind
        ssthresh = getattr(opts, "tcp_ssthresh", 0) if opts else 0
        init_segments = getattr(opts, "tcp_windows", INIT_CWND_SEGMENTS) \
            if opts else INIT_CWND_SEGMENTS
        # --tcp-windows also seeds the pre-handshake peer-window assumption
        # (the real value arrives with the first packet's window field)
        self.snd_wnd = max(1, init_segments) * MSS
        return make_congestion_control(kind, MSS, ssthresh, init_segments)

    def _iface(self):
        return self.host.interface_for_ip(self.bound_ip)

    def _adv_window(self) -> int:
        used = self.read_bytes + self.reorder_bytes
        return max(0, self.recv_buf_size - used)

    def _send_capacity(self) -> int:
        """Sender limit: min(cwnd, peer window) minus bytes in flight."""
        flight = self.snd_nxt - self.snd_una
        cwnd = self.cong.cwnd if self.cong is not None else MSS
        return max(0, min(cwnd, max(self.snd_wnd, 0)) - flight)

    # ------------------------------------------------------------------
    # packet construction / emission
    # ------------------------------------------------------------------
    def _emit(self, flags: int, seq: int, payload: bytes = b"",
              echo_ts: Optional[int] = None, track: bool = True,
              notify: bool = True) -> None:
        """Create one packet and hand it to the interface qdisc.

        ``notify=False`` defers the interface kick — the segmentizing loop
        in :meth:`_flush` emits many packets and notifies ONCE, so the
        interface drain doesn't re-run per segment."""
        now = self.host.now
        adv_window = self._adv_window()
        header = TCPHeader(self.bound_ip, self.bound_port,
                           self.peer_ip, self.peer_port,
                           flags, seq,
                           self.rcv_nxt if flags & TCP_ACK else 0,
                           adv_window,
                           self._sack_blocks() if (self.reorder
                                                   and flags & TCP_ACK) else None,
                           now,
                           echo_ts if echo_ts is not None else 0)
        pkt = Packet.new_tcp(self.host.next_packet_uid(),
                             self.host.next_packet_priority(), header, payload)
        if flags & TCP_ACK:
            # this packet carries a current ACK; any pending delayed ACK is
            # now redundant (tcp.c:1106-1107)
            self._delack_counter = 0
        consumes = len(payload) + (1 if flags & (TCP_SYN | TCP_FIN) else 0)
        if track and consumes:
            seg = _Segment(seq, seq + consumes, payload, flags, now)
            self.unacked[seq] = seg
            self._arm_rto()
        self._last_adv_window = header.window
        self.out_packets.append(pkt)
        self.out_bytes += pkt.total_size
        pkt.add_status("SND_SOCKET_BUFFERED")
        if notify:
            iface = self._iface()
            if iface is not None:
                iface.wants_send(self)

    def _sack_blocks(self) -> List[Tuple[int, int]]:
        """Contiguous runs in the reorder buffer, newest-first capped at 4
        (SACK generation; reference builds these from its unordered input)."""
        if not self.reorder:
            return []
        seqs = sorted(self.reorder)
        blocks: List[Tuple[int, int]] = []
        start = prev_end = None
        for s in seqs:
            p = self.reorder[s]
            e = s + p.payload_size
            if start is None:
                start, prev_end = s, e
            elif s <= prev_end:
                prev_end = max(prev_end, e)
            else:
                blocks.append((start, prev_end))
                start, prev_end = s, e
        blocks.append((start, prev_end))
        return blocks[-MAX_SACK_BLOCKS:]

    def _send_ack(self, echo_ts: Optional[int] = None) -> None:
        self._emit(TCP_ACK, self.snd_nxt, b"", echo_ts=echo_ts, track=False)

    def _schedule_delayed_ack(self) -> None:
        """Coalesce pure ACKs for in-order data behind a short timer
        (tcp.c:2066-2091): quick ACKs (1 ms) early in the connection to keep
        the peer's send rate growing, 5 ms after.  One timer per socket; the
        counter is cleared whenever any ACK-carrying packet goes out."""
        self._delack_counter += 1
        if self._delack_scheduled:
            return
        w = current_worker()
        if w is None:
            self._delack_counter = 0
            self._send_ack()
            return
        if self._quick_acks < 1000:
            self._quick_acks += 1
            delay = stime.SIM_TIME_MS
        else:
            delay = 5 * stime.SIM_TIME_MS
        self._delack_scheduled = True
        if w.schedule_task(Task(_delayed_ack_task, self, None,
                                name="tcp_delack"),
                           delay, dst_host=self.host) is None:
            # scheduling declined (engine stopping / past end time): leave
            # the timer unarmed so a later segment can try again
            self._delack_scheduled = False

    def _on_delayed_ack_fire(self) -> None:
        self._delack_scheduled = False
        if self._delack_counter > 0 and not self.closed \
                and self.state != CLOSED:
            self._send_ack()   # _emit clears the counter

    # ------------------------------------------------------------------
    # user API: connect / listen / accept
    # ------------------------------------------------------------------
    def connect_to(self, dst_ip: int, dst_port: int) -> bool:
        """Begin the three-way handshake; returns False (in progress).
        The caller blocks on WRITABLE (set at ESTABLISHED)."""
        if self.state != CLOSED:
            raise OSError("EISCONN")
        if not self.is_bound:
            self.host.autobind_socket(self, dst_ip)
        self.peer_ip, self.peer_port = dst_ip, dst_port
        iface = self._iface()
        if iface is not None:
            # narrow the wildcard binding to the 4-tuple for reply routing
            iface.disassociate("tcp", self.bound_port)
            iface.associate(self, "tcp", self.bound_port, dst_ip, dst_port)
        self.cong = self._make_cong()
        self.iss = 0
        self.snd_una = self.snd_nxt = self.iss
        self.state = SYN_SENT
        self._emit(TCP_SYN, self.snd_nxt)
        self.snd_nxt += 1
        return False

    def take_socket_error(self) -> Optional[str]:
        err, self.error = self.error, None
        return err

    def listen(self, backlog: int = 128) -> None:
        if self.state not in (CLOSED, LISTEN):
            raise OSError("EINVAL")
        if not self.is_bound:
            self.host.autobind_socket(self, 0)
        self.state = LISTEN
        self.backlog = backlog

    def accept_child(self) -> Optional["TCPSocket"]:
        if self.accept_queue:
            child = self.accept_queue.popleft()
            child.accepted = True
            self.adjust_status(S_READABLE, bool(self.accept_queue))
            return child
        return None

    # ------------------------------------------------------------------
    # user API: send / receive
    # ------------------------------------------------------------------
    def send_user_data(self, data: bytes, dst_ip: int = 0, dst_port: int = 0) -> int:
        if self.write_shutdown:
            # POSIX: writing after SHUT_WR is EPIPE, not ENOTCONN
            raise OSError("EPIPE")
        if self.state not in (ESTABLISHED, CLOSE_WAIT):
            raise OSError("ENOTCONN" if self.error is None else self.error)
        space = self.send_buf_size - self.send_pending_bytes \
            - (self.snd_nxt - self.snd_una)
        n = min(len(data), max(0, space))
        if n == 0:
            self._update_writable()
            return 0
        self.send_pending.append(bytes(data[:n]))
        self.send_pending_bytes += n
        self._flush()
        self._update_writable()
        return n

    def peek_user_data(self, nbytes: int):
        """MSG_PEEK: copy up to nbytes of in-order data without consuming
        (reference socket buffers support peeking the same way; real HTTP
        clients like wget peek the response head before reading it)."""
        if not self.read_queue:
            if self.eof_received or self.error is not None:
                return b"", self.peer_ip or 0, self.peer_port or 0
            return None
        out = bytearray()
        for chunk in self.read_queue:
            take = nbytes - len(out)
            if take <= 0:
                break
            out.extend(chunk[:take])
        return bytes(out), self.peer_ip or 0, self.peer_port or 0

    def receive_user_data(self, nbytes: int):
        if not self.read_queue:
            if self.eof_received or self.error is not None:
                return b"", self.peer_ip or 0, self.peer_port or 0
            return None
        out = bytearray()
        while self.read_queue and len(out) < nbytes:
            chunk = self.read_queue[0]
            take = nbytes - len(out)
            if len(chunk) <= take:
                out.extend(chunk)
                self.read_queue.popleft()
            else:
                out.extend(chunk[:take])
                self.read_queue[0] = chunk[take:]
        self.read_bytes -= len(out)
        self._update_readable()
        # reopened receive window after a zero-window advertisement?
        if self._last_adv_window == 0 and self._adv_window() > 0 \
                and self.state in (ESTABLISHED, FIN_WAIT_1, FIN_WAIT_2):
            self._send_ack()
        return bytes(out), self.peer_ip or 0, self.peer_port or 0

    # ------------------------------------------------------------------
    # the send pipeline (tcp.c _tcp_flush :1121-1278)
    # ------------------------------------------------------------------
    def _flush(self) -> None:
        if self.state == CLOSED:
            return
        # 1. retransmit ranges the tally marked lost.  The dirty flag is set
        # by the two loss-marking paths (dup-ACK tally update, fast
        # retransmit) so the vast majority of flushes skip the native-lib
        # range query entirely.
        if self._tally_dirty:
            self._tally_dirty = False
            lost = self.tally.lost_ranges()
            if lost:
                self.tally.clear_lost()
                for b, e in lost:
                    self._retransmit_range(b, e)
        # 2. new data within min(cwnd, peer window).  The send buffer is a
        # byte STREAM: small app writes coalesce into full-MSS segments,
        # exactly like the reference segmentizing its buffered user bytes
        # (tcp.c:1121-1278) — a 512 B-per-write app still fills 1460 B
        # packets here.
        pending = self.send_pending
        emitted = False
        while pending:
            n = min(MSS, self._send_capacity())
            if n == 0:
                break
            chunk = pending[0]
            clen = len(chunk)
            if clen == n:
                payload = chunk
                pending.popleft()
            elif clen > n:
                payload = chunk[:n]
                pending[0] = chunk[n:]
            else:
                # gather several queued writes into one segment
                parts = [chunk]
                pending.popleft()
                size = clen
                while pending and size < n:
                    chunk = pending[0]
                    take = n - size
                    if len(chunk) <= take:
                        parts.append(chunk)
                        pending.popleft()
                        size += len(chunk)
                    else:
                        parts.append(chunk[:take])
                        pending[0] = chunk[take:]
                        size += take
                payload = b"".join(parts)
                n = size
            self.send_pending_bytes -= n
            self._emit(TCP_ACK, self.snd_nxt, payload, notify=False)
            self.snd_nxt += n
            emitted = True
        # 3. FIN once all data is out
        if self.fin_pending and not self.send_pending \
                and self.fin_seq is None:
            self.fin_seq = self.snd_nxt
            self._emit(TCP_FIN | TCP_ACK, self.snd_nxt, notify=False)
            self.snd_nxt += 1
            self.fin_pending = False
            emitted = True
        if emitted:
            iface = self._iface()
            if iface is not None:
                iface.wants_send(self)
        # 4. zero-window persist: if the peer closed its window and nothing
        # is in flight (so no RTO is running), probe so a lost window-update
        # ACK cannot deadlock the connection
        if self.send_pending and self.snd_wnd <= 0 and not self.unacked:
            self._schedule_persist()

    def _retransmit_range(self, b: int, e: int) -> None:
        for seq in sorted(self.unacked):
            seg = self.unacked[seq]
            if seg.end <= b or seg.seq >= e:
                continue
            self._retransmit_segment(seg)

    def _retransmit_segment(self, seg: _Segment) -> None:
        seg.rtx_count += 1
        seg.send_time_ns = self._now()
        self.tally.mark_retransmitted(seg.seq, seg.end)
        # a client retransmitting its SYN has nothing to ack yet
        flags = seg.flags if self.state == SYN_SENT else seg.flags | TCP_ACK
        header = TCPHeader(self.bound_ip, self.bound_port,
                           self.peer_ip, self.peer_port,
                           flags=flags, sequence=seg.seq,
                           acknowledgment=self.rcv_nxt,
                           window=self._adv_window(),
                           sel_acks=self._sack_blocks(),
                           timestamp=seg.send_time_ns, timestamp_echo=0)
        # fresh uid: the drop draw for a retransmission is independent
        # (reference redraws rand on every worker_sendPacket)
        pkt = Packet.new_tcp(self.host.next_packet_uid(),
                             self.host.next_packet_priority(), header,
                             seg.payload)
        pkt.add_status("SND_TCP_ENQUEUE_RETRANSMIT")
        self.out_packets.append(pkt)
        self.out_bytes += pkt.total_size
        iface = self._iface()
        if iface is not None:
            iface.wants_send(self)

    # ------------------------------------------------------------------
    # RTO timer (tcp.c retransmit timer tasks :923-1026)
    # ------------------------------------------------------------------
    def _arm_rto(self) -> None:
        now = self._now()
        self.rto_expiry = now + self.rto_ns
        if self._rto_scheduled:
            return
        w = current_worker()
        if w is None:
            return
        self._rto_scheduled = True
        gen = self._rto_generation
        w.schedule_task(Task(_rto_fire_task, self, gen, name="tcp_rto"),
                        self.rto_ns, dst_host=self.host)

    def _cancel_rto(self) -> None:
        self._rto_generation += 1
        self._rto_scheduled = False

    def _on_rto_fire(self, generation: int) -> None:
        # a stale generation must not clear the flag: a live task for the
        # current generation may still be pending, and clearing here would
        # let _arm_rto schedule a duplicate
        if generation != self._rto_generation or self.closed:
            return
        self._rto_scheduled = False
        now = self._now()
        if not self.unacked:
            return
        if now < self.rto_expiry:
            # a newer ACK pushed the deadline; re-sleep the difference
            w = current_worker()
            if w is not None:
                self._rto_scheduled = True
                w.schedule_task(Task(_rto_fire_task, self,
                                     self._rto_generation, name="tcp_rto"),
                                self.rto_expiry - now, dst_host=self.host)
            return
        # timeout: back off, collapse window, retransmit the oldest segment
        first_seq = min(self.unacked)
        seg = self.unacked[first_seq]
        if self.state == SYN_SENT and seg.rtx_count >= MAX_SYN_RETRIES:
            self._fail_connection("ETIMEDOUT")
            return
        if seg.rtx_count >= MAX_RETRIES:
            self._fail_connection("ETIMEDOUT")
            return
        if self.cong is not None:
            self.cong.on_timeout()
        self.dup_ack_count = 0
        self.rto_ns = _g_rto_backoff(self.rto_ns)
        self._retransmit_segment(seg)
        self._arm_rto()

    def _schedule_persist(self) -> None:
        if self._persist_scheduled:
            return
        w = current_worker()
        if w is None:
            return
        self._persist_scheduled = True
        w.schedule_task(Task(_persist_fire_task, self, None,
                             name="tcp_persist"),
                        max(self.rto_ns, RTO_MIN_NS), dst_host=self.host)

    def _on_persist_fire(self) -> None:
        self._persist_scheduled = False
        if self.closed or self.state not in (ESTABLISHED, CLOSE_WAIT,
                                             FIN_WAIT_1):
            return
        if not self.send_pending or self.snd_wnd > 0 or self.unacked:
            self._flush()
            return
        # window probe: force out 1 byte of pending data as a real segment
        chunk = self.send_pending[0]
        if len(chunk) == 1:
            self.send_pending.popleft()
        else:
            self.send_pending[0] = chunk[1:]
        self.send_pending_bytes -= 1
        self._emit(TCP_ACK, self.snd_nxt, bytes(chunk[:1]))
        self.snd_nxt += 1
        self._schedule_persist()

    def _fail_connection(self, err: str) -> None:
        self.error = err
        self._cancel_rto()
        self.eof_received = True
        if self.parent is not None and not self.accepted:
            # embryonic/queued child: no app holds it, so nobody will ever
            # close() it — release the descriptor, the 4-tuple binding and
            # the parent link now, else new SYNs from the same client port
            # route to this dead child forever
            self._teardown()
        else:
            self.state = CLOSED
            self.release_bindings()
        self.adjust_status(S_READABLE | S_WRITABLE, True)  # wake blockers

    # ------------------------------------------------------------------
    # RTT estimation (RFC 6298; tcp.c:991)
    # ------------------------------------------------------------------
    def _rtt_sample(self, sample_ns: int) -> None:
        if sample_ns <= 0:
            return
        # rttvar first: it reads the PRE-update srtt (RFC 6298 order)
        self.rttvar_ns = _g_rttvar_update(self.srtt_ns, self.rttvar_ns,
                                          sample_ns)
        self.srtt_ns = _g_srtt_update(self.srtt_ns, sample_ns)
        self.rto_ns = _g_rto_from_estimate(self.srtt_ns, self.rttvar_ns)
        self._autotune(sample_ns)

    def _recv_autotune(self) -> None:
        """Receiver-side buffer autotuning, ticked from the RECEIVE path
        (the reference tunes its receive buffer while data arrives,
        tcp.c:441-521): once per RTT-ish window, grow toward 2x the bytes
        received in that window.  A pure receiver never processes ACKs, so
        the sender-path hook alone would never fire for it."""
        if not self.autotune_recv:
            return
        now = self.host.now
        if self._rtt_window_start == 0:
            self._rtt_window_start = now
            return
        rtt = self.srtt_ns or (200 * stime.SIM_TIME_MS)
        if now - self._rtt_window_start < rtt:
            return
        target = 2 * self._rtt_bytes_in
        if target > self.recv_buf_size:
            self.recv_buf_size = min(target, defs.CONFIG_TCP_RMEM_MAX)
        self._rtt_bytes_in = 0
        self._rtt_window_start = now

    def _autotune(self, rtt_ns: int) -> None:
        """Grow buffers toward 2x the measured bandwidth-delay product
        (reference per-RTT autotuning, tcp.c:441-600)."""
        now = self._now()
        if self._rtt_window_start == 0:
            self._rtt_window_start = now
            return
        elapsed = now - self._rtt_window_start
        if elapsed < rtt_ns:
            return
        if self.autotune_recv and self._rtt_bytes_in > 0:
            target = 2 * self._rtt_bytes_in
            if target > self.recv_buf_size:
                self.recv_buf_size = min(target, defs.CONFIG_TCP_RMEM_MAX)
        if self.autotune_send and self.cong is not None:
            target = 2 * self.cong.cwnd
            if target > self.send_buf_size:
                self.send_buf_size = min(target, defs.CONFIG_TCP_WMEM_MAX)
        self._rtt_bytes_in = 0
        self._rtt_window_start = now

    # ------------------------------------------------------------------
    # inbound packet processing (tcp.c tcp_processPacket :1777-2099)
    # ------------------------------------------------------------------
    def push_in_packet(self, packet: Packet) -> None:
        flags = packet.header.flags
        if self.state == LISTEN:
            self._listen_process(packet)
            return
        if flags & TCP_RST:
            self._process_rst(packet)
            return
        if self.state == SYN_SENT:
            self._syn_sent_process(packet)
            return
        if flags & TCP_SYN:
            # duplicate SYN (our SYN+ACK or its ACK was lost): re-ACK
            self._send_ack(echo_ts=packet.header.timestamp)
            return
        if flags & TCP_ACK:
            self._ack_processing(packet)
        if packet.payload_size > 0 or flags & TCP_FIN:
            self._data_processing(packet)
        packet.add_status("RCV_SOCKET_PROCESSED")

    # -- LISTEN: spawn children (tcp.c child/server mux :91-113) ----------
    def _listen_process(self, packet: Packet) -> None:
        flags = packet.header.flags
        key = (packet.src_ip, packet.src_port)
        child = self.children.get(key)
        if child is not None:
            child.push_in_packet(packet)
            return
        if not flags & TCP_SYN:
            return  # stray non-SYN to listener: ignore
        # backlog counts connections not yet handed to accept()
        pending = len(self.accept_queue) + sum(
            1 for c in self.children.values() if c.state == SYN_RECEIVED)
        if pending >= max(self.backlog, 1):
            return  # backlog full: drop; client will retransmit SYN
        host = self.host
        handle = host.allocate_handle()
        child = TCPSocket(host, handle, host.params.recv_buf_size,
                          host.params.send_buf_size, parent=self)
        host.register_descriptor(child)
        # reply with the address the SYN actually arrived on (matters for a
        # wildcard-bound listener reachable on loopback and eth)
        child.bind_to(packet.dst_ip, self.bound_port)
        child.peer_ip, child.peer_port = key
        child.cong = child._make_cong()
        self.children[key] = child
        iface = host.interface_for_ip(packet.dst_ip) or self._iface()
        if iface is not None:
            iface.associate(child, "tcp", child.bound_port,
                            packet.src_ip, packet.src_port)
        # receive SYN
        child.irs = packet.header.sequence
        child.rcv_nxt = packet.header.sequence + 1
        child.snd_wnd = packet.header.window or MSS
        child.state = SYN_RECEIVED
        child.iss = 0
        child.snd_una = child.snd_nxt = child.iss
        child._emit(TCP_SYN | TCP_ACK, child.snd_nxt,
                    echo_ts=packet.header.timestamp)
        child.snd_nxt += 1

    def _child_established(self, child: "TCPSocket") -> None:
        self.accept_queue.append(child)
        self.adjust_status(S_READABLE, True)

    def _detach_child(self, child: "TCPSocket") -> None:
        self.children.pop((child.peer_ip, child.peer_port), None)
        if child in self.accept_queue:
            self.accept_queue.remove(child)
            self._update_readable()

    # -- SYN_SENT ---------------------------------------------------------
    def _syn_sent_process(self, packet: Packet) -> None:
        flags = packet.header.flags
        if not (flags & TCP_SYN and flags & TCP_ACK):
            return
        if packet.header.acknowledgment != self.snd_nxt:
            return
        self.irs = packet.header.sequence
        self.rcv_nxt = packet.header.sequence + 1
        self.snd_una = packet.header.acknowledgment
        self.snd_wnd = packet.header.window or MSS
        self.unacked.pop(self.iss, None)
        self._cancel_rto()
        self._rtt_sample(self._now() - packet.header.timestamp_echo
                         if packet.header.timestamp_echo else 0)
        self.state = ESTABLISHED
        self._send_ack(echo_ts=packet.header.timestamp)
        self._update_writable()

    # -- RST --------------------------------------------------------------
    def _process_rst(self, packet: Packet) -> None:
        err = "ECONNREFUSED" if self.state == SYN_SENT else "ECONNRESET"
        if self.parent is not None:
            self.parent._detach_child(self)
        self._fail_connection(err)

    # -- ACK processing (tcp.c _tcp_ackProcessing :1662) ------------------
    def _ack_processing(self, packet: Packet) -> None:
        h = packet.header
        ack = h.acknowledgment
        self.snd_wnd = h.window
        now = self._now()
        # SACK blocks into the tally
        for b, e in h.sel_acks:
            if e > self.snd_una:
                self.tally.mark_sacked(max(b, self.snd_una), e)
        if ack > self.snd_una:
            acked_bytes = ack - self.snd_una
            self.snd_una = ack
            self.dup_ack_count = 0
            self.tally.advance_una(ack)
            # drop fully-acked segments; RTT from the newest acked segment
            newest_ts = 0
            for seq in [s for s in self.unacked if self.unacked[s].end <= ack]:
                seg = self.unacked.pop(seq)
                if seg.rtx_count == 0:
                    newest_ts = max(newest_ts, seg.send_time_ns)
            if h.timestamp_echo:
                self._rtt_sample(now - h.timestamp_echo)
            elif newest_ts:
                self._rtt_sample(now - newest_ts)
            if self.cong is not None:
                self.cong.on_new_ack(acked_bytes, self.snd_una, now)
            if self.unacked:
                self.rto_expiry = now + self.rto_ns
                self._arm_rto()
            else:
                self._cancel_rto()
            self._on_snd_una_advanced(ack)
        elif ack == self.snd_una and self.snd_nxt > self.snd_una \
                and packet.payload_size == 0 \
                and not (h.flags & (TCP_SYN | TCP_FIN)):
            # pure duplicate ACK
            self.dup_ack_count += 1
            self.tally.update_lost(self.snd_una, self.snd_nxt,
                                   self.dup_ack_count)
            self._tally_dirty = True
            if self.cong is not None \
                    and self.cong.on_duplicate_ack(self.dup_ack_count,
                                                   self.snd_nxt):
                # fast retransmit: without SACK info, the una segment is lost
                if not self.tally.lost_ranges() and self.snd_una in self.unacked:
                    self.tally.mark_lost(self.snd_una,
                                         self.unacked[self.snd_una].end)
        self._flush()
        self._update_writable()

    def _on_snd_una_advanced(self, ack: int) -> None:
        """Handshake/teardown transitions driven by our bytes being acked."""
        if self.state == SYN_RECEIVED and ack >= self.iss + 1:
            self.state = ESTABLISHED
            self._update_writable()
            if self.parent is not None:
                self.parent._child_established(self)
        if self.fin_seq is not None and ack >= self.fin_seq + 1:
            self.fin_acked = True
            if self.state == FIN_WAIT_1:
                self.state = FIN_WAIT_2
            elif self.state == CLOSING:
                self._enter_time_wait()
            elif self.state == LAST_ACK:
                self._teardown()

    # -- data + FIN (tcp.c _tcp_dataProcessing :1597) ---------------------
    def _data_processing(self, packet: Packet) -> None:
        h = packet.header
        seq = h.sequence
        size = packet.payload_size
        end = seq + size
        if size > 0:
            if end <= self.rcv_nxt:
                # full duplicate: re-ACK so the sender's tally advances
                self._send_ack(echo_ts=h.timestamp)
                return
            if seq > self.rcv_nxt:
                # out of order: hold in reorder buffer if window allows
                if self.reorder_bytes + size <= self.recv_buf_size \
                        and seq not in self.reorder:
                    self.reorder[seq] = packet
                    self.reorder_bytes += size
                    packet.add_status("RCV_SOCKET_BUFFERED")
                else:
                    self.drop_packet(packet)
                self._send_ack(echo_ts=h.timestamp)  # dup ACK w/ SACK blocks
                return
            # in order (possibly partially duplicate)
            payload = packet.payload[self.rcv_nxt - seq:]
            self._append_read(payload)
            self.rcv_nxt = end
            self._drain_reorder()
        fin = bool(h.flags & TCP_FIN)
        if fin:
            fin_seq = seq + size
            if fin_seq == self.rcv_nxt:
                self.rcv_nxt = fin_seq + 1
                self._on_fin_received()
        if fin:
            # FIN ACKs go out now so the close sequence completes promptly
            # (the reference always sends FIN-related control immediately)
            self._send_ack(echo_ts=h.timestamp)
        else:
            # in-order new data: the pure ACK can be delayed (tcp.c:2047-2051)
            self._schedule_delayed_ack()
        if size > 0:
            self._rtt_bytes_in += size
            self._recv_autotune()
            self._update_readable()

    def _append_read(self, data: bytes) -> None:
        if not data:
            return
        self.read_queue.append(data)
        self.read_bytes += len(data)

    def _drain_reorder(self) -> None:
        while self.rcv_nxt in self.reorder:
            p = self.reorder.pop(self.rcv_nxt)
            self.reorder_bytes -= p.payload_size
            self._append_read(p.payload)
            self.rcv_nxt += p.payload_size
            if p.header.flags & TCP_FIN:
                self.rcv_nxt += 1
                self._on_fin_received()

    def _on_fin_received(self) -> None:
        self.eof_received = True
        if self.state == ESTABLISHED:
            self.state = CLOSE_WAIT
        elif self.state == FIN_WAIT_1:
            self.state = CLOSING if not self.fin_acked else TIME_WAIT
            if self.state == TIME_WAIT:
                self._enter_time_wait()
        elif self.state == FIN_WAIT_2:
            self._enter_time_wait()
        self.adjust_status(S_READABLE, True)  # EOF is readable

    # ------------------------------------------------------------------
    # teardown
    # ------------------------------------------------------------------
    def shutdown(self, how: int) -> None:
        """shutdown(2): 0=SHUT_RD, 1=SHUT_WR, 2=SHUT_RDWR.

        SHUT_WR sends FIN after pending data but the app keeps receiving
        (the classic half-close: 'I'm done sending, finish your reply');
        SHUT_RD discards buffered input and makes further reads return EOF.
        The descriptor stays open either way — close() still owns teardown.
        """
        if how not in (0, 1, 2):
            raise OSError("EINVAL")
        if self.state in (CLOSED, LISTEN, SYN_SENT):
            raise OSError("ENOTCONN")
        if how in (1, 2) and not self.fin_pending and self.fin_seq is None:
            if self.state in (ESTABLISHED, SYN_RECEIVED):
                self.state = FIN_WAIT_1
                self.fin_pending = True
                self._flush()
            elif self.state == CLOSE_WAIT:
                self.state = LAST_ACK
                self.fin_pending = True
                self._flush()
            self.write_shutdown = True
            self.adjust_status(S_WRITABLE, False)
        if how in (0, 2):
            self.read_queue.clear()
            self.read_bytes = 0
            self.eof_received = True
            self._update_readable()

    def close(self) -> None:
        """Application close: send FIN after pending data (half-close of
        our direction), keep the machinery alive until teardown."""
        if self.app_closed:
            return
        self.app_closed = True
        if self.state == LISTEN or (self.state == CLOSED and self.error is None
                                    and self.cong is None):
            self._teardown()
            return
        if self.state in (CLOSED, TIME_WAIT):
            self._teardown()
            return
        if self.state in (ESTABLISHED, SYN_RECEIVED):
            self.state = FIN_WAIT_1
            self.fin_pending = True
            self._flush()
        elif self.state == CLOSE_WAIT:
            self.state = LAST_ACK
            self.fin_pending = True
            self._flush()
        elif self.state == SYN_SENT:
            self._fail_connection("ECONNABORTED")
            self._teardown()

    def _enter_time_wait(self) -> None:
        self.state = TIME_WAIT
        self._cancel_rto()
        w = current_worker()
        if w is not None:
            w.schedule_task(Task(_time_wait_task, self, None,
                                 name="tcp_time_wait"),
                            TIME_WAIT_NS, dst_host=self.host)
        else:
            self._teardown()

    def _teardown(self) -> None:
        """Final resource release (descriptor close + binding removal)."""
        self.state = CLOSED
        self._cancel_rto()
        # a closing listener resets every connection the app has not
        # accepted: they would otherwise complete handshakes into a dead
        # accept queue and leak (tcp.c resets pending children on server
        # close)
        for child in list(self.children.values()):
            child.parent = None
            if not child.accepted and not child.closed:
                if child.state not in (CLOSED, LISTEN):
                    child._emit(TCP_RST | TCP_ACK, child.snd_nxt)
                child._teardown()
        self.children.clear()
        self.accept_queue.clear()
        if self.parent is not None:
            self.parent._detach_child(self)
        self.tally.close()
        if not self.closed:
            # Socket.close drops every interface binding this socket holds
            super().close()

    # ------------------------------------------------------------------
    # status upkeep
    # ------------------------------------------------------------------
    def _update_readable(self) -> None:
        readable = bool(self.read_queue) or self.eof_received \
            or bool(self.accept_queue)
        if bool(self.status & S_READABLE) != readable:
            self.adjust_status(S_READABLE, readable)

    def _update_writable(self) -> None:
        if self.state not in (ESTABLISHED, CLOSE_WAIT):
            if self.error is not None:
                self.adjust_status(S_WRITABLE, True)
            return
        space = self.send_buf_size - self.send_pending_bytes \
            - (self.snd_nxt - self.snd_una)
        writable = space > 0
        if bool(self.status & S_WRITABLE) != writable:
            self.adjust_status(S_WRITABLE, writable)

    def pull_out_packet(self):
        p = super().pull_out_packet()
        self._update_writable()
        return p


def _rto_fire_task(sock: TCPSocket, generation: int) -> None:
    sock._on_rto_fire(generation)


def _persist_fire_task(sock: TCPSocket, _arg) -> None:
    sock._on_persist_fire()


def _delayed_ack_task(sock: TCPSocket, _arg) -> None:
    sock._on_delayed_ack_fire()


def _time_wait_task(sock: TCPSocket, _arg) -> None:
    if sock.state == TIME_WAIT:
        sock._teardown()
