"""Pluggable TCP congestion control.

Capability parity with the reference's CC vtable (tcp_cong.h:17-31 hooks:
duplicateAck / fastRecovery / newAck / timeout / ssthresh) and its
``--tcp-congestion-control`` choices reno/aimd/cubic (options.c,
tcp.c:2514 tcpCongestion_getType).  The reference ships Reno
(tcp_cong_reno.c); we implement all three advertised algorithms.

Windows are in bytes; ``mss`` is the segment size used for increments.
"""

from __future__ import annotations

INIT_CWND_SEGMENTS = 10       # Linux default initial window (RFC 6928)

# >>> simgen:begin region=congestion-params spec=f421682bce6f body=6a36d8b1dbdf
# CUBIC coefficient families (RFC 9438 §4.1 / §4.6).
CUBIC_C = 0.4      # cubic: scaling constant
CUBIC_BETA = 0.7   # cubic: multiplicative decrease
CUBICX_C = 0.6      # cubicx: scaling constant
CUBICX_BETA = 0.85   # cubicx: multiplicative decrease
# <<< simgen:end region=congestion-params


class CongestionControl:
    """Base vtable: slow start + congestion avoidance scaffolding."""

    name = "base"

    def __init__(self, mss: int, ssthresh: int = 0,
                 init_segments: int = INIT_CWND_SEGMENTS):
        self.mss = mss
        # --tcp-windows: initial window in packets (reference tcp.c:2459)
        self.cwnd = max(1, init_segments) * mss
        # 0 = "infinite" until first loss
        self.ssthresh = ssthresh if ssthresh > 0 else (1 << 30)
        self.in_fast_recovery = False
        self.recovery_point = 0       # snd_nxt at loss detection
        self._avoid_acc = 0           # byte accumulator for CA increments

    # -- hooks (tcp_cong.h:17-31) -----------------------------------------
    def on_new_ack(self, acked_bytes: int, snd_una: int, now_ns: int) -> None:
        if self.in_fast_recovery:
            if snd_una >= self.recovery_point:
                self._exit_recovery()
            else:
                return  # partial ACK: stay in recovery
        if self.cwnd < self.ssthresh:
            self.cwnd += min(acked_bytes, self.mss)   # slow start
        else:
            self._congestion_avoidance(acked_bytes, now_ns)

    def on_duplicate_ack(self, count: int, snd_nxt: int) -> bool:
        """Returns True when the caller should fast-retransmit (3rd dup)."""
        if count == 3 and not self.in_fast_recovery:
            self._enter_recovery(snd_nxt)
            return True
        if self.in_fast_recovery:
            self.cwnd += self.mss   # window inflation per extra dup
        return False

    def on_timeout(self) -> None:
        self.ssthresh = max(self.cwnd // 2, 2 * self.mss)
        self.cwnd = self.mss
        self.in_fast_recovery = False
        self._avoid_acc = 0

    # -- internals ---------------------------------------------------------
    def _enter_recovery(self, snd_nxt: int) -> None:
        self.ssthresh = max(self.cwnd // 2, 2 * self.mss)
        self.cwnd = self.ssthresh + 3 * self.mss
        self.in_fast_recovery = True
        self.recovery_point = snd_nxt

    def _exit_recovery(self) -> None:
        self.cwnd = self.ssthresh
        self.in_fast_recovery = False
        self._avoid_acc = 0

    def _congestion_avoidance(self, acked_bytes: int, now_ns: int) -> None:
        # +1 MSS per cwnd of acked bytes (Reno linear growth)
        self._avoid_acc += acked_bytes
        if self._avoid_acc >= self.cwnd:
            self._avoid_acc -= self.cwnd
            self.cwnd += self.mss


class Reno(CongestionControl):
    """NewReno-style fast recovery (reference tcp_cong_reno.c)."""

    name = "reno"


class AIMD(CongestionControl):
    """Plain additive-increase/multiplicative-decrease: like Reno but with
    no window inflation during recovery (the reference's 'aimd' choice)."""

    name = "aimd"

    def on_duplicate_ack(self, count: int, snd_nxt: int) -> bool:
        if count == 3 and not self.in_fast_recovery:
            self._enter_recovery(snd_nxt)
            self.cwnd = self.ssthresh  # no +3 inflation
            return True
        return False


class Cubic(CongestionControl):
    """CUBIC (RFC 9438): window growth is a cubic function of time since
    the last congestion event, independent of RTT."""

    name = "cubic"
    C = CUBIC_C          # scaling constant (RFC 9438 §4.1)
    BETA = CUBIC_BETA    # multiplicative decrease factor

    def __init__(self, mss: int, ssthresh: int = 0,
                 init_segments: int = INIT_CWND_SEGMENTS):
        super().__init__(mss, ssthresh, init_segments)
        self.w_max = 0.0          # window before last reduction (bytes)
        self.epoch_start_ns = 0
        self.k = 0.0              # time to regrow to w_max (seconds)

    def _enter_recovery(self, snd_nxt: int) -> None:
        self.w_max = float(self.cwnd)
        self.ssthresh = max(int(self.cwnd * self.BETA), 2 * self.mss)
        self.cwnd = self.ssthresh
        self.in_fast_recovery = True
        self.recovery_point = snd_nxt
        self.epoch_start_ns = 0   # new epoch starts at next ACK

    def on_timeout(self) -> None:
        self.w_max = float(self.cwnd)
        super().on_timeout()
        self.epoch_start_ns = 0

    def _congestion_avoidance(self, acked_bytes: int, now_ns: int) -> None:
        if self.epoch_start_ns == 0:
            self.epoch_start_ns = now_ns
            wm = max(self.w_max, float(self.cwnd))
            self.k = ((wm - self.cwnd) / (self.C * self.mss)) ** (1.0 / 3.0) \
                if wm > self.cwnd else 0.0
        t = (now_ns - self.epoch_start_ns) / 1e9
        target = self.w_max + self.C * self.mss * (t - self.k) ** 3
        if target > self.cwnd:
            # approach the cubic target over the next RTT-ish step
            self.cwnd += max(self.mss // 8,
                             int((target - self.cwnd) / 8))
        else:
            super()._congestion_avoidance(acked_bytes, now_ns)


# >>> simgen:begin region=congestion-variants spec=f421682bce6f body=a5ad8258f75d
class CubicX(Cubic):
    """Spec-defined CUBIC variant 'cubicx': (C, beta) = (0.6, 0.85).

    Same window-growth machinery as Cubic (the base class reads
    ``self.C``/``self.BETA``); only the coefficients differ.
    """

    name = "cubicx"
    C = CUBICX_C
    BETA = CUBICX_BETA


# config token -> generated class (make_congestion_control consults this)
CC_GENERATED = {
    "cubicx": CubicX,
}
# <<< simgen:end region=congestion-variants


def make_congestion_control(kind: str, mss: int, ssthresh: int = 0,
                            init_segments: int = INIT_CWND_SEGMENTS
                            ) -> CongestionControl:
    if kind == "reno":
        return Reno(mss, ssthresh, init_segments)
    if kind == "aimd":
        return AIMD(mss, ssthresh, init_segments)
    if kind == "cubic":
        return Cubic(mss, ssthresh, init_segments)
    cls = CC_GENERATED.get(kind)
    if cls is not None:
        return cls(mss, ssthresh, init_segments)
    raise ValueError(f"unknown congestion control {kind!r}")
