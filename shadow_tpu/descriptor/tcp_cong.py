"""Pluggable TCP congestion control.

Capability parity with the reference's CC vtable (tcp_cong.h:17-31 hooks:
duplicateAck / fastRecovery / newAck / timeout / ssthresh) and its
``--tcp-congestion-control`` choices reno/aimd/cubic (options.c,
tcp.c:2514 tcpCongestion_getType).  The reference ships Reno
(tcp_cong_reno.c); we implement all three advertised algorithms.

Windows are in bytes; ``mss`` is the segment size used for increments.
"""

from __future__ import annotations

INIT_CWND_SEGMENTS = 10       # Linux default initial window (RFC 6928)

# >>> simgen:begin region=congestion-params spec=293c930bb679 body=6a36d8b1dbdf
# CUBIC coefficient families (RFC 9438 §4.1 / §4.6).
CUBIC_C = 0.4      # cubic: scaling constant
CUBIC_BETA = 0.7   # cubic: multiplicative decrease
CUBICX_C = 0.6      # cubicx: scaling constant
CUBICX_BETA = 0.85   # cubicx: multiplicative decrease
# <<< simgen:end region=congestion-params

# >>> simgen:begin region=congestion-logic spec=293c930bb679 body=5b1b752f25a6
# bbrx estimator parameters (spec surface: congestion)
BBRX_BETA_DEN = 8
BBRX_BETA_NUM = 7
BBRX_BW_CAP_BPS = 1000000000000
BBRX_CYCLE_LEN = 8
BBRX_CYCLE_NS = 25000000
BBRX_GAIN_CRUISE_NUM = 4
BBRX_GAIN_DEN = 4
BBRX_GAIN_DOWN_NUM = 3
BBRX_GAIN_UP_NUM = 5
BBRX_MIN_CWND_SEGMENTS = 4
BBRX_RTT_CAP_NS = 1000000000
BBRX_RTT_FLOOR_NS = 100000


# congestion update logic, generated from the spec's expression IR

def _g_bbrx_bdp_bytes(btl_bw_bps, min_rtt_ns):
    """bandwidth-delay product; the /1000 then /1e6 split keeps the intermediate below 2**63 at the bw/rtt caps"""
    return (((btl_bw_bps // 1000) * min(min_rtt_ns, 1000000000)) // 1000000)


def _g_bbrx_btl_bw(btl_bw_bps, bw_sample_bps):
    """bottleneck-bandwidth max filter"""
    return max(btl_bw_bps, bw_sample_bps)


def _g_bbrx_bw_decay(btl_bw_bps):
    """multiplicative bandwidth-estimate decay on loss"""
    return ((btl_bw_bps * 7) // 8)


def _g_bbrx_bw_sample(acked_bytes, interval_ns):
    """delivery-rate sample in bytes/sec from one ACK's bytes over the inter-ACK interval, capped"""
    return min(((acked_bytes * 1000000000) // max(interval_ns, 1)), 1000000000000)


def _g_bbrx_gain_num(cycle_idx):
    """gain numerator for the cycle phase: probe up, drain down, then cruise (BBR's 5/4, 3/4, 1.0 x6 over BBRX_GAIN_DEN)"""
    return (5 if (cycle_idx == 0) else (3 if (cycle_idx == 1) else 4))


def _g_bbrx_inflight_cap(bdp_bytes, gain_num, mss):
    """cwnd = max(gain * bdp, floor segments)"""
    return max(((bdp_bytes * gain_num) // 4), (4 * mss))


def _g_bbrx_min_rtt(min_rtt_ns, interval_ns):
    """min-RTT filter over floored inter-ACK intervals"""
    return min(min_rtt_ns, max(interval_ns, 100000))


def _g_bbrx_next_cycle(cycle_idx):
    """pacing-gain cycle advance"""
    return ((cycle_idx + 1) % 8)


def _g_recovery_cwnd(ssthresh, mss):
    """fast-recovery window inflation (ssthresh + 3*mss)"""
    return (ssthresh + (3 * mss))


def _g_ssthresh_after_loss(cwnd, mss):
    """ssthresh = max(cwnd/2, 2*mss) on loss (RFC 5681)"""
    return max((cwnd // 2), (2 * mss))
# <<< simgen:end region=congestion-logic


class CongestionControl:
    """Base vtable: slow start + congestion avoidance scaffolding."""

    name = "base"

    def __init__(self, mss: int, ssthresh: int = 0,
                 init_segments: int = INIT_CWND_SEGMENTS):
        self.mss = mss
        # --tcp-windows: initial window in packets (reference tcp.c:2459)
        self.cwnd = max(1, init_segments) * mss
        # 0 = "infinite" until first loss
        self.ssthresh = ssthresh if ssthresh > 0 else (1 << 30)
        self.in_fast_recovery = False
        self.recovery_point = 0       # snd_nxt at loss detection
        self._avoid_acc = 0           # byte accumulator for CA increments

    # -- hooks (tcp_cong.h:17-31) -----------------------------------------
    def on_new_ack(self, acked_bytes: int, snd_una: int, now_ns: int) -> None:
        if self.in_fast_recovery:
            if snd_una >= self.recovery_point:
                self._exit_recovery()
            else:
                return  # partial ACK: stay in recovery
        if self.cwnd < self.ssthresh:
            self.cwnd += min(acked_bytes, self.mss)   # slow start
        else:
            self._congestion_avoidance(acked_bytes, now_ns)

    def on_duplicate_ack(self, count: int, snd_nxt: int) -> bool:
        """Returns True when the caller should fast-retransmit (3rd dup)."""
        if count == 3 and not self.in_fast_recovery:
            self._enter_recovery(snd_nxt)
            return True
        if self.in_fast_recovery:
            self.cwnd += self.mss   # window inflation per extra dup
        return False

    def on_timeout(self) -> None:
        self.ssthresh = _g_ssthresh_after_loss(self.cwnd, self.mss)
        self.cwnd = self.mss
        self.in_fast_recovery = False
        self._avoid_acc = 0

    # -- internals ---------------------------------------------------------
    def _enter_recovery(self, snd_nxt: int) -> None:
        self.ssthresh = _g_ssthresh_after_loss(self.cwnd, self.mss)
        self.cwnd = _g_recovery_cwnd(self.ssthresh, self.mss)
        self.in_fast_recovery = True
        self.recovery_point = snd_nxt

    def _exit_recovery(self) -> None:
        self.cwnd = self.ssthresh
        self.in_fast_recovery = False
        self._avoid_acc = 0

    def _congestion_avoidance(self, acked_bytes: int, now_ns: int) -> None:
        # +1 MSS per cwnd of acked bytes (Reno linear growth)
        self._avoid_acc += acked_bytes
        if self._avoid_acc >= self.cwnd:
            self._avoid_acc -= self.cwnd
            self.cwnd += self.mss


class Reno(CongestionControl):
    """NewReno-style fast recovery (reference tcp_cong_reno.c)."""

    name = "reno"


class AIMD(CongestionControl):
    """Plain additive-increase/multiplicative-decrease: like Reno but with
    no window inflation during recovery (the reference's 'aimd' choice)."""

    name = "aimd"

    def on_duplicate_ack(self, count: int, snd_nxt: int) -> bool:
        if count == 3 and not self.in_fast_recovery:
            self._enter_recovery(snd_nxt)
            self.cwnd = self.ssthresh  # no +3 inflation
            return True
        return False


class Cubic(CongestionControl):
    """CUBIC (RFC 9438): window growth is a cubic function of time since
    the last congestion event, independent of RTT."""

    name = "cubic"
    C = CUBIC_C          # scaling constant (RFC 9438 §4.1)
    BETA = CUBIC_BETA    # multiplicative decrease factor

    def __init__(self, mss: int, ssthresh: int = 0,
                 init_segments: int = INIT_CWND_SEGMENTS):
        super().__init__(mss, ssthresh, init_segments)
        self.w_max = 0.0          # window before last reduction (bytes)
        self.epoch_start_ns = 0
        self.k = 0.0              # time to regrow to w_max (seconds)

    def _enter_recovery(self, snd_nxt: int) -> None:
        self.w_max = float(self.cwnd)
        self.ssthresh = max(int(self.cwnd * self.BETA), 2 * self.mss)
        self.cwnd = self.ssthresh
        self.in_fast_recovery = True
        self.recovery_point = snd_nxt
        self.epoch_start_ns = 0   # new epoch starts at next ACK

    def on_timeout(self) -> None:
        self.w_max = float(self.cwnd)
        super().on_timeout()
        self.epoch_start_ns = 0

    def _congestion_avoidance(self, acked_bytes: int, now_ns: int) -> None:
        if self.epoch_start_ns == 0:
            self.epoch_start_ns = now_ns
            wm = max(self.w_max, float(self.cwnd))
            self.k = ((wm - self.cwnd) / (self.C * self.mss)) ** (1.0 / 3.0) \
                if wm > self.cwnd else 0.0
        t = (now_ns - self.epoch_start_ns) / 1e9
        target = self.w_max + self.C * self.mss * (t - self.k) ** 3
        if target > self.cwnd:
            # approach the cubic target over the next RTT-ish step
            self.cwnd += max(self.mss // 8,
                             int((target - self.cwnd) / 8))
        else:
            super()._congestion_avoidance(acked_bytes, now_ns)


# >>> simgen:begin region=congestion-variants spec=293c930bb679 body=08dd1007c920
class CubicX(Cubic):
    """Spec-defined CUBIC variant 'cubicx': (C, beta) = (0.6, 0.85).

    Same window-growth machinery as Cubic (the base class reads
    ``self.C``/``self.BETA``); only the coefficients differ.
    """

    name = "cubicx"
    C = CUBICX_C
    BETA = CUBICX_BETA


class BbrX(CongestionControl):
    """Spec-defined 'bbrx' (ISSUE 19): a BBR-flavored family — windowed
    bandwidth (max filter + loss decay), min-RTT from ACK spacing, a
    pacing-gain cycle, and an inflight cap from the BDP.  Every update
    expression is generated from the spec's logic IR; this class holds
    only the estimator state and the hook wiring.
    """

    name = "bbrx"

    def __init__(self, mss, ssthresh=0,
                 init_segments=INIT_CWND_SEGMENTS):
        super().__init__(mss, ssthresh, init_segments)
        self.btl_bw_bps = 0
        self.min_rtt_ns = BBRX_RTT_CAP_NS
        self.last_ack_ns = 0
        self.cycle_idx = 0
        self.cycle_start_ns = 0

    def on_new_ack(self, acked_bytes, snd_una, now_ns):
        if self.in_fast_recovery:
            if snd_una >= self.recovery_point:
                self._exit_recovery()
            else:
                return  # partial ACK: stay in recovery
        if self.last_ack_ns > 0:
            interval_ns = now_ns - self.last_ack_ns
            self.btl_bw_bps = _g_bbrx_btl_bw(
                self.btl_bw_bps,
                _g_bbrx_bw_sample(acked_bytes, interval_ns))
            self.min_rtt_ns = _g_bbrx_min_rtt(self.min_rtt_ns,
                                              interval_ns)
        self.last_ack_ns = now_ns
        if now_ns - self.cycle_start_ns >= BBRX_CYCLE_NS:
            self.cycle_idx = _g_bbrx_next_cycle(self.cycle_idx)
            self.cycle_start_ns = now_ns
        if self.btl_bw_bps > 0:
            self.cwnd = _g_bbrx_inflight_cap(
                _g_bbrx_bdp_bytes(self.btl_bw_bps, self.min_rtt_ns),
                _g_bbrx_gain_num(self.cycle_idx), self.mss)

    def _enter_recovery(self, snd_nxt):
        self.btl_bw_bps = _g_bbrx_bw_decay(self.btl_bw_bps)
        self.ssthresh = _g_ssthresh_after_loss(self.cwnd, self.mss)
        self.cwnd = _g_recovery_cwnd(self.ssthresh, self.mss)
        self.in_fast_recovery = True
        self.recovery_point = snd_nxt

    def on_timeout(self):
        self.btl_bw_bps = _g_bbrx_bw_decay(self.btl_bw_bps)
        self.ssthresh = _g_ssthresh_after_loss(self.cwnd, self.mss)
        self.cwnd = self.mss
        self.in_fast_recovery = False
        self._avoid_acc = 0


# config token -> generated class (make_congestion_control consults this)
CC_GENERATED = {
    "bbrx": BbrX,
    "cubicx": CubicX,
}
# <<< simgen:end region=congestion-variants


def make_congestion_control(kind: str, mss: int, ssthresh: int = 0,
                            init_segments: int = INIT_CWND_SEGMENTS
                            ) -> CongestionControl:
    if kind == "reno":
        return Reno(mss, ssthresh, init_segments)
    if kind == "aimd":
        return AIMD(mss, ssthresh, init_segments)
    if kind == "cubic":
        return Cubic(mss, ssthresh, init_segments)
    cls = CC_GENERATED.get(kind)
    if cls is not None:
        return cls(mss, ssthresh, init_segments)
    raise ValueError(f"unknown congestion control {kind!r}")
