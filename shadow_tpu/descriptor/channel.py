"""Channel: pipes and socketpairs as linked in-memory byte queues
(reference host/descriptor/channel.c + utility/byte_queue.c)."""

from __future__ import annotations

from ..utils.byte_queue import ByteQueue
from .base import S_READABLE, S_WRITABLE, Transport


class Channel(Transport):
    """One end of a pipe/socketpair.  ``link`` joins two ends; writes to one
    end land in the other's read buffer."""

    def __init__(self, host, handle: int, writable: bool = True,
                 readable: bool = True, buffer_size: int = 65536):
        super().__init__(host, handle, "pipe")
        self.buffer = ByteQueue()
        self.buffer_size = buffer_size
        self.linked: "Channel" = None
        self.can_read = readable
        self.can_write = writable
        self.adjust_status(S_WRITABLE if writable else 0, True)

    @staticmethod
    def new_pipe(host, read_handle: int, write_handle: int):
        r = Channel(host, read_handle, writable=False, readable=True)
        w = Channel(host, write_handle, writable=True, readable=False)
        r.linked = w
        w.linked = r
        return r, w

    @staticmethod
    def new_socketpair(host, handle_a: int, handle_b: int):
        a = Channel(host, handle_a)
        b = Channel(host, handle_b)
        a.linked = b
        b.linked = a
        return a, b

    def send_user_data(self, data: bytes, dst_ip: int = 0, dst_port: int = 0) -> int:
        if not self.can_write or self.linked is None or self.linked.closed:
            raise BrokenPipeError("EPIPE")
        peer = self.linked
        space = peer.buffer_size - len(peer.buffer)
        if space <= 0:
            return 0  # EWOULDBLOCK
        chunk = data[:space]
        peer.buffer.push(chunk)
        peer.adjust_status(S_READABLE, True)
        if len(peer.buffer) >= peer.buffer_size:
            self.adjust_status(S_WRITABLE, False)
        return len(chunk)

    def receive_user_data(self, nbytes: int):
        if not self.can_read:
            raise OSError("EBADF: read end only")
        data = self.buffer.pop(nbytes)
        if not data:
            if self.linked is None or self.linked.closed:
                return b"", 0, 0  # EOF
            return None  # EWOULDBLOCK
        if len(self.buffer) == 0:
            self.adjust_status(S_READABLE, False)
        if self.linked is not None:
            self.linked.adjust_status(S_WRITABLE, True)
        return data, 0, 0

    def close(self) -> None:
        if self.linked is not None and not self.linked.closed:
            # peer sees EOF (readable with empty buffer) / EPIPE
            self.linked.adjust_status(S_READABLE, True)
        super().close()
