"""timerfd emulation backed by scheduled tasks (reference
host/descriptor/timer.c): settable one-shot/periodic expiration, readable
when expirations are pending, read() returns-and-clears the expiration
count."""

from __future__ import annotations

from ..core.task import Task
from .base import Descriptor, S_READABLE
from ..core.worker import current_worker


class Timer(Descriptor):
    def __init__(self, host, handle: int):
        super().__init__(host, handle, "timer")
        self.expire_count = 0
        self.interval_ns = 0
        self.next_expire_time = -1
        self._generation = 0  # invalidates stale scheduled tasks on re-arm

    def arm(self, initial_ns: int, interval_ns: int = 0) -> None:
        """timerfd_settime: initial_ns relative; 0 disarms."""
        self._generation += 1
        self.interval_ns = interval_ns
        if initial_ns <= 0:
            self.next_expire_time = -1
            return
        w = current_worker()
        now = w.now if w is not None else 0
        self.next_expire_time = now + initial_ns
        if w is not None:
            w.schedule_task(Task(_timer_expire_task, self, self._generation,
                                 name="timer_expire"),
                            initial_ns, dst_host=self.host)

    def disarm(self) -> None:
        self.arm(0)

    def _on_expire(self, generation: int) -> None:
        if generation != self._generation or self.closed:
            return
        self.expire_count += 1
        self.adjust_status(S_READABLE, True)
        if self.interval_ns > 0:
            w = current_worker()
            if w is not None:
                self.next_expire_time = w.now + self.interval_ns
                w.schedule_task(Task(_timer_expire_task, self, self._generation,
                                     name="timer_expire"),
                                self.interval_ns, dst_host=self.host)

    def read_expirations(self) -> int:
        n = self.expire_count
        self.expire_count = 0
        self.adjust_status(S_READABLE, False)
        return n


def _timer_expire_task(timer: Timer, generation: int) -> None:
    timer._on_expire(generation)
