"""signalfd emulation: virtual signals delivered as a readable descriptor.

The reference models signals through its pth substrate (rpth's signal
handling) and the process_emu layer; in the split-process design a signal
raised inside the simulation (raise()/kill() on the virtual pid) is routed
by the shim to the simulator, which queues it for the process — signalfd(2)
semantics for the subset Tor-class event loops use (block the signal, put
the signalfd in epoll, read 128-byte signalfd_siginfo records):

* each descriptor carries a signal-number mask;
* a blocked pending signal is ONE process-wide instance: EVERY open
  signalfd whose mask matches becomes readable, and the FIRST read (from
  any of them) consumes the shared instance — after which the others stop
  being readable (unless more pending signals match them).  Two epoll loops
  watching signalfds with overlapping masks therefore both wake, and
  exactly one wins the read — the kernel's behavior;
* standard signals (1-31) coalesce to one pending instance; real-time
  signals (>= 32) queue each raise.

Records are 128-byte signalfd_siginfo structs with ssi_signo filled and
the sender fields zero (the only in-sim senders are the process itself and
the simulator).
"""

from __future__ import annotations

import struct
from collections import deque
from typing import Optional

from .base import Descriptor, S_READABLE

SIGINFO_SIZE = 128


class SharedSignalPending:
    """The per-process pending-signal store every signalfd of the process
    shares (the kernel's per-process pending set).  Owns the routing:
    deliver() marks ALL matching fds readable; consume() pops the first
    instance matching the reading fd's mask and refreshes every sibling's
    readable bit."""

    def __init__(self):
        self.pending: deque = deque()
        self.fds: list = []

    def register(self, fd: "SignalFD") -> None:
        self.fds.append(fd)
        # signalfd(2) reports already-pending signals immediately: a fd
        # opened while a matching signal sits in the process pending set is
        # readable from the start
        if any(fd.matches(p) for p in self.pending):
            fd.adjust_status(S_READABLE, True)

    def _live(self) -> list:
        live = [s for s in self.fds if not s.closed]
        self.fds = live
        return live

    def deliver(self, signo: int) -> int:
        """Queue one pending instance and wake every matching signalfd.
        Returns the number of matching fds (0 = caller falls back to its
        recorded handler)."""
        matched = [s for s in self._live() if s.matches(signo)]
        if not matched:
            return 0
        if not (signo < 32 and signo in self.pending):
            self.pending.append(signo)   # standard signals coalesce
        # mark matched fds readable even on the coalesced path: a fd opened
        # between the original raise and this one must still wake
        for s in matched:
            s.adjust_status(S_READABLE, True)
        return len(matched)

    def consume(self, fd: "SignalFD") -> Optional[int]:
        """First read wins: pop the oldest pending signal matching ``fd``'s
        mask, then recompute every sibling's readable status against what
        remains pending."""
        signo = None
        for i, s in enumerate(self.pending):
            if fd.matches(s):
                signo = s
                del self.pending[i]
                break
        if signo is None:
            return None
        for s in self._live():
            s.adjust_status(
                S_READABLE, any(s.matches(p) for p in self.pending))
        return signo


class SignalFD(Descriptor):
    def __init__(self, host, handle: int, mask: int,
                 shared: Optional[SharedSignalPending] = None):
        super().__init__(host, handle, "signalfd")
        self.mask = int(mask)          # bit (signo-1) set = in mask
        # standalone fallback queue (direct constructions without a
        # process-shared store keep the old single-fd behavior)
        self.pending: deque = deque()
        self.shared = shared
        if shared is not None:
            shared.register(self)

    def matches(self, signo: int) -> bool:
        return 1 <= signo <= 64 and bool(self.mask >> (signo - 1) & 1)

    def deliver(self, signo: int) -> bool:
        if self.closed or not self.matches(signo):
            return False
        if self.shared is not None:
            # process-shared routing: deliver through the store so every
            # matching sibling wakes too
            return self.shared.deliver(signo) > 0
        # standard signals (1-31) coalesce: the kernel keeps ONE pending
        # instance per signal, so a second raise before the first read is
        # invisible; real-time signals (>=32) queue each instance
        if signo < 32 and signo in self.pending:
            return True
        self.pending.append(signo)
        self.adjust_status(S_READABLE, True)
        return True

    def read_siginfo(self) -> Optional[bytes]:
        if self.shared is not None:
            signo = self.shared.consume(self)
            if signo is None:
                return None
        else:
            if not self.pending:
                return None
            signo = self.pending.popleft()
            if not self.pending:
                self.adjust_status(S_READABLE, False)
        # struct signalfd_siginfo: u32 ssi_signo, s32 ssi_errno, s32
        # ssi_code, then ids/addresses we zero-fill, padded to 128 bytes
        return struct.pack("<Iii", signo, 0, 0).ljust(SIGINFO_SIZE, b"\0")
