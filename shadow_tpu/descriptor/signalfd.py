"""signalfd emulation: virtual signals delivered as a readable descriptor.

The reference models signals through its pth substrate (rpth's signal
handling) and the process_emu layer; in the split-process design a signal
raised inside the simulation (raise()/kill() on the virtual pid) is routed
by the shim to the simulator, which queues it on any matching signalfd the
process holds — signalfd(2) semantics for the subset Tor-class event loops
use (block the signal, put the signalfd in epoll, read 128-byte
signalfd_siginfo records):

* the descriptor carries a signal-number mask;
* deliver(signo) queues a record iff signo is in the mask;
* read() pops one record (blocks/EAGAIN when empty); readable iff queued.

Records are 128-byte signalfd_siginfo structs with ssi_signo filled and
the sender fields zero (the only in-sim senders are the process itself and
the simulator).
"""

from __future__ import annotations

import struct
from collections import deque
from typing import Optional

from .base import Descriptor, S_READABLE

SIGINFO_SIZE = 128


class SignalFD(Descriptor):
    def __init__(self, host, handle: int, mask: int):
        super().__init__(host, handle, "signalfd")
        self.mask = int(mask)          # bit (signo-1) set = in mask
        self.pending: deque = deque()

    def matches(self, signo: int) -> bool:
        return 1 <= signo <= 64 and bool(self.mask >> (signo - 1) & 1)

    def deliver(self, signo: int) -> bool:
        if self.closed or not self.matches(signo):
            return False
        # standard signals (1-31) coalesce: the kernel keeps ONE pending
        # instance per signal, so a second raise before the first read is
        # invisible; real-time signals (>=32) queue each instance
        if signo < 32 and signo in self.pending:
            return True
        self.pending.append(signo)
        self.adjust_status(S_READABLE, True)
        return True

    def read_siginfo(self) -> Optional[bytes]:
        if not self.pending:
            return None
        signo = self.pending.popleft()
        if not self.pending:
            self.adjust_status(S_READABLE, False)
        # struct signalfd_siginfo: u32 ssi_signo, s32 ssi_errno, s32
        # ssi_code, then ids/addresses we zero-fill, padded to 128 bytes
        return struct.pack("<Iii", signo, 0, 0).ljust(SIGINFO_SIZE, b"\0")
