"""Descriptor object model: the virtual-kernel side of file descriptors.

Mirrors the reference's single-inheritance C hierarchy
(host/descriptor/descriptor.h:14-59 base with status bits + epoll listener
set; transport.h:16-42 send/recv vtable; socket.h:20-78 buffers + binding):

    Descriptor -> Transport -> Socket -> {TCP, UDP}
    Descriptor -> {Epoll, Timer, Channel(pipe)}

Status bits drive everything: when a descriptor's READABLE/WRITABLE set
changes, listeners (epoll instances and blocked green threads) are notified,
which is what resumes virtual processes (descriptor_adjustStatus -> epoll
notify -> process_continue in the reference).
"""

from __future__ import annotations

from collections import deque
from typing import Callable, List, Optional, Set

# >>> simgen:begin region=status-bits spec=293c930bb679 body=dab61b8b2aea
# Status bits (reference descriptor.h DS_*).
S_NONE = 0
S_ACTIVE = 1
S_READABLE = 2
S_WRITABLE = 4
S_CLOSED = 8
# <<< simgen:end region=status-bits


class Descriptor:
    # the C plane a descriptor's state lives in — always None for Python
    # descriptors; NativeSocket (duck-typed, not a subclass) carries the
    # real plane.  Class-level so process._dispatch's native-block routing
    # reads it as a plain attribute on every blocking syscall.
    plane = None

    def __init__(self, host, handle: int, kind: str):
        self.host = host
        self.handle = handle
        self.kind = kind          # "tcp"/"udp"/"epoll"/"timer"/"pipe"...
        self.status = S_NONE
        self._listeners: List[Callable[["Descriptor", int], None]] = []
        self.closed = False

    # -- status ------------------------------------------------------------
    def adjust_status(self, bits: int, on: bool) -> None:
        old = self.status
        if on:
            self.status |= bits
        else:
            self.status &= ~bits
        changed = old ^ self.status
        if changed:
            for listener in list(self._listeners):
                listener(self, changed)

    def has_status(self, bits: int) -> bool:
        return (self.status & bits) == bits

    def add_listener(self, cb: Callable[["Descriptor", int], None]) -> None:
        if cb not in self._listeners:
            self._listeners.append(cb)

    def remove_listener(self, cb) -> None:
        if cb in self._listeners:
            self._listeners.remove(cb)

    # -- lifecycle ---------------------------------------------------------
    def close(self) -> None:
        if self.closed:
            return
        self.closed = True
        self.adjust_status(S_ACTIVE | S_READABLE | S_WRITABLE, False)
        self.adjust_status(S_CLOSED, True)
        if self.host is not None:
            self.host.descriptor_table_remove(self.handle)

    def __repr__(self) -> str:  # pragma: no cover
        return f"{type(self).__name__}(fd={self.handle})"


class Transport(Descriptor):
    """send/recv vtable layer (transport.c)."""

    def send_user_data(self, data: bytes, dst_ip: int = 0, dst_port: int = 0) -> int:
        raise NotImplementedError

    def receive_user_data(self, nbytes: int):
        """Returns (data, src_ip, src_port) or None if nothing available."""
        raise NotImplementedError


class Socket(Transport):
    """Buffers + naming common to TCP/UDP (socket.c/.h).

    Packet queues carry simulated Packets; byte accounting throttles against
    configured buffer sizes.  ``peek/pull_out_packet`` feed the interface
    send loop; ``push_in_packet`` is the arrival entry point.
    """

    def __init__(self, host, handle: int, kind: str, recv_buf_size: int,
                 send_buf_size: int):
        super().__init__(host, handle, kind)
        self.recv_buf_size = recv_buf_size
        self.send_buf_size = send_buf_size
        self.in_packets: deque = deque()
        self.in_bytes = 0
        self.out_packets: deque = deque()
        self.out_bytes = 0
        # naming
        self.bound_ip: Optional[int] = None
        self.bound_port: Optional[int] = None
        self.peer_ip: Optional[int] = None
        self.peer_port: Optional[int] = None
        self.unix_path: Optional[str] = None
        # (iface, binding-key) pairs maintained by NetworkInterface.associate
        self._associations: List[tuple] = []
        self.adjust_status(S_ACTIVE, True)

    # -- naming ------------------------------------------------------------
    @property
    def is_bound(self) -> bool:
        return self.bound_port is not None

    def bind_to(self, ip: int, port: int) -> None:
        self.bound_ip = ip
        self.bound_port = port

    def release_bindings(self) -> None:
        """Drop every interface binding this socket holds (frees its ports/
        4-tuples for reuse while the descriptor may stay open)."""
        for iface, key in list(self._associations):
            iface.disassociate_key(key, self)
        self._associations.clear()

    def close(self) -> None:
        """Release every interface binding this socket holds, then close."""
        if self.closed:
            return
        self.release_bindings()
        super().close()

    # -- output queue (interface side) ------------------------------------
    def add_out_packet(self, packet) -> None:
        self.out_packets.append(packet)
        self.out_bytes += packet.total_size
        packet.add_status("SND_SOCKET_BUFFERED")

    def peek_out_packet(self):
        return self.out_packets[0] if self.out_packets else None

    def pull_out_packet(self):
        if not self.out_packets:
            return None
        p = self.out_packets.popleft()
        self.out_bytes -= p.total_size
        return p

    def has_out_space(self, nbytes: int) -> bool:
        return self.out_bytes + nbytes <= self.send_buf_size

    # -- input queue -------------------------------------------------------
    def push_in_packet(self, packet) -> None:
        raise NotImplementedError  # protocol-specific (process_packet)

    def drop_packet(self, packet) -> None:
        packet.add_status("RCV_SOCKET_DROPPED")

    def has_in_space(self, nbytes: int) -> bool:
        return self.in_bytes + nbytes <= self.recv_buf_size
