"""UDP socket.

Thin wrapper over the packet pipeline like the reference's udp.c: sends chop
user data into datagrams (<= CONFIG_DATAGRAM_MAX_SIZE) handed straight to
the interface; arrivals queue whole packets for recvfrom.  Cites
udp_sendUserData (udp.c:75) / udp_processPacket (udp.c:53).
"""

from __future__ import annotations

from ..core import defs
from ..routing.packet import Packet
from .base import S_READABLE, S_WRITABLE, Socket


class UDPSocket(Socket):
    def __init__(self, host, handle: int, recv_buf_size: int, send_buf_size: int):
        super().__init__(host, handle, "udp", recv_buf_size, send_buf_size)
        self.adjust_status(S_WRITABLE, True)
        self.default_interface = None   # set when bound

    # -- connect (datagram semantics) --------------------------------------
    def connect_to(self, dst_ip: int, dst_port: int) -> bool:
        """UDP connect(2): record the default destination and filter
        arrivals to that peer.  Completes immediately (returns True) —
        there is no handshake.  Real resolver-style clients connect their
        UDP sockets before send/recv."""
        if not self.is_bound:
            self.host.autobind_socket(self, dst_ip)
        self.peer_ip, self.peer_port = dst_ip, dst_port
        return True

    def take_socket_error(self):
        return None

    # -- send --------------------------------------------------------------
    def send_user_data(self, data: bytes, dst_ip: int = 0, dst_port: int = 0) -> int:
        host = self.host
        if dst_ip == 0:
            if self.peer_ip is None:
                raise ConnectionError("EDESTADDRREQ: unconnected UDP send without address")
            dst_ip, dst_port = self.peer_ip, self.peer_port
        if not self.is_bound:
            host.autobind_socket(self, dst_ip)
        if len(data) > defs.CONFIG_DATAGRAM_MAX_SIZE:
            raise OSError("EMSGSIZE: datagram too large")
        need = len(data) + defs.CONFIG_HEADER_SIZE_UDPIPETH
        if need > self.send_buf_size:
            # can never fit even in an empty buffer: returning 0 would make a
            # blocking sender spin at one virtual instant forever
            raise OSError("EMSGSIZE: datagram exceeds send buffer")
        if not self.has_out_space(need):
            return 0  # EWOULDBLOCK; caller retries when WRITABLE
        packet = Packet.new_udp(host.next_packet_uid(), host.next_packet_priority(),
                                self.bound_ip, self.bound_port, dst_ip, dst_port,
                                data)
        self.add_out_packet(packet)
        iface = host.interface_for_ip(self.bound_ip)
        if iface is not None:
            iface.wants_send(self)
        self._update_writable()
        return len(data)

    # -- receive -----------------------------------------------------------
    def peek_user_data(self, nbytes: int):
        """MSG_PEEK: the next datagram's payload without consuming it."""
        if not self.in_packets:
            return None
        p = self.in_packets[0]
        return p.payload[:nbytes], p.src_ip, p.src_port

    def receive_user_data(self, nbytes: int):
        if not self.in_packets:
            return None
        p = self.in_packets.popleft()
        self.in_bytes -= p.total_size
        data = p.payload[:nbytes]  # datagram semantics: excess is discarded
        p.add_status("RCV_SOCKET_DELIVERED")
        self._update_readable()
        self._update_writable()
        return data, p.src_ip, p.src_port

    def push_in_packet(self, packet) -> None:
        # a connected UDP socket only accepts datagrams from its peer
        if self.peer_ip is not None and (
                packet.src_ip != self.peer_ip
                or packet.src_port != self.peer_port):
            self.drop_packet(packet)
            return
        if not self.has_in_space(packet.total_size):
            self.drop_packet(packet)
            return
        packet.add_status("RCV_SOCKET_BUFFERED")
        self.in_packets.append(packet)
        self.in_bytes += packet.total_size
        self._update_readable()

    # -- status upkeep -----------------------------------------------------
    def _update_readable(self) -> None:
        self.adjust_status(S_READABLE, bool(self.in_packets))

    def _update_writable(self) -> None:
        # WRITABLE must imply a max-size datagram send will succeed, or a
        # blocking sender spins on (send -> 0, block-on-writable -> already
        # set) without ever advancing virtual time.  Clamped to the buffer
        # size so tiny configured send buffers can still become writable
        # (they just can't take a max-size datagram without draining first).
        max_need = min(defs.CONFIG_DATAGRAM_MAX_SIZE
                       + defs.CONFIG_HEADER_SIZE_UDPIPETH, self.send_buf_size)
        self.adjust_status(S_WRITABLE,
                           self.has_out_space(max_need) and not self.closed)

    def pull_out_packet(self):
        p = super().pull_out_packet()
        self._update_writable()
        return p
