"""Task: a schedulable closure.

Mirrors the capability of the reference's refcounted Task
(core/work/task.c:24 ``task_new`` / :68 ``task_execute``): a callback bound to
an object and an argument.  Python's GC replaces the manual refcount/free-func
machinery; we keep the (callback, obj, arg) shape so call sites read the same.
"""

from __future__ import annotations

from typing import Any, Callable, Optional


class Task:
    __slots__ = ("callback", "obj", "arg", "name")

    def __init__(self, callback: Callable[[Any, Any], None], obj: Any = None,
                 arg: Any = None, name: str = ""):
        self.callback = callback
        self.obj = obj
        self.arg = arg
        self.name = name or getattr(callback, "__name__", "task")

    def execute(self) -> None:
        self.callback(self.obj, self.arg)

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"Task({self.name})"
