"""Object lifecycle counters and the shutdown leak report.

Capability of the reference's ObjectCounter (core/support/object_counter.c):
per-type new/free tallies kept per worker, merged into the engine at exit,
with a leak report if any type has new != free (slave.c:238-239).
"""

from __future__ import annotations

import threading
from collections import defaultdict
from typing import Dict


class ObjectCounter:
    def __init__(self):
        self._new: Dict[str, int] = defaultdict(int)
        self._free: Dict[str, int] = defaultdict(int)

    def count_new(self, kind: str, n: int = 1) -> None:
        self._new[kind] += n

    def count_free(self, kind: str, n: int = 1) -> None:
        self._free[kind] += n

    def merge(self, other: "ObjectCounter") -> None:
        for k, v in other._new.items():
            self._new[k] += v
        for k, v in other._free.items():
            self._free[k] += v

    def leaks(self) -> Dict[str, int]:
        out = {}
        # sorted: the leak dict reaches the metrics summary JSON and the
        # shutdown report — byte-stable output across runs (SIM003)
        for k in sorted(set(self._new) | set(self._free)):
            d = self._new[k] - self._free[k]
            if d != 0:
                out[k] = d
        return out

    def report(self) -> str:
        lines = ["object counts (new/free):"]
        for k in sorted(set(self._new) | set(self._free)):
            n, f = self._new[k], self._free[k]
            flag = "" if n == f else "  <-- LEAK"
            lines.append(f"  {k:<16} {n:>10} / {f:>10}{flag}")
        return "\n".join(lines)

    def summary(self) -> Dict[str, object]:
        """The shutdown report in metrics-summary form (obs/metrics.py):
        the leak map plus per-type [new, free] pairs — the SAME numbers
        report() formats for the log, so the two surfaces cannot drift."""
        return {
            "object_leaks": dict(self.leaks()),
            "object_counts": {k: [self._new[k], self._free[k]]
                              for k in sorted(set(self._new)
                                              | set(self._free))},
        }
