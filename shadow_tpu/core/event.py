"""Event: a Task bound to a virtual time, with a total deterministic order.

The causality contract of the whole simulator lives here.  The reference
orders events by the tuple (time, dstHostID, srcHostID, srcHostEventID)
(core/work/event.c:110-153 ``event_compare``); every scheduler policy — and
our batched TPU kernel — must produce executions consistent with that total
order.  We keep the exact same key so CPU/TPU event-order parity can be
checked bit-for-bit.

``event.execute`` also applies the host CPU-delay model before running the
task (reference event.c:65-93): if the destination host's virtual CPU is
"blocked" (accumulated delay above threshold), the event is rescheduled
instead of executed.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple


class Event:
    __slots__ = ("task", "time", "dst_host", "src_host", "sequence",
                 "pq_entry")

    def __init__(self, task, time: int, dst_host, src_host, sequence: int):
        self.task = task
        self.time = time
        self.dst_host = dst_host      # Host object (owns execution context)
        self.src_host = src_host      # Host that scheduled it
        self.sequence = sequence      # per-src-host monotonic event id
        self.pq_entry = None          # intrusive heap slot (utils/pqueue.py)

    def order_key(self) -> Tuple[int, int, int, int]:
        return (self.time,
                self.dst_host.id if self.dst_host is not None else -1,
                self.src_host.id if self.src_host is not None else -1,
                self.sequence)

    def __lt__(self, other: "Event") -> bool:
        return self.order_key() < other.order_key()

    def execute(self, worker) -> bool:
        """Run the task under the destination host's context.

        Returns False if the host CPU model deferred the event (it was
        rescheduled; reference event.c:75-84), True if the task ran.
        """
        host = self.dst_host
        if host is not None:
            cpu = host.cpu
            if cpu is not None:
                cpu.update_time(self.time)
                delay = cpu.get_delay()
                if cpu.is_blocked():
                    # Defer by the pending CPU delay; keep ordering stable by
                    # re-inserting with the same (src,seq) identity.
                    worker.reschedule_event(self, self.time + delay)
                    return False
            host.now = self.time
            worker.active_host = host
            t = self.task
            try:
                t.callback(t.obj, t.arg)   # Task.execute, inlined (hot)
            finally:
                worker.active_host = None
        else:
            t = self.task
            t.callback(t.obj, t.arg)
        return True

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        d = self.dst_host.id if self.dst_host is not None else -1
        return f"Event(t={self.time}, dst={d}, task={self.task.name})"
