"""Simulation time: a nanosecond-resolution virtual clock.

The reference keeps simulation time as an unsigned 64-bit nanosecond counter
(``SimulationTime`` in src/main/core/support/definitions.h:18) and derives an
"emulated" wall-clock time by offsetting from a fixed boot epoch
(definitions.h:78).  We keep the same contract — integer nanoseconds
everywhere, no floats on the clock path — because event-order parity between
the CPU scheduler policies and the batched TPU kernel requires exact integer
arithmetic on both sides.

All values are plain Python ints (arbitrary precision, always exact); device
code uses int64 and the simulator asserts times stay below 2**63.
"""

from __future__ import annotations

# >>> simgen:begin region=clock spec=293c930bb679 body=0992823276f8
# One simulated nanosecond is the base unit.
SIM_TIME_NS = 1
SIM_TIME_US = 1000
SIM_TIME_MS = 1000000
SIM_TIME_SEC = 1000000000
# <<< simgen:end region=clock
SIM_TIME_MIN = 60 * SIM_TIME_SEC
SIM_TIME_HOUR = 3600 * SIM_TIME_SEC

# Sentinels (reference definitions.h: SIMTIME_INVALID / SIMTIME_MAX).
SIM_TIME_INVALID = -1
SIM_TIME_MAX = (1 << 62)  # far future; still safely inside int64

# Emulated Unix epoch offset: simulated time 0 corresponds to this wall-clock
# instant, so plugins asking for the time of day get a plausible date
# (reference definitions.h:78 uses 946684800s = 2000-01-01T00:00:00Z).
EMULATED_TIME_OFFSET = 946_684_800 * SIM_TIME_SEC


def from_seconds(seconds: float) -> int:
    """Convert (possibly fractional) seconds to integer sim-time ns."""
    return int(round(seconds * SIM_TIME_SEC))


def from_millis(ms: float) -> int:
    return int(round(ms * SIM_TIME_MS))


def to_seconds(t: int) -> float:
    return t / SIM_TIME_SEC

def to_millis(t: int) -> float:
    return t / SIM_TIME_MS


def emulated_from_sim(sim_ns: int) -> int:
    """Emulated (wall-clock-looking) ns since the Unix epoch for a sim time."""
    return sim_ns + EMULATED_TIME_OFFSET


def sim_from_emulated(emu_ns: int) -> int:
    return emu_ns - EMULATED_TIME_OFFSET


def is_valid(t: int) -> bool:
    return 0 <= t < SIM_TIME_MAX
