"""Round-boundary state snapshots, state digests, and replay-based resume.

The reference has no checkpointing (SURVEY.md §5: "Checkpoint/resume:
absent").  This module adds the capability the TPU rebuild can offer
cheaply, in three pieces:

* :func:`state_digest` — a deterministic hash over the complete observable
  simulation state (clock, per-host protocol/interface/tracker state,
  pending event queue shape, RNG draw counts).  Two runs are in the same
  state iff their digests match; this is the machine-checkable form of the
  event-order parity metric (BASELINE.json) and is what the cross-policy
  parity tests assert.

* :func:`save_snapshot` / :func:`load_snapshot` — pickle the digestible
  state to disk at round boundaries (``--checkpoint-interval N`` writes
  ``checkpoint_<simsec>.ckpt`` into ``--checkpoint-dir``).  Snapshots are
  for failure diagnosis and cross-run comparison; they deliberately exclude
  live app coroutines and native plugin processes (OS state that cannot be
  serialized — the same reason the reference never checkpointed).

* :func:`resume_digest` — recovery leans on the determinism kernel: re-run
  the same config+seed to the snapshot's time and verify the digest
  matches, then continue.  Deterministic replay makes restart-after-crash
  exact rather than approximate.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
from typing import Dict, Optional

from . import stime


def _socket_state(sock) -> tuple:
    state = (sock.kind, getattr(sock, "state", None),
             sock.bound_ip, sock.bound_port,
             getattr(sock, "peer_ip", None), getattr(sock, "peer_port", None),
             sock.in_bytes, sock.out_bytes)
    if sock.kind == "tcp":
        state += (sock.snd_una, sock.snd_nxt, sock.rcv_nxt, sock.snd_wnd,
                  len(sock.unacked), len(sock.reorder),
                  sock.send_pending_bytes, sock.read_bytes,
                  sock.cong.cwnd if sock.cong is not None else 0)
    return state


def _host_state(host) -> Dict:
    descriptors = {}
    for handle, desc in sorted(host._descriptors.items()):
        if hasattr(desc, "digest_tuple"):  # native-plane sockets: the C
            descriptors[handle] = desc.digest_tuple()  # state IS the state
        elif hasattr(desc, "in_bytes"):  # sockets (tcp/udp/pipe ends)
            descriptors[handle] = _socket_state(desc)
        else:
            descriptors[handle] = (desc.kind, desc.status, desc.closed)
    t = host.tracker
    plane = getattr(host, "native_plane", None)
    if plane is not None:
        plane.sync_tracker(host.id, t)
    # the digest is an observation point: fold the device plane's pending
    # per-node byte deltas (lazily accumulated by its collects) so the
    # snapshot carries the true totals at this boundary
    t.pull_device()
    return {
        "name": host.name,
        "descriptors": descriptors,
        "tracker": (t.in_remote.bytes_total, t.out_remote.bytes_total,
                    t.in_remote.packets_total, t.out_remote.packets_total,
                    t.out_remote.packets_retrans, t.drops),
        "processes": [(p.name, p.running, p.exited, p.exit_code)
                      for p in host.processes],
        "ifaces": plane.iface_digest(host.id) if plane is not None else
                  {ip: (i.send_bucket.bytes_remaining,
                        i.receive_bucket.bytes_remaining)
                   for ip, i in sorted(host.interfaces.items())},
    }


def assemble_state(sim_time_ns: int, rounds: int, host_states: Dict,
                   pending_events) -> Dict:
    """Build the canonical digestible state dict.  Single construction point
    so a sharded run (parallel/procs.py) that gathers ``_host_state`` maps
    from its shard engines produces byte-identical pickles — and therefore
    identical digests — to a single-process run."""
    return {
        "sim_time_ns": sim_time_ns,
        "rounds": rounds,
        "hosts": {hid: host_states[hid] for hid in sorted(host_states)},
        "pending_events": pending_events,
    }


def collect_host_states(engine) -> Dict:
    """Per-host digest states for every host this engine owns: live Host
    objects plus still-quiet table rows (scale/hosttable.py synthesizes
    the identical dict from columns).  Shared by the serial collector
    below and the sharded one (parallel/procs.py)."""
    states = {hid: _host_state(h) for hid, h in engine.hosts.items()
              if engine.owns_host(h)}
    table = getattr(engine, "host_table", None)
    if table is not None:
        states.update(table.host_states())
    return states


def collect_state(engine) -> Dict:
    """The digestible snapshot of everything the simulation has computed."""
    return assemble_state(
        engine.scheduler.window_start,
        engine.rounds_executed,
        collect_host_states(engine),
        engine.scheduler.pending_count()
        if hasattr(engine.scheduler.policy, "pending_count") else None,
    )


def digest_of_state(state: Dict) -> str:
    """Digest over a canonical JSON rendering, NOT the pickle bytes: pickle
    memoizes repeated objects by identity, so two structurally equal states
    can pickle differently depending on which strings happen to be shared
    in-process (a sharded run's states cross a pipe and lose sharing).
    JSON with sorted keys is identity-blind; tuples/lists and int/str dict
    keys normalize uniformly.  No ``default=`` fallback on purpose: a
    non-canonical value (set, object) in a future state field would hash by
    repr — i.e. by address/hash order — and silently reintroduce the
    problem, so it raises instead."""
    blob = json.dumps(state, sort_keys=True,
                      separators=(",", ":")).encode()
    return hashlib.sha256(blob).hexdigest()


def state_digest(engine) -> str:
    """Deterministic hex digest of the current simulation state."""
    return digest_of_state(collect_state(engine))


def save_state(state: Dict, path: str, options_info: Dict) -> str:
    """Stamp ``state`` with its digest + run options and pickle it to disk
    (shared by the engine-side writer and the procs parent).

    The write is atomic (tmp + fsync + rename + DIRECTORY fsync): a run
    SIGKILLed mid-write can never leave a truncated file under the snapshot
    name, and the rename itself is made crash-durable — on ext4 and
    friends, tmp+fsync+rename alone persists the bytes but not necessarily
    the new NAME, so a power cut could forget the snapshot existed.  Every
    file a resume scan sees is therefore complete, named, and durable —
    the property crash recovery leans on."""
    state["digest"] = digest_of_state(state)
    state["options"] = options_info
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        pickle.dump(state, f, protocol=4)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    dirfd = os.open(os.path.dirname(os.path.abspath(path)), os.O_RDONLY)
    try:
        os.fsync(dirfd)
    finally:
        os.close(dirfd)
    return state["digest"]


def save_snapshot(engine, path: str) -> str:
    return save_state(collect_state(engine), path, {
        "seed": engine.options.seed,
        "scheduler_policy": engine.options.scheduler_policy,
        "workers": engine.options.workers,
        "stop_time_sec": engine.options.stop_time_sec,
    })


def load_snapshot(path: str, verify: bool = False) -> Dict:
    """Load a snapshot; ``verify=True`` additionally recomputes the digest
    over the carried state and raises ``ValueError`` on mismatch — the
    defense against a corrupt/tampered file silently seeding a resume.

    Trailing garbage past the pickled payload is TOLERATED with a warning
    (the BENCH_HISTORY.jsonl torn-final-entry pattern): a crash during an
    append-style rewrite can leave a complete snapshot followed by a torn
    partial write, and 'resume from the last GOOD state' means reading the
    complete prefix, not refusing the file.  The digest verification below
    still judges what was actually loaded, so a torn PREFIX (truncated
    pickle) keeps failing loudly."""
    with open(path, "rb") as f:
        snap = pickle.load(f)
        trailing = len(f.read())
    if trailing:
        from .logger import get_logger
        get_logger().warning(
            "checkpoint",
            f"snapshot {path!r}: skipping {trailing} bytes of trailing "
            "garbage after the payload (torn final write tolerated)")
    if not isinstance(snap, dict):
        raise ValueError(f"snapshot {path!r} is corrupt: payload is "
                         f"{type(snap).__name__}, not a state dict")
    if verify:
        core = {k: v for k, v in snap.items()
                if k not in ("digest", "options")}
        if digest_of_state(core) != snap.get("digest"):
            raise ValueError(f"snapshot {path!r} is corrupt: stored digest "
                             "does not match its state")
    return snap


def find_last_good_snapshot(path: str):
    """Resolve ``--resume PATH``: a snapshot file loads (digest-verified);
    a directory yields the newest snapshot that verifies, skipping corrupt
    ones with a logged warning (a crash can outrun fsync on a shared fs;
    'resume from the last GOOD snapshot' is the contract).  Returns
    ``(snapshot, resolved_path)``."""
    from .logger import get_logger
    if os.path.isdir(path):
        # every candidate is loaded + digest-verified (no early exit on
        # name order: the interval- and round-triggered naming schemes
        # interleave, so only the carried sim_time orders them — and a
        # resume happens once per crash, so the full scan is cheap where
        # it matters)
        candidates = [p for p in os.listdir(path) if p.endswith(".ckpt")]
        best = None
        for name in candidates:
            full = os.path.join(path, name)
            try:
                snap = load_snapshot(full, verify=True)
            except (ValueError, OSError, pickle.UnpicklingError, EOFError) as e:
                get_logger().warning(
                    "checkpoint", f"skipping bad snapshot {full}: {e}")
                continue
            if best is None or snap["sim_time_ns"] > best[0]["sim_time_ns"]:
                best = (snap, full)
        if best is None:
            raise FileNotFoundError(
                f"--resume {path!r}: no loadable snapshot found")
        return best
    return load_snapshot(path, verify=True), path


def verify_resume_boundary(snap: Dict, window_start_ns: int, digest: str,
                           domain: str) -> None:
    """The --resume gate, shared by the serial engine and the sharded
    parent: the replay must land on the EXACT round boundary the snapshot
    was written at, in the EXACT state.  A time overshoot or digest
    mismatch means the config/seed diverged from the snapshotted run —
    continuing would silently simulate something else, so abort loudly."""
    if window_start_ns != snap["sim_time_ns"]:
        raise RuntimeError(
            f"--resume verification failed: the replay reached round "
            f"boundary t={window_start_ns / 1e9:.6f}s but the snapshot was "
            f"written at t={snap['sim_time_ns'] / 1e9:.6f}s — the "
            "config/seed does not match the snapshotted run")
    if digest != snap["digest"]:
        raise RuntimeError(
            f"--resume verification failed at "
            f"t={window_start_ns / 1e9:.3f}s: replayed state digest "
            f"{digest[:16]}… != snapshot digest {snap['digest'][:16]}… — "
            "the config/seed does not match the snapshotted run")
    from .logger import get_logger
    get_logger().message(
        domain,
        f"resume verified at t={window_start_ns / 1e9:.3f}s (digest "
        f"{digest[:16]}…): continuing past the snapshot boundary")


def warn_resume_unreached(snap: Dict, domain: str) -> None:
    """Logged at end of run when the snapshot boundary was never reached
    (snapshot time past the run's last round)."""
    from .logger import get_logger
    get_logger().warning(
        domain,
        "--resume snapshot boundary was never reached (snapshot "
        f"t={snap['sim_time_ns'] / 1e9:.3f}s is past this run's last "
        "round) — resume NOT verified")


def resume_digest(snapshot: Dict, engine) -> bool:
    """True iff a replayed engine has reached exactly the snapshot's state
    (call after running the same config+seed to snapshot['sim_time_ns'])."""
    return digest_of_state(collect_state(engine)) == snapshot["digest"]


class CheckpointWriter:
    """Round-boundary snapshot cadence: every ``interval_sec`` of virtual
    time and/or every ``every_rounds`` engine rounds (either may be 0 =
    off).  Engine-agnostic on purpose — the serial engine and the sharded
    parent (parallel/procs.py) share one instance shape, so their write
    boundaries (and therefore snapshot digests) line up exactly.

    ``rounds_done`` everywhere below is the number of COMPLETED rounds at
    the round-boundary hook, i.e. the engine's counter before it increments
    for the current round — the same value the state digest carries."""

    def __init__(self, interval_sec: int, out_dir: str,
                 every_rounds: int = 0):
        self.interval_ns = interval_sec * stime.SIM_TIME_SEC
        self.every_rounds = int(every_rounds)
        self.out_dir = out_dir
        self.next_at = self.interval_ns if interval_sec > 0 else None
        self.next_round = self.every_rounds if self.every_rounds > 0 else None
        self.written = []

    def due(self, window_start_ns: int, rounds_done: int) -> bool:
        """True iff this round boundary writes — checked by the engine
        BEFORE forcing an early flush consume, so a checkpointing run keeps
        the async launch/consume overlap on all the rounds that don't
        actually write."""
        if self.next_at is not None and window_start_ns >= self.next_at:
            return True
        return (self.next_round is not None
                and rounds_done + 1 >= self.next_round)

    def path_for(self, window_start_ns: int, rounds_done: int) -> str:
        """Zero-padded so lexicographic and chronological order agree.
        Round-triggered writes are stamped with the round number (several
        rounds can share one sim-second); interval writes keep the
        sim-second name."""
        if self.next_round is not None and rounds_done + 1 >= self.next_round:
            return os.path.join(self.out_dir,
                                f"checkpoint_r{rounds_done + 1:08d}.ckpt")
        sim_sec = window_start_ns // stime.SIM_TIME_SEC
        return os.path.join(self.out_dir, f"checkpoint_{sim_sec:08d}.ckpt")

    def mark_written(self, window_start_ns: int, rounds_done: int,
                     path: str) -> None:
        self.written.append(path)
        while self.next_at is not None and self.next_at <= window_start_ns:
            self.next_at += self.interval_ns
        while self.next_round is not None \
                and self.next_round <= rounds_done + 1:
            self.next_round += self.every_rounds

    def maybe_write(self, engine) -> Optional[str]:
        """Engine-side convenience: write if due, return the path."""
        now = engine.scheduler.window_start
        rounds = engine.rounds_executed
        if not self.due(now, rounds):
            return None
        os.makedirs(self.out_dir, exist_ok=True)
        path = self.path_for(now, rounds)
        save_snapshot(engine, path)
        self.mark_written(now, rounds, path)
        return path
