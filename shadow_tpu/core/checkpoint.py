"""Round-boundary state snapshots, state digests, and replay-based resume.

The reference has no checkpointing (SURVEY.md §5: "Checkpoint/resume:
absent").  This module adds the capability the TPU rebuild can offer
cheaply, in three pieces:

* :func:`state_digest` — a deterministic hash over the complete observable
  simulation state (clock, per-host protocol/interface/tracker state,
  pending event queue shape, RNG draw counts).  Two runs are in the same
  state iff their digests match; this is the machine-checkable form of the
  event-order parity metric (BASELINE.json) and is what the cross-policy
  parity tests assert.

* :func:`save_snapshot` / :func:`load_snapshot` — pickle the digestible
  state to disk at round boundaries (``--checkpoint-interval N`` writes
  ``checkpoint_<simsec>.ckpt`` into ``--checkpoint-dir``).  Snapshots are
  for failure diagnosis and cross-run comparison; they deliberately exclude
  live app coroutines and native plugin processes (OS state that cannot be
  serialized — the same reason the reference never checkpointed).

* :func:`resume_digest` — recovery leans on the determinism kernel: re-run
  the same config+seed to the snapshot's time and verify the digest
  matches, then continue.  Deterministic replay makes restart-after-crash
  exact rather than approximate.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
from typing import Dict, Optional

from . import stime


def _socket_state(sock) -> tuple:
    state = (sock.kind, getattr(sock, "state", None),
             sock.bound_ip, sock.bound_port,
             getattr(sock, "peer_ip", None), getattr(sock, "peer_port", None),
             sock.in_bytes, sock.out_bytes)
    if sock.kind == "tcp":
        state += (sock.snd_una, sock.snd_nxt, sock.rcv_nxt, sock.snd_wnd,
                  len(sock.unacked), len(sock.reorder),
                  sock.send_pending_bytes, sock.read_bytes,
                  sock.cong.cwnd if sock.cong is not None else 0)
    return state


def _host_state(host) -> Dict:
    descriptors = {}
    for handle, desc in sorted(host._descriptors.items()):
        if hasattr(desc, "digest_tuple"):  # native-plane sockets: the C
            descriptors[handle] = desc.digest_tuple()  # state IS the state
        elif hasattr(desc, "in_bytes"):  # sockets (tcp/udp/pipe ends)
            descriptors[handle] = _socket_state(desc)
        else:
            descriptors[handle] = (desc.kind, desc.status, desc.closed)
    t = host.tracker
    plane = getattr(host, "native_plane", None)
    if plane is not None:
        plane.sync_tracker(host.id, t)
    return {
        "name": host.name,
        "descriptors": descriptors,
        "tracker": (t.in_remote.bytes_total, t.out_remote.bytes_total,
                    t.in_remote.packets_total, t.out_remote.packets_total,
                    t.out_remote.packets_retrans, t.drops),
        "processes": [(p.name, p.running, p.exited, p.exit_code)
                      for p in host.processes],
        "ifaces": plane.iface_digest(host.id) if plane is not None else
                  {ip: (i.send_bucket.bytes_remaining,
                        i.receive_bucket.bytes_remaining)
                   for ip, i in sorted(host.interfaces.items())},
    }


def assemble_state(sim_time_ns: int, rounds: int, host_states: Dict,
                   pending_events) -> Dict:
    """Build the canonical digestible state dict.  Single construction point
    so a sharded run (parallel/procs.py) that gathers ``_host_state`` maps
    from its shard engines produces byte-identical pickles — and therefore
    identical digests — to a single-process run."""
    return {
        "sim_time_ns": sim_time_ns,
        "rounds": rounds,
        "hosts": {hid: host_states[hid] for hid in sorted(host_states)},
        "pending_events": pending_events,
    }


def collect_state(engine) -> Dict:
    """The digestible snapshot of everything the simulation has computed."""
    return assemble_state(
        engine.scheduler.window_start,
        engine.rounds_executed,
        {hid: _host_state(h) for hid, h in engine.hosts.items()
         if engine.owns_host(h)},
        engine.scheduler.policy.pending_count()
        if hasattr(engine.scheduler.policy, "pending_count") else None,
    )


def digest_of_state(state: Dict) -> str:
    """Digest over a canonical JSON rendering, NOT the pickle bytes: pickle
    memoizes repeated objects by identity, so two structurally equal states
    can pickle differently depending on which strings happen to be shared
    in-process (a sharded run's states cross a pipe and lose sharing).
    JSON with sorted keys is identity-blind; tuples/lists and int/str dict
    keys normalize uniformly.  No ``default=`` fallback on purpose: a
    non-canonical value (set, object) in a future state field would hash by
    repr — i.e. by address/hash order — and silently reintroduce the
    problem, so it raises instead."""
    blob = json.dumps(state, sort_keys=True,
                      separators=(",", ":")).encode()
    return hashlib.sha256(blob).hexdigest()


def state_digest(engine) -> str:
    """Deterministic hex digest of the current simulation state."""
    return digest_of_state(collect_state(engine))


def save_state(state: Dict, path: str, options_info: Dict) -> str:
    """Stamp ``state`` with its digest + run options and pickle it to disk
    (shared by the engine-side writer and the procs parent)."""
    state["digest"] = digest_of_state(state)
    state["options"] = options_info
    with open(path, "wb") as f:
        pickle.dump(state, f, protocol=4)
    return state["digest"]


def save_snapshot(engine, path: str) -> str:
    return save_state(collect_state(engine), path, {
        "seed": engine.options.seed,
        "scheduler_policy": engine.options.scheduler_policy,
        "workers": engine.options.workers,
        "stop_time_sec": engine.options.stop_time_sec,
    })


def load_snapshot(path: str) -> Dict:
    with open(path, "rb") as f:
        return pickle.load(f)


def resume_digest(snapshot: Dict, engine) -> bool:
    """True iff a replayed engine has reached exactly the snapshot's state
    (call after running the same config+seed to snapshot['sim_time_ns'])."""
    return digest_of_state(collect_state(engine)) == snapshot["digest"]


class CheckpointWriter:
    """Engine-side round-boundary hook: writes a snapshot every
    ``interval_sec`` of virtual time into ``out_dir``."""

    def __init__(self, interval_sec: int, out_dir: str):
        self.interval_ns = interval_sec * stime.SIM_TIME_SEC
        self.out_dir = out_dir
        self.next_at = self.interval_ns
        self.written = []

    def due(self, engine) -> bool:
        """True iff maybe_write would snapshot this round — checked by the
        engine BEFORE forcing an early flush consume, so a run with
        --checkpoint-interval keeps the async launch/consume overlap on all
        the rounds that don't actually write."""
        return engine.scheduler.window_start >= self.next_at

    def maybe_write(self, engine) -> Optional[str]:
        now = engine.scheduler.window_start
        if now < self.next_at:
            return None
        os.makedirs(self.out_dir, exist_ok=True)
        sim_sec = now // stime.SIM_TIME_SEC
        # zero-padded so lexicographic and chronological order agree
        path = os.path.join(self.out_dir, f"checkpoint_{sim_sec:08d}.ckpt")
        save_snapshot(engine, path)
        self.written.append(path)
        while self.next_at <= now:
            self.next_at += self.interval_ns
        return path
