"""Round-boundary state snapshots, state digests, and replay-based resume.

The reference has no checkpointing (SURVEY.md §5: "Checkpoint/resume:
absent").  This module adds the capability the TPU rebuild can offer
cheaply, in three pieces:

* :func:`state_digest` — a deterministic hash over the complete observable
  simulation state (clock, per-host protocol/interface/tracker state,
  pending event queue shape, RNG draw counts).  Two runs are in the same
  state iff their digests match; this is the machine-checkable form of the
  event-order parity metric (BASELINE.json) and is what the cross-policy
  parity tests assert.

* :func:`save_snapshot` / :func:`load_snapshot` — pickle the digestible
  state to disk at round boundaries (``--checkpoint-interval N`` writes
  ``checkpoint_<simsec>.ckpt`` into ``--checkpoint-dir``).  Snapshots are
  for failure diagnosis and cross-run comparison; they deliberately exclude
  live app coroutines and native plugin processes (OS state that cannot be
  serialized — the same reason the reference never checkpointed).

* :func:`resume_digest` — recovery leans on the determinism kernel: re-run
  the same config+seed to the snapshot's time and verify the digest
  matches, then continue.  Deterministic replay makes restart-after-crash
  exact rather than approximate.
"""

from __future__ import annotations

import hashlib
import os
import pickle
from typing import Dict, Optional

from . import stime


def _socket_state(sock) -> tuple:
    state = (sock.kind, getattr(sock, "state", None),
             sock.bound_ip, sock.bound_port,
             getattr(sock, "peer_ip", None), getattr(sock, "peer_port", None),
             sock.in_bytes, sock.out_bytes)
    if sock.kind == "tcp":
        state += (sock.snd_una, sock.snd_nxt, sock.rcv_nxt, sock.snd_wnd,
                  len(sock.unacked), len(sock.reorder),
                  sock.send_pending_bytes, sock.read_bytes,
                  sock.cong.cwnd if sock.cong is not None else 0)
    return state


def _host_state(host) -> Dict:
    descriptors = {}
    for handle, desc in sorted(host._descriptors.items()):
        if hasattr(desc, "in_bytes"):  # sockets (tcp/udp/pipe ends)
            descriptors[handle] = _socket_state(desc)
        else:
            descriptors[handle] = (desc.kind, desc.status, desc.closed)
    t = host.tracker
    return {
        "name": host.name,
        "descriptors": descriptors,
        "tracker": (t.in_remote.bytes_total, t.out_remote.bytes_total,
                    t.in_remote.packets_total, t.out_remote.packets_total,
                    t.out_remote.packets_retrans, t.drops),
        "processes": [(p.name, p.running, p.exited, p.exit_code)
                      for p in host.processes],
        "ifaces": {ip: (i.send_bucket.bytes_remaining, i.receive_bucket.bytes_remaining)
                   for ip, i in sorted(host.interfaces.items())},
    }


def collect_state(engine) -> Dict:
    """The digestible snapshot of everything the simulation has computed."""
    return {
        "sim_time_ns": engine.scheduler.window_start,
        "rounds": engine.rounds_executed,
        "hosts": {hid: _host_state(h) for hid, h in sorted(engine.hosts.items())},
        "pending_events": engine.scheduler.policy.pending_count()
        if hasattr(engine.scheduler.policy, "pending_count") else None,
    }


def state_digest(engine) -> str:
    """Deterministic hex digest of the current simulation state."""
    blob = pickle.dumps(collect_state(engine), protocol=4)
    return hashlib.sha256(blob).hexdigest()


def save_snapshot(engine, path: str) -> str:
    state = collect_state(engine)
    state["digest"] = hashlib.sha256(
        pickle.dumps(state, protocol=4)).hexdigest()
    state["options"] = {
        "seed": engine.options.seed,
        "scheduler_policy": engine.options.scheduler_policy,
        "workers": engine.options.workers,
        "stop_time_sec": engine.options.stop_time_sec,
    }
    with open(path, "wb") as f:
        pickle.dump(state, f, protocol=4)
    return state["digest"]


def load_snapshot(path: str) -> Dict:
    with open(path, "rb") as f:
        return pickle.load(f)


def resume_digest(snapshot: Dict, engine) -> bool:
    """True iff a replayed engine has reached exactly the snapshot's state
    (call after running the same config+seed to snapshot['sim_time_ns'])."""
    current = collect_state(engine)
    blob = pickle.dumps(current, protocol=4)
    return hashlib.sha256(blob).hexdigest() == snapshot["digest"]


class CheckpointWriter:
    """Engine-side round-boundary hook: writes a snapshot every
    ``interval_sec`` of virtual time into ``out_dir``."""

    def __init__(self, interval_sec: int, out_dir: str):
        self.interval_ns = interval_sec * stime.SIM_TIME_SEC
        self.out_dir = out_dir
        self.next_at = self.interval_ns
        self.written = []

    def maybe_write(self, engine) -> Optional[str]:
        now = engine.scheduler.window_start
        if now < self.next_at:
            return None
        os.makedirs(self.out_dir, exist_ok=True)
        sim_sec = now // stime.SIM_TIME_SEC
        # zero-padded so lexicographic and chronological order agree
        path = os.path.join(self.out_dir, f"checkpoint_{sim_sec:08d}.ckpt")
        save_snapshot(engine, path)
        self.written.append(path)
        while self.next_at <= now:
            self.next_at += self.interval_ns
        return path
