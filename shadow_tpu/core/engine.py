"""Engine: the per-machine simulation engine (reference Slave, core/slave.c).

Owns the Scheduler, host registry, DNS, program registry, data directories,
object counters, and the round loop (slave_run :413, round loop :437-462):

    while events remain:
        window = [min_next_event_time, +lookahead)
        workers drain their queues up to the window end     (parallel)
        flush logger, heartbeat                              (main thread)
        compute next window from global min next event time

Multi-worker execution uses Python threads with two CountDownLatch barriers
per round (the reference uses five; ours fold the start/prepare pairs).
"""

from __future__ import annotations

import os
import resource
import threading
import time as _walltime
from typing import Dict, List, Optional

from ..routing.dns import DNS
from ..utils.count_down_latch import CountDownLatch
from . import stime
from .counters import ObjectCounter
from .logger import get_logger
from .rng import RandomSource, derive, uniform_np
from .scheduler import Scheduler
from .task import Task
from .worker import Worker, current_worker, set_current_worker


def _tracker_sweep_task(args, _unused) -> None:
    """The per-interval heartbeat tick: RECORD the due sweep and reschedule.
    The tracker work itself runs at the round boundary (_flush_round, main
    thread, workers parked) — an in-event sweep over ALL hosts would race
    the other workers' event execution on those hosts' trackers, which the
    retired per-host heartbeat events never did (they ran under each
    host's own execution serialization)."""
    engine, interval_sec = args
    w = current_worker()
    engine._pending_sweeps.append((interval_sec,
                                   w.now if w is not None else 0))
    if w is not None:
        w.schedule_task(Task(_tracker_sweep_task, (engine, interval_sec),
                             None, name="heartbeat"),
                        interval_sec * stime.SIM_TIME_SEC, dst_host=None)

DEFAULT_LOOKAHEAD_NS = 10 * stime.SIM_TIME_MS  # master.c:133-146 default jump


class Engine:
    def __init__(self, options, topology, seed_key: Optional[int] = None):
        self.options = options
        self.topology = topology
        # observability plane (shadow_tpu/obs/): installed module-global
        # like the logger, FIRST, so everything built below (scheduler,
        # native plane, device plane, plugins) binds the run's tracer
        from ..obs import configure_observability
        self.tracer, self.metrics, self._metrics_writer = \
            configure_observability(options)
        self.root_key = seed_key if seed_key is not None else derive(options.seed, "root")
        self.dns = DNS()
        self.random = RandomSource(derive(self.root_key, "engine"))
        self.hosts: Dict[int, object] = {}          # id -> Host
        self.hosts_by_ip: Dict[int, object] = {}
        self.hosts_by_name: Dict[str, object] = {}
        self.end_time = options.stop_time_sec * stime.SIM_TIME_SEC
        self.bootstrap_end = options.bootstrap_end_sec * stime.SIM_TIME_SEC
        self.counters = ObjectCounter()
        self._counters_lock = threading.Lock()
        self.plugin_errors = 0
        self.data_directory = options.data_directory
        # --data-template: seed the data directory from a template tree
        # (reference slave.c:201-218 copies dataDirTemplatePath)
        template = getattr(options, "data_template", None)
        if template:
            if not os.path.isdir(template):
                raise FileNotFoundError(
                    f"--data-template {template!r} is not a directory")
            if os.path.exists(self.data_directory):
                get_logger().warning(
                    "engine",
                    f"--data-template ignored: data directory "
                    f"{self.data_directory!r} already exists (delete it to "
                    "re-seed from the template)")
            else:
                import shutil
                shutil.copytree(template, self.data_directory)
        self.scheduler = Scheduler(self, options.scheduler_policy,
                                   options.workers, derive(self.root_key, "sched"))
        self._drop_key = derive(self.root_key, "packet_drop")
        # Process-parallel sharding (parallel/procs.py): this engine OWNS
        # hosts with (id-1) % shard_count == shard_id and executes only their
        # events; packets bound for other shards are appended to per-shard
        # outboxes drained at the round barrier.  shard_count == 1 (the
        # default) means everything below is inert.
        self.shard_id = int(getattr(options, "shard_id", 0) or 0)
        self.shard_count = max(1, int(getattr(options, "shard_count", 1) or 1))
        self.shard_outboxes: List[list] = [[] for _ in range(self.shard_count)]
        self._global_seq = 0
        self._running = True
        self._host_id_counter = 0
        self.sim_start_wall: float = 0.0
        self.rounds_executed = 0
        self.events_executed = 0
        # per-round perf introspection (reference logs per-thread barrier
        # waits + Dijkstra timings, scheduler.c:266-268 / topology.c:1785-88;
        # ours splits each round into host-execute vs flush/device wall time)
        self.host_exec_ns = 0
        self.flush_ns = 0
        # compacted-flush dirty tracking (ISSUE 10): rounds whose whole
        # flush phase (policy flush + checkpoint + logger) did no work,
        # and what those quiet rounds still cost — the bench-smoke gate
        # pins the per-quiet-round cost ~zero
        self.flush_quiet_skips = 0
        self.flush_quiet_ns = 0
        # heartbeat sweeps due this round: (interval_sec, tick sim time),
        # recorded by the tick event (worker 0) and drained at the round
        # boundary by _flush_round on the main thread — the round latch
        # orders the append before the drain
        self._pending_sweeps: List = []
        # wall ns spent resuming plugin code (green-thread continues +
        # native RPC serving), accumulated under _counters_lock from
        # process/process.py — subtracted from host_exec for the
        # plugin-vs-control-plane split the perf hunt steers by
        self.plugin_exec_ns = 0
        self._last_heartbeat_wall = 0.0
        self.heartbeat_wall_interval = 5.0
        # adaptive heartbeat gate: between wall reads the per-round cost is
        # one integer decrement (the monotonic() syscall per round was
        # measurable at tor10k round rates)
        self._hb_countdown = 0
        self._hb_stride = 1
        self._hb_last_check = 0.0
        # superwindow negotiation (ISSUE 7): how many consecutive lookahead
        # rounds one device-plane launch may merge when no host-side event
        # falls inside them; 1 disables
        self._superwindow = max(
            1, int(getattr(options, "superwindow_rounds", 8) or 1))
        # device-resident traffic plane (parallel/device_plane.py); set by
        # the Controller when the workload has device-mode flows
        self.device_plane = None
        # C data plane (parallel/native_plane.py); set by attach() when the
        # run is eligible — protocol/interface/hop events then execute in C
        self.native_plane = None
        # struct-of-arrays host plane (scale/hosttable.py); set by the
        # Controller when hosts boot as table rows — quiet hosts then cost
        # array columns, and Host objects materialize lazily on their
        # first boot event or incoming lookup
        self.host_table = None
        self._boot_done = False
        # supervision ledger: watchdog fires, degradations, resume state
        # (core/supervision.py) — every fault seam reports here
        from .supervision import SupervisionStats
        self.supervision = SupervisionStats()
        self._checkpointer = None
        if getattr(options, "checkpoint_interval_sec", 0) > 0 \
                or getattr(options, "checkpoint_every_rounds", 0) > 0:
            from .checkpoint import CheckpointWriter
            self._checkpointer = CheckpointWriter(
                options.checkpoint_interval_sec, options.checkpoint_dir,
                getattr(options, "checkpoint_every_rounds", 0))
        # --resume: deterministic replay to the snapshot's virtual time,
        # digest-verified there (_verify_resume), then the run continues —
        # recovery leans on the determinism kernel, so restart-after-crash
        # is exact rather than approximate
        self._resume_snapshot = None
        resume = getattr(options, "resume_path", None)
        if resume:
            from .checkpoint import find_last_good_snapshot
            snap, resolved = find_last_good_snapshot(resume)
            self._resume_snapshot = snap
            self.supervision.resume_path = resolved
            get_logger().message(
                "engine",
                f"resuming from {resolved} "
                f"(t={snap['sim_time_ns'] / 1e9:.3f}s, "
                f"rounds={snap['rounds']}): replaying to the snapshot "
                "boundary, digest-verified there")
        # metrics sources: the engine's phase split, the policy/kernel and
        # plane introspection, and the supervision ledger all scrape from
        # ONE registry — bench.py reads flush_sec / device_wait_sec /
        # pipeline_overlap_sec here instead of re-deriving them with
        # ad-hoc timers per run
        self.metrics.source("engine", self._scrape_metrics)
        self.metrics.source(
            "supervision",
            lambda: {f"supervision.{k}": v
                     for k, v in self.supervision.summary().items()})
        self.metrics.gauge(
            "engine.wall_uptime_sec",
            lambda: round(_walltime.monotonic() - self.sim_start_wall, 3))
        self._checkpoint_counter = self.metrics.counter(
            "engine.checkpoints_written")

    # -- registry ----------------------------------------------------------
    def add_host(self, host, requested_ip: Optional[int] = None) -> None:
        """Register + set up a host (slave_addNewVirtualHost :296)."""
        addr = self.dns.register(host.id, host.name, requested_ip)
        if not self.owns_host(host):
            # replica on another shard's engine: opening its pcap file here
            # would truncate the owner's capture (N processes, same path)
            host.params.log_pcap = False
        host.setup(self, addr)
        vidx = self.topology.attach_host(
            addr.ip, ip_hint=host.params.ip_hint, city_hint=host.params.city_hint,
            country_hint=host.params.country_hint,
            geocode_hint=host.params.geocode_hint, type_hint=host.params.type_hint,
            choice_rand=host.random.next_u64())
        # fill in bandwidths from the topology vertex if unset (master.c:336-377)
        if host.params.bw_down_kibps <= 0 or host.params.bw_up_kibps <= 0:
            down, up = self.topology.vertex_bandwidth_kibps(vidx)
            if host.params.bw_down_kibps <= 0:
                host.params.bw_down_kibps = down or 102400
            if host.params.bw_up_kibps <= 0:
                host.params.bw_up_kibps = up or 102400
            # rebuild the eth token buckets with resolved rates
            eth = host.interfaces[addr.ip]
            from ..host.network_interface import TokenBucket
            eth.send_bucket = TokenBucket(host.params.bw_up_kibps)
            eth.receive_bucket = TokenBucket(host.params.bw_down_kibps)
        # cache the topology matrix row so the hot path never does the
        # ip->row dict lookup per packet (rows are fixed at attach time)
        host.topo_row = self.topology.row_for_ip(addr.ip)
        self.hosts[host.id] = host
        self.hosts_by_ip[addr.ip] = host
        self.hosts_by_name[host.name] = host
        self.scheduler.add_host(host)
        if self.owns_host(host):
            self.counters.count_new("host")

    def adopt_host(self, host, addr, owned: bool = True) -> None:
        """Register a host whose DNS entry and topology attachment already
        happened at table-reserve time (scale/hosttable.py materialize):
        the add_host tail without re-registering or re-attaching.  The
        caller provides params with RESOLVED bandwidths, so no bucket
        rebuild is needed either."""
        if not owned:
            host.params.log_pcap = False    # replica: owner holds the pcap
        host.setup(self, addr)
        self.hosts[host.id] = host
        self.hosts_by_ip[addr.ip] = host
        self.hosts_by_name[host.name] = host
        self.scheduler.add_host(host)
        if owned:
            with self._counters_lock:
                self.counters.count_new("host")

    def next_host_id(self) -> int:
        self._host_id_counter += 1
        return self._host_id_counter

    def total_host_count(self) -> int:
        """Materialized hosts + still-quiet table rows."""
        n = len(self.hosts)
        if self.host_table is not None:
            n += self.host_table.unmaterialized_count()
        return n

    def host_by_ip(self, ip: int):
        h = self.hosts_by_ip.get(ip)
        if h is None and self.host_table is not None:
            # a packet (or policy delivery) reached a quiet table row:
            # materialize it so routers/RST paths behave exactly as the
            # eager host would
            h = self.host_table.materialize_by_ip(ip)
        return h

    def shard_of(self, host) -> int:
        """The single definition of the host partition (round-robin by id);
        owns_host and every outbox index derive from it."""
        return (host.id - 1) % self.shard_count

    def owns_host(self, host) -> bool:
        """True iff this engine executes ``host``'s events (every host in a
        single-process run; the shard's partition under --processes N)."""
        return self.shard_count == 1 or self.shard_of(host) == self.shard_id

    def drain_outboxes(self) -> List[list]:
        out = self.shard_outboxes
        self.shard_outboxes = [[] for _ in range(self.shard_count)]
        return out

    def host_by_name(self, name: str):
        h = self.hosts_by_name.get(name)
        if h is None and self.host_table is not None:
            h = self.host_table.materialize_by_name(name)
        return h

    def host_by_id(self, hid: int):
        h = self.hosts.get(hid)
        if h is None and self.host_table is not None:
            h = self.host_table.materialize_by_id(hid)
        return h

    def iter_process_specs(self):
        """(host_id, host_name, app_path, args) over every configured
        process — live Host objects and deferred table rows alike, in
        host-id order.  The device plane's spec scan uses this so table-on
        and table-off builds see identical workloads."""
        specs = []
        for hid in sorted(self.hosts):
            host = self.hosts[hid]
            for proc in host.processes:
                specs.append((hid, host.name,
                              str(getattr(proc, "app_path", "")), proc.args))
        if self.host_table is not None:
            specs.extend(self.host_table.iter_process_specs())
        specs.sort(key=lambda s: s[0])
        return specs

    def host_stream_key(self, name: str) -> Optional[int]:
        """The per-host deterministic RNG stream key (what Host.random is
        seeded with), WITHOUT materializing a table row — derivation is
        arithmetic on (root_key, host id)."""
        h = self.hosts_by_name.get(name)
        if h is not None:
            return h.random.key
        if self.host_table is not None:
            row = self.host_table.row_of_name(name)
            if row is not None:
                return int(self.host_table.rng_keys[row])
        return None

    # -- deterministic draws ----------------------------------------------
    def packet_drop_uniform(self, packet_uid: int) -> float:
        """Order-independent drop draw keyed by packet uid (shared with the
        TPU kernel; see ops/round_step.py)."""
        import numpy as np
        return float(uniform_np(self._drop_key, np.uint64(packet_uid)))

    def count_packet_drop(self, packet) -> None:
        self.counters.count_new("packet_drop")

    # -- misc --------------------------------------------------------------
    def is_running(self) -> bool:
        return self._running

    def next_global_sequence(self) -> int:
        self._global_seq += 1
        return self._global_seq

    def merge_counters(self, c: ObjectCounter) -> None:
        with self._counters_lock:
            self.counters.merge(c)

    def increment_plugin_error(self) -> None:
        self.plugin_errors += 1

    def add_plugin_exec_ns(self, ns: int) -> None:
        """Accumulate plugin-execution wall time (called once per
        process-continue / RPC leg, from worker threads on threaded
        schedulers — hence the lock)."""
        with self._counters_lock:
            self.plugin_exec_ns += ns

    @property
    def lookahead_ns(self) -> int:
        if self.options.runahead_ms > 0:
            return self.options.runahead_ms * stime.SIM_TIME_MS
        m = getattr(self.topology, "min_latency_ns", 0)
        if 0 < m < stime.SIM_TIME_MAX:
            return m
        return DEFAULT_LOOKAHEAD_NS

    # -- observability -----------------------------------------------------
    def _scrape_metrics(self) -> Dict:
        """The 'engine' metrics source: phase wall split + policy/kernel +
        plane + native-plane introspection, one flat namespace."""
        with self._counters_lock:
            plugin_ns = self.plugin_exec_ns
        out = {
            "engine.rounds": self.rounds_executed,
            "engine.events": self.events_executed,
            "engine.host_exec_sec": round(self.host_exec_ns / 1e9, 4),
            # the host_exec split (ISSUE 7): wall spent resuming plugin
            # code vs everything else on the round path (event dispatch,
            # scheduler, protocol control plane) — the number that says
            # whether the remaining wall is app work or engine overhead
            "engine.host_exec_plugin_sec": round(plugin_ns / 1e9, 4),
            "engine.host_exec_ctrl_sec": round(
                max(self.host_exec_ns - plugin_ns, 0) / 1e9, 4),
            "engine.flush_sec": round(self.flush_ns / 1e9, 4),
            "engine.flush_quiet_skips": self.flush_quiet_skips,
            "engine.flush_quiet_sec": round(self.flush_quiet_ns / 1e9, 4),
        }
        pol = self.scheduler.policy
        if hasattr(pol, "device_ns"):       # tpu policy phase timers
            out["policy.device_wait_sec"] = round(pol.device_ns / 1e9, 4)
            out["policy.flush_host_sec"] = round(pol.host_flush_ns / 1e9, 4)
        kern = getattr(pol, "_kernel", None)
        if kern is not None:
            out["policy.device_calls"] = kern.device_calls
            out["policy.host_calls"] = kern.host_calls
        if self.device_plane is not None:
            out.update({f"plane.{k}": v
                        for k, v in self.device_plane.stats().items()})
        if self.native_plane is not None:
            sched, execd, drops, _last = self.native_plane.counters()
            out["native.events_scheduled"] = sched
            out["native.events_executed"] = execd
            out["native.drops"] = drops
            pol = self.scheduler.policy
            if hasattr(pol, "round_windows"):
                # C round executor engagement (ISSUE 10): windows driven
                # by ONE extension call, and whether a failure demoted the
                # executor back to the per-event path
                out["native.round_windows"] = pol.round_windows
                out["native.round_demoted"] = int(pol.round_demoted)
                out["native.round_repromoted"] = int(
                    getattr(pol, "round_repromoted", False))
            # batched continuation plane (ISSUE 12): green-thread resumes
            # delivered per py_exec_batch call vs one-callback-each
            # (getattr: test stand-in planes predate the ledger)
            np_ = self.native_plane
            batches = getattr(np_, "py_exec_batch_calls", 0)
            fused = getattr(np_, "continuations_fused", 0)
            out["native.py_exec_batch_calls"] = batches
            out["native.continuations_fused"] = fused
            out["native.continuations_single"] = getattr(
                np_, "continuations_single", 0)
            out["native.continuation_batch_size"] = round(
                fused / max(batches, 1), 2)
        return out

    def _obs_round_end(self) -> None:
        """Round-cadence observability hook (both run loops): scrape the
        registry to the JSONL stream when due.  One None-check per round
        when metrics are off."""
        if self._metrics_writer is not None:
            self._metrics_writer.maybe_write(self.metrics,
                                             self.rounds_executed,
                                             self.scheduler.window_start)

    def _obs_emergency(self) -> None:
        """Crash-path observability: export whatever the flight recorder
        holds and close the metrics stream with a summary.  Every step is
        best-effort — this runs while an exception is propagating and must
        never mask it."""
        try:
            if self.tracer.enabled and self.shard_count == 1:
                path = self.tracer.export()
                if path:
                    get_logger().warning(
                        "engine",
                        f"flight recorder exported after abnormal "
                        f"termination: {path}")
            if self._metrics_writer is not None:
                self._metrics_writer.write_summary(
                    self.metrics, self.rounds_executed,
                    self.scheduler.window_start)
            get_logger().flush()
        except Exception:
            pass

    def _obs_finish(self) -> None:
        """End-of-run observability: final metrics summary (carrying the
        ObjectCounter leak report + supervision ledger + plane stats) and
        the trace export.  Shard engines skip the export — their rings are
        drained over the procs protocol and merged by the parent."""
        if self._metrics_writer is not None:
            # final tracker sweep: one closing heartbeat per host so the
            # summary's tracker.* aggregates (and the last legacy log
            # sample tools parse) reflect END-of-run totals, not the last
            # sim-gated heartbeat's.  Under the native plane the sweep's
            # counter reads come from ONE bulk C snapshot, not a C
            # round-trip per host (ISSUE 7 control-plane cut).
            from contextlib import nullcontext
            ctx = self.native_plane.bulk_sync() \
                if self.native_plane is not None else nullcontext()
            with ctx:
                for hid in sorted(self.hosts):
                    host = self.hosts[hid]
                    if self.owns_host(host):
                        host.tracker.heartbeat(self.scheduler.window_start)
            for key, val in self.counters.summary().items():
                self.metrics.set_summary_info(key, val)
            self._metrics_writer.write_summary(self.metrics,
                                               self.rounds_executed,
                                               self.scheduler.window_start)
            get_logger().message(
                "engine",
                f"metrics written: {self._metrics_writer.path} "
                f"({self._metrics_writer.records_written} records)")
        if self.tracer.enabled and self.shard_count == 1:
            path = self.tracer.export()
            if path:
                get_logger().message("engine", f"trace written: {path}")

    # -- boot events -------------------------------------------------------
    def schedule_boot(self) -> None:
        """Host boots + process starts at t=0 (host_boot :372-390)."""
        # commit the host->worker assignment (seeded Fisher-Yates shuffle,
        # reference scheduler.c:437-472) now that every host is registered
        self.scheduler.finalize_hosts()
        boot_worker = Worker(0, self)
        set_current_worker(boot_worker)
        try:
            for hid in sorted(self.hosts):
                host = self.hosts[hid]
                if not self.owns_host(host):
                    # replica of a host another shard executes: it exists so
                    # DNS/topology/addressing resolve identically, but it
                    # boots (and runs) only on its owner
                    continue
                boot_worker.set_active_host(host)
                host.boot()
                for proc in host.processes:
                    proc.schedule_start(boot_worker)
                boot_worker.set_active_host(None)
            self._schedule_heartbeat_sweeps(boot_worker)
        finally:
            set_current_worker(None)
        self.merge_counters(boot_worker.counters)
        # table rows boot lazily from here on: a row materialized after
        # this point replays this exact sequence for itself
        self._boot_done = True

    def _schedule_heartbeat_sweeps(self, worker) -> None:
        """ONE recurring sweep event per distinct per-host heartbeat
        interval replaces the per-host heartbeat events (ISSUE 10 batched
        control plane): at each tick the sweep heartbeats every owned host
        on that interval in one pass — under the native plane through ONE
        bulk C tracker snapshot — so a 10k-host run pays one event + one
        extension call per interval, not 10k events with a C round-trip
        each.  Log lines keep the same sim-time stamps and global host-id
        order; the VALUES are sampled at the tick's round boundary (the
        sweep drains there, workers parked) rather than the tick's exact
        slot in the event order, so they can include up to one lookahead
        window of post-tick traffic — deterministic, and fresher, but not
        bit-equal to the retired per-host events' mid-round samples."""
        intervals = {h.params.heartbeat_interval_sec
                     for h in self.hosts.values()
                     if self.owns_host(h)
                     and h.params.heartbeat_interval_sec > 0}
        if self.host_table is not None:
            intervals |= self.host_table.heartbeat_intervals()
        for sec in sorted(intervals):
            worker.schedule_task(
                Task(_tracker_sweep_task, (self, sec), None,
                     name="heartbeat"),
                sec * stime.SIM_TIME_SEC, dst_host=None)

    def run_tracker_sweep(self, interval_sec: int, now: int) -> None:
        """One heartbeat sweep tick, run at the round boundary (workers
        parked — no tracker races): heartbeat every owned host on this
        interval in GLOBAL host-id order, quiet table rows merged in place
        (reported from columns, never materialized), with ONE bulk C
        tracker snapshot when the native plane is attached.  Quiet hosts
        pay the prev==row dirty check inside sync_tracker and the
        filtered-level early-out inside heartbeat."""
        from contextlib import nullcontext
        rows = self.host_table.heartbeat_rows(interval_sec) \
            if self.host_table is not None else []
        ri = 0
        ctx = self.native_plane.bulk_sync() \
            if self.native_plane is not None else nullcontext()
        with ctx:
            for hid in sorted(self.hosts):
                while ri < len(rows) and rows[ri][0] < hid:
                    self.host_table.heartbeat_row(rows[ri], now)
                    ri += 1
                host = self.hosts[hid]
                if host.params.heartbeat_interval_sec == interval_sec \
                        and self.owns_host(host):
                    host.tracker.heartbeat(now)
        while ri < len(rows):
            self.host_table.heartbeat_row(rows[ri], now)
            ri += 1

    # -- round loop --------------------------------------------------------
    def run(self) -> int:
        """The slave_run equivalent.  Returns process-style exit code."""
        log = get_logger()
        # per-packet delivery-status audit trails only when debugging
        # (packet.c PDS_* flags are logged at debug level there too);
        # sampled at run start so set_level() before run() is honored
        from ..routing import packet as packet_mod
        packet_mod.AUDIT_STATUSES = log.would_log("debug")
        self.sim_start_wall = _walltime.monotonic()
        self.schedule_boot()
        # The hot loop allocates millions of short-lived Events/Packets that
        # die by refcount; cyclic GC passes over them are pure overhead (the
        # few true cycles — e.g. TCP parent/child links — are reclaimed by
        # the final collect).  Mirrors the reference's G_SLICE tuning intent.
        import gc
        gc_was_enabled = gc.isenabled()
        if gc_was_enabled:
            gc.collect()
            gc.freeze()
            gc.disable()
        lookahead = self.lookahead_ns
        log.message("engine",
                    f"starting simulation: {self.total_host_count()} hosts, "
                    f"policy={self.scheduler.policy_name}, "
                    f"workers={self.options.workers}, "
                    f"lookahead={lookahead / 1e6:.3f} ms, "
                    f"end={self.end_time / 1e9:.1f} s")
        try:
            if self.options.workers == 0:
                self._run_serial(lookahead)
            else:
                self._run_threaded(lookahead)
        except BaseException:
            # abnormal termination: best-effort flight-recorder export +
            # metrics summary BEFORE the exception propagates — the
            # post-mortem timeline is exactly what the flight recorder
            # exists to preserve (the success path exports in _obs_finish)
            self._obs_emergency()
            raise
        finally:
            if gc_was_enabled:
                gc.enable()
                gc.unfreeze()
                gc.collect()
        self._running = False
        if self.device_plane is not None:
            # fold every pending device-plane byte delta so post-run
            # readers see final tracker totals
            self.device_plane.flush_all_trackers()
        if self.native_plane is not None:
            # post-run reads (tests, tools, digests) see the Python tracker
            # objects; the authoritative counts accumulated in C — fetched
            # with ONE bulk C call for all hosts, not 10k round trips
            with self.native_plane.bulk_sync():
                for host in self.hosts.values():
                    self.native_plane.sync_tracker(host.id, host.tracker)
        # teardown: hosts (and their descriptors) are reclaimed here
        for host in self.hosts.values():
            # dict.fromkeys: dedupe multi-IP interfaces in insertion order
            # (set iteration order varies run-to-run — SIM003)
            for iface in dict.fromkeys(host.interfaces.values()):
                if iface.pcap is not None:
                    iface.pcap.close()
            self.counters.count_free("host")
        if self.host_table is not None:
            # never-materialized rows: balance the host ledger in bulk
            self.host_table.close_counters()
        log.flush()
        leaks = self.counters.leaks()
        if self.device_plane is not None:
            st = self.device_plane.stats()
            log.message(
                "engine",
                f"device plane: {st['completed']}/{st['circuits']} flows "
                f"complete, {st['forwards']} cell forwards on-device over "
                f"{st['dispatches']} dispatches (mode={st['mode']})")
        log.message("engine",
                    f"simulation finished: {self.rounds_executed} rounds, "
                    f"{self.events_executed} events, "
                    f"{_walltime.monotonic() - self.sim_start_wall:.3f}s wall "
                    f"(host_exec {self.host_exec_ns / 1e9:.3f}s, "
                    f"flush {self.flush_ns / 1e9:.3f}s)")
        if self._resume_snapshot is not None:
            from .checkpoint import warn_resume_unreached
            warn_resume_unreached(self._resume_snapshot, "engine")
        if self.supervision.recoveries:
            log.message("engine",
                        f"supervision: {self.supervision.summary()}")
        if leaks:
            log.message("engine", self.counters.report())
        self._obs_finish()
        log.flush()
        return 1 if self.plugin_errors else 0

    def _flush_round(self) -> bool:
        """Round-boundary hook for batching policies (tpu): LAUNCH the device
        step for the packets sent this round.  In async mode the results are
        materialized by _consume_flush at the top of the next loop iteration
        (always before the next window is computed), so the device computes
        through the logger flush / heartbeat / window bookkeeping.  (The
        device traffic plane launches EARLIER — _launch_plane at the top of
        the round — so its dispatch overlaps the whole round's host work.)

        Returns True when any leg did real work — the round loop's
        dirty-tracking signal (ISSUE 10 compacted flush): quiet rounds are
        counted and their flush cost pinned ~zero by the bench-smoke
        control-plane gate."""
        did = False
        if self._pending_sweeps:
            # heartbeat sweeps recorded by this round's tick events run
            # HERE, at the quiescent boundary (workers parked), so the
            # tracker reads/folds never race worker-thread event execution
            sweeps, self._pending_sweeps = self._pending_sweeps, []
            for interval_sec, now in sweeps:
                self.run_tracker_sweep(interval_sec, now)
            did = True
        flush = getattr(self.scheduler.policy, "flush_round", None)
        if flush is not None:
            did = bool(flush(self)) or did
        ws = self.scheduler.window_start
        if self._resume_snapshot is not None \
                and ws >= self._resume_snapshot["sim_time_ns"]:
            self._consume_flush()
            self._verify_resume(ws)
            did = True
        if self._checkpointer is not None \
                and self._checkpointer.due(ws, self.rounds_executed):
            # snapshots must include every in-flight delivery: consume first
            # (only on rounds that actually write — an unconditional consume
            # here would forfeit the async launch/consume overlap for the
            # whole run)
            self._consume_flush()
            with self.tracer.span("checkpoint.write", "engine", sim_ns=ws):
                path = self._checkpointer.maybe_write(self)
            did = True
            if path:
                self._checkpoint_counter.inc()
                get_logger().message("engine", f"checkpoint written: {path}")
        return did

    def _verify_resume(self, window_start: int) -> None:
        from .checkpoint import (collect_state, digest_of_state,
                                 verify_resume_boundary)
        snap, self._resume_snapshot = self._resume_snapshot, None
        verify_resume_boundary(snap, window_start,
                               digest_of_state(collect_state(self)),
                               "engine")
        self.supervision.resume_verified = True

    def _consume_flush(self) -> None:
        """Materialize + push any async flush results (no-op otherwise)."""
        consume = getattr(self.scheduler.policy, "consume_flush", None)
        if consume is not None:
            consume(self)
        if self.device_plane is not None:
            self.device_plane.consume(self)

    def _launch_plane(self) -> None:
        """Pipeline stage boundary: launch the device traffic plane's window
        dispatch at the TOP of the round, right after the window is
        computed — the dispatch then computes while the host drains the
        round's arrivals (plugin execution + the native C plane), and
        _consume_flush collects it at the next loop iteration, always
        before the next window.  The previous dispatch was committed by
        that same _consume_flush, so round N's state is final before round
        N+1's staged injections are folded in (the determinism contract
        tests/test_device_pipeline.py pins)."""
        if self.device_plane is not None:
            self.device_plane.advance(self)

    def _superwindow_budget(self):
        """(max_rounds, cap_time) for this round's superwindow negotiation.
        Checkpoint and resume boundaries must land on span starts with K=1
        semantics — the snapshot digest is collected (and --resume verified)
        at an exact round boundary, so merging may never cross one: cap_time
        caps merged windows below the next sim-time boundary, and the round
        budget stops the counter short of the next round-cadence write."""
        max_rounds = self._superwindow
        cap = None
        if self._resume_snapshot is not None:
            cap = self._resume_snapshot["sim_time_ns"]
        ck = self._checkpointer
        if ck is not None:
            if ck.next_at is not None:
                cap = ck.next_at if cap is None else min(cap, ck.next_at)
            if ck.next_round is not None:
                max_rounds = min(
                    max_rounds,
                    max(ck.next_round - 1 - self.rounds_executed, 1))
        return max_rounds, cap

    def _advance_window(self, lookahead: int) -> bool:
        # the earliest HOST-side event: the Python queues (and, under the
        # native merged policy, the C heap — its next_time folds both)
        host_next = self.scheduler.next_event_time()
        nxt = host_next
        if self.device_plane is not None:
            # a busy device plane needs windows even when the Python plane
            # is idle (its dispatch cadence is the "next event")
            nxt = min(nxt, self.device_plane.next_time())
        if nxt >= self.end_time or nxt >= stime.SIM_TIME_MAX:
            return False
        self.scheduler.window_start = nxt
        self.scheduler.window_end = min(nxt + lookahead, self.end_time)
        if self.device_plane is not None and self._superwindow > 1:
            # superwindow negotiation (ISSUE 7): when no host event falls
            # inside the next K lookahead rounds, merge them into ONE
            # window so the plane executes them in one kernel launch
            max_rounds, cap = self._superwindow_budget()
            merged = self.device_plane.negotiate_superwindow(
                nxt, lookahead, host_next, self.end_time, cap, max_rounds)
            if merged is not None:
                self.scheduler.window_end = merged
        if self.native_plane is not None:
            # the C plane clamps its cross-host pushes to the same barrier
            self.native_plane.set_window(self.scheduler.window_end)
        if self.host_table is not None:
            # promotion sweep: table rows whose first boot event falls in
            # this window materialize NOW (main thread, workers parked) and
            # replay their boot — event times identical to an eager boot
            self.host_table.promote_due(self.scheduler.window_end)
        return True

    def _heartbeat(self) -> None:
        """Periodic (wall-clock-gated) engine heartbeat with the per-round
        host-vs-device split the perf hunt steers by.  The values are
        computed ONCE into a dict that feeds both the legacy log line
        (tools/plot_log.py keeps scraping it) and the metrics registry —
        the promotion ISSUE 3 asks for, with both consumers guaranteed to
        read the same numbers.

        Cadence-gated (ISSUE 7): between wall-clock reads the per-round
        cost is ONE integer decrement.  The stride adapts geometrically so
        the wall is still checked ~4x per reporting interval — fast rounds
        (tor10k reaches 10k+ rounds/s with the C plane) stop paying a
        monotonic() syscall each, slow rounds keep prompt heartbeats."""
        if self._hb_countdown > 0:
            self._hb_countdown -= 1
            return
        now_wall = _walltime.monotonic()
        gap = now_wall - self._hb_last_check
        self._hb_last_check = now_wall
        target = self.heartbeat_wall_interval / 4.0
        if gap < target / 4.0:
            # the 256 cap bounds the silence after a fast->slow phase flip
            # (256 suddenly-1s rounds, then the reset below) while still
            # cutting the syscall rate ~256x at tor10k round rates
            self._hb_stride = min(self._hb_stride * 2, 256)
        elif gap > target:
            # overshot: rounds turned slow — reset (not halve) so the next
            # heartbeat is at most one round late, not a geometric tail
            self._hb_stride = 1
        self._hb_countdown = self._hb_stride - 1
        if now_wall - self._last_heartbeat_wall < self.heartbeat_wall_interval:
            return
        self._last_heartbeat_wall = now_wall
        policy = self.scheduler.policy
        # resource usage line, reference slave.c:390-411 heartbeat getrusage
        ru = resource.getrusage(resource.RUSAGE_SELF)
        vals = {
            "rounds": self.rounds_executed,
            "simtime_s": round(self.scheduler.window_start / 1e9, 3),
            "wall_s": round(now_wall - self.sim_start_wall, 1),
            "host_exec_ms": round(self.host_exec_ns / 1e6, 1),
            "flush_ms": round(self.flush_ns / 1e6, 1),
            "cpu_user_s": round(ru.ru_utime, 1),
            "cpu_sys_s": round(ru.ru_stime, 1),
            "maxrss_mb": round(ru.ru_maxrss / 1024),
        }
        extra = ""
        if self.native_plane is not None:
            _sched, execd, drops, _last = self.native_plane.counters()
            vals["native_events"] = execd
            vals["native_drops"] = drops
            extra = f" native_events={execd} native_drops={drops}"
        kern = getattr(policy, "_kernel", None)
        if kern is not None:
            vals["device_ms"] = round(policy.device_ns / 1e6, 1)
            vals["flush_host_ms"] = round(policy.host_flush_ns / 1e6, 1)
            vals["last_batch"] = policy.last_batch
            vals["device_calls"] = kern.device_calls
            vals["recompiles"] = len(kern.buckets_seen)
            extra = (f" device_ms={vals['device_ms']:.1f}"
                     f" flush_host_ms={vals['flush_host_ms']:.1f}"
                     f" last_batch={policy.last_batch}"
                     f" device_calls={kern.device_calls}"
                     f" recompiles={len(kern.buckets_seen)}")
        self.metrics.record_engine_heartbeat(vals)
        self.tracer.instant("engine.heartbeat", "engine",
                            sim_ns=self.scheduler.window_start)
        get_logger().message(
            "engine",
            f"[engine-heartbeat] rounds={vals['rounds']}"
            f" simtime={vals['simtime_s']:.3f}s"
            f" wall={vals['wall_s']:.1f}s"
            f" host_exec_ms={vals['host_exec_ms']:.1f}"
            f" flush_ms={vals['flush_ms']:.1f}"
            f" cpu_user_s={vals['cpu_user_s']:.1f}"
            f" cpu_sys_s={vals['cpu_sys_s']:.1f}"
            f" maxrss_mb={vals['maxrss_mb']}{extra}",
            sim_time=self.scheduler.window_start)

    def _run_serial(self, lookahead: int) -> None:
        worker = Worker(0, self)
        set_current_worker(worker)
        perf = _walltime.perf_counter_ns
        tracer = self.tracer
        log = get_logger()
        plane = self.device_plane
        try:
            while True:
                tc = perf()
                # plane interaction disqualifies the iteration from the
                # quiet-round count below: a collect (in-flight dispatch
                # materialized here) or a launch is flush-phase work
                plane_active = plane is not None and plane._inflight
                with tracer.span("collect", "engine",
                                 sim_ns=self.scheduler.window_start):
                    self._consume_flush()
                self.flush_ns += perf() - tc
                if not self._advance_window(lookahead):
                    break
                ws = self.scheduler.window_start
                tl = perf()
                dispatches0 = plane.dispatches if plane is not None else 0
                with tracer.span("dispatch.launch", "engine", sim_ns=ws):
                    self._launch_plane()
                self.flush_ns += perf() - tl
                plane_active = plane_active or (
                    plane is not None and plane.dispatches != dispatches0)
                worker.round_end = self.scheduler.window_end
                t0 = perf()
                with tracer.span("round", "engine", sim_ns=ws,
                                 args={"round": self.rounds_executed}):
                    worker.run_round()
                t1 = perf()
                with tracer.span("flush", "engine", sim_ns=ws):
                    did_flush = self._flush_round()
                t2 = perf()
                self.flush_ns += t2 - t1
                self.host_exec_ns += t1 - t0
                self.rounds_executed += 1
                self._heartbeat()
                self._obs_round_end()
                # compacted flush (ISSUE 10): one pending() read skips the
                # whole sort-and-emit leg (and its span) on quiet rounds
                if log.pending():
                    with tracer.span("log.flush", "engine", sim_ns=ws):
                        log.flush()
                elif not (did_flush or plane_active):
                    self.flush_quiet_skips += 1
                    self.flush_quiet_ns += t2 - t1
            self.events_executed = worker.counters._free.get("event", 0)
            self._fold_native_events(worker.counters)
        finally:
            worker.finish()
            set_current_worker(None)

    def _run_threaded(self, lookahead: int) -> None:
        n = self.scheduler.n_threads
        start_latch = CountDownLatch(n + 1)
        done_latch = CountDownLatch(n + 1)
        stop_flag = {"stop": False}
        errors: List[BaseException] = []
        workers = [Worker(i, self) for i in range(n)]

        def body(worker: Worker) -> None:
            set_current_worker(worker)
            try:
                while True:
                    start_latch.count_down_await()
                    if stop_flag["stop"]:
                        break
                    try:
                        worker.round_end = self.scheduler.window_end
                        worker.run_round()
                    except BaseException as e:  # surface, don't deadlock the latch
                        errors.append(e)  # simlint: disable=SIM102 -- done_latch's condvar orders this append before the parent's post-barrier read
                    done_latch.count_down_await()
            finally:
                worker.finish()
                set_current_worker(None)

        threads = [threading.Thread(target=body, args=(w,), daemon=True,
                                    name=f"worker-{w.id}") for w in workers]
        for t in threads:
            t.start()
        perf = _walltime.perf_counter_ns
        tracer = self.tracer
        log = get_logger()
        plane = self.device_plane
        try:
            while True:
                tc = perf()
                plane_active = plane is not None and plane._inflight
                with tracer.span("collect", "engine",
                                 sim_ns=self.scheduler.window_start):
                    self._consume_flush()
                self.flush_ns += perf() - tc
                if not self._advance_window(lookahead):
                    break
                ws = self.scheduler.window_start
                tl = perf()
                dispatches0 = plane.dispatches if plane is not None else 0
                with tracer.span("dispatch.launch", "engine", sim_ns=ws):
                    self._launch_plane()
                self.flush_ns += perf() - tl
                plane_active = plane_active or (
                    plane is not None and plane.dispatches != dispatches0)
                t0 = perf()
                with tracer.span("round", "engine", sim_ns=ws,
                                 args={"round": self.rounds_executed,
                                       "workers": n}):
                    start_latch.count_down_await()
                    start_latch.reset()
                    done_latch.count_down_await()
                    done_latch.reset()
                t1 = perf()
                if errors:
                    raise errors[0]
                with tracer.span("flush", "engine", sim_ns=ws):
                    did_flush = self._flush_round()
                t2 = perf()
                self.flush_ns += t2 - t1
                self.host_exec_ns += t1 - t0
                self.rounds_executed += 1
                self._heartbeat()
                self._obs_round_end()
                if log.pending():
                    with tracer.span("log.flush", "engine", sim_ns=ws):
                        log.flush()
                elif not (did_flush or plane_active):
                    self.flush_quiet_skips += 1
                    self.flush_quiet_ns += t2 - t1
        finally:
            stop_flag["stop"] = True
            start_latch.count_down_await()
            for t in threads:
                t.join(timeout=30)
        self.events_executed = self.counters._free.get("event", 0)
        self._fold_native_events(self.counters)

    def _fold_native_events(self, counters: ObjectCounter) -> None:
        """Fold the C plane's event lifecycle into the engine's totals
        (created at schedule, freed at execution — same accounting the
        Python events get).  Shared by BOTH runners: _run_threaded used to
        skip this fold entirely, so a threaded run with a native plane
        attached under-reported events_executed and leaked the C plane's
        event/drop counts from the ObjectCounter ledger (ISSUE 7
        satellite; regression-pinned by tests/test_superwindow.py)."""
        if self.native_plane is None:
            return
        sched, execd, drops, _last = self.native_plane.counters()
        self.events_executed += execd
        counters.count_new("event", sched)
        counters.count_free("event", execd)
        if drops:
            counters.count_new("packet_drop", drops)
