"""Scheduler: event queues, round barriers, worker thread pool.

Capability parity with the reference scheduler (core/scheduler/scheduler.c +
the policy vtable scheduler_policy.h:40-51): a policy owns the event-queue
topology (who stores which host's events, who may pop them); the scheduler
drives rounds — conservative time windows [start, end) sized by the topology
lookahead — with barriers between phases (the reference uses 5 CountDownLatch
barriers per round, scheduler.c:35-42).

Policies implemented (slave.c:104-120 name mapping):
  * ``global``        — one queue, single thread (SP_SERIAL_GLOBAL)
  * ``host``          — per-host queues, threads own fixed host sets
  * ``steal``         — per-host queues + work stealing (default)
  * ``thread``        — one queue per worker thread
  * ``threadXthread`` — N×N mailbox queues
  * ``threadXhost``   — per-(thread,host) queues
  * ``tpu``           — per-host queues + device-batched packet hop
                        (parallel/tpu_policy.py)

The causality contract: an event pushed across hosts during a round is
clamped to at least the next round barrier (reference
scheduler_policy_host_steal.c:225-242); with lookahead = min path latency the
clamp never actually fires for packet events, it is a safety net.
"""

from __future__ import annotations

import heapq
import threading
from typing import Dict, List, Optional

from ..utils.count_down_latch import CountDownLatch
from ..utils.pqueue import PriorityQueue
from . import stime
from .event import Event
from .logger import get_logger


class SchedulerPolicy:
    """Vtable equivalent of scheduler_policy.h:40-51."""

    def add_host(self, host, worker_id: int) -> None:
        raise NotImplementedError

    def assigned_hosts(self, worker_id: int) -> List:
        raise NotImplementedError

    def push(self, event: Event, worker_id: int, barrier: int) -> None:
        raise NotImplementedError

    def push_batch(self, events: List[Event], worker_id: int,
                   barrier: int) -> None:
        """Land a pre-built batch of events in one call — the scheduler
        seam for vectorized producers (the device plane's completion-wake
        fold, ISSUE 10).  Policies with per-event side channels (the
        native merged policy's lower_limit) inherit them through push."""
        for ev in events:
            self.push(ev, worker_id, barrier)

    def pop(self, worker_id: int, window_end: int) -> Optional[Event]:
        raise NotImplementedError

    def done(self, event: Event, worker_id: int) -> None:
        """Called by the worker after executing a popped event (lets policies
        that migrate hosts know the host's state is no longer in use)."""

    def next_time(self) -> int:
        """Min event time across all queues (for the next round window)."""
        raise NotImplementedError

    def pending_count(self) -> int:
        """Total queued events (round-boundary state digests; called only
        at quiescent points, so unlocked sums are safe)."""
        raise NotImplementedError


class GlobalSinglePolicy(SchedulerPolicy):
    """One global pqueue drained by worker 0 only — the serial total-order
    policy (scheduler_policy_global_single.c).  Locked so stray pushes from
    other threads (e.g. a misconfigured --workers N run) stay safe; pops from
    workers other than 0 return nothing, preserving the serial guarantee."""

    def __init__(self):
        self.queue: PriorityQueue = PriorityQueue()
        self.hosts: List = []
        self._lock = threading.Lock()
        # set by Scheduler when n_workers == 0: the whole simulation runs on
        # one thread, so the queue lock is pure overhead on the hottest path
        self.serial = False

    def add_host(self, host, worker_id: int) -> None:
        self.hosts.append(host)

    def assigned_hosts(self, worker_id: int) -> List:
        return self.hosts if worker_id == 0 else []

    def push(self, event: Event, worker_id: int, barrier: int) -> None:
        if event.dst_host is not event.src_host and event.time < barrier:
            event.time = barrier
        if self.serial:
            self.queue.push(event)
            return
        with self._lock:
            self.queue.push(event)

    def pop(self, worker_id: int, window_end: int) -> Optional[Event]:
        if worker_id != 0:
            return None
        if self.serial:
            return self.queue.pop_before(window_end)
        with self._lock:
            return self.queue.pop_before(window_end)

    def next_time(self) -> int:
        with self._lock:
            key = self.queue.peek_key()
        return key[0] if key is not None else stime.SIM_TIME_MAX

    def pending_count(self) -> int:
        return len(self.queue)


class HostQueuesPolicy(SchedulerPolicy):
    """Per-host locked queues with fixed host->worker assignment — the
    ``host`` policy (scheduler_policy_host_single.c); base for ``steal`` and
    ``tpu``.

    Indexed ready tracking: each worker keeps a lazy min-heap of
    ``(order_key, host_id)`` entries so pop() is O(log hosts) instead of the
    O(hosts) scan the naive layout needs (the reference keeps explicit
    unprocessed/processed host lists for the same reason,
    scheduler_policy_host_steal.c:28-45).  The published-key map records the
    earliest entry each host currently has in ANY heap; a push that lowers a
    queue's minimum publishes a fresh (earlier) entry, so the invariant is:
    every non-empty host queue has an entry with key <= its actual top.
    Entries are validated against the queue top when popped; stale ones are
    discarded (and the live top re-published), which keeps the index exact
    without ever rebuilding it."""

    def __init__(self):
        self._host_queues: Dict[int, PriorityQueue] = {}
        self._host_locks: Dict[int, threading.Lock] = {}
        self._assignment: Dict[int, List] = {}       # worker -> hosts
        self._host_worker: Dict[int, int] = {}       # host id -> worker
        self._create_lock = threading.Lock()         # lazy queue creation
        # Per-host execution locks, held from pop() to done(): a host's
        # events never execute on two threads at once, even across a
        # work-stealing migration (the reference guarantees this with its
        # unprocessed/processed host lists + ordered dual-locking,
        # scheduler_policy_host_steal.c:366-416).
        self._exec_locks: Dict[int, threading.Lock] = {}
        # ready-host index: worker -> heap of (key, hid), plus the earliest
        # published key per host; one lock guards the whole index (pushes
        # already serialize on host locks, and index ops are tiny)
        self._ready_heaps: Dict[int, List] = {}
        self._ready_lock = threading.Lock()
        self._published: Dict[int, tuple] = {}       # hid -> earliest entry key
        # set by Scheduler when n_workers == 0: single-threaded, so host
        # locks, exec locks and the ready-index lock are pure overhead
        self.serial = False

    def pending_count(self) -> int:
        return sum(len(q) for q in self._host_queues.values())

    def _queue_for_host(self, hid: int) -> PriorityQueue:
        q = self._host_queues.get(hid)
        if q is None:
            with self._create_lock:
                q = self._host_queues.get(hid)
                if q is None:
                    self._host_locks[hid] = threading.Lock()
                    self._exec_locks[hid] = threading.Lock()
                    q = self._host_queues[hid] = PriorityQueue()
        return q

    def _publish(self, wid: int, key, hid: int) -> None:
        """Publish 'host hid has pending work, earliest = key' to worker
        wid's ready heap unless an entry at least as early already exists."""
        with self._ready_lock:
            cur = self._published.get(hid)
            if cur is None or key < cur:
                self._published[hid] = key
                heap = self._ready_heaps.get(wid)
                if heap is None:
                    heap = self._ready_heaps[wid] = []
                heapq.heappush(heap, (key, hid))

    def add_host(self, host, worker_id: int) -> None:
        self._queue_for_host(host.id)
        with self._ready_lock:
            self._ready_heaps.setdefault(worker_id, [])
        self._assignment.setdefault(worker_id, []).append(host)
        self._host_worker[host.id] = worker_id

    def assigned_hosts(self, worker_id: int) -> List:
        return self._assignment.get(worker_id, [])

    def push(self, event: Event, worker_id: int, barrier: int) -> None:
        if event.dst_host is not event.src_host and event.time < barrier:
            event.time = barrier
        hid = event.dst_host.id if event.dst_host is not None else -1
        q = self._queue_for_host(hid)
        if self.serial:
            q.push(event)
            top = q.peek_key()
            cur = self._published.get(hid)
            if cur is None or top < cur:
                self._published[hid] = top
                heap = self._ready_heaps.get(0)
                if heap is None:
                    heap = self._ready_heaps[0] = []
                heapq.heappush(heap, (top, hid))
            return
        with self._host_locks[hid]:
            q.push(event)
            top = q.peek_key()
        self._publish(self._host_worker.get(hid, 0), top, hid)

    def _pop_from_heap(self, heap_wid: int, window_end: int) -> Optional[Event]:
        """Pop the earliest runnable event reachable through worker
        ``heap_wid``'s ready heap.  Busy hosts (exec lock held elsewhere)
        are set aside and re-published before returning."""
        heap = self._ready_heaps.get(heap_wid)
        if heap is None:
            return None
        busy: List = []
        result = None
        while True:
            with self._ready_lock:
                if not heap or heap[0][0][0] >= window_end:
                    break
                key, hid = heapq.heappop(heap)
                if self._published.get(hid) == key:
                    del self._published[hid]
            q = self._host_queues[hid]
            exec_lock = self._exec_locks[hid]
            if not exec_lock.acquire(blocking=False):
                # mid-event on another thread; retry it later
                busy.append((key, hid))
                continue
            with self._host_locks[hid]:
                actual = q.peek_key()
                if actual is None:
                    exec_lock.release()
                    continue          # stale entry; queue drained
                if actual[0] >= window_end:
                    exec_lock.release()
                    # live again next round
                    self._publish(self._host_worker.get(hid, heap_wid),
                                  actual, hid)
                    continue
                result = q.pop()
                nxt = q.peek_key()
            if nxt is not None:
                self._publish(self._host_worker.get(hid, heap_wid), nxt, hid)
            break
        for key, hid in busy:
            self._publish(self._host_worker.get(hid, heap_wid), key, hid)
        return result

    def _pop_serial(self, window_end: int) -> Optional[Event]:
        """Single-threaded pop: same index algorithm, no locks."""
        heap = self._ready_heaps.get(0)
        if not heap:
            return None
        published = self._published
        queues = self._host_queues
        while heap:
            key, hid = heap[0]
            if key[0] >= window_end:
                return None
            heapq.heappop(heap)
            if published.get(hid) == key:
                del published[hid]
            q = queues[hid]
            actual = q.peek_key()
            if actual is None:
                continue
            if actual[0] >= window_end:
                cur = published.get(hid)
                if cur is None or actual < cur:
                    published[hid] = actual
                    heapq.heappush(heap, (actual, hid))
                continue
            ev = q.pop()
            nxt = q.peek_key()
            if nxt is not None:
                cur = published.get(hid)
                if cur is None or nxt < cur:
                    published[hid] = nxt
                    heapq.heappush(heap, (nxt, hid))
            return ev
        return None

    def pop(self, worker_id: int, window_end: int) -> Optional[Event]:
        if self.serial:
            return self._pop_serial(window_end)
        return self._pop_from_heap(worker_id, window_end)

    def done(self, event: Event, worker_id: int) -> None:
        """Release the host execution lock taken by pop()."""
        if self.serial:
            return
        hid = event.dst_host.id if event.dst_host is not None else -1
        lk = self._exec_locks.get(hid)
        if lk is not None and lk.locked():
            try:
                lk.release()
            except RuntimeError:  # pragma: no cover - not ours (detached)
                pass

    def next_time(self) -> int:
        """Min pending event time.  Called at quiescent round boundaries
        (workers parked).  Stale entries surfacing at a heap top are dropped
        and the queue's live top re-published until the top entry is exact;
        entries <= the published invariant make the first exact top the true
        global minimum for that heap."""
        t = stime.SIM_TIME_MAX
        for wid, heap in self._ready_heaps.items():
            while heap:
                key, hid = heap[0]
                actual = self._host_queues[hid].peek_key()
                if actual == key:
                    if key[0] < t:
                        t = key[0]
                    break
                heapq.heappop(heap)
                if self._published.get(hid) == key:
                    del self._published[hid]
                if actual is not None:
                    cur = self._published.get(hid)
                    if cur is None or actual < cur:
                        self._published[hid] = actual
                        heapq.heappush(heap, (actual, hid))
        return t


class HostStealPolicy(HostQueuesPolicy):
    """Work stealing on top of per-host queues
    (scheduler_policy_host_steal.c): when a worker's own ready heap is
    drained for this window, it pops directly from other workers' heaps
    (earliest-first) and migrates the host it took (host_migrate :172-196),
    so future pushes for that host land on this worker.  Exclusive execution
    is enforced by the per-host exec locks in the base pop, so a racy
    migration can never run one host on two threads."""

    def __init__(self):
        super().__init__()
        self._steal_lock = threading.Lock()

    def _migrate(self, hid: int, to_worker: int) -> None:
        with self._steal_lock:
            victim = self._host_worker.get(hid)
            if victim is None or victim == to_worker:
                return
            for host in self._assignment.get(victim, []):
                if host.id == hid:
                    self._assignment[victim].remove(host)
                    self._assignment.setdefault(to_worker, []).append(host)
                    break
            self._host_worker[hid] = to_worker

    def pop(self, worker_id: int, window_end: int) -> Optional[Event]:
        ev = self._pop_from_heap(worker_id, window_end)
        if ev is not None:
            return ev
        # steal from the victim whose earliest entry is oldest; snapshot the
        # heap tops under the index lock (concurrent pops mutate the heaps)
        with self._ready_lock:
            tops = [(heap[0], wid)
                    for wid, heap in self._ready_heaps.items()
                    if wid != worker_id and heap]
        for _top, wid in sorted(tops):
            ev = self._pop_from_heap(wid, window_end)
            if ev is not None:
                hid = ev.dst_host.id if ev.dst_host is not None else -1
                self._migrate(hid, worker_id)
                return ev
        return None


class ThreadSinglePolicy(SchedulerPolicy):
    """One locked queue per worker thread
    (scheduler_policy_thread_single.c): all events for a worker's hosts land
    in that worker's single queue."""

    def __init__(self):
        self._queues: Dict[int, PriorityQueue] = {}
        self._locks: Dict[int, threading.Lock] = {}
        self._assignment: Dict[int, List] = {}
        self._host_worker: Dict[int, int] = {}
        # guards lazy queue/mailbox CREATION: a first-push from worker A
        # while worker B iterates the dict raised "dictionary changed
        # size during iteration" (fuzz-era flake); iterations below also
        # snapshot via list() (atomic under the GIL) so a racing create
        # can never invalidate a live iterator
        self._create_lock = threading.Lock()

    def add_host(self, host, worker_id: int) -> None:
        self._assignment.setdefault(worker_id, []).append(host)
        self._host_worker[host.id] = worker_id
        if worker_id not in self._queues:
            with self._create_lock:
                if worker_id not in self._queues:
                    self._locks[worker_id] = threading.Lock()
                    self._queues[worker_id] = PriorityQueue()

    def assigned_hosts(self, worker_id: int) -> List:
        return self._assignment.get(worker_id, [])

    def _queue_for(self, event: Event) -> int:
        hid = event.dst_host.id if event.dst_host is not None else -1
        return self._host_worker.get(hid, 0)

    def push(self, event: Event, worker_id: int, barrier: int) -> None:
        if event.dst_host is not event.src_host and event.time < barrier:
            event.time = barrier
        w = self._queue_for(event)
        if w not in self._queues:
            with self._create_lock:
                if w not in self._queues:
                    # lock first: anyone who can see the queue key must
                    # be able to take its lock
                    self._locks[w] = threading.Lock()
                    self._queues[w] = PriorityQueue()
        with self._locks[w]:
            self._queues[w].push(event)

    def pop(self, worker_id: int, window_end: int) -> Optional[Event]:
        q = self._queues.get(worker_id)
        if q is None:
            return None
        with self._locks[worker_id]:
            key = q.peek_key()
            if key is None or key[0] >= window_end:
                return None
            return q.pop()

    def next_time(self) -> int:
        t = stime.SIM_TIME_MAX
        for w, q in list(self._queues.items()):
            with self._locks[w]:
                key = q.peek_key()
            if key is not None:
                t = min(t, key[0])
        return t

    def pending_count(self) -> int:
        return sum(len(q) for q in list(self._queues.values()))


class ThreadPerThreadPolicy(ThreadSinglePolicy):
    """N×N mailboxes (scheduler_policy_thread_perthread.c): queue (i,j)
    holds events pushed by worker i for worker j's hosts, so at most two
    threads ever contend on a queue."""

    def __init__(self):
        super().__init__()
        self._mailboxes: Dict[tuple, PriorityQueue] = {}
        self._mlocks: Dict[tuple, threading.Lock] = {}

    def pending_count(self) -> int:
        return (sum(len(q) for q in list(self._queues.values()))
                + sum(len(q) for q in list(self._mailboxes.values())))

    def push(self, event: Event, worker_id: int, barrier: int) -> None:
        if event.dst_host is not event.src_host and event.time < barrier:
            event.time = barrier
        dst_worker = self._queue_for(event)
        key = (worker_id, dst_worker)
        if key not in self._mailboxes:
            with self._create_lock:
                if key not in self._mailboxes:
                    self._mlocks[key] = threading.Lock()
                    self._mailboxes[key] = PriorityQueue()
        with self._mlocks[key]:
            self._mailboxes[key].push(event)

    def pop(self, worker_id: int, window_end: int) -> Optional[Event]:
        best_key, best_mb = None, None
        for (src, dst), q in list(self._mailboxes.items()):
            if dst != worker_id:
                continue
            with self._mlocks[(src, dst)]:
                key = q.peek_key()
            if key is not None and key[0] < window_end and (
                    best_key is None or key < best_key):
                best_key, best_mb = key, (src, dst)
        if best_mb is None:
            return None
        with self._mlocks[best_mb]:
            return self._mailboxes[best_mb].pop()

    def next_time(self) -> int:
        t = stime.SIM_TIME_MAX
        for key, q in list(self._mailboxes.items()):
            with self._mlocks[key]:
                k = q.peek_key()
            if k is not None:
                t = min(t, k[0])
        return t


class ThreadPerHostPolicy(SchedulerPolicy):
    """Per-(thread, src-host) mailboxes + one main queue per thread
    (scheduler_policy_thread_perhost.c:1-258): a push whose destination host
    belongs to the pushing thread goes straight into that thread's main
    queue (:131-134); a cross-thread push lands in the destination thread's
    per-source-host mailbox (:141-148, locked only when the pusher isn't the
    destination thread); mailboxes are drained into the main queues at round
    boundaries (:194-206 getNextTime), so during a round each worker pops
    its main queue with zero cross-thread contention."""

    def __init__(self):
        self._main: Dict[int, PriorityQueue] = {}
        self._main_locks: Dict[int, threading.Lock] = {}
        self._mailboxes: Dict[tuple, PriorityQueue] = {}  # (dst_wid, src_hid)
        self._mbox_locks: Dict[int, threading.Lock] = {}  # per dst wid
        self._assignment: Dict[int, List] = {}
        self._host_worker: Dict[int, int] = {}
        self._create_lock = threading.Lock()

    def _ensure_worker(self, wid: int) -> None:
        if wid not in self._main:
            with self._create_lock:
                if wid not in self._main:
                    self._main_locks[wid] = threading.Lock()
                    self._mbox_locks[wid] = threading.Lock()
                    self._main[wid] = PriorityQueue()

    def add_host(self, host, worker_id: int) -> None:
        self._ensure_worker(worker_id)
        self._assignment.setdefault(worker_id, []).append(host)
        self._host_worker[host.id] = worker_id

    def assigned_hosts(self, worker_id: int) -> List:
        return self._assignment.get(worker_id, [])

    def push(self, event: Event, worker_id: int, barrier: int) -> None:
        src_hid = event.src_host.id if event.src_host is not None else -1
        dst_hid = event.dst_host.id if event.dst_host is not None else -1
        src_wid = self._host_worker.get(src_hid, worker_id)
        dst_wid = self._host_worker.get(dst_hid, 0)
        # inter-thread events are delayed to the barrier for causality
        # (thread_perhost.c:120-124 clamps when the threads differ)
        if src_wid != dst_wid and event.time < barrier:
            event.time = barrier
        self._ensure_worker(dst_wid)
        if dst_wid == worker_id:
            with self._main_locks[dst_wid]:
                self._main[dst_wid].push(event)
            return
        with self._mbox_locks[dst_wid]:
            key = (dst_wid, src_hid)
            mb = self._mailboxes.get(key)
            if mb is None:
                mb = self._mailboxes[key] = PriorityQueue()
            mb.push(event)

    def pop(self, worker_id: int, window_end: int) -> Optional[Event]:
        q = self._main.get(worker_id)
        if q is None:
            return None
        with self._main_locks[worker_id]:
            key = q.peek_key()
            if key is None or key[0] >= window_end:
                return None
            return q.pop()

    def _drain_mailboxes(self) -> None:
        """Between rounds (quiescent): empty every mailbox into its
        destination thread's main queue (thread_perhost.c:194-206)."""
        for (dst_wid, _src), mb in self._mailboxes.items():
            q = self._main[dst_wid]
            while True:
                ev = mb.pop()
                if ev is None:
                    break
                q.push(ev)

    def next_time(self) -> int:
        self._drain_mailboxes()
        t = stime.SIM_TIME_MAX
        for wid, q in self._main.items():
            key = q.peek_key()
            if key is not None and key[0] < t:
                t = key[0]
        return t

    def pending_count(self) -> int:
        return (sum(len(q) for q in self._main.values())
                + sum(len(mb) for mb in self._mailboxes.values()))


def make_policy(name: str, n_workers: int = 0) -> SchedulerPolicy:
    if name == "global":
        return GlobalSinglePolicy()
    if name == "host":
        return HostQueuesPolicy()
    if name == "steal":
        return HostStealPolicy()
    if name == "thread":
        return ThreadSinglePolicy()
    if name == "threadXthread":
        return ThreadPerThreadPolicy()
    if name == "threadXhost":
        return ThreadPerHostPolicy()
    if name == "tpu":
        # storage layout follows the execution mode: the single global
        # queue for serial runs (per-host queues cost a min-scan per pop
        # for no benefit without threads), per-host queues when workers
        # pop in parallel
        if n_workers == 0:
            from ..parallel.tpu_policy import TPUSerialPolicy
            return TPUSerialPolicy()
        from ..parallel.tpu_policy import TPUPolicy
        return TPUPolicy()
    raise ValueError(f"unknown scheduler policy {name!r}")


def shuffle_permutation(n: int, seed_key: int):
    """The seeded Fisher-Yates permutation of range(n) as an index array.
    The swap draws are the sequential stream ``next_int(i+1)`` for
    i = n-1..1 — evaluated as ONE vectorized threefry call (counter c for
    the c-th draw), bitwise identical to RandomSource's scalar chain."""
    import numpy as np
    from .rng import bits64_np, derive
    idx = np.arange(n, dtype=np.int64)
    if n < 2:
        return idx
    key = derive(seed_key, "host-shuffle")
    counters = np.arange(n - 1, dtype=np.uint64)
    bounds = np.arange(n, 1, -1, dtype=np.uint64)     # i+1 for i=n-1..1
    draws = bits64_np(key, counters) % bounds
    for k, i in enumerate(range(n - 1, 0, -1)):
        j = int(draws[k])
        idx[i], idx[j] = idx[j], idx[i]
    return idx


def shuffle_hosts(hosts: List, seed_key: int) -> List:
    """Deal order for finalize_hosts: ``hosts`` permuted by the seeded
    Fisher-Yates index array."""
    perm = shuffle_permutation(len(hosts), seed_key)
    return [hosts[int(i)] for i in perm]


class Scheduler:
    """Drives rounds over worker threads (serial when n_workers == 0)."""

    def __init__(self, engine, policy_name: str, n_workers: int, seed_key: int):
        self.engine = engine
        self.policy_name = policy_name
        self.n_workers = max(0, n_workers)
        self.n_threads = max(1, self.n_workers)
        if self.n_workers == 0 and policy_name == "steal":
            # reference falls back to a serial queue for 0 workers
            # (scheduler.c:139-142)
            policy_name = "global"
            self.policy_name = "global"
        self.policy = make_policy(policy_name, self.n_workers)
        if self.n_workers == 0 and isinstance(
                self.policy, (GlobalSinglePolicy, HostQueuesPolicy)):
            self.policy.serial = True
        self.seed_key = seed_key
        self.window_start = 0
        self.window_end = 1
        self._next_host_worker = 0
        self._host_count = 0
        self._pending_hosts: List = []
        self._hosts_finalized = False
        self._late_add_lock = threading.Lock()
        self._running = True
        self._threads: List[threading.Thread] = []
        self._workers: List = []
        self._round_start_latch: Optional[CountDownLatch] = None
        self._round_done_latch: Optional[CountDownLatch] = None

    # -- host assignment (scheduler.c:437-531 random shuffle) --------------
    def add_host(self, host) -> None:
        """Hosts registered before finalize_hosts() are collected and dealt
        to workers in seeded-shuffle order at boot.  A host added after
        boot — a HostTable row materializing on first need — is dealt
        round-robin from the cursor, serialized by a lock because a
        mid-round promote-on-lookup runs on whichever worker thread's
        packet reached the quiet row first.  Late-assignment order is
        therefore arrival order, exactly like a work-stealing migration:
        it moves load balance only, never results (state digests are
        assignment-independent — the cross-policy parity gates and the
        threaded table-parity test pin that)."""
        if self._hosts_finalized:
            with self._late_add_lock:
                self._assign(host)
            return
        self._pending_hosts.append(host)

    def _assign(self, host) -> None:
        wid = self._next_host_worker
        self._next_host_worker = (self._next_host_worker + 1) % self.n_threads
        self.policy.add_host(host, wid)
        self._host_count += 1

    def finalize_hosts(self) -> None:
        """Commit the host->worker assignment: a Fisher-Yates shuffle keyed
        off the simulation seed (the reference shuffles its host list with
        the scheduler RNG before dealing round-robin, scheduler.c:437-472),
        so no adversarial config ordering can pile heavy hosts onto one
        worker.  Deterministic: same seed, same assignment — and the final
        state digest is assignment-independent anyway (the cross-policy
        parity gates pin that), so the shuffle affects load balance only.

        The shuffle operates on a host-ID ARRAY with all swap indices
        drawn in one vectorized threefry call — bitwise identical to the
        sequential next_int chain it replaces (tests/test_scale.py pins
        the permutation AND the per-seed digest), but a 100k-host boot no
        longer permutes a Python list of Host objects through 100k scalar
        cipher evaluations."""
        if self._hosts_finalized:
            return
        self._hosts_finalized = True
        hosts, self._pending_hosts = self._pending_hosts, []
        for host in shuffle_hosts(hosts, self.seed_key):
            self._assign(host)

    # -- push/pop (worker-facing) -----------------------------------------
    def push(self, event: Event, worker) -> None:
        self.policy.push(event, worker.id, self.window_end)

    def pop(self, worker) -> Optional[Event]:
        if not self._running:
            return None
        return self.policy.pop(worker.id, self.window_end)

    def event_done(self, event: Event, worker) -> None:
        self.policy.done(event, worker.id)

    def next_event_time(self) -> int:
        """Min pending host-side event time: the policy's queues, the
        native C heap (folded inside the merged policy), and — under the
        scale tier — the host table's earliest boot wake, so windows land
        on the same boundaries whether a host is an object or a row."""
        t = self.policy.next_time()
        table = getattr(self.engine, "host_table", None)
        if table is not None:
            wake = table.next_wake()
            if wake < t:
                t = wake
        return t

    def pending_count(self) -> int:
        """Queued events + the host table's deferred boot events (events
        an eager boot would already hold in queues for still-quiet rows)
        — the digest's pending_events field must not depend on which
        boot path ran."""
        n = self.policy.pending_count()
        table = getattr(self.engine, "host_table", None)
        if table is not None:
            n += table.pending_boot_events()
        return n

    def set_window(self, start: int, end: int) -> None:
        """Rebind the current round window.  Used by the device plane's
        superwindow collect to align the engine's bookkeeping with the
        virtual round a multi-round kernel launch actually reached (the
        kernel may halt at an earlier negotiated boundary on a completion
        — parallel/device_plane.py consume())."""
        self.window_start = start
        self.window_end = end

    def stop(self) -> None:
        self._running = False

    @property
    def is_running(self) -> bool:
        return self._running
