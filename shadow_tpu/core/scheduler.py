"""Scheduler: event queues, round barriers, worker thread pool.

Capability parity with the reference scheduler (core/scheduler/scheduler.c +
the policy vtable scheduler_policy.h:40-51): a policy owns the event-queue
topology (who stores which host's events, who may pop them); the scheduler
drives rounds — conservative time windows [start, end) sized by the topology
lookahead — with barriers between phases (the reference uses 5 CountDownLatch
barriers per round, scheduler.c:35-42).

Policies implemented (slave.c:104-120 name mapping):
  * ``global``        — one queue, single thread (SP_SERIAL_GLOBAL)
  * ``host``          — per-host queues, threads own fixed host sets
  * ``steal``         — per-host queues + work stealing (default)
  * ``thread``        — one queue per worker thread
  * ``threadXthread`` — N×N mailbox queues
  * ``threadXhost``   — per-(thread,host) queues
  * ``tpu``           — per-host queues + device-batched packet hop
                        (parallel/tpu_policy.py)

The causality contract: an event pushed across hosts during a round is
clamped to at least the next round barrier (reference
scheduler_policy_host_steal.c:225-242); with lookahead = min path latency the
clamp never actually fires for packet events, it is a safety net.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

from ..utils.count_down_latch import CountDownLatch
from ..utils.pqueue import PriorityQueue
from . import stime
from .event import Event
from .logger import get_logger


class SchedulerPolicy:
    """Vtable equivalent of scheduler_policy.h:40-51."""

    def add_host(self, host, worker_id: int) -> None:
        raise NotImplementedError

    def assigned_hosts(self, worker_id: int) -> List:
        raise NotImplementedError

    def push(self, event: Event, worker_id: int, barrier: int) -> None:
        raise NotImplementedError

    def pop(self, worker_id: int, window_end: int) -> Optional[Event]:
        raise NotImplementedError

    def done(self, event: Event, worker_id: int) -> None:
        """Called by the worker after executing a popped event (lets policies
        that migrate hosts know the host's state is no longer in use)."""

    def next_time(self) -> int:
        """Min event time across all queues (for the next round window)."""
        raise NotImplementedError

    def pending_count(self) -> int:
        """Total queued events (round-boundary state digests; called only
        at quiescent points, so unlocked sums are safe)."""
        raise NotImplementedError


class GlobalSinglePolicy(SchedulerPolicy):
    """One global pqueue drained by worker 0 only — the serial total-order
    policy (scheduler_policy_global_single.c).  Locked so stray pushes from
    other threads (e.g. a misconfigured --workers N run) stay safe; pops from
    workers other than 0 return nothing, preserving the serial guarantee."""

    def __init__(self):
        self.queue: PriorityQueue = PriorityQueue()
        self.hosts: List = []
        self._lock = threading.Lock()

    def add_host(self, host, worker_id: int) -> None:
        self.hosts.append(host)

    def assigned_hosts(self, worker_id: int) -> List:
        return self.hosts if worker_id == 0 else []

    def push(self, event: Event, worker_id: int, barrier: int) -> None:
        if event.dst_host is not event.src_host and event.time < barrier:
            event.time = barrier
        with self._lock:
            self.queue.push(event)

    def pop(self, worker_id: int, window_end: int) -> Optional[Event]:
        if worker_id != 0:
            return None
        with self._lock:
            key = self.queue.peek_key()
            if key is None or key[0] >= window_end:
                return None
            return self.queue.pop()

    def next_time(self) -> int:
        with self._lock:
            key = self.queue.peek_key()
        return key[0] if key is not None else stime.SIM_TIME_MAX

    def pending_count(self) -> int:
        return len(self.queue)


class HostQueuesPolicy(SchedulerPolicy):
    """Per-host locked queues with fixed host->worker assignment — the
    ``host`` policy (scheduler_policy_host_single.c); base for ``steal`` and
    ``tpu``."""

    def __init__(self):
        self._host_queues: Dict[int, PriorityQueue] = {}
        self._host_locks: Dict[int, threading.Lock] = {}
        self._assignment: Dict[int, List] = {}       # worker -> hosts
        self._host_worker: Dict[int, int] = {}       # host id -> worker
        self._create_lock = threading.Lock()         # lazy queue creation
        # Per-host execution locks, held from pop() to done(): a host's
        # events never execute on two threads at once, even across a
        # work-stealing migration (the reference guarantees this with its
        # unprocessed/processed host lists + ordered dual-locking,
        # scheduler_policy_host_steal.c:366-416).
        self._exec_locks: Dict[int, threading.Lock] = {}

    def pending_count(self) -> int:
        return sum(len(q) for q in self._host_queues.values())

    def _queue_for_host(self, hid: int) -> PriorityQueue:
        q = self._host_queues.get(hid)
        if q is None:
            with self._create_lock:
                q = self._host_queues.get(hid)
                if q is None:
                    self._host_locks[hid] = threading.Lock()
                    self._exec_locks[hid] = threading.Lock()
                    q = self._host_queues[hid] = PriorityQueue()
        return q

    def add_host(self, host, worker_id: int) -> None:
        self._queue_for_host(host.id)
        self._assignment.setdefault(worker_id, []).append(host)
        self._host_worker[host.id] = worker_id

    def assigned_hosts(self, worker_id: int) -> List:
        return self._assignment.get(worker_id, [])

    def push(self, event: Event, worker_id: int, barrier: int) -> None:
        if event.dst_host is not event.src_host and event.time < barrier:
            event.time = barrier
        hid = event.dst_host.id if event.dst_host is not None else -1
        q = self._queue_for_host(hid)
        with self._host_locks[hid]:
            q.push(event)

    def pop(self, worker_id: int, window_end: int) -> Optional[Event]:
        # pop the earliest event among this worker's hosts, honoring the
        # global order key so same-window events execute deterministically
        # per host (cross-host order within a window is free, as in the
        # reference — causality is guaranteed by the lookahead window).
        excluded: set = set()
        while True:
            best = None
            best_key = None
            for host in list(self._assignment.get(worker_id, [])):
                if host.id in excluded:
                    continue
                q = self._host_queues[host.id]
                with self._host_locks[host.id]:
                    key = q.peek_key()
                if key is not None and key[0] < window_end:
                    if best_key is None or key < best_key:
                        best, best_key = host, key
            # also drain the detached (-1) queue from worker 0
            if worker_id == 0 and -1 in self._host_queues:
                with self._host_locks[-1]:
                    key = self._host_queues[-1].peek_key()
                    if key is not None and key[0] < window_end and (
                            best_key is None or key < best_key):
                        return self._host_queues[-1].pop()
            if best is None:
                return None
            exec_lock = self._exec_locks[best.id]
            if not exec_lock.acquire(blocking=False):
                # another thread is mid-event on this host (stealing race);
                # look at the remaining hosts instead
                excluded.add(best.id)
                continue
            with self._host_locks[best.id]:
                # re-check under the queue lock: a thief may have drained it
                key = self._host_queues[best.id].peek_key()
                if key is None or key[0] >= window_end:
                    exec_lock.release()
                    excluded.add(best.id)
                    continue
                return self._host_queues[best.id].pop()

    def done(self, event: Event, worker_id: int) -> None:
        """Release the host execution lock taken by pop()."""
        hid = event.dst_host.id if event.dst_host is not None else -1
        lk = self._exec_locks.get(hid)
        if lk is not None and lk.locked():
            try:
                lk.release()
            except RuntimeError:  # pragma: no cover - not ours (detached)
                pass

    def next_time(self) -> int:
        t = stime.SIM_TIME_MAX
        for hid, q in self._host_queues.items():
            with self._host_locks[hid]:
                key = q.peek_key()
            if key is not None:
                t = min(t, key[0])
        return t


class HostStealPolicy(HostQueuesPolicy):
    """Work stealing on top of per-host queues
    (scheduler_policy_host_steal.c): when a worker's own hosts are drained
    for this window, it scans other workers' hosts and migrates one with
    runnable events (host_migrate :172-196).  Migration only moves queue
    ownership; host state follows because the thief executes the host's
    events after the migration point."""

    def __init__(self):
        super().__init__()
        self._steal_lock = threading.Lock()

    def pop(self, worker_id: int, window_end: int) -> Optional[Event]:
        ev = super().pop(worker_id, window_end)
        if ev is not None:
            return ev
        # steal: find a host with runnable work that nobody is mid-event on
        # and take it over.  Exclusive execution is enforced by the per-host
        # exec locks in the base pop(), so even a racy migration here cannot
        # run one host on two threads; the busy check just avoids migrating
        # hosts that are actively being drained.  The O(hosts) victim scan
        # runs lock-free on list snapshots; only the migration itself takes
        # the steal lock, so concurrent idle workers scan in parallel.
        while True:
            candidate = victim = None
            for victim_worker, hosts in list(self._assignment.items()):
                if victim_worker == worker_id:
                    continue
                for host in list(hosts):
                    if self._exec_locks[host.id].locked():
                        continue
                    q = self._host_queues[host.id]
                    with self._host_locks[host.id]:
                        key = q.peek_key()
                    if key is not None and key[0] < window_end:
                        candidate, victim = host, victim_worker
                        break
                if candidate is not None:
                    break
            if candidate is None:
                return None
            with self._steal_lock:
                hosts = self._assignment.get(victim, [])
                if candidate in hosts:  # still the victim's: migrate it
                    hosts.remove(candidate)
                    self._assignment.setdefault(worker_id, []).append(candidate)
                    self._host_worker[candidate.id] = worker_id
            ev = super().pop(worker_id, window_end)
            if ev is not None:
                return ev
            # raced with another thief or the queue drained; rescan


class ThreadSinglePolicy(SchedulerPolicy):
    """One locked queue per worker thread
    (scheduler_policy_thread_single.c): all events for a worker's hosts land
    in that worker's single queue."""

    def __init__(self):
        self._queues: Dict[int, PriorityQueue] = {}
        self._locks: Dict[int, threading.Lock] = {}
        self._assignment: Dict[int, List] = {}
        self._host_worker: Dict[int, int] = {}

    def add_host(self, host, worker_id: int) -> None:
        self._assignment.setdefault(worker_id, []).append(host)
        self._host_worker[host.id] = worker_id
        if worker_id not in self._queues:
            self._queues[worker_id] = PriorityQueue()
            self._locks[worker_id] = threading.Lock()

    def assigned_hosts(self, worker_id: int) -> List:
        return self._assignment.get(worker_id, [])

    def _queue_for(self, event: Event) -> int:
        hid = event.dst_host.id if event.dst_host is not None else -1
        return self._host_worker.get(hid, 0)

    def push(self, event: Event, worker_id: int, barrier: int) -> None:
        if event.dst_host is not event.src_host and event.time < barrier:
            event.time = barrier
        w = self._queue_for(event)
        if w not in self._queues:
            self._queues[w] = PriorityQueue()
            self._locks[w] = threading.Lock()
        with self._locks[w]:
            self._queues[w].push(event)

    def pop(self, worker_id: int, window_end: int) -> Optional[Event]:
        q = self._queues.get(worker_id)
        if q is None:
            return None
        with self._locks[worker_id]:
            key = q.peek_key()
            if key is None or key[0] >= window_end:
                return None
            return q.pop()

    def next_time(self) -> int:
        t = stime.SIM_TIME_MAX
        for w, q in self._queues.items():
            with self._locks[w]:
                key = q.peek_key()
            if key is not None:
                t = min(t, key[0])
        return t

    def pending_count(self) -> int:
        return sum(len(q) for q in self._queues.values())


class ThreadPerThreadPolicy(ThreadSinglePolicy):
    """N×N mailboxes (scheduler_policy_thread_perthread.c): queue (i,j)
    holds events pushed by worker i for worker j's hosts, so at most two
    threads ever contend on a queue."""

    def __init__(self):
        super().__init__()
        self._mailboxes: Dict[tuple, PriorityQueue] = {}
        self._mlocks: Dict[tuple, threading.Lock] = {}

    def pending_count(self) -> int:
        return (sum(len(q) for q in self._queues.values())
                + sum(len(q) for q in self._mailboxes.values()))

    def push(self, event: Event, worker_id: int, barrier: int) -> None:
        if event.dst_host is not event.src_host and event.time < barrier:
            event.time = barrier
        dst_worker = self._queue_for(event)
        key = (worker_id, dst_worker)
        if key not in self._mailboxes:
            self._mailboxes[key] = PriorityQueue()
            self._mlocks[key] = threading.Lock()
        with self._mlocks[key]:
            self._mailboxes[key].push(event)

    def pop(self, worker_id: int, window_end: int) -> Optional[Event]:
        best_key, best_mb = None, None
        for (src, dst), q in self._mailboxes.items():
            if dst != worker_id:
                continue
            with self._mlocks[(src, dst)]:
                key = q.peek_key()
            if key is not None and key[0] < window_end and (
                    best_key is None or key < best_key):
                best_key, best_mb = key, (src, dst)
        if best_mb is None:
            return None
        with self._mlocks[best_mb]:
            return self._mailboxes[best_mb].pop()

    def next_time(self) -> int:
        t = stime.SIM_TIME_MAX
        for key, q in self._mailboxes.items():
            with self._mlocks[key]:
                k = q.peek_key()
            if k is not None:
                t = min(t, k[0])
        return t


class ThreadPerHostPolicy(HostQueuesPolicy):
    """Per-(thread,host) queues (scheduler_policy_thread_perhost.c).  With
    our per-host locking the host-queue layout already gives the same
    contention profile; kept as a named policy for config parity."""


def make_policy(name: str) -> SchedulerPolicy:
    if name == "global":
        return GlobalSinglePolicy()
    if name == "host":
        return HostQueuesPolicy()
    if name == "steal":
        return HostStealPolicy()
    if name == "thread":
        return ThreadSinglePolicy()
    if name == "threadXthread":
        return ThreadPerThreadPolicy()
    if name == "threadXhost":
        return ThreadPerHostPolicy()
    if name == "tpu":
        from ..parallel.tpu_policy import TPUPolicy
        return TPUPolicy()
    raise ValueError(f"unknown scheduler policy {name!r}")


class Scheduler:
    """Drives rounds over worker threads (serial when n_workers == 0)."""

    def __init__(self, engine, policy_name: str, n_workers: int, seed_key: int):
        self.engine = engine
        self.policy_name = policy_name
        self.n_workers = max(0, n_workers)
        self.n_threads = max(1, self.n_workers)
        if self.n_workers == 0 and policy_name == "steal":
            # reference falls back to a serial queue for 0 workers
            # (scheduler.c:139-142)
            policy_name = "global"
            self.policy_name = "global"
        self.policy = make_policy(policy_name)
        self.seed_key = seed_key
        self.window_start = 0
        self.window_end = 1
        self._next_host_worker = 0
        self._host_count = 0
        self._running = True
        self._threads: List[threading.Thread] = []
        self._workers: List = []
        self._round_start_latch: Optional[CountDownLatch] = None
        self._round_done_latch: Optional[CountDownLatch] = None

    # -- host assignment (scheduler.c:437-531 random shuffle) --------------
    def add_host(self, host) -> None:
        # deterministic round-robin assignment; the reference shuffles with
        # the scheduler seed — round-robin is equally balanced and stable
        wid = self._next_host_worker
        self._next_host_worker = (self._next_host_worker + 1) % self.n_threads
        self.policy.add_host(host, wid)
        self._host_count += 1

    # -- push/pop (worker-facing) -----------------------------------------
    def push(self, event: Event, worker) -> None:
        self.policy.push(event, worker.id, self.window_end)

    def pop(self, worker) -> Optional[Event]:
        if not self._running:
            return None
        return self.policy.pop(worker.id, self.window_end)

    def event_done(self, event: Event, worker) -> None:
        self.policy.done(event, worker.id)

    def next_event_time(self) -> int:
        return self.policy.next_time()

    def stop(self) -> None:
        self._running = False

    @property
    def is_running(self) -> bool:
        return self._running
