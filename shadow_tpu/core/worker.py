"""Worker: the per-thread execution context and event loop.

Capability parity with the reference's Worker (core/worker.c): a thread-local
context holding the clocks (now / last-executed / round barrier), the active
host/process, and the two hot operations:

* :meth:`Worker.schedule_task` — push a task onto the event queue with the
  per-source-host sequence id that completes the deterministic order tuple
  (worker.c:218).
* :meth:`Worker.send_packet` — the inter-host hot path (worker.c:243-304):
  reliability draw → maybe drop; latency lookup → delivery time; push a
  deliver-packet event to the destination host, clamped to the round barrier
  for causality.

Under the ``tpu`` scheduler policy, send_packet instead appends the packet to
the round's device batch; the TPU kernel performs the latency gather +
reliability draw for all packets at once (see ops/round_step.py).  Both paths
use the same counter-based RNG keyed by packet uid, so drops are identical.
"""

from __future__ import annotations

import threading
from typing import Optional

from . import stime
from .event import Event
from .task import Task
from .counters import ObjectCounter
from .logger import get_logger

_tls = threading.local()


def current_worker() -> Optional["Worker"]:
    return getattr(_tls, "worker", None)


def set_current_worker(w: Optional["Worker"]) -> None:
    _tls.worker = w


class Worker:
    def __init__(self, worker_id: int, engine):
        self.id = worker_id
        self.engine = engine                  # Slave-equivalent
        self.scheduler = engine.scheduler
        self.now: int = 0                     # current virtual time
        self.last_event_time: int = 0
        self.round_end: int = stime.SIM_TIME_MAX
        self.active_host = None
        self.active_process = None
        self.counters = ObjectCounter()
        self.min_next_event_time: int = stime.SIM_TIME_MAX

    # -- context -----------------------------------------------------------
    def set_active_host(self, host) -> None:
        self.active_host = host

    @property
    def emulated_now(self) -> int:
        return stime.emulated_from_sim(self.now)

    def is_bootstrapping(self) -> bool:
        """During the bootstrap grace period links are perfectly reliable and
        unthrottled (reference worker.c:445-453, master.c:261-268)."""
        return self.now < self.engine.bootstrap_end

    # -- scheduling --------------------------------------------------------
    def schedule_task(self, task: Task, delay_ns: int, dst_host=None) -> Optional[Event]:
        """Schedule ``task`` on ``dst_host`` (default: active host) after
        ``delay_ns``.  Reference worker.c:218 ``worker_scheduleTask``."""
        if not self.engine.is_running():
            return None
        src_host = self.active_host
        dst_host = dst_host if dst_host is not None else src_host
        t = self.now + max(0, int(delay_ns))
        if t >= self.engine.end_time:
            return None
        seq_owner = src_host if src_host is not None else dst_host
        seq = seq_owner.next_event_sequence() if seq_owner is not None \
            else self.engine.next_global_sequence()
        ev = Event(task, t, dst_host, src_host, seq)
        self.counters.count_new("event")
        self.scheduler.push(ev, self)
        return ev

    def reschedule_event(self, ev: Event, new_time: int) -> None:
        ev.time = int(new_time)
        self.scheduler.push(ev, self)

    # -- the inter-host hot path ------------------------------------------
    def send_packet(self, packet) -> None:
        """Move a packet from its source host toward its destination host.

        Mirrors reference worker.c:243-304: look up path reliability, draw a
        uniform keyed by the packet uid (NOT by execution order), drop or
        schedule delivery at now + latency.  The scheduler policy may clamp
        the delivery time to the next round barrier (causality; reference
        scheduler_policy_host_steal.c:229-242 does this for cross-host pushes).
        """
        if not self.engine.is_running():
            return
        offer = getattr(self.scheduler.policy, "offer_packet", None)
        if offer is not None:
            # tpu policy: defer the hop to the round's batched device step
            offer(packet, self)
            return
        topo = self.engine.topology
        src_ip, dst_ip = packet.src_ip, packet.dst_ip
        reliability = topo.reliability_ip(src_ip, dst_ip)
        # Bootstrap period: force-reliable links.
        if not self.is_bootstrapping() and reliability < 1.0:
            u = self.engine.packet_drop_uniform(packet.uid)
            if u > reliability:
                packet.add_status("INET_DROPPED")
                self.engine.count_packet_drop(packet)
                return
        latency = topo.latency_ns_ip(src_ip, dst_ip)
        packet.add_status("INET_SENT")
        engine = self.engine
        dst_host = engine.host_by_ip(dst_ip)
        if dst_host is None:
            packet.add_status("INET_DROPPED")
            return
        if not engine.owns_host(dst_host):
            # --processes shard boundary: claim the source-host sequence id
            # exactly where the local path would (inside schedule_task), then
            # ship the finished hop to the owner shard; it pushes the
            # delivery event with the identical (time, dst, src, seq) tuple.
            t = self.now + max(0, int(latency))  # schedule_task's normalization
            if t >= engine.end_time:
                return
            src_host = self.active_host
            if src_host is None:
                raise RuntimeError("cross-shard send without an active host")
            seq = src_host.next_event_sequence()
            self.counters.count_new("event")
            engine.shard_outboxes[engine.shard_of(dst_host)].append(
                (t, dst_host.id, src_host.id, seq, packet.to_wire()))
            return
        task = Task(_deliver_packet_task, dst_host, packet, name="deliver_packet")
        self.schedule_task(task, latency, dst_host=dst_host)

    # -- event loop --------------------------------------------------------
    def run_round(self) -> None:
        """Drain this worker's runnable events for the current window
        (reference worker.c:149-216 inner loop; the pop returns None at the
        window end).

        When the scheduler policy exposes a round executor (the native
        merged policy's ``run_window``, ISSUE 10), the WHOLE window is
        driven from one extension call and this loop never spins; the
        per-event loop below remains the fallback — and the continuation
        path when a mid-window executor failure demotes it (both paths
        execute the identical total order, so finishing a half-executed
        window per-event is exact)."""
        sched = self.scheduler
        rw = getattr(sched.policy, "run_window", None)
        if rw is not None and sched.is_running \
                and rw(self, sched.window_end):
            return
        while True:
            ev = self.scheduler.pop(self)
            if ev is None:
                break
            self.now = ev.time
            try:
                if ev.execute(self):
                    self.last_event_time = ev.time
                    self.counters.count_free("event")
                # else: CPU model deferred it — the same Event object was
                # re-pushed with a later time and will be accounted when it
                # actually runs.
            finally:
                # release the host execution lock taken by the policy pop
                self.scheduler.event_done(ev, self)

    def finish(self) -> None:
        self.engine.merge_counters(self.counters)


def _deliver_packet_task(dst_host, packet) -> None:
    """Arrival at the destination: enqueue into the upstream router (CoDel
    admit/drop) which feeds the interface receive loop.  Reference
    worker.c:236-241 ``_worker_runDeliverPacketTask`` → router_enqueue."""
    packet.add_status("ROUTER_ENQUEUED")
    iface = dst_host.interface_for_ip(packet.dst_ip)
    if iface is None:
        packet.add_status("INET_DROPPED")
        return
    if iface.router is not None:
        iface.router.enqueue(packet)
    else:
        iface.push_arrival(packet)
