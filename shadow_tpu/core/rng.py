"""Deterministic, order-independent random numbers for the simulator.

The reference derives all randomness from a seed hierarchy of ``rand_r``
streams (utility/random.c:32, master.c:417 master->slave->per-host seeding).
That design couples random draws to *execution order*, which would make the
CPU scheduler policies and the batched TPU kernel diverge the moment events
are reordered within a round.

We instead use a **counter-based** generator — Threefry-2x32 (Salmon et al.,
"Parallel Random Numbers: As Easy as 1, 2, 3", SC'11), the same block cipher
JAX's PRNG is built on — keyed by a (stream, substream) pair and indexed by a
64-bit counter.  A draw is a pure function ``threefry(key, counter)``:

* the CPU event loop evaluates it with numpy (vectorized or scalar), and
* the TPU round kernel evaluates the *identical* function with jax.numpy,

so reliability drops, jitter draws, etc. are bitwise identical no matter which
backend executes the packet hop or in what order packets are processed.

The seed hierarchy of the reference is preserved in spirit: a root seed
expands into named child streams via the same cipher (see :func:`derive`).
"""

from __future__ import annotations

from typing import Any, Tuple

import numpy as np

_MASK32 = np.uint32(0xFFFFFFFF)
# >>> simgen:begin region=threefry spec=293c930bb679 body=73de375b3b8e
# Threefry-2x32 rotation constants (Salmon et al., Table 2).
_ROTATIONS = (13, 15, 26, 6, 17, 29, 16, 24)
_PARITY = 0x1BD11BDA  # SKEIN_KS_PARITY32
# <<< simgen:end region=threefry


def _rotl32_np(x: np.ndarray, d: int) -> np.ndarray:
    return ((x << np.uint32(d)) | (x >> np.uint32(32 - d))) & _MASK32


def threefry2x32_np(k0, k1, c0, c1) -> Tuple[np.ndarray, np.ndarray]:
    """Threefry-2x32, 20 rounds, numpy (scalars or arrays of uint32)."""
    with np.errstate(over="ignore"):
        k0 = np.asarray(k0, dtype=np.uint32)
        k1 = np.asarray(k1, dtype=np.uint32)
        x0 = np.asarray(c0, dtype=np.uint32).copy()
        x1 = np.asarray(c1, dtype=np.uint32).copy()
        ks = (k0, k1, np.uint32(_PARITY) ^ k0 ^ k1)
        x0 = (x0 + ks[0]).astype(np.uint32)
        x1 = (x1 + ks[1]).astype(np.uint32)
        for block in range(5):  # 5 blocks of 4 rounds = 20 rounds
            rots = _ROTATIONS[0:4] if block % 2 == 0 else _ROTATIONS[4:8]
            for r in rots:
                x0 = (x0 + x1).astype(np.uint32)
                x1 = _rotl32_np(x1, r)
                x1 = x1 ^ x0
            x0 = (x0 + ks[(block + 1) % 3]).astype(np.uint32)
            x1 = (x1 + ks[(block + 2) % 3] + np.uint32(block + 1)).astype(np.uint32)
    return x0, x1


def threefry2x32_jnp(k0, k1, c0, c1):
    """Threefry-2x32, 20 rounds, jax.numpy — bitwise identical to the numpy
    version above (asserted by tests/test_rng.py)."""
    import jax.numpy as jnp

    k0 = jnp.asarray(k0, dtype=jnp.uint32)
    k1 = jnp.asarray(k1, dtype=jnp.uint32)
    x0 = jnp.asarray(c0, dtype=jnp.uint32)
    x1 = jnp.asarray(c1, dtype=jnp.uint32)
    ks = (k0, k1, jnp.uint32(_PARITY) ^ k0 ^ k1)
    x0 = x0 + ks[0]
    x1 = x1 + ks[1]
    for block in range(5):
        rots = _ROTATIONS[0:4] if block % 2 == 0 else _ROTATIONS[4:8]
        for r in rots:
            x0 = x0 + x1
            x1 = (x1 << r) | (x1 >> (32 - r))
            x1 = x1 ^ x0
        x0 = x0 + ks[(block + 1) % 3]
        x1 = x1 + ks[(block + 2) % 3] + jnp.uint32(block + 1)
    return x0, x1


_M32 = 0xFFFFFFFF


def threefry2x32_int(k0: int, k1: int, c0: int, c1: int) -> Tuple[int, int]:
    """Threefry-2x32 on plain Python ints — bitwise identical to the numpy
    version (same ops mod 2^32; asserted by tests/test_rng.py) and ~50x
    faster for SCALAR draws: numpy scalar arithmetic pays per-op dispatch
    that dominates host boot (20k hosts x 3 derive calls) and the per-packet
    CPU drop draw."""
    k0 &= _M32
    k1 &= _M32
    x0 = c0 & _M32
    x1 = c1 & _M32
    ks = (k0, k1, (_PARITY ^ k0 ^ k1) & _M32)
    x0 = (x0 + ks[0]) & _M32
    x1 = (x1 + ks[1]) & _M32
    for block in range(5):
        rots = _ROTATIONS[0:4] if block % 2 == 0 else _ROTATIONS[4:8]
        for r in rots:
            x0 = (x0 + x1) & _M32
            x1 = ((x1 << r) | (x1 >> (32 - r))) & _M32
            x1 ^= x0
        x0 = (x0 + ks[(block + 1) % 3]) & _M32
        x1 = (x1 + ks[(block + 2) % 3] + block + 1) & _M32
    return x0, x1


def _bits64_scalar(key: int, counter: int) -> int:
    x0, x1 = threefry2x32_int(key & _M32, (key >> 32) & _M32,
                              counter & _M32, (counter >> 32) & _M32)
    return x0 | (x1 << 32)


def _split64(v) -> Tuple[np.ndarray, np.ndarray]:
    v = np.asarray(v, dtype=np.uint64)
    return (v & np.uint64(0xFFFFFFFF)).astype(np.uint32), (v >> np.uint64(32)).astype(np.uint32)


def uniform_np(key: int, counter) -> np.ndarray:
    """Uniform float64 in [0, 1) from a 64-bit key and 64-bit counter(s).

    Uses the high lane's top 24 bits so the same construction is cheap and
    exact in float32 on device (see :func:`uniform_jnp`).
    """
    if isinstance(counter, (int, np.integer)):
        key_i = int(key) & 0xFFFFFFFFFFFFFFFF
        c = int(counter) & 0xFFFFFFFFFFFFFFFF
        x0, _ = threefry2x32_int(key_i & _M32, (key_i >> 32) & _M32,
                                 c & _M32, (c >> 32) & _M32)
        return np.float64((x0 >> 8) * (1.0 / (1 << 24)))
    k0, k1 = _split64(np.uint64(key & 0xFFFFFFFFFFFFFFFF))
    c0, c1 = _split64(counter)
    x0, _x1 = threefry2x32_np(k0, k1, c0, c1)
    return (x0 >> np.uint32(8)).astype(np.float64) * (1.0 / (1 << 24))


def uniform_jnp_pair(key: int, c_lo, c_hi):
    """Device-side twin of :func:`uniform_np` with the 64-bit counter passed
    as two uint32 lanes (works with or without jax x64 mode).

    float32 with the same 24-bit mantissa construction — bitwise-equal
    decisions for any threshold expressible in float32, which all
    reliability values are.
    """
    import jax.numpy as jnp

    kv = int(key) & 0xFFFFFFFFFFFFFFFF
    k0 = jnp.uint32(kv & 0xFFFFFFFF)
    k1 = jnp.uint32((kv >> 32) & 0xFFFFFFFF)
    c0 = jnp.asarray(c_lo, dtype=jnp.uint32)
    c1 = jnp.asarray(c_hi, dtype=jnp.uint32)
    x0, _x1 = threefry2x32_jnp(k0, k1, c0, c1)
    return (x0 >> jnp.uint32(8)).astype(jnp.float32) * jnp.float32(1.0 / (1 << 24))


def uniform_jnp(key, counter):
    """Device-side uniform taking 64-bit integer counters.

    Host-side (numpy/int) inputs are split into 32-bit lanes with numpy so
    they are always exact.  Device arrays must already be an 8-byte integer
    dtype (x64 mode, see shadow_tpu.ops) — a 4-byte device array is rejected
    rather than silently dropping the counter's high bits, which would make
    CPU and TPU drop decisions diverge for packet uids >= 2**32.
    """
    import jax.numpy as jnp

    if isinstance(counter, (int, np.integer, np.ndarray, list, tuple)):
        c_lo, c_hi = _split64(np.asarray(counter, dtype=np.uint64))
        return uniform_jnp_pair(key, c_lo, c_hi)
    counter = jnp.asarray(counter)
    if counter.dtype.itemsize != 8:
        raise ValueError(
            f"uniform_jnp needs a 64-bit counter dtype, got {counter.dtype}; "
            "import shadow_tpu.ops to enable x64 or pass lanes to uniform_jnp_pair")
    c_lo = (counter & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32)
    c_hi = (counter >> jnp.uint64(32)).astype(jnp.uint32)
    return uniform_jnp_pair(key, c_lo, c_hi)


def bits64_np(key: int, counter) -> np.ndarray:
    """64 random bits as uint64 from key + counter."""
    if isinstance(counter, (int, np.integer)):
        return np.uint64(_bits64_scalar(int(key) & 0xFFFFFFFFFFFFFFFF,
                                        int(counter) & 0xFFFFFFFFFFFFFFFF))
    k0, k1 = _split64(np.uint64(key & 0xFFFFFFFFFFFFFFFF))
    c0, c1 = _split64(counter)
    x0, x1 = threefry2x32_np(k0, k1, c0, c1)
    return x0.astype(np.uint64) | (x1.astype(np.uint64) << np.uint64(32))


def bits64_keys_np(keys, counter) -> np.ndarray:
    """64 random bits per KEY: the vector-key dual of :func:`bits64_np`
    (one key, many counters).  Used by the scale tier to evaluate the
    first draw of many per-host streams in one threefry call instead of a
    Python loop over 100k scalar ciphers (scale/hosttable.py)."""
    keys = np.asarray(keys, dtype=np.uint64)
    k0 = (keys & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    k1 = (keys >> np.uint64(32)).astype(np.uint32)
    c0, c1 = _split64(np.uint64(int(counter) & 0xFFFFFFFFFFFFFFFF))
    x0, x1 = threefry2x32_np(k0, k1, c0, c1)
    return x0.astype(np.uint64) | (x1.astype(np.uint64) << np.uint64(32))


def derive_np(key: int, label: Any, ids) -> np.ndarray:
    """Vectorized :func:`derive` over the FINAL path element: the child
    keys ``derive(key, label, i) for i in ids`` as one uint64 array.
    Bitwise identical to the scalar chain (tests/test_scale.py pins it) —
    the scalar derive folds each label with ``k = bits64(k, label)``, so
    only the last fold varies per id and the whole family is one
    vectorized cipher evaluation."""
    k1 = derive(key, label)
    return bits64_np(k1, np.asarray(ids, dtype=np.uint64))


def derive(key: int, *path: Any) -> int:
    """Derive a child 64-bit key from a parent key and a path of labels.

    Replaces the reference's seed hierarchy (master.c:417: master seeds slave,
    slave seeds scheduler and each host).  Labels may be ints or strings;
    strings are hashed with the cipher itself so derivation is stable across
    runs and platforms (no Python hash randomization).
    """
    k = np.uint64(key & 0xFFFFFFFFFFFFFFFF)
    for label in path:
        if isinstance(label, str):
            acc = np.uint64(1469598103934665603)  # FNV-1a offset basis
            for b in label.encode("utf-8"):
                acc = np.uint64((int(acc) ^ b) * 1099511628211 & 0xFFFFFFFFFFFFFFFF)
            label_int = int(acc)
        else:
            label_int = int(label) & 0xFFFFFFFFFFFFFFFF
        k = bits64_np(int(k), np.uint64(label_int))
    return int(k)


class RandomSource:
    """A sequential deterministic stream, for host-side draws that have a
    natural per-object ordering (e.g. a host's ephemeral-port allocator).

    Mirrors the role of the reference's ``Random`` (utility/random.c) but is
    built on the counter cipher, so streams never collide and reseeding is
    never needed.
    """

    __slots__ = ("key", "counter")

    def __init__(self, key: int):
        self.key = int(key) & 0xFFFFFFFFFFFFFFFF
        self.counter = 0

    def next_u64(self) -> int:
        v = _bits64_scalar(self.key, self.counter)
        self.counter += 1
        return v

    def next_double(self) -> float:
        v = float(uniform_np(self.key, self.counter))
        self.counter += 1
        return v

    def next_int(self, bound: int) -> int:
        """Uniform int in [0, bound)."""
        assert bound > 0
        return self.next_u64() % bound

    def next_bytes(self, n: int) -> bytes:
        out = bytearray()
        while len(out) < n:
            out += int(self.next_u64()).to_bytes(8, "little")
        return bytes(out[:n])

    def spawn(self, *path: Any) -> "RandomSource":
        return RandomSource(derive(self.key, *path))
