"""Simulator options — the CLI flag surface.

Mirrors the reference's Options (core/support/options.c): every knob the
reference exposes has an equivalent here, plus the new ``tpu`` scheduler
policy and device options.  Parsed with argparse; also constructible directly
for tests.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
from typing import List, Optional, Tuple

SCHEDULER_POLICIES = ("global", "host", "steal", "thread", "threadXthread",
                      "threadXhost", "tpu")
QDISC_KINDS = ("fifo", "rr")
ROUTER_QUEUE_KINDS = ("codel", "single", "static")

_SPEC_PATH = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))),
    "spec", "protocol_spec.json")
_FALLBACK_CC_KINDS = ("reno", "aimd", "cubic", "cubicx", "bbrx")


def _cc_kinds_from_spec() -> Tuple[str, ...]:
    """Valid --tcp-congestion-control tokens, in kind-id order, read from
    the authoritative spec.  The JSON is read directly (NOT via
    ops.protocol_tables) so importing options never pulls in jax; an
    installed copy without the spec tree falls back to the baked list."""
    try:
        with open(_SPEC_PATH, encoding="utf-8") as f:
            kinds = json.load(f)["congestion"]["kinds"]
    except (OSError, KeyError, ValueError):
        return _FALLBACK_CC_KINDS
    return tuple(sorted(kinds, key=lambda k: kinds[k]))


TCP_CC_KINDS = _cc_kinds_from_spec()


@dataclasses.dataclass
class Options:
    # Core (reference options.c flags)
    workers: int = 0                     # --workers (0 = serial, nWorkers=0 mode)
    processes: int = 0                   # --processes: shard the simulation
                                         # across N OS processes with a
                                         # conservative round barrier
                                         # (parallel/procs.py) — real
                                         # multicore scaling where the GIL
                                         # caps the threaded policies
    shard_id: int = 0                    # internal: this engine's shard
    shard_count: int = 1                 # internal: total shard engines
    scheduler_policy: str = "steal"      # --scheduler-policy (default steal, options.c:199)
    seed: int = 1                        # --seed
    runahead_ms: int = 0                 # --runahead (0 = derive from topology; floor 10ms)
    bootstrap_end_sec: int = 0           # <shadow bootstraptime>: grace period, no drops
    stop_time_sec: int = 60              # <shadow stoptime>
    stop_time_explicit: bool = False     # --stop-time given on the CLI
    # TCP
    tcp_congestion_control: str = "reno"  # --tcp-congestion-control
    tcp_ssthresh: int = 0                 # --tcp-ssthresh (0 = unset)
    tcp_windows: int = 10                 # --tcp-windows: initial cwnd and
                                          # pre-handshake send-window seed,
                                          # in packets (reference default 10,
                                          # options.c:77; recv window follows
                                          # the buffer size/autotuning)
    # Interface / buffers
    interface_qdisc: str = "fifo"        # --interface-qdisc
    interface_buffer: int = 1024000      # --interface-buffer (bytes)
    interface_batch_ms: int = 1          # --interface-batch: accepted for
                                         # flag parity only — the reference
                                         # parses it and never consumes it
                                         # (options.c:131); refills are
                                         # fixed at 1 ms (defs.py)
    router_queue: str = "codel"          # upstream AQM kind (reference host.c:205 default codel)
    socket_recv_buffer: int = 174760     # --socket-recv-buffer (0 = autotune)
    socket_send_buffer: int = 131072     # --socket-send-buffer (0 = autotune)
    socket_autotune: bool = True
    # CPU model
    cpu_threshold_ns: int = -1           # --cpu-threshold (ns of delay before block; <=0 disables)
    cpu_precision_ns: int = 200          # --cpu-precision
    # Telemetry
    heartbeat_interval_sec: int = 60     # --heartbeat-frequency
    heartbeat_log_level: str = "message"
    log_level: str = "message"           # --log-level
    pcap_dir: Optional[str] = None
    data_directory: str = "shadow.data"
    data_template: Optional[str] = None
    # TPU backend
    tpu_max_inflight: int = 1 << 16      # padded packet-batch capacity
    tpu_devices: int = 0                 # 0 = all local devices
    tpu_shard_matrix: bool = False       # row-shard path matrices over the mesh
    tpu_device_threshold: int = 0        # >0: batches below N bypass to numpy
    tpu_chunk: int = 0                   # mid-round async launch size (0=off)
    device_plane: str = "device"         # device | numpy (bit-identical twin)
    dataplane: str = "auto"              # auto | native | python: C data
                                         # plane for eligible serial runs
                                         # (parallel/native_plane.py)
    host_table: str = "auto"             # auto | on | off: struct-of-
                                         # arrays host plane with lazy
                                         # Host materialization
                                         # (scale/hosttable.py); auto = on
                                         # exactly when the config carries
                                         # processless device flows
                                         # (generated scale scenarios)
    device_plane_granule_ms: int = 0     # step size override (0 = auto)
    device_plane_batch_steps: int = 8    # min steps per kernel dispatch
    superwindow_rounds: int = 8          # max lookahead rounds merged into
                                         # one device launch when no host
                                         # event falls inside (1 = off)
    device_plane_sync: bool = False      # block on the dispatch at launch
                                         # (serial oracle; digests identical
                                         # to the pipelined default)
    exchange_mode: str = "auto"          # mesh cross-shard exchange kernel:
                                         # auto = measured cost model when
                                         # calibrated (simprof), else the
                                         # PR-9 heuristic; fused/ppermute
                                         # force one identical-result
                                         # kernel (digest parity pinned)
    device_autotune: str = "on"          # COSTMODEL-driven dispatch tuner
                                         # (prof/autotune.py): picks the
                                         # effective superwindow depth and
                                         # the delta-compacted flush from
                                         # measured per-box costs; only
                                         # ever chooses between digest-
                                         # identical executions. "off" =
                                         # the hand defaults, untouched
    cost_model: str = ""                 # --cost-model: per-box measured
                                         # cost model path (simprof
                                         # calibrate); "" = $SHADOW_COSTMODEL
                                         # or the repo-root COSTMODEL.json;
                                         # refuses a fingerprint mismatch
                                         # and falls back to heuristics
    # Checkpointing (new capability; absent in the reference — SURVEY.md §5)
    checkpoint_interval_sec: int = 0     # --checkpoint-interval (0 = off)
    checkpoint_every_rounds: int = 0     # --checkpoint-every N rounds (0 = off)
    checkpoint_dir: str = "shadow-checkpoints"  # --checkpoint-dir
    resume_path: Optional[str] = None    # --resume: snapshot file or dir;
                                         # replay-verify to the last good
                                         # snapshot's digest, then continue
    # Supervision / fault recovery (core/supervision.py)
    plugin_watchdog_sec: float = 0.0     # wall-clock silence budget per
                                         # native plugin; 0 = module default
                                         # (SHADOW_TPU_PLUGIN_STALL_TIMEOUT,
                                         # 300 s)
    device_watchdog_sec: float = 300.0   # timeout on collecting an in-flight
                                         # device dispatch (0 = unbounded)
    shard_watchdog_sec: float = 0.0      # parent aborts if a LIVE shard is
                                         # silent this long (0 = only dead-
                                         # shard detection, always on)
    fault_inject: str = ""               # deterministic fault harness
                                         # (supervision.parse_fault_inject)
    max_resurrections: int = 3           # --max-resurrections: dead-shard
                                         # respawn budget per run (ISSUE 17);
                                         # exceeded = abort loudly (the
                                         # PR-2 diagnostic), 0 = never
                                         # resurrect (PR-2 behavior)
    repromote_after: int = 0             # --repromote-after R: after a
                                         # demotion, re-attempt the faster
                                         # rung ONCE after R clean rounds
                                         # with the replay guard armed
                                         # (0 = demotions stay permanent)
    # Observability (shadow_tpu/obs/): flight-recorder tracing + metrics
    trace_path: Optional[str] = None     # --trace: Chrome trace-event JSON
                                         # (Perfetto-loadable) written at
                                         # end of run; enables the
                                         # flight-recorder span ring
    trace_ring: int = 0                  # --trace-ring: events kept per
                                         # track (0 = obs.trace.DEFAULT_RING)
    metrics_path: Optional[str] = None   # --metrics: JSONL scrape stream +
                                         # final summary record
    metrics_every_rounds: int = 0        # --metrics-every N rounds cadence
                                         # (0 = MetricsWriter.DEFAULT_EVERY)
    # Misc
    config_path: Optional[str] = None
    test_mode: bool = False              # --test builtin example


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="shadow-tpu",
        description="TPU-native discrete-event network simulator "
                    "(capabilities of Shadow 1.14.0, re-architected for JAX/XLA).")
    p.add_argument("config_path", nargs="?", help="simulation config (.xml, .yaml, .json)")
    p.add_argument("--workers", type=int, default=0)
    p.add_argument("--processes", type=int, default=0,
                   help="shard hosts across N OS processes exchanging "
                        "packets at round barriers (0 = single process)")
    p.add_argument("--scheduler-policy", choices=SCHEDULER_POLICIES, default="steal",
                   dest="scheduler_policy")
    p.add_argument("--seed", type=int, default=1)
    p.add_argument("--runahead", type=int, default=0, dest="runahead_ms",
                   help="minimum allowed lookahead window (ms)")
    p.add_argument("--stop-time", type=int, default=None, dest="stop_time_sec")
    p.add_argument("--bootstrap-end", type=int, default=None, dest="bootstrap_end_sec")
    p.add_argument("--tcp-congestion-control", choices=TCP_CC_KINDS, default="reno",
                   dest="tcp_congestion_control")
    p.add_argument("--tcp-ssthresh", type=int, default=0, dest="tcp_ssthresh")
    p.add_argument("--tcp-windows", type=int, default=10, dest="tcp_windows",
                   help="initial TCP windows in packets (reference options.c:138)")
    p.add_argument("--interface-qdisc", choices=QDISC_KINDS, default="fifo",
                   dest="interface_qdisc")
    p.add_argument("--interface-buffer", type=int, default=1024000, dest="interface_buffer")
    p.add_argument("--checkpoint-interval", type=int, default=0,
                   dest="checkpoint_interval_sec",
                   help="write a state snapshot every N virtual seconds")
    p.add_argument("--checkpoint-every", type=int, default=0,
                   dest="checkpoint_every_rounds",
                   help="write a state snapshot every N engine rounds "
                        "(composes with --checkpoint-interval; 0 = off)")
    p.add_argument("--checkpoint-dir", default="shadow-checkpoints",
                   dest="checkpoint_dir")
    p.add_argument("--resume", default=None, dest="resume_path",
                   help="resume from a snapshot file (or the newest good "
                        "snapshot in a checkpoint dir): deterministic "
                        "replay to the snapshot's virtual time, digest-"
                        "verified there, then the run continues")
    p.add_argument("--plugin-watchdog-sec", type=float, default=0.0,
                   dest="plugin_watchdog_sec",
                   help="kill a native plugin silent on its RPC socketpair "
                        "for this many wall seconds; its simulated process "
                        "is marked exited and the run continues (0 = the "
                        "SHADOW_TPU_PLUGIN_STALL_TIMEOUT default, 300 s)")
    p.add_argument("--device-watchdog-sec", type=float, default=300.0,
                   dest="device_watchdog_sec",
                   help="abandon an in-flight device-plane dispatch that "
                        "has not completed after this many wall seconds; "
                        "the window replays on the numpy twin and the "
                        "backend is demoted (0 = wait forever)")
    p.add_argument("--shard-watchdog-sec", type=float, default=0.0,
                   dest="shard_watchdog_sec",
                   help="abort with a diagnostic if a live shard is silent "
                        "this long at a round barrier (0 = no wall limit; "
                        "dead-shard detection is always on)")
    p.add_argument("--fault-inject", default="", dest="fault_inject",
                   help="deterministic fault-injection harness (tests): "
                        "device-dispatch:N | device-dispatch-hang:N | "
                        "plugin-stall:NAME:NREQ | shard-exit:SID:ROUND | "
                        "native-round:N | continuation-batch:N | "
                        "shard-exit-resurrect:SID:ROUND | device-lost:ROUND "
                        "| demote-repromote:N")
    p.add_argument("--max-resurrections", type=int, default=3,
                   dest="max_resurrections",
                   help="respawn a dead shard from the newest verifying "
                        "snapshot (round-zero replay when none exists) up "
                        "to N times per run, with exponential backoff "
                        "between attempts; the budget exhausted aborts "
                        "loudly (0 = never resurrect, abort on first death)")
    p.add_argument("--repromote-after", type=int, default=0,
                   dest="repromote_after",
                   help="recovery-ladder probation: after a demotion "
                        "(device plane -> numpy twin, native executor -> "
                        "per-event), re-attempt the faster rung ONCE after "
                        "R clean rounds with the window-replay guard armed; "
                        "a repeat fault re-demotes permanently (0 = "
                        "demotions stay permanent)")
    p.add_argument("--interface-batch", type=int, default=1, dest="interface_batch_ms")
    p.add_argument("--router-queue", choices=ROUTER_QUEUE_KINDS, default="codel",
                   dest="router_queue")
    p.add_argument("--socket-recv-buffer", type=int, default=174760, dest="socket_recv_buffer")
    p.add_argument("--socket-send-buffer", type=int, default=131072, dest="socket_send_buffer")
    p.add_argument("--cpu-threshold", type=int, default=-1, dest="cpu_threshold_ns")
    p.add_argument("--cpu-precision", type=int, default=200, dest="cpu_precision_ns")
    p.add_argument("--heartbeat-frequency", type=int, default=60, dest="heartbeat_interval_sec")
    p.add_argument("--log-level", choices=("error", "critical", "warning", "message",
                                           "info", "debug", "trace"), default="message",
                   dest="log_level")
    p.add_argument("--pcap-dir", default=None, dest="pcap_dir")
    p.add_argument("--data-directory", default="shadow.data", dest="data_directory")
    p.add_argument("--data-template", default=None, dest="data_template")
    p.add_argument("--tpu-max-inflight", type=int, default=1 << 16, dest="tpu_max_inflight")
    p.add_argument("--tpu-devices", type=int, default=0, dest="tpu_devices")
    p.add_argument("--tpu-shard-matrix", action="store_true",
                   dest="tpu_shard_matrix",
                   help="row-shard the path matrices across the device mesh "
                        "(for graphs whose tensors exceed one chip's HBM)")
    p.add_argument("--tpu-device-threshold", type=int, default=0,
                   dest="tpu_device_threshold",
                   help="route round batches smaller than N to the "
                        "bit-identical numpy path instead of the device "
                        "(0 = always dispatch to the device)")
    p.add_argument("--dataplane", choices=("auto", "native", "python"),
                   default="auto", dest="dataplane",
                   help="C data plane for the per-event hot path (auto: "
                        "engage when the run is serial/global-policy "
                        "without pcap/CPU-model/debug; native: require it; "
                        "python: pure-Python plane)")
    p.add_argument("--host-table", choices=("auto", "on", "off"),
                   default="auto", dest="host_table",
                   help="boot hosts as struct-of-arrays table rows with "
                        "lazy Host materialization (scale tier; digest-"
                        "identical to eager boot).  auto: on exactly when "
                        "the config has processless device flows")
    p.add_argument("--device-plane", choices=("device", "numpy"),
                   default="device", dest="device_plane",
                   help="execution mode for device-registered bulk flows: "
                        "'device' runs them in HBM, 'numpy' runs the "
                        "bit-identical host twin (parity/debug)")
    p.add_argument("--device-plane-granule-ms", type=int, default=0,
                   dest="device_plane_granule_ms",
                   help="device-plane step size in ms (0 = auto-sized from "
                        "the topology's max latency; bandwidth stays exact, "
                        "per-hop latency rounds up to the step)")
    p.add_argument("--device-plane-sync", action="store_true",
                   dest="device_plane_sync",
                   help="block on each device-plane dispatch at launch "
                        "instead of overlapping it with the round's host "
                        "work (the serial oracle: digests are identical to "
                        "the pipelined default, only wall time differs)")
    p.add_argument("--exchange-mode", choices=("auto", "fused", "ppermute"),
                   default="auto", dest="exchange_mode",
                   help="mesh cross-shard exchange kernel: 'auto' decides "
                        "from the measured cost model (simprof calibrate; "
                        "heuristic when uncalibrated), 'fused'/'ppermute' "
                        "force one of the identical-result kernels "
                        "(scheduling only — digests never change)")
    p.add_argument("--device-autotune", choices=("on", "off"),
                   default="on", dest="device_autotune",
                   help="COSTMODEL-driven dispatch auto-tuner: pick the "
                        "effective superwindow depth and the delta-"
                        "compacted flush from this box's measured costs "
                        "(prof/autotune.py; engages only with a loaded, "
                        "covering model and only moves knobs still at "
                        "their hand defaults — digests never change); "
                        "'off' restores the hand defaults exactly")
    p.add_argument("--cost-model", default="", dest="cost_model",
                   help="path to the per-box measured cost model "
                        "(simprof calibrate); default: $SHADOW_COSTMODEL "
                        "or the repo-root COSTMODEL.json; a fingerprint "
                        "mismatch refuses loudly and heuristics run")
    p.add_argument("--device-plane-batch-steps", type=int, default=8,
                   dest="device_plane_batch_steps",
                   help="accumulate at least N plane steps per kernel "
                        "dispatch (amortizes the per-dispatch state copy "
                        "on backends where the carried state cannot alias)")
    p.add_argument("--superwindow-rounds", type=int, default=8,
                   dest="superwindow_rounds",
                   help="merge up to N consecutive lookahead rounds into "
                        "ONE device-plane kernel launch whenever no "
                        "host-side event falls inside them (digest-"
                        "identical to per-round dispatch; 1 = disable)")
    p.add_argument("--tpu-chunk", type=int, default=0, dest="tpu_chunk",
                   help="launch a device step as soon as N packet hops "
                        "accumulate mid-round, overlapping device compute "
                        "with the rest of the round (0 = launch at the "
                        "barrier only)")
    p.add_argument("--trace", default=None, dest="trace_path",
                   help="record sim+wall-time spans into the flight "
                        "recorder and write Chrome trace-event JSON here "
                        "at end of run (load in Perfetto / "
                        "chrome://tracing)")
    p.add_argument("--trace-ring", type=int, default=0, dest="trace_ring",
                   help="flight-recorder depth: events kept per track "
                        "(bounded ring; 0 = default 65536)")
    p.add_argument("--metrics", default=None, dest="metrics_path",
                   help="scrape the metrics registry to this JSONL file on "
                        "a round cadence, plus a final summary record")
    p.add_argument("--metrics-every", type=int, default=0,
                   dest="metrics_every_rounds",
                   help="rounds between metrics scrapes (0 = default 50)")
    p.add_argument("--test", action="store_true", dest="test_mode",
                   help="run the built-in example simulation")
    return p


def parse_args(argv: Optional[List[str]] = None) -> Options:
    ns = build_parser().parse_args(argv)
    opts = Options()
    for f in dataclasses.fields(Options):
        v = getattr(ns, f.name, None)
        if v is not None:
            setattr(opts, f.name, v)
    opts.stop_time_explicit = ns.stop_time_sec is not None
    return opts
