"""Simulation logger: sim-time-stamped records, async buffered, round-flushed.

Capability parity with the reference's ShadowLogger pipeline
(core/logger/shadow_logger.c): records carry BOTH wall-clock and simulated
time; producers append to per-thread buffers; a flush (invoked by the engine
at round boundaries, slave.c:445-450) sorts records by (sim_time, thread) and
emits them, so multi-threaded runs produce stable, comparable logs.  The
determinism test (tests/test_determinism.py) diffs these logs between two
identically-seeded runs, exactly like the reference's
determinism*_compare.cmake + strip_log_for_compare.py gate.
"""

from __future__ import annotations

import sys
import threading
import time as _walltime
from typing import List, Optional, TextIO, Tuple

LEVELS = {"error": 0, "critical": 1, "warning": 2, "message": 3, "info": 4,
          "debug": 5, "trace": 6}


class LogRecord:
    __slots__ = ("sim_time", "wall_time", "thread", "level", "domain", "text")

    def __init__(self, sim_time, wall_time, thread, level, domain, text):
        self.sim_time = sim_time
        self.wall_time = wall_time
        self.thread = thread
        self.level = level
        self.domain = domain
        self.text = text

    def format(self) -> str:
        if self.sim_time is None or self.sim_time < 0:
            st = "n/a"
        else:
            secs, ns = divmod(self.sim_time, 1_000_000_000)
            h, rem = divmod(secs, 3600)
            m, s = divmod(rem, 60)
            st = f"{h:02d}:{m:02d}:{s:02d}.{ns:09d}"
        return f"{self.wall_time:.6f} [{self.thread}] {st} [{self.level}] [{self.domain}] {self.text}"


class SimLogger:
    def __init__(self, stream: Optional[TextIO] = None, level: str = "message",
                 buffered: bool = True):
        self.stream = stream if stream is not None else sys.stderr
        self.level = LEVELS.get(level, 3)
        self.buffered = buffered
        self._lock = threading.Lock()
        self._records: List[LogRecord] = []
        self._start = _walltime.monotonic()

    def set_level(self, level: str) -> None:
        self.level = LEVELS.get(level, 3)

    def would_log(self, level: str) -> bool:
        return LEVELS.get(level, 3) <= self.level

    def log(self, level: str, domain: str, text: str, sim_time: Optional[int] = None,
            thread: Optional[str] = None) -> None:
        if LEVELS.get(level, 3) > self.level:
            return
        if sim_time is None:
            # Pull the worker clock if one is active on this thread.
            from . import worker as _worker_mod
            w = _worker_mod.current_worker()
            sim_time = w.now if w is not None else -1
        rec = LogRecord(sim_time, _walltime.monotonic() - self._start,
                        thread or threading.current_thread().name, level, domain, text)
        if self.buffered:
            with self._lock:
                self._records.append(rec)
        else:
            with self._lock:
                self.stream.write(rec.format() + "\n")

    def pending(self) -> int:
        """Buffered record count — the round loop's dirty check (ISSUE 10
        compacted flush): a quiet round skips the flush entirely on one
        attribute read.  Unlocked on purpose: a record appended during the
        read is flushed one round later, which the sort-by-sim-time output
        contract is indifferent to."""
        return len(self._records)

    def flush(self) -> None:
        """Sort buffered records by (sim_time, thread) and emit (reference
        logger helper sorts by time then thread, logger_helper.c).  Free
        when nothing is buffered — the engine calls this once per round."""
        if not self._records:
            return
        with self._lock:
            records, self._records = self._records, []
        records.sort(key=lambda r: (r.sim_time if r.sim_time is not None else -1, r.thread))
        with self._lock:
            for r in records:
                self.stream.write(r.format() + "\n")
            try:
                self.stream.flush()
            except Exception:
                pass

    # Convenience levels
    def error(self, domain, text, **kw):   self.log("error", domain, text, **kw)
    def warning(self, domain, text, **kw): self.log("warning", domain, text, **kw)
    def message(self, domain, text, **kw): self.log("message", domain, text, **kw)
    def info(self, domain, text, **kw):    self.log("info", domain, text, **kw)
    def debug(self, domain, text, **kw):   self.log("debug", domain, text, **kw)


_default: Optional[SimLogger] = None

# Fleet lanes (ISSUE 18) run one engine per THREAD in a shared process;
# the process-global default would interleave every lane's records into
# one stream (and one lane's log_tail would leak into another's fuzz
# verdict).  A thread sets its own logger here and get_logger() prefers
# it — the process-global behavior is unchanged for every existing
# single-engine caller.
_tls = threading.local()


def get_logger() -> SimLogger:
    overlay = getattr(_tls, "logger", None)
    if overlay is not None:
        return overlay
    global _default
    if _default is None:
        _default = SimLogger()
    return _default


def set_logger(logger: SimLogger) -> None:
    global _default
    _default = logger


def set_thread_logger(logger: Optional[SimLogger]) -> None:
    """Route this THREAD's get_logger() to ``logger`` (None clears the
    overlay, falling back to the process-global default)."""
    _tls.logger = logger
