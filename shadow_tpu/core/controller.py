"""Controller: top-level simulation driver (reference Master, core/master.c).

Loads configuration + topology, registers programs and hosts into the
Engine, computes the lookahead, runs the simulation, reports results
(master_run :400 flow).
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional

from ..apps import registry as app_registry
from ..host.host import Host, HostParams
from ..process.process import Process
from ..routing.address import ip_to_int
from ..routing.topology import Topology, single_vertex_topology
from . import stime
from .configuration import Configuration
from .engine import Engine
from .logger import get_logger
from .options import Options


class Controller:
    def __init__(self, options: Options, config: Configuration):
        self.options = options
        self.config = config
        self.topology = self._load_topology()
        self.engine = Engine(options, self.topology)
        self._program_paths: Dict[str, str] = {}

    def _load_topology(self) -> Topology:
        cfg = self.config
        if cfg.topology_text:
            return Topology.from_graphml(cfg.topology_text)
        if cfg.topology_path:
            path = cfg.topology_path
            if not os.path.isabs(path) and self.options.config_path:
                base = os.path.dirname(os.path.abspath(self.options.config_path))
                cand = os.path.join(base, path)
                if os.path.exists(cand):
                    path = cand
            return Topology.from_file(path)
        return single_vertex_topology()

    def _validated_tcp_cc(self, hc):
        """Per-host <host tcpcc="..."> must name a known CC kind at
        CONFIG time — not crash as a KeyError in the native plane or a
        mid-run ValueError at first socket creation."""
        from .options import TCP_CC_KINDS
        if hc.tcp_cc and hc.tcp_cc not in TCP_CC_KINDS:
            raise ValueError(
                f"host {hc.id!r}: unknown tcpcc={hc.tcp_cc!r} "
                f"(choices: {', '.join(TCP_CC_KINDS)})")
        return hc.tcp_cc

    def _host_params_kwargs(self, hc) -> dict:
        """The HostParams keyword set shared by a whole config entry —
        everything but the per-host name and the topology-resolved
        bandwidths.  ONE construction point for the eager path and the
        HostTable's deferred materialization, so the two can never drift
        (the table-vs-object digest parity gates lean on it)."""
        opts = self.options
        return dict(
            qdisc=hc.qdisc or opts.interface_qdisc,
            tcp_cc=self._validated_tcp_cc(hc),
            router_queue=opts.router_queue,
            # 0 means "default start size + autotune", never a
            # zero-byte buffer (a 0 advertised window would
            # deadlock every transfer at handshake)
            recv_buf_size=(hc.socket_recv_buffer
                           or opts.socket_recv_buffer or 174760),
            send_buf_size=(hc.socket_send_buffer
                           or opts.socket_send_buffer or 131072),
            autotune_recv=opts.socket_autotune and not hc.socket_recv_buffer,
            autotune_send=opts.socket_autotune and not hc.socket_send_buffer,
            cpu_frequency_khz=hc.cpu_frequency_khz,
            cpu_threshold_ns=opts.cpu_threshold_ns,
            cpu_precision_ns=opts.cpu_precision_ns,
            interface_buffer=hc.interface_buffer or opts.interface_buffer,
            heartbeat_interval_sec=(hc.heartbeat_interval_sec
                                    or opts.heartbeat_interval_sec),
            log_pcap=hc.log_pcap,
            pcap_dir=hc.pcap_dir or opts.pcap_dir,
            ip_hint=hc.ip_hint, city_hint=hc.city_hint,
            country_hint=hc.country_hint, geocode_hint=hc.geocode_hint,
            type_hint=hc.type_hint,
            log_level=hc.log_level,
            heartbeat_log_level=hc.heartbeat_log_level)

    def _table_mode(self) -> bool:
        """Whether hosts boot as HostTable rows (scale/hosttable.py):
        --host-table on/off, or auto = on exactly when the config carries
        processless device flows (generated scale scenarios) — existing
        workloads keep the eager path and its native-plane eligibility."""
        mode = getattr(self.options, "host_table", "auto")
        if mode == "on":
            return True
        if mode == "off":
            return False
        return any(hc.flows for hc in self.config.hosts)

    def setup(self) -> None:
        """Register programs and hosts (master.c:279-392)."""
        opts = self.options
        # <shadow environment="K=V;..."> is injected into every native
        # plugin's environment (reference main.c:474-524); a config-level
        # preload path rides the same mechanism (main.c scrubs/builds
        # LD_PRELOAD the same way)
        self.engine.plugin_environment = dict(self.config.environment or {})
        if self.config.preload:
            prior = self.engine.plugin_environment.get("LD_PRELOAD", "")
            self.engine.plugin_environment["LD_PRELOAD"] = (
                self.config.preload + (" " + prior if prior else ""))
        for prog in self.config.programs:
            self._program_paths[prog.id] = prog.path

        from ..scale.memprof import BootProfile
        profile = BootProfile()
        profile.snapshot()
        if self._table_mode():
            self._setup_table_hosts()
        else:
            self._setup_eager_hosts()
        profile.commit(self.engine.total_host_count())
        profile.install(self.engine)
        self.topology.finalize()
        # the C data plane (parallel/native_plane.py): TCP/UDP pipeline +
        # interfaces + router + hop execute natively for eligible serial
        # runs; Python keeps the control plane.  No-op (with a logged
        # reason) when ineligible in auto mode.
        from ..parallel.native_plane import attach as attach_native
        attach_native(self.engine)

    def _setup_eager_hosts(self) -> None:
        """The classic boot path: one Host object per quantity expansion."""
        for hc in self.config.hosts:
            if hc.flows:
                raise ValueError(
                    f"host {hc.id!r} has device flows; flows need the host "
                    "table (--host-table=on or auto)")
            kw = self._host_params_kwargs(hc)
            for q in range(hc.quantity):
                name = hc.id if hc.quantity == 1 else f"{hc.id}{q + 1}"
                params = HostParams(
                    name=name,
                    bw_down_kibps=hc.bandwidth_down_kibps,
                    bw_up_kibps=hc.bandwidth_up_kibps, **kw)
                host = Host(self.engine.next_host_id(), params,
                            self.engine.root_key)
                requested_ip = ip_to_int(hc.ip_hint) if hc.ip_hint else None
                self.engine.add_host(host, requested_ip)
                for pc in hc.processes:
                    self._add_process(host, pc)

    def _setup_table_hosts(self) -> None:
        """Scale boot path: every host becomes a HostTable row; Host
        objects materialize lazily (scale/hosttable.py)."""
        from ..scale.hosttable import HostTable
        total = sum(hc.quantity for hc in self.config.hosts)
        table = HostTable(self.engine, total)
        self.engine.host_table = table
        from .configuration import tokenize_arguments
        for hc in self.config.hosts:
            table.reserve_group(hc, self._host_params_kwargs(hc),
                                self._add_process)
            grp = table.groups[-1]
            for pc in hc.processes:
                path = self._program_paths.get(pc.plugin, pc.plugin)
                table.add_group_process_spec(
                    grp, pc, path, tokenize_arguments(pc.arguments))
        table.freeze()

    def _add_process(self, host: Host, pc) -> None:
        path = self._program_paths.get(pc.plugin, pc.plugin)
        app_main = app_registry.resolve(path)
        from .configuration import tokenize_arguments
        args = tokenize_arguments(pc.arguments)
        stop_ns = stime.from_seconds(pc.stop_time_sec) if pc.stop_time_sec else 0
        proc = Process(host, f"{host.name}.{pc.plugin}", app_main, args,
                       start_time_ns=stime.from_seconds(pc.start_time_sec),
                       stop_time_ns=stop_ns, preload=pc.preload)
        proc.app_path = path    # device-plane scan matches on resolved app

    def run(self) -> int:
        self.setup()
        # device-mode clients in the workload promote their bulk traffic to
        # the device-resident plane (parallel/device_plane.py); None when
        # the workload has none — the engine hooks are then inert
        from ..parallel.device_plane import build_plane_from_engine
        self.engine.device_plane = build_plane_from_engine(
            self.engine, mode=getattr(self.options, "device_plane", "device"))
        return self.engine.run()


def run_simulation(options: Options, config: Configuration) -> int:
    """One-call entry used by the CLI and tests.  ``--processes N`` (N >= 2)
    routes to the sharded multi-process coordinator."""
    if getattr(options, "processes", 0) >= 2:
        from ..parallel.procs import run_sharded
        return run_sharded(options, config)
    return Controller(options, config).run()
