"""Wire/protocol constants.

Values match the reference's definitions.h so simulated byte/packet accounting
is comparable (file:line cited per group).
"""

# Ethernet/IP framing (definitions.h:169-193)
CONFIG_HEADER_SIZE_UDPIPETH = 42    # UDP+IP+ETH header bytes
CONFIG_HEADER_SIZE_TCPIPETH = 66    # TCP+IP+ETH header bytes (with options)
CONFIG_MTU = 1500
CONFIG_DATAGRAM_MAX_SIZE = 65507
CONFIG_TCP_MAX_SEGMENT_SIZE = CONFIG_MTU - (CONFIG_HEADER_SIZE_TCPIPETH - 14)  # IP payload minus TCP/IP hdr

# Interface batching (network_interface.c:93-95, 207-214)
INTERFACE_REFILL_INTERVAL_NS = 1_000_000        # 1 ms token refill
INTERFACE_CAPACITY_FACTOR = 1                   # capacity = refill*factor + MTU
CONFIG_RECEIVE_BATCH_TIME_NS = 10_000_000       # definitions.h:169

# TCP buffer sizing (definitions.h:109-114)
CONFIG_TCP_WMEM_MIN = 4096
CONFIG_TCP_WMEM_DEFAULT = 16384
CONFIG_TCP_WMEM_MAX = 4194304
CONFIG_TCP_RMEM_MIN = 4096
CONFIG_TCP_RMEM_DEFAULT = 87380
CONFIG_TCP_RMEM_MAX = 6291456

# TCP timers, in ms (definitions.h:115-131; NET_TCP_HZ = 1000 ms base)
NET_TCP_HZ_MS = 1000
CONFIG_TCP_RTO_INIT_MS = NET_TCP_HZ_MS
CONFIG_TCP_RTO_MIN_MS = NET_TCP_HZ_MS // 5
CONFIG_TCP_RTO_MAX_MS = NET_TCP_HZ_MS * 120
CONFIG_TCP_DELACK_MIN_MS = NET_TCP_HZ_MS // 25
CONFIG_TCP_DELACK_MAX_MS = NET_TCP_HZ_MS // 5
