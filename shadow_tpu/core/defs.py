"""Wire/protocol constants.

Values match the reference's definitions.h so simulated byte/packet accounting
is comparable.  The protocol-spec surfaces below are GENERATED from
``spec/protocol_spec.json`` (simgen; `make gen`) — edit the spec, not the
fenced region.
"""

# >>> simgen:begin region=wire-defs spec=293c930bb679 body=8d099a58ba06
# Ethernet/IP framing (reference definitions.h:169-193).
CONFIG_HEADER_SIZE_UDPIPETH = 42    # UDP+IP+ETH header bytes
CONFIG_HEADER_SIZE_TCPIPETH = 66    # TCP+IP+ETH header bytes (with options)
CONFIG_MTU = 1500
CONFIG_DATAGRAM_MAX_SIZE = 65507
CONFIG_TCP_MAX_SEGMENT_SIZE = CONFIG_MTU - (CONFIG_HEADER_SIZE_TCPIPETH - 14)  # 1448

# Interface token bucket (reference network_interface.c:93-95, 207-214).
INTERFACE_REFILL_INTERVAL_NS = 1000000        # 1 ms token refill
INTERFACE_CAPACITY_FACTOR = 1                   # capacity = refill*factor + MTU

# TCP buffer caps (reference definitions.h:109-114).
CONFIG_TCP_WMEM_MAX = 4194304
CONFIG_TCP_RMEM_MAX = 6291456

# TCP retransmit-timer bounds, ms (reference definitions.h:115-131).
CONFIG_TCP_RTO_INIT_MS = 1000
CONFIG_TCP_RTO_MIN_MS = 200
CONFIG_TCP_RTO_MAX_MS = 120000
# <<< simgen:end region=wire-defs

# Hand-kept knobs (not protocol-spec surfaces).
CONFIG_RECEIVE_BATCH_TIME_NS = 10_000_000       # definitions.h:169
CONFIG_TCP_WMEM_MIN = 4096
CONFIG_TCP_WMEM_DEFAULT = 16384
CONFIG_TCP_RMEM_MIN = 4096
CONFIG_TCP_RMEM_DEFAULT = 87380
NET_TCP_HZ_MS = 1000
CONFIG_TCP_DELACK_MIN_MS = NET_TCP_HZ_MS // 25
CONFIG_TCP_DELACK_MAX_MS = NET_TCP_HZ_MS // 5
