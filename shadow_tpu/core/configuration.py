"""Simulation configuration: hosts, plugins/programs, processes, topology.

Capability parity with the reference's Configuration
(core/support/configuration.c, element/attr schema configuration.h:37-99):

* ``<shadow stoptime bootstraptime environment preload>``
* ``<topology path=.../>`` or inline GraphML cdata
* ``<plugin id path startsymbol>`` — here a *program*: either a registered
  Python app (``python:echo``) or a native plugin path (later rounds)
* ``<host id quantity bandwidthdown bandwidthup iphint citycodehint
  countrycodehint geocodehint typehint socketrecvbuffer socketsendbuffer
  interfacebuffer qdisc loglevel logpcap pcapdir cpufrequency heartbeat...>``
* ``<process plugin starttime stoptime arguments>`` (child of host)

We accept the legacy XML verbatim plus a native YAML/JSON schema with the
same field names, so existing Shadow configs keep working.
"""

from __future__ import annotations

import dataclasses
import json
import xml.etree.ElementTree as ET
from typing import Dict, List, Optional


@dataclasses.dataclass
class ProcessConfig:
    plugin: str = ""                 # program id
    start_time_sec: float = 0.0
    stop_time_sec: float = 0.0       # 0 = run to sim end
    arguments: str = ""
    preload: Optional[str] = None


@dataclasses.dataclass
class FlowConfig:
    """A processless device-plane bulk flow (scale tier, shadow_tpu/scale/):
    the host transfers ``down_bytes``/``up_bytes`` with ``dest`` entirely on
    the device-resident traffic plane — no plugin ever executes on the host,
    so a quantity-expanded group of flow hosts stays struct-of-arrays table
    rows for the whole run (scale/hosttable.py).

    ``path`` is an optional comma-separated relay list in client order
    (guard,middle,exit) for tor-shaped 5-hop chains; absent = the 2-hop
    star shape (dest<->host).  ``tor_path_seed`` instead derives a distinct
    3-relay path per quantity-expanded host from a seeded draw over
    ``tor_relays`` hosts named ``<tor_relay_prefix>1..N`` (and a dest drawn
    over ``tor_servers`` hosts named ``<tor_server_prefix>1..N``), so a
    100k-client Tor shape needs ONE FlowConfig, not 100k.

    ``dest_seed`` draws a distinct 2-hop destination per quantity-expanded
    host from a seeded draw over ``dest_count`` hosts named
    ``<dest_prefix>1..N`` (a draw landing on the host itself shifts to the
    next name, so a group can target its own peers) — the cdn flash-crowd
    (many clients over few origins) and the swarm many-to-many shape need
    ONE FlowConfig per piece, not one per client.  ``stagger``:
    host q's start is start_time_sec + (q %% stagger_waves) * stagger_step_sec."""
    dest: str = ""
    start_time_sec: float = 1.0
    down_bytes: int = 65536
    up_bytes: int = 0
    path: Optional[str] = None
    stagger_waves: int = 1
    stagger_step_sec: float = 0.0
    tor_path_seed: Optional[int] = None
    tor_relays: int = 0
    tor_relay_prefix: str = "relay"
    tor_servers: int = 0
    tor_server_prefix: str = "dest"
    dest_seed: Optional[int] = None
    dest_count: int = 0
    dest_prefix: str = ""


def tokenize_arguments(arguments: str) -> List[str]:
    """Shell-style tokenization of a <process arguments=...> string: a
    superset of the reference's bare strtok-on-spaces (process.c:769) that
    also supports quoted arguments.  Unbalanced quotes fall back to plain
    split.  ONE definition shared by the eager process constructor
    (core/controller.py) and the host table's deferred process specs
    (scale/hosttable.py) so both paths parse identically."""
    if not arguments:
        return []
    if '"' in arguments or "'" in arguments or "\\" in arguments:
        import shlex
        try:
            return shlex.split(arguments)
        except ValueError:
            return arguments.split()
    return arguments.split()


@dataclasses.dataclass
class HostConfig:
    id: str = "host"
    quantity: int = 1
    bandwidth_down_kibps: int = 0    # KiB/s, 0 = from topology vertex
    bandwidth_up_kibps: int = 0
    ip_hint: Optional[str] = None
    city_hint: Optional[str] = None
    country_hint: Optional[str] = None
    geocode_hint: Optional[str] = None
    type_hint: Optional[str] = None
    socket_recv_buffer: int = 0      # 0 = simulator default / autotune
    socket_send_buffer: int = 0
    interface_buffer: int = 0
    qdisc: Optional[str] = None
    tcp_cc: Optional[str] = None     # per-host congestion control
    log_level: Optional[str] = None
    log_pcap: bool = False
    pcap_dir: Optional[str] = None
    cpu_frequency_khz: int = 0       # 0 = disable CPU delay model
    heartbeat_interval_sec: int = 0
    heartbeat_log_level: Optional[str] = None
    heartbeat_log_info: str = "node"
    processes: List[ProcessConfig] = dataclasses.field(default_factory=list)
    flows: List[FlowConfig] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class ProgramConfig:
    id: str = ""
    path: str = ""                   # "python:<app-name>" or native .so path
    start_symbol: Optional[str] = None


@dataclasses.dataclass
class Configuration:
    stop_time_sec: float = 60.0
    bootstrap_end_sec: float = 0.0
    environment: Dict[str, str] = dataclasses.field(default_factory=dict)
    preload: Optional[str] = None
    topology_path: Optional[str] = None
    topology_text: Optional[str] = None   # inline GraphML
    programs: List[ProgramConfig] = dataclasses.field(default_factory=list)
    hosts: List[HostConfig] = dataclasses.field(default_factory=list)

    def total_process_count(self) -> int:
        return sum(h.quantity * len(h.processes) for h in self.hosts)


def _to_int(v, default=0) -> int:
    if v is None or v == "":
        return default
    return int(float(v))


def _to_float(v, default=0.0) -> float:
    if v is None or v == "":
        return default
    return float(v)


def _parse_time_sec(v, default=0.0) -> float:
    """Times in configs are seconds (reference XML uses integer seconds;
    we accept fractional)."""
    return _to_float(v, default)


def parse_xml(text: str) -> Configuration:
    """Parse the legacy ``shadow.config.xml`` schema."""
    root = ET.fromstring(text)
    if root.tag != "shadow":
        raise ValueError(f"expected <shadow> root element, got <{root.tag}>")
    cfg = Configuration()
    cfg.stop_time_sec = _parse_time_sec(root.get("stoptime"), 60.0)
    cfg.bootstrap_end_sec = _parse_time_sec(root.get("bootstraptime"), 0.0)
    cfg.preload = root.get("preload")
    env = root.get("environment")
    if env:
        for pair in env.split(";"):
            if "=" in pair:
                k, _, v = pair.partition("=")
                cfg.environment[k] = v

    for el in root:
        if el.tag == "topology":
            cfg.topology_path = el.get("path")
            if el.text and el.text.strip():
                cfg.topology_text = el.text.strip()
        elif el.tag == "plugin":
            cfg.programs.append(ProgramConfig(
                id=el.get("id", ""), path=el.get("path", ""),
                start_symbol=el.get("startsymbol")))
        elif el.tag in ("host", "node"):
            h = HostConfig(
                id=el.get("id", "host"),
                quantity=_to_int(el.get("quantity"), 1),
                bandwidth_down_kibps=_to_int(el.get("bandwidthdown")),
                bandwidth_up_kibps=_to_int(el.get("bandwidthup")),
                ip_hint=el.get("iphint"),
                city_hint=el.get("citycodehint"),
                country_hint=el.get("countrycodehint"),
                geocode_hint=el.get("geocodehint"),
                type_hint=el.get("typehint"),
                socket_recv_buffer=_to_int(el.get("socketrecvbuffer")),
                socket_send_buffer=_to_int(el.get("socketsendbuffer")),
                interface_buffer=_to_int(el.get("interfacebuffer")),
                qdisc=el.get("qdisc"),
                tcp_cc=el.get("tcpcc"),
                log_level=el.get("loglevel"),
                log_pcap=(el.get("logpcap", "").lower() in ("1", "true", "yes")),
                pcap_dir=el.get("pcapdir"),
                cpu_frequency_khz=_to_int(el.get("cpufrequency")),
                heartbeat_interval_sec=_to_int(el.get("heartbeatfrequency")),
                heartbeat_log_level=el.get("heartbeatloglevel"),
                heartbeat_log_info=el.get("heartbeatloginfo", "node"),
            )
            for pel in el:
                if pel.tag in ("process", "application"):
                    h.processes.append(ProcessConfig(
                        plugin=pel.get("plugin", ""),
                        start_time_sec=_parse_time_sec(pel.get("starttime")),
                        stop_time_sec=_parse_time_sec(pel.get("stoptime")),
                        arguments=pel.get("arguments", ""),
                        preload=pel.get("preload")))
                elif pel.tag == "flow":
                    h.flows.append(FlowConfig(
                        dest=pel.get("dest", ""),
                        start_time_sec=_parse_time_sec(pel.get("starttime"), 1.0),
                        down_bytes=_to_int(pel.get("down"), 65536),
                        up_bytes=_to_int(pel.get("up")),
                        path=pel.get("path"),
                        stagger_waves=_to_int(pel.get("staggerwaves"), 1),
                        stagger_step_sec=_to_float(pel.get("staggerstep")),
                        tor_path_seed=(_to_int(pel.get("torpathseed"))
                                       if pel.get("torpathseed") else None),
                        tor_relays=_to_int(pel.get("torrelays")),
                        tor_relay_prefix=pel.get("torrelayprefix", "relay"),
                        tor_servers=_to_int(pel.get("torservers")),
                        tor_server_prefix=pel.get("torserverprefix", "dest"),
                        dest_seed=(_to_int(pel.get("destseed"))
                                   if pel.get("destseed") else None),
                        dest_count=_to_int(pel.get("destcount")),
                        dest_prefix=pel.get("destprefix", "")))
            cfg.hosts.append(h)
    return cfg


def parse_dict(d: dict) -> Configuration:
    """Parse the native YAML/JSON schema (same field names, nested)."""
    cfg = Configuration()
    g = d.get("general", d)
    cfg.stop_time_sec = _parse_time_sec(g.get("stop_time"), 60.0)
    cfg.bootstrap_end_sec = _parse_time_sec(g.get("bootstrap_end_time"), 0.0)
    cfg.environment = dict(g.get("environment", {}))
    topo = d.get("network", d.get("topology", {}))
    if isinstance(topo, str):
        cfg.topology_path = topo
    elif isinstance(topo, dict):
        graph = topo.get("graph", topo)
        cfg.topology_path = graph.get("path")
        cfg.topology_text = graph.get("inline") or graph.get("text")
    for pid, p in (d.get("programs", {}) or {}).items():
        if isinstance(p, str):
            cfg.programs.append(ProgramConfig(id=pid, path=p))
        else:
            cfg.programs.append(ProgramConfig(id=pid, path=p.get("path", ""),
                                              start_symbol=p.get("start_symbol")))
    hosts = d.get("hosts", {})
    items = hosts.items() if isinstance(hosts, dict) else ((h.get("id", f"host{i}"), h) for i, h in enumerate(hosts))
    for hid, h in items:
        hc = HostConfig(
            id=hid,
            quantity=_to_int(h.get("quantity"), 1),
            bandwidth_down_kibps=_to_int(h.get("bandwidth_down")),
            bandwidth_up_kibps=_to_int(h.get("bandwidth_up")),
            ip_hint=h.get("ip_addr") or h.get("ip_hint"),
            city_hint=h.get("city_code_hint"),
            country_hint=h.get("country_code_hint"),
            geocode_hint=h.get("geocode_hint"),
            type_hint=h.get("type_hint"),
            socket_recv_buffer=_to_int(h.get("socket_recv_buffer")),
            socket_send_buffer=_to_int(h.get("socket_send_buffer")),
            interface_buffer=_to_int(h.get("interface_buffer")),
            qdisc=h.get("qdisc"),
            tcp_cc=h.get("tcpcc") or h.get("tcp_cc"),
            log_level=h.get("log_level"),
            log_pcap=bool(h.get("pcap", False)),
            pcap_dir=h.get("pcap_dir"),
            cpu_frequency_khz=_to_int(h.get("cpu_frequency")),
            heartbeat_interval_sec=_to_int(h.get("heartbeat_interval")),
        )
        for p in h.get("processes", []):
            hc.processes.append(ProcessConfig(
                plugin=p.get("path", p.get("plugin", "")),
                start_time_sec=_parse_time_sec(p.get("start_time")),
                stop_time_sec=_parse_time_sec(p.get("stop_time")),
                arguments=p.get("args", p.get("arguments", "")) if not isinstance(
                    p.get("args"), list) else " ".join(str(a) for a in p["args"]),
            ))
        for fl in h.get("flows", []):
            hc.flows.append(FlowConfig(
                dest=fl.get("dest", ""),
                start_time_sec=_parse_time_sec(fl.get("start_time"), 1.0),
                down_bytes=_to_int(fl.get("down_bytes"), 65536),
                up_bytes=_to_int(fl.get("up_bytes")),
                path=fl.get("path"),
                stagger_waves=_to_int(fl.get("stagger_waves"), 1),
                stagger_step_sec=_to_float(fl.get("stagger_step_sec")),
                tor_path_seed=(_to_int(fl.get("tor_path_seed"))
                               if fl.get("tor_path_seed") is not None
                               else None),
                tor_relays=_to_int(fl.get("tor_relays")),
                tor_relay_prefix=fl.get("tor_relay_prefix", "relay"),
                tor_servers=_to_int(fl.get("tor_servers")),
                tor_server_prefix=fl.get("tor_server_prefix", "dest"),
                dest_seed=(_to_int(fl.get("dest_seed"))
                           if fl.get("dest_seed") is not None else None),
                dest_count=_to_int(fl.get("dest_count")),
                dest_prefix=fl.get("dest_prefix", "")))
        cfg.hosts.append(hc)
    return cfg


def load(path: str) -> Configuration:
    with open(path, "r") as f:
        text = f.read()
    if path.endswith(".xml") or text.lstrip().startswith("<"):
        return parse_xml(text)
    if path.endswith(".json"):
        return parse_dict(json.loads(text))
    import yaml
    return parse_dict(yaml.safe_load(text))
