"""Supervision & fault-recovery accounting (ISSUE 2).

The simulator runs *real* OS processes (plugin binaries, shard engines) and
asynchronous device dispatches, so it inherits every way a real process can
wedge: a plugin that stops responding, an in-flight kernel dispatch that
fails or never completes, a shard process that dies mid-protocol.  Each of
those seams now carries a watchdog; this module is the shared ledger they
report into, plus the parser for the deterministic fault-injection harness
the recovery tests drive.

Recovery accounting is deliberately separate from ``engine.plugin_errors``:
a *supervised* kill (watchdog fired, simulation continued by design) is a
counted recovery, not a failure — the run's exit code reflects unsupervised
faults only, and bench.py exports ``recoveries``/``watchdog_overhead_sec``
so the steady-state cost of the supervision layer stays pinned at ~0.
"""

from __future__ import annotations

from typing import Dict, Optional

from .logger import get_logger


class SupervisionStats:
    """Per-run ledger of watchdog fires, degradations, and their cost.

    ``overhead_ns`` accumulates ONLY the bookkeeping the supervision layer
    adds on the healthy path (guard-thread spawn, liveness polls) — never
    the time legitimately spent waiting on results — so it is an honest
    measure of what supervision costs when nothing goes wrong.
    """

    __slots__ = ("plugin_watchdog_kills", "dispatch_recoveries",
                 "shard_deaths_detected", "native_round_demotions",
                 "shard_resurrections", "reshards", "repromotions",
                 "mttr_ns", "overhead_ns", "resume_path", "resume_verified")

    def __init__(self) -> None:
        self.plugin_watchdog_kills = 0
        self.dispatch_recoveries = 0
        self.shard_deaths_detected = 0
        self.native_round_demotions = 0
        self.shard_resurrections = 0
        self.reshards = 0
        self.repromotions = 0
        self.mttr_ns = 0
        self.overhead_ns = 0
        self.resume_path: Optional[str] = None
        self.resume_verified = False

    @property
    def recoveries(self) -> int:
        return (self.plugin_watchdog_kills + self.dispatch_recoveries
                + self.shard_deaths_detected + self.native_round_demotions
                + self.shard_resurrections + self.reshards
                + self.repromotions)

    @staticmethod
    def _dump_flight_recorder(reason: str) -> None:
        """Every recovery arrives with its timeline attached: the flight
        recorder's recent spans are logged alongside the watchdog report
        (ISSUE 3).  A no-op note when the run wasn't traced."""
        from ..obs.trace import get_tracer
        get_tracer().dump_recent("supervision", reason)

    def count_plugin_kill(self, name: str, reason: str) -> None:
        self.plugin_watchdog_kills += 1
        get_logger().warning(
            "supervision",
            f"plugin {name} killed by watchdog ({reason}); its simulated "
            "process is marked exited — the host and round loop continue")
        self._dump_flight_recorder(f"plugin watchdog: {name}")

    def count_dispatch_recovery(self, reason: str) -> None:
        self.dispatch_recoveries += 1
        get_logger().warning("supervision", reason)
        self._dump_flight_recorder("device dispatch recovery")

    def count_native_round_demotion(self, reason: str) -> None:
        """The C round executor failed mid-window; the per-event pop path
        finished the window (both paths execute the identical total order,
        so resuming per-event after K executed events is exact) and takes
        over permanently — same graceful-degradation contract as the
        device dispatch guard (ISSUE 10)."""
        self.native_round_demotions += 1
        get_logger().warning(
            "supervision",
            f"native round executor failed ({reason}); window completed on "
            "the per-event path — executor permanently demoted")
        self._dump_flight_recorder("native round executor demotion")

    def count_shard_resurrection(self, sid: int, attempt: int,
                                 mttr_ns: int) -> None:
        """A dead shard was respawned, deterministically replayed to the
        round barrier, digest-verified at the join boundary, and the run
        CONTINUED (ISSUE 17) — a bounded, measured detour rather than an
        abort.  ``mttr_ns`` is detection → rejoin wall time."""
        self.shard_resurrections += 1
        self.mttr_ns += mttr_ns
        get_logger().warning(
            "supervision",
            f"shard {sid} resurrected (attempt {attempt}) and rejoined the "
            f"round barrier after {mttr_ns / 1e9:.2f}s — run continues")
        self._dump_flight_recorder(f"shard resurrection: {sid}")

    def count_reshard(self, n_before: int, n_after: int,
                      mttr_ns: int = 0) -> None:
        """The sharded mesh lost a device mid-run and re-partitioned onto
        the survivors at a quiesced boundary, with the state translation
        digest-pinned before == after (ROADMAP 4(b))."""
        self.reshards += 1
        self.mttr_ns += mttr_ns
        get_logger().warning(
            "supervision",
            f"mesh re-sharded {n_before} -> {n_after} devices at a "
            "quiesced boundary; re-layout digest verified — run continues")
        self._dump_flight_recorder(f"mesh re-shard: {n_before}->{n_after}")

    def count_repromotion(self, rung: str, after_rounds: int) -> None:
        """A demoted rung climbed back after its probation: ``after_rounds``
        clean rounds passed, the faster path was re-attempted with the
        replay guard armed, and it held.  One shot only — a second fault on
        the same rung re-demotes permanently (ISSUE 17)."""
        self.repromotions += 1
        get_logger().warning(
            "supervision",
            f"{rung} re-promoted after {after_rounds} clean probation "
            "rounds — replay guard stays armed; next fault is permanent")
        self._dump_flight_recorder(f"re-promotion: {rung}")

    def summary(self) -> Dict:
        return {
            "recoveries": self.recoveries,
            "plugin_watchdog_kills": self.plugin_watchdog_kills,
            "dispatch_recoveries": self.dispatch_recoveries,
            "shard_deaths_detected": self.shard_deaths_detected,
            "native_round_demotions": self.native_round_demotions,
            "shard_resurrections": self.shard_resurrections,
            "reshards": self.reshards,
            "repromotions": self.repromotions,
            "mttr_sec": round(self.mttr_ns / 1e9, 4),
            "watchdog_overhead_sec": round(self.overhead_ns / 1e9, 4),
        }


def parse_fault_inject(spec: str) -> Optional[Dict]:
    """Parse a ``--fault-inject`` token (the deterministic fault harness the
    recovery tests drive; a no-op in production runs).  Formats:

    * ``device-dispatch:N``      — poison the Nth device-plane dispatch so
      its collect raises (exercises the numpy-replay degradation path);
    * ``device-dispatch-hang:N`` — the Nth dispatch's collect hangs instead
      (exercises the dispatch watchdog timeout);
    * ``plugin-stall:NAME:NREQ`` — SIGSTOP the native plugin whose process
      name contains NAME after serving its NREQth request (a plugin frozen
      mid-syscall-stream; exercises the plugin watchdog);
    * ``shard-exit:SID:ROUND``   — shard SID hard-exits (``os._exit``, no
      error report — simulating SIGKILL/OOM) at the start of round ROUND
      (exercises dead-shard detection);
    * ``native-round:N``         — the Nth C round-executor window raises,
      exercising permanent demotion to the per-event dispatch path with
      digest parity (ISSUE 10);
    * ``continuation-batch:N``   — the Nth batched-continuation delivery
      (py_exec_batch) raises mid-window, exercising demotion to the
      per-event pop loop where continuations deliver one callback each
      (ISSUE 12);
    * ``shard-exit-resurrect:SID:ROUND`` — shard SID hard-exits at round
      ROUND exactly like ``shard-exit``, but the parent is expected to
      RESURRECT it (respawn + deterministic replay to the barrier) rather
      than abort — the self-healing drill (ISSUE 17);
    * ``device-lost:ROUND``      — the sharded mesh "loses" a device at
      round ROUND: the plane re-partitions onto D-1 survivors at the next
      quiesced boundary with the re-layout digest pinned (ISSUE 17);
    * ``demote-repromote:N``     — the Nth device dispatch is poisoned like
      ``device-dispatch:N`` but the demotion is expected to heal: after
      ``--repromote-after`` clean rounds the plane re-attempts the device
      rung once (ISSUE 17).
    """
    if not spec:
        return None
    parts = spec.split(":")
    kind = parts[0]
    if kind in ("device-dispatch", "device-dispatch-hang"):
        if len(parts) != 2:
            raise ValueError(f"--fault-inject {spec!r}: expected {kind}:N")
        return {"kind": kind, "dispatch": int(parts[1])}
    if kind == "plugin-stall":
        if len(parts) != 3:
            raise ValueError(
                f"--fault-inject {spec!r}: expected plugin-stall:NAME:NREQ")
        return {"kind": kind, "name": parts[1], "nreq": int(parts[2])}
    if kind in ("shard-exit", "shard-exit-resurrect"):
        if len(parts) != 3:
            raise ValueError(
                f"--fault-inject {spec!r}: expected {kind}:SID:ROUND")
        return {"kind": kind, "shard": int(parts[1]), "round": int(parts[2])}
    if kind == "device-lost":
        if len(parts) != 2:
            raise ValueError(f"--fault-inject {spec!r}: expected "
                             "device-lost:ROUND")
        return {"kind": kind, "round": int(parts[1])}
    if kind == "demote-repromote":
        if len(parts) != 2:
            raise ValueError(f"--fault-inject {spec!r}: expected "
                             "demote-repromote:N")
        return {"kind": kind, "dispatch": int(parts[1])}
    if kind == "native-round":
        if len(parts) != 2:
            raise ValueError(f"--fault-inject {spec!r}: expected "
                             "native-round:N")
        return {"kind": kind, "window": int(parts[1])}
    if kind == "continuation-batch":
        if len(parts) != 2:
            raise ValueError(f"--fault-inject {spec!r}: expected "
                             "continuation-batch:N")
        return {"kind": kind, "batch": int(parts[1])}
    raise ValueError(f"--fault-inject {spec!r}: unknown fault kind {kind!r}")
