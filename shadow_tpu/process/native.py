"""Native plugin plane: run real, unmodified binaries under the simulator.

Capability parity with the reference's interposition substrate — preload/
interposer.c routing libc calls to process.c's process_emu_* family, with
rpth green threads providing blocking semantics against the virtual clock
(SURVEY.md §2.7).  Our architecture runs each plugin as a real OS process
with ``libshadow_preload.so`` (native/preload/shim.cc) LD_PRELOADed; every
interposed libc call arrives here over a socketpair as a framed request
(native/preload/protocol.h) and is executed against the same virtual-kernel
objects the Python plugin plane uses (descriptors, DNS, timers, random).

Scheduling contract (the determinism core): the plugin process only executes
between our response and its next request.  The green thread that serves a
plugin blocks in a *real* ``recv`` while the plugin computes — plugin code
is "instantaneous" in virtual time, exactly like the reference's pth model
(process.c:1197 process_continue runs green threads until all block).  When
a request can't complete (blocking recv on an empty socket), the serving
green thread yields to the simulator and the response is simply delayed
until the virtual clock makes the operation ready — which is how real
blocking apps run under a discrete-event clock.
"""

from __future__ import annotations

import atexit
import errno as errno_mod
import os
import socket as real_socket
import struct
import subprocess
from typing import List, Optional

from ..core import stime
from ..core.logger import get_logger
from ..descriptor.base import Descriptor, S_CLOSED, S_READABLE, S_WRITABLE
from ..descriptor.epoll import Epoll, EPOLLERR, EPOLLHUP, EPOLLIN, EPOLLOUT
from ..obs.trace import get_tracer
from .process import _Block, _Sleep

# -- protocol constants (mirror native/preload/protocol.h) -------------------
OP_SOCKET = 1
OP_BIND = 2
OP_LISTEN = 3
OP_ACCEPT = 4
OP_CONNECT = 5
OP_SEND = 6
OP_SENDTO = 7
OP_RECV = 8
OP_RECVFROM = 9
OP_CLOSE = 10
OP_EPOLL_CREATE = 11
OP_EPOLL_CTL = 12
OP_EPOLL_WAIT = 13
OP_POLL = 14
OP_GETTIME = 15
OP_SLEEP = 16
OP_GETADDRINFO = 17
OP_GETHOSTNAME = 18
OP_RANDOM = 19
OP_SETSOCKOPT = 20
OP_GETSOCKOPT = 21
OP_GETSOCKNAME = 22
OP_GETPEERNAME = 23
OP_SHUTDOWN = 24
OP_FCNTL = 25
OP_IOCTL = 26
OP_OPEN_RANDOM = 27
OP_READ = 28
OP_WRITE = 29
OP_EXIT = 30
OP_LOG = 31
OP_TIMERFD_CREATE = 32
OP_TIMERFD_SETTIME = 33
OP_PIPE = 34
OP_SOCKETPAIR = 35
OP_EVENTFD = 36
OP_SIGNALFD = 37
OP_KILL = 38
OP_GETNAMEINFO = 39

REQ_HDR = struct.Struct("<IIqqqq")
RESP_HDR = struct.Struct("<IIqq")

O_NONBLOCK = 0o4000
F_GETFL = 3
F_SETFL = 4
FIONREAD = 0x541B
SOCK_STREAM = 1
SOCK_DGRAM = 2
SOL_SOCKET = 1
SO_ERROR = 4
SO_SNDBUF = 7
SO_RCVBUF = 8
POLLIN = 0x001
POLLOUT = 0x004
POLLERR = 0x008
POLLHUP = 0x010

_PRELOAD_LIB = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                            "native", "libshadow_preload.so")

# A plugin that spins without making a syscall would freeze the virtual
# clock forever (the simulator's determinism seam is a blocking read while
# plugin code runs).  The reference bounds this with its CPU model + pth
# preemption; our analog is a generous wall-clock stall watchdog: a plugin
# silent for this long is declared dead and torn down loudly.
STALL_TIMEOUT_SEC = float(os.environ.get("SHADOW_TPU_PLUGIN_STALL_TIMEOUT",
                                         "300"))


def _watchdog_sec(api) -> float:
    """The plugin RPC watchdog budget: ``--plugin-watchdog-sec`` when set,
    else the module/env default.  One resolution point so the serve loop,
    the handshake wait, and the pooled loop all honor the same knob."""
    opts = getattr(getattr(api.host, "engine", None), "options", None)
    v = float(getattr(opts, "plugin_watchdog_sec", 0) or 0)
    return v if v > 0 else STALL_TIMEOUT_SEC


def _supervise_kill(api, reason: str) -> None:
    """Mark the simulated process as supervisor-killed: its app generator
    exits with code 124 (the timeout convention), process._finish routes
    the exit to the supervision ledger instead of plugin_errors, and the
    host + round loop continue."""
    get_logger().warning("native", f"{api.process.name}: {reason}")
    api.process.supervised_kill = reason


def _fault_stall_after(api) -> int:
    """Fault harness: ``plugin-stall:NAME:NREQ`` -> NREQ for this process
    (SIGSTOP its child after serving that many requests), else 0."""
    opts = getattr(getattr(api.host, "engine", None), "options", None)
    spec = getattr(opts, "fault_inject", "") or ""
    if spec.startswith("plugin-stall:"):
        from ..core.supervision import parse_fault_inject
        f = parse_fault_inject(spec)
        if f["name"] in api.process.name:
            return f["nreq"]
    return 0

_live_children: List[subprocess.Popen] = []


def _kill_stragglers(grace_sec: float = 2.0) -> None:
    """Tear down surviving plugin/pool children: terminate -> grace ->
    kill, then ``wait`` (waitpid) each one so no zombies outlive a run —
    a bare SIGKILL without reaping used to leave defunct entries behind
    for the life of the test process."""
    import time as _wt
    live = [p for p in _live_children if p.poll() is None]
    for p in live:
        try:
            p.terminate()
        except OSError:
            pass
    deadline = _wt.monotonic() + grace_sec
    for p in live:
        try:
            p.wait(timeout=max(0.0, deadline - _wt.monotonic()))
        except subprocess.TimeoutExpired:
            try:
                p.kill()
            except OSError:
                pass
            try:
                p.wait(timeout=5.0)
            except subprocess.TimeoutExpired:  # pragma: no cover - D state
                pass
        except OSError:  # pragma: no cover - already reaped elsewhere
            pass


atexit.register(_kill_stragglers)


def preload_lib_path() -> str:
    return _PRELOAD_LIB


def host_data_dir(host) -> str:
    """The single definition of the per-host data layout
    (<data-directory>/hosts/<name>, reference slave.c hostDataPath);
    created on first use."""
    data_root = getattr(getattr(host, "engine", None), "data_directory",
                        None) or "shadow.data"
    host_dir = os.path.join(data_root, "hosts", host.name)
    os.makedirs(host_dir, exist_ok=True)
    return host_dir


def _errno_of(exc: OSError) -> int:
    """Map our virtual-kernel OSError style ('EADDRINUSE: detail') to a
    numeric errno."""
    if exc.errno:
        return exc.errno
    text = (exc.args[0] if exc.args else "") or ""
    name = str(text).split(":")[0].strip().split()[0] if text else ""
    return getattr(errno_mod, name, errno_mod.EINVAL)


class RandomDescriptor(Descriptor):
    """Deterministic /dev/random-style source (the reference keeps per-host
    /dev/random handles, host.c:47-105; reads come from the host PRNG)."""

    def __init__(self, host, handle: int):
        super().__init__(host, handle, "random")
        self.adjust_status(S_READABLE, True)

    def read_bytes(self, n: int) -> bytes:
        return self.host.random.next_bytes(n)


class NativeKernel:
    """Dispatches one plugin's protocol requests against the virtual kernel.

    Runs inside the plugin's green thread: handlers that must wait for
    virtual readiness ``yield`` simulator blocks, so one kernel instance
    serves exactly one plugin process, serially.
    """

    def __init__(self, api, conn: real_socket.socket):
        self.api = api
        self.host = api.host
        self.conn = conn
        self.exit_code: Optional[int] = None

    # -- descriptor helpers ------------------------------------------------
    def _desc(self, handle: int):
        d = self.host.descriptor_table_get(int(handle))
        if d is None:
            raise OSError("EBADF")
        return d

    def _nonblock(self, desc) -> bool:
        return bool(getattr(desc, "_nonblock", False))

    def _recv_payload(self, desc, nbytes: int):
        """One receive attempt -> payload tuple or None."""
        return desc.receive_user_data(int(nbytes))

    def _is_eof(self, desc) -> bool:
        return desc.closed or desc.has_status(S_CLOSED)

    # -- dispatch ----------------------------------------------------------
    def dispatch(self, op: int, a: int, b: int, c: int, d: int,
                 payload: bytes):
        """Generator: returns (ret, resp_payload)."""
        try:
            handler = self._HANDLERS[op]
        except KeyError:
            return -errno_mod.ENOSYS, b""
        try:
            result = yield from handler(self, a, b, c, d, payload)
        except OSError as e:
            return -_errno_of(e), b""
        except (FileExistsError, FileNotFoundError) as e:
            return -_errno_of(e), b""
        return result

    # -- socket ops --------------------------------------------------------
    def op_socket(self, a, b, c, d, payload):
        kind = "tcp" if b == SOCK_STREAM else "udp"
        fd = self.api.socket(kind)
        return fd, b""
        yield  # pragma: no cover — make this a generator

    def op_bind(self, a, b, c, d, payload):
        self.api.bind(int(a), (int(b), int(c)))
        return 0, b""
        yield  # pragma: no cover

    def op_listen(self, a, b, c, d, payload):
        self.api.listen(int(a), int(b))
        return 0, b""
        yield  # pragma: no cover

    def op_accept(self, a, b, c, d, payload):
        sock = self._desc(a)
        nonblock = self._nonblock(sock) or bool(b)
        while True:
            child = sock.accept_child()
            if child is not None:
                break
            if nonblock:
                return -errno_mod.EAGAIN, b""
            if self._is_eof(sock):
                return -errno_mod.EINVAL, b""
            yield _Block(sock, S_READABLE)
        resp = struct.pack("<IH", child.peer_ip & 0xFFFFFFFF, child.peer_port)
        return child.handle, resp

    def op_connect(self, a, b, c, d, payload):
        sock = self._desc(a)
        done = sock.connect_to(int(b), int(c))
        if done:
            return 0, b""
        if self._nonblock(sock) or bool(d):
            return -errno_mod.EINPROGRESS, b""
        yield _Block(sock, S_WRITABLE)
        err = sock.take_socket_error()
        if err:
            return -getattr(errno_mod, str(err).split(":")[0], errno_mod.ECONNREFUSED), b""
        return 0, b""

    def op_send(self, a, b, c, d, payload):
        sock = self._desc(a)
        nonblock = self._nonblock(sock) or bool(b)
        total = 0
        view = memoryview(payload)
        while total < len(view):
            # bounded slice: re-materializing the whole tail each retry
            # would make a large blocking write O(n^2) in copied bytes
            chunk = bytes(view[total:total + 262144])
            n = sock.send_user_data(chunk)
            total += n
            if total >= len(view) or nonblock:
                break
            if n == 0:
                if self._is_eof(sock):
                    return (total if total else -errno_mod.EPIPE), b""
                yield _Block(sock, S_WRITABLE)
        if total == 0 and nonblock and len(view) > 0:
            return -errno_mod.EAGAIN, b""
        return total, b""

    def op_sendto(self, a, b, c, d, payload):
        sock = self._desc(a)
        nonblock = self._nonblock(sock) or bool(b)
        while True:
            n = sock.send_user_data(payload, int(c), int(d))
            if n > 0 or len(payload) == 0:
                return n, b""
            if nonblock:
                return -errno_mod.EAGAIN, b""
            yield _Block(sock, S_WRITABLE)

    def op_recv(self, a, b, c, d, payload):
        sock = self._desc(a)
        nonblock = self._nonblock(sock) or bool(c)
        peek = bool(d)
        while True:
            if peek:
                peeker = getattr(sock, "peek_user_data", None)
                if peeker is None:
                    return -errno_mod.EINVAL, b""
                r = peeker(int(b))
            else:
                r = self._recv_payload(sock, b)
            if r is not None:
                data = r[0] if isinstance(r, tuple) else r
                return len(data), bytes(data)
            if self._is_eof(sock):
                return 0, b""
            if nonblock:
                return -errno_mod.EAGAIN, b""
            yield _Block(sock, S_READABLE)

    def op_recvfrom(self, a, b, c, d, payload):
        sock = self._desc(a)
        nonblock = self._nonblock(sock) or bool(c)
        while True:
            r = self._recv_payload(sock, b)
            if r is not None:
                data, ip, port = r[0], r[1], r[2]
                hdr = struct.pack("<IH", ip & 0xFFFFFFFF, port & 0xFFFF)
                return len(data), hdr + bytes(data)
            if self._is_eof(sock):
                return 0, struct.pack("<IH", 0, 0)
            if nonblock:
                return -errno_mod.EAGAIN, b""
            yield _Block(sock, S_READABLE)

    def op_close(self, a, b, c, d, payload):
        self.api.close(int(a))
        return 0, b""
        yield  # pragma: no cover

    def op_shutdown(self, a, b, c, d, payload):
        sock = self._desc(a)
        if hasattr(sock, "shutdown"):
            sock.shutdown(int(b))
        else:
            sock.close()
        return 0, b""
        yield  # pragma: no cover

    def op_getsockopt(self, a, b, c, d, payload):
        sock = self._desc(a)
        val = 0
        if b == SOL_SOCKET and c == SO_ERROR:
            err = sock.take_socket_error() if hasattr(sock, "take_socket_error") else None
            val = getattr(errno_mod, str(err).split(":")[0], 0) if err else 0
        elif b == SOL_SOCKET and c == SO_SNDBUF:
            val = getattr(sock, "send_buf_size", 0)
        elif b == SOL_SOCKET and c == SO_RCVBUF:
            val = getattr(sock, "recv_buf_size", 0)
        return 0, struct.pack("<i", int(val))
        yield  # pragma: no cover

    def op_setsockopt(self, a, b, c, d, payload):
        sock = self._desc(a)
        if b == SOL_SOCKET and c in (SO_SNDBUF, SO_RCVBUF) and len(payload) >= 4:
            (val,) = struct.unpack("<i", payload[:4])
            # the kernel doubles setsockopt buffer sizes (reference honors
            # this in options --socket-recv-buffer semantics)
            if c == SO_SNDBUF and hasattr(sock, "send_buf_size"):
                sock.send_buf_size = max(4096, val)
            if c == SO_RCVBUF and hasattr(sock, "recv_buf_size"):
                sock.recv_buf_size = max(4096, val)
        return 0, b""
        yield  # pragma: no cover

    def _name_payload(self, ip, port):
        return struct.pack("<IH", (ip or 0) & 0xFFFFFFFF, (port or 0) & 0xFFFF)

    def op_getsockname(self, a, b, c, d, payload):
        sock = self._desc(a)
        return 0, self._name_payload(getattr(sock, "bound_ip", 0),
                                     getattr(sock, "bound_port", 0))
        yield  # pragma: no cover

    def op_getpeername(self, a, b, c, d, payload):
        sock = self._desc(a)
        ip = getattr(sock, "peer_ip", 0)
        if not ip:
            return -errno_mod.ENOTCONN, b""
        return 0, self._name_payload(ip, getattr(sock, "peer_port", 0))
        yield  # pragma: no cover

    # -- generic fd ops ----------------------------------------------------
    def op_read(self, a, b, c, d, payload):
        desc = self._desc(a)
        if isinstance(desc, RandomDescriptor):
            return 0, desc.read_bytes(int(b))
        if desc.kind == "timer":
            while desc.expire_count == 0:
                if self._nonblock(desc) or bool(c):
                    return -errno_mod.EAGAIN, b""
                yield _Block(desc, S_READABLE)
            n = desc.read_expirations()
            return 8, struct.pack("<Q", n)
        if desc.kind == "eventfd":
            while True:
                v = desc.read_value()
                if v is not None:
                    return 8, struct.pack("<Q", v)
                if self._nonblock(desc) or bool(c):
                    return -errno_mod.EAGAIN, b""
                yield _Block(desc, S_READABLE)
        if desc.kind == "signalfd":
            while True:
                rec = desc.read_siginfo()
                if rec is not None:
                    return len(rec), rec
                if self._nonblock(desc) or bool(c):
                    return -errno_mod.EAGAIN, b""
                yield _Block(desc, S_READABLE)
        r = yield from self.op_recv(a, b, c, d, payload)
        return r

    def op_write(self, a, b, c, d, payload):
        desc = self._desc(a)
        if desc.kind == "eventfd":
            if len(payload) < 8:            # kernel: EINVAL under 8 bytes,
                return -errno_mod.EINVAL, b""   # first 8 used otherwise
            val = struct.unpack("<Q", payload[:8])[0]
            while True:
                r = desc.write_value(val)
                if r is None:
                    return -errno_mod.EINVAL, b""
                if r:
                    return 8, b""
                if self._nonblock(desc) or bool(b):
                    return -errno_mod.EAGAIN, b""
                # can't park on S_WRITABLE: POLLOUT stays asserted while
                # counter < max even though THIS (large) value won't fit
                # (eventfd(2)); retry each refill tick of virtual time
                yield _Sleep(1_000_000)
        r = yield from self.op_send(a, b, c, d, payload)
        return r

    def op_fcntl(self, a, b, c, d, payload):
        desc = self._desc(a)
        if b == F_GETFL:
            return (O_NONBLOCK if self._nonblock(desc) else 0), b""
        if b == F_SETFL:
            desc._nonblock = bool(int(c) & O_NONBLOCK)
            return 0, b""
        return -errno_mod.EINVAL, b""
        yield  # pragma: no cover

    def op_ioctl(self, a, b, c, d, payload):
        desc = self._desc(a)
        if b == FIONREAD:
            return int(getattr(desc, "in_bytes", 0)), b""
        return -errno_mod.ENOTTY, b""
        yield  # pragma: no cover

    # -- epoll/poll --------------------------------------------------------
    def op_epoll_create(self, a, b, c, d, payload):
        return self.api.epoll_create(), b""
        yield  # pragma: no cover

    def op_epoll_ctl(self, a, b, c, d, payload):
        ep = self._desc(a)
        desc = self._desc(c)
        data = struct.unpack("<Q", payload[:8])[0] if len(payload) >= 8 else int(c)
        if b == 1:
            ep.ctl_add(desc, int(d), data)
        elif b == 2:
            ep.ctl_mod(desc, int(d), data)
        else:
            ep.ctl_del(desc)
        return 0, b""
        yield  # pragma: no cover

    def op_epoll_wait(self, a, b, c, d, payload):
        ep = self._desc(a)
        timeout_ms = int(c)
        if not ep.has_ready():
            if timeout_ms == 0:
                return 0, b""
            if timeout_ms > 0:
                deadline = self.api.now_ns() + timeout_ms * stime.SIM_TIME_MS
                while not ep.has_ready():
                    remaining = deadline - self.api.now_ns()
                    if remaining <= 0:
                        break
                    fired = yield _Block(ep, S_READABLE, timeout_ns=remaining)
                    if not fired:
                        break
            else:
                while not ep.has_ready():
                    yield _Block(ep, S_READABLE)
        events = ep.wait(int(b))
        out = b"".join(struct.pack("<IQ", rev & 0xFFFFFFFF, int(data))
                       for data, rev in events)
        return len(events), out

    def op_poll(self, a, b, c, d, payload):
        nfds = int(a)
        timeout_ms = int(b)
        entries = []
        for i in range(nfds):
            h, ev = struct.unpack_from("<ih", payload, i * 6)
            entries.append((h, ev))

        def scan():
            revents = []
            ready = 0
            for h, ev in entries:
                desc = self.host.descriptor_table_get(h) if h >= 0 else None
                r = 0
                if desc is not None:
                    if (ev & POLLIN) and desc.has_status(S_READABLE):
                        r |= POLLIN
                    if (ev & POLLOUT) and desc.has_status(S_WRITABLE):
                        r |= POLLOUT
                    if desc.has_status(S_CLOSED):
                        r |= POLLHUP
                elif h >= 0:
                    r |= POLLERR  # stale sim fd
                if r:
                    ready += 1
                revents.append(r)
            return ready, revents

        ready, revents = scan()
        if ready == 0 and timeout_ms != 0:
            # block on all polled descriptors via a scratch epoll (the
            # reference implements poll on top of its epoll too)
            ep = Epoll(self.host, self.host.allocate_handle())
            added = []
            for h, ev in entries:
                desc = self.host.descriptor_table_get(h) if h >= 0 else None
                if desc is None or desc is ep:
                    continue
                want = 0
                if ev & POLLIN:
                    want |= EPOLLIN
                if ev & POLLOUT:
                    want |= EPOLLOUT
                try:
                    ep.ctl_add(desc, want, h)
                    added.append(desc)
                except (OSError, FileExistsError):
                    pass
            try:
                if added:
                    if timeout_ms > 0:
                        yield _Block(ep, S_READABLE,
                                     timeout_ns=timeout_ms * stime.SIM_TIME_MS)
                    else:
                        yield _Block(ep, S_READABLE)
                elif timeout_ms > 0:
                    yield _Sleep(timeout_ms * stime.SIM_TIME_MS)
            finally:
                for desc in added:
                    try:
                        ep.ctl_del(desc)
                    except (OSError, FileNotFoundError):
                        pass
                ep.close()
            ready, revents = scan()
        out = b"".join(struct.pack("<h", r) for r in revents)
        return ready, out

    # -- time/sleep --------------------------------------------------------
    def op_gettime(self, a, b, c, d, payload):
        return 0, b""
        yield  # pragma: no cover

    def op_sleep(self, a, b, c, d, payload):
        if a > 0:
            yield _Sleep(int(a))
        return 0, b""

    # -- identity / DNS / random ------------------------------------------
    def op_getaddrinfo(self, a, b, c, d, payload):
        name = payload.decode("utf-8", "replace")
        try:
            ip = self.api.gethostbyname(name)
        except OSError:
            return -errno_mod.ENOENT, b""
        return 0, struct.pack("<I", ip & 0xFFFFFFFF)
        yield  # pragma: no cover

    def op_getnameinfo(self, a, b, c, d, payload):
        """Reverse lookup (getnameinfo without NI_NUMERICHOST): ip -> the
        simulated host's name through the engine DNS."""
        addr = self.host.engine.dns.resolve_ip(int(a))
        if addr is None:
            return -errno_mod.ENOENT, b""
        return 0, addr.name.encode()
        yield  # pragma: no cover

    def op_gethostname(self, a, b, c, d, payload):
        return 0, self.api.gethostname().encode()
        yield  # pragma: no cover

    def op_random(self, a, b, c, d, payload):
        n = max(0, min(int(a), 4096))
        return n, self.api.random_bytes(n)
        yield  # pragma: no cover

    def op_open_random(self, a, b, c, d, payload):
        handle = self.host.allocate_handle()
        self.host.register_descriptor(RandomDescriptor(self.host, handle))
        return handle, b""
        yield  # pragma: no cover

    # -- timers / pipes ----------------------------------------------------
    def op_timerfd_create(self, a, b, c, d, payload):
        return self.api.timerfd_create(), b""
        yield  # pragma: no cover

    def op_timerfd_settime(self, a, b, c, d, payload):
        self._desc(a).arm(int(b), int(c))
        return 0, b""
        yield  # pragma: no cover

    def op_pipe(self, a, b, c, d, payload):
        rh, wh = self.api.pipe()
        return rh, struct.pack("<I", wh)
        yield  # pragma: no cover

    def op_socketpair(self, a, b, c, d, payload):
        ha, hb = self.api.socketpair()
        return ha, struct.pack("<I", hb)
        yield  # pragma: no cover

    def op_eventfd(self, a, b, c, d, payload):
        # a=initval, b: bit0 = EFD_SEMAPHORE (shim-decoded)
        return self.api.eventfd_create(int(a), bool(int(b) & 1)), b""
        yield  # pragma: no cover

    def op_signalfd(self, a, b, c, d, payload):
        # a = 64-bit signal mask bitmap (bit signo-1)
        return self.api.signalfd_create(int(a)), b""
        yield  # pragma: no cover

    def op_kill(self, a, b, c, d, payload):
        # a = signo, self-directed (shim routes only own-pid kills here);
        # returns the number of matching signalfds so the shim can fall
        # back to its recorded handler when none matched
        return self.api.deliver_signal(int(a)), b""
        yield  # pragma: no cover

    # -- misc --------------------------------------------------------------
    def op_exit(self, a, b, c, d, payload):
        self.exit_code = int(a)
        return 0, b""
        yield  # pragma: no cover

    def op_log(self, a, b, c, d, payload):
        self.api.log(payload.decode("utf-8", "replace"))
        return 0, b""
        yield  # pragma: no cover

    _HANDLERS = {
        OP_SOCKET: op_socket, OP_BIND: op_bind, OP_LISTEN: op_listen,
        OP_ACCEPT: op_accept, OP_CONNECT: op_connect, OP_SEND: op_send,
        OP_SENDTO: op_sendto, OP_RECV: op_recv, OP_RECVFROM: op_recvfrom,
        OP_CLOSE: op_close, OP_EPOLL_CREATE: op_epoll_create,
        OP_EPOLL_CTL: op_epoll_ctl, OP_EPOLL_WAIT: op_epoll_wait,
        OP_POLL: op_poll, OP_GETTIME: op_gettime, OP_SLEEP: op_sleep,
        OP_GETADDRINFO: op_getaddrinfo, OP_GETHOSTNAME: op_gethostname,
        OP_RANDOM: op_random, OP_SETSOCKOPT: op_setsockopt,
        OP_GETSOCKOPT: op_getsockopt, OP_GETSOCKNAME: op_getsockname,
        OP_GETPEERNAME: op_getpeername, OP_SHUTDOWN: op_shutdown,
        OP_FCNTL: op_fcntl, OP_IOCTL: op_ioctl,
        OP_OPEN_RANDOM: op_open_random, OP_READ: op_read,
        OP_WRITE: op_write, OP_EXIT: op_exit, OP_LOG: op_log,
        OP_TIMERFD_CREATE: op_timerfd_create,
        OP_TIMERFD_SETTIME: op_timerfd_settime, OP_PIPE: op_pipe,
        OP_SOCKETPAIR: op_socketpair, OP_EVENTFD: op_eventfd,
        OP_SIGNALFD: op_signalfd, OP_KILL: op_kill,
        OP_GETNAMEINFO: op_getnameinfo,
    }


def _read_exact_raising(conn: real_socket.socket, n: int) -> Optional[bytes]:
    """Blocking read of exactly n bytes; None on EOF; socket timeouts
    propagate (TimeoutError) so every bounded read distinguishes 'child
    stalled' (a watchdog fire) from 'child exited' (a normal teardown).

    This *real* blocking read is the determinism seam: while we're here, the
    plugin is executing (instantaneous in virtual time); it will either send
    another request, stall, or exit."""
    chunks = []
    got = 0
    while got < n:
        chunk = conn.recv(n - got)
        if not chunk:
            return None
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def _dispatch_traced(tracer, kernel, name: str, op: int, a, b, c, d,
                     payload):
    """One native-plugin RPC wrapped in a ``plugin.rpc`` span (ISSUE 3:
    plugin execution is a named span).  Only called on TRACED runs — the
    serve loops call ``kernel.dispatch`` directly otherwise, so the
    disabled per-syscall path gains no extra generator frame.  The span's
    wall duration covers any virtual-time blocking the syscall performed —
    i.e. it is the request's *service* time, which is what the flight
    recorder wants around a watchdog fire."""
    with tracer.span("plugin.rpc", "plugin", sim_ns=kernel.api.now_ns(),
                     args={"op": op, "proc": name}):
        ret = yield from kernel.dispatch(op, a, b, c, d, payload)
    return ret


def run_native_plugin(api, args: List[str], binary: str,
                      extra_env: Optional[dict] = None):
    """App-main generator serving one native plugin process.

    The reference's equivalent flow: _process_start loads the plugin into a
    namespace and pth-schedules its main (process.c:1055-1195); here we exec
    the real binary with the shim preloaded and serve its syscall stream.
    """
    log = get_logger()
    name = api.process.name
    sim_side, child_side = real_socket.socketpair()
    env = dict(os.environ)
    # config-level environment injection (<shadow environment=...>) FIRST,
    # then the shim is prepended so an injected LD_PRELOAD (the config
    # 'preload' attribute) chains behind it instead of clobbering it
    env.update(getattr(getattr(api.host, "engine", None),
                       "plugin_environment", None) or {})
    # LD_PRELOAD chain: shim first, then <process preload=...>, then any
    # config/ambient preloads (reference per-process preload attribute)
    proc_preload = getattr(api.process, "preload", None)
    chain = [_PRELOAD_LIB]
    if proc_preload:
        chain.append(proc_preload)
    if env.get("LD_PRELOAD"):
        chain.append(env["LD_PRELOAD"])
    env["LD_PRELOAD"] = " ".join(chain)
    env["SHADOW_TPU_FD"] = str(child_side.fileno())
    env["SHADOW_TPU_EPOCH_NS"] = str(stime.EMULATED_TIME_OFFSET)
    # deterministic virtual pid (the reference's plugins see their virtual
    # process id through process_emu_getpid)
    env["SHADOW_TPU_PID"] = str(api.process.pid)
    # per-host file namespace: the plugin's cwd is its host's data dir
    # (reference slave.c data-dir layout: each host gets hostDataPath and
    # plugins run against it), so relative paths isolate per host
    host_dir = host_data_dir(api.host)
    env["SHADOW_TPU_DATA_DIR"] = os.path.abspath(host_dir)
    if extra_env:
        env.update(extra_env)
    # stdout/stderr go to per-process files (the reference writes each
    # plugin's output under its host data dir, slave.c data-dir layout);
    # a pipe could deadlock a chatty plugin against our blocking read loop
    import tempfile
    out_file = tempfile.NamedTemporaryFile(
        mode="w+b", prefix=f"shadow-{name.replace('/', '_')}-", suffix=".out",
        delete=False)
    try:
        proc = subprocess.Popen([binary] + list(args), env=env,
                                pass_fds=(child_side.fileno(),),
                                stdout=out_file, stderr=subprocess.STDOUT,
                                cwd=host_dir, close_fds=True)
    except OSError as e:
        log.warning("native", f"{name}: failed to exec {binary}: {e}")
        child_side.close()
        sim_side.close()
        out_file.close()
        os.unlink(out_file.name)
        return 127
    _live_children.append(proc)
    child_side.close()
    kernel = NativeKernel(api, sim_side)
    tracer = get_tracer()
    wd = _watchdog_sec(api)
    stall_after = _fault_stall_after(api)
    served = 0
    try:
        # the shim's constructor sends a GETTIME before the plugin's main()
        # runs, so the first request arrives within exec latency.  A binary
        # the shim cannot interpose (statically linked, exec'd helper)
        # would otherwise block the whole simulator in the first read —
        # bound that wait and fail loudly instead.
        # Wall-clock pressure must not change simulation outcomes, so a
        # slow-but-alive child gets generous retries; only a child that is
        # alive yet silent for the full watchdog budget (the shim speaks
        # before main() runs, so silence means it isn't interposed) is
        # killed.
        import select as _select
        spoke = False
        waited = 0.0
        slice_sec = min(10.0, wd)
        while waited < wd:
            readable, _, _ = _select.select([sim_side], [], [], slice_sec)
            if readable or proc.poll() is not None:
                spoke = True
                break
            waited += slice_sec
        if not spoke:
            log.warning("native",
                        f"{name}: {binary} never spoke the interposition "
                        "protocol (statically linked? exec'd a helper?); "
                        "killing it")
            raise OSError("plugin not interposable")
        # select only guarantees one readable byte: bound the header read
        # too, so a child that writes a partial/garbage header then hangs
        # fails loudly instead of freezing the simulator
        sim_side.settimeout(min(30.0, wd))
        try:
            hdr = _read_exact_raising(sim_side, REQ_HDR.size)
        except TimeoutError:
            log.warning("native",
                        f"{name}: {binary} sent a partial first header and "
                        "stalled; killing it")
            raise OSError("plugin handshake timeout")
        # stall watchdog for the whole run: a TIMEOUT (as opposed to EOF)
        # means the plugin went silent without exiting — a supervised kill:
        # the simulated process is marked exited with the reason, the host
        # and round loop continue (the finally block kills + reaps the OS
        # process)
        sim_side.settimeout(wd)
        first = True
        while True:
            if not first:
                try:
                    hdr = _read_exact_raising(sim_side, REQ_HDR.size)
                except TimeoutError:
                    _supervise_kill(
                        api, f"no syscall for {wd:.0f}s wall (SIGSTOP'd? "
                        "busy spin without syscalls?); watchdog killing "
                        "the plugin")
                    hdr = None
            first = False
            if hdr is None:
                break
            length, op, a, b, c, d = REQ_HDR.unpack(hdr)
            plen = length - REQ_HDR.size
            payload = b""
            if plen > 0:
                # the payload read must distinguish timeout from EOF too: a
                # plugin frozen MID-REQUEST (header delivered, payload
                # stalled — exactly where a SIGSTOP can land) is a watchdog
                # kill, not a silent exit
                try:
                    payload = _read_exact_raising(sim_side, plen)
                except TimeoutError:
                    _supervise_kill(
                        api, f"request truncated mid-payload for "
                        f"{wd:.0f}s wall; watchdog killing the plugin")
                    payload = None
                except OSError:
                    payload = None      # reset mid-payload = plugin exit
                if payload is None:
                    break
            if tracer.enabled:
                ret, resp_payload = yield from _dispatch_traced(
                    tracer, kernel, name, op, a, b, c, d, payload)
            else:
                ret, resp_payload = yield from kernel.dispatch(
                    op, a, b, c, d, payload)
            resp = RESP_HDR.pack(RESP_HDR.size + len(resp_payload), 0,
                                 int(ret), api.now_ns()) + resp_payload
            try:
                sim_side.sendall(resp)
            except TimeoutError:
                # response stuck for the full watchdog budget: the plugin
                # stopped draining its socket mid-syscall — same supervised
                # teardown as request-side silence
                _supervise_kill(
                    api, f"response undeliverable for {wd:.0f}s wall; "
                    "watchdog killing the plugin")
                break
            except OSError:
                break
            served += 1
            if stall_after and served == stall_after:
                # fault harness (plugin-stall:NAME:NREQ): freeze the child
                # mid-syscall-stream, deterministically — the next request
                # read must trip the watchdog, never hang the simulator
                import signal as _signal
                log.warning("native",
                            f"{name}: fault injection — SIGSTOP after "
                            f"request #{served}")
                os.kill(proc.pid, _signal.SIGSTOP)
    finally:
        sim_side.close()
        if proc.poll() is None:
            proc.kill()
        proc.wait()
        if proc in _live_children:
            _live_children.remove(proc)
        out_file.flush()
        out_file.seek(0)
        captured = out_file.read()
        out_file.close()
        os.unlink(out_file.name)
        api.process.app_state = {"stdout": captured,
                                 "returncode": proc.returncode}
        if captured:
            log.debug("native", f"{name} output: {captured[:2000]!r}")
    if getattr(api.process, "supervised_kill", None):
        return 124          # timeout convention; routed to the supervision
                            # ledger by process._finish, not plugin_errors
    rc = kernel.exit_code if kernel.exit_code is not None else proc.returncode
    return rc if rc is not None else 0


def make_native_app(binary: str):
    """Registry adapter: a plugin path that is a real executable becomes an
    app whose main serves the interposition protocol."""
    def app_main(api, args):
        rc = yield from run_native_plugin(api, args, binary)
        return rc
    return app_main


# ---------------------------------------------------------------------------
# Pooled plugins: many instances per OS process (native/pool/pool_main.cc).
#
# The reference hosts thousands of plugin namespaces in ONE process via its
# custom elf-loader (dlmopen, SURVEY.md §2.7); shadow_pool is the same
# capability on glibc dlmopen.  Plugins must be `.so`s linked against
# libshadow_preload.so (reference plugins likewise link shadow's libs).
# Each pool holds up to POOL_CAPACITY instances (glibc's DL_NNS namespace
# limit is 16); the manager spawns additional pools as needed, so N
# instances cost ceil(N / POOL_CAPACITY) OS processes instead of N.
# ---------------------------------------------------------------------------

POOL_CAPACITY = 13
_POOL_BIN = os.path.join(os.path.dirname(_PRELOAD_LIB), "shadow_pool")


class NativePool:
    """One shadow_pool helper process + its control channel."""

    def __init__(self, extra_env: Optional[dict] = None):
        self.control, child_control = real_socket.socketpair()
        env = dict(os.environ)
        if extra_env:
            env.update(extra_env)
        env.pop("SHADOW_TPU_FD", None)  # the pool itself is not interposed
        # every dlmopen namespace carries its own libc/shim static TLS; the
        # default surplus covers ~10 namespaces, so raise it (the reference
        # solved the same problem by computing LD_STATIC_TLS_EXTRA before
        # re-exec, main.c:283-320 — glibc 2.35+ exposes it as a tunable)
        tls = "glibc.rtld.optional_static_tls=4096000"
        env["GLIBC_TUNABLES"] = (env["GLIBC_TUNABLES"] + ":" + tls
                                 if env.get("GLIBC_TUNABLES") else tls)
        # let pooled plugin .so's resolve libshadow_preload.so
        lib_dir = os.path.dirname(_PRELOAD_LIB)
        env["LD_LIBRARY_PATH"] = (lib_dir + ":" + env["LD_LIBRARY_PATH"]
                                  if env.get("LD_LIBRARY_PATH") else lib_dir)
        # pass_fds preserves the parent's fd number; tell the pool which
        env["SHADOW_POOL_CONTROL_FD"] = str(child_control.fileno())
        self.proc = subprocess.Popen(
            [_POOL_BIN], env=env, pass_fds=(child_control.fileno(),),
            stdout=subprocess.DEVNULL, close_fds=True)
        child_control.close()
        _live_children.append(self.proc)
        self.count = 0

    def add_instance(self, so_path: str, args: List[str], vpid: int,
                     data_dir: str = ""):
        """Returns the simulator-side protocol socket for the new instance.
        ``data_dir`` is the instance's host data dir (op 2 payload leads
        with it), cached by the namespace's shim for per-host absolute-path
        virtualization (shim_files.cc)."""
        sim_side, inst_side = real_socket.socketpair()
        argv = [so_path] + list(args)
        payload = data_dir.encode() + b"\0" \
            + b"".join(a.encode() + b"\0" for a in argv)
        hdr = struct.pack("<IIq", 16 + len(payload), 2, int(vpid))
        real_socket.send_fds(self.control, [hdr + payload],
                             [inst_side.fileno()])
        inst_side.close()
        self.count += 1
        return sim_side

    def close(self) -> None:
        try:
            self.control.close()
        except OSError:
            pass


def _pool_for(engine) -> NativePool:
    pools = getattr(engine, "_native_pools", None)
    if pools is None:
        pools = engine._native_pools = []
    if not pools or pools[-1].count >= POOL_CAPACITY \
            or pools[-1].proc.poll() is not None:
        pools.append(NativePool(
            extra_env=getattr(engine, "plugin_environment", None)))
    return pools[-1]


def run_pooled_plugin(api, args: List[str], so_path: str):
    """App-main generator serving one pooled plugin instance: same protocol
    loop as run_native_plugin, but the instance lives inside a shared
    shadow_pool process instead of its own."""
    log = get_logger()
    name = api.process.name
    engine = api.host.engine
    pool = _pool_for(engine)
    host_dir = host_data_dir(api.host)
    try:
        sim_side = pool.add_instance(so_path, args, api.process.pid,
                                     os.path.abspath(host_dir))
    except OSError as e:
        log.warning("native", f"{name}: pool add_instance failed: {e}")
        return 127
    kernel = NativeKernel(api, sim_side)
    tracer = get_tracer()
    wd = _watchdog_sec(api)
    sim_side.settimeout(wd)
    try:
        while True:
            try:
                hdr = _read_exact_raising(sim_side, REQ_HDR.size)
            except TimeoutError:
                _supervise_kill(
                    api, f"no syscall for {wd:.0f}s wall; watchdog "
                    "retiring the pooled instance")
                hdr = None
            if hdr is None:
                break
            length, op, a, b, c, d = REQ_HDR.unpack(hdr)
            plen = length - REQ_HDR.size
            payload = b""
            if plen > 0:
                try:
                    payload = _read_exact_raising(sim_side, plen)
                except TimeoutError:
                    _supervise_kill(
                        api, f"request truncated mid-payload for "
                        f"{wd:.0f}s wall; watchdog retiring the pooled "
                        "instance")
                    payload = None
                except OSError:
                    payload = None      # reset mid-payload = instance exit
                if payload is None:
                    break
            if tracer.enabled:
                ret, resp_payload = yield from _dispatch_traced(
                    tracer, kernel, name, op, a, b, c, d, payload)
            else:
                ret, resp_payload = yield from kernel.dispatch(
                    op, a, b, c, d, payload)
            resp = RESP_HDR.pack(RESP_HDR.size + len(resp_payload), 0,
                                 int(ret), api.now_ns()) + resp_payload
            try:
                sim_side.sendall(resp)
            except TimeoutError:
                # same supervised teardown as the standalone loop: an
                # instance that stops draining its socket mid-response is
                # a watchdog fire, not a clean exit
                _supervise_kill(
                    api, f"response undeliverable for {wd:.0f}s wall; "
                    "watchdog retiring the pooled instance")
                break
            except OSError:
                break
    finally:
        sim_side.close()
    if getattr(api.process, "supervised_kill", None):
        return 124
    return kernel.exit_code if kernel.exit_code is not None else 0


def make_pooled_app(so_path: str):
    """Registry adapter for `.so` plugins: hosted in shared pool processes,
    ceil(N/13) OS processes for N instances."""
    def app_main(api, args):
        rc = yield from run_pooled_plugin(api, args, so_path)
        return rc
    return app_main
