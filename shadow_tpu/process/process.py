"""Virtual processes: application code running under the simulated kernel.

The reference runs *real binaries* via LD_PRELOAD interposition + rpth green
threads (host/process.c: 257 process_emu_* syscalls, pth_gctx per process;
SURVEY.md §2.4/§2.7).  The TPU rebuild keeps that capability split in two
planes:

* **Python plugin plane (this module)**: apps are Python generator
  coroutines — the direct analog of rpth green threads under a virtual
  clock.  Every syscall is a ``yield`` to the simulated kernel
  (:class:`SyscallAPI`), which either completes it immediately or suspends
  the green thread until a descriptor status change / timer wakes it —
  exactly the descriptor->epoll->process_continue resumption chain of the
  reference (process.c:1197 process_continue).
* **Native plugin plane** (native/, later rounds): LD_PRELOAD interposer
  for unmodified C binaries speaking the same virtual-kernel API over IPC.

Determinism: threads resume in creation order; all syscall effects happen at
the virtual time of the event that woke them.
"""

from __future__ import annotations

import inspect
import time as _walltime
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..core import stime
from ..core.logger import get_logger
from ..core.task import Task
from ..descriptor.base import S_CLOSED, S_READABLE, S_WRITABLE
from ..core.worker import current_worker
from ..obs.trace import get_tracer

_perf = _walltime.perf_counter_ns

RUNNABLE = "runnable"
BLOCKED = "blocked"
DONE = "done"


class _Syscall:
    """Base class for yielded syscall requests."""
    __slots__ = ()


class _Block(_Syscall):
    """Block until ``desc`` has any of ``bits`` (or is closed), with an
    optional timeout.  Resumes with True if the condition fired, False on
    timeout."""
    __slots__ = ("desc", "bits", "timeout_ns")

    def __init__(self, desc, bits, timeout_ns: int = -1):
        self.desc = desc
        self.bits = bits
        self.timeout_ns = timeout_ns


class _Sleep(_Syscall):
    __slots__ = ("ns",)

    def __init__(self, ns: int):
        self.ns = ns


class _DeviceWait(_Syscall):
    """Block until a device-plane flow completes (parallel/device_plane.py);
    wake_value = the completion sim time ns."""
    __slots__ = ("circuit",)

    def __init__(self, circuit: int):
        self.circuit = circuit


class _Stop(_Syscall):
    __slots__ = ()


class GreenThread:
    _ids = 0

    def __init__(self, process: "Process", gen):
        GreenThread._ids += 1
        self.tid = GreenThread._ids
        self.process = process
        self.gen = gen
        self.state = RUNNABLE
        self.wake_value: Any = None
        self.wake_exception: Optional[BaseException] = None
        self._unblock_cb = None  # cleanup for registered waiters


class Process:
    """A virtual process on a Host (reference process.c capability)."""

    def __init__(self, host, name: str, app_main: Callable, args: List[str],
                 start_time_ns: int, stop_time_ns: int = 0,
                 preload: Optional[str] = None):
        self.host = host
        self.name = name
        # per-process extra LD_PRELOAD libs (reference <process preload=...>)
        self.preload = preload
        self.pid = host.next_process_id()
        self.app_main = app_main
        self.args = args
        self.start_time_ns = start_time_ns
        self.stop_time_ns = stop_time_ns
        self.threads: List[GreenThread] = []
        self.api = SyscallAPI(self)
        self.running = False
        self.exited = False
        self.exit_code: Optional[int] = None
        # set by a watchdog (process/native.py _supervise_kill) when this
        # process was torn down BY DESIGN: the nonzero exit then counts as
        # a supervision recovery, not a plugin error
        self.supervised_kill: Optional[str] = None
        self.return_values: Dict[int, Any] = {}
        self.app_state: Any = None  # apps may park observable state here (tests)
        self._continue_scheduled = False
        self._in_continue = False   # suppress redundant continue events for
                                    # wakes arriving DURING continue_ (the
                                    # running loop rescans — ISSUE 12)
        self._cont_token = None     # C-side coalescing token (native plane)
        # tracer hook bound ONCE at construction (the zero-cost pattern
        # native.run uses): the untraced resume path pays no span
        # construction, no get_tracer lookup, no null context manager
        tracer = get_tracer()
        self._continue_now = self._continue_traced if tracer.enabled \
            else self._continue_fast
        self._tracer = tracer
        self._signal_fds: List = []   # open SignalFD descriptors (delivery)
        # the kernel's per-process pending-signal set, shared by every
        # signalfd this process opens (descriptor/signalfd.py)
        from ..descriptor.signalfd import SharedSignalPending
        self._signal_pending = SharedSignalPending()
        host.add_process(self)

    # -- lifecycle ---------------------------------------------------------
    def schedule_start(self, worker) -> None:
        worker.schedule_task(Task(_process_start_task, self, None,
                                  name=f"start:{self.name}"),
                             self.start_time_ns, dst_host=self.host)
        if self.stop_time_ns > 0:
            worker.schedule_task(Task(_process_stop_task, self, None,
                                      name=f"stop:{self.name}"),
                                 self.stop_time_ns, dst_host=self.host)

    def start(self) -> None:
        if self.running or self.exited:
            return
        self.running = True
        get_logger().info("process", f"starting process {self.name} (pid {self.pid})")
        gen = self.app_main(self.api, self.args)
        if not inspect.isgenerator(gen):
            # app completed synchronously (no syscalls)
            self.exited = True
            self.exit_code = gen if isinstance(gen, int) else 0
            return
        self.spawn_thread(gen)
        self.continue_()

    def stop(self) -> None:
        if self.exited:
            return
        for t in self.threads:
            if t.state != DONE:
                t.gen.close()
                t.state = DONE
        self._finish(exit_code=0)

    def _finish(self, exit_code: int) -> None:
        self.exited = True
        self.running = False
        self.exit_code = exit_code
        get_logger().info("process",
                          f"process {self.name} (pid {self.pid}) exited with {exit_code}")
        if exit_code != 0 and self.host.engine is not None:
            if self.supervised_kill:
                self.host.engine.supervision.count_plugin_kill(
                    self.name, self.supervised_kill)
            else:
                self.host.engine.increment_plugin_error()

    # -- green threads -----------------------------------------------------
    def spawn_thread(self, gen) -> GreenThread:
        t = GreenThread(self, gen)
        self.threads.append(t)
        return t

    def continue_(self) -> None:
        """Resume all runnable green threads until everything blocks
        (reference process_continue :1197-1275), attributing the wall to
        the plugin side of the host_exec split.  Batched deliveries
        (parallel/native_plane.py ContinuationLedger) call
        ``_continue_now`` directly and time the whole batch once."""
        if self.exited:
            return
        t0 = _perf()
        self._continue_now()
        engine = self.host.engine
        if engine is not None:
            engine.add_plugin_exec_ns(_perf() - t0)

    def _continue_traced(self) -> None:
        """One plugin-execution span per resume when the run is traced
        (ISSUE 3); selected at construction so the untraced path never
        pays the span machinery (ISSUE 12 satellite)."""
        if self.exited:
            return
        with self._tracer.span("plugin.continue", "plugin",
                               sim_ns=self.host.now,
                               args={"proc": self.name}):
            self._run_runnable()

    def _continue_fast(self) -> None:
        if self.exited:
            return
        self._run_runnable()

    def _run_runnable(self) -> None:
        # _in_continue: a wake arriving DURING the loop (an app send making
        # another descriptor of this process readable) marks its thread
        # RUNNABLE and the rescan resumes it — scheduling a continue event
        # for it would execute as a redundant no-op (ISSUE 12 satellite:
        # the coalescing flag used to reset before the generators ran)
        self._in_continue = True
        try:
            progressed = True
            while progressed:
                progressed = False
                for t in list(self.threads):
                    if t.state == RUNNABLE:
                        progressed = True
                        self._run_thread(t)
        finally:
            self._in_continue = False
        if all(t.state == DONE for t in self.threads) and not self.exited:
            main_done = self.threads[0].state == DONE if self.threads else True
            if main_done:
                rv = self.return_values.get(self.threads[0].tid) if self.threads else 0
                self._finish(exit_code=rv if isinstance(rv, int) else 0)

    def _run_thread(self, t: GreenThread) -> None:
        while t.state == RUNNABLE:
            try:
                if t.wake_exception is not None:
                    exc, t.wake_exception = t.wake_exception, None
                    req = t.gen.throw(exc)
                else:
                    req = t.gen.send(t.wake_value)
                t.wake_value = None
            except StopIteration as si:
                t.state = DONE
                self.return_values[t.tid] = si.value
                return
            except Exception as e:  # app crashed
                t.state = DONE
                get_logger().error("process",
                                   f"process {self.name} thread {t.tid} crashed: {e!r}")
                import traceback
                get_logger().debug("process", traceback.format_exc())
                self._finish(exit_code=1)
                return
            self._dispatch(t, req)

    def _dispatch(self, t: GreenThread, req) -> None:
        w = current_worker()
        plane = self.host.native_plane
        if isinstance(req, _Sleep):
            t.state = BLOCKED
            if w is not None:
                if plane is not None:
                    # one C-heap continuation event, no Python Task/Event
                    plane.push_sleep(self, t, w.now, req.ns)
                else:
                    w.schedule_task(Task(_thread_wake_task, (self, t), None,
                                         name="sleep_wake"), req.ns,
                                    dst_host=self.host)
            return
        if isinstance(req, _Block):
            desc, bits = req.desc, req.bits
            if plane is not None and desc.plane is plane:
                # C-plane socket: the block waiter lives in C — the wake
                # condition is decided at status-change time with no
                # Python callback, and the wake itself is a C-heap
                # continuation event (ISSUE 12)
                t.state = BLOCKED
                if not plane.block_native(self, t, desc, bits,
                                          req.timeout_ns if w is not None
                                          else -1,
                                          w.now if w is not None else
                                          self.host.now):
                    t.state = RUNNABLE
                    t.wake_value = True  # condition already true
                return
            if desc.status & (bits | S_CLOSED):
                t.wake_value = True  # condition already true; loop continues
                return
            t.state = BLOCKED
            armed = [True]

            def on_status(d, changed, _t=t, _bits=bits):
                if armed[0] and d.status & (_bits | S_CLOSED):
                    armed[0] = False
                    d.remove_listener(on_status)
                    _t.wake_value = True
                    self._wake_thread(_t)

            desc.add_listener(on_status)
            t._unblock_cb = (desc, on_status)
            if req.timeout_ns >= 0 and w is not None:
                if plane is not None:
                    # Python-descriptor block under the native plane: the
                    # wake stays a listener, the timeout is a C-heap
                    # continuation event
                    plane.push_block_timeout(
                        self, t, armed, w.now, req.timeout_ns,
                        (lambda _desc=desc, _cb=on_status:
                         _desc.remove_listener(_cb)))
                    return

                def on_timeout(_pair, _arg, _t=t, _desc=desc):
                    if armed[0] and _t.state == BLOCKED:
                        armed[0] = False
                        _desc.remove_listener(on_status)
                        _t.wake_value = False
                        self._wake_thread(_t)

                w.schedule_task(Task(on_timeout, None, None, name="block_timeout"),
                                req.timeout_ns, dst_host=self.host)
            return
        if isinstance(req, _DeviceWait):
            plane = getattr(self.host.engine, "device_plane", None)
            if plane is None:
                raise RuntimeError(
                    f"{self.name}: device flow wait but the engine has no "
                    "device plane (is the client missing its 'device' arg?)")
            if plane.is_done(req.circuit):
                t.wake_value = plane.result(req.circuit)
            else:
                plane.register_waiter(req.circuit, self, t)
                t.state = BLOCKED
            return
        if isinstance(req, _Stop):
            t.state = DONE
            return
        # unknown yield: treat as cooperative yield point
        t.wake_value = None

    def _wake_thread(self, t: GreenThread) -> None:
        if t.state != BLOCKED:
            return
        t.state = RUNNABLE
        t._unblock_cb = None
        self._schedule_continue()

    def _schedule_continue(self) -> None:
        """Coalesced process_continue wakeup: ONE continue event in flight
        per process.  The flag (Python-plane ``_continue_scheduled``; the
        C-side token mirror under the native plane) clears when the event
        DELIVERS, not when continue_ starts — and wakes arriving while
        continue_ is running schedule nothing at all (the loop rescans), so
        no redundant same-time events exist on either path (ISSUE 12
        satellite: the old reset-at-entry scheduled one per mid-continue
        wake)."""
        if self.exited or self._in_continue:
            return
        w = current_worker()
        if w is None:
            self.continue_()
            return
        plane = self.host.native_plane
        if plane is not None:
            plane.sched_continue(self, w.now)
            return
        if self._continue_scheduled:
            return
        self._continue_scheduled = True
        w.schedule_task(Task(_process_continue_task, self, None,
                             name=f"continue:{self.name}"), 0, dst_host=self.host)


def _process_start_task(process: Process, _arg) -> None:
    process.start()


def _process_stop_task(process: Process, _arg) -> None:
    process.stop()


def _process_continue_task(process: Process, _arg) -> None:
    # the in-flight continue event has left the queue: clear the coalescing
    # flag BEFORE resuming (a wake during continue_ is absorbed by the
    # rescan; one arriving after schedules a fresh event)
    process._continue_scheduled = False
    process.continue_()


def _thread_wake_task(pair, _arg) -> None:
    # sleep wake is itself the continue event: resume directly, without
    # routing through _schedule_continue (which would queue a redundant
    # same-time continue event — ISSUE 12 satellite)
    process, t = pair
    if t.state == BLOCKED:
        t.state = RUNNABLE
        t._unblock_cb = None
    process.continue_()


class SyscallAPI:
    """The virtual-kernel call surface handed to apps.

    Mirrors (at capability level) the reference's process_emu_* families
    (process.c:1412-7671): sockets, epoll, timers, time, DNS, random, pipes,
    sleeping, logging.  Blocking calls are generators — app code uses
    ``yield from api.recv(fd, n)``; non-blocking variants return immediately.
    """

    def __init__(self, process: Process):
        self.process = process
        self.host = process.host

    # -- time (process.c time family -> worker_getEmulatedTime) -----------
    def now_ns(self) -> int:
        w = current_worker()
        return w.now if w is not None else 0

    def time(self) -> float:
        """Emulated wall-clock seconds (epoch-offset like the reference)."""
        return stime.emulated_from_sim(self.now_ns()) / stime.SIM_TIME_SEC

    def sleep(self, seconds: float):
        yield _Sleep(stime.from_seconds(seconds))

    def usleep(self, usec: int):
        yield _Sleep(usec * stime.SIM_TIME_US)

    # -- identity / DNS ----------------------------------------------------
    def gethostname(self) -> str:
        return self.host.name

    def gethostbyname(self, name: str) -> int:
        addr = self.host.engine.dns.resolve_name(name)
        if addr is None:
            raise OSError(f"EAI_NONAME: unknown host {name!r}")
        return addr.ip

    def getaddrinfo(self, name: str, port: int) -> Tuple[int, int]:
        return (self.gethostbyname(name), port)

    # -- random (process.c rand family -> host Random) ---------------------
    def rand(self) -> int:
        return self.host.random.next_int(2 ** 31)

    def random_bytes(self, n: int) -> bytes:
        return self.host.random.next_bytes(n)

    # -- sockets -----------------------------------------------------------
    def socket(self, kind: str) -> int:
        host = self.host
        plane = getattr(host, "native_plane", None)
        if plane is not None and kind in ("tcp", "udp"):
            # C data plane: the socket state lives natively; the wrapper
            # carries the descriptor surface (parallel/native_plane.py)
            return plane.create_socket(host, kind).handle
        handle = host.allocate_handle()
        if kind == "udp":
            from ..descriptor.udp import UDPSocket
            sock = UDPSocket(host, handle, host.params.recv_buf_size,
                             host.params.send_buf_size)
        elif kind == "tcp":
            from ..descriptor.tcp import TCPSocket
            sock = TCPSocket(host, handle, host.params.recv_buf_size,
                             host.params.send_buf_size)
        else:
            raise ValueError(f"unsupported socket kind {kind!r}")
        host.register_descriptor(sock)
        return handle

    def _sock(self, fd: int):
        s = self.host.descriptor_table_get(fd)
        if s is None:
            raise OSError(f"EBADF: {fd}")
        return s

    def bind(self, fd: int, addr: Tuple[Any, int]) -> None:
        sock = self._sock(fd)
        wildcard = addr[0] in ("", "0.0.0.0", None, 0)
        ip = self._resolve(addr[0])
        if hasattr(sock, "bind_native"):
            # C-plane socket: the binding tables live natively
            sock.bind_native(ip, addr[1], wildcard)
            return
        iface = self.host.interface_for_ip(ip)
        if iface is None:
            raise OSError("EADDRNOTAVAIL")
        # INADDR_ANY claims the port on every interface (loopback + eth),
        # like the reference's bind-to-any association — so both the
        # ephemeral-port scan and the in-use check must cover every
        # interface it will claim
        # dict.fromkeys: dedupe in insertion order so the ephemeral-port
        # scan and association order are run-to-run stable (SIM003)
        targets = list(dict.fromkeys(self.host.interfaces.values())) \
            if wildcard else [iface]
        port = addr[1]
        if port == 0:
            port = self.host.allocate_ephemeral_port(sock.kind, ip,
                                                     ifaces=targets)
        if any(t.is_associated(sock.kind, port) for t in targets):
            raise OSError("EADDRINUSE")
        sock.bind_to(iface.address.ip, port)
        for t in targets:
            t.associate(sock, sock.kind, port)

    def _resolve(self, name_or_ip) -> int:
        if isinstance(name_or_ip, int):
            return name_or_ip
        if name_or_ip in ("", "0.0.0.0", None):
            return self.host.default_address.ip
        if name_or_ip in ("localhost", "127.0.0.1"):
            from ..routing.address import LOCALHOST_IP
            return LOCALHOST_IP
        try:
            from ..routing.address import ip_to_int
            return ip_to_int(name_or_ip)
        except Exception:
            return self.gethostbyname(name_or_ip)

    def sendto(self, fd: int, data: bytes, addr: Optional[Tuple[Any, int]] = None) -> int:
        sock = self._sock(fd)
        if addr is not None:
            return sock.send_user_data(data, self._resolve(addr[0]), addr[1])
        return sock.send_user_data(data)

    def send(self, fd: int, data: bytes):
        """Blocking send: waits for buffer space (generator)."""
        sock = self._sock(fd)
        if type(data) is not bytes:
            data = bytes(data)
        if not data:
            return 0
        # fast path: the whole buffer fits in one call (no copies)
        n = sock.send_user_data(data)
        total = n
        size = len(data)
        while total < size:
            if n == 0:
                yield _Block(sock, S_WRITABLE)
            n = sock.send_user_data(data[total:])
            total += n
        return total

    def recvfrom(self, fd: int, nbytes: int = 65536):
        """Blocking receive (generator): returns (data, (src_ip, src_port))."""
        sock = self._sock(fd)
        while True:
            r = sock.receive_user_data(nbytes)
            if r is not None:
                data, ip, port = r
                return data, (ip, port)
            if sock.closed or sock.has_status(S_CLOSED):
                return b"", (0, 0)
            yield _Block(sock, S_READABLE)

    def recv(self, fd: int, nbytes: int = 65536):
        """Blocking receive, data only (flattened: one generator frame)."""
        sock = self._sock(fd)
        while True:
            r = sock.receive_user_data(nbytes)
            if r is not None:
                return r[0]
            if sock.closed or sock.has_status(S_CLOSED):
                return b""
            yield _Block(sock, S_READABLE)

    def recv_exact(self, fd: int, nbytes: int):
        """Blocking read of exactly ``nbytes``; None on EOF mid-read.  The
        shared framing helper for stream-protocol apps (flattened — this is
        the hottest read path of the cell-based app models)."""
        sock = self._sock(fd)
        parts = []
        got = 0
        while got < nbytes:
            r = sock.receive_user_data(nbytes - got)
            if r is None:
                if sock.closed or sock.has_status(S_CLOSED):
                    return None
                yield _Block(sock, S_READABLE)
                continue
            data = r[0]
            if not data:
                return None
            parts.append(data)
            got += len(data)
        return parts[0] if len(parts) == 1 else b"".join(parts)

    def try_recvfrom(self, fd: int, nbytes: int = 65536):
        """Non-blocking: None if nothing available."""
        r = self._sock(fd).receive_user_data(nbytes)
        if r is None:
            return None
        data, ip, port = r
        return data, (ip, port)

    def close(self, fd: int) -> None:
        d = self.host.descriptor_table_get(fd)
        if d is not None:
            d.close()

    def shutdown(self, fd: int, how: int = 1) -> None:
        """shutdown(2) on a connected TCP socket (0=RD, 1=WR, 2=RDWR)."""
        sock = self._sock(fd)
        if hasattr(sock, "shutdown"):
            sock.shutdown(how)
        else:
            raise OSError("ENOTSOCK")

    # -- TCP-specific (listen/accept/connect implemented with the TCP stack;
    # available once descriptor/tcp.py lands) ------------------------------
    def listen(self, fd: int, backlog: int = 128) -> None:
        self._sock(fd).listen(backlog)

    def accept(self, fd: int):
        sock = self._sock(fd)
        while True:
            child = sock.accept_child()
            if child is not None:
                return child.handle, (child.peer_ip, child.peer_port)
            yield _Block(sock, S_READABLE)

    def connect(self, fd: int, addr: Tuple[Any, int]):
        sock = self._sock(fd)
        ip = self._resolve(addr[0])
        done = sock.connect_to(ip, addr[1])
        if not done:
            yield _Block(sock, S_WRITABLE)
            err = sock.take_socket_error()
            if err:
                raise OSError(err)
        return 0

    # -- epoll -------------------------------------------------------------
    def epoll_create(self) -> int:
        from ..descriptor.epoll import Epoll
        host = self.host
        handle = host.allocate_handle()
        ep = Epoll(host, handle)
        host.register_descriptor(ep)
        return handle

    def epoll_ctl(self, epfd: int, op: str, fd: int, events: int = 0, data=None) -> None:
        ep = self._sock(epfd)
        desc = self._sock(fd)
        if op == "add":
            ep.ctl_add(desc, events, data if data is not None else fd)
        elif op == "mod":
            ep.ctl_mod(desc, events, data if data is not None else fd)
        elif op == "del":
            ep.ctl_del(desc)
        else:
            raise ValueError(op)

    def epoll_wait(self, epfd: int, timeout_sec: float = -1.0, max_events: int = 64):
        """Blocking epoll_wait (generator)."""
        ep = self._sock(epfd)
        if ep.has_ready():
            return ep.wait(max_events)
        if timeout_sec == 0:
            return []
        if timeout_sec > 0:
            deadline = self.now_ns() + stime.from_seconds(timeout_sec)
            while not ep.has_ready():
                remaining = deadline - self.now_ns()
                if remaining <= 0:
                    break
                fired = yield _Block(ep, S_READABLE, timeout_ns=remaining)
                if not fired:
                    break
            return ep.wait(max_events)
        while not ep.has_ready():
            yield _Block(ep, S_READABLE)
        return ep.wait(max_events)

    # -- timers ------------------------------------------------------------
    def timerfd_create(self) -> int:
        from ..descriptor.timer import Timer
        host = self.host
        handle = host.allocate_handle()
        tm = Timer(host, handle)
        host.register_descriptor(tm)
        return handle

    def eventfd_create(self, initval: int = 0, semaphore: bool = False) -> int:
        """eventfd(2): counter descriptor (thread-pool wakeups in epoll)."""
        from ..descriptor.eventfd import EventFD
        host = self.host
        handle = host.allocate_handle()
        ev = EventFD(host, handle, initval, semaphore)
        host.register_descriptor(ev)
        return handle

    def signalfd_create(self, mask: int) -> int:
        """signalfd(2): virtual-signal queue descriptor for this process."""
        from ..descriptor.signalfd import SignalFD
        host = self.host
        handle = host.allocate_handle()
        sfd = SignalFD(host, handle, mask, shared=self.process._signal_pending)
        host.register_descriptor(sfd)
        self.process._signal_fds.append(sfd)
        return handle

    def deliver_signal(self, signo: int) -> int:
        """Route a virtual signal raised by this process (raise()/kill() on
        the virtual pid).  signalfd(2) semantics: a blocked pending signal
        is ONE process-wide instance visible on EVERY open matching
        signalfd (all of them become readable — two epoll loops with
        overlapping masks both wake), and the FIRST read consumes it.
        Returns the number of matching signalfds; 0 = caller may fall back
        to its recorded handler (which is what the shim does).  Routing and
        liveness pruning live in the shared store (SharedSignalPending) —
        the process's _signal_fds list is just the descriptor registry."""
        return self.process._signal_pending.deliver(signo)

    def timerfd_settime(self, fd: int, initial_sec: float, interval_sec: float = 0.0) -> None:
        self._sock(fd).arm(stime.from_seconds(initial_sec),
                           stime.from_seconds(interval_sec))

    def timerfd_read(self, fd: int) -> int:
        return self._sock(fd).read_expirations()

    # -- pipes -------------------------------------------------------------
    def pipe(self) -> Tuple[int, int]:
        from ..descriptor.channel import Channel
        host = self.host
        rh, wh = host.allocate_handle(), host.allocate_handle()
        r, w = Channel.new_pipe(host, rh, wh)
        host.register_descriptor(r)
        host.register_descriptor(w)
        return rh, wh

    def socketpair(self) -> Tuple[int, int]:
        from ..descriptor.channel import Channel
        host = self.host
        ha, hb = host.allocate_handle(), host.allocate_handle()
        a, b = Channel.new_socketpair(host, ha, hb)
        host.register_descriptor(a)
        host.register_descriptor(b)
        return ha, hb

    def write(self, fd: int, data: bytes) -> int:
        return self._sock(fd).send_user_data(data)

    def read(self, fd: int, nbytes: int = 65536):
        """Blocking read from a pipe/channel (generator)."""
        d = self._sock(fd)
        while True:
            r = d.receive_user_data(nbytes)
            if r is not None:
                return r[0]
            yield _Block(d, S_READABLE)

    # -- threads (pthread family -> green threads) -------------------------
    def spawn(self, gen_func, *args) -> int:
        """pthread_create analog: runs another generator coroutine in this
        process."""
        t = self.process.spawn_thread(gen_func(*args))
        return t.tid

    def yield_(self):
        """Cooperative yield (pth_yield)."""
        yield None

    # -- device traffic plane ---------------------------------------------
    def device_flow_start(self, cells: Optional[int] = None,
                          route=None) -> int:
        """Hand this host's registered bulk transfer to the device traffic
        plane (parallel/device_plane.py); returns the flow handle.  The
        flow's route/size come from the process's own config args — apps
        call this once their control-plane setup (e.g. circuit build) is
        done, which is the moment the cells start moving on-device.
        ``route`` (hop host names, client-side order) cross-checks the
        runtime path against the plane's startup prediction for auto:
        consensus clients — a mismatch means the predicted consensus
        diverged from the fetched one, and must fail loudly."""
        plane = getattr(self.host.engine, "device_plane", None)
        if plane is None:
            raise RuntimeError("no device traffic plane in this simulation")
        if route is not None:
            plane.check_route(self.host.name, list(route))
        return plane.activate(self.host.name, cells)

    def device_flow_join(self, circuit: int):
        """Block until the device flow completes; returns the completion
        sim time ns (generator)."""
        result = yield _DeviceWait(circuit)
        return result

    # -- logging -----------------------------------------------------------
    def log(self, text: str, level: str = "message") -> None:
        """App log line, honoring the host's per-host loglevel filter
        (reference per-host ``loglevel`` attribute)."""
        from ..core.logger import LEVELS
        host_level = getattr(self.host.params, "log_level", None)
        if host_level is not None \
                and LEVELS.get(level, 3) > LEVELS.get(host_level, 3):
            return
        get_logger().log(level, f"app/{self.process.name}", text)


