# Developer/CI entry points for shadow-tpu.  Native artifacts have their
# own Makefile (native/); this one wires the static-analysis lanes.

PY ?= python
# `make lint-diff BASE=origin/main` lints only files changed since BASE
# (simlint) / reports only changed-file findings (simrace/simtwin —
# their rules are cross-module/cross-plane, so the ANALYSIS stays
# package-wide either way).
BASE ?= HEAD

.PHONY: lint lint-diff gen gen-check spec test bench-smoke bench-multichip \
	fuzz-smoke profile-smoke fault-smoke fleet-smoke check native \
	sanitize sanitize-thread

lint: gen-check
	$(PY) -m shadow_tpu.analysis.simlint shadow_tpu
	$(PY) -m shadow_tpu.analysis.simrace shadow_tpu
	$(PY) -m shadow_tpu.analysis.simtwin shadow_tpu native
	$(PY) -m shadow_tpu.analysis.simjit shadow_tpu

lint-diff:
	$(PY) -m shadow_tpu.analysis.simlint shadow_tpu --diff $(BASE)
	$(PY) -m shadow_tpu.analysis.simrace shadow_tpu --diff $(BASE)
	$(PY) -m shadow_tpu.analysis.simtwin shadow_tpu native --diff $(BASE)
	$(PY) -m shadow_tpu.analysis.simjit shadow_tpu --diff $(BASE)

# ISSUE 11: spec/protocol_spec.json is AUTHORITATIVE.  `make gen`
# materializes its surfaces into the fenced regions of all three planes
# (simgen --write) and refreshes the extracted read-back IR
# (spec/protocol.json, still byte-stable).  `make gen-check` fails on a
# stale or hand-edited region and on any read-back IR drift; it runs
# inside `make lint` so the gate is part of every lint pass.  (The
# read-back and the simtwin step each build the cross-plane TwinModel —
# a deliberate ~1-2s duplication: separate processes, independently
# trustworthy gates.)
gen:
	$(PY) -m shadow_tpu.analysis.simgen --write
	$(PY) -m shadow_tpu.analysis.simtwin --emit-spec spec/protocol.json --force

gen-check:
	$(PY) -m shadow_tpu.analysis.simgen --check

# retired: the extracted IR is no longer the thing you regenerate by hand
spec:
	@echo "make spec is retired: spec/protocol_spec.json is authoritative."
	@echo "Edit the spec, then run \`make gen\` (simgen --write +"
	@echo "simtwin --emit-spec); \`make gen-check\` verifies."
	@exit 1

test:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m 'not slow'

# <60s perf-machinery gate (ISSUE 7): a phold+star pass asserting
# superwindows engage (rounds_per_launch > 1) and the overlap/host-exec
# telemetry lands in the metrics JSONL (read back via
# tools/trace_report.py --metrics).  Gates the machinery, not rates.
bench-smoke:
	JAX_PLATFORMS=cpu $(PY) bench.py --smoke

# the MULTICHIP bench row (ISSUE 9): the mesh traffic plane over >= 2
# devices (the 8-virtual-device CPU mesh when no accelerator pool is
# present), bounded — a wedged run is killed and reported, never rc 124.
# Also gated inside bench-smoke via trace_report's metrics read-back.
bench-multichip:
	JAX_PLATFORMS=cpu $(PY) bench.py --multichip

# the scenario-fuzzing smoke (ISSUE 13): replay the checked-in
# fuzz/corpus/ regression set, then a bounded seeded sweep — each
# scenario in its own wall-capped child (the bench-multichip subprocess
# pattern: killed + reported on overrun, never rc 124), the sweep capped
# overall so a loaded box stops early and says so.  Any violation exits
# 1 with a shrunk repro file to replay (`simfuzz --repro PATH`).
fuzz-smoke:
	JAX_PLATFORMS=cpu $(PY) -m shadow_tpu.fuzz --corpus --in-process
	JAX_PLATFORMS=cpu $(PY) -m shadow_tpu.fuzz --seeds 8 \
		--timeout-sec 240 --wall-cap-sec 420

# the cost-observatory smoke (ISSUE 15): a wall-capped QUICK calibration
# on the virtual CPU mesh (temp output — the checked-in COSTMODEL.json is
# never touched), then `simprof check` validates the checked-in model's
# schema/digest and drills the stale-fingerprint + tamper refusal paths.
# On a box whose fingerprint differs from the model's, check still
# passes: refusing to load THERE is the contract being verified.
profile-smoke:
	JAX_PLATFORMS=cpu $(PY) -m shadow_tpu.prof calibrate --quick \
		--wall-cap-sec 240 --out /tmp/shadow-profile-smoke.json
	JAX_PLATFORMS=cpu $(PY) -m shadow_tpu.prof check

# the self-healing drill sweep (ISSUE 17): every rung of the recovery
# ladder — shard resurrection, mid-run device-loss re-shard, demote ->
# probation -> re-promotion — run end to end on the 8-virtual-device CPU
# mesh, gated BOTH ways (detour counted on the supervision ledger AND
# the drilled run lands its fault-free twin's exact digest); drill rows
# persist to BENCH_HISTORY.jsonl.
fault-smoke:
	JAX_PLATFORMS=cpu \
	XLA_FLAGS="--xla_force_host_platform_device_count=8" \
		$(PY) bench.py --fault-smoke

# the fleet-plane smoke (ISSUE 18): a bounded N=8 mixed fleet (drawn
# from the fuzz generator) run twice — serially (the reference) and as
# concurrent lanes over ONE shared vmapped device program — digest-gated
# bit for bit, and fail-closed on a fleet that never fired a batched
# launch.  `simfleet smoke` prints one JSON summary line, like bench.py.
# Also the runtime half of the SIM305 compile-budget contract (ISSUE 20):
# measured fleet.compiles / device_plane.sharded_variants are checked
# against the [tool.simjit.budget] table, failing on drift either way.
fleet-smoke:
	JAX_PLATFORMS=cpu $(PY) -m shadow_tpu.fleet smoke --lanes 8 --seeds 8

# the lint-adjacent gate set: static analysis + the fuzz/profile/fault/
# fleet smokes
check: lint fuzz-smoke profile-smoke fault-smoke fleet-smoke

native:
	$(MAKE) -C native

sanitize:
	$(MAKE) -C native sanitize

sanitize-thread:
	$(MAKE) -C native sanitize-thread
