"""COSTMODEL-driven dispatch auto-tuner + delta-compacted flush (ISSUE 16).

1. Decision table (pure plan_dispatch units): synthetic models force each
   regime — launch-bound => deep K, transfer-bound / no size slope =>
   compaction off, uncalibrated or out-of-range => hand defaults,
   ``--device-autotune off`` => untouched, an explicitly-set knob is
   always honored, cadence/granule stay at contract values.
2. Capped flush mechanics (ops level): the capped pack is bit-identical
   to the full pack on the surviving entries, the TRUE header counts make
   overflow detectable, and parse_flush reads the capped layout.
3. Engine integration: digest parity tuned-vs-hand-defaults,
   device-vs-numpy, explicit-K=1-vs-deep-K, and sharded-vs-serial under
   the tuner; compaction savings accounted in the scrape; the
   prof.model_stale alarm fires when the TUNED prediction misses the
   band (the tuner's audit trail is live, not just recorded).

Runs are shared through a module cache (the test_meshplane pattern) so
the new gates displace soak depth instead of growing the tier-1 wall.
"""

import os
import tempfile

import numpy as np
import pytest

from shadow_tpu.core import configuration
from shadow_tpu.core.checkpoint import state_digest
from shadow_tpu.core.controller import Controller
from shadow_tpu.core.options import Options
from shadow_tpu.prof import autotune, model as prof_model
from shadow_tpu.tools import workloads

# single-device star with enough chains (48) that the capped flush
# sections are strictly smaller than the full buffer — the compaction
# regime is reachable; still ~seconds at the 4 ms granule
STAR24_XML = workloads.star_bulk(24, stoptime=120,
                                 bulk_bytes=16 * 1024 * 1024,
                                 device_data=True)
# small sharded star for the mesh-path parity legs (test_simprof's size)
STAR6_XML = workloads.star_bulk(6, stoptime=120,
                                bulk_bytes=16 * 1024 * 1024,
                                device_data=True)

# models shared across tests/cached runs need a module-stable path
# (pytest tmp_path would fork the run-cache key per test)
_TD = tempfile.mkdtemp(prefix="autotune-models-")


def _measurements(step_points, dispatch_us=400.0, flush_us=1600.0,
                  flush_us_per_mb=0.0):
    return {
        "collectives": {
            "ppermute": {"2x24": 300.0, "8x24": 300.0},
            "all_to_all": {"2x24": 320.0, "8x24": 320.0},
            "psum": {"2x24": 50.0, "8x24": 50.0},
        },
        "step_kernel": {"points": step_points},
        "transfer": {"dispatch_us": dispatch_us, "flush_us": flush_us,
                     "flush_us_per_mb": flush_us_per_mb},
    }


def _model(step_points, **kw):
    return prof_model.CostModel(
        prof_model.build_model(_measurements(step_points, **kw)))


def _model_file(name, step_points, **kw):
    p = os.path.join(_TD, name)
    if not os.path.exists(p):
        prof_model.save_model(
            p, prof_model.build_model(_measurements(step_points, **kw)))
    return p


# a covering launch-bound model: flat cheap step cost, large fixed
# per-launch transfer, strong flush size slope — forces deep K AND
# compaction wherever the capped sections actually shrink the buffer
def _launch_bound_file():
    return _model_file("launch-bound.json",
                       [{"flows": 1, "us_per_step": 30.0},
                        {"flows": 1_000_000, "us_per_step": 30.0}],
                       flush_us_per_mb=200_000.0)


class _Opts:
    def __init__(self, k=8, cadence=8, autotune="on"):
        self.superwindow_rounds = k
        self.device_plane_batch_steps = cadence
        self.device_autotune = autotune


# -- 1. decision table ------------------------------------------------------

def test_plan_off_restores_hand_defaults():
    m = _model([{"flows": 1, "us_per_step": 30.0},
                {"flows": 1000, "us_per_step": 30.0}])
    plan = autotune.plan_dispatch(m, "loaded", _Opts(autotune="off"),
                                  500, 48, 25)
    assert plan.source == "off"
    assert plan.superwindow_rounds == autotune.DEFAULT_K
    assert plan.flush_compact is False


def test_plan_uncalibrated_falls_back_to_defaults():
    # no model on this box / model refused
    for model, status in ((None, "absent"), (None, "refused")):
        plan = autotune.plan_dispatch(model, status, _Opts(), 500, 48, 25)
        assert plan.source == "defaults"
        assert plan.superwindow_rounds == autotune.DEFAULT_K
        assert plan.flush_compact is False
    # loaded but the flow table sits outside the calibrated range: the
    # no-extrapolation guard refuses to tune from it
    m = _model([{"flows": 100_000, "us_per_step": 30.0},
                {"flows": 1_000_000, "us_per_step": 30.0}])
    assert not m.covers(500)
    plan = autotune.plan_dispatch(m, "loaded", _Opts(), 500, 48, 25)
    assert plan.source == "defaults"


def test_plan_launch_bound_deepens_k():
    # fixed transfer 2000us vs 30us/step at cadence 8: the fixed half
    # dominates -> K deepens to the MAX_K ceiling; cadence and granule
    # stay at their digest-bearing contract values
    m = _model([{"flows": 1, "us_per_step": 30.0},
                {"flows": 1_000_000, "us_per_step": 30.0}])
    plan = autotune.plan_dispatch(m, "loaded", _Opts(), 500, 12, 7)
    assert plan.source == "model"
    assert plan.superwindow_rounds == autotune.MAX_K
    assert plan.min_dispatch_steps == autotune.DEFAULT_CADENCE
    assert plan.granule_source == "contract"
    # a compute-bound box (expensive steps, same fixed cost) keeps the
    # hand default — no gratuitous deepening
    m2 = _model([{"flows": 1, "us_per_step": 5000.0},
                 {"flows": 1_000_000, "us_per_step": 5000.0}])
    plan2 = autotune.plan_dispatch(m2, "loaded", _Opts(), 500, 12, 7)
    assert plan2.source == "model"
    assert plan2.superwindow_rounds == autotune.DEFAULT_K


def test_plan_compaction_needs_measured_slope_and_real_savings():
    pts = [{"flows": 1, "us_per_step": 30.0},
           {"flows": 1_000_000, "us_per_step": 30.0}]
    # transfer-bound box but NO measured size slope: compaction cannot
    # price its savings -> stays off
    plan = autotune.plan_dispatch(_model(pts), "loaded", _Opts(),
                                  500, 4096, 1024)
    assert plan.source == "model" and plan.flush_compact is False
    # slope present + big buffer: on, with the capped sections recorded
    m = _model(pts, flush_us_per_mb=200_000.0)
    plan = autotune.plan_dispatch(m, "loaded", _Opts(), 500, 4096, 1024)
    assert plan.flush_compact is True
    assert plan.flush_cap_chains == autotune.flush_caps(4096, 1024)[0]
    assert plan.flush_bytes_cap_saved > 0
    # slope present but a tiny buffer the caps cannot shrink: off
    plan = autotune.plan_dispatch(m, "loaded", _Opts(), 500, 12, 7)
    assert plan.flush_compact is False


def test_plan_honors_explicit_user_knob():
    m = _model([{"flows": 1, "us_per_step": 30.0},
                {"flows": 1_000_000, "us_per_step": 30.0}])
    plan = autotune.plan_dispatch(m, "loaded", _Opts(k=1), 500, 12, 7)
    assert plan.source == "model"
    assert plan.superwindow_rounds == 1   # the user's knob, not ours


def test_plan_metrics_audit_trail():
    m = _model([{"flows": 1, "us_per_step": 30.0},
                {"flows": 1_000_000, "us_per_step": 30.0}])
    got = autotune.plan_dispatch(m, "loaded", _Opts(), 500, 12, 7).metrics()
    for key in ("prof.autotune_source", "prof.autotune_k",
                "prof.autotune_cadence", "prof.autotune_granule",
                "prof.autotune_flush_compact",
                "prof.autotune_predicted_us"):
        assert key in got, f"audit trail lost {key}"
    assert got["prof.autotune_source"] == "model"
    assert got["prof.autotune_granule"] == "contract"
    assert got["prof.autotune_predicted_us"] > 0


# -- 2. capped flush mechanics ----------------------------------------------

def test_capped_pack_parse_and_overflow_detection():
    from shadow_tpu.ops.torcells_device import (
        _pack_flush_jnp, flush_len, flush_overflowed, pack_flush_np,
        parse_flush)
    import jax.numpy as jnp

    C, H = 10, 12
    newly = np.zeros(C, bool)
    newly[[1, 4, 5, 9]] = True
    done_last = np.arange(C, dtype=np.int64) * 7
    sent_delta = np.zeros(H, np.int64)
    sent_delta[[0, 2, 3, 7, 8, 11]] = np.int64([5, -2, 9, 1, 4, 6])
    args = (np.int64(123), np.int64(456), np.int64(789),
            jnp.asarray(newly), jnp.asarray(done_last),
            jnp.asarray(sent_delta))
    full = np.asarray(_pack_flush_jnp(*args))
    # full-length pack is bit-identical to the numpy twin
    np.testing.assert_array_equal(
        full, pack_flush_np(np.int64(123), np.int64(456), np.int64(789),
                            newly, done_last, sent_delta))
    ref = parse_flush(full, C, H)
    # generous caps: same parse through the capped layout
    capped = np.asarray(_pack_flush_jnp(*args, cap_chains=8, cap_nodes=8))
    assert len(capped) == flush_len(C, H, 8, 8) < len(full)
    assert not flush_overflowed(capped, 8, 8)
    got = parse_flush(capped, C, H, 8, 8)
    assert got[:3] == ref[:3]
    for a, b in zip(got[3:], ref[3:]):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # tight caps: entries were dropped, and the TRUE header counts say so
    tight = np.asarray(_pack_flush_jnp(*args, cap_chains=2, cap_nodes=3))
    assert flush_overflowed(tight, 2, 3)
    assert int(tight[2]) == 4 and int(tight[3]) == 6


def test_flush_caps_shape():
    from shadow_tpu.ops.torcells_device import flush_len
    # floors of 16 chains / 64 nodes: a tiny net's caps cover the whole
    # buffer (flush_len clamps to the true sizes -> zero savings, and
    # plan_dispatch keeps compaction off)
    assert autotune.flush_caps(12, 7) == (16, 64)
    assert flush_len(12, 7, *autotune.flush_caps(12, 7)) == flush_len(12, 7)
    assert autotune.flush_caps(48, 25) == (16, 64)
    cap_c, cap_h = autotune.flush_caps(4096, 1024)
    assert cap_c == 512 and cap_h == 256


# -- 3. engine integration --------------------------------------------------

def _run(xml, n_dev=1, mode="device", k=8, sync=False,
         cost_model="/nonexistent-no-model", autotune_opt="on"):
    cfg = configuration.parse_xml(xml)
    cfg.stop_time_sec = 120
    ctrl = Controller(
        Options(scheduler_policy="global", workers=0, seed=3,
                stop_time_sec=120, log_level="warning",
                device_plane=mode, device_plane_sync=sync,
                superwindow_rounds=k, tpu_devices=n_dev,
                device_plane_granule_ms=4, cost_model=cost_model,
                device_autotune=autotune_opt), cfg)
    assert ctrl.run() == 0
    return ctrl


_CACHE: dict = {}


def _cached(xml_key, **kw):
    key = (xml_key, tuple(sorted(kw.items())))
    if key not in _CACHE:
        xml = STAR24_XML if xml_key == "star24" else STAR6_XML
        _CACHE[key] = _run(xml, **kw)
    return _CACHE[key]


def test_tuned_run_engages_and_accounts_savings():
    ctrl = _cached("star24", cost_model=_launch_bound_file())
    scrape = ctrl.engine.metrics.scrape()
    assert scrape["prof.autotune_source"] == "model"
    assert scrape["prof.autotune_k"] == autotune.MAX_K
    assert scrape["prof.autotune_flush_compact"] == 1
    # the capped encoding actually ran: readback bytes saved accumulated,
    # and any window that outran the caps was re-read full-length (the
    # digest-parity gate below proves none of it changed results)
    assert scrape["prof.flush_bytes_saved"] > 0
    st = ctrl.engine.device_plane.stats()
    assert st["flush_bytes_saved"] == scrape["prof.flush_bytes_saved"]
    # deep K engaged: launches amortize above the hand-default floor
    assert st["rounds_per_launch"] > 1


def test_digest_parity_tuned_vs_hand_defaults_and_numpy():
    tuned = _cached("star24", cost_model=_launch_bound_file())
    base = _cached("star24", cost_model=_launch_bound_file(),
                   autotune_opt="off")
    assert state_digest(base.engine) == state_digest(tuned.engine)
    assert base.engine.events_executed == tuned.engine.events_executed
    # the off side really ran the hand defaults
    assert base.engine.metrics.scrape()["prof.autotune_source"] == "off"
    twin = _cached("star24", cost_model=_launch_bound_file(), mode="numpy")
    assert state_digest(twin.engine) == state_digest(tuned.engine)


def test_digest_parity_explicit_k1_vs_deep_k():
    # --superwindow-rounds 1 is the user's knob: honored (K=1) even with
    # the launch-bound model, and bit-identical to the tuned deep-K run
    tuned = _cached("star24", cost_model=_launch_bound_file())
    k1 = _cached("star24", cost_model=_launch_bound_file(), k=1)
    assert k1.engine.metrics.scrape()["prof.autotune_k"] == 1
    assert state_digest(k1.engine) == state_digest(tuned.engine)


def test_digest_parity_sharded_tuned_vs_off_and_serial():
    import jax
    if len(jax.devices()) < 2:
        pytest.skip("mesh parity needs the virtual device mesh")
    tuned = _cached("star6", n_dev=8, cost_model=_launch_bound_file())
    off = _cached("star6", n_dev=8, cost_model=_launch_bound_file(),
                  autotune_opt="off")
    serial = _cached("star6", n_dev=8, cost_model=_launch_bound_file(),
                     sync=True)
    assert state_digest(off.engine) == state_digest(tuned.engine)
    assert state_digest(serial.engine) == state_digest(tuned.engine)
    scrape = tuned.engine.metrics.scrape()
    assert scrape["prof.autotune_source"] == "model"
    # quiet-tick fusion bookkeeping: the masked variants never claim more
    # active legs than the schedule has
    assert 0 <= scrape["mesh.legs_active"] <= scrape["mesh.exchange_legs"]


def test_model_stale_fires_on_tuned_misprediction():
    # an absurd covering model engages the tuner (source=model) AND its
    # prediction misses the band on every launch — the audit loop is
    # live on tuned runs, not only on hand-default ones
    absurd = _model_file("absurd.json",
                         [{"flows": 1, "us_per_step": 5e6},
                          {"flows": 1_000_000, "us_per_step": 5e6}],
                         dispatch_us=5e6, flush_us=5e6)
    ctrl = _cached("star6", cost_model=absurd)
    scrape = ctrl.engine.metrics.scrape()
    assert scrape["prof.autotune_source"] == "model"
    assert scrape["prof.model_stale"] > 0
