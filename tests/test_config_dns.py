"""Configuration (legacy XML + YAML) and DNS registry tests."""

import textwrap

from shadow_tpu.core import configuration
from shadow_tpu.routing.dns import DNS
from shadow_tpu.routing.address import ip_to_int, int_to_ip

LEGACY_XML = textwrap.dedent("""\
    <shadow stoptime="3600" bootstraptime="300" environment="A=1;B=2">
      <topology path="topology.graphml.xml" />
      <plugin id="tgen" path="python:tgen" />
      <host id="server" quantity="1" bandwidthdown="102400" bandwidthup="102400"
            iphint="100.0.0.1" logpcap="true" cpufrequency="2500000">
        <process plugin="tgen" starttime="1" arguments="server 80" />
      </host>
      <host id="client" quantity="10" typehint="client">
        <process plugin="tgen" starttime="2" stoptime="600" arguments="client server 80" />
      </host>
    </shadow>
""")


def test_parse_legacy_xml():
    cfg = configuration.parse_xml(LEGACY_XML)
    assert cfg.stop_time_sec == 3600
    assert cfg.bootstrap_end_sec == 300
    assert cfg.environment == {"A": "1", "B": "2"}
    assert cfg.topology_path == "topology.graphml.xml"
    assert len(cfg.programs) == 1 and cfg.programs[0].path == "python:tgen"
    assert len(cfg.hosts) == 2
    server = cfg.hosts[0]
    assert server.id == "server" and server.bandwidth_down_kibps == 102400
    assert server.ip_hint == "100.0.0.1" and server.log_pcap
    assert server.cpu_frequency_khz == 2500000
    assert server.processes[0].arguments == "server 80"
    client = cfg.hosts[1]
    assert client.quantity == 10 and client.type_hint == "client"
    assert client.processes[0].stop_time_sec == 600
    assert cfg.total_process_count() == 11


def test_parse_yaml_dict():
    d = {
        "general": {"stop_time": 100},
        "network": {"graph": {"path": "g.graphml"}},
        "hosts": {
            "h1": {"processes": [{"path": "python:echo", "args": ["udp", "server"],
                                  "start_time": 1}]},
        },
    }
    cfg = configuration.parse_dict(d)
    assert cfg.stop_time_sec == 100
    assert cfg.topology_path == "g.graphml"
    assert cfg.hosts[0].processes[0].arguments == "udp server"


def test_dns_assignment_deterministic_and_restricted():
    d1, d2 = DNS(), DNS()
    a1 = [d1.register(i, f"h{i}") for i in range(50)]
    a2 = [d2.register(i, f"h{i}") for i in range(50)]
    assert [a.ip for a in a1] == [a.ip for a in a2]
    assert len({a.ip for a in a1}) == 50
    for a in a1:
        assert not int_to_ip(a.ip).startswith("127.")
    assert d1.resolve_name("h7").ip == a1[7].ip
    assert d1.resolve_ip(a1[7].ip).name == "h7"


def test_dns_requested_ip():
    d = DNS()
    want = ip_to_int("100.1.2.3")
    a = d.register(0, "pinned", requested_ip=want)
    assert a.ip == want
    # duplicate request falls back to auto-assignment
    b = d.register(1, "other", requested_ip=want)
    assert b.ip != want


def test_load_dispatches_by_format(tmp_path):
    """configuration.load() handles .xml, .json, and .yaml files."""
    xml = ('<shadow stoptime="9"><plugin id="e" path="python:echo" />'
           '<host id="h"><process plugin="e" starttime="1" '
           'arguments="udp server 1" /></host></shadow>')
    d = {"general": {"stop_time": 9},
         "hosts": {"h": {"processes": [
             {"path": "python:echo", "args": ["udp", "server", "1"],
              "start_time": 1}]}}}
    import json
    (tmp_path / "c.xml").write_text(xml)
    (tmp_path / "c.json").write_text(json.dumps(d))
    import yaml
    (tmp_path / "c.yaml").write_text(yaml.safe_dump(d))
    for name in ("c.xml", "c.json", "c.yaml"):
        cfg = configuration.load(str(tmp_path / name))
        assert cfg.stop_time_sec == 9, name
        assert len(cfg.hosts) == 1, name
