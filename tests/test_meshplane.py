"""meshplane parity suite (ISSUE 9): the multi-chip sharded traffic plane
(shadow_tpu/parallel/mesh/) on the 8-virtual-device CPU mesh.

1. Partition + exchange statics: the chain partitioner is deterministic,
   segment-aligned, balanced, and never cuts more hops than the old
   contiguous split; the BvN schedule covers every cross-shard successor
   edge exactly once with <= D-1 permutation legs.
2. Kernel bit parity (migrated from test_device_plane's PR-7 sharded
   kernel gate): the mesh superwindow kernel — shard-local arrival ring,
   ppermute exchange legs — is bit-identical to the single-device span
   kernel, packed flush included, at D=8 and uneven D=3.
3. Engine digest parity sharded-vs-single-device-vs-numpy-twin-vs-serial
   (--device-plane-sync) on a generated star scenario and a tor network,
   at K=1 and K=8, with the acceptance metrics: mesh.host_bounces == 0
   (cross-shard forwards never transit the host), cross_shard_cells > 0
   (the legs actually carried traffic), and <= 3 device calls per
   dispatch (the single-device plane's pipeline budget).
4. Composition: K=8 superwindows engage with the halt flag psum'd across
   shards, checkpoint/resume mid-superwindow on a sharded run, and the
   device-dispatch fault drill demoting the sharded plane to the numpy
   twin with digest parity preserved.
"""

import glob

import numpy as np
import pytest

from shadow_tpu.core import configuration
from shadow_tpu.core.checkpoint import state_digest
from shadow_tpu.core.controller import Controller
from shadow_tpu.core.options import Options
from shadow_tpu.tools import workloads

STAR_XML = workloads.star_bulk(6, stoptime=120, bulk_bytes=192 * 1024 * 1024,
                               device_data=True)
TOR_XML = workloads.tor_network(8, n_clients=5, n_servers=2, stoptime=60,
                                stream_spec="512:20200", device_data=True)


def _run(xml, n_dev=8, k=1, mode="device", policy="global", sync=False,
         stop=120, **opt_kw):
    cfg = configuration.parse_xml(xml)
    cfg.stop_time_sec = stop
    ctrl = Controller(Options(scheduler_policy=policy, workers=0, seed=3,
                              stop_time_sec=stop, log_level="warning",
                              device_plane=mode, device_plane_sync=sync,
                              superwindow_rounds=k, tpu_devices=n_dev,
                              **opt_kw), cfg)
    assert ctrl.run() == 0
    return ctrl


# several gates compare against the same star configurations; runs are
# deterministic, so repeat configurations are executed once and shared
# (keeps the suite's tier-1 wall share down — each run is a few seconds)
_STAR_CACHE: dict = {}


def _star(n_dev=8, k=1, mode="device"):
    key = (n_dev, k, mode)
    if key not in _STAR_CACHE:
        _STAR_CACHE[key] = _run(STAR_XML, n_dev=n_dev, k=k, mode=mode)
    return _STAR_CACHE[key]


def _mesh_scrape(ctrl):
    return {k: v for k, v in ctrl.engine.metrics.scrape().items()
            if k.startswith("mesh.")}


# -- partition + exchange statics ------------------------------------------

def _toy_flows():
    from shadow_tpu.ops.torcells_device import DeviceTorCells
    inst = DeviceTorCells(n_relays=6, n_circuits=20, seed=5,
                          relay_bw_kibps=512, max_latency_ms=20)
    return inst


def test_chain_partition_deterministic_balanced_and_no_worse():
    from shadow_tpu.parallel.mesh.partition import (chain_partition,
                                                    contiguous_partition)
    inst = _toy_flows()
    fl = inst.flows
    a, cross_a = chain_partition(fl["flow_node"], fl["flow_succ"], 8)
    b, cross_b = chain_partition(fl["flow_node"], fl["flow_succ"], 8)
    np.testing.assert_array_equal(a, b)
    # segment alignment: every flow's node maps to exactly one shard by
    # construction; balance: no shard exceeds budget + one max segment
    f = len(fl["flow_node"])
    sizes = np.bincount(a[fl["flow_node"]], minlength=8)
    seg_max = np.bincount(fl["flow_node"]).max()
    assert sizes.max() <= -(-f // 8) + seg_max
    # the chain walker must not cut more hops than the pre-mesh
    # contiguous split (its baseline) on the same table
    contig = contiguous_partition(fl["flow_node"], 8)
    valid = fl["flow_succ"] >= 0
    cut = np.count_nonzero(
        contig[fl["flow_node"][valid]]
        != contig[fl["flow_node"][fl["flow_succ"][valid]]])
    assert cross_a <= cut


def test_exchange_schedule_is_a_bvn_decomposition():
    """Every cross-shard successor edge rides exactly one leg slot; each
    leg is a rotation permutation (shard s talks only to (s+r) % D), and
    the leg count is bounded by D-1 (offset 0 is local traffic)."""
    from shadow_tpu.parallel.mesh.partition import build_mesh_layout
    inst = _toy_flows()
    fl = inst.flows
    for n_dev in (8, 3):
        lay = build_mesh_layout(fl["flow_node"], fl["flow_lat"],
                                fl["flow_succ"], fl["seg_start"],
                                inst.refill, inst.capacity, n_dev)
        sched = lay["exchange"]
        assert 1 <= sched.legs <= n_dev - 1
        assert all(0 < r < n_dev for r in sched.offsets)
        pad = lay["pad"]
        succ = lay["succ_global"]
        # reconstruct (src shard, local src, local dst) triples from the
        # leg tables and compare against the raw cross edges
        from_tables = set()
        for r, w, snd, rcv in zip(sched.offsets, sched.widths,
                                  sched.send_src, sched.recv_dst):
            for s in range(n_dev):
                d = (s + r) % n_dev
                for slot in range(w):
                    src_row = snd[s * w + slot]
                    dst_row = rcv[d * w + slot]
                    assert (src_row < 0) == (dst_row < 0), \
                        "sender/receiver slot tables out of step"
                    if src_row >= 0:
                        from_tables.add((s, int(src_row), d, int(dst_row)))
        expect = set()
        for i in np.flatnonzero(succ >= 0).tolist():
            s, d = i // pad, int(succ[i]) // pad
            if s != d:
                expect.add((s, i - s * pad, d, int(succ[i]) - d * pad))
        assert from_tables == expect
        assert sched.cross_edges == len(expect)


def test_pad_state_contract():
    """pad_state is the one original->padded translation: real rows land
    at inv positions, padding rows keep the fill value."""
    from shadow_tpu.parallel.mesh.partition import (build_mesh_layout,
                                                    pad_state)
    inst = _toy_flows()
    fl = inst.flows
    lay = build_mesh_layout(fl["flow_node"], fl["flow_lat"],
                            fl["flow_succ"], fl["seg_start"],
                            inst.refill, inst.capacity, 8)
    a = np.arange(inst.n_flows, dtype=np.int64) + 7
    p = pad_state(lay, a, fill=-5)
    np.testing.assert_array_equal(p[lay["inv"]], a)
    assert (p[~lay["keep"]] == -5).all()


# -- kernel bit parity ------------------------------------------------------

@pytest.mark.parametrize("n_dev", [8, 3, 2])
def test_mesh_kernel_bit_parity(n_dev):
    """The mesh superwindow kernel (shard-local ring, fused on-device
    exchange) is bit-identical to the single-device span kernel across
    split windows, packed flush buffer included — at D=8 (fused
    all_to_all), uneven D=3 (N % D != 0 exercises per-shard padding),
    and D=2 (a single-leg schedule exercises the lone-ppermute path)."""
    import jax.numpy as jnp
    from shadow_tpu.ops.torcells_device import (
        RING_DTYPE, flush_len, torcells_step_window_flush_nodonate)
    from shadow_tpu.parallel.mesh import device_mesh
    from shadow_tpu.parallel.mesh.exchange import make_mesh_span_flush
    from shadow_tpu.parallel.mesh.partition import (build_mesh_layout,
                                                    pad_state)

    inst = _toy_flows()
    fl = inst.flows
    f = inst.n_flows
    h = len(inst.refill)
    c = len(np.flatnonzero(fl["flow_succ"] < 0))
    last_flow = np.flatnonzero(fl["flow_succ"] < 0)
    queued0 = np.where(fl["flow_stage"] == 0, 30, 0).astype(np.int64)
    target0 = np.where(fl["flow_succ"] < 0, 30, 0).astype(np.int64)
    zeros = np.zeros(f, np.int64)
    targets1 = np.array([40], dtype=np.int64)
    targets2 = np.array([140, 240, 540], dtype=np.int64)

    # single-device oracle: one window, then a 3-span superwindow
    sstate = (jnp.int64(0), jnp.zeros(f, jnp.int64),
              jnp.zeros((inst.ring_len, f), RING_DTYPE),
              jnp.asarray(inst.capacity), jnp.zeros(f, jnp.int64),
              jnp.zeros(f, jnp.int64), jnp.full(f, -1, jnp.int64),
              jnp.zeros(h, jnp.int64))
    args = (jnp.asarray(fl["flow_node"]), jnp.asarray(fl["flow_lat"]),
            jnp.asarray(fl["flow_succ"]), jnp.asarray(fl["seg_start"]),
            jnp.asarray(inst.refill), jnp.asarray(inst.capacity),
            jnp.asarray(last_flow))
    ref = torcells_step_window_flush_nodonate(
        *sstate, queued0, target0, targets1, np.int64(0), *args,
        ring_len=inst.ring_len)
    ref = torcells_step_window_flush_nodonate(
        *ref[:8], zeros, zeros, targets2, np.int64(0), *args,
        ring_len=inst.ring_len)

    mesh = device_mesh(n_dev, axis_names=("flows",))
    lay = build_mesh_layout(fl["flow_node"], fl["flow_lat"],
                            fl["flow_succ"], fl["seg_start"],
                            inst.refill, inst.capacity, n_dev)
    fp = len(lay["src"])
    step = make_mesh_span_flush(mesh, "flows", inst.ring_len, lay,
                                lay["inv"][last_flow], lay["node_src"], h)
    statics = (lay["flow_node_local"], lay["succ_global"],
               lay["seg_start_local"], lay["refill"], lay["capacity"],
               lay["arr_lat"], lay["shard_base"])
    zp = np.zeros(fp, np.int64)
    mstate = (np.int64(0), jnp.asarray(pad_state(lay, zeros)),
              jnp.zeros((inst.ring_len, fp), RING_DTYPE),
              jnp.asarray(lay["capacity"]), jnp.zeros(fp, jnp.int64),
              jnp.zeros(fp, jnp.int64), jnp.full(fp, -1, jnp.int64),
              jnp.zeros(len(lay["refill"]), jnp.int64))
    out = step(*mstate, pad_state(lay, queued0), pad_state(lay, target0),
               targets1, np.int64(0), *statics)
    out = step(*out[:8], zp, zp, targets2, np.int64(0), *statics)

    inv = lay["inv"]
    for name, i in (("queued", 1), ("delivered", 4), ("target", 5),
                    ("done", 6)):
        np.testing.assert_array_equal(np.asarray(out[i])[inv],
                                      np.asarray(ref[i]), err_msg=name)
    assert int(out[0]) == int(ref[0])           # halt boundary agrees
    base = flush_len(c, h)
    np.testing.assert_array_equal(np.asarray(out[9])[:base],
                                  np.asarray(ref[9]))
    assert int(np.asarray(out[9])[base]) > 0    # legs carried cells


# -- engine digest parity (the acceptance gate) ----------------------------

def _assert_mesh_contract(ctrl, max_calls=3):
    plane = ctrl.engine.device_plane
    scrape = _mesh_scrape(ctrl)
    assert plane._shard is not None, "mesh layout did not engage"
    assert scrape["mesh.host_bounces"] == 0
    assert scrape["mesh.cross_shard_cells"] > 0, \
        "no cells crossed shards — the exchange gate is vacuous"
    assert scrape["mesh.exchange_legs"] >= 1
    assert scrape["mesh.devices"] == plane._meshinfo.n_devices
    st = plane.stats()
    assert st["device_calls"] / max(st["dispatches"], 1) <= max_calls, st


def test_star_parity_sharded_vs_single_vs_twin_k1_and_k8():
    """The acceptance gate on the generated star scenario: sharded(8),
    single-device, and the numpy twin end bit-identical at K=1 and K=8,
    with cross-shard forwards exchanged on-device (host_bounces == 0) and
    the per-dispatch device-call budget <= 3."""
    digests = {}
    for k in (1, 8):
        sharded = _star(n_dev=8, k=k)
        _assert_mesh_contract(sharded)
        single = _star(n_dev=1, k=k)
        assert single.engine.device_plane._shard is None
        twin = _star(n_dev=8, k=k, mode="numpy")
        d = state_digest(sharded.engine)
        assert d == state_digest(single.engine), f"K={k} sharded != single"
        assert d == state_digest(twin.engine), f"K={k} sharded != twin"
        st = sharded.engine.device_plane.stats()
        assert st["completed"] == st["circuits"] == 6
        digests[k] = d
    assert digests[1] == digests[8]


def test_star_parity_pipelined_vs_serial_schedule():
    """Sharded pipelined vs the --device-plane-sync serial oracle: the
    same digest, so overlap never reorders anything on the mesh either."""
    piped = _star(n_dev=8, k=8)
    serial = _run(STAR_XML, n_dev=8, k=8, sync=True)
    assert state_digest(piped.engine) == state_digest(serial.engine)


def test_tor_parity_sharded_vs_single_vs_twin():
    """tor-shaped control chatter (circuit TCP through the real engine)
    with the bulk phase sharded: digests match single-device and the twin
    at K=1 and K=8."""
    for k in (1, 8):
        sharded = _run(TOR_XML, n_dev=8, k=k, stop=60)
        _assert_mesh_contract(sharded)
        single = _run(TOR_XML, n_dev=1, k=k, stop=60)
        twin = _run(TOR_XML, n_dev=8, k=k, stop=60, mode="numpy")
        d = state_digest(sharded.engine)
        assert d == state_digest(single.engine), f"K={k}"
        assert d == state_digest(twin.engine), f"K={k}"


def test_uneven_partition_parity():
    """N % D != 0: 6 circuits over 3 and 5 devices — per-shard padding
    differs per shard and digests still match single-device."""
    single = _star(n_dev=1)
    for n_dev in (3, 5):
        sharded = _run(STAR_XML, n_dev=n_dev)
        assert sharded.engine.device_plane._shard is not None
        assert sharded.engine.device_plane._shard["n_shards"] == n_dev
        assert state_digest(sharded.engine) == state_digest(single.engine)


# -- composition: superwindows, checkpoints, fault drill -------------------

def test_superwindow_halt_flag_psum_across_shards():
    """K=8 on the mesh: superwindows engage (multi-round launches), the
    per-tick completion flag is psum'd so every shard halts at the same
    boundary — pinned by digest parity against K=1 and by the wake times
    all landing inside the run."""
    k8 = _star(n_dev=8, k=8)
    k1 = _star(n_dev=8, k=1)
    st = k8.engine.device_plane.stats()
    assert st["superwindows"] > 0, "superwindows never engaged on the mesh"
    assert st["rounds_per_launch"] > 1.0
    assert st["completed"] == 6
    assert state_digest(k8.engine) == state_digest(k1.engine)
    assert k8.engine.rounds_executed == k1.engine.rounds_executed


def test_checkpoint_resume_mid_superwindow_sharded(tmp_path):
    """--checkpoint-every on a sharded K=8 run: snapshots land on exact
    round boundaries (the superwindow budget stops merges short of every
    cadence point), and --resume replays to a digest-verified boundary
    and finishes bit-identical to the uninterrupted run."""
    d_clean = state_digest(_star(n_dev=8, k=8).engine)
    ckdir = str(tmp_path / "ck")
    _run(STAR_XML, n_dev=8, k=8, checkpoint_every_rounds=30,
         checkpoint_dir=ckdir)
    snaps = sorted(glob.glob(ckdir + "/checkpoint_r*.ckpt"))
    assert snaps, "sharded K=8 run wrote no snapshots"
    resumed = _run(STAR_XML, n_dev=8, k=8, resume_path=ckdir,
                   checkpoint_dir=str(tmp_path / "ck2"))
    assert resumed.engine.supervision.resume_verified
    assert state_digest(resumed.engine) == d_clean


def test_fault_drill_demotes_sharded_plane_to_numpy_twin():
    """--fault-inject device-dispatch:2 on the mesh: the failed in-flight
    dispatch replays on the numpy twin, the backend demotes permanently,
    and the final digest still matches the clean twin run.  The demoted
    windows' cross-shard forwards run HOST-side, so mesh.host_bounces
    goes NONZERO here — the proof that the steady-state == 0 gate is
    falsifiable, not a tautology."""
    dev = _run(STAR_XML, n_dev=8, fault_inject="device-dispatch:2")
    plane = dev.engine.device_plane
    assert plane.demoted and plane.mode == "numpy"
    assert plane.recoveries == 1
    assert dev.engine.supervision.recoveries == 1
    scrape = _mesh_scrape(dev)
    assert scrape["mesh.host_bounces"] > 0, \
        "demoted cross-shard windows must count as host bounces"
    assert scrape["mesh.demoted"] == 1
    twin = _star(n_dev=8, mode="numpy")
    assert state_digest(dev.engine) == state_digest(twin.engine)


# -- tor200 (the acceptance scale point; excluded from tier-1) -------------

@pytest.mark.slow
def test_tor200_parity_sharded_vs_single_vs_serial():
    """The ISSUE 9 acceptance gate at the tor200 scale point: digest
    parity sharded-vs-single-device-vs-serial-schedule at K=1 and K=8
    with on-device cross-shard exchange asserted."""
    xml = workloads.tor_network(200, stoptime=60, device_data=True)
    for k in (1, 8):
        sharded = _run(xml, n_dev=8, k=k, stop=60)
        _assert_mesh_contract(sharded)
        single = _run(xml, n_dev=1, k=k, stop=60)
        serial = _run(xml, n_dev=8, k=k, stop=60, sync=True)
        d = state_digest(sharded.engine)
        assert d == state_digest(single.engine), f"K={k} sharded != single"
        assert d == state_digest(serial.engine), f"K={k} sharded != serial"
