"""Sim-time and event total-order tests (reference event.c:110-153 contract)."""

import itertools

from shadow_tpu.core import stime
from shadow_tpu.core.event import Event
from shadow_tpu.core.task import Task
from shadow_tpu.utils.pqueue import PriorityQueue


class FakeHost:
    def __init__(self, hid):
        self.id = hid
        self.cpu = None


def _noop(obj, arg):
    pass


def mk(t, dst, src, seq):
    return Event(Task(_noop), t, FakeHost(dst), FakeHost(src), seq)


def test_time_conversions():
    assert stime.from_seconds(1.5) == 1_500_000_000
    assert stime.from_millis(10) == 10_000_000
    assert stime.to_seconds(2_000_000_000) == 2.0
    assert stime.emulated_from_sim(0) == 946_684_800 * stime.SIM_TIME_SEC
    assert stime.sim_from_emulated(stime.emulated_from_sim(123)) == 123


def test_event_total_order():
    # (time, dst, src, seq) lexicographic — every permutation sorts the same.
    events = [mk(2, 0, 0, 0), mk(1, 1, 0, 0), mk(1, 0, 1, 0), mk(1, 0, 0, 1),
              mk(1, 0, 0, 0), mk(3, 5, 5, 5)]
    expected = sorted(events, key=lambda e: e.order_key())
    for perm in itertools.permutations(events):
        assert sorted(perm, key=lambda e: e.order_key()) == expected


def test_pqueue_orders_events():
    q = PriorityQueue()
    evs = [mk(5, 1, 1, 0), mk(1, 0, 0, 0), mk(5, 0, 0, 0), mk(3, 2, 2, 2)]
    for e in evs:
        q.push(e)
    popped = [q.pop() for _ in range(len(evs))]
    assert popped == sorted(evs, key=lambda e: e.order_key())
    assert q.pop() is None


def test_pqueue_pop_breaks_refcycle():
    # The engine runs with cyclic GC disabled: a popped item must be
    # collectable by refcount alone, i.e. pop()/pop_before() must clear the
    # entry->item and item->entry links (ADVICE r3 high finding).
    import gc
    import weakref

    class Item:
        __slots__ = ("pq_entry", "__weakref__")

        def __init__(self):
            self.pq_entry = None

    q = PriorityQueue()
    refs = []
    for t in (1, 2):
        e = Item()
        refs.append(weakref.ref(e))
        q.push(e, key=(t, 0, 0, 0))
    del e
    gc.disable()
    try:
        a = q.pop()
        assert a.pq_entry is None
        del a
        assert refs[0]() is None, "popped event still referenced (ref cycle)"
        b = q.pop_before(10)
        assert b.pq_entry is None
        del b
        assert refs[1]() is None, "pop_before event still referenced"
    finally:
        gc.enable()


def test_pqueue_remove():
    q = PriorityQueue()
    a, b = mk(1, 0, 0, 0), mk(2, 0, 0, 0)
    q.push(a); q.push(b)
    assert a in q
    assert q.remove(a)
    assert not q.remove(a)
    assert q.pop() is b
    assert len(q) == 0
