"""Multi-threaded stress over the shared observability planes (ISSUE 5
satellite): ``TraceRing``/``Tracer``, ``MetricsRegistry`` and
``SimLogger`` hammered from concurrent threads, asserting EXACT counts
(the registry lock — simrace's first customer — is what makes unlocked
``value += n`` update loss impossible), schema-valid records, and
byte-stable output where the format promises determinism (the logger's
(sim_time, thread) sort; the registry's sorted scrape).

These are the dynamic complements to the simrace static pass: the rules
prove the locks exist; this file proves they do their job under real
contention.
"""

from __future__ import annotations

import io
import json
import re
import threading

from shadow_tpu.core.logger import SimLogger
from shadow_tpu.obs.metrics import MetricsRegistry
from shadow_tpu.obs.trace import Tracer

N_THREADS = 8
N_OPS = 2_000


def _storm(n_threads, body):
    """Run ``body(tid)`` on n threads through a start barrier (maximum
    contention), re-raising any worker exception."""
    barrier = threading.Barrier(n_threads)
    errors = []

    def run(tid):
        try:
            barrier.wait(timeout=30)
            body(tid)
        except BaseException as e:  # noqa: BLE001 - surfaced below
            errors.append(e)

    threads = [threading.Thread(target=run, args=(i,), daemon=True)
               for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
        assert not t.is_alive(), "stress worker wedged"
    if errors:
        raise errors[0]


# ---------------------------------------------------------------------------
# MetricsRegistry


def test_metrics_exact_counts_under_contention():
    reg = MetricsRegistry(enabled=True)
    c = reg.counter("stress.counter")
    h = reg.histogram("stress.hist_us")

    def body(tid):
        g = reg.gauge(f"stress.gauge.{tid}")
        for i in range(N_OPS):
            c.inc()
            h.observe(i + 1)
            g.set(i)
            reg.record_host_heartbeat(f"host{tid}", {"tx": 1, "rx": i})

    _storm(N_THREADS, body)
    scrape = reg.scrape()
    # unlocked `value += n` loses updates under this contention level;
    # the registry lock makes the totals EXACT, not approximate
    assert scrape["stress.counter"] == N_THREADS * N_OPS
    assert scrape["stress.hist_us"]["count"] == N_THREADS * N_OPS
    assert scrape["stress.hist_us"]["min"] == 1
    assert scrape["stress.hist_us"]["max"] == N_OPS
    assert scrape["tracker.hosts_reporting"] == N_THREADS
    for tid in range(N_THREADS):
        assert scrape[f"stress.gauge.{tid}"] == N_OPS - 1


def test_metrics_scrape_consistent_while_storming():
    """Concurrent scrapes during the storm: every record must be
    internally consistent (histogram count == bucket sum — the property
    a torn mid-observe read would break) and JSON-serializable."""
    reg = MetricsRegistry(enabled=True)
    c = reg.counter("s.c")
    h = reg.histogram("s.h")
    stop = threading.Event()
    scrapes = []

    def reader():
        while not stop.is_set():
            scrapes.append(reg.scrape())
        scrapes.append(reg.scrape())

    rt = threading.Thread(target=reader, daemon=True)
    rt.start()

    def body(tid):
        for i in range(N_OPS):
            c.inc()
            h.observe(i + 1)
            if i % 257 == 0:
                # concurrent source REGISTRATION while scrape iterates
                # sorted(self._sources.items()) — unlocked, this raises
                # "dictionary changed size during iteration"
                reg.source(f"src.{tid}.{i}", lambda t=tid: {f"sv.{t}": 1})

    try:
        _storm(N_THREADS, body)
    finally:
        stop.set()
        rt.join(timeout=60)
    assert not rt.is_alive()
    assert scrapes, "reader thread never scraped"
    for s in scrapes:
        json.dumps(s, sort_keys=True)       # schema-valid / serializable
        hist = s["s.h"]
        if hist["count"]:
            assert sum(hist["buckets"].values()) == hist["count"], \
                "torn histogram read: bucket sum != count"
    final = reg.scrape()
    assert final["s.c"] == N_THREADS * N_OPS
    # quiesced: two scrapes are byte-identical
    assert json.dumps(final, sort_keys=True) == \
        json.dumps(reg.scrape(), sort_keys=True)


# ---------------------------------------------------------------------------
# Tracer flight-recorder ring


def test_tracer_ring_exact_and_schema_valid_under_contention():
    ring = 256
    tracer = Tracer(enabled=True, ring=ring)

    def body(tid):
        for i in range(N_OPS // 2):
            with tracer.span(f"work.{tid}", "stress", sim_ns=i):
                pass
            tracer.instant(f"mark.{tid}", "stress", sim_ns=i)

    _storm(N_THREADS, body)
    events = tracer.events()
    per_thread = 2 * (N_OPS // 2)
    # ring accounting is exact under the tracer lock: kept + dropped ==
    # recorded, and every track respects its bound
    assert len(events) + tracer.dropped == N_THREADS * per_thread
    tracks = {}
    for ev in events:
        tracks.setdefault(ev["tid"], []).append(ev)
        assert ev["ph"] in ("X", "i")
        assert isinstance(ev["ts"], float) and ev["ts"] >= 0
        assert ev["args"]["sim_ns"] >= 0
        assert ev["cat"] == "stress"
    assert len(tracks) == N_THREADS
    for tid, evs in tracks.items():
        assert len(evs) <= ring, f"track {tid} overflowed its ring"
        # each surviving ring is the TAIL of that thread's stream, in
        # emission order (deque append order survives the storm)
        sims = [e["args"]["sim_ns"] for e in evs if e["ph"] == "i"]
        assert sims == sorted(sims)
    # drain empties atomically
    drained = tracer.drain()
    assert len(drained) == len(events)
    assert tracer.events() == []


def test_tracer_recent_readable_during_storm():
    """The flight-recorder dump path (supervision reads ``recent`` from
    another thread mid-run) never sees a mid-mutation deque."""
    tracer = Tracer(enabled=True, ring=64)
    stop = threading.Event()
    reads = []

    def reader():
        while not stop.is_set():
            reads.append(len(tracer.recent(16)))

    rt = threading.Thread(target=reader, daemon=True)
    rt.start()

    def body(tid):
        for i in range(N_OPS // 4):
            tracer.instant(f"ev.{tid}", "stress", sim_ns=i)

    try:
        _storm(N_THREADS, body)
    finally:
        stop.set()
        rt.join(timeout=60)
    assert not rt.is_alive()
    assert reads and all(n <= 16 for n in reads)


# ---------------------------------------------------------------------------
# SimLogger


_LINE_RE = re.compile(
    r"^\d+\.\d{6} \[[\w>-]+\] (\d{2}:\d{2}:\d{2}\.\d{9}|n/a) "
    r"\[\w+\] \[stress\] t\d+ op\d+$")


def _logger_storm() -> str:
    """One deterministic concurrent logging storm; returns the flushed
    output with the (nondeterministic) wall-time column stripped."""
    stream = io.StringIO()
    log = SimLogger(stream=stream, level="message", buffered=True)

    def body(tid):
        for i in range(N_OPS // 4):
            # unique, deterministic (sim_time, thread) key per record ->
            # the flush sort fully determines the output order
            log.message("stress", f"t{tid} op{i}",
                        sim_time=i * 1_000_000, thread=f"w{tid:02d}")

    _storm(N_THREADS, body)
    log.flush()
    return re.sub(r"^\d+\.\d{6} ", "", stream.getvalue(),
                  flags=re.MULTILINE)


def test_logger_concurrent_output_byte_stable_and_untorn():
    out1 = _logger_storm()
    lines = out1.splitlines()
    assert len(lines) == N_THREADS * (N_OPS // 4)
    for ln in lines:
        assert _LINE_RE.match("0.000000 " + ln), f"torn line: {ln!r}"
    # two independent storms produce byte-identical wall-stripped output:
    # the (sim_time, thread) sort erases scheduling nondeterminism
    assert out1 == _logger_storm()


def test_logger_flush_during_storm_loses_nothing():
    stream = io.StringIO()
    log = SimLogger(stream=stream, level="message", buffered=True)
    stop = threading.Event()

    def flusher():
        while not stop.is_set():
            log.flush()

    ft = threading.Thread(target=flusher, daemon=True)
    ft.start()

    def body(tid):
        for i in range(N_OPS // 4):
            log.message("stress", f"t{tid} op{i}",
                        sim_time=i, thread=f"w{tid:02d}")

    try:
        _storm(N_THREADS, body)
    finally:
        stop.set()
        ft.join(timeout=60)
    assert not ft.is_alive()
    log.flush()
    lines = stream.getvalue().splitlines()
    assert len(lines) == N_THREADS * (N_OPS // 4)   # no record lost/torn
