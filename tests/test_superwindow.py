"""Superwindow scheduling gates (ISSUE 7): one device launch covers K
consecutive lookahead rounds whenever no host-side event falls inside
them, digest-identical to per-round dispatch.

1. Digest parity pinned at every cut: K=1 vs K=8 (the acceptance gate),
   device vs numpy twin, pipelined vs --device-plane-sync oracle, and
   threaded vs serial — all at K=8, all bit-identical.
2. Edge cases: an injection landing exactly on a superwindow boundary
   (kernel-level AND a staggered-wave integration run), K clamped when a
   host event falls mid-window (negotiate unit gates), and checkpoint/
   --resume round-stamp alignment when rounds advance K at a time.
3. The halt-at-completion rule: a K-round launch stops at the end of the
   first sub-window in which any chain completed, so completion wakes
   clamp to the launching round's barrier exactly as K=1 would.
4. Satellites: _run_threaded folds the native C plane's counters through
   the same helper _run_serial uses (regression), Tracker.heartbeat skips
   the format/values work when both the log line and the registry are
   off, and NativePlane.bulk_sync's one-call snapshot matches per-host C
   reads row for row.
"""

import glob
import textwrap
from contextlib import contextmanager

import numpy as np
import pytest

from shadow_tpu.core import configuration
from shadow_tpu.core.checkpoint import load_snapshot, state_digest
from shadow_tpu.core.controller import Controller
from shadow_tpu.core.options import Options
from shadow_tpu.ops.torcells_device import (CELL_WIRE_BYTES,
                                            torcells_step_span_numpy,
                                            torcells_step_window_numpy)
from shadow_tpu.tools import workloads

# few circuits + long transfers => the bulk phase is a host-quiet stretch
# deep enough for multi-round merges (the tor10k-class regime)
STAR_KW = dict(n_clients=8, stoptime=120, bulk_bytes=256 * 1024 * 1024,
               device_data=True)


# deterministic repeat runs shared via a module cache (the
# test_meshplane pattern, holding the tier-1 wall): the DEFAULT star
# run at a given (K, policy, workers, mode, sync, stop) is identical
# every time — several parity tests use the same K=8 baseline, which
# used to re-execute per test.  Runs with custom xml or extra options
# (checkpoint dirs etc.) are never cached.
_RUN_CACHE: dict = {}


def _run(superwindow_rounds, policy="global", workers=0, mode="device",
         sync=False, stop=120, xml=None, **opt_kw):
    key = (superwindow_rounds, policy, workers, mode, sync, stop)
    cacheable = xml is None and not opt_kw
    if cacheable and key in _RUN_CACHE:
        return _RUN_CACHE[key]
    cfg = configuration.parse_xml(xml or workloads.star_bulk(**STAR_KW))
    cfg.stop_time_sec = stop
    ctrl = Controller(Options(scheduler_policy=policy, workers=workers,
                              seed=3, stop_time_sec=stop,
                              log_level="warning", device_plane=mode,
                              device_plane_sync=sync,
                              superwindow_rounds=superwindow_rounds,
                              **opt_kw), cfg)
    assert ctrl.run() == 0
    if cacheable:
        _RUN_CACHE[key] = ctrl
    return ctrl


# -- digest parity at every cut -------------------------------------------

def test_digest_parity_k1_vs_k8():
    """The acceptance gate: K=8 merges multiple rounds per launch
    (rounds_per_launch well past 1, dispatch count cut) and ends in the
    bit-identical state K=1 reaches one round at a time."""
    k1 = _run(1)
    k8 = _run(8)
    s1, s8 = k1.engine.device_plane.stats(), k8.engine.device_plane.stats()
    assert s8["superwindows"] > 0, "superwindows never engaged"
    assert s8["rounds_per_launch"] >= 2.0, s8
    assert s8["dispatches"] < s1["dispatches"]
    assert s1["rounds_per_launch"] == 1.0
    assert s1["completed"] == s8["completed"] == 8
    # the round counter counts VIRTUAL rounds: merged launches advance it
    # by the rounds they covered, so both runs agree
    assert k1.engine.rounds_executed == k8.engine.rounds_executed
    assert state_digest(k1.engine) == state_digest(k8.engine)


def test_digest_parity_with_host_chatter():
    """tor-shaped control chatter (circuit TCP, timers) lands host events
    in most windows: negotiation must clamp around every one of them and
    still produce the K=1 digest."""
    xml = workloads.tor_network(8, n_clients=5, n_servers=2, stoptime=60,
                                stream_spec="512:2020000", device_data=True)
    k1 = _run(1, xml=xml, stop=60)
    k8 = _run(8, xml=xml, stop=60)
    assert state_digest(k1.engine) == state_digest(k8.engine)


def test_device_vs_numpy_twin_at_k8():
    dev = _run(8, mode="device")
    twin = _run(8, mode="numpy")
    assert dev.engine.device_plane.stats()["superwindows"] > 0
    assert state_digest(dev.engine) == state_digest(twin.engine)


def test_pipelined_vs_sync_oracle_at_k8():
    """--device-plane-sync (block on the dispatch at launch) generalizes
    from K=1: the serial oracle and the pipelined default agree at K=8."""
    piped = _run(8, sync=False)
    serial = _run(8, sync=True)
    assert piped.engine.device_plane.stats()["superwindows"] > 0
    assert state_digest(piped.engine) == state_digest(serial.engine)


def test_threaded_vs_serial_at_k8():
    serial = _run(8, policy="global", workers=0)
    threaded = _run(8, policy="steal", workers=2)
    assert threaded.engine.device_plane.stats()["superwindows"] > 0
    assert state_digest(serial.engine) == state_digest(threaded.engine)


# -- negotiation clamps (K drops to 1 around host events) ------------------

def _negotiation_plane():
    """A set-up (not run) star engine whose plane is forced busy, so
    negotiate_superwindow's replay can be probed with synthetic host/cap
    times."""
    cfg = configuration.parse_xml(workloads.star_bulk(**STAR_KW))
    cfg.stop_time_sec = 120
    ctrl = Controller(Options(scheduler_policy="global", workers=0, seed=3,
                              stop_time_sec=120, log_level="warning",
                              superwindow_rounds=8), cfg)
    ctrl.setup()
    eng = ctrl.engine
    from shadow_tpu.parallel.device_plane import build_plane_from_engine
    eng.device_plane = build_plane_from_engine(eng, mode="device")
    plane = eng.device_plane
    plane._init_state()
    plane._cells_dispatched = 1000          # busy: undelivered cells
    plane._cells_delivered_seen = 0
    return eng, plane


def test_negotiation_full_depth_when_quiet():
    from shadow_tpu.parallel.device_plane import TICK_NS

    eng, plane = _negotiation_plane()
    grid = TICK_NS * plane.granule
    q = plane.min_dispatch_steps
    la = eng.lookahead_ns
    nxt = q * grid
    far = 1 << 60
    end = eng.end_time
    merged = plane.negotiate_superwindow(nxt, la, far, end, None, 8)
    assert merged is not None
    plan = plane._pending_plan
    assert len(plan.bounds) == 8
    assert plan.targets == sorted(plan.targets)
    assert merged == plan.bounds[-1][1]
    # every merged round ends before the host event, every target is a
    # dispatch-cadence point the K=1 recurrence would have picked
    assert all(we <= far for _, we in plan.bounds)
    assert all(t * grid <= merged for t in plan.targets)


def test_negotiation_k1_when_host_event_in_first_window():
    """A plugin timer (or any host event) inside the next lookahead round:
    no merge — the round runs K=1."""
    from shadow_tpu.parallel.device_plane import TICK_NS

    eng, plane = _negotiation_plane()
    grid = TICK_NS * plane.granule
    nxt = plane.min_dispatch_steps * grid
    la = eng.lookahead_ns
    assert plane.negotiate_superwindow(nxt, la, nxt + la // 2, eng.end_time,
                                       None, 8) is None
    assert plane._pending_plan is None


def test_negotiation_clamps_at_mid_span_host_event():
    """A host event inside round i clamps the merge to the rounds before
    it (K shrinks, never skips the event's round)."""
    from shadow_tpu.parallel.device_plane import TICK_NS

    eng, plane = _negotiation_plane()
    grid = TICK_NS * plane.granule
    q = plane.min_dispatch_steps
    la = eng.lookahead_ns
    nxt = q * grid
    full = plane.negotiate_superwindow(nxt, la, 1 << 60, eng.end_time,
                                       None, 8)
    plan_full = plane._pending_plan
    plane._pending_plan = None
    # place the host event inside the 4th merged round's window
    ws3, we3 = plan_full.bounds[3]
    merged = plane.negotiate_superwindow(nxt, la, ws3 + la // 2,
                                         eng.end_time, None, 8)
    assert merged is not None and merged < full
    assert len(plane._pending_plan.bounds) == 3
    assert plane._pending_plan.bounds[-1][1] <= ws3 + la // 2


def test_negotiation_respects_checkpoint_cap():
    """cap_time (a checkpoint/resume boundary) stops the merge BEFORE the
    round containing it, so the snapshot digest lands on an exact visited
    round boundary."""
    from shadow_tpu.parallel.device_plane import TICK_NS

    eng, plane = _negotiation_plane()
    grid = TICK_NS * plane.granule
    q = plane.min_dispatch_steps
    la = eng.lookahead_ns
    nxt = q * grid
    full = plane.negotiate_superwindow(nxt, la, 1 << 60, eng.end_time,
                                       None, 8)
    plan_full = plane._pending_plan
    plane._pending_plan = None
    cap = plan_full.bounds[2][1]            # boundary after round 2
    merged = plane.negotiate_superwindow(nxt, la, 1 << 60, eng.end_time,
                                         cap, 8)
    assert merged is not None and merged <= cap < full
    for ws, we in plane._pending_plan.bounds:
        assert we <= cap


# -- kernel-level span semantics ------------------------------------------

def _chain_fixture():
    """One 2-hop chain (relay node 0 -> exit node 1), numpy arrays in the
    step-window layout."""
    cell = CELL_WIRE_BYTES
    return dict(
        queued=np.array([60, 0], dtype=np.int64),
        ring=np.zeros((6, 2), dtype=np.int64),
        tokens=np.array([4 * cell, 3 * cell], dtype=np.int64),
        delivered=np.zeros(2, dtype=np.int64),
        target=np.array([0, 40], dtype=np.int64),
        done_tick=np.full(2, -1, dtype=np.int64),
        node_sent=np.zeros(2, dtype=np.int64),
        flow_node=np.array([0, 1], dtype=np.int64),
        flow_lat=np.array([2, 0], dtype=np.int64),
        flow_succ=np.array([1, -1], dtype=np.int64),
        seg_start=np.array([0, 1], dtype=np.int64),
        refill=np.array([4 * cell, 3 * cell], dtype=np.int64),
        capacity=np.array([8 * cell, 6 * cell], dtype=np.int64),
    )


def _run_span(fx, t0, targets, inject=(0, 0), idle=0):
    f = fx
    return torcells_step_span_numpy(
        np.int64(t0), f["queued"].copy(), f["ring"].copy(),
        f["tokens"].copy(), f["delivered"].copy(), f["target"].copy(),
        f["done_tick"].copy(), f["node_sent"].copy(),
        np.array(inject, dtype=np.int64), np.zeros(2, dtype=np.int64),
        np.array(targets, dtype=np.int64), np.int64(idle),
        f["flow_node"], f["flow_lat"], f["flow_succ"], f["seg_start"],
        f["refill"], f["capacity"], 6)


def _run_sequential(fx, t0, targets, inject=(0, 0)):
    """The K=1 oracle: one single-target window per boundary, halting
    after the first window in which a chain newly completed (exactly the
    per-round engine behavior a completion wake imposes)."""
    f = fx
    state = (np.int64(t0), f["queued"].copy(), f["ring"].copy(),
             f["tokens"].copy(), f["delivered"].copy(), f["target"].copy(),
             f["done_tick"].copy(), f["node_sent"].copy())
    inj = np.array(inject, dtype=np.int64)
    forwards = 0
    for tgt in targets:
        done_before = state[6].copy()
        out = torcells_step_window_numpy(
            *state, inj, np.zeros(2, dtype=np.int64),
            np.int64(int(tgt) - int(state[0])), np.int64(0),
            f["flow_node"], f["flow_lat"], f["flow_succ"], f["seg_start"],
            f["refill"], f["capacity"], 6)
        inj = np.zeros(2, dtype=np.int64)   # injections fold at base only
        state = out[:8]
        forwards += int(out[8])
        if ((done_before < 0) & (state[6] >= 0)).any():
            break                           # K=1: the wake halts the run
    return (*state, np.int64(forwards))


def _assert_states_equal(a, b):
    assert int(a[0]) == int(b[0])           # reached boundary
    for i in range(1, 8):
        np.testing.assert_array_equal(np.asarray(a[i]), np.asarray(b[i]))
    assert int(a[8]) == int(b[8])           # forwards


def test_span_matches_sequential_windows_no_completion():
    fx = _chain_fixture()
    fx["target"] = np.array([0, 10 ** 9], dtype=np.int64)  # never completes
    targets = [4, 9, 13, 20]
    _assert_states_equal(_run_span(fx, 0, targets),
                         _run_sequential(fx, 0, targets))


def test_span_halts_at_completion_boundary():
    """The chain completes mid-plan: the span stops at that sub-window's
    boundary with state equal to the sequential windows run to the same
    point — never past it."""
    fx = _chain_fixture()
    targets = [4, 9, 13, 20, 30]
    span = _run_span(fx, 0, targets)
    seq = _run_sequential(fx, 0, targets)
    _assert_states_equal(span, seq)
    assert int(span[0]) in targets[:-1], \
        f"completion did not halt the span (reached {int(span[0])})"
    assert (np.asarray(span[6]) >= 0).any()


def test_injection_exactly_on_span_boundary():
    """An injection staged to a superwindow boundary folds at the NEXT
    dispatch's base step: span [0..a] then span [a..] with the injection
    equals the sequential windows with the same base-step fold."""
    fx = _chain_fixture()
    fx["target"] = np.array([0, 10 ** 9], dtype=np.int64)
    first = _run_span(fx, 0, [4, 9])
    fx2 = dict(fx, queued=np.asarray(first[1]), ring=np.asarray(first[2]),
               tokens=np.asarray(first[3]), delivered=np.asarray(first[4]),
               target=np.asarray(first[5]), done_tick=np.asarray(first[6]),
               node_sent=np.asarray(first[7]))
    span = _run_span(fx2, 9, [13, 20], inject=(25, 0))
    seq = _run_sequential(fx2, 9, [13, 20], inject=(25, 0))
    _assert_states_equal(span, seq)
    # the injected cells actually entered the first sub-window's service
    assert int(np.asarray(span[7]).sum()) > int(np.asarray(first[7]).sum())


def test_staggered_wave_injection_parity():
    """Integration form of the boundary-injection case: a second client
    wave activates (socket write -> plane injection) while the first
    wave's transfers sit in merged superwindows."""
    lines = ['<shadow stoptime="120">',
             '  <plugin id="tgen" path="python:tgen" />',
             '  <host id="server" bandwidthdown="1048576" '
             'bandwidthup="1048576">',
             '    <process plugin="tgen" starttime="1" '
             'arguments="server 80" />',
             '  </host>']
    for i in range(6):
        start = 2 if i < 3 else 40          # second wave mid-quiet-stretch
        lines.append(
            f'  <host id="client{i}" bandwidthdown="102400" '
            f'bandwidthup="51200">\n'
            f'    <process plugin="tgen" starttime="{start}" '
            f'arguments="client server 80 256:67108864 device" />\n'
            '  </host>')
    lines.append('</shadow>')
    xml = "\n".join(lines) + "\n"
    k1 = _run(1, xml=xml)
    k8 = _run(8, xml=xml)
    assert k8.engine.device_plane.stats()["superwindows"] > 0
    assert k8.engine.device_plane.stats()["completed"] == 6
    assert state_digest(k1.engine) == state_digest(k8.engine)


# -- checkpoint / resume alignment ----------------------------------------

def test_checkpoint_round_stamps_align_k1_vs_k8(tmp_path):
    """--checkpoint-every N with rounds advancing K at a time: the merge
    budget stops short of every cadence point, so K=8 writes the same
    round-stamped snapshot files with the same digests as K=1."""
    digests = {}
    for k in (1, 8):
        ckdir = str(tmp_path / f"ck{k}")
        _run(k, stop=72, checkpoint_every_rounds=40, checkpoint_dir=ckdir)
        snaps = sorted(glob.glob(ckdir + "/checkpoint_r*.ckpt"))
        assert snaps, f"K={k} wrote no snapshots"
        digests[k] = [(p.rsplit("/", 1)[1], load_snapshot(p)["digest"],
                       load_snapshot(p)["rounds"]) for p in snaps]
    assert digests[1] == digests[8]


def test_resume_from_superwindow_run(tmp_path):
    """A K=8 run resumed from one of its own mid-run snapshots replays to
    the digest an uninterrupted K=8 run reaches."""
    ckdir = str(tmp_path / "ck")
    full = _run(8, stop=72, checkpoint_every_rounds=40,
                checkpoint_dir=ckdir)
    want = state_digest(full.engine)
    snaps = sorted(glob.glob(ckdir + "/checkpoint_r*.ckpt"))
    assert len(snaps) >= 1
    resumed = _run(8, stop=72, resume_path=snaps[-1])
    assert state_digest(resumed.engine) == want


# -- satellite: threaded native-counter fold ------------------------------

class _FakeNativePlane:
    """Stand-in C plane (the real one is serial-only): fixed counters plus
    the window/teardown surface the engine touches."""

    def __init__(self):
        self.windows = []

    def counters(self):
        return (7, 5, 2, 123)               # sched, execd, drops, last

    def set_window(self, end):
        self.windows.append(end)

    @contextmanager
    def bulk_sync(self):
        yield

    def sync_tracker(self, hid, tracker):
        pass


ECHO_XML = textwrap.dedent("""\
    <shadow stoptime="30">
      <plugin id="echo" path="python:echo" />
      <host id="u1"><process plugin="echo" starttime="1" arguments="udp server 9000" /></host>
      <host id="u2"><process plugin="echo" starttime="2" arguments="udp client u1 9000 5 700" /></host>
    </shadow>
""")


@pytest.mark.parametrize("policy,workers", [("global", 0), ("steal", 2)])
def test_native_fold_in_both_runners(policy, workers):
    """_run_threaded used to skip the native-counter fold entirely
    (engine.py: only _run_serial folded) — both runners now route through
    _fold_native_events: events_executed includes the C plane's executed
    count and the ObjectCounter ledger carries its event lifecycle."""
    cfg = configuration.parse_xml(ECHO_XML)
    cfg.stop_time_sec = 30
    ctrl = Controller(Options(scheduler_policy=policy, workers=workers,
                              seed=3, stop_time_sec=30,
                              log_level="warning", dataplane="python"), cfg)
    ctrl.setup()
    eng = ctrl.engine
    eng.native_plane = _FakeNativePlane()
    assert eng.run() == 0
    scrape = eng.metrics.scrape()
    assert scrape["native.events_executed"] == 5
    # the fold ran: engine totals include the C plane's executed events...
    assert eng.events_executed == scrape["engine.events"]
    assert eng.events_executed >= 5
    # ...and the ledger absorbed its lifecycle (5 of the 7 scheduled
    # executed => 2 still live in C, plus the drop count)
    assert eng.counters._new.get("packet_drop", 0) >= 2


# -- satellite: heartbeat format gated behind the log level ---------------

def test_heartbeat_work_gated_when_silent(monkeypatch):
    """With the heartbeat log level filtered out AND the metrics registry
    disabled, a host heartbeat never computes heartbeat_values nor
    formats the line — 10k silent hosts pay only the counter pulls."""
    from shadow_tpu.host.tracker import Tracker

    calls = {"n": 0}
    orig = Tracker.heartbeat_values

    def counting(self):
        calls["n"] += 1
        return orig(self)

    monkeypatch.setattr(Tracker, "heartbeat_values", counting)
    cfg = configuration.parse_xml(ECHO_XML)
    cfg.stop_time_sec = 30
    ctrl = Controller(Options(scheduler_policy="global", workers=0, seed=3,
                              stop_time_sec=30, log_level="warning"), cfg)
    assert ctrl.run() == 0
    assert calls["n"] == 0, \
        "filtered heartbeats still computed their payload"


def test_heartbeat_values_flow_when_metrics_on(monkeypatch, tmp_path):
    """Same run with --metrics: the registry still records every host's
    closing heartbeat even though the log line stays filtered."""
    from shadow_tpu.host.tracker import Tracker

    calls = {"n": 0}
    orig = Tracker.heartbeat_values

    def counting(self):
        calls["n"] += 1
        return orig(self)

    monkeypatch.setattr(Tracker, "heartbeat_values", counting)
    cfg = configuration.parse_xml(ECHO_XML)
    cfg.stop_time_sec = 30
    mpath = str(tmp_path / "m.jsonl")
    ctrl = Controller(Options(scheduler_policy="global", workers=0, seed=3,
                              stop_time_sec=30, log_level="warning",
                              metrics_path=mpath), cfg)
    assert ctrl.run() == 0
    assert calls["n"] >= 2                   # closing sweep, one per host
    from shadow_tpu.obs.metrics import read_metrics_file
    summary = [r for r in read_metrics_file(mpath) if r.get("summary")][-1]
    assert any(k.startswith("tracker.") for k in summary["metrics"])


# -- satellite: bulk tracker snapshot parity ------------------------------

def test_native_bulk_sync_matches_per_host_reads():
    """NativePlane.tracker_all (one C call) row-for-row equals the
    per-host c.tracker() reads it replaces on the heartbeat/teardown
    sweeps."""
    from shadow_tpu.parallel import native_plane as npl

    if not npl.native_available():
        pytest.skip("native extension unavailable")
    cfg = configuration.parse_xml(ECHO_XML)
    cfg.stop_time_sec = 30
    ctrl = Controller(Options(scheduler_policy="global", workers=0, seed=3,
                              stop_time_sec=30, log_level="warning",
                              dataplane="native"), cfg)
    ctrl.setup()
    eng = ctrl.engine
    assert eng.native_plane is not None, "native plane did not engage"
    assert eng.run() == 0
    plane = eng.native_plane
    rows = np.frombuffer(plane.c.tracker_all(),
                         dtype=np.int64).reshape(-1, 34)
    assert len(rows) == len(eng.hosts)
    for row in rows:
        hid = int(row[0])
        assert tuple(int(x) for x in row[1:]) == tuple(plane.c.tracker(hid))
