"""simjit (shadow_tpu/analysis/simjit.py): the compile-surface
static-analysis pass, ISSUE 20's tentpole.

Fixture pairs (fire + suppress) for every SIM3xx rule over the
package-wide jit-program model (decorated defs, partial(jax.jit, ...),
factories, attr handles, literal-capped variant caches), the checked-in
[tool.simjit.budget] audit in both drift directions, the runtime
cross-check half (crosscheck_budget / load_runtime_budget, wired into
`simfleet smoke`), the cross-tool pragma-ownership semantics (simlint /
simrace ignore SIM3xx pragmas, simjit ignores SIM00x/SIM1xx pragmas —
each tool judges staleness only for rules it runs), the ``--diff BASE``
reporting filter over a still-package-wide analysis, the JSON schema
and CLI — and THE GATE: simjit over all of shadow_tpu/ must report ZERO
unsuppressed findings, so every recompile hazard, hidden sync, int64
promotion, donation misuse and budget drift a future PR introduces
fails with the file:line.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import textwrap

import pytest

from shadow_tpu.analysis.simlint import Config, lint_source
from shadow_tpu.analysis.simrace import race_sources
from shadow_tpu.analysis.simjit import (crosscheck_budget, jit_paths,
                                        jit_sources, load_jit_config,
                                        load_runtime_budget, parse_budget)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _jit(srcs, config: Config = None, budget=None, kernel=None):
    if isinstance(srcs, str):
        srcs = {"shadow_tpu/fake/mod.py": srcs}
    return jit_sources({k: textwrap.dedent(v) for k, v in srcs.items()},
                       config, budget=budget, kernel=kernel)


def _rules_of(findings):
    return sorted({f.rule for f in findings if not f.suppressed})


# ---------------------------------------------------------------------------
# SIM301 — recompile hazard


_SIM301_FIXTURE = """
    from functools import partial
    import jax
    import jax.numpy as jnp

    @partial(jax.jit, static_argnums=(1,))
    def run(x, width):
        return x[:width]

    def drive(batch):
        return run(jnp.asarray(batch), len(batch)){P}
"""


def test_sim301_fires_on_unbucketed_static_width():
    out = _jit(_SIM301_FIXTURE.replace("{P}", ""),
               budget={"shadow_tpu/fake/mod.py": 1})
    assert _rules_of(out) == ["SIM301"]
    assert "one compilation per distinct value" in out[0].message


def test_sim301_suppressible_with_reason():
    out = _jit(_SIM301_FIXTURE.replace(
        "{P}", "  # simjit: disable=SIM301 -- fixture justification"),
        budget={"shadow_tpu/fake/mod.py": 1})
    assert _rules_of(out) == []
    assert [f.rule for f in out if f.suppressed] == ["SIM301"]


def test_sim301_quiet_when_width_is_bucketed():
    # the pad_state contract: a pad/pow2/bucket-named wrapper bounds the
    # class set, so the width is no longer one-compile-per-value
    out = _jit("""
        from functools import partial
        import jax
        import jax.numpy as jnp

        @partial(jax.jit, static_argnums=(1,))
        def run(x, width):
            return x[:width]

        def pad_pow2(n):
            return max(8, 1 << (n - 1).bit_length())

        def drive(batch):
            return run(jnp.asarray(batch), pad_pow2(len(batch)))
    """, budget={"shadow_tpu/fake/mod.py": 1})
    assert out == []


def test_sim301_fires_on_global_mutated_traced_closure():
    out = _jit("""
        import jax

        WIDTH = 8

        def bump():
            global WIDTH
            WIDTH += 1

        def body(x):
            return x * WIDTH

        step = jax.jit(body)
    """, budget={"shadow_tpu/fake/mod.py": 1})
    assert _rules_of(out) == ["SIM301"]
    assert "closes over global `WIDTH`" in out[0].message


def test_sim301_fires_on_loop_varying_closure():
    # the traced def reads `width`, which the enclosing function rebinds
    # per loop iteration AFTER tracing — one iteration's value is baked
    out = _jit("""
        import jax

        def sweep(xs):
            width = 0

            def body(x):
                return x * width

            if len(xs) >= 4:
                pass
            step = jax.jit(body)
            outs = []
            for width in range(4):
                outs.append(step(xs))
            return outs
    """, budget={"shadow_tpu/fake/mod.py": 4})
    assert _rules_of(out) == ["SIM301"]
    assert "rebinds per iteration" in out[0].message


# ---------------------------------------------------------------------------
# SIM302 — implicit host<->device sync in the dispatch window


_SIM302_FIXTURE = """
    import numpy as np
    import jax

    @jax.jit
    def step(x):
        return x + 1

    def drive(x):
        out = step(x)
        return np.asarray(out){P}
"""


def test_sim302_fires_on_asarray_of_live_result():
    out = _jit(_SIM302_FIXTURE.replace("{P}", ""),
               budget={"shadow_tpu/fake/mod.py": 1})
    assert _rules_of(out) == ["SIM302"]
    assert "pulls the buffer" in out[0].message


def test_sim302_suppressible_with_reason():
    out = _jit(_SIM302_FIXTURE.replace(
        "{P}", "  # simjit: disable=SIM302 -- fixture justification"),
        budget={"shadow_tpu/fake/mod.py": 1})
    assert _rules_of(out) == []
    assert [f.rule for f in out if f.suppressed] == ["SIM302"]


def test_sim302_quiet_after_explicit_block_until_ready():
    # an explicit sync point makes every later pull in the function a
    # designed collect, not an implicit one (the phold_device idiom)
    out = _jit("""
        import numpy as np
        import jax

        @jax.jit
        def step(x):
            return x + 1

        def drive(x):
            out = step(x)
            jax.block_until_ready(out)
            return np.asarray(out)
    """, budget={"shadow_tpu/fake/mod.py": 1})
    assert out == []


def test_sim302_quiet_on_metadata_and_none_checks():
    # len()/.shape/.dtype and `is None` read host metadata, not the
    # buffer — no sync
    out = _jit("""
        import jax
        import jax.numpy as jnp

        @jax.jit
        def step(x):
            return x + 1

        def drive(x):
            out = step(x)
            if out is None:
                return 0
            return out.shape[0] + out.ndim
    """, budget={"shadow_tpu/fake/mod.py": 1})
    assert out == []


def test_sim302_fires_on_item_and_device_branch():
    out = _jit("""
        import jax

        @jax.jit
        def step(x):
            return x + 1

        def drive(x):
            out = step(x)
            if out > 0:
                return 1
            return out.item()
    """, budget={"shadow_tpu/fake/mod.py": 1})
    assert _rules_of(out) == ["SIM302"]
    assert len([f for f in out if not f.suppressed]) == 2


# ---------------------------------------------------------------------------
# SIM303 — int64-contract promotion drift (kernel-tagged files only)


_SIM303_FIXTURE = """
    def halve(lat_ns):
        return lat_ns / 2{P}
"""


def test_sim303_fires_on_true_division_of_time_lane():
    out = _jit({"shadow_tpu/fake/kern.py":
                _SIM303_FIXTURE.replace("{P}", "")},
               kernel=["shadow_tpu/fake/*.py"])
    assert _rules_of(out) == ["SIM303"]
    assert "promotes the int64 ns value to float" in out[0].message


def test_sim303_suppressible_with_reason():
    out = _jit({"shadow_tpu/fake/kern.py": _SIM303_FIXTURE.replace(
        "{P}", "  # simjit: disable=SIM303 -- fixture justification")},
        kernel=["shadow_tpu/fake/*.py"])
    assert _rules_of(out) == []
    assert [f.rule for f in out if f.suppressed] == ["SIM303"]


def test_sim303_scoped_to_kernel_tagged_files():
    # identical source outside the [tool.simjit] kernel globs is quiet —
    # host-side float math is not the contract's concern
    out = _jit({"shadow_tpu/fake/kern.py":
                _SIM303_FIXTURE.replace("{P}", "")},
               kernel=["shadow_tpu/ops/*.py"])
    assert out == []


def test_sim303_fires_on_float_literal_and_cast_quiet_on_floordiv():
    out = _jit({"shadow_tpu/fake/kern.py": """
        import jax.numpy as jnp

        def scale(delay_ns, arrive):
            a = delay_ns * 0.5
            b = arrive.astype(jnp.float32)
            c = delay_ns // 2
            return a, b, c
    """}, kernel=["shadow_tpu/fake/*.py"])
    assert _rules_of(out) == ["SIM303"]
    assert len(out) == 2
    assert "weak-type-promotes" in out[0].message
    assert "lose integer exactness" in out[1].message


# ---------------------------------------------------------------------------
# SIM304 — donation misuse


_SIM304_CPU_FIXTURE = """
    from functools import partial
    import jax

    @partial(jax.jit, donate_argnums=(0,), backend="cpu")
    def f(x):{P}
        return x + 1
"""


def test_sim304_fires_on_cpu_backend_donation():
    out = _jit(_SIM304_CPU_FIXTURE.replace("{P}", ""),
               budget={"shadow_tpu/fake/mod.py": 1})
    assert _rules_of(out) == ["SIM304"]
    assert "donates buffers on the CPU backend" in out[0].message


def test_sim304_suppressible_with_reason():
    out = _jit(_SIM304_CPU_FIXTURE.replace(
        "{P}", "  # simjit: disable=SIM304 -- fixture justification"),
        budget={"shadow_tpu/fake/mod.py": 1})
    assert _rules_of(out) == []
    assert [f.rule for f in out if f.suppressed] == ["SIM304"]


def test_sim304_fires_on_shared_donated_program():
    # two distinct enclosing functions calling ONE donated program alias
    # each other's invalidated buffers — one finding per call site
    out = _jit("""
        from functools import partial
        import jax

        @partial(jax.jit, donate_argnums=(0,))
        def f(x):
            return x + 1

        def one(x):
            return f(x)

        def two(x):
            return f(x)
    """, budget={"shadow_tpu/fake/mod.py": 1})
    assert _rules_of(out) == ["SIM304"]
    assert len(out) == 2
    assert "multiple owners" in out[0].message


def test_sim304_quiet_on_single_owner_donation():
    out = _jit("""
        from functools import partial
        import jax

        @partial(jax.jit, donate_argnums=(0,))
        def f(x):
            return x + 1

        def one(x):
            return f(f(x))
    """, budget={"shadow_tpu/fake/mod.py": 1})
    assert out == []


# ---------------------------------------------------------------------------
# SIM305 — compile-budget audit


_SIM305_MODULE = """
    import jax

    @jax.jit
    def f(x):
        return x + 1
"""


def test_sim305_fires_when_module_has_no_budget_entry():
    out = _jit(_SIM305_MODULE, budget={})
    assert _rules_of(out) == ["SIM305"]
    assert "has no [tool.simjit.budget] entry" in out[0].message


def test_sim305_quiet_when_budget_matches():
    assert _jit(_SIM305_MODULE, budget={"shadow_tpu/fake/mod.py": 1}) == []


def test_sim305_fires_on_drift_both_directions():
    over = _jit(_SIM305_MODULE, budget={"shadow_tpu/fake/mod.py": 3})
    assert _rules_of(over) == ["SIM305"]
    assert "shrank below its budget" in over[0].message
    grew = _jit("""
        import jax

        @jax.jit
        def f(x):
            return x + 1

        @jax.jit
        def g(x):
            return x - 1
    """, budget={"shadow_tpu/fake/mod.py": 1})
    assert _rules_of(grew) == ["SIM305"]
    assert "grew past its budget" in grew[0].message


def test_sim305_stale_entry_is_anchored_at_pyproject():
    out = _jit(_SIM305_MODULE,
               budget={"shadow_tpu/fake/mod.py": 1,
                       "shadow_tpu/fake/gone.py": 2})
    assert _rules_of(out) == ["SIM305"]
    (f,) = out
    assert f.path == "pyproject.toml"
    assert "is stale" in f.message


def test_sim305_unbounded_in_function_creation_always_fires():
    # no literal cache bound around a function-scope jit creation: every
    # call mints a fresh compiled program — a finding regardless of any
    # budget entry
    out = _jit("""
        import jax

        def make(scale):
            step = jax.jit(lambda x: x * scale)
            return step
    """, budget={"shadow_tpu/fake/mod.py": 99})
    assert "SIM305" in _rules_of(out)
    assert any("no literal cache bound" in f.message for f in out)


_SIM305_CAPPED_CACHE = """
    import jax

    class Plane:
        def __init__(self):
            self._variants = {}

        def pick(self, bits, fn):
            if bits not in self._variants:
                if len(self._variants) >= 4:
                    raise RuntimeError("cap")
                step = jax.jit(fn)
                self._variants[bits] = step
            return self._variants[bits]
"""


def test_sim305_literal_cap_must_match_runtime_budget():
    # the static half of the fleet-smoke cross-check: the literal cache
    # cap in device_plane must equal `device_plane.sharded_variants`
    rel = "shadow_tpu/parallel/device_plane.py"
    bad = _jit({rel: _SIM305_CAPPED_CACHE},
               budget={rel: 4, "device_plane.sharded_variants": 8})
    assert _rules_of(bad) == ["SIM305"]
    assert "variant-cache literal cap 4" in bad[0].message
    ok = _jit({rel: _SIM305_CAPPED_CACHE},
              budget={rel: 4, "device_plane.sharded_variants": 4})
    assert ok == []


# ---------------------------------------------------------------------------
# the budget table: parsing + the runtime cross-check half


def test_parse_budget_reads_quoted_keys_and_ignores_other_sections():
    budget = parse_budget(textwrap.dedent("""
        [tool.simjit]
        kernel = ["shadow_tpu/ops/*.py"]

        [tool.simjit.budget]
        # a comment line
        "shadow_tpu/ops/mod.py" = 3   # trailing comment
        "fleet.compiles" = 64

        [tool.other]
        "shadow_tpu/ops/mod.py" = 99
    """))
    assert budget == {"shadow_tpu/ops/mod.py": 3, "fleet.compiles": 64}


def test_load_runtime_budget_returns_only_dotted_entries():
    runtime = load_runtime_budget(REPO)
    assert runtime.get("fleet.compiles", 0) > 0
    assert runtime.get("device_plane.sharded_variants", 0) > 0
    assert not any(k.endswith(".py") for k in runtime)


def test_crosscheck_budget_consistent_is_empty():
    assert crosscheck_budget({"fleet.compiles": 3},
                             {"fleet.compiles": 64,
                              "shadow_tpu/ops/mod.py": 1}) == []


def test_crosscheck_budget_fails_on_growth_past_budget():
    (p,) = crosscheck_budget({"fleet.compiles": 65},
                             {"fleet.compiles": 64})
    assert "exceeds its" in p


def test_crosscheck_budget_fails_on_unmeasured_budget_entry():
    (p,) = crosscheck_budget({}, {"fleet.compiles": 64})
    assert "was not measured" in p


def test_crosscheck_budget_zero_semantics():
    # a measured zero is fine for mode-gated caches, but fails for keys
    # the calling smoke is guaranteed to exercise
    assert crosscheck_budget({"device_plane.sharded_variants": 0},
                             {"device_plane.sharded_variants": 4}) == []
    (p,) = crosscheck_budget({"fleet.compiles": 0}, {"fleet.compiles": 64},
                             require_nonzero=("fleet.compiles",))
    assert "never compiled" in p


def test_crosscheck_budget_fails_on_unbudgeted_runtime_key():
    (p,) = crosscheck_budget({"fleet.compiles": 1, "new.cache": 2},
                             {"fleet.compiles": 64})
    assert "no [tool.simjit.budget] entry" in p


# ---------------------------------------------------------------------------
# cross-tool pragma ownership (one vocabulary, per-tool staleness)


def test_simjit_pragma_invisible_to_simlint_and_simrace():
    # a used SIM302 pragma: simjit consumes it; simlint/simrace neither
    # honor it nor flag it stale (they don't run SIM3xx)
    src = _SIM302_FIXTURE.replace(
        "{P}", "  # simjit: disable=SIM302 -- fixture justification")
    out = _jit(src, budget={"shadow_tpu/fake/mod.py": 1})
    assert _rules_of(out) == []
    assert [f.rule for f in out if f.suppressed] == ["SIM302"]
    assert lint_source(textwrap.dedent(src)) == []
    assert race_sources(
        {"shadow_tpu/fake/mod.py": textwrap.dedent(src)}) == []


def test_simlint_and_simrace_pragmas_invisible_to_simjit():
    # reverse direction: SIM00x/SIM1xx pragmas on their own findings are
    # owned by their tools — simjit reports neither stale nor suppressed
    src = """
        import time as _wt
        import threading

        def stall():
            _wt.sleep(1.0)  # simlint: disable=SIM005 -- fault harness

        class S:
            def __init__(self):
                self.alock = threading.Lock()
                self.block = threading.Lock()

            def one(self, conn):
                with self.alock:
                    return conn.recv()  # simlint: disable=SIM103 -- t
    """
    assert _jit(src) == []


def test_stale_simjit_pragma_is_sim000():
    out = _jit("""
        x = 1  # simjit: disable=SIM301 -- nothing here anymore
    """)
    assert _rules_of(out) == ["SIM000"]
    assert "matched no finding" in out[0].message
    # ...and that staleness is invisible to simlint (SIM3xx not its rule)
    assert lint_source(textwrap.dedent(
        "x = 1  # simjit: disable=SIM301 -- nothing here\n")) == []


def test_unknown_rule_pragma_flagged():
    out = _jit("""
        x = 1  # simjit: disable=SIM999 -- no such rule
    """)
    assert _rules_of(out) == ["SIM000"]


# ---------------------------------------------------------------------------
# allowlists


def test_allowlist_exempts_by_rule_and_path():
    cfg = Config(allow={"SIM302": ["shadow_tpu/prof/*"]})
    src = _SIM302_FIXTURE.replace("{P}", "")
    assert _jit({"shadow_tpu/prof/probe.py": src}, cfg,
                budget={"shadow_tpu/prof/probe.py": 1}) == []
    assert _rules_of(_jit({"shadow_tpu/core/hot.py": src}, cfg,
                          budget={"shadow_tpu/core/hot.py": 1})) \
        == ["SIM302"]


def test_repo_config_unions_simjit_allow_section():
    cfg, budget, kernel = load_jit_config(
        os.path.join(REPO, "pyproject.toml"))
    assert "shadow_tpu/prof/*" in cfg.allow.get("SIM302", [])
    assert budget.get("fleet.compiles", 0) > 0
    assert any(g.endswith("ops/*.py") for g in kernel)


def test_unparsable_file_is_a_finding_not_a_crash():
    out = jit_sources({"shadow_tpu/bad.py": "def f(:\n"})
    assert [f.rule for f in out] == ["SIM000"]
    assert "parse" in out[0].message


# ---------------------------------------------------------------------------
# --diff: reporting filters to changed files, analysis stays package-wide


def _git(cwd, *args):
    return subprocess.run(
        ["git", "-c", "user.email=t@t", "-c", "user.name=t"] + list(args),
        cwd=cwd, capture_output=True, text=True, timeout=60)


def test_diff_mode_reports_only_changed_files_but_analyzes_package(
        tmp_path):
    # the SIM304 pair spans two modules: a.py owns the donated program
    # and one call site, b.py adds the second owner.  With only b.py
    # changed, the cross-module finding still COMPLETES (analysis is
    # package-wide) but only b.py's half is reported.
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "a.py").write_text(textwrap.dedent("""
        from functools import partial
        import jax

        @partial(jax.jit, donate_argnums=(0,))
        def f(x):
            return x + 1

        def one(x):
            return f(x)
    """))
    (pkg / "b.py").write_text("y = 1\n")
    (tmp_path / "pyproject.toml").write_text(textwrap.dedent("""
        [tool.simjit.budget]
        "pkg/a.py" = 1
    """))
    assert _git(tmp_path, "init", "-q").returncode == 0
    assert _git(tmp_path, "add", "-A").returncode == 0
    assert _git(tmp_path, "commit", "-qm", "base").returncode == 0
    (pkg / "b.py").write_text(textwrap.dedent("""
        from a import f

        def two(x):
            return f(x)
    """))
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    full = subprocess.run(
        [sys.executable, "-m", "shadow_tpu.analysis.simjit",
         str(pkg), "--json", "--config", str(tmp_path / "pyproject.toml")],
        capture_output=True, text=True, cwd=tmp_path, env=env, timeout=120)
    doc = json.loads(full.stdout)
    assert doc["summary"]["by_rule"] == {"SIM304": 2}
    assert sorted(f["path"] for f in doc["findings"]) \
        == ["pkg/a.py", "pkg/b.py"]
    diffed = subprocess.run(
        [sys.executable, "-m", "shadow_tpu.analysis.simjit",
         str(pkg), "--json", "--diff", "HEAD",
         "--config", str(tmp_path / "pyproject.toml")],
        capture_output=True, text=True, cwd=tmp_path, env=env, timeout=120)
    doc = json.loads(diffed.stdout)
    assert doc["summary"]["by_rule"] == {"SIM304": 1}
    (f,) = doc["findings"]
    assert f["path"] == "pkg/b.py"


def test_diff_mode_bad_ref_is_usage_error():
    run = subprocess.run(
        [sys.executable, "-m", "shadow_tpu.analysis.simjit",
         "shadow_tpu", "--diff", "no-such-ref-xyz"],
        capture_output=True, text=True, cwd=REPO, timeout=120)
    assert run.returncode == 2
    assert "--diff" in run.stderr


def test_make_lint_target_runs_simjit():
    with open(os.path.join(REPO, "Makefile"), encoding="utf-8") as f:
        text = f.read()
    assert "simjit" in text and "lint:" in text


# ---------------------------------------------------------------------------
# JSON schema + CLI round trip


def test_json_schema_and_cli_roundtrip(tmp_path):
    (tmp_path / "pyproject.toml").write_text(textwrap.dedent("""
        [tool.simjit.budget]
        "mod.py" = 1
    """))
    mod = tmp_path / "mod.py"
    mod.write_text(textwrap.dedent("""
        import numpy as np
        import jax

        @jax.jit
        def step(x):
            return x + 1

        def ok(x):
            out = step(x)
            return np.asarray(out)  # simjit: disable=SIM302 -- t

        def bad(x):
            out = step(x)
            return out.item()
    """))
    run = subprocess.run(
        [sys.executable, "-m", "shadow_tpu.analysis.simjit",
         str(mod), "--json", "--config", str(tmp_path / "pyproject.toml")],
        capture_output=True, text=True, cwd=REPO, timeout=120)
    assert run.returncode == 1, run.stderr
    doc = json.loads(run.stdout)
    assert doc["version"] == 1 and doc["tool"] == "simjit"
    assert doc["files"] == 1
    assert doc["summary"]["findings"] == 1
    assert doc["summary"]["suppressed"] == 1
    assert doc["summary"]["by_rule"] == {"SIM302": 1}
    (f,) = doc["findings"]
    assert set(f) == {"rule", "severity", "path", "line", "col", "message"}
    assert f["rule"] == "SIM302" and f["severity"] == "error"


def test_cli_exit_codes(tmp_path):
    clean = tmp_path / "clean.py"
    clean.write_text("x = 1\n")
    ok = subprocess.run(
        [sys.executable, "-m", "shadow_tpu.analysis.simjit", str(clean)],
        capture_output=True, text=True, cwd=REPO, timeout=120)
    assert ok.returncode == 0
    missing = subprocess.run(
        [sys.executable, "-m", "shadow_tpu.analysis.simjit",
         str(tmp_path / "nope.py")],
        capture_output=True, text=True, cwd=REPO, timeout=120)
    assert missing.returncode == 2
    rules = subprocess.run(
        [sys.executable, "-m", "shadow_tpu.analysis.simjit",
         "--list-rules"],
        capture_output=True, text=True, cwd=REPO, timeout=120)
    assert rules.returncode == 0
    for rid in ("SIM301", "SIM302", "SIM303", "SIM304", "SIM305"):
        assert rid in rules.stdout


# ---------------------------------------------------------------------------
# THE GATE: zero unsuppressed findings over the whole package


def test_gate_zero_findings_over_shadow_tpu():
    """Every compile-surface violation in shadow_tpu/ is fixed, budgeted
    or justified.

    A future PR that adds an unbucketed width at a jit boundary, pulls a
    live jit result mid-window, float-promotes a ns lane in a kernel
    file, shares a donated program, or mints a jit identity without
    bumping [tool.simjit.budget] fails HERE with the file:line — the
    only ways out are to fix it, budget it consciously, or justify it
    with a reasoned `# simjit: disable=<RULE> -- <why>` pragma."""
    cfg, budget, kernel = load_jit_config(
        os.path.join(REPO, "pyproject.toml"))
    result = jit_paths([os.path.join(REPO, "shadow_tpu")], cfg,
                       budget=budget, kernel=kernel)
    assert result.files > 50, "package discovery looks broken"
    pretty = "\n".join(f.render() for f in result.unsuppressed)
    assert not result.unsuppressed, (
        f"simjit found unsuppressed violations:\n{pretty}\n"
        "fix them, budget them, or justify with "
        "`# simjit: disable=<RULE> -- <why>`")
    for f in result.suppressed:
        assert f.reason, f"reasonless suppression survived: {f.render()}"


def test_gate_cli_matches_api():
    run = subprocess.run(
        [sys.executable, "-m", "shadow_tpu.analysis.simjit",
         "shadow_tpu", "--json"],
        capture_output=True, text=True, cwd=REPO, timeout=300)
    assert run.returncode == 0, run.stdout + run.stderr
    doc = json.loads(run.stdout)
    assert doc["findings"] == []
