"""Scale tier gates (shadow_tpu/scale/): table-vs-object digest parity,
lazy materialization, processless device flows, generated scenarios,
the vectorized shuffle, and the memory metrics surface.

The central contract: a simulation booted through the HostTable
(--host-table=on) is byte-identical in its state digest to the same
simulation booted eagerly — across scheduler policies, across the
device/numpy plane twins, and across --processes sharding."""

import io
import json

import numpy as np
import pytest

from shadow_tpu.core import configuration
from shadow_tpu.core.checkpoint import state_digest
from shadow_tpu.core.controller import Controller
from shadow_tpu.core.logger import SimLogger, set_logger
from shadow_tpu.core.options import Options
from shadow_tpu.scale import genscen
from shadow_tpu.tools.workloads import tor_network


def _run(xml, stop, table, policy="global", workers=0, seed=7, **kw):
    set_logger(SimLogger(stream=io.StringIO(), level="warning"))
    cfg = configuration.parse_xml(xml) if isinstance(xml, str) else xml
    cfg.stop_time_sec = stop
    ctrl = Controller(Options(scheduler_policy=policy, workers=workers,
                              stop_time_sec=stop, seed=seed,
                              host_table=table, dataplane="python", **kw),
                      cfg)
    rc = ctrl.run()
    assert rc == 0
    return ctrl


MIXED_XML = """<shadow stoptime="60">
  <plugin id="tgen" path="python:tgen" />
  <host id="server" bandwidthdown="102400" bandwidthup="102400">
    <process plugin="tgen" starttime="1" arguments="server 80" />
  </host>
  <host id="client" quantity="3" bandwidthdown="10240" bandwidthup="5120">
    <process plugin="tgen" starttime="5" arguments="client server 80 1024:204800" />
  </host>
  <host id="quiet" quantity="5" bandwidthdown="10240" bandwidthup="5120">
  </host>
</shadow>"""


# ---------------------------------------------------------------------------
# table-vs-object digest parity
# ---------------------------------------------------------------------------

def test_table_parity_mixed_small():
    """Quiet rows + lazily-promoted clients: digest identical to eager
    boot, and the quiet hosts never materialize."""
    off = _run(MIXED_XML, 60, "off")
    on = _run(MIXED_XML, 60, "on")
    assert state_digest(on.engine) == state_digest(off.engine)
    assert on.engine.events_executed == off.engine.events_executed
    assert on.engine.rounds_executed == off.engine.rounds_executed
    table = on.engine.host_table
    assert table is not None
    # server + 3 clients materialized (their processes ran); 5 quiet rows
    # stayed struct-of-arrays for the whole run
    assert table.materialized_count == 4
    assert table.unmaterialized_count() == 5


def test_lazy_promotion_first_plugin_event():
    """A host promoted mid-run (first plugin event at t=5) produces
    byte-identical digests: the boot replay reproduces the eager event
    times and per-host sequence draws exactly."""
    off = _run(MIXED_XML, 60, "off")
    on = _run(MIXED_XML, 60, "on")
    table = on.engine.host_table
    # the client rows were NOT materialized at setup: their promotion
    # happened at their start-time window (mid-run), not at boot
    client = on.engine.hosts_by_name.get("client1")
    assert client is not None and client.processes[0].exited
    assert state_digest(on.engine) == state_digest(off.engine)


def test_table_parity_tor200():
    """The tor200 gate: 305 hosts, full circuit builds over real TCP,
    table on vs off across serial global, tpu, and --processes 2."""
    xml = tor_network(200, n_clients=100, n_servers=5, stoptime=24,
                      stream_spec="512:20480")
    oracle = state_digest(_run(xml, 24, "off").engine)
    assert state_digest(_run(xml, 24, "on").engine) == oracle
    assert state_digest(
        _run(xml, 24, "on", policy="tpu").engine) == oracle


def test_table_parity_star_device_modes():
    """star (tgen device flows, plugin-driven): table on/off and
    device/numpy plane twins all byte-identical."""
    from shadow_tpu.tools.workloads import star_bulk
    xml = star_bulk(12, stoptime=60, bulk_bytes=512 * 1024,
                    device_data=True)

    def run(table, mode):
        set_logger(SimLogger(stream=io.StringIO(), level="warning"))
        cfg = configuration.parse_xml(xml)
        cfg.stop_time_sec = 60
        ctrl = Controller(Options(scheduler_policy="tpu", workers=0,
                                  stop_time_sec=60, seed=7,
                                  host_table=table, dataplane="python",
                                  device_plane=mode), cfg)
        assert ctrl.run() == 0
        return state_digest(ctrl.engine)

    oracle = run("off", "numpy")
    assert run("on", "numpy") == oracle
    assert run("on", "device") == oracle


def test_table_parity_procs():
    """--processes 2 with the table on: shard-assembled digest equals the
    eager serial digest (replicas materialize on cross-shard delivery)."""
    from shadow_tpu.parallel.procs import ProcsController
    xml = tor_network(n_relays=8, n_clients=4, n_servers=1, stoptime=90,
                      seed=3)
    oracle = state_digest(_run(xml, 90, "off").engine)

    set_logger(SimLogger(stream=io.StringIO(), level="warning"))
    cfg = configuration.parse_xml(xml)
    cfg.stop_time_sec = 90
    pc = ProcsController(Options(scheduler_policy="global", workers=0,
                                 seed=7, stop_time_sec=90, processes=2,
                                 host_table="on", dataplane="python"), cfg)
    assert pc.run() == 0
    assert pc.digest == oracle


def test_table_parity_threaded():
    """Threaded scheduler (workers=2, host policy) with the table on:
    mid-round lookup promotions from worker threads keep the digest
    identical to the serial eager run (assignment-independence)."""
    oracle = state_digest(_run(MIXED_XML, 60, "off").engine)
    on = _run(MIXED_XML, 60, "on", policy="host", workers=2)
    assert state_digest(on.engine) == oracle


def test_midrun_checkpoint_parity_and_resume(tmp_path):
    """MID-RUN snapshots must match too: deferred boot events count into
    pending_events (Scheduler.pending_count folds the table), and a
    --resume from a table-mode snapshot replays to the same digest."""
    import glob
    import pickle
    off_dir, on_dir = str(tmp_path / "off"), str(tmp_path / "on")
    off = _run(MIXED_XML, 60, "off", checkpoint_every_rounds=10,
               checkpoint_dir=off_dir)
    on = _run(MIXED_XML, 60, "on", checkpoint_every_rounds=10,
              checkpoint_dir=on_dir)
    assert state_digest(on.engine) == state_digest(off.engine)
    snaps_off = sorted(glob.glob(off_dir + "/*.ckpt"))
    snaps_on = sorted(glob.glob(on_dir + "/*.ckpt"))
    assert snaps_off and len(snaps_off) == len(snaps_on)
    for a, b in zip(snaps_off, snaps_on):
        with open(a, "rb") as fa, open(b, "rb") as fb:
            assert pickle.load(fa)["digest"] == pickle.load(fb)["digest"]
    resumed = _run(MIXED_XML, 60, "on", resume_path=on_dir)
    assert resumed.engine.supervision.resume_verified
    assert state_digest(resumed.engine) == state_digest(off.engine)


def test_native_plane_defers_to_table():
    """With unmaterialized rows the C data plane must decline (it
    registers every host at attach) — and the pure-Python run it falls
    back to stays digest-identical, so the fallback costs speed only."""
    from shadow_tpu.parallel import native_plane
    set_logger(SimLogger(stream=io.StringIO(), level="warning"))
    cfg = configuration.parse_xml(MIXED_XML)
    cfg.stop_time_sec = 60
    ctrl = Controller(Options(scheduler_policy="global", workers=0,
                              stop_time_sec=60, seed=7, host_table="on"),
                      cfg)
    assert ctrl.run() == 0
    # quiet rows remain, so the plane declined at attach time and still
    # declines now
    assert ctrl.engine.native_plane is None
    reason = native_plane.eligible(ctrl.engine)
    assert reason is not None and "host table" in reason
    assert state_digest(ctrl.engine) == \
        state_digest(_run(MIXED_XML, 60, "off").engine)


# ---------------------------------------------------------------------------
# processless device flows (generated scenarios)
# ---------------------------------------------------------------------------

def _run_scenario(cfg, mode="numpy", policy="global", seed=7):
    set_logger(SimLogger(stream=io.StringIO(), level="warning"))
    ctrl = Controller(Options(scheduler_policy=policy, workers=0,
                              stop_time_sec=int(cfg.stop_time_sec),
                              seed=seed, host_table="on",
                              heartbeat_interval_sec=0,
                              device_plane=mode), cfg)
    rc = ctrl.run()
    assert rc == 0
    return ctrl


def test_star_flows_all_quiet():
    """star: every client completes its transfer with ZERO Host objects
    materialized — the tracker bytes land in the table's columns."""
    ctrl = _run_scenario(genscen.star(200, stoptime=120, stagger_waves=2,
                                      stagger_step_sec=1.0))
    e = ctrl.engine
    st = e.device_plane.stats()
    assert st["completed"] == st["circuits"] == 200
    assert e.host_table.materialized_count == 0
    # download bytes folded into the quiet rows' rx columns (server is
    # row 0; clients rows 1..200)
    assert int(e.host_table.rx_bytes[1]) > 0
    assert int(e.host_table.tx_bytes[0]) > 0
    # and the digest reads them without materializing anyone
    state_digest(e)
    assert e.host_table.materialized_count == 0


def test_star_flows_deterministic_and_mode_parity():
    d = []
    for mode in ("numpy", "numpy", "device"):
        cfg = genscen.star(100, stoptime=120, stagger_waves=2,
                           stagger_step_sec=1.0)
        d.append(state_digest(_run_scenario(cfg, mode).engine))
    assert d[0] == d[1] == d[2]


def test_tor_shape_flows():
    """tor100k's shape at n=300: 5-hop chains (guard/middle/exit drawn
    per client from the seeded vectorized triple), everything quiet."""
    ctrl = _run_scenario(genscen.tor(300, stoptime=120, stagger_waves=2))
    e = ctrl.engine
    st = e.device_plane.stats()
    assert st["completed"] == st["circuits"]
    assert e.host_table.materialized_count == 0
    # a relay row carries BOTH directions (tx and rx) of forwarded cells
    table = e.host_table
    relay_rows = range(0, 30)   # relays are the first group
    moved = sum(int(table.rx_bytes[r]) + int(table.tx_bytes[r])
                for r in relay_rows)
    assert moved > 0


def test_distinct3_is_distinct():
    rng = np.random.default_rng(5)
    a, b, c = genscen._distinct3(rng, 10_000, 30)
    assert (a != b).all() and (b != c).all() and (a != c).all()
    assert int(a.max()) < 30 and int(c.max()) < 30


# ---------------------------------------------------------------------------
# generators + CLI
# ---------------------------------------------------------------------------

def test_genscen_deterministic():
    assert genscen.config_digest(genscen.star(1000)) == \
        genscen.config_digest(genscen.star(1000))
    assert genscen.config_digest(genscen.tor(1000)) != \
        genscen.config_digest(genscen.tor(1000, seed=43))


def test_genscen_rejects_unknown_overrides():
    """ISSUE 13 satellite: a typo'd override must raise naming the valid
    set, never silently build the default scenario (the fuzzer's repro
    files depend on override fidelity)."""
    with pytest.raises(ValueError, match="stoptme"):
        genscen.build("star", stoptme=5)
    with pytest.raises(ValueError, match="valid:"):
        genscen.build("tor10k", n_clients=5)   # tor takes n_hosts
    with pytest.raises(ValueError, match="unknown scenario"):
        genscen.build("nope")


def test_genscen_preset_merge():
    """Preset + overrides MERGE (overrides win): build("star10k",
    stoptime=5) is the 10k preset at stoptime 5, not the family
    default."""
    cfg = genscen.build("star10k", stoptime=5)
    assert cfg.stop_time_sec == 5
    assert sum(h.quantity for h in cfg.hosts) == 10_001


def test_config_digest_covers_flow_params_and_argv():
    """ISSUE 13 satellite: two scenarios differing only in a FlowConfig
    field or only in app argv must not share a digest — it keys the fuzz
    corpus dedupe."""
    assert genscen.config_digest(genscen.star(100)) != \
        genscen.config_digest(genscen.star(100, down_bytes=999))
    assert genscen.config_digest(genscen.star(100)) != \
        genscen.config_digest(genscen.star(100, stagger_waves=3))
    assert genscen.config_digest(genscen.phold(10)) != \
        genscen.config_digest(genscen.phold(10, msgs_in_flight=2))
    assert genscen.config_digest(genscen.swarm(50)) != \
        genscen.config_digest(genscen.swarm(50, seed=2))


# ---------------------------------------------------------------------------
# workload fleet: cdn flash-crowd + swarm many-to-many (ISSUE 13)
# ---------------------------------------------------------------------------

def test_cdn_generator_shape():
    cfg = genscen.cdn(500, n_origins=4, stoptime=60)
    assert sum(h.quantity for h in cfg.hosts) == 504
    assert genscen.config_digest(cfg) == \
        genscen.config_digest(genscen.cdn(500, n_origins=4, stoptime=60))
    # the seeded dest draw spreads clients over every origin
    table_flows = []
    from shadow_tpu.scale.genscen import expand_flows

    class _Grp:
        def __init__(self, hc, first_row, count):
            self.hc, self.first_row, self.count = hc, first_row, count

        def name_of(self, q):
            return f"{self.hc.id}{q + 1}"
    grp = _Grp(cfg.hosts[1], 4, 500)
    table_flows = expand_flows(None, grp)
    dests = {f[1][0] for f in table_flows}
    assert dests == {"origin1", "origin2", "origin3", "origin4"}


def test_mixnet_generator_shape_and_determinism():
    """ISSUE 19 satellite: the mixnet family — tor shape plus per-client
    constant-rate cover cells, each cover wave on its own seeded circuit
    with NO stagger (the rate the plane sees must be genuinely
    constant)."""
    cfg = genscen.build("mixnet500")
    groups = [(h.id, h.quantity, len(h.flows or ())) for h in cfg.hosts]
    assert groups == [("mixrelay", 50, 0), ("mixdest", 5, 0),
                      ("mixclient", 445, 5)]
    assert genscen.config_digest(cfg) == \
        genscen.config_digest(genscen.build("mixnet500"))
    assert genscen.config_digest(cfg) != \
        genscen.config_digest(genscen.build("mixnet500", cover_cells=3))
    payload, *cover = cfg.hosts[2].flows
    assert payload.stagger_waves == 4
    assert [f.start_time_sec for f in cover] == \
        [2.0 + 2.0 * k for k in range(4)]
    for f in cover:
        assert f.stagger_waves == 1 and f.down_bytes == f.up_bytes == 512
    seeds = {f.tor_path_seed for f in cover}
    assert len(seeds) == 4 and payload.tor_path_seed not in seeds
    with pytest.raises(ValueError, match="cover cell"):
        genscen.mixnet(500, cover_cells=0)


def test_mixnet_cover_traffic_all_on_device():
    """Every payload circuit and every cover cell completes as a
    processless 5-hop device chain — zero Host objects materialize."""
    ctrl = _run_scenario(genscen.build("mixnet500", stoptime=60))
    e = ctrl.engine
    st = e.device_plane.stats()
    assert st["circuits"] == 445 * 5
    assert st["completed"] == st["circuits"]
    assert e.host_table.materialized_count == 0
    table = e.host_table
    moved = sum(int(table.rx_bytes[r]) + int(table.tx_bytes[r])
                for r in range(50))          # relays are the first group
    assert moved > 0


def test_swarm_generator_no_self_flows():
    cfg = genscen.swarm(60, pieces=3, stoptime=60)
    from shadow_tpu.scale.genscen import expand_flows

    class _Grp:
        def __init__(self, hc, first_row, count):
            self.hc, self.first_row, self.count = hc, first_row, count

        def name_of(self, q):
            return f"{self.hc.id}{q + 1}"
    flows = expand_flows(None, _Grp(cfg.hosts[0], 0, 60))
    assert len(flows) == 180
    for _row, down, up, _d, _u, _s in flows:
        assert down[0] != down[1], "swarm drew a self-flow"
        assert up == (down[1], down[0])


def test_fleet_end_to_end_on_device():
    """The fleet acceptance shape at test size, three runs doing triple
    duty: (a) every flow completes with >= 90% of traffic advancing on
    the device plane (measured from the metrics registry, like the
    bench rows); (b) the fuzz-found bare-name bug stays fixed (the
    sub-100-host tor shape has ONE dest named bare ``dest``; cdn runs
    with ONE origin); (c) nobody materializes."""
    for cfg in (genscen.tor(60, stoptime=30, stagger_waves=1,
                            down_bytes=4096, up_bytes=1024),
                genscen.cdn(60, n_origins=1, stoptime=30,
                            stagger_waves=1, down_bytes=8192),
                genscen.swarm(30, pieces=2, stoptime=30,
                              piece_bytes=8192)):
        ctrl = _run_scenario(cfg)
        e = ctrl.engine
        scrape = e.metrics.scrape()
        st = e.device_plane.stats()
        assert st["completed"] == st["circuits"] > 0
        assert e.host_table.materialized_count == 0
        fraction = scrape["plane.forwards"] / max(
            scrape["plane.forwards"] + e.events_executed, 1)
        assert fraction >= 0.90, fraction


def test_genscen_xml_roundtrip():
    """<flow> elements survive config_to_xml -> parse_xml."""
    import dataclasses
    from shadow_tpu.tools.mkscenario import config_to_xml
    # structural equality (56 == 56.0: XML re-parse floats times; the
    # simulation consumes them identically)
    cfg = genscen.star(50, stoptime=60)
    cfg2 = configuration.parse_xml(config_to_xml(cfg))
    assert dataclasses.asdict(cfg2) == dataclasses.asdict(cfg)
    tor_cfg = genscen.tor(400, stoptime=60)
    tor2 = configuration.parse_xml(config_to_xml(tor_cfg))
    assert dataclasses.asdict(tor2) == dataclasses.asdict(tor_cfg)
    # the seeded-dest fields (cdn/swarm) round-trip too
    for cfg3 in (genscen.cdn(40, n_origins=2, stoptime=60),
                 genscen.swarm(20, pieces=2, stoptime=60)):
        back = configuration.parse_xml(config_to_xml(cfg3))
        assert dataclasses.asdict(back) == dataclasses.asdict(cfg3)


def test_mkscenario_cli(capsys):
    from shadow_tpu.tools import mkscenario
    assert mkscenario.main(["star100k"]) == 0
    row = json.loads(capsys.readouterr().out)
    assert row["hosts"] == 100_001 and row["flows"] == 100_000
    # XML refusal above the cap: generating multi-megabyte XML is what
    # the Configuration-object generators exist to avoid
    assert mkscenario.main(["star100k", "--xml"]) == 2
    assert mkscenario.main(["nope"]) == 2


def test_mkscenario_seed_flag(capsys):
    """ISSUE 13 satellite: --seed pins the seeded families' structural
    draws from the CLI (fuzz-discovered scenarios replay by seed)."""
    from shadow_tpu.tools import mkscenario
    assert mkscenario.main(["swarm500", "--seed", "5"]) == 0
    a = json.loads(capsys.readouterr().out)
    assert mkscenario.main(["swarm500", "--seed", "6"]) == 0
    b = json.loads(capsys.readouterr().out)
    assert a["digest"] != b["digest"]
    # the argparse --seed=N spelling must hit the builder too (a
    # silently-skipped override would replay a DIFFERENT scenario)
    assert mkscenario.main(["swarm500", "--seed=5"]) == 0
    assert json.loads(capsys.readouterr().out)["digest"] == a["digest"]
    # star has no builder seed: the flag still parses (engine seed only)
    assert mkscenario.main(["star2k", "--seed", "9"]) == 0
    capsys.readouterr()
    assert mkscenario.main(["star2k", "--seed", "oops"]) == 2


def test_mkscenario_run_propagates_rc(monkeypatch):
    """ISSUE 13 satellite: --run must surface the child engine's nonzero
    exit (a failed fuzz replay cannot report rc 0)."""
    from shadow_tpu.core.configuration import (Configuration, HostConfig,
                                               ProcessConfig)
    from shadow_tpu.scale import genscen as g
    from shadow_tpu.tools import mkscenario
    bad = Configuration(stop_time_sec=10)
    hc = HostConfig(id="c", bandwidth_down_kibps=1024,
                    bandwidth_up_kibps=1024)
    hc.processes.append(ProcessConfig(
        plugin="python:echo", start_time_sec=1.0,
        arguments="udp client nosuchhost 8000 1 64"))
    bad.hosts.append(hc)
    monkeypatch.setattr(g, "build", lambda name, **kw: bad)
    rc = mkscenario.main(["star2k", "--run", "--log-level", "error"])
    assert rc == 1


def test_phold_generator_runs_eager_shape():
    """phold is the host-plane stress: all hosts carry a real plugin, so
    they all materialize — through the same table machinery."""
    cfg = genscen.phold(12, stoptime=15, msgs_in_flight=1)
    ctrl = _run_scenario(cfg)
    e = ctrl.engine
    assert e.host_table.materialized_count == 12
    assert e.events_executed > 0


# ---------------------------------------------------------------------------
# vectorized RNG + shuffle satellites
# ---------------------------------------------------------------------------

def test_derive_np_matches_scalar():
    from shadow_tpu.core.rng import derive, derive_np
    ids = np.array([1, 2, 3, 1000, 123456], dtype=np.int64)
    vec = derive_np(99, "host", ids)
    for i, hid in enumerate(ids):
        assert int(vec[i]) == derive(99, "host", int(hid))


def test_bits64_keys_np_matches_scalar():
    from shadow_tpu.core.rng import RandomSource, bits64_keys_np, derive
    keys = [derive(7, "host", i) for i in range(5)]
    vec = bits64_keys_np(np.array(keys, dtype=np.uint64), 0)
    for i, k in enumerate(keys):
        assert int(vec[i]) == RandomSource(k).next_u64()


def test_shuffle_permutation_matches_sequential_fisher_yates():
    """The vectorized host shuffle is bitwise the sequential chain it
    replaced: same seed, same permutation — assignments unchanged."""
    from shadow_tpu.core.rng import RandomSource, derive
    from shadow_tpu.core.scheduler import shuffle_permutation
    for n in (0, 1, 2, 17, 400):
        ref = list(range(n))
        rng = RandomSource(derive(1234, "host-shuffle"))
        for i in range(n - 1, 0, -1):
            j = rng.next_int(i + 1)
            ref[i], ref[j] = ref[j], ref[i]
        assert shuffle_permutation(n, 1234).tolist() == ref


def test_shuffle_digest_invariant_per_seed():
    """The shuffle affects load balance only: digests identical across
    worker counts/policies that deal hosts differently (PR 2's pin,
    re-asserted over the array shuffle)."""
    a = _run(MIXED_XML, 60, "off", policy="global", workers=0)
    b = _run(MIXED_XML, 60, "off", policy="host", workers=3)
    assert state_digest(a.engine) == state_digest(b.engine)


# ---------------------------------------------------------------------------
# DNS block reservation
# ---------------------------------------------------------------------------

def test_dns_try_reserve_block_declines_dirty_ranges():
    """A candidate block crossing a registered IP or a restricted CIDR is
    DECLINED (None), not pushed past it: unique_ip skips only the
    colliding addresses, so a jumped block would assign different IPs
    than eager per-host registration and break digest parity."""
    from shadow_tpu.routing.dns import DNS
    from shadow_tpu.routing.address import ip_to_int
    d = DNS()
    d.register(1, "pre", ip_to_int("11.0.0.5"))
    assert d.try_reserve_block(10) is None
    d2 = DNS()
    d2._ip_counter = ip_to_int("126.255.255.250")
    assert d2.try_reserve_block(100) is None
    d3 = DNS()
    base = d3.try_reserve_block(100_000)
    assert base == ip_to_int("11.0.0.1")


def test_table_parity_with_ip_hint_neighbor():
    """The regression the verify drive caught: an ip_hint host registered
    before a quantity group must leave the group's IPs identical to eager
    assignment (the group falls back to per-row registration)."""
    xml = """<shadow stoptime="60">
      <plugin id="echo" path="python:echo" />
      <host id="pinned" iphint="11.0.0.3" bandwidthdown="10240" bandwidthup="10240">
        <process plugin="echo" starttime="1" arguments="udp server 8000" />
      </host>
      <host id="caller" bandwidthdown="10240" bandwidthup="10240">
        <process plugin="echo" starttime="2" arguments="udp client pinned 8000 5 200" />
      </host>
      <host id="fleet" quantity="20" bandwidthdown="10240" bandwidthup="10240"></host>
    </shadow>"""
    off = _run(xml, 60, "off")
    on = _run(xml, 60, "on")
    assert state_digest(on.engine) == state_digest(off.engine)


def test_name_domain_collision_rejected():
    """Eager boot raises at dns.register on a duplicate name; lazily-
    resolved block groups must reject the same collision at reserve."""
    xml = """<shadow stoptime="10">
      <host id="client" quantity="20" bandwidthdown="1024" bandwidthup="1024"></host>
      <host id="client12" bandwidthdown="1024" bandwidthup="1024"></host>
    </shadow>"""
    set_logger(SimLogger(stream=io.StringIO(), level="warning"))
    cfg = configuration.parse_xml(xml)
    with pytest.raises(ValueError, match="client12"):
        Controller(Options(stop_time_sec=10, host_table="on"), cfg).run()


def test_dns_hint_cannot_enter_reserved_block():
    """An ip_hint landing inside a lazily reserved block must NOT be
    honored (block IPs are assigned but not in _by_ip); eager boot would
    have detected the collision and assigned a fresh IP."""
    from shadow_tpu.routing.dns import DNS
    d = DNS()
    base = d.try_reserve_block(1000)
    a = d.register(999, "evil", base + 4)
    assert not (base <= a.ip < base + 1000)
    # and unique_ip never wanders into a reserved block either
    d2 = DNS()
    b2 = d2.try_reserve_block(10)
    assert not (b2 <= d2.unique_ip() < b2 + 10)


def test_row_of_name_rejects_leading_zeros():
    """"client01" must not alias client1 — eager boot would fail to
    resolve the misspelling, so the lazy path must too."""
    ctrl = _run_scenario(genscen.star(50, stoptime=60))
    table = ctrl.engine.host_table
    assert table.row_of_name("client7") is not None
    assert table.row_of_name("client07") is None
    assert table.row_of_name("client007") is None
    assert ctrl.engine.host_by_name("client07") is None


def test_dns_lazy_resolution():
    """Quiet rows resolve by name and ip without materializing."""
    ctrl = _run_scenario(genscen.star(50, stoptime=60))
    e = ctrl.engine
    addr = e.dns.resolve_name("client7")
    assert addr is not None and e.host_table.materialized_count == 0
    assert e.dns.resolve_ip(addr.ip).name == "client7"


# ---------------------------------------------------------------------------
# memory metrics surface
# ---------------------------------------------------------------------------

def test_scale_metrics_in_jsonl(tmp_path):
    """scale.* lands in the metrics JSONL and reads back through
    trace_report --metrics — the path bench-smoke gates on."""
    from shadow_tpu.obs.metrics import read_metrics_file
    from shadow_tpu.tools.trace_report import summarize_metrics
    mpath = str(tmp_path / "metrics.jsonl")
    cfg = genscen.star(100, stoptime=60)
    set_logger(SimLogger(stream=io.StringIO(), level="warning"))
    ctrl = Controller(Options(scheduler_policy="global", workers=0,
                              stop_time_sec=60, seed=7, host_table="on",
                              heartbeat_interval_sec=0,
                              device_plane="numpy", metrics_path=mpath),
                      cfg)
    assert ctrl.run() == 0
    final = summarize_metrics(read_metrics_file(mpath))["final"]
    for key in ("scale.table_rows", "scale.materialized_hosts",
                "scale.table_bytes_per_host", "scale.peak_rss_mb",
                "scale.boot_sec", "scale.bytes_per_host"):
        assert key in final, key
    assert final["scale.table_rows"] == 101
    assert final["scale.materialized_hosts"] == 0
    assert final["scale.table_bytes_per_host"] <= 256


def test_table_host_state_matches_eager_quiet_host():
    """The synthesized digest dict for a quiet row is field-identical to
    the _host_state of the same host booted eagerly."""
    from shadow_tpu.core.checkpoint import _host_state
    off = _run(MIXED_XML, 60, "off")
    on = _run(MIXED_XML, 60, "on")
    table = on.engine.host_table
    for name in ("quiet1", "quiet5"):
        row = table.row_of_name(name)
        assert row is not None and not table.materialized[row]
        eager = _host_state(off.engine.hosts_by_name[name])
        synth = table.host_state(row)
        assert synth == eager, name


@pytest.mark.slow
def test_scale_star10k_end_to_end():
    """The scale acceptance shape at tier-2 size: 10k+1 hosts boot as
    table rows, all flows complete, >= 1 sim-sec/wall-sec, nobody
    materializes.  (star100k runs in bench.py — same machinery, 10x.)"""
    import time as _walltime
    t0 = _walltime.monotonic()
    cfg = genscen.star(10_000, stoptime=300, stagger_waves=4,
                       stagger_step_sec=1.0)
    ctrl = _run_scenario(cfg)
    wall = _walltime.monotonic() - t0
    e = ctrl.engine
    st = e.device_plane.stats()
    assert st["completed"] == 10_000
    assert e.host_table.materialized_count == 0
    assert 300 / wall >= 1.0, f"{300 / wall:.2f} sim-sec/wall-sec"


@pytest.mark.slow
def test_scale_tor100k_sharded_end_to_end(tmp_path):
    """ROADMAP item 2's remaining step, through ISSUE 9's mesh plane:
    tor100k (the reference's Tor shape — ~10% relays, ~1% fat servers,
    per-client seeded 3-hop circuits; the generated stand-in for the
    reference GraphML, which is not present in this container) runs
    end-to-end through tools/mkscenario.py --run with the flow table
    SHARDED over the 8-virtual-device mesh.  Every circuit completes,
    cross-shard forwards ride the device-side exchange (host_bounces 0),
    and the per-dispatch device-call budget holds.  The 10 ms granule
    bounds the tick count on the virtual mesh (30k 1 ms ticks of a
    ~900k-flow table would run minutes for no extra coverage)."""
    from shadow_tpu.obs.metrics import read_metrics_file
    from shadow_tpu.tools import mkscenario
    from shadow_tpu.tools.trace_report import summarize_metrics

    mpath = str(tmp_path / "tor100k-metrics.jsonl")
    cfg = genscen.tor(100_000, stoptime=30, stagger_waves=2)
    rc = mkscenario.run_scenario(
        cfg, ["--stop-time", "30", "--tpu-devices", "8",
              "--device-plane-granule-ms", "10", "--metrics", mpath,
              "--log-level", "warning"])
    assert rc == 0
    final = summarize_metrics(read_metrics_file(mpath))["final"]
    assert final["plane.completed"] == final["plane.circuits"] == 89_000
    assert final["mesh.devices"] == 8
    assert final["mesh.host_bounces"] == 0
    assert final["mesh.cross_shard_cells"] > 0
    assert 1 <= final["mesh.exchange_legs"] <= 7
    assert final["plane.device_calls"] \
        <= 3 * max(final["plane.dispatches"], 1)
    assert final["scale.peak_rss_mb"] < 4096
