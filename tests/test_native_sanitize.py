"""Sanitizer-hardened native plane (ISSUE 4 satellite, slow tier).

Builds the C data plane as ``_shadow_dataplane_san.so`` with
``-fsanitize=address,undefined -fno-omit-frame-pointer`` (native/Makefile
``SANITIZE=``), then replays the ENTIRE native dataplane digest-parity
suite (tests/test_native_dataplane.py) in a subprocess running under the
instrumented extension — ``SHADOW_SANITIZE`` makes
``native_plane._load_module`` pick the hardened twin, and ``LD_PRELOAD``
supplies the ASan runtime a stock interpreter lacks.  Any sanitizer
report (heap overflow, use-after-free, UB) fails the test; a toolchain
without sanitizer runtimes skips LOUDLY rather than passing vacuously.

Slow-marked: the instrumented suite costs minutes, so it rides the slow
tier, not the tier-1 gate.
"""

from __future__ import annotations

import os
import shutil
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NATIVE_DIR = os.path.join(REPO, "native")
SAN_SPEC = "address,undefined"
SAN_SO = os.path.join(REPO, "shadow_tpu", "native",
                      "_shadow_dataplane_san.so")


def _sanitizer_toolchain_or_skip(tmp_path) -> str:
    """Verify g++ can produce AND link sanitized objects here; return the
    libasan runtime path for LD_PRELOAD.  Skips (loudly, with the reason)
    when any piece is missing."""
    gxx = os.environ.get("CXX") or "g++"
    if shutil.which(gxx) is None:
        pytest.skip(f"no C++ compiler ({gxx}) — cannot build the "
                    "sanitized native plane")
    smoke = tmp_path / "smoke.cc"
    smoke.write_text("extern \"C\" int shd_smoke(int x) { return x + 1; }\n")
    try:
        probe = subprocess.run(
            [gxx, f"-fsanitize={SAN_SPEC}", "-fno-omit-frame-pointer",
             "-shared", "-fPIC", "-o", str(tmp_path / "smoke.so"),
             str(smoke)],
            capture_output=True, text=True, timeout=120)
    except (OSError, subprocess.TimeoutExpired) as e:
        pytest.skip(f"sanitizer smoke compile failed to run: {e!r}")
    if probe.returncode != 0:
        pytest.skip("toolchain lacks sanitizer runtimes "
                    f"(-fsanitize={SAN_SPEC} failed):\n{probe.stderr}")
    libasan = subprocess.run(
        [gxx, "-print-file-name=libasan.so"],
        capture_output=True, text=True, timeout=60).stdout.strip()
    if not os.path.isabs(libasan) or not os.path.exists(libasan):
        pytest.skip("libasan runtime not found "
                    f"(g++ -print-file-name gave {libasan!r})")
    return libasan


def _san_env(libasan: str) -> dict:
    env = dict(os.environ)
    env.update({
        "SHADOW_SANITIZE": SAN_SPEC,
        "LD_PRELOAD": libasan,
        # detect_leaks=0: CPython intentionally leaks interned/static
        # allocations at exit — LSan would drown real reports.
        # abort_on_error=1 turns any ASan report into a nonzero exit the
        # assertion below catches even if the report text is garbled.
        "ASAN_OPTIONS": "detect_leaks=0:abort_on_error=1",
        # UBSan prints-and-continues by default; halt so a report fails.
        "UBSAN_OPTIONS": "halt_on_error=1:print_stacktrace=1",
        "JAX_PLATFORMS": "cpu",
    })
    return env


def test_native_dataplane_suite_under_sanitizers(tmp_path):
    libasan = _sanitizer_toolchain_or_skip(tmp_path)
    # build the instrumented twin (separate artifact: never clobbers the
    # production _shadow_dataplane.so)
    build = subprocess.run(
        ["make", f"SANITIZE={SAN_SPEC}",
         os.path.join("..", "shadow_tpu", "native",
                      "_shadow_dataplane_san.so")],
        cwd=NATIVE_DIR, capture_output=True, text=True, timeout=300)
    if build.returncode != 0:
        pytest.skip("sanitized dataplane build failed (toolchain lacks "
                    f"sanitizer support?):\n{build.stderr[-2000:]}")
    assert os.path.exists(SAN_SO), "make succeeded but produced no .so"
    env = _san_env(libasan)
    # the hardened twin must actually LOAD — otherwise the suite below
    # would skip its native cases and this test would pass vacuously
    probe = subprocess.run(
        [sys.executable, "-c",
         "from shadow_tpu.parallel import native_plane as n; import sys; "
         "sys.exit(0 if n.native_available() else 3)"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=300)
    if probe.returncode == 3:
        pytest.skip("sanitized extension built but did not load "
                    "(sanitizer runtime mismatch?) — stderr:\n"
                    f"{probe.stderr[-2000:]}")
    assert probe.returncode == 0, (
        f"probe interpreter died under sanitizers (rc={probe.returncode}):"
        f"\n{probe.stderr[-3000:]}")
    # the full digest-parity suite, now instrumented end to end
    run = subprocess.run(
        [sys.executable, "-m", "pytest", "-q", "-p", "no:cacheprovider",
         os.path.join("tests", "test_native_dataplane.py")],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=1800)
    text = run.stdout + run.stderr
    for marker in ("ERROR: AddressSanitizer", "ERROR: LeakSanitizer",
                   "runtime error:", "AddressSanitizer:DEADLYSIGNAL"):
        assert marker not in text, (
            f"sanitizer report under the native dataplane suite "
            f"({marker}):\n{text[-4000:]}")
    assert run.returncode == 0, (
        f"sanitized dataplane suite failed (rc={run.returncode}):\n"
        f"{text[-4000:]}")
