"""Full determinism gate: two identically-seeded runs must produce
byte-identical stripped logs (the reference's determinism1/2_compare ctest,
src/test/determinism + tools/strip_log_for_compare.py).

This is the de-facto race detector (SURVEY.md §5): any nondeterminism in
event ordering, RNG consumption, or scheduler interleaving shows up as a
log diff."""

import io
import textwrap

from shadow_tpu.core import configuration
from shadow_tpu.core.controller import Controller
from shadow_tpu.core.logger import SimLogger, set_logger
from shadow_tpu.core.options import Options
from shadow_tpu.tools.parse_log import parse_log, strip_log

# lossy links + TCP retransmits + app randomness: the hard determinism case
LOSSY_XML = textwrap.dedent("""\
    <shadow stoptime="120">
      <topology><![CDATA[<?xml version="1.0" encoding="UTF-8"?>
        <graphml xmlns="http://graphml.graphdrawing.org/xmlns">
        <key id="d0" for="edge" attr.name="latency" attr.type="double"/>
        <key id="d1" for="edge" attr.name="packetloss" attr.type="double"/>
        <key id="d2" for="node" attr.name="bandwidthdown" attr.type="int"/>
        <key id="d3" for="node" attr.name="bandwidthup" attr.type="int"/>
        <graph edgedefault="undirected">
          <node id="n0"><data key="d2">10240</data><data key="d3">10240</data></node>
          <edge source="n0" target="n0"><data key="d0">25.0</data><data key="d1">0.02</data></edge>
        </graph></graphml>]]></topology>
      <plugin id="tgen" path="python:tgen" />
      <plugin id="echo" path="python:echo" />
      <host id="server">
        <process plugin="tgen" starttime="1" arguments="server 80" />
      </host>
      <host id="client" quantity="4">
        <process plugin="tgen" starttime="2"
                 arguments="client server 80 2048:204800" />
      </host>
      <host id="u1"><process plugin="echo" starttime="1" arguments="udp server 8000" /></host>
      <host id="u2"><process plugin="echo" starttime="2" arguments="udp client u1 8000 20 900" /></host>
    </shadow>
""")


def run_logged(xml, policy="global", workers=0, seed=7):
    sink = io.StringIO()
    set_logger(SimLogger(stream=sink, level="message"))
    try:
        cfg = configuration.parse_xml(xml)
        cfg.stop_time_sec = 120
        opts = Options(scheduler_policy=policy, workers=workers,
                       stop_time_sec=120, seed=seed)
        ctrl = Controller(opts, cfg)
        rc = ctrl.run()
    finally:
        set_logger(SimLogger())
    return rc, sink.getvalue(), ctrl


def test_stripped_log_identical_across_runs():
    rc1, log1, c1 = run_logged(LOSSY_XML)
    rc2, log2, c2 = run_logged(LOSSY_XML)
    assert rc1 == rc2 == 0
    s1 = "\n".join(strip_log(log1.splitlines()))
    s2 = "\n".join(strip_log(log2.splitlines()))
    assert s1 == s2, "stripped logs differ between identically-seeded runs"
    # losses actually happened (the topology has 2% loss), so the gate
    # covered the retransmit/RNG paths
    summary = parse_log(log1.splitlines())
    assert summary["total_retrans"] + summary["total_drops"] > 0


def test_different_seed_diverges():
    """Sanity check on the gate itself: a different seed must change the
    packet-loss draws and therefore the log."""
    _, log1, _ = run_logged(LOSSY_XML, seed=7)
    _, log2, _ = run_logged(LOSSY_XML, seed=8)
    s1 = "\n".join(strip_log(log1.splitlines()))
    s2 = "\n".join(strip_log(log2.splitlines()))
    assert s1 != s2


def test_parallel_policy_matches_serial():
    """Event outcomes are schedule-independent: host-steal with 4 workers
    produces the same stripped log as the serial global policy (the
    CPU-policy equivalence half of the reference's parity strategy)."""
    rc1, log1, _ = run_logged(LOSSY_XML, policy="global", workers=0)
    rc2, log2, _ = run_logged(LOSSY_XML, policy="steal", workers=4)
    assert rc1 == rc2 == 0
    # the [engine] banner legitimately differs (policy name, wall time);
    # everything the simulation itself produced must match
    s1 = sorted(l for l in strip_log(log1.splitlines()) if "[engine]" not in l)
    s2 = sorted(l for l in strip_log(log2.splitlines()) if "[engine]" not in l)
    assert s1 == s2


def test_parse_log_summary():
    rc, log, ctrl = run_logged(LOSSY_XML)
    assert rc == 0
    summary = parse_log(log.splitlines())
    assert summary["num_hosts"] >= 6
    assert summary["run"]["events"] == ctrl.engine.events_executed
    assert summary["total_rx_bytes"] > 4 * 204800  # the bulk downloads
    assert summary["sim_seconds"] > 0
