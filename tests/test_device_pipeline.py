"""Async device-pipeline gates (stage -> launch -> collect).

1. The pipelined plane (default: dispatch launched at the top of the round,
   collected at the next loop iteration while the host executes the round
   in between) produces BIT-IDENTICAL digests to the serial plane
   (--device-plane-sync blocks on the dispatch at launch) across 2+
   overlapped dispatch rounds — the engine commits round N's plane state
   before round N+1's staged injections are folded in, so overlap can never
   reorder anything.
2. An exception raised inside an in-flight dispatch surfaces at COLLECT
   time (consume materializes the flush buffer), not swallowed.
3. The packed flush buffer drives consume: exactly one small device read
   per dispatch (device_calls <= 3 including the dispatch and any inject
   upload).
4. signalfd fan-out (satellite): a blocked pending signal wakes EVERY
   matching signalfd; the first read consumes the shared instance.
"""

import numpy as np
import pytest

from shadow_tpu.core import configuration
from shadow_tpu.core.checkpoint import state_digest
from shadow_tpu.core.controller import Controller
from shadow_tpu.core.options import Options
from shadow_tpu.tools import workloads


def _run(sync: bool, stop: int = 60, mode: str = "device"):
    cfg = configuration.parse_xml(workloads.tor_network(
        8, n_clients=5, n_servers=2, stoptime=stop,
        stream_spec="512:20200", device_data=True))
    cfg.stop_time_sec = stop
    ctrl = Controller(Options(scheduler_policy="global", workers=0, seed=3,
                              stop_time_sec=stop, log_level="warning",
                              device_plane=mode, device_plane_sync=sync),
                      cfg)
    assert ctrl.run() == 0
    return ctrl


def test_pipelined_vs_serial_digest_parity():
    """Pipelined vs serial device plane: identical digests and identical
    plane summaries, with at least two dispatches in flight across round
    boundaries (the 2+-round overlap depth the launch/collect split
    creates)."""
    piped = _run(sync=False)
    serial = _run(sync=True)
    pa = piped.engine.device_plane
    pb = serial.engine.device_plane
    assert pa.dispatches >= 2, "workload too small to overlap dispatches"
    assert pa.dispatches == pb.dispatches
    assert pa.total_forwards == pb.total_forwards
    assert pa.stats()["completed"] == pb.stats()["completed"] == 5
    # the async run actually overlapped (wall elapsed between launch and
    # collect); the sync run blocked at launch by definition
    assert pa.pipeline_overlap_ns > 0
    assert state_digest(piped.engine) == state_digest(serial.engine)


def test_collect_is_one_packed_read_per_dispatch():
    """Transfer-chatter gate: the plane's host<->device interactions are
    bounded by 3 per dispatch (kernel call + flush read + at most one
    inject upload)."""
    ctrl = _run(sync=False, stop=120)
    plane = ctrl.engine.device_plane
    st = plane.stats()
    assert st["completed"] == st["circuits"]
    assert plane.dispatches > 0
    assert plane.device_calls <= 3 * plane.dispatches, \
        (f"{plane.device_calls} device calls for {plane.dispatches} "
         "dispatches (> 3 per dispatch)")


class _PoisonFlush:
    """Materializes like an in-flight device array whose computation
    failed."""

    def __array__(self, *a, **k):
        raise RuntimeError("boom-in-flight")


def test_inflight_exception_recovered_at_collect(monkeypatch):
    """A failure inside the launched dispatch surfaces at consume() (where
    the flush buffer materializes) and is RECOVERED by the dispatch guard
    (ISSUE 2): the window history replays on the numpy twin, the backend is
    permanently demoted, and the recovery is counted — never swallowed,
    never fatal.  End-to-end digest parity of this path is pinned by
    tests/test_supervision.py."""
    xml = workloads.tor_network(8, n_clients=2, n_servers=1, stoptime=10,
                                stream_spec="512:5120", device_data=True)
    cfg = configuration.parse_xml(xml)
    ctrl = Controller(Options(scheduler_policy="global", workers=0,
                              stop_time_sec=10, log_level="warning",
                              tpu_devices=1), cfg)
    ctrl.setup()
    from shadow_tpu.parallel.device_plane import build_plane_from_engine
    plane = build_plane_from_engine(ctrl.engine, mode="device")
    assert plane is not None and plane._shard is None
    eng = ctrl.engine
    eng.device_plane = plane

    import shadow_tpu.ops.torcells_device as td
    real = td.step_window_flush_for_backend()

    def poisoned(*args, **kw):
        out = real(*args, **kw)
        return (*out[:9], _PoisonFlush())

    monkeypatch.setattr(td, "step_window_flush_for_backend",
                        lambda: poisoned)
    plane.activate(plane.specs[0].client_name)
    eng.scheduler.window_end = 10 ** 9
    plane.advance(eng)
    assert plane._inflight
    plane.consume(eng)
    assert not plane._inflight
    assert plane.demoted and plane.mode == "numpy"
    assert plane.recoveries == 1
    assert eng.supervision.dispatch_recoveries == 1
    # demotion is permanent: the next windows run on the twin, no new slot
    # poisoning possible (the monkeypatched device path is never hit again)
    eng.scheduler.window_end = 2 * 10 ** 9
    plane.advance(eng)
    plane.consume(eng)
    assert plane.recoveries == 1


def test_signalfd_shared_pending_fanout():
    """satellite: deliver_signal semantics — ALL matching signalfds become
    readable on a blocked pending signal; the first read consumes the ONE
    process-wide instance and the others stop being readable."""
    from shadow_tpu.descriptor.base import S_READABLE
    from shadow_tpu.descriptor.signalfd import SharedSignalPending, SignalFD

    shared = SharedSignalPending()
    mask = 1 << (15 - 1)          # SIGTERM
    a = SignalFD(None, 3, mask, shared=shared)
    b = SignalFD(None, 4, mask, shared=shared)
    c = SignalFD(None, 5, 1 << (10 - 1), shared=shared)   # SIGUSR1 only

    assert shared.deliver(15) == 2          # both matching fds woke
    assert a.has_status(S_READABLE) and b.has_status(S_READABLE)
    assert not c.has_status(S_READABLE)

    rec = a.read_siginfo()                  # first read wins
    assert rec is not None and rec[0] == 15
    assert not a.has_status(S_READABLE)
    assert not b.has_status(S_READABLE), \
        "shared pending instance must vanish from the sibling on read"
    assert b.read_siginfo() is None

    # coalescing still holds through the shared store: two raises of a
    # standard signal collapse to one pending instance
    shared.deliver(15)
    shared.deliver(15)
    assert a.read_siginfo() is not None
    assert b.read_siginfo() is None

    # an unmatched signal reports 0 matching fds (handler fallback)
    assert shared.deliver(2) == 0

    # a signalfd opened while a matching signal is already pending is
    # readable from the start (signalfd(2) reports the pending set), and a
    # coalesced re-raise still wakes fds opened after the original raise
    shared.deliver(15)
    d = SignalFD(None, 6, mask, shared=shared)
    assert d.has_status(S_READABLE)
    e_mask_fd = SignalFD(None, 7, mask, shared=shared)
    assert e_mask_fd.has_status(S_READABLE)
    assert d.read_siginfo() is not None
    assert not e_mask_fd.has_status(S_READABLE)


def test_signalfd_process_route_via_api():
    """deliver_signal through the process API returns the matching-fd count
    and routes through the shared store (regression for the first-match
    behavior)."""
    from shadow_tpu.core.logger import SimLogger, set_logger
    set_logger(SimLogger(level="warning"))
    xml = ('<shadow stoptime="5"><plugin id="echo" path="python:echo" />'
           '<host id="h"><process plugin="echo" starttime="1" '
           'arguments="udp server 9000" /></host></shadow>')
    cfg = configuration.parse_xml(xml)
    ctrl = Controller(Options(scheduler_policy="global", workers=0,
                              stop_time_sec=5, log_level="warning"), cfg)
    ctrl.setup()
    host = ctrl.engine.host_by_name("h")
    proc = host.processes[0]
    api = proc.api
    mask = 1 << (15 - 1)
    fd1 = api.signalfd_create(mask)
    fd2 = api.signalfd_create(mask)
    assert fd1 != fd2
    assert api.deliver_signal(15) == 2
    # both descriptors readable; one read consumes the shared instance
    d1, d2 = proc._signal_fds
    from shadow_tpu.descriptor.base import S_READABLE
    assert d1.has_status(S_READABLE) and d2.has_status(S_READABLE)
    assert d2.read_siginfo() is not None
    assert d1.read_siginfo() is None
    assert not d1.has_status(S_READABLE)
