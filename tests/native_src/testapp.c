/* Dual-execution test program for the native plugin plane.
 *
 * The reference's test strategy (SURVEY.md §4, src/test/tcp etc.) builds
 * each test as a real program runnable both natively and under the
 * simulator; exit code 0 is the oracle.  This single binary exposes the
 * scenarios as subcommands:
 *
 *   vtime                          virtual clock: sleep advances exactly
 *   udpserver <port> <count>       echo <count> datagrams
 *   udpclient <host> <port> <count> <size>
 *   tcpserver <port> <expect>      accept one, read till EOF, check bytes
 *   tcpclient <host> <port> <bytes>
 *   epollserver <port> <nclients>  nonblocking epoll echo server
 *   pollclient <host> <port>       nonblocking connect + poll + echo check
 *   selectclient <host> <port>     same via select()
 *   randcheck                      getrandom + /dev/urandom read
 *   hostname <expected>            gethostname/getaddrinfo self-check
 *
 * Under the simulator the clock checks are exact (discrete virtual time);
 * natively they are loose.  SHADOW_TPU_FD in the environment tells us which
 * mode we're in (the shim passes through when it's absent).
 */
#define _GNU_SOURCE
#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <netdb.h>
#include <poll.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/epoll.h>
#include <sys/random.h>
#include <sys/select.h>
#include <sys/socket.h>
#include <sys/time.h>
#include <time.h>
#include <unistd.h>

static int under_sim(void) { return getenv("SHADOW_TPU_FD") != NULL; }

static int64_t now_ns(void) {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return (int64_t)ts.tv_sec * 1000000000LL + ts.tv_nsec;
}

static int resolve(const char *host, uint16_t port, struct sockaddr_in *out) {
  struct addrinfo hints, *res = NULL;
  memset(&hints, 0, sizeof hints);
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  char portstr[16];
  snprintf(portstr, sizeof portstr, "%u", port);
  if (getaddrinfo(host, portstr, &hints, &res) != 0 || !res) return -1;
  memcpy(out, res->ai_addr, sizeof *out);
  out->sin_port = htons(port);
  freeaddrinfo(res);
  return 0;
}

/* ------------------------------------------------------------------ vtime */
static int cmd_vtime(void) {
  int64_t t0 = now_ns();
  struct timespec req = {2, 500000000}; /* 2.5 s */
  if (nanosleep(&req, NULL) != 0) return 1;
  int64_t t1 = now_ns();
  int64_t elapsed = t1 - t0;
  struct timeval tv;
  gettimeofday(&tv, NULL);
  /* emulated epoch is 2000-01-01 (definitions.h:78) => seconds > 9e8 */
  if (tv.tv_sec < 900000000L) return 2;
  if (under_sim()) {
    if (elapsed != 2500000000LL) {
      fprintf(stderr, "vtime: elapsed %lld != 2.5e9\n", (long long)elapsed);
      return 3;
    }
  } else if (elapsed < 2400000000LL || elapsed > 60000000000LL) {
    return 3;
  }
  usleep(1000);
  int64_t t2 = now_ns();
  if (under_sim() && t2 - t1 != 1000000LL) return 4;
  printf("vtime OK elapsed=%lld\n", (long long)elapsed);
  return 0;
}

/* -------------------------------------------------------------------- udp */
static int cmd_udpserver(uint16_t port, int count) {
  int fd = socket(AF_INET, SOCK_DGRAM, 0);
  if (fd < 0) return 1;
  struct sockaddr_in sin;
  memset(&sin, 0, sizeof sin);
  sin.sin_family = AF_INET;
  sin.sin_addr.s_addr = htonl(INADDR_ANY);
  sin.sin_port = htons(port);
  if (bind(fd, (struct sockaddr *)&sin, sizeof sin) != 0) return 2;
  char buf[65536];
  for (int i = 0; i < count; i++) {
    struct sockaddr_in peer;
    socklen_t plen = sizeof peer;
    ssize_t n = recvfrom(fd, buf, sizeof buf, 0, (struct sockaddr *)&peer,
                         &plen);
    if (n < 0) return 3;
    if (sendto(fd, buf, (size_t)n, 0, (struct sockaddr *)&peer, plen) != n)
      return 4;
  }
  close(fd);
  printf("udpserver OK count=%d\n", count);
  return 0;
}

static int cmd_udpclient(const char *host, uint16_t port, int count,
                         int size) {
  int fd = socket(AF_INET, SOCK_DGRAM, 0);
  if (fd < 0) return 1;
  struct sockaddr_in dst;
  if (resolve(host, port, &dst) != 0) return 2;
  char *buf = malloc((size_t)size);
  char *rbuf = malloc((size_t)size);
  int64_t first_rtt = -1;
  for (int i = 0; i < count; i++) {
    memset(buf, 'a' + (i % 26), (size_t)size);
    int64_t t0 = now_ns();
    if (sendto(fd, buf, (size_t)size, 0, (struct sockaddr *)&dst,
               sizeof dst) != size)
      return 3;
    struct sockaddr_in peer;
    socklen_t plen = sizeof peer;
    ssize_t n = recvfrom(fd, rbuf, (size_t)size, 0, (struct sockaddr *)&peer,
                         &plen);
    if (n != size) return 4;
    if (memcmp(buf, rbuf, (size_t)size) != 0) return 5;
    if (first_rtt < 0) first_rtt = now_ns() - t0;
  }
  /* under the simulator the echo crosses 2 links with >= 1 ms total latency;
   * virtual RTT must be nonzero and sane */
  if (under_sim() && (first_rtt <= 0 || first_rtt > 10000000000LL)) return 6;
  printf("udpclient OK count=%d rtt_ns=%lld\n", count, (long long)first_rtt);
  close(fd);
  free(buf);
  free(rbuf);
  return 0;
}

/* -------------------------------------------------------------------- tcp */
static uint32_t pattern_sum(int64_t nbytes) {
  uint32_t sum = 0;
  for (int64_t i = 0; i < nbytes; i++) sum = sum * 31 + (uint32_t)(i & 0xFF);
  return sum;
}

static int cmd_tcpserver(uint16_t port, int64_t expect) {
  int lfd = socket(AF_INET, SOCK_STREAM, 0);
  if (lfd < 0) return 1;
  int one = 1;
  setsockopt(lfd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
  struct sockaddr_in sin;
  memset(&sin, 0, sizeof sin);
  sin.sin_family = AF_INET;
  sin.sin_addr.s_addr = htonl(INADDR_ANY);
  sin.sin_port = htons(port);
  if (bind(lfd, (struct sockaddr *)&sin, sizeof sin) != 0) return 2;
  if (listen(lfd, 8) != 0) return 3;
  struct sockaddr_in peer;
  socklen_t plen = sizeof peer;
  int fd = accept(lfd, (struct sockaddr *)&peer, &plen);
  if (fd < 0) return 4;
  char buf[65536];
  int64_t total = 0;
  uint32_t sum = 0;
  for (;;) {
    ssize_t n = recv(fd, buf, sizeof buf, 0);
    if (n < 0) return 5;
    if (n == 0) break;
    for (ssize_t i = 0; i < n; i++)
      sum = sum * 31 + (uint32_t)(unsigned char)buf[i];
    total += n;
  }
  if (total != expect) {
    fprintf(stderr, "tcpserver: got %lld want %lld\n", (long long)total,
            (long long)expect);
    return 6;
  }
  if (sum != pattern_sum(expect)) return 7;
  close(fd);
  close(lfd);
  printf("tcpserver OK bytes=%lld\n", (long long)total);
  return 0;
}

static int cmd_tcpclient(const char *host, uint16_t port, int64_t nbytes) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return 1;
  struct sockaddr_in dst;
  if (resolve(host, port, &dst) != 0) return 2;
  if (connect(fd, (struct sockaddr *)&dst, sizeof dst) != 0) {
    fprintf(stderr, "tcpclient: connect: %s\n", strerror(errno));
    return 3;
  }
  char buf[65536];
  int64_t sent = 0;
  while (sent < nbytes) {
    size_t chunk = sizeof buf;
    if ((int64_t)chunk > nbytes - sent) chunk = (size_t)(nbytes - sent);
    for (size_t i = 0; i < chunk; i++)
      buf[i] = (char)((sent + (int64_t)i) & 0xFF);
    ssize_t n = send(fd, buf, chunk, 0);
    if (n <= 0) {
      fprintf(stderr, "tcpclient: send: %s\n", strerror(errno));
      return 4;
    }
    sent += n;
  }
  close(fd);
  printf("tcpclient OK bytes=%lld\n", (long long)sent);
  return 0;
}

/* ------------------------------------------------------------------ epoll */
static int g_epoll_flags_extra = 0;   /* EPOLLET for the etserver twin */
static int cmd_epollserver(uint16_t port, int nclients) {
  int lfd = socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK, 0);
  if (lfd < 0) return 1;
  struct sockaddr_in sin;
  memset(&sin, 0, sizeof sin);
  sin.sin_family = AF_INET;
  sin.sin_addr.s_addr = htonl(INADDR_ANY);
  sin.sin_port = htons(port);
  if (bind(lfd, (struct sockaddr *)&sin, sizeof sin) != 0) return 2;
  if (listen(lfd, 16) != 0) return 3;
  int ep = epoll_create1(0);
  if (ep < 0) return 4;
  struct epoll_event ev;
  ev.events = EPOLLIN | g_epoll_flags_extra;
  ev.data.fd = lfd;
  if (epoll_ctl(ep, EPOLL_CTL_ADD, lfd, &ev) != 0) return 5;
  int done = 0, active = 0;
  char buf[65536];
  while (done < nclients) {
    struct epoll_event evs[32];
    int n = epoll_wait(ep, evs, 32, 30000);
    if (n < 0) return 6;
    if (n == 0) {
      fprintf(stderr, "epollserver: timeout with %d/%d done\n", done,
              nclients);
      return 7;
    }
    for (int i = 0; i < n; i++) {
      int fd = evs[i].data.fd;
      if (fd == lfd) {
        for (;;) {
          int cfd = accept4(lfd, NULL, NULL, SOCK_NONBLOCK);
          if (cfd < 0) break;
          struct epoll_event cev;
          cev.events = EPOLLIN | g_epoll_flags_extra;
          cev.data.fd = cfd;
          if (epoll_ctl(ep, EPOLL_CTL_ADD, cfd, &cev) != 0) return 8;
          active++;
        }
      } else {
        for (;;) {
          ssize_t r = recv(fd, buf, sizeof buf, 0);
          if (r > 0) {
            ssize_t off = 0;
            while (off < r) {
              ssize_t w = send(fd, buf + off, (size_t)(r - off), 0);
              if (w <= 0) break;
              off += w;
            }
          } else if (r == 0) {
            epoll_ctl(ep, EPOLL_CTL_DEL, fd, NULL);
            close(fd);
            done++;
            break;
          } else {
            if (errno == EAGAIN || errno == EWOULDBLOCK) break;
            return 9;
          }
        }
      }
    }
  }
  close(ep);
  close(lfd);
  printf("epollserver OK clients=%d\n", done);
  return 0;
}

static int echo_once_connected(int fd, const char *tag) {
  const char msg[] = "hello through the virtual network";
  if (send(fd, msg, sizeof msg, 0) != (ssize_t)sizeof msg) return 4;
  char rbuf[sizeof msg];
  size_t got = 0;
  while (got < sizeof msg) {
    ssize_t n = recv(fd, rbuf + got, sizeof msg - got, 0);
    if (n <= 0) {
      if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) continue;
      return 5;
    }
    got += (size_t)n;
  }
  if (memcmp(msg, rbuf, sizeof msg) != 0) return 6;
  printf("%s OK\n", tag);
  return 0;
}

static int cmd_pollclient(const char *host, uint16_t port) {
  int fd = socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK, 0);
  if (fd < 0) return 1;
  struct sockaddr_in dst;
  if (resolve(host, port, &dst) != 0) return 2;
  int r = connect(fd, (struct sockaddr *)&dst, sizeof dst);
  if (r != 0 && errno != EINPROGRESS) return 3;
  struct pollfd pfd = {fd, POLLOUT, 0};
  if (poll(&pfd, 1, 10000) != 1 || !(pfd.revents & POLLOUT)) return 7;
  int err = -1;
  socklen_t elen = sizeof err;
  if (getsockopt(fd, SOL_SOCKET, SO_ERROR, &err, &elen) != 0 || err != 0)
    return 8;
  /* wait readable via poll between send and recv */
  const char msg[] = "hello through the virtual network";
  if (send(fd, msg, sizeof msg, 0) != (ssize_t)sizeof msg) return 4;
  pfd.events = POLLIN;
  if (poll(&pfd, 1, 10000) != 1) return 9;
  char rbuf[sizeof msg];
  size_t got = 0;
  while (got < sizeof msg) {
    ssize_t n = recv(fd, rbuf + got, sizeof msg - got, 0);
    if (n <= 0) {
      if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
        if (poll(&pfd, 1, 10000) != 1) return 10;
        continue;
      }
      return 5;
    }
    got += (size_t)n;
  }
  if (memcmp(msg, rbuf, sizeof msg) != 0) return 6;
  close(fd);
  printf("pollclient OK\n");
  return 0;
}

static int cmd_selectclient(const char *host, uint16_t port) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return 1;
  struct sockaddr_in dst;
  if (resolve(host, port, &dst) != 0) return 2;
  if (connect(fd, (struct sockaddr *)&dst, sizeof dst) != 0) return 3;
  const char msg[] = "hello through the virtual network";
  if (send(fd, msg, sizeof msg, 0) != (ssize_t)sizeof msg) return 4;
  fd_set rfds;
  FD_ZERO(&rfds);
  FD_SET(fd, &rfds);
  struct timeval tv = {10, 0};
  int r = select(fd + 1, &rfds, NULL, NULL, &tv);
  if (r != 1 || !FD_ISSET(fd, &rfds)) return 7;
  char rbuf[sizeof msg];
  size_t got = 0;
  while (got < sizeof msg) {
    ssize_t n = recv(fd, rbuf + got, sizeof msg - got, 0);
    if (n <= 0) return 5;
    got += (size_t)n;
  }
  if (memcmp(msg, rbuf, sizeof msg) != 0) return 6;
  close(fd);
  printf("selectclient OK\n");
  return 0;
}

/* ----------------------------------------------------------------- random */
static int cmd_randcheck(void) {
  unsigned char a[16], b[16];
  if (getrandom(a, sizeof a, 0) != (ssize_t)sizeof a) return 1;
  int fd = open("/dev/urandom", O_RDONLY);
  if (fd < 0) return 2;
  if (read(fd, b, sizeof b) != (ssize_t)sizeof b) return 3;
  close(fd);
  printf("randcheck ");
  for (size_t i = 0; i < sizeof a; i++) printf("%02x", a[i]);
  printf(" ");
  for (size_t i = 0; i < sizeof b; i++) printf("%02x", b[i]);
  printf("\n");
  return 0;
}

static int cmd_hostname(const char *expected) {
  char name[256];
  if (gethostname(name, sizeof name) != 0) return 1;
  if (strcmp(name, expected) != 0) {
    fprintf(stderr, "hostname: got %s want %s\n", name, expected);
    return 2;
  }
  struct sockaddr_in self;
  if (resolve(name, 80, &self) != 0) return 3;
  printf("hostname OK %s\n", name);
  return 0;
}


/* -------------------------------------------------------- half-close ----- */
/* sumserver: read until EOF, reply with the total byte count (u64), close.
 * Pairs with halfclient to exercise shutdown(SHUT_WR). */
static int cmd_sumserver(uint16_t port) {
  int lfd = socket(AF_INET, SOCK_STREAM, 0);
  if (lfd < 0) return 1;
  struct sockaddr_in sin;
  memset(&sin, 0, sizeof sin);
  sin.sin_family = AF_INET;
  sin.sin_addr.s_addr = htonl(INADDR_ANY);
  sin.sin_port = htons(port);
  if (bind(lfd, (struct sockaddr *)&sin, sizeof sin) != 0) return 2;
  if (listen(lfd, 4) != 0) return 3;
  int fd = accept(lfd, NULL, NULL);
  if (fd < 0) return 4;
  char buf[65536];
  uint64_t total = 0;
  for (;;) {
    ssize_t n = recv(fd, buf, sizeof buf, 0);
    if (n < 0) return 5;
    if (n == 0) break; /* client half-closed */
    total += (uint64_t)n;
  }
  /* our direction is still open: send the tally back */
  if (send(fd, &total, sizeof total, 0) != (ssize_t)sizeof total) return 6;
  close(fd);
  close(lfd);
  printf("sumserver OK total=%llu\n", (unsigned long long)total);
  return 0;
}

static int cmd_halfclient(const char *host, uint16_t port, int64_t nbytes) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return 1;
  struct sockaddr_in dst;
  if (resolve(host, port, &dst) != 0) return 2;
  if (connect(fd, (struct sockaddr *)&dst, sizeof dst) != 0) return 3;
  char buf[4096];
  memset(buf, 'z', sizeof buf);
  int64_t sent = 0;
  while (sent < nbytes) {
    size_t chunk = sizeof buf;
    if ((int64_t)chunk > nbytes - sent) chunk = (size_t)(nbytes - sent);
    ssize_t n = send(fd, buf, chunk, 0);
    if (n <= 0) return 4;
    sent += n;
  }
  if (shutdown(fd, SHUT_WR) != 0) return 5; /* half-close: FIN, keep reading */
  uint64_t total = 0;
  size_t got = 0;
  while (got < sizeof total) {
    ssize_t n = recv(fd, (char *)&total + got, sizeof total - got, 0);
    if (n <= 0) return 6;
    got += (size_t)n;
  }
  if ((int64_t)total != nbytes) {
    fprintf(stderr, "halfclient: server counted %llu, sent %lld\n",
            (unsigned long long)total, (long long)nbytes);
    return 7;
  }
  close(fd);
  printf("halfclient OK bytes=%lld\n", (long long)nbytes);
  return 0;
}

/* ---- pthread scenarios (routed to the shim's cooperative green threads
 * under simulation, to real pthreads natively — dual execution proves the
 * cooperative semantics match) ---- */
#include <pthread.h>
#include <signal.h>
#include <sys/utsname.h>
#include <ifaddrs.h>

static pthread_mutex_t th_lock = PTHREAD_MUTEX_INITIALIZER;
static pthread_cond_t th_cond = PTHREAD_COND_INITIALIZER;
static long th_counter = 0;
static int th_turn = 0;   /* strict alternation: cond enforces the order */

struct th_arg { int id; int iters; };

static void *th_worker(void *argp) {
  struct th_arg *a = (struct th_arg *)argp;
  for (int i = 0; i < a->iters; i++) {
    pthread_mutex_lock(&th_lock);
    /* strict alternation via condvar: worker id must match the turn */
    while (th_turn != a->id) pthread_cond_wait(&th_cond, &th_lock);
    th_counter++;
    th_turn = 1 - th_turn;
    pthread_cond_broadcast(&th_cond);
    pthread_mutex_unlock(&th_lock);
    /* a virtual-time pause so interleaving crosses sleep parks too */
    usleep(1000);
  }
  return (void *)(long)(a->id + 100);
}

static int cmd_threads(void) {
  int iters = 50;
  pthread_t t1, t2;
  struct th_arg a1 = {0, iters}, a2 = {1, iters};
  int64_t t0 = now_ns();
  if (pthread_create(&t1, NULL, th_worker, &a1) != 0) return 1;
  if (pthread_create(&t2, NULL, th_worker, &a2) != 0) return 1;
  void *r1 = NULL, *r2 = NULL;
  if (pthread_join(t1, &r1) != 0 || pthread_join(t2, &r2) != 0) return 2;
  if ((long)r1 != 100 || (long)r2 != 101) return 3;
  if (th_counter != 2L * iters) return 4;
  int64_t elapsed = now_ns() - t0;
  /* each worker usleeps 1ms x iters; interleaved they cover ~iters ms of
   * virtual time at least (loose bound holds natively too) */
  if (elapsed < (int64_t)iters * 1000000LL / 2) {
    printf("threads: clock advanced only %lld ns\n", (long long)elapsed);
    return 6;
  }
  printf("threads OK counter=%ld elapsed_ms=%lld\n", th_counter,
         (long long)(elapsed / 1000000));
  return 0;
}

/* one thread serves a TCP connection while the main thread sleeps in
 * virtual time — proves fd parks and sleep parks coexist */
static void *th_tcpserver(void *argp) {
  long port = (long)argp;
  return (void *)(long)cmd_tcpserver((uint16_t)port, 50000);
}

static int cmd_mtserver(uint16_t port) {
  pthread_t t;
  if (pthread_create(&t, NULL, th_tcpserver, (void *)(long)port) != 0)
    return 1;
  for (int i = 0; i < 10; i++) usleep(200000);   /* 2 virtual seconds */
  void *rv = NULL;
  if (pthread_join(t, &rv) != 0) return 2;
  return (int)(long)rv;
}

static int cmd_miscsys(const char *expected_host) {
  struct utsname un;
  if (uname(&un) != 0) return 1;
  if (strcmp(un.sysname, "Linux") != 0) return 2;
  if (under_sim() && strcmp(un.nodename, expected_host) != 0) {
    printf("uname nodename %s != %s\n", un.nodename, expected_host);
    return 3;
  }
  if (getpid() <= 0) return 4;
  if (under_sim()) {
    /* fork/exec are ENOSYS stubs inside the simulation */
    if (fork() != -1 || errno != ENOSYS) return 5;
    char *const eargv[] = {(char *)"/bin/true", NULL};
    if (execv("/bin/true", eargv) != -1 || errno != ENOSYS) return 6;
  }
  if (signal(SIGUSR1, SIG_IGN) == SIG_ERR) return 7;
  struct sigaction sa, old;
  memset(&sa, 0, sizeof sa);
  sa.sa_handler = SIG_DFL;
  if (sigaction(SIGUSR2, &sa, &old) != 0) return 8;
  struct ifaddrs *ifa = NULL;
  if (getifaddrs(&ifa) != 0 || ifa == NULL) return 9;
  int saw_lo = 0, saw_eth = 0;
  for (struct ifaddrs *p = ifa; p; p = p->ifa_next) {
    if (p->ifa_name && !strcmp(p->ifa_name, "lo")) saw_lo = 1;
    if (p->ifa_name && (!strncmp(p->ifa_name, "eth", 3) ||
                        !strncmp(p->ifa_name, "en", 2) ||
                        !strncmp(p->ifa_name, "wl", 2)))
      saw_eth = 1;
  }
  freeifaddrs(ifa);
  if (!saw_lo) return 10;
  if (under_sim() && !saw_eth) return 11;
  srand(42);
  int r1 = rand(), r2 = rand();
  if (r1 < 0 || r2 < 0) return 12;
  FILE *f = fopen("/dev/urandom", "rb");
  if (!f) return 13;
  unsigned char buf[16] = {0}, zero[16] = {0};
  if (fread(buf, 1, sizeof buf, f) != sizeof buf) { fclose(f); return 14; }
  fclose(f);
  if (memcmp(buf, zero, sizeof buf) == 0) return 15;
  printf("miscsys OK pid=%d node=%s\n", (int)getpid(), un.nodename);
  return 0;
}

/* timerfd: periodic expirations under the virtual clock (reference:
 * src/test/timerfd) */
#include <sys/timerfd.h>

static int cmd_timercheck(void) {
  int tfd = timerfd_create(CLOCK_MONOTONIC, 0);
  if (tfd < 0) return 1;
  struct itimerspec its;
  memset(&its, 0, sizeof its);
  its.it_value.tv_nsec = 50 * 1000 * 1000;      /* first: 50 ms */
  its.it_interval.tv_nsec = 100 * 1000 * 1000;  /* then: 100 ms */
  if (timerfd_settime(tfd, 0, &its, NULL) != 0) return 2;
  int64_t t0 = now_ns();
  uint64_t expirations = 0;
  if (read(tfd, &expirations, sizeof expirations) != sizeof expirations)
    return 3;
  if (expirations != 1) return 4;
  int64_t waited = now_ns() - t0;
  if (under_sim() && waited != 50 * 1000 * 1000LL) {
    fprintf(stderr, "timercheck: first expiry at %lld ns\n",
            (long long)waited);
    return 5;
  }
  /* sleep past several periods: the next read reports them batched */
  usleep(350 * 1000);
  if (read(tfd, &expirations, sizeof expirations) != sizeof expirations)
    return 6;
  if (under_sim() && expirations != 3) {
    fprintf(stderr, "timercheck: batched expirations %llu != 3\n",
            (unsigned long long)expirations);
    return 7;
  }
  if (!under_sim() && expirations < 2) return 7;
  /* poll readiness: not readable right after a read consumed them */
  struct pollfd p = {tfd, POLLIN, 0};
  if (poll(&p, 1, 0) != 0) return 8;
  close(tfd);
  printf("timercheck OK\n");
  return 0;
}

/* connected-UDP client: connect(2) on a datagram socket then plain
 * send/recv (the resolver pattern; reference: src/test/udp) */
static int cmd_udpconnclient(const char *host, uint16_t port, int count,
                             int size) {
  struct sockaddr_in sin;
  if (resolve(host, port, &sin) != 0) return 1;
  int fd = socket(AF_INET, SOCK_DGRAM, 0);
  if (fd < 0) return 2;
  if (connect(fd, (struct sockaddr *)&sin, sizeof sin) != 0) return 3;
  char *buf = malloc((size_t)size);
  char *echo = malloc((size_t)size);
  for (int i = 0; i < count; i++) {
    memset(buf, 'a' + (i % 26), (size_t)size);
    if (send(fd, buf, (size_t)size, 0) != (ssize_t)size) return 4;
    ssize_t r = recv(fd, echo, (size_t)size, 0);
    if (r != (ssize_t)size || memcmp(buf, echo, (size_t)size) != 0) return 5;
  }
  /* getpeername reflects the connect */
  struct sockaddr_in out;
  socklen_t olen = sizeof out;
  if (getpeername(fd, (struct sockaddr *)&out, &olen) != 0) return 6;
  if (out.sin_port != sin.sin_port) return 7;
  close(fd);
  free(buf);
  free(echo);
  printf("udpconnclient OK\n");
  return 0;
}

/* socketpair + pipe self-messaging (reference: src/test/unistd pipes;
 * real Tor signals its event loop over a socketpair) */
static int cmd_selfpipe(void) {
  int sp[2];
  if (socketpair(AF_UNIX, SOCK_STREAM, 0, sp) != 0) return 1;
  const char ping[] = "ping-through-pair";
  if (write(sp[0], ping, sizeof ping) != (ssize_t)sizeof ping) return 2;
  char buf[64] = {0};
  if (read(sp[1], buf, sizeof buf) != (ssize_t)sizeof ping) return 3;
  if (strcmp(buf, ping) != 0) return 4;
  /* poll readiness across the pair */
  if (write(sp[1], "x", 1) != 1) return 5;
  struct pollfd p = {sp[0], POLLIN, 0};
  if (poll(&p, 1, 1000) != 1 || !(p.revents & POLLIN)) return 6;
  close(sp[0]);
  close(sp[1]);
  int pfd[2];
  if (pipe(pfd) != 0) return 7;
  if (write(pfd[1], "z", 1) != 1) return 8;
  if (read(pfd[0], buf, 1) != 1 || buf[0] != 'z') return 9;
  close(pfd[0]);
  close(pfd[1]);
  printf("selfpipe OK\n");
  return 0;
}

/* sockbuf/bind/name-query corner cases (reference: src/test/sockbuf,
 * src/test/bind) */
static int cmd_sockmisc(void) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return 1;
  /* setsockopt buffer sizes are honored (readable back) */
  int sz = 262144;
  if (setsockopt(fd, SOL_SOCKET, SO_RCVBUF, &sz, sizeof sz) != 0) return 2;
  int got = 0;
  socklen_t glen = sizeof got;
  if (getsockopt(fd, SOL_SOCKET, SO_RCVBUF, &got, &glen) != 0) return 3;
  if (got < 4096) return 4;   /* kernel may round, must not vanish */
  /* bind + EADDRINUSE on a second bind to the same port */
  struct sockaddr_in sin;
  memset(&sin, 0, sizeof sin);
  sin.sin_family = AF_INET;
  sin.sin_addr.s_addr = htonl(INADDR_ANY);
  sin.sin_port = htons(39123);
  if (bind(fd, (struct sockaddr *)&sin, sizeof sin) != 0) return 5;
  int fd2 = socket(AF_INET, SOCK_STREAM, 0);
  if (bind(fd2, (struct sockaddr *)&sin, sizeof sin) == 0) return 6;
  if (errno != EADDRINUSE) return 7;
  /* getsockname reflects the binding */
  struct sockaddr_in out;
  socklen_t olen = sizeof out;
  if (getsockname(fd, (struct sockaddr *)&out, &olen) != 0) return 8;
  if (ntohs(out.sin_port) != 39123) return 9;
  /* getpeername on an unconnected socket is ENOTCONN */
  olen = sizeof out;
  if (getpeername(fd, (struct sockaddr *)&out, &olen) == 0) return 10;
  if (errno != ENOTCONN) return 11;
  close(fd2);
  close(fd);
  printf("sockmisc OK\n");
  return 0;
}

/* ------------------------------------------------------------- files ----
 * Absolute-path per-host file namespace (shim_files.cc): mkdir chain,
 * fopen-write, stat, rename, open-read-back, access.  Under the simulator
 * every absolute path below lands in <host-data-dir>/vfs/...; natively it
 * uses the real fs (both must succeed — the dual-execution property). */
#include <sys/stat.h>
static int cmd_files(const char *tag) {
  /* under the simulator the directory is fixed (tests assert the vfs
   * layout); natively it is keyed by pid so concurrent runs on a shared
   * machine never race on the same real /var/tmp names */
  char dir[128];
  if (under_sim())
    snprintf(dir, sizeof dir, "/var/tmp/shadowfiles");
  else
    snprintf(dir, sizeof dir, "/var/tmp/shadowfiles.%ld", (long)getpid());
  if (mkdir("/var", 0755) != 0 && errno != EEXIST) return 1;
  if (mkdir("/var/tmp", 0755) != 0 && errno != EEXIST) return 2;
  if (mkdir(dir, 0755) != 0 && errno != EEXIST) return 3;
  char path[256], path2[256], want[160];
  snprintf(path, sizeof path, "%s/%s.tmp", dir, tag);
  snprintf(path2, sizeof path2, "%s/%s.dat", dir, tag);
  snprintf(want, sizeof want, "hello-%s", tag);
  FILE *f = fopen(path, "w");
  if (!f) return 4;
  if (fputs(want, f) < 0) return 5;
  fclose(f);
  struct stat st;
  if (stat(path, &st) != 0) return 6;
  if (st.st_size != (off_t)strlen(want)) return 7;
  if (rename(path, path2) != 0) return 8;
  if (access(path, F_OK) == 0) return 9;      /* old name must be gone */
  int fd = open(path2, O_RDONLY);
  if (fd < 0) return 10;
  char buf[160];
  ssize_t n = read(fd, buf, sizeof buf - 1);
  close(fd);
  if (n != (ssize_t)strlen(want)) return 11;
  buf[n] = '\0';
  if (strcmp(buf, want) != 0) return 12;
  /* chdir through the namespace, then a RELATIVE write must land in the
   * same directory an absolute path names (cwd/namespace consistency) */
  if (chdir(dir) != 0) return 15;
  char relname[160], absname[320];
  snprintf(relname, sizeof relname, "%s.rel", tag);
  snprintf(absname, sizeof absname, "%s/%s.rel", dir, tag);
  FILE *rf = fopen(relname, "w");
  if (!rf) return 16;
  fputs(tag, rf);
  fclose(rf);
  if (stat(absname, &st) != 0) return 17;     /* absolute sees relative */
  if (st.st_size != (off_t)strlen(tag)) return 18;
  /* getcwd must compose consistently with the namespace */
  char cwd[1024];
  if (!getcwd(cwd, sizeof cwd)) return 19;
  char composed[1400];
  snprintf(composed, sizeof composed, "%s/%s", cwd, relname);
  if (access(composed, F_OK) != 0) return 20;
  /* symlink/readlink/link through the namespace: the stored target is
   * vfs-resolved on create and must reverse-map to the app-visible path
   * on readlink; traversal and hard links stay inside the namespace */
  char lnk[340], hard[340], tbuf[512];
  snprintf(lnk, sizeof lnk, "%s/%s.lnk", dir, tag);
  snprintf(hard, sizeof hard, "%s/%s.hard", dir, tag);
  if (symlink(path2, lnk) != 0) return 21;
  ssize_t ln = readlink(lnk, tbuf, sizeof tbuf - 1);
  if (ln <= 0) return 22;
  tbuf[ln] = '\0';
  if (strcmp(tbuf, path2) != 0) return 23;   /* app-visible target */
  if (stat(lnk, &st) != 0) return 24;        /* follows to the file */
  if (st.st_size != (off_t)strlen(want)) return 25;
  struct stat sl;
  if (lstat(lnk, &sl) != 0 || !S_ISLNK(sl.st_mode)) return 30;
  if (sl.st_size != (off_t)ln) return 31;    /* lstat == readlink length */
  char tbuf2[512];
  ssize_t ln2 = readlinkat(AT_FDCWD, lnk, tbuf2, sizeof tbuf2 - 1);
  if (ln2 != ln || memcmp(tbuf, tbuf2, (size_t)ln) != 0) return 32;
  if (link(path2, hard) != 0) return 26;
  struct stat sh;
  if (stat(hard, &sh) != 0 || sh.st_size != (off_t)strlen(want)) return 27;
  if (unlink(path2) != 0) return 28;         /* hard link keeps the data */
  if (stat(hard, &sh) != 0 || sh.st_size != (off_t)strlen(want)) return 29;
  if (under_sim()) {
    /* deep creating open: the namespace makes parent dirs on demand */
    char deep[256];
    snprintf(deep, sizeof deep, "/srv/%s/a/b/deep.txt", tag);
    int dfd = open(deep, O_CREAT | O_WRONLY, 0644);
    if (dfd < 0) return 13;
    if (write(dfd, tag, strlen(tag)) != (ssize_t)strlen(tag)) return 14;
    close(dfd);
  } else {
    /* native run: clean up the real fs */
    unlink(absname);
    unlink(lnk);
    unlink(hard);
    rmdir(dir);
  }
  printf("files OK tag=%s\n", tag);
  return 0;
}

/* xattr family through the namespace (ENOTSUP on the backing fs => 99,
 * callers skip) */
#include <sys/xattr.h>
static int xattr_done(const char *file, const char *dir, int rc) {
  /* single exit path: native runs clean the real fs even on the
   * ENOTSUP-skip and error returns */
  if (!under_sim()) {
    unlink(file);
    rmdir(dir);
  }
  return rc;
}

static int cmd_xattr(const char *tag) {
  char dir[160], file[224], val[64];
  snprintf(dir, sizeof dir, "/var/tmp/xattrcheck-%s", tag);
  snprintf(file, sizeof file, "%s/f", dir);
  mkdir("/var", 0755);
  mkdir("/var/tmp", 0755);
  if (mkdir(dir, 0755) != 0 && errno != EEXIST) return 1;
  int fd = open(file, O_CREAT | O_WRONLY, 0644);
  if (fd < 0) return xattr_done(file, dir, 2);
  close(fd);
  if (setxattr(file, "user.shadow", tag, strlen(tag), 0) != 0)
    return xattr_done(file, dir, errno == ENOTSUP ? 99 : 3);
  ssize_t n = getxattr(file, "user.shadow", val, sizeof val);
  if (n != (ssize_t)strlen(tag) || memcmp(val, tag, (size_t)n) != 0)
    return xattr_done(file, dir, 4);
  char names[256];
  ssize_t ln = listxattr(file, names, sizeof names);
  if (ln <= 0 || !memmem(names, (size_t)ln, "user.shadow", 11))
    return xattr_done(file, dir, 5);
  if (removexattr(file, "user.shadow") != 0) return xattr_done(file, dir, 6);
  if (getxattr(file, "user.shadow", val, sizeof val) >= 0)
    return xattr_done(file, dir, 7);
  printf("xattr OK tag=%s\n", tag);
  return xattr_done(file, dir, 0);
}

/* ----------------------------------------------------- torserver/client --
 * The Tor-shaped dual-execution pair: everything a Tor-class daemon leans
 * on at once — a multi-threaded epoll event loop whose epoll set contains
 * a LISTEN socket, a SIGNALFD (SIGTERM shutdown), an EVENTFD (worker pool
 * completion wakeups), and a TIMERFD (heartbeat) — plus a pthread worker
 * pool consuming accepted connections from a mutex+condvar queue and
 * echoing 512-byte cells.  The client runs a thread pool of sequential
 * streams and finally raises the server's shutdown via a QUIT cell, which
 * the handling WORKER thread converts to raise(SIGTERM) -> the signal
 * lands in the main loop's signalfd.  Exit 0 is the oracle in both
 * executions (reference: src/test/pthreads + src/test/signal + the epoll
 * matrix, run together as one program the way tor itself would). */

#include <pthread.h>
#include <signal.h>
#include <sys/eventfd.h>
#include <sys/signalfd.h>
#include <sys/timerfd.h>

#define TOR_CELL 512
#define TOR_DATA 1
#define TOR_QUIT 2

static struct {
  int fds[256];
  int head, tail, stop;
  pthread_mutex_t mu;
  pthread_cond_t cv;
  int efd;
  long served;      /* completed connections (mu-protected) */
} g_pool;

/* the daemon's "consensus": version/checksum pair guarded by a rwlock the
 * way real tor's tor_rwlock guards its routerlist — workers HOLD the read
 * lock across cell echoes (which park on fd I/O), the main loop write-locks
 * on every heartbeat tick, so the lock is genuinely contended across parks */
static pthread_rwlock_t g_cons_lock = PTHREAD_RWLOCK_INITIALIZER;
static struct { long version; long checksum; } g_cons;
static long g_cons_reads = 0;   /* mu-protected tally */

static void *tor_worker(void *arg) {
  (void)arg;
  char cell[TOR_CELL];
  for (;;) {
    pthread_mutex_lock(&g_pool.mu);
    while (g_pool.head == g_pool.tail && !g_pool.stop)
      pthread_cond_wait(&g_pool.cv, &g_pool.mu);
    if (g_pool.head == g_pool.tail && g_pool.stop) {
      pthread_mutex_unlock(&g_pool.mu);
      return NULL;
    }
    int fd = g_pool.fds[g_pool.head % 256];
    g_pool.head++;
    pthread_mutex_unlock(&g_pool.mu);

    int quit = 0, broken = 0;
    for (;;) {
      /* daemon-realistic read timeout via ppoll (preload ppoll surface);
       * a timeout means the transfer HUNG — that must fail the served
       * audit, not silently count as a completed connection */
      struct pollfd pf = {fd, POLLIN, 0};
      struct timespec ts = {25, 0};
      if (ppoll(&pf, 1, &ts, NULL) <= 0) { broken = 1; goto conn_done; }
      size_t got = 0;
      while (got < TOR_CELL) {
        ssize_t r = recv(fd, cell + got, TOR_CELL - got, 0);
        if (r <= 0) goto conn_done;
        got += (size_t)r;
      }
      uint32_t type;
      memcpy(&type, cell, 4);
      if (type == TOR_QUIT) { quit = 1; goto conn_done; }
      /* consult the consensus under rdlock and HOLD it across the echo
       * (the send can park): a torn version/checksum pair would mean the
       * rwlock failed to exclude the heartbeat's write */
      pthread_rwlock_rdlock(&g_cons_lock);
      long v0 = g_cons.version, c0 = g_cons.checksum;
      size_t sent = 0;          /* echo the cell (relay hop) */
      while (sent < TOR_CELL) {
        ssize_t w = send(fd, cell + sent, TOR_CELL - sent, 0);
        if (w <= 0) break;
        sent += (size_t)w;
      }
      long v1 = g_cons.version, c1 = g_cons.checksum;
      pthread_rwlock_unlock(&g_cons_lock);
      if (c0 != v0 * 7 || v1 != v0 || c1 != c0) broken = 1;
      pthread_mutex_lock(&g_pool.mu);
      g_cons_reads++;
      pthread_mutex_unlock(&g_pool.mu);
      if (sent < TOR_CELL || broken) goto conn_done;
    }
  conn_done:
    close(fd);
    pthread_mutex_lock(&g_pool.mu);
    g_pool.served += broken ? 0 : 1;   /* a torn read fails the audit */
    pthread_mutex_unlock(&g_pool.mu);
    uint64_t one = 1;           /* wake the event loop */
    if (write(g_pool.efd, &one, 8) != 8) return NULL;
    /* worker-thread shutdown request.  Process-directed kill, NOT raise():
     * raise targets the calling THREAD, and a thread-pending signal never
     * reaches a signalfd (real-kernel semantics; the sim routes both the
     * same way, so the native leg is the stricter oracle here). */
    if (quit) kill(getpid(), SIGTERM);
  }
}

static int cmd_torserver(uint16_t port, int nworkers, long expect_conns) {
  memset(&g_pool, 0, sizeof g_pool);
  pthread_mutex_init(&g_pool.mu, NULL);
  pthread_cond_init(&g_pool.cv, NULL);

  sigset_t mask;
  sigemptyset(&mask);
  sigaddset(&mask, SIGTERM);
  if (sigprocmask(SIG_BLOCK, &mask, NULL) != 0) return 10;
  int sfd = signalfd(-1, &mask, SFD_NONBLOCK);
  if (sfd < 0) return 11;
  g_pool.efd = eventfd(0, EFD_NONBLOCK);
  if (g_pool.efd < 0) return 12;
  int tfd = timerfd_create(CLOCK_MONOTONIC, TFD_NONBLOCK);
  if (tfd < 0) return 13;
  /* heartbeat: first expiry 1 ms (so even a fast native run observes a
   * tick before shutdown), then every 200 ms */
  struct itimerspec its = {{0, 200000000}, {0, 1000000}};
  if (timerfd_settime(tfd, 0, &its, NULL) != 0) return 14;

  int lfd = socket(AF_INET, SOCK_STREAM, 0);
  struct sockaddr_in sin;
  memset(&sin, 0, sizeof sin);
  sin.sin_family = AF_INET;
  sin.sin_addr.s_addr = htonl(INADDR_ANY);
  sin.sin_port = htons(port);
  int one = 1;
  setsockopt(lfd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
  if (bind(lfd, (struct sockaddr *)&sin, sizeof sin) != 0) return 15;
  if (listen(lfd, 64) != 0) return 16;
  fcntl(lfd, F_SETFL, O_NONBLOCK);   /* drain-accept loop needs EAGAIN */

  pthread_t th[32];
  if (nworkers > 32) nworkers = 32;
  for (int i = 0; i < nworkers; i++)
    if (pthread_create(&th[i], NULL, tor_worker, NULL) != 0) return 17;

  int ep = epoll_create1(0);
  struct epoll_event ev, evs[16];
  ev.events = EPOLLIN; ev.data.fd = lfd;
  epoll_ctl(ep, EPOLL_CTL_ADD, lfd, &ev);
  ev.data.fd = sfd; epoll_ctl(ep, EPOLL_CTL_ADD, sfd, &ev);
  ev.data.fd = g_pool.efd; epoll_ctl(ep, EPOLL_CTL_ADD, g_pool.efd, &ev);
  ev.data.fd = tfd; epoll_ctl(ep, EPOLL_CTL_ADD, tfd, &ev);

  long wakeups = 0, ticks = 0;
  int term = 0;
  while (!term) {
    int n = epoll_wait(ep, evs, 16, 30000);
    if (n <= 0) return 18;
    for (int i = 0; i < n; i++) {
      int fd = evs[i].data.fd;
      if (fd == lfd) {
        int cfd;
        while ((cfd = accept(lfd, NULL, NULL)) >= 0) {
          pthread_mutex_lock(&g_pool.mu);
          g_pool.fds[g_pool.tail % 256] = cfd;
          g_pool.tail++;
          pthread_cond_signal(&g_pool.cv);
          pthread_mutex_unlock(&g_pool.mu);
        }
      } else if (fd == g_pool.efd) {
        uint64_t val;
        if (read(g_pool.efd, &val, 8) == 8) wakeups += (long)val;
      } else if (fd == tfd) {
        uint64_t exp;
        if (read(tfd, &exp, 8) == 8) ticks += (long)exp;
        /* heartbeat publishes a new consensus under the WRITE lock while
         * workers may be holding read locks across parked echoes */
        pthread_rwlock_wrlock(&g_cons_lock);
        g_cons.version++;
        g_cons.checksum = g_cons.version * 7;
        pthread_rwlock_unlock(&g_cons_lock);
      } else if (fd == sfd) {
        struct signalfd_siginfo si;
        if (read(sfd, &si, sizeof si) != sizeof si) return 19;
        if (si.ssi_signo != SIGTERM) return 20;
        term = 1;
      }
    }
  }
  /* graceful shutdown: stop the pool, join, audit */
  pthread_mutex_lock(&g_pool.mu);
  g_pool.stop = 1;
  pthread_cond_broadcast(&g_pool.cv);
  pthread_mutex_unlock(&g_pool.mu);
  for (int i = 0; i < nworkers; i++) pthread_join(th[i], NULL);
  if (g_pool.served < expect_conns + 1) return 21;  /* +1 = the QUIT conn */
  if (wakeups < expect_conns) return 22;
  if (ticks < 1) return 23;
  /* the rwlock audit only means something if reads actually happened:
   * every data connection consults the consensus at least once per cell */
  if (g_cons_reads < expect_conns) return 24;
  return 0;
}

static int tor_send_cell(int fd, uint32_t type, uint32_t seq) {
  char cell[TOR_CELL];
  memset(cell, 0, sizeof cell);
  memcpy(cell, &type, 4);
  memcpy(cell + 4, &seq, 4);
  memset(cell + 8, (int)('a' + (seq % 26)), TOR_CELL - 8);
  size_t sent = 0;
  while (sent < TOR_CELL) {
    ssize_t w = send(fd, cell + sent, TOR_CELL - sent, 0);
    if (w <= 0) return -1;
    sent += (size_t)w;
  }
  return 0;
}

static struct {
  struct sockaddr_in dst;
  int streams, cells;
  int failed;
} g_cli;

static void *tor_client_thread(void *arg) {
  (void)arg;
  char cell[TOR_CELL];
  for (int s = 0; s < g_cli.streams; s++) {
    int fd = socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0 || connect(fd, (struct sockaddr *)&g_cli.dst,
                          sizeof g_cli.dst) != 0) {
      g_cli.failed = 1;
      if (fd >= 0) close(fd);
      return NULL;
    }
    for (int c = 0; c < g_cli.cells; c++) {
      if (tor_send_cell(fd, TOR_DATA, (uint32_t)c) != 0) { g_cli.failed = 1; break; }
      size_t got = 0;
      while (got < TOR_CELL) {
        ssize_t r = recv(fd, cell + got, TOR_CELL - got, 0);
        if (r <= 0) { g_cli.failed = 1; break; }
        got += (size_t)r;
      }
      if (got != TOR_CELL) break;
      uint32_t seq;
      memcpy(&seq, cell + 4, 4);
      if (seq != (uint32_t)c || cell[8] != (char)('a' + (c % 26))) {
        g_cli.failed = 1;
        break;
      }
    }
    close(fd);
    if (g_cli.failed) return NULL;
  }
  return NULL;
}

static int cmd_torclient(const char *host, uint16_t port, int nthreads,
                         int streams, int cells) {
  memset(&g_cli, 0, sizeof g_cli);
  if (resolve(host, port, &g_cli.dst) != 0) return 30;
  g_cli.streams = streams;
  g_cli.cells = cells;
  pthread_t th[32];
  if (nthreads > 32) nthreads = 32;
  for (int i = 0; i < nthreads; i++)
    if (pthread_create(&th[i], NULL, tor_client_thread, NULL) != 0) return 31;
  for (int i = 0; i < nthreads; i++) pthread_join(th[i], NULL);
  if (g_cli.failed) return 32;
  /* shut the server down */
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0 || connect(fd, (struct sockaddr *)&g_cli.dst,
                        sizeof g_cli.dst) != 0) return 33;
  if (tor_send_cell(fd, TOR_QUIT, 0) != 0) return 34;
  close(fd);
  return 0;
}

/* eventfd kernel-semantics corners: semaphore mode decrements, counter
 * mode resets, the all-ones write is EINVAL, and reads at zero are EAGAIN
 * (nonblocking).  Same checks native and in-sim. */
static int cmd_efdsem(void) {
  int efd = eventfd(3, EFD_SEMAPHORE | EFD_NONBLOCK);
  if (efd < 0) return 40;
  uint64_t v;
  for (int i = 0; i < 3; i++) {
    if (read(efd, &v, 8) != 8 || v != 1) return 41;  /* semaphore: -1 each */
  }
  if (read(efd, &v, 8) != -1 || errno != EAGAIN) return 42;
  v = 0xFFFFFFFFFFFFFFFFull;                         /* never writable */
  if (write(efd, &v, 8) != -1 || errno != EINVAL) return 43;
  v = 2;
  if (write(efd, &v, 8) != 8) return 44;
  if (read(efd, &v, 8) != 8 || v != 1) return 45;
  close(efd);
  int cfd = eventfd(0, EFD_NONBLOCK);                /* counter mode */
  if (cfd < 0) return 46;
  v = 5;
  if (write(cfd, &v, 8) != 8) return 47;
  v = 7;
  if (write(cfd, &v, 8) != 8) return 48;
  if (read(cfd, &v, 8) != 8 || v != 12) return 49;   /* read resets */
  if (read(cfd, &v, 8) != -1 || errno != EAGAIN) return 50;
  close(cfd);
  return 0;
}

/* self-signal delivery: an UNBLOCKED signal with a handler runs the
 * handler (both plain and SA_SIGINFO arity) and execution continues; with
 * SIG_DFL on a fatal signal the process dies (the caller checks the death
 * separately via the sigdfl scenario). */
static volatile int g_plain_hits, g_info_hits, g_info_signo;

static void plain_handler(int sig) { g_plain_hits += (sig == SIGUSR1); }

static void info_handler(int sig, siginfo_t *si, void *ctx) {
  (void)ctx;
  g_info_hits++;
  g_info_signo = si ? si->si_signo : -1;
  (void)sig;
}

static int cmd_sighandler(void) {
  /* dispositions/masks survive exec: start from a known-pristine state so
   * an ignoring/masking test runner can't produce spurious failures */
  sigset_t none;
  sigemptyset(&none);
  if (sigprocmask(SIG_SETMASK, &none, NULL) != 0) return 59;
  if (signal(SIGUSR1, plain_handler) == SIG_ERR) return 60;
  if (kill(getpid(), SIGUSR1) != 0) return 61;
  if (g_plain_hits != 1) return 62;
  struct sigaction sa;
  memset(&sa, 0, sizeof sa);
  sa.sa_sigaction = info_handler;
  sa.sa_flags = SA_SIGINFO;
  if (sigaction(SIGUSR2, &sa, NULL) != 0) return 63;
  if (raise(SIGUSR2) != 0) return 65;
  if (g_info_hits != 1 || g_info_signo != SIGUSR2) return 66;
  /* blocked signal stays pending; unblock delivers it */
  sigset_t m;
  sigemptyset(&m);
  sigaddset(&m, SIGUSR1);
  if (sigprocmask(SIG_BLOCK, &m, NULL) != 0) return 67;
  if (kill(getpid(), SIGUSR1) != 0) return 68;
  if (g_plain_hits != 1) return 69;          /* not delivered while blocked */
  if (sigprocmask(SIG_UNBLOCK, &m, NULL) != 0) return 70;
  if (g_plain_hits != 2) return 71;          /* released on unblock */
  return 0;
}

static int cmd_sigdfl(void) {
  /* default action: this must TERMINATE the process (caller checks).
   * Reset the inherited disposition/mask first — SIG_IGN survives exec. */
  sigset_t none;
  sigemptyset(&none);
  sigprocmask(SIG_SETMASK, &none, NULL);
  signal(SIGTERM, SIG_DFL);
  kill(getpid(), SIGTERM);
  return 0;                                  /* reached = failure */
}

/* ---- rwlock / barrier / spinlock / once under contention (dual-exec: the
 * cooperative shim must survive exactly the case that would deadlock a
 * naive green-thread layer — a writer arriving while readers HOLD the lock
 * across a virtual-time sleep, and four threads meeting at a barrier).
 * Reference surface: rpth's pthread.c rwlock/barrier sections. ---- */

static pthread_rwlock_t rws_lock = PTHREAD_RWLOCK_INITIALIZER;
static pthread_barrier_t rws_barrier;
static pthread_spinlock_t rws_spin;
static pthread_once_t rws_once = PTHREAD_ONCE_INIT;
static int rws_once_runs = 0;
static long rws_shared[2] = {0, 0};   /* invariant: [0] == [1] */
static long rws_reads_ok = 0, rws_spin_counter = 0, rws_serial_seen = 0;
static pthread_mutex_t rws_tally = PTHREAD_MUTEX_INITIALIZER;

static void rws_once_init(void) {
  usleep(2000);                       /* init parks mid-run (racers wait) */
  rws_once_runs++;
}

#define RWS_PHASES 6

static void *rws_worker(void *argp) {
  long id = (long)argp;
  pthread_once(&rws_once, rws_once_init);
  for (int phase = 0; phase < RWS_PHASES; phase++) {
    if (id < 2) {
      /* readers: take the lock, HOLD it across a virtual-time sleep (this
       * is the contended case: writers arrive while we sleep holding it) */
      pthread_rwlock_rdlock(&rws_lock);
      long a = rws_shared[0];
      usleep(3000);
      long b = rws_shared[1];
      pthread_rwlock_unlock(&rws_lock);
      pthread_mutex_lock(&rws_tally);
      if (a == b) rws_reads_ok++;
      pthread_mutex_unlock(&rws_tally);
    } else {
      /* writers: stagger in behind the sleeping readers, then mutate both
       * halves non-atomically with a sleep in between — a read slipping
       * inside would observe the broken invariant */
      usleep(1000);
      pthread_rwlock_wrlock(&rws_lock);
      rws_shared[0]++;
      usleep(2000);
      rws_shared[1]++;
      pthread_rwlock_unlock(&rws_lock);
    }
    /* spin-guarded tally crossing the phase */
    pthread_spin_lock(&rws_spin);
    rws_spin_counter++;
    pthread_spin_unlock(&rws_spin);
    /* all four meet; exactly one gets PTHREAD_BARRIER_SERIAL_THREAD */
    int r = pthread_barrier_wait(&rws_barrier);
    if (r == PTHREAD_BARRIER_SERIAL_THREAD) {
      pthread_mutex_lock(&rws_tally);
      rws_serial_seen++;
      pthread_mutex_unlock(&rws_tally);
    } else if (r != 0) {
      return (void *)1L;
    }
  }
  return (void *)0L;
}

static int cmd_rwsync(void) {
  if (pthread_barrier_init(&rws_barrier, NULL, 4) != 0) return 1;
  if (pthread_spin_init(&rws_spin, PTHREAD_PROCESS_PRIVATE) != 0) return 2;
  /* trylock surface: uncontended succeeds, then conflicts report EBUSY */
  if (pthread_rwlock_tryrdlock(&rws_lock) != 0) return 3;
  if (pthread_rwlock_trywrlock(&rws_lock) != EBUSY) return 4;
  pthread_rwlock_unlock(&rws_lock);
  if (pthread_rwlock_trywrlock(&rws_lock) != 0) return 5;
  if (pthread_rwlock_tryrdlock(&rws_lock) != EBUSY) return 6;
  pthread_rwlock_unlock(&rws_lock);
  pthread_t th[4];
  for (long i = 0; i < 4; i++)
    if (pthread_create(&th[i], NULL, rws_worker, (void *)i) != 0) return 7;
  long bad = 0;
  for (int i = 0; i < 4; i++) {
    void *rv = NULL;
    if (pthread_join(th[i], &rv) != 0) return 8;
    bad += (long)rv;
  }
  if (bad) return 9;
  if (rws_once_runs != 1) return 10;
  if (rws_reads_ok != 2L * RWS_PHASES) {
    printf("rwsync: only %ld/%d consistent reads\n", rws_reads_ok,
           2 * RWS_PHASES);
    return 11;
  }
  if (rws_shared[0] != 2L * RWS_PHASES || rws_shared[1] != rws_shared[0])
    return 12;
  if (rws_spin_counter != 4L * RWS_PHASES) return 13;
  if (rws_serial_seen != RWS_PHASES) {
    printf("rwsync: %ld serial threads over %d phases\n", rws_serial_seen,
           RWS_PHASES);
    return 14;
  }
  if (pthread_barrier_destroy(&rws_barrier) != 0) return 15;
  if (pthread_spin_destroy(&rws_spin) != 0) return 16;
  printf("rwsync OK writes=%ld reads_ok=%ld spins=%ld\n", rws_shared[0],
         rws_reads_ok, rws_spin_counter);
  return 0;
}

/* ---- ppoll/pselect + reentrant resolver family (dual-exec; reference
 * preload_defs.h carries ppoll/pselect/gethostbyname_r/gethostbyname2_r/
 * getnameinfo — libevent-based apps like Tor reach all of them) ---- */
#include <netdb.h>

static int cmd_resolvers(const char *expected_host) {
  /* gethostbyname_r of our own name (in-sim: the engine DNS) */
  struct hostent he, *result = NULL;
  char buf[1024];
  int herr = 0;
  char self_name[256];
  if (gethostname(self_name, sizeof self_name) != 0) return 1;
  if (gethostbyname_r(self_name, &he, buf, sizeof buf, &result, &herr) != 0
      || result == NULL)
    return 2;
  if (result->h_addrtype != AF_INET || result->h_length != 4) return 3;
  uint32_t ip_net;
  memcpy(&ip_net, result->h_addr_list[0], 4);
  if (ip_net == 0) return 4;
  struct hostent he2, *result2 = NULL;
  char buf2[1024];
  if (gethostbyname2_r(self_name, AF_INET, &he2, buf2, sizeof buf2,
                       &result2, &herr) != 0 || result2 == NULL)
    return 5;
  /* ERANGE on a too-small buffer */
  char tiny[8];
  struct hostent he3, *result3 = NULL;
  if (gethostbyname_r(self_name, &he3, tiny, sizeof tiny, &result3,
                      &herr) != ERANGE)
    return 6;
  /* getnameinfo: reverse of our own address must produce our hostname
   * in-sim (the engine DNS holds the reverse map); numeric form always */
  struct sockaddr_in sin;
  memset(&sin, 0, sizeof sin);
  sin.sin_family = AF_INET;
  sin.sin_addr.s_addr = ip_net;
  sin.sin_port = htons(1234);
  char hostbuf[256], servbuf[32];
  if (getnameinfo((struct sockaddr *)&sin, sizeof sin, hostbuf,
                  sizeof hostbuf, servbuf, sizeof servbuf, 0) != 0)
    return 7;
  if (under_sim() && strcmp(hostbuf, expected_host) != 0) {
    printf("getnameinfo: %s != %s\n", hostbuf, expected_host);
    return 8;
  }
  if (strcmp(servbuf, "1234") != 0) return 9;
  if (getnameinfo((struct sockaddr *)&sin, sizeof sin, hostbuf,
                  sizeof hostbuf, NULL, 0, NI_NUMERICHOST) != 0)
    return 10;
  if (strchr(hostbuf, '.') == NULL) return 11;   /* dotted quad */

  /* ppoll/pselect over a sim socketpair: writable immediately; readable
   * only after data; a ppoll with a timeout must consume VIRTUAL time */
  int sv[2];
  if (socketpair(AF_UNIX, SOCK_STREAM, 0, sv) != 0) return 12;
  struct pollfd pf = {sv[0], POLLIN, 0};
  struct timespec ts = {0, 200 * 1000000};   /* 200 ms */
  int64_t t0 = now_ns();
  int r = ppoll(&pf, 1, &ts, NULL);
  int64_t waited = now_ns() - t0;
  if (r != 0) return 13;                     /* nothing readable yet */
  if (under_sim() && waited < 150 * 1000000LL) return 14;
  if (send(sv[1], "x", 1, 0) != 1) return 15;
  pf.revents = 0;
  if (ppoll(&pf, 1, NULL, NULL) != 1 || !(pf.revents & POLLIN)) return 16;
  char c;
  if (recv(sv[0], &c, 1, 0) != 1 || c != 'x') return 17;
  /* pselect: write side writable; read side not readable */
  fd_set rfds, wfds;
  FD_ZERO(&rfds);
  FD_ZERO(&wfds);
  FD_SET(sv[0], &rfds);
  FD_SET(sv[1], &wfds);
  struct timespec pts = {0, 50 * 1000000};
  int n = pselect((sv[0] > sv[1] ? sv[0] : sv[1]) + 1, &rfds, &wfds, NULL,
                  &pts, NULL);
  if (n != 1 || FD_ISSET(sv[0], &rfds) || !FD_ISSET(sv[1], &wfds))
    return 18;
  close(sv[0]);
  close(sv[1]);
  printf("resolvers OK host=%s\n", hostbuf);
  return 0;
}

int main(int argc, char **argv) {
  if (argc < 2) return 64;
  const char *cmd = argv[1];
  if (!strcmp(cmd, "rwsync")) return cmd_rwsync();
  if (!strcmp(cmd, "resolvers") && argc >= 3) return cmd_resolvers(argv[2]);
  if (!strcmp(cmd, "efdsem")) return cmd_efdsem();
  if (!strcmp(cmd, "sighandler")) return cmd_sighandler();
  if (!strcmp(cmd, "sigdfl")) return cmd_sigdfl();
  if (!strcmp(cmd, "torserver") && argc >= 5)
    return cmd_torserver((uint16_t)atoi(argv[2]), atoi(argv[3]),
                         atol(argv[4]));
  if (!strcmp(cmd, "torclient") && argc >= 7)
    return cmd_torclient(argv[2], (uint16_t)atoi(argv[3]), atoi(argv[4]),
                         atoi(argv[5]), atoi(argv[6]));
  if (!strcmp(cmd, "xattrcheck") && argc >= 3) return cmd_xattr(argv[2]);
  if (!strcmp(cmd, "files") && argc >= 3) return cmd_files(argv[2]);
  if (!strcmp(cmd, "vtime")) return cmd_vtime();
  if (!strcmp(cmd, "sockmisc")) return cmd_sockmisc();
  if (!strcmp(cmd, "selfpipe")) return cmd_selfpipe();
  if (!strcmp(cmd, "timercheck")) return cmd_timercheck();
  if (!strcmp(cmd, "envcheck") && argc >= 4) {
    /* <shadow environment=...> injection (reference main.c:474-524) */
    const char *v = getenv(argv[2]);
    return (v && strcmp(v, argv[3]) == 0) ? 0 : 1;
  }
  if (!strcmp(cmd, "relay") && argc >= 5) {
    /* TCP relay: accept one connection, dial the next hop, shuttle bytes
     * both ways until both sides close — a chain of these is the
     * onion-routing-shaped path real Tor builds (reference workload #3/#4
     * run chains of real relays the same way) */
    uint16_t lport = (uint16_t)atoi(argv[2]);
    const char *nhost = argv[3];
    uint16_t nport = (uint16_t)atoi(argv[4]);
    int lfd = socket(AF_INET, SOCK_STREAM, 0);
    struct sockaddr_in sin;
    memset(&sin, 0, sizeof sin);
    sin.sin_family = AF_INET;
    sin.sin_addr.s_addr = htonl(INADDR_ANY);
    sin.sin_port = htons(lport);
    if (bind(lfd, (struct sockaddr *)&sin, sizeof sin) != 0) return 1;
    if (listen(lfd, 4) != 0) return 2;
    int cfd = accept(lfd, NULL, NULL);
    if (cfd < 0) return 3;
    struct sockaddr_in dst;
    if (resolve(nhost, nport, &dst) != 0) return 4;
    int ufd = socket(AF_INET, SOCK_STREAM, 0);
    if (connect(ufd, (struct sockaddr *)&dst, sizeof dst) != 0) return 5;
    struct pollfd pf[2] = {{cfd, POLLIN, 0}, {ufd, POLLIN, 0}};
    char rbuf[16384];
    int open_dirs = 2;
    while (open_dirs > 0) {
      if (poll(pf, 2, 30000) <= 0) return 6;
      for (int k = 0; k < 2; k++) {
        if (!(pf[k].revents & (POLLIN | POLLHUP))) continue;
        int from = pf[k].fd, to = (pf[k].fd == cfd) ? ufd : cfd;
        ssize_t r = recv(from, rbuf, sizeof rbuf, 0);
        if (r < 0) return 7;
        if (r == 0) {
          shutdown(to, SHUT_WR);
          pf[k].events = 0;
          open_dirs--;
          continue;
        }
        ssize_t off = 0;
        while (off < r) {
          ssize_t w = send(to, rbuf + off, (size_t)(r - off), 0);
          if (w <= 0) return 8;
          off += w;
        }
      }
    }
    close(cfd);
    close(ufd);
    close(lfd);
    printf("relay OK\n");
    return 0;
  }
  if (!strcmp(cmd, "filewrite") && argc >= 3) {
    /* per-host file namespace: cwd is this host's data dir, so a relative
     * path never collides with another host's (reference data-dir layout,
     * slave.c:201-218) */
    FILE *f = fopen("state.txt", "w");
    if (!f) return 1;
    fprintf(f, "%s", argv[2]);
    fclose(f);
    f = fopen("state.txt", "r");
    if (!f) return 2;
    char buf[256] = {0};
    if (!fgets(buf, sizeof buf, f)) { fclose(f); return 3; }
    fclose(f);
    return strcmp(buf, argv[2]) == 0 ? 0 : 4;
  }
  if (!strcmp(cmd, "spin")) {
    /* pathological plugin: burns CPU forever without any syscall — the
     * simulator's stall watchdog must kill it rather than freeze */
    volatile unsigned long x = 1;
    for (;;) x = x * 2654435761u + 1;
  }
  if (!strcmp(cmd, "threads")) return cmd_threads();
  if (!strcmp(cmd, "mtserver") && argc >= 3)
    return cmd_mtserver((uint16_t)atoi(argv[2]));
  if (!strcmp(cmd, "miscsys") && argc >= 3) return cmd_miscsys(argv[2]);
  if (!strcmp(cmd, "udpserver") && argc >= 4)
    return cmd_udpserver((uint16_t)atoi(argv[2]), atoi(argv[3]));
  if (!strcmp(cmd, "udpclient") && argc >= 6)
    return cmd_udpclient(argv[2], (uint16_t)atoi(argv[3]), atoi(argv[4]),
                         atoi(argv[5]));
  if (!strcmp(cmd, "udpconnclient") && argc >= 6)
    return cmd_udpconnclient(argv[2], (uint16_t)atoi(argv[3]), atoi(argv[4]),
                             atoi(argv[5]));
  if (!strcmp(cmd, "tcpserver") && argc >= 4)
    return cmd_tcpserver((uint16_t)atoi(argv[2]), atoll(argv[3]));
  if (!strcmp(cmd, "tcpclient") && argc >= 5)
    return cmd_tcpclient(argv[2], (uint16_t)atoi(argv[3]), atoll(argv[4]));
  if (!strcmp(cmd, "epollserver") && argc >= 4)
    return cmd_epollserver((uint16_t)atoi(argv[2]), atoi(argv[3]));
  if (!strcmp(cmd, "etserver") && argc >= 4) {
    /* same server, edge-triggered: the drain-until-EAGAIN loops above
     * are exactly the ET contract */
    g_epoll_flags_extra = EPOLLET;
    return cmd_epollserver((uint16_t)atoi(argv[2]), atoi(argv[3]));
  }
  if (!strcmp(cmd, "pollclient") && argc >= 4)
    return cmd_pollclient(argv[2], (uint16_t)atoi(argv[3]));
  if (!strcmp(cmd, "selectclient") && argc >= 4)
    return cmd_selectclient(argv[2], (uint16_t)atoi(argv[3]));
  if (!strcmp(cmd, "sumserver") && argc >= 3)
    return cmd_sumserver((uint16_t)atoi(argv[2]));
  if (!strcmp(cmd, "halfclient") && argc >= 5)
    return cmd_halfclient(argv[2], (uint16_t)atoi(argv[3]), atoll(argv[4]));
  if (!strcmp(cmd, "randcheck")) return cmd_randcheck();
  if (!strcmp(cmd, "hostname") && argc >= 3) return cmd_hostname(argv[2]);
  (void)echo_once_connected;
  return 64;
}
