// C++ plugin sanity (reference: src/test/cpp): iostream, std::string,
// exceptions, and a socket round trip through the interposed libc — the
// C++ runtime (static init, unwinding, locales) must work under the shim.
#include <arpa/inet.h>
#include <netdb.h>
#include <sys/socket.h>
#include <unistd.h>

#include <iostream>
#include <stdexcept>
#include <string>
#include <vector>

static int resolve4(const std::string &host, uint16_t port,
                    sockaddr_in *out) {
  addrinfo hints{}, *res = nullptr;
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_DGRAM;
  if (getaddrinfo(host.c_str(), nullptr, &hints, &res) != 0 || !res)
    return -1;
  *out = *reinterpret_cast<sockaddr_in *>(res->ai_addr);
  out->sin_port = htons(port);
  freeaddrinfo(res);
  return 0;
}

int main(int argc, char **argv) {
  std::vector<std::string> args(argv + 1, argv + argc);
  try {
    if (!args.empty() && args[0] == "throwcheck") {
      throw std::runtime_error("caught");
    }
  } catch (const std::runtime_error &e) {
    if (std::string(e.what()) != "caught") return 2;
  }
  if (args.size() >= 3 && args[0] == "udp") {
    const std::string host = args[1];
    const uint16_t port = static_cast<uint16_t>(std::stoi(args[2]));
    sockaddr_in dst{};
    if (resolve4(host, port, &dst) != 0) return 3;
    int fd = socket(AF_INET, SOCK_DGRAM, 0);
    if (fd < 0) return 4;
    const std::string msg = "hello-from-cpp";
    if (sendto(fd, msg.data(), msg.size(), 0,
               reinterpret_cast<sockaddr *>(&dst), sizeof dst) !=
        static_cast<ssize_t>(msg.size()))
      return 5;
    std::string echo(msg.size(), '\0');
    if (recv(fd, echo.data(), echo.size(), 0) !=
        static_cast<ssize_t>(msg.size()))
      return 6;
    if (echo != msg) return 7;
    close(fd);
  }
  std::cout << "cppapp OK" << std::endl;
  return 0;
}
