"""Self-healing runs (ISSUE 17): shard resurrection, mid-run device-loss
re-sharding, and the recovery ladder's climb back up.

1. Shard resurrection: a shard hard-killed mid-run is respawned from the
   newest verifying snapshot (round-zero deterministic replay when none),
   digest-verified at the join boundary, and the run finishes rc 0 with
   the digest of a fault-free run — `supervision` counts the death, the
   resurrection, and a nonzero MTTR.  The budget (`--max-resurrections`)
   exhausting aborts loudly instead of looping.
2. Device-loss re-shard: an injected device loss on the sharded mesh
   re-partitions onto D-1 devices at a quiesced boundary — digest pinned
   against the fault-free baseline at K=1 AND K=8 (mid-superwindow), and
   D=2 collapses to the single-device plane rather than a 1-way mesh.
3. Re-promotion: with --repromote-after R, a demotion (device-plane
   dispatch drill, native round executor drill) is probational — R clean
   rounds climb back up the ladder, counted in supervision.repromotions,
   digest unchanged; without the flag demotions stay permanent (the
   PR-2/PR-10 contract).
"""

import textwrap

import pytest

from shadow_tpu.core import configuration
from shadow_tpu.core.checkpoint import state_digest
from shadow_tpu.core.controller import Controller
from shadow_tpu.core.options import Options
from shadow_tpu.parallel.procs import ProcsController
from shadow_tpu.tools import workloads

# -- shard-resurrection harness: the lossy 7-host mix test_procs.py uses
# (cross-shard flows in both directions under any 2-way partition) -------

LOSSY_TOPO = """<topology><![CDATA[<?xml version="1.0" encoding="UTF-8"?>
<graphml xmlns="http://graphml.graphdrawing.org/xmlns">
<key id="d0" for="edge" attr.name="latency" attr.type="double"/>
<key id="d1" for="edge" attr.name="packetloss" attr.type="double"/>
<key id="d2" for="node" attr.name="bandwidthdown" attr.type="int"/>
<key id="d3" for="node" attr.name="bandwidthup" attr.type="int"/>
<graph edgedefault="undirected">
  <node id="n0"><data key="d2">10240</data><data key="d3">10240</data></node>
  <edge source="n0" target="n0"><data key="d0">25.0</data><data key="d1">0.02</data></edge>
</graph></graphml>]]></topology>"""

XML = textwrap.dedent("""\
    <shadow stoptime="60">
      {topo}
      <plugin id="tgen" path="python:tgen" />
      <plugin id="echo" path="python:echo" />
      <host id="server"><process plugin="tgen" starttime="1" arguments="server 80" /></host>
      <host id="c1"><process plugin="tgen" starttime="2" arguments="client server 80 1024:204800" /></host>
      <host id="c2"><process plugin="tgen" starttime="3" arguments="client server 80 2048:102400" /></host>
      <host id="u1"><process plugin="echo" starttime="1" arguments="udp server 9000" /></host>
      <host id="u2"><process plugin="echo" starttime="2" arguments="udp client u1 9000 12 700" /></host>
    </shadow>
""").format(topo=LOSSY_TOPO)


def _cfg(stop=60):
    cfg = configuration.parse_xml(XML)
    cfg.stop_time_sec = stop
    return cfg


def _sharded(stop=60, **opt_kw):
    ctrl = ProcsController(Options(scheduler_policy="global", workers=0,
                                   seed=7, stop_time_sec=stop, processes=2,
                                   **opt_kw), _cfg(stop))
    assert ctrl.run() == 0
    return ctrl


_CLEAN: dict = {}


def _clean_sharded_digest():
    if "procs" not in _CLEAN:
        _CLEAN["procs"] = _sharded().digest
    return _CLEAN["procs"]


def test_shard_killed_midrun_resurrected_digest_identical():
    """The headline acceptance: shard 1 hard-exits at round 3 (the
    supervisor sees exactly what a SIGKILL produces — a dead pipe), is
    respawned and replayed to the barrier, and the run finishes rc 0
    with the fault-free digest.  Every detour is on the ledger."""
    res = _sharded(fault_inject="shard-exit-resurrect:1:3")
    assert res.digest == _clean_sharded_digest()
    s = res.supervision.summary()
    assert s["shard_deaths_detected"] == 1
    assert s["shard_resurrections"] == 1
    assert s["mttr_sec"] > 0
    assert s["recoveries"] >= 2       # the death + the resurrection


def test_resurrection_verified_at_checkpoint_boundary(tmp_path):
    """A death AFTER snapshots exist: the replayed shard must pass the
    join-boundary digest gate recorded at each checkpoint round."""
    res = _sharded(fault_inject="shard-exit-resurrect:0:20",
                   checkpoint_every_rounds=8,
                   checkpoint_dir=str(tmp_path / "ck"))
    assert res.digest == _clean_sharded_digest()
    assert res.supervision.shard_resurrections == 1


def test_resurrection_budget_exhaustion_aborts_loudly():
    """--max-resurrections 0: the drill's death must abort the run with
    a diagnosable error, never silently retry forever."""
    with pytest.raises(RuntimeError, match="resurrection budget exhausted"):
        _sharded(fault_inject="shard-exit-resurrect:1:3",
                 max_resurrections=0)


# -- device-loss re-shard: the sharded mesh on the 8-virtual-device CPU
# mesh (conftest forces xla_force_host_platform_device_count=8) ----------

STAR_XML = workloads.star_bulk(6, stoptime=120,
                               bulk_bytes=192 * 1024 * 1024,
                               device_data=True)


def _mesh(n_dev=8, k=1, **opt_kw):
    cfg = configuration.parse_xml(STAR_XML)
    cfg.stop_time_sec = 120
    ctrl = Controller(Options(scheduler_policy="global", workers=0, seed=3,
                              stop_time_sec=120, log_level="warning",
                              device_plane="device", superwindow_rounds=k,
                              tpu_devices=n_dev, **opt_kw), cfg)
    assert ctrl.run() == 0
    return ctrl


def _clean_mesh_digest(n_dev, k):
    key = ("mesh", n_dev, k)
    if key not in _CLEAN:
        _CLEAN[key] = state_digest(_mesh(n_dev, k).engine)
    return _CLEAN[key]


def test_device_loss_reshards_8_to_7_digest_pinned():
    lost = _mesh(8, 1, fault_inject="device-lost:4")
    assert state_digest(lost.engine) == _clean_mesh_digest(8, 1)
    s = lost.engine.supervision.summary()
    assert s["reshards"] == 1
    assert s["mttr_sec"] > 0
    assert lost.engine.device_plane._meshinfo.n_devices == 7
    # re-sharded exchange still never transits the host
    assert lost.engine.metrics.scrape()["mesh.host_bounces"] == 0


def test_device_loss_mid_superwindow_k8_digest_pinned():
    """The hard case: the loss lands inside a K=8 superwindow — the
    re-shard must happen at a quiesced boundary, not mid-kernel."""
    lost = _mesh(8, 8, fault_inject="device-lost:3")
    assert state_digest(lost.engine) == _clean_mesh_digest(8, 8)
    assert lost.engine.supervision.reshards == 1
    assert lost.engine.device_plane._meshinfo.n_devices == 7


def test_device_loss_on_two_devices_falls_to_single_plane():
    """D=2 minus one is not a mesh: the survivor runs the single-device
    plane (no exchange at all), digest unchanged."""
    lost = _mesh(2, 1, fault_inject="device-lost:4")
    assert state_digest(lost.engine) == _clean_mesh_digest(2, 1)
    assert lost.engine.supervision.reshards == 1
    assert lost.engine.device_plane._shard is None


# -- the ladder climbs back up: device-plane re-promotion ----------------

def test_demote_probation_repromote_roundtrip():
    """A drilled dispatch failure demotes to the numpy twin; after
    --repromote-after clean rounds the plane climbs back to the device
    rung — counted, digest identical to the fault-free run."""
    rp = _mesh(1, 1, fault_inject="demote-repromote:2", repromote_after=3)
    plane = rp.engine.device_plane
    s = rp.engine.supervision.summary()
    assert s["dispatch_recoveries"] == 1
    assert s["repromotions"] == 1
    assert plane.mode == "device" and not plane.demoted
    assert plane.stats()["repromoted"]
    assert state_digest(rp.engine) == _clean_mesh_digest(1, 1)


def test_demotion_stays_permanent_without_repromote_after():
    """The ladder's default is unchanged: no --repromote-after, no climb
    back (the PR-2 permanent-demotion contract)."""
    perm = _mesh(1, 1, fault_inject="demote-repromote:2")
    plane = perm.engine.device_plane
    assert plane.mode == "numpy" and plane.demoted
    assert not plane.stats()["repromoted"]
    assert perm.engine.supervision.repromotions == 0
    assert state_digest(perm.engine) == _clean_mesh_digest(1, 1)


# -- and the native round executor rung ----------------------------------

TOR_KW = dict(n_relays=40, n_clients=25, n_servers=3, stoptime=30,
              stream_spec="512:20480")


def _native(**opt_kw):
    cfg = configuration.parse_xml(workloads.tor_network(**TOR_KW))
    cfg.stop_time_sec = 30
    ctrl = Controller(Options(scheduler_policy="global", workers=0, seed=3,
                              stop_time_sec=30, log_level="warning",
                              **opt_kw), cfg)
    assert ctrl.run() == 0
    return ctrl.engine


def _clean_native_digest():
    if "native" not in _CLEAN:
        _CLEAN["native"] = state_digest(_native())
    return _CLEAN["native"]


def test_native_round_executor_repromotes_after_probation():
    eng = _native(fault_inject="native-round:4", repromote_after=5)
    pol = eng.scheduler.policy
    s = eng.supervision.summary()
    assert s["native_round_demotions"] == 1
    assert s["repromotions"] == 1
    assert not pol.round_demoted and pol.round_repromoted
    assert pol.round_windows > 4, "executor never re-engaged after probation"
    assert state_digest(eng) == _clean_native_digest()
    scrape = eng.metrics.scrape()
    assert scrape["native.round_repromoted"] == 1
    assert scrape["native.round_demoted"] == 0


def test_native_round_demotion_permanent_without_flag():
    eng = _native(fault_inject="native-round:4")
    assert eng.scheduler.policy.round_demoted
    assert not eng.scheduler.policy.round_repromoted
    assert state_digest(eng) == _clean_native_digest()
