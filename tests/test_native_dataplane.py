"""C data plane (native/dataplane.cc + parallel/native_plane.py) tests.

The native plane's contract is BIT-EXACT digest parity with the Python
plane: the C code is a faithful re-expression of descriptor/tcp.py,
descriptor/udp.py, host/network_interface.py, host/router.py and
core/worker.py's hop, so a native run must produce the identical state
digest, event count, tracker totals, and app outcomes.  These tests pin
that contract on workloads that exercise every subsystem: handshakes,
bulk transfer, loss/retransmit/SACK/RTO, multi-hop tor cells, UDP, and
the interface/router machinery.
"""

from __future__ import annotations

import pytest

from shadow_tpu.core import configuration
from shadow_tpu.core.checkpoint import state_digest
from shadow_tpu.core.controller import Controller
from shadow_tpu.core.options import Options
from shadow_tpu.core.logger import SimLogger, set_logger
from shadow_tpu.parallel.native_plane import native_available
from shadow_tpu.tools import workloads

pytestmark = pytest.mark.skipif(not native_available(),
                                reason="native dataplane not built")


def _run(xml: str, plane: str, stop: int, seed: int = 42, policy="global",
         workers=0, **kw):
    set_logger(SimLogger(level="warning"))
    cfg = configuration.parse_xml(xml)
    cfg.stop_time_sec = stop
    ctrl = Controller(Options(scheduler_policy=policy, workers=workers,
                              stop_time_sec=stop, seed=seed, dataplane=plane,
                              **kw), cfg)
    rc = ctrl.run()
    eng = ctrl.engine
    return rc, eng


def _two_host_xml(args: str, loss: float = 0.0, stop: int = 120) -> str:
    import sys, os
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from test_tcp_e2e import two_host_xml
    return two_host_xml(args, loss=loss, stop=stop)


def _assert_parity(xml: str, stop: int, **kw):
    rc_p, eng_p = _run(xml, "python", stop, **kw)
    rc_n, eng_n = _run(xml, "native", stop, **kw)
    assert rc_p == 0 and rc_n == 0
    assert eng_n.native_plane is not None, "native plane did not engage"
    assert eng_p.native_plane is None
    assert eng_p.events_executed == eng_n.events_executed
    assert state_digest(eng_p) == state_digest(eng_n)
    return eng_p, eng_n


def test_parity_tcp_echo_lossless():
    _assert_parity(_two_host_xml("tcp client server 8000 5 2048"), 120)


def test_parity_tcp_echo_lossy():
    """10% loss: drop draws, retransmits, SACK, RTO — all in C — must
    reproduce the Python plane's trajectory exactly."""
    eng_p, eng_n = _assert_parity(
        _two_host_xml("tcp client server 8000 5 2048", loss=0.1, stop=300),
        300)
    p = eng_n.host_by_name("client").processes[0]
    assert p.exited and p.exit_code == 0


def test_parity_tor_multihop():
    """20 relays + 10 clients: circuit builds over real TCP, cell
    store-and-forward, delayed ACKs, interface contention."""
    xml = workloads.tor_network(20, n_clients=10, n_servers=2, stoptime=60,
                                stream_spec="512:20480")
    _assert_parity(xml, 60)


def test_parity_star_bulk():
    xml = workloads.star_bulk(10, stoptime=30, bulk_bytes=131072)
    _assert_parity(xml, 30)


def test_parity_udp_phold():
    """PHOLD is UDP: datagram sends, binding lookups, hop draws in C."""
    n = 16
    xml = (f'<shadow stoptime="20"><plugin id="phold" path="python:phold" />'
           f'<host id="phold" quantity="{n}" bandwidthdown="10240" '
           f'bandwidthup="10240"><process plugin="phold" starttime="1" '
           f'arguments="{n} 4 9000" /></host></shadow>')
    _assert_parity(xml, 20)


def test_parity_across_congestion_controls():
    xml = _two_host_xml("tcp client server 8000 4 8192", loss=0.05, stop=200)
    for cc in ("reno", "aimd", "cubic"):
        _assert_parity(xml, 200, tcp_congestion_control=cc)


def test_native_faster_than_python_on_tor():
    """The point of the C plane (VERDICT r4 next #1): a meaningful speedup
    on the tor workload shape.  Conservative 1.5x bound here (CI noise);
    bench.py records the real ratio (~4x on tor200)."""
    import time
    xml = workloads.tor_network(40, n_clients=20, n_servers=2, stoptime=60,
                                stream_spec="512:30720")
    t0 = time.perf_counter()
    _run(xml, "python", 60)
    t_py = time.perf_counter() - t0
    t0 = time.perf_counter()
    _run(xml, "native", 60)
    t_nat = time.perf_counter() - t0
    assert t_nat < t_py, (t_nat, t_py)


def test_eligibility_fallbacks():
    """Threaded / non-global / procs runs fall back to the Python plane in
    auto mode; --dataplane=native raises instead of silently degrading."""
    xml = _two_host_xml("tcp client server 8000 2 1024")
    rc, eng = _run(xml, "auto", 60, policy="steal", workers=2)
    assert rc == 0 and eng.native_plane is None
    with pytest.raises(RuntimeError, match="dataplane=native"):
        _run(xml, "native", 60, policy="steal", workers=2)


def test_native_wrapper_errors():
    """API error surface parity: EPIPE after shutdown(WR), ENOTCONN before
    connect, EADDRINUSE on a double bind."""
    xml = _two_host_xml("tcp client server 8000 2 1024")
    set_logger(SimLogger(level="warning"))
    cfg = configuration.parse_xml(xml)
    cfg.stop_time_sec = 30
    ctrl = Controller(Options(scheduler_policy="global", workers=0,
                              stop_time_sec=30, dataplane="native"), cfg)
    ctrl.setup()
    eng = ctrl.engine
    plane = eng.native_plane
    assert plane is not None
    host = eng.host_by_name("client")
    sock = plane.create_socket(host, "tcp")
    with pytest.raises(OSError, match="ENOTCONN"):
        sock.send_user_data(b"x")
    a = plane.create_socket(host, "tcp")
    b = plane.create_socket(host, "tcp")
    a.bind_native(host.ip, 5555, False)
    with pytest.raises(OSError, match="EADDRINUSE"):
        b.bind_native(host.ip, 5555, False)


def test_native_shards_match_serial_native():
    """--processes with C-plane shards: every shard runs the native data
    plane (cross-shard hops ship through the C outbox callback and land in
    the owner's C event heap), and the 3-shard digest equals the serial
    native digest bit-for-bit — the multicore scaling configuration at C
    speed."""
    from shadow_tpu.parallel.procs import ProcsController
    xml = workloads.tor_network(12, n_clients=6, n_servers=1, stoptime=40,
                                stream_spec="512:20480")
    rc, eng = _run(xml, "native", 40)
    assert rc == 0
    serial_digest = state_digest(eng)
    set_logger(SimLogger(level="warning"))
    cfg = configuration.parse_xml(xml)
    cfg.stop_time_sec = 40
    pc = ProcsController(Options(scheduler_policy="global", workers=0,
                                 stop_time_sec=40, seed=42, processes=3,
                                 log_level="warning"), cfg)
    assert pc.run() == 0
    assert pc.digest == serial_digest


def test_native_digest_matches_threaded_python_policies():
    """The strongest cross-plane claim: a native serial run digests
    identically to a THREADED python-plane run under another policy (the
    existing cross-policy parity extended across planes)."""
    xml = workloads.tor_network(12, n_clients=6, n_servers=1, stoptime=40,
                                stream_spec="512:10240")
    rc_n, eng_n = _run(xml, "native", 40, policy="global", workers=0)
    rc_t, eng_t = _run(xml, "python", 40, policy="steal", workers=2)
    assert rc_n == 0 and rc_t == 0
    assert state_digest(eng_n) == state_digest(eng_t)
