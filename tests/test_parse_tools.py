"""Log-parse tooling (tools/parse_log.py) against a real run's output, and
the benchmark workload-generator CLI (tools/workloads.py) — the
parse-shadow.py / generate-config capability row."""

import io
import textwrap

from shadow_tpu.core import configuration
from shadow_tpu.core.controller import Controller
from shadow_tpu.core.logger import SimLogger, set_logger, get_logger
from shadow_tpu.core.options import Options
from shadow_tpu.tools import workloads
from shadow_tpu.tools.parse_log import parse_log, strip_log


def test_parse_log_summarizes_a_real_run():
    xml = textwrap.dedent("""\
        <shadow stoptime="130">
          <plugin id="echo" path="python:echo" />
          <host id="server" heartbeatfrequency="60">
            <process plugin="echo" starttime="1" arguments="udp server 9000" />
          </host>
          <host id="client" heartbeatfrequency="60">
            <process plugin="echo" starttime="2"
                     arguments="udp client server 9000 10 500" />
          </host>
        </shadow>
    """)
    buf = io.StringIO()
    set_logger(SimLogger(level="message", stream=buf))
    try:
        cfg = configuration.parse_xml(xml)
        ctrl = Controller(Options(scheduler_policy="global", workers=0,
                                  stop_time_sec=cfg.stop_time_sec), cfg)
        assert ctrl.run() == 0
        get_logger().flush()
    finally:
        set_logger(SimLogger())
    summary = parse_log(buf.getvalue().splitlines())
    assert summary["num_hosts"] == 2
    assert summary["total_rx_bytes"] > 0
    assert summary["run"]["rounds"] == ctrl.engine.rounds_executed
    assert summary["run"]["events"] == ctrl.engine.events_executed
    assert summary["sim_seconds"] > 0
    # heartbeat series carry per-host time points
    assert all(len(s) >= 2 for s in summary["series"].values())
    # strip form is stable and wall-free
    stripped = list(strip_log(buf.getvalue().splitlines()))
    assert stripped and not any("wall=" in l for l in stripped)


def test_plot_log_renders_real_run(tmp_path):
    """plot-shadow.py analog: a real run's log renders to non-empty PNGs
    (throughput panels + engine-heartbeat panels)."""
    import pytest
    pytest.importorskip("matplotlib")
    xml = textwrap.dedent("""\
        <shadow stoptime="130">
          <plugin id="echo" path="python:echo" />
          <host id="server" heartbeatfrequency="30">
            <process plugin="echo" starttime="1" arguments="udp server 9000" />
          </host>
          <host id="client" heartbeatfrequency="30">
            <process plugin="echo" starttime="2"
                     arguments="udp client server 9000 200 500 0.5" />
          </host>
        </shadow>
    """)
    buf = io.StringIO()
    set_logger(SimLogger(level="message", stream=buf))
    try:
        cfg = configuration.parse_xml(xml)
        ctrl = Controller(Options(scheduler_policy="global", workers=0,
                                  stop_time_sec=cfg.stop_time_sec), cfg)
        # force an engine heartbeat line regardless of wall speed
        ctrl.engine.heartbeat_wall_interval = 0.0
        assert ctrl.run() == 0
        get_logger().flush()
    finally:
        set_logger(SimLogger())
    lines = buf.getvalue().splitlines()
    from shadow_tpu.tools.plot_log import engine_heartbeats, plot_heartbeats
    from shadow_tpu.tools.parse_log import plot_log
    out = tmp_path / "tp.png"
    assert plot_log(lines, str(out))
    assert out.stat().st_size > 1000
    hbs = engine_heartbeats(lines)
    assert hbs and all(h["maxrss_mb"] > 0 for h in hbs)
    hb_out = tmp_path / "hb.png"
    assert plot_heartbeats(lines, str(hb_out))
    assert hb_out.stat().st_size > 1000


def test_workload_generator_configs_parse():
    """Every named benchmark config the generator emits is loadable by the
    configuration layer (tor10k only when the reference topology exists)."""
    import os
    for name, make in workloads.NAMED.items():
        if name == "tor10k" and not os.path.exists(
                "/root/reference/resource/topology.graphml.xml.xz"):
            continue
        cfg = configuration.parse_xml(make())
        assert cfg.hosts, name
        assert cfg.stop_time_sec > 0, name


def test_per_host_loglevel_filters_app_logs():
    """The per-host loglevel attribute silences that host's app messages
    without touching other hosts (reference per-host loglevel)."""
    xml = textwrap.dedent("""\
        <shadow stoptime="20">
          <plugin id="echo" path="python:echo" />
          <host id="quiet" loglevel="warning">
            <process plugin="echo" starttime="1" arguments="udp server 9000" />
          </host>
          <host id="chatty">
            <process plugin="echo" starttime="2"
                     arguments="udp client quiet 9000 3 200" />
          </host>
        </shadow>
    """)
    buf = io.StringIO()
    set_logger(SimLogger(level="message", stream=buf))
    try:
        cfg = configuration.parse_xml(xml)
        ctrl = Controller(Options(scheduler_policy="global", workers=0,
                                  stop_time_sec=cfg.stop_time_sec), cfg)
        assert ctrl.run() == 0
        get_logger().flush()
    finally:
        set_logger(SimLogger())
    out = buf.getvalue()
    assert "app/chatty" in out          # unfiltered host logs normally
    assert "app/quiet" not in out       # warning-level host is silenced
