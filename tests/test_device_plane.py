"""Device-resident traffic plane (parallel/device_plane.py) gates.

Three contracts:
1. The windowed stateful kernel (torcells_step_window) advances the model
   IDENTICALLY to the reference run-to-completion kernel (torcells_run) and
   to its own numpy twin, bit for bit, across arbitrary window splits and
   idle-gap folds.
2. A full engine simulation produces identical state digests whether the
   bulk flows run on the device plane or its numpy twin, and whether the
   scheduler policy is serial or tpu.
3. Conservation: every injected cell is delivered exactly once when the
   simulation runs long enough.
"""

import numpy as np
import pytest

from shadow_tpu.core import configuration
from shadow_tpu.core.checkpoint import state_digest
from shadow_tpu.core.controller import Controller
from shadow_tpu.core.options import Options
from shadow_tpu.tools import workloads


def _run(policy="global", mode="device", n_relays=8, n_clients=5, stop=60):
    cfg = configuration.parse_xml(workloads.tor_network(
        n_relays, n_clients=n_clients, n_servers=2, stoptime=stop,
        stream_spec="512:20200", device_data=True))
    cfg.stop_time_sec = stop
    ctrl = Controller(Options(scheduler_policy=policy, workers=0, seed=3,
                              stop_time_sec=stop, log_level="warning",
                              device_plane=mode), cfg)
    rc = ctrl.run()
    assert rc == 0
    return ctrl


# ---------------------------------------------------------------------------
# kernel-level parity
# ---------------------------------------------------------------------------

def _toy_instance():
    from shadow_tpu.ops.torcells_device import DeviceTorCells
    return DeviceTorCells(n_relays=6, n_circuits=20, seed=5,
                          relay_bw_kibps=512, max_latency_ms=20)


def test_windowed_kernel_matches_run_to_completion():
    """torcells_step_window with one big window == torcells_run (pins the
    duplicated per-tick math together bit-for-bit)."""
    import jax.numpy as jnp
    from shadow_tpu.ops.torcells_device import (torcells_run,
                                                torcells_step_window)
    inst = _toy_instance()
    fl = inst.flows
    queued0 = np.where(fl["flow_stage"] == 0, 40, 0).astype(np.int64)
    ref_del, ref_ticks, ref_fwd = inst.run_device(40, max_ticks=5000)

    f = inst.n_flows
    h = len(inst.refill)
    state = (jnp.int64(0), jnp.zeros(f, jnp.int64),
             jnp.zeros((inst.ring_len, f), jnp.int64),
             jnp.asarray(inst.capacity),
             jnp.zeros(f, jnp.int64), jnp.zeros(f, jnp.int64),
             jnp.full(f, -1, jnp.int64), jnp.zeros(h, jnp.int64))
    out = torcells_step_window(
        *state, jnp.asarray(queued0), jnp.asarray(queued0),
        np.int64(ref_ticks), np.int64(0),
        jnp.asarray(fl["flow_node"]), jnp.asarray(fl["flow_lat"]),
        jnp.asarray(fl["flow_succ"]), jnp.asarray(fl["seg_start"]),
        jnp.asarray(inst.refill), jnp.asarray(inst.capacity),
        ring_len=inst.ring_len)
    np.testing.assert_array_equal(np.asarray(out[4]), ref_del)
    assert int(out[8]) == ref_fwd


def test_windowed_kernel_split_and_idle_invariance():
    """Many small windows + an idle-gap fold == one big window (numpy twin
    vs device, both ways)."""
    import jax.numpy as jnp
    from shadow_tpu.ops.torcells_device import (torcells_step_window,
                                                torcells_step_window_numpy)
    inst = _toy_instance()
    fl = inst.flows
    f = inst.n_flows
    h = len(inst.refill)
    queued0 = np.where(fl["flow_stage"] == 0, 25, 0).astype(np.int64)
    flow_args = (fl["flow_node"], fl["flow_lat"], fl["flow_succ"],
                 fl["seg_start"], inst.refill, inst.capacity)

    def np_state():
        return [np.int64(0), np.zeros(f, np.int64),
                np.zeros((inst.ring_len, f), np.int64),
                inst.capacity.copy().astype(np.int64),
                np.zeros(f, np.int64), np.zeros(f, np.int64),
                np.full(f, -1, np.int64), np.zeros(h, np.int64)]

    zeros = np.zeros(f, np.int64)
    # one 600-tick window
    big = torcells_step_window_numpy(*np_state(), queued0, queued0, 600, 0,
                                     *flow_args, inst.ring_len)
    # split: 7 + 93 + 500 with injection only in the first
    s = np_state()
    out = torcells_step_window_numpy(*s, queued0, queued0, 7, 0,
                                     *flow_args, inst.ring_len)
    out = torcells_step_window_numpy(*out[:8], zeros, zeros, 93, 0,
                                     *flow_args, inst.ring_len)
    out = torcells_step_window_numpy(*out[:8], zeros, zeros, 500, 0,
                                     *flow_args, inst.ring_len)
    for i in (1, 3, 4, 5, 6, 7):
        np.testing.assert_array_equal(out[i], big[i])

    # device twin of the split run
    dev = tuple(jnp.asarray(a) for a in np_state())
    dout = torcells_step_window(*dev, jnp.asarray(queued0),
                                jnp.asarray(queued0), np.int64(7),
                                np.int64(0),
                                *(jnp.asarray(a) for a in flow_args),
                                ring_len=inst.ring_len)
    dout = torcells_step_window(*dout[:8], jnp.asarray(zeros),
                                jnp.asarray(zeros), np.int64(93),
                                np.int64(0),
                                *(jnp.asarray(a) for a in flow_args),
                                ring_len=inst.ring_len)
    dout = torcells_step_window(*dout[:8], jnp.asarray(zeros),
                                jnp.asarray(zeros), np.int64(500),
                                np.int64(0),
                                *(jnp.asarray(a) for a in flow_args),
                                ring_len=inst.ring_len)
    for i in (1, 3, 4, 5, 6, 7):
        np.testing.assert_array_equal(np.asarray(dout[i]), big[i])

    # idle fold: running 100 empty ticks == banking them as idle_ticks
    idle_a = torcells_step_window_numpy(*[x.copy() if hasattr(x, "copy")
                                          else x for x in out[:8]],
                                        zeros, zeros, 100, 0,
                                        *flow_args, inst.ring_len)
    idle_b = torcells_step_window_numpy(*[x.copy() if hasattr(x, "copy")
                                          else x for x in out[:8]],
                                        zeros, zeros, 0, 100,
                                        *flow_args, inst.ring_len)
    np.testing.assert_array_equal(idle_a[3], idle_b[3])   # tokens
    np.testing.assert_array_equal(idle_a[4], idle_b[4])   # delivered


# ---------------------------------------------------------------------------
# engine-level parity + conservation
# ---------------------------------------------------------------------------

def test_engine_device_vs_numpy_plane_digest_parity():
    # NOTE: under the 8-virtual-device test mesh, mode="device" runs the
    # SHARDED layout by default (tpu_devices=0 -> all local devices), so
    # this is simultaneously the sharded-engine vs single-host-twin gate.
    a = _run(mode="device")
    b = _run(mode="numpy")
    assert a.engine.device_plane._shard is not None, \
        "expected the sharded layout under the 8-device test mesh"
    assert state_digest(a.engine) == state_digest(b.engine)
    assert a.engine.device_plane.stats()["forwards"] == \
        b.engine.device_plane.stats()["forwards"]


def test_engine_sharded_vs_single_device_plane_digest_parity():
    """Force single-device layout (tpu_devices=1) and compare against the
    default sharded run: identical digests — multichip is semantics-free."""
    from shadow_tpu.core import configuration
    from shadow_tpu.core.options import Options
    from shadow_tpu.core.controller import Controller

    def run(n_dev):
        cfg = configuration.parse_xml(workloads.tor_network(
            8, n_clients=5, n_servers=2, stoptime=60,
            stream_spec="512:20200", device_data=True))
        cfg.stop_time_sec = 60
        ctrl = Controller(Options(scheduler_policy="global", workers=0,
                                  seed=3, stop_time_sec=60,
                                  log_level="warning", tpu_devices=n_dev),
                          cfg)
        assert ctrl.run() == 0
        return ctrl

    single = run(1)
    sharded = run(8)
    assert single.engine.device_plane._shard is None
    assert sharded.engine.device_plane._shard is not None
    assert state_digest(single.engine) == state_digest(sharded.engine)


def test_engine_policy_parity_with_device_plane():
    a = _run(policy="global")
    b = _run(policy="tpu")
    assert state_digest(a.engine) == state_digest(b.engine)


def test_cell_conservation_and_completion():
    ctrl = _run(stop=120)
    st = ctrl.engine.device_plane.stats()
    assert st["completed"] == st["circuits"], \
        f"only {st['completed']}/{st['circuits']} flows completed"
    # each injected cell is forwarded exactly once per stage (5 stages)
    assert st["forwards"] == st["injected_cells"] * 5
    plane = ctrl.engine.device_plane
    delivered, _done, _sent = plane._read_summaries()
    assert int(delivered[plane.last_flow].sum()) == st["injected_cells"]


def test_varying_dispatch_sizes_preserve_arrivals():
    """The kernel's carried step counter must track the plane's synced step
    exactly across dispatches of VARYING size (round windows are
    event-driven, so n differs every dispatch).  A wrong re-base
    desynchronizes the arrival ring's absolute slots — in-flight cells get
    skipped and arrive a ring revolution late (r4 review repro)."""
    ctrl = _run(stop=120)
    plane = ctrl.engine.device_plane
    # kernel step counter + idle steps banked since the last dispatch ==
    # the plane's synced step (with the off-by-n re-base this diverges by
    # the final dispatch's size)
    assert (int(np.asarray(plane._state[0])) + plane._idle_ticks_banked
            == plane._ticks_synced)


# (test_sharded_windowed_kernel_bit_parity migrated to
# tests/test_meshplane.py: the PR-7 replicated-ring sharded kernel was
# retired by the mesh plane, whose parity suite pins the same contract
# against the partition/exchange kernels.)


def test_auto_consensus_device_clients():
    """auto: consensus clients work on the device plane (VERDICT r4 next
    #6a): the plane predicts each client's path at startup by replaying
    its derived draw over the config-determined consensus; the runtime
    fetch + route cross-check agree, circuits complete, and digests match
    the numpy twin."""
    from shadow_tpu.core.checkpoint import state_digest
    xml = workloads.tor_network(8, n_clients=4, n_servers=1, stoptime=120,
                                stream_spec="512:20200", dirauth=True,
                                device_data=True)
    runs = {}
    for mode in ("numpy", "device"):
        cfg = configuration.parse_xml(xml)
        ctrl = Controller(Options(scheduler_policy="global", workers=0,
                                  seed=3, stop_time_sec=120,
                                  log_level="warning", device_plane=mode),
                          cfg)
        rc = ctrl.run()
        assert rc == 0
        st = ctrl.engine.device_plane.stats()
        assert st["completed"] == st["circuits"] == 4
        runs[mode] = state_digest(ctrl.engine)
    assert runs["numpy"] == runs["device"]


def test_star_bulk_device_plane():
    """Workload #2 on the device plane (VERDICT r4 next #6b): 2-hop
    star-bulk chains, >=90% of traffic on-device, digest parity across
    execution modes."""
    from shadow_tpu.core.checkpoint import state_digest
    xml = workloads.star_bulk(20, stoptime=120, bulk_bytes=262144,
                              device_data=True)
    runs = {}
    for mode in ("numpy", "device"):
        cfg = configuration.parse_xml(xml)
        ctrl = Controller(Options(scheduler_policy="global", workers=0,
                                  seed=7, stop_time_sec=120,
                                  log_level="warning", device_plane=mode),
                          cfg)
        rc = ctrl.run()
        assert rc == 0
        eng = ctrl.engine
        st = eng.device_plane.stats()
        assert st["completed"] == st["circuits"] == 20
        total = st["forwards"] + eng.events_executed
        assert st["forwards"] / total >= 0.9, \
            f"device fraction {st['forwards'] / total:.3f} < 0.9"
        runs[mode] = state_digest(eng)
    assert runs["numpy"] == runs["device"]


def test_check_route_rejects_divergence():
    from shadow_tpu.parallel.device_plane import (DeviceTrafficPlane,
                                                  parse_device_client)

    class FakeEngine:
        shard_count = 1
        options = type("O", (), {})()

    spec = parse_device_client(
        "c0", ["client", "9050", "g0,m0,e0", "dest0", "80", "1",
               "512:51200", "device"])
    plane = object.__new__(DeviceTrafficPlane)
    plane._by_client = {"c0": spec}
    plane.check_route("c0", ["g0", "m0", "e0"])   # matching: no raise
    with pytest.raises(RuntimeError, match="diverged"):
        plane.check_route("c0", ["g0", "m0", "eX"])


def test_plane_refuses_sharded_engines():
    from shadow_tpu.parallel.device_plane import DeviceTrafficPlane

    class FakeEngine:
        shard_count = 2

    with pytest.raises(RuntimeError):
        DeviceTrafficPlane(FakeEngine(), [], mode="device")


def test_parse_device_client_defaults_with_nstreams_omitted():
    """ADVICE r4: 'client 9050 <path> dest 80 device' (nstreams omitted)
    must fall back to the defaults, not crash on int('device')."""
    from shadow_tpu.parallel.device_plane import parse_device_client
    spec = parse_device_client(
        "c0", ["client", "9050", "g0,m0,e0", "dest0", "80", "device"])
    assert spec is not None
    assert spec.cells_down > 0 and spec.cells_up > 0
    assert spec.route_down == ["dest0", "e0", "m0", "g0", "c0"]


def test_duplicate_device_clients_on_one_host_rejected():
    """ADVICE r4 (medium): two device-mode clients on one host would
    silently share a flow keyed by host name — must raise instead."""
    from shadow_tpu.parallel.device_plane import (DeviceTrafficPlane,
                                                  parse_device_client)

    class FakeEngine:
        shard_count = 1
        options = Options = type("O", (), {})()

    spec_a = parse_device_client(
        "c0", ["client", "9050", "g0,m0,e0", "dest0", "80", "1",
               "512:51200", "device"])
    spec_b = parse_device_client(
        "c0", ["client", "9051", "g1,m1,e1", "dest0", "80", "1",
               "512:51200", "device"])
    with pytest.raises(ValueError, match="multiple device-mode"):
        DeviceTrafficPlane(FakeEngine(), [spec_a, spec_b], mode="numpy")


def test_activate_zero_cells_rejected():
    """ADVICE r4: activate(cells=0) could never complete (target>0 gate) —
    the joining client would hang to end_time; reject loudly instead."""
    from shadow_tpu.core import configuration
    from shadow_tpu.core.controller import Controller
    from shadow_tpu.core.options import Options
    from shadow_tpu.parallel.device_plane import build_plane_from_engine
    from shadow_tpu.tools import workloads

    xml = workloads.tor_network(8, n_clients=2, n_servers=1, stoptime=10,
                                stream_spec="512:5120", device_data=True)
    cfg = configuration.parse_xml(xml)
    ctrl = Controller(Options(scheduler_policy="global", workers=0,
                              stop_time_sec=10), cfg)
    ctrl.setup()
    plane = build_plane_from_engine(ctrl.engine, mode="numpy")
    assert plane is not None
    client = plane.specs[0].client_name
    with pytest.raises(ValueError, match="at least 1 cell"):
        plane.activate(client, cells=0)
