"""Cross-policy equivalence on PHOLD (the reference's scheduler stress test,
src/test/phold/test_phold.c): uniform all-to-all traffic run under every
scheduler policy and worker count must produce identical traffic totals —
per-host RNG draws are sequential per host, and packet drops are keyed by
uid, so results are policy- and thread-count-independent."""

import textwrap

import pytest

from shadow_tpu.core import configuration
from shadow_tpu.core.controller import Controller
from shadow_tpu.core.options import Options

N_HOSTS = 8

CONFIG_XML = textwrap.dedent(f"""\
    <shadow stoptime="8">
      <plugin id="phold" path="python:phold" />
      <host id="phold" quantity="{N_HOSTS}" bandwidthdown="10240" bandwidthup="10240">
        <process plugin="phold" starttime="1" arguments="{N_HOSTS} 2 9000" />
      </host>
    </shadow>
""")


def run_phold(policy, workers):
    cfg = configuration.parse_xml(CONFIG_XML)
    opts = Options(scheduler_policy=policy, workers=workers,
                   stop_time_sec=cfg.stop_time_sec)
    ctrl = Controller(opts, cfg)
    rc = ctrl.run()
    assert rc == 0
    totals = tuple(
        (h.tracker.out_remote.packets_data, h.tracker.in_remote.packets_data)
        for h in (ctrl.engine.host_by_name(f"phold{i + 1}")
                  for i in range(N_HOSTS)))
    return totals


@pytest.fixture(scope="module")
def serial_totals():
    return run_phold("global", 0)


@pytest.mark.parametrize("policy,workers", [
    ("host", 4), ("steal", 2), ("steal", 4),
    ("thread", 2), ("threadXthread", 4), ("threadXhost", 4),
    ("tpu", 0), ("tpu", 2),
])
def test_policy_equivalence(policy, workers, serial_totals):
    assert run_phold(policy, workers) == serial_totals


def test_phold_population_constant(serial_totals):
    """The fix for self-directed messages: every host keeps forwarding, so
    everyone sends and receives plenty of messages over 20s."""
    for out_pkts, in_pkts in serial_totals:
        assert out_pkts >= 2, serial_totals
        assert in_pkts >= 2, serial_totals


def test_steal_soak_large_phold():
    """Concurrency soak for the indexed ready-heap + stealing paths: a
    larger PHOLD (36 hosts, 8 worker threads, many rounds) must match the
    serial run exactly.  Shakes the publish/consume races the small
    equivalence fixtures might never hit."""
    n = 36
    xml = textwrap.dedent(f"""\
        <shadow stoptime="6">
          <plugin id="phold" path="python:phold" />
          <host id="phold" quantity="{n}" bandwidthdown="10240" bandwidthup="10240">
            <process plugin="phold" starttime="1" arguments="{n} 3 9000" />
          </host>
        </shadow>
    """)

    def run(policy, workers):
        cfg = configuration.parse_xml(xml)
        ctrl = Controller(Options(scheduler_policy=policy, workers=workers,
                                  stop_time_sec=cfg.stop_time_sec), cfg)
        assert ctrl.run() == 0
        return tuple(
            (h.tracker.out_remote.packets_data,
             h.tracker.in_remote.packets_data)
            for h in (ctrl.engine.host_by_name(f"phold{i + 1}")
                      for i in range(n)))

    serial = run("global", 0)
    assert run("steal", 8) == serial
    assert run("threadXhost", 8) == serial


def test_host_worker_shuffle_deterministic_and_balanced():
    """Satellite (ISSUE 2): host->worker assignment is a Fisher-Yates
    shuffle keyed off the sim seed (reference scheduler.c:437-472), dealt
    round-robin in shuffled order — deterministic per seed, balanced to
    within one host, different across seeds, and NOT the identity
    round-robin (so adversarial config ordering can't pile heavy hosts
    onto one worker)."""
    from collections import Counter

    from shadow_tpu.core.scheduler import Scheduler

    class _H:
        def __init__(self, hid):
            self.id = hid

    def assignment(seed, n=64, workers=4):
        s = Scheduler(None, "host", workers, seed)
        for i in range(1, n + 1):
            s.add_host(_H(i))
        s.finalize_hosts()
        return dict(s.policy._host_worker)

    a = assignment(111)
    assert a == assignment(111), "same seed must give the same assignment"
    assert a != assignment(222), "different seeds should shuffle differently"
    counts = Counter(a.values())
    assert len(counts) == 4
    assert max(counts.values()) - min(counts.values()) <= 1
    round_robin = {hid: (hid - 1) % 4 for hid in a}
    assert a != round_robin, "shuffle degenerated to identity round-robin"
    # late registration (after boot) still lands somewhere valid
    s = Scheduler(None, "host", 4, 111)
    for i in range(1, 9):
        s.add_host(_H(i))
    s.finalize_hosts()
    s.add_host(_H(99))
    assert s.policy._host_worker[99] in (0, 1, 2, 3)
