"""Cross-policy equivalence on PHOLD (the reference's scheduler stress test,
src/test/phold/test_phold.c): uniform all-to-all traffic run under every
scheduler policy and worker count must produce identical traffic totals —
per-host RNG draws are sequential per host, and packet drops are keyed by
uid, so results are policy- and thread-count-independent."""

import textwrap

import pytest

from shadow_tpu.core import configuration
from shadow_tpu.core.controller import Controller
from shadow_tpu.core.options import Options

N_HOSTS = 8

CONFIG_XML = textwrap.dedent(f"""\
    <shadow stoptime="8">
      <plugin id="phold" path="python:phold" />
      <host id="phold" quantity="{N_HOSTS}" bandwidthdown="10240" bandwidthup="10240">
        <process plugin="phold" starttime="1" arguments="{N_HOSTS} 2 9000" />
      </host>
    </shadow>
""")


def run_phold(policy, workers):
    cfg = configuration.parse_xml(CONFIG_XML)
    opts = Options(scheduler_policy=policy, workers=workers,
                   stop_time_sec=cfg.stop_time_sec)
    ctrl = Controller(opts, cfg)
    rc = ctrl.run()
    assert rc == 0
    totals = tuple(
        (h.tracker.out_remote.packets_data, h.tracker.in_remote.packets_data)
        for h in (ctrl.engine.host_by_name(f"phold{i + 1}")
                  for i in range(N_HOSTS)))
    return totals


@pytest.fixture(scope="module")
def serial_totals():
    return run_phold("global", 0)


@pytest.mark.parametrize("policy,workers", [
    ("host", 4), ("steal", 2), ("steal", 4),
    ("thread", 2), ("threadXthread", 4), ("threadXhost", 4),
    ("tpu", 0), ("tpu", 2),
])
def test_policy_equivalence(policy, workers, serial_totals):
    assert run_phold(policy, workers) == serial_totals


def test_phold_population_constant(serial_totals):
    """The fix for self-directed messages: every host keeps forwarding, so
    everyone sends and receives plenty of messages over 20s."""
    for out_pkts, in_pkts in serial_totals:
        assert out_pkts >= 2, serial_totals
        assert in_pkts >= 2, serial_totals


def test_steal_soak_large_phold():
    """Concurrency soak for the indexed ready-heap + stealing paths: a
    larger PHOLD (48 hosts, 8 worker threads, many rounds) must match the
    serial run exactly.  Shakes the publish/consume races the small
    equivalence fixtures might never hit."""
    n = 48
    xml = textwrap.dedent(f"""\
        <shadow stoptime="6">
          <plugin id="phold" path="python:phold" />
          <host id="phold" quantity="{n}" bandwidthdown="10240" bandwidthup="10240">
            <process plugin="phold" starttime="1" arguments="{n} 3 9000" />
          </host>
        </shadow>
    """)

    def run(policy, workers):
        cfg = configuration.parse_xml(xml)
        ctrl = Controller(Options(scheduler_policy=policy, workers=workers,
                                  stop_time_sec=cfg.stop_time_sec), cfg)
        assert ctrl.run() == 0
        return tuple(
            (h.tracker.out_remote.packets_data,
             h.tracker.in_remote.packets_data)
            for h in (ctrl.engine.host_by_name(f"phold{i + 1}")
                      for i in range(n)))

    serial = run("global", 0)
    assert run("steal", 8) == serial
    assert run("threadXhost", 8) == serial
