"""Checkpoint/state-digest subsystem (new capability — the reference has
none, SURVEY.md §5): round-boundary snapshots, deterministic state digests,
and digest-verified replay-based resume."""

import glob
import textwrap

from shadow_tpu.core import configuration
from shadow_tpu.core.checkpoint import (load_snapshot, resume_digest,
                                        save_snapshot, state_digest)
from shadow_tpu.core.controller import Controller
from shadow_tpu.core.options import Options

XML = textwrap.dedent("""\
    <shadow stoptime="90">
      <plugin id="tgen" path="python:tgen" />
      <plugin id="echo" path="python:echo" />
      <host id="server"><process plugin="tgen" starttime="1" arguments="server 80" /></host>
      <host id="c1"><process plugin="tgen" starttime="2" arguments="client server 80 1024:409600" /></host>
      <host id="c2"><process plugin="tgen" starttime="3" arguments="client server 80 2048:204800" /></host>
      <host id="u1"><process plugin="echo" starttime="1" arguments="udp server 9000" /></host>
      <host id="u2"><process plugin="echo" starttime="2" arguments="udp client u1 9000 10 700" /></host>
    </shadow>
""")


def run(policy="global", workers=0, seed=5, stop=90, **opt_kw):
    cfg = configuration.parse_xml(XML)
    cfg.stop_time_sec = stop
    opts = Options(scheduler_policy=policy, workers=workers, seed=seed,
                   stop_time_sec=stop, **opt_kw)
    ctrl = Controller(opts, cfg)
    rc = ctrl.run()
    assert rc == 0
    return ctrl


def test_state_digest_deterministic():
    d1 = state_digest(run().engine)
    d2 = state_digest(run().engine)
    assert d1 == d2


def test_state_digest_cross_policy_parity():
    """The event-order parity metric (BASELINE.json) as one hash: serial,
    host-steal(4 workers), and tpu policies end in the identical state."""
    d_global = state_digest(run(policy="global", workers=0).engine)
    d_steal = state_digest(run(policy="steal", workers=4).engine)
    d_tpu = state_digest(run(policy="tpu", workers=0).engine)
    assert d_global == d_steal == d_tpu


LOSSY_TOPO = """<topology><![CDATA[<?xml version="1.0" encoding="UTF-8"?>
<graphml xmlns="http://graphml.graphdrawing.org/xmlns">
<key id="d0" for="edge" attr.name="latency" attr.type="double"/>
<key id="d1" for="edge" attr.name="packetloss" attr.type="double"/>
<key id="d2" for="node" attr.name="bandwidthdown" attr.type="int"/>
<key id="d3" for="node" attr.name="bandwidthup" attr.type="int"/>
<graph edgedefault="undirected">
  <node id="n0"><data key="d2">10240</data><data key="d3">10240</data></node>
  <edge source="n0" target="n0"><data key="d0">25.0</data><data key="d1">0.03</data></edge>
</graph></graphml>]]></topology>"""


def test_state_digest_sensitive():
    """On a lossy topology the seed changes which packets drop, so final
    states (retransmit counters, cwnd) must differ.  (On a loss-free
    topology different seeds legitimately converge to the same state.)"""
    lossy_xml = XML.replace("<plugin", LOSSY_TOPO + "\n  <plugin", 1)
    cfg_runs = []
    for seed in (5, 6):
        cfg = configuration.parse_xml(lossy_xml)
        cfg.stop_time_sec = 90
        opts = Options(scheduler_policy="global", workers=0, seed=seed,
                       stop_time_sec=90)
        ctrl = Controller(opts, cfg)
        assert ctrl.run() == 0
        cfg_runs.append(state_digest(ctrl.engine))
    assert cfg_runs[0] != cfg_runs[1]


def test_checkpoint_interval_writes(tmp_path):
    ckdir = str(tmp_path / "ck")
    # checkpoints land on round boundaries, and rounds only exist where
    # events do (the engine fast-forwards quiet stretches); a 10s heartbeat
    # guarantees boundaries all along the run
    ctrl = run(checkpoint_interval_sec=20, checkpoint_dir=ckdir,
               heartbeat_interval_sec=10)
    written = sorted(glob.glob(ckdir + "/checkpoint_*.ckpt"))
    assert len(written) >= 3  # ~90s of sim, one per 20s
    snap = load_snapshot(written[0])
    assert snap["sim_time_ns"] >= 20e9
    assert snap["options"]["seed"] == 5
    assert len(snap["hosts"]) == 5
    del ctrl


def test_replay_reaches_snapshot_state(tmp_path):
    """Resume-by-replay: a fresh run of the same config+seed, stopped at the
    snapshot's virtual time, reproduces the snapshot state exactly."""
    ckdir = str(tmp_path / "ck")
    run(checkpoint_interval_sec=30, checkpoint_dir=ckdir)
    snaps = sorted(glob.glob(ckdir + "/checkpoint_*.ckpt"))
    assert snaps
    snap = load_snapshot(snaps[0])
    # replay with an identical config but a second checkpointer: collect the
    # same boundary snapshot and compare digests
    ckdir2 = str(tmp_path / "ck2")
    run(checkpoint_interval_sec=30, checkpoint_dir=ckdir2)
    snap2 = load_snapshot(sorted(glob.glob(ckdir2 + "/checkpoint_*.ckpt"))[0])
    assert snap["digest"] == snap2["digest"]


def test_save_and_resume_digest_roundtrip(tmp_path):
    ctrl = run()
    path = str(tmp_path / "final.ckpt")
    save_snapshot(ctrl.engine, path)
    snap = load_snapshot(path)
    assert resume_digest(snap, ctrl.engine)
    # a run in a genuinely different state (stopped earlier) must not match
    ctrl2 = run(stop=45)
    assert not resume_digest(snap, ctrl2.engine)


def test_checkpoint_every_rounds_writes_verified(tmp_path):
    """--checkpoint-every N: round-cadence snapshots, round-stamped names,
    atomic + digest-verified on load (the crash-recovery substrate)."""
    ckdir = str(tmp_path / "ck")
    ctrl = run(checkpoint_every_rounds=25, checkpoint_dir=ckdir)
    written = sorted(glob.glob(ckdir + "/checkpoint_r*.ckpt"))
    assert len(written) >= 2
    for path in written:
        snap = load_snapshot(path, verify=True)   # raises if corrupt
        assert snap["options"]["seed"] == 5
    # rounds strictly increase with the file names
    rounds = [load_snapshot(p)["rounds"] for p in written]
    assert rounds == sorted(rounds)
    assert not glob.glob(ckdir + "/*.tmp"), "atomic write left a tmp file"
    del ctrl


def test_corrupt_snapshot_detected(tmp_path):
    """A truncated snapshot file fails verified load instead of seeding a
    resume with garbage."""
    import pytest

    ckdir = str(tmp_path / "ck")
    run(checkpoint_every_rounds=25, checkpoint_dir=ckdir)
    path = sorted(glob.glob(ckdir + "/checkpoint_r*.ckpt"))[0]
    import os
    with open(path, "r+b") as f:
        f.truncate(os.path.getsize(path) // 2)
    with pytest.raises(Exception):
        load_snapshot(path, verify=True)


def test_checkpoint_parity_across_policies(tmp_path):
    """Mid-run round-boundary snapshots are policy-independent: the first
    checkpoint written under global, steal x4, and tpu scheduling carries
    the identical state digest (event-order parity at an interior virtual
    time, not just at the end)."""
    digests = {}
    for policy, workers in (("global", 0), ("steal", 4), ("tpu", 0)):
        ckdir = str(tmp_path / f"ck-{policy}{workers}")
        run(policy=policy, workers=workers,
            checkpoint_interval_sec=30, checkpoint_dir=ckdir)
        snaps = sorted(glob.glob(ckdir + "/checkpoint_*.ckpt"))
        assert snaps, (policy, workers)
        digests[(policy, workers)] = load_snapshot(snaps[0])["digest"]
    assert len(set(digests.values())) == 1, digests
