"""Device-resident bandwidth saturation model (ops/saturate_device.py).

Three gates, strongest last:

1. device vs numpy twin — bit-identical state after thousands of ticks;
2. closed-form rates — saturation delivers at exactly the bucket refill
   rate, underload delivers everything;
3. ENGINE cross-check — the same flow through the real simulator (blast
   source -> throttled sink, static drop-tail router, full
   interface/socket stack) delivers and drops EXACTLY the counts the
   device model computes.  This is the north-star bandwidth composition
   validated against the product, not against itself.
"""

import numpy as np
import pytest

from shadow_tpu.core import defs
from shadow_tpu.ops.saturate_device import DeviceSaturate

PAYLOAD = 958
SIZE = PAYLOAD + defs.CONFIG_HEADER_SIZE_UDPIPETH   # 1000 B on the wire


def test_device_matches_numpy_twin():
    rng = np.random.default_rng(11)
    h = 64
    sat = DeviceSaturate(rng.integers(200, 2000, size=h))
    first = rng.integers(0, 50, size=h).astype(np.int64)
    n = rng.integers(100, 3000, size=h).astype(np.int64)
    ticks = 5000
    dev = sat.run_device(first, n, ticks)
    ref = sat.run_numpy(first, n, ticks)
    for d, r, name in zip(dev, ref, ("delivered", "dropped", "queue",
                                     "tokens")):
        assert np.array_equal(d, r), name
    # sanity: the parameter range actually exercises both regimes
    assert (dev[1] > 0).any(), "no host dropped — saturation not exercised"
    assert (dev[1] == 0).any(), "every host dropped — underload not covered"


def test_closed_form_rates():
    # capacity 0.5 pkt/ms (refill 500 B/tick vs 1000 B packets at 1/tick)
    bw = np.array([489, 4882], dtype=np.int64)   # ~500 and ~5000 B/tick
    sat = DeviceSaturate(bw)
    n = np.array([4000, 4000], dtype=np.int64)
    first = np.zeros(2, dtype=np.int64)
    ticks = 30_000          # long enough to drain every backlog
    delivered, dropped, queue, _tok = sat.run_device(first, n, ticks)
    assert (queue == 0).all()
    assert delivered[0] + dropped[0] == 4000
    # underloaded host delivers everything
    assert delivered[1] == 4000 and dropped[1] == 0
    # saturated host: inflow 1 pkt/ms vs drain ~0.5 pkt/ms fills the
    # 1024-packet queue, after which inflow drops; delivered is the queue
    # plus what drained during + after the flow — far from either extreme
    assert 2000 < delivered[0] < 4000
    assert dropped[0] > 500


@pytest.mark.parametrize("bw_kibps,expect_drops", [(489, True),
                                                   (4882, False)])
def test_engine_cross_check(bw_kibps, expect_drops):
    """The device model's delivered/dropped counts equal the REAL engine's
    for the same flow: blast source (1 x 958 B datagram per ms, 4000 total)
    into a receiver whose downlink bucket and static drop-tail router are
    the state the model mirrors."""
    import textwrap

    from shadow_tpu.core import configuration
    from shadow_tpu.core.controller import Controller
    from shadow_tpu.core.options import Options

    # latency 10.3 ms: off the 1 ms refill grid, so arrival/refill
    # ordering is never ambiguous; sender host id < receiver host id puts
    # tied events (arrival, refill restart) in the model's order anyway
    topo = """<?xml version="1.0" encoding="UTF-8"?>
<graphml xmlns="http://graphml.graphdrawing.org/xmlns">
<key id="lat" for="edge" attr.name="latency" attr.type="double"/>
<key id="bd" for="node" attr.name="bandwidthdown" attr.type="int"/>
<key id="bu" for="node" attr.name="bandwidthup" attr.type="int"/>
<graph edgedefault="undirected">
<node id="n0"><data key="bd">1048576</data><data key="bu">1048576</data></node>
<edge source="n0" target="n0"><data key="lat">10.3</data></edge>
</graph></graphml>"""
    n_pkts = 4000
    xml = textwrap.dedent(f"""\
        <shadow stoptime="30">
          <topology><![CDATA[{topo}]]></topology>
          <plugin id="source" path="python:source" />
          <plugin id="sink" path="python:sink" />
          <host id="src" bandwidthdown="1048576" bandwidthup="1048576">
            <process plugin="source" starttime="2"
                     arguments="source dst 9000 {n_pkts} {PAYLOAD} 0.001" />
          </host>
          <host id="dst" bandwidthdown="{bw_kibps}" bandwidthup="1048576">
            <process plugin="sink" starttime="1" arguments="sink 9000" />
          </host>
        </shadow>
    """)
    cfg = configuration.parse_xml(xml)
    ctrl = Controller(Options(scheduler_policy="global", workers=0,
                              stop_time_sec=30, router_queue="static"),
                      cfg)
    assert ctrl.run() == 0
    sink = ctrl.engine.host_by_name("dst").processes[0].app_state
    sat = DeviceSaturate(np.array([bw_kibps], dtype=np.int64))
    delivered, dropped, queue, _tok = sat.run_device(
        np.zeros(1, dtype=np.int64), np.array([n_pkts], dtype=np.int64),
        27_000)   # 27 virtual seconds after the first arrival
    assert queue[0] == 0
    assert sink.received == delivered[0], \
        f"engine delivered {sink.received}, model {delivered[0]}"
    assert n_pkts - sink.received == dropped[0]
    assert (dropped[0] > 0) == expect_drops
