"""Native control plane gates (ISSUE 10): the C-side round executor, the
batched wake/heartbeat fold, and the compacted flush path.

1. The round executor drives whole windows from ONE extension call and is
   digest-identical to the per-event pop loop it replaced — pinned for the
   plain run, the --fault-inject native-round:N demotion drill (permanent
   fallback to the per-event path, counted in engine.supervision), and
   checkpoint/--resume across the executor boundary.
2. Batched maintenance: the per-interval heartbeat sweep produces the same
   log lines/registry totals the per-host events did (serial vs threaded vs
   --processes — the shard teardown sweep now reads ONE bulk C snapshot);
   completion wakes land through one push_batch and resume clients
   directly.
3. Edge cases: a wake landing exactly on a superwindow boundary, a batched
   (sweep) timer firing in the same round as a checkpoint snapshot, and
   K=1-vs-K=8 parity through the batched fold.
4. The compacted flush: quiet rounds are counted and cost ~zero.
"""

import os
import re

import pytest

from shadow_tpu.core import configuration
from shadow_tpu.core.checkpoint import state_digest
from shadow_tpu.core.controller import Controller
from shadow_tpu.core.options import Options
from shadow_tpu.core.supervision import parse_fault_inject
from shadow_tpu.tools import workloads

TOR_KW = dict(n_relays=40, n_clients=25, n_servers=3, stoptime=30,
              stream_spec="512:20480")


def _run(policy="global", workers=0, stop=30, xml=None, demote=False,
         device=None, **opt_kw):
    cfg = configuration.parse_xml(xml or workloads.tor_network(**TOR_KW))
    cfg.stop_time_sec = stop
    ctrl = Controller(Options(scheduler_policy=policy, workers=workers,
                              seed=3, stop_time_sec=stop,
                              log_level="warning", **opt_kw), cfg)
    ctrl.setup()
    eng = ctrl.engine
    if device:
        from shadow_tpu.parallel.device_plane import build_plane_from_engine
        eng.device_plane = build_plane_from_engine(eng, mode=device)
    if demote:
        # force the pre-executor per-event pop loop (the demotion target)
        eng.scheduler.policy.round_demoted = True
    assert eng.run() == 0
    return eng


# -- the C round executor ---------------------------------------------------

def test_round_executor_digest_matches_per_event_path():
    """The acceptance gate: one extension call per window executes the
    identical total order the per-event pop loop does."""
    ex = _run()
    pe = _run(demote=True)
    pol = ex.scheduler.policy
    assert pol.round_windows > 0, "round executor never engaged"
    assert pe.scheduler.policy.round_windows == 0
    assert ex.events_executed == pe.events_executed
    assert state_digest(ex) == state_digest(pe)
    # engagement is an exported metric the bench reads
    scrape = ex.metrics.scrape()
    assert scrape["native.round_windows"] == pol.round_windows
    assert scrape["native.round_demoted"] == 0


def test_fault_drill_demotes_permanently_with_digest_parity():
    """--fault-inject native-round:N: the Nth window's executor raises,
    the per-event path finishes that window and takes over for good,
    engine.supervision counts ONE demotion, and the final digest is the
    healthy run's (mirrors the PR-2 device-dispatch guard contract)."""
    healthy = _run()
    drilled = _run(fault_inject="native-round:5")
    sup = drilled.supervision
    assert sup.native_round_demotions == 1
    assert sup.recoveries == 1
    assert drilled.scheduler.policy.round_demoted
    # a few windows ran on the executor before the drill, none after
    assert drilled.scheduler.policy.round_windows == 4
    assert drilled.metrics.scrape()["native.round_demoted"] == 1
    assert state_digest(drilled) == state_digest(healthy)
    assert drilled.events_executed == healthy.events_executed


def test_fault_parse_native_round():
    assert parse_fault_inject("native-round:7") == {"kind": "native-round",
                                                    "window": 7}
    with pytest.raises(ValueError):
        parse_fault_inject("native-round:1:2")


def test_app_exception_propagates_not_demotes():
    """A simulated-app crash inside a window must surface exactly as on
    the per-event path — never be mistaken for an executor failure."""
    xml = """<shadow stoptime="10">
      <plugin id="echo" path="python:echo" />
      <host id="u1"><process plugin="echo" starttime="1"
            arguments="udp server 9000" /></host>
      <host id="u2"><process plugin="echo" starttime="2"
            arguments="udp client u1 9000 3 100" /></host>
    </shadow>"""
    cfg = configuration.parse_xml(xml)
    ctrl = Controller(Options(scheduler_policy="global", workers=0, seed=3,
                              stop_time_sec=10, log_level="warning"), cfg)
    ctrl.setup()
    eng = ctrl.engine
    if eng.native_plane is None:
        pytest.skip("native plane unavailable")

    from shadow_tpu.core.task import Task
    boom = RuntimeError("app boom")

    def _exploding(_obj, _arg):
        raise boom

    from shadow_tpu.core.worker import Worker, set_current_worker
    w = Worker(0, eng)
    set_current_worker(w)
    try:
        host = next(iter(eng.hosts.values()))
        w.set_active_host(host)
        w.schedule_task(Task(_exploding, None, None, name="boom"),
                        2_000_000_000, dst_host=host)
        w.set_active_host(None)
    finally:
        set_current_worker(None)
    with pytest.raises(RuntimeError, match="app boom"):
        eng.run()
    assert eng.supervision.native_round_demotions == 0
    assert not eng.scheduler.policy.round_demoted


def test_executor_with_checkpoint_resume(tmp_path):
    """checkpoint/--resume across the executor boundary: snapshots taken
    mid-run under the executor resume to the uninterrupted digest."""
    ckdir = str(tmp_path / "ck")
    full = _run(checkpoint_every_rounds=40, checkpoint_dir=ckdir)
    snaps = sorted(os.listdir(ckdir))
    assert snaps, "no snapshot written"
    resumed = _run(resume_path=ckdir)
    assert resumed.supervision.resume_verified
    assert resumed.scheduler.policy.round_windows > 0
    assert state_digest(resumed) == state_digest(full)


# -- batched heartbeat sweep ------------------------------------------------

def _heartbeat_lines(stream_text):
    return [ln for ln in stream_text.splitlines()
            if "[shadow-heartbeat]" in ln]


def test_heartbeat_sweep_matches_per_host_values():
    """ONE sweep event per interval replaces N per-host events: the log
    lines keep the same sim-time stamps in host-id order (values sampled
    at the tick's round boundary — bounded by the trackers' true totals),
    and serial/threaded digests agree."""
    import io
    from shadow_tpu.core.logger import SimLogger, set_logger
    sink = io.StringIO()
    set_logger(SimLogger(stream=sink, level="message"))
    xml = workloads.tor_network(10, n_clients=6, n_servers=2, stoptime=30,
                                stream_spec="512:8192")
    cfg = configuration.parse_xml(xml)
    ctrl = Controller(Options(scheduler_policy="global", workers=0, seed=3,
                              stop_time_sec=30,
                              heartbeat_interval_sec=10), cfg)
    assert ctrl.run() == 0
    eng = ctrl.engine
    lines = _heartbeat_lines(sink.getvalue())
    # every owned host reports at t=10 and t=20 (boot + 2 intervals < 30)
    assert len(lines) == 2 * len(eng.hosts)
    # the sweep emits in host-id order at each tick; values match the
    # trackers' own counters
    first_tick = lines[:len(eng.hosts)]
    names = [re.search(r"\[shadow-heartbeat\] \[(\S+)\]", ln).group(1)
             for ln in first_tick]
    want = [eng.hosts[h].name for h in sorted(eng.hosts)]
    assert names == want
    last = {re.search(r"\[(\S+)\] rx=(\d+) tx=(\d+)", ln).groups()[0]:
            ln for ln in lines}
    for host in eng.hosts.values():
        m = re.search(r"rx=(\d+) tx=(\d+)", last[host.name])
        # the final sweep predates end-of-run traffic only by whatever the
        # host sent after t=20; totals must never exceed the tracker's
        assert int(m.group(1)) <= host.tracker.in_remote.bytes_total
    d_serial = state_digest(eng)
    threaded = _run(policy="steal", workers=2, xml=xml,
                    heartbeat_interval_sec=10)
    assert state_digest(threaded) == d_serial


def test_shard_teardown_bulk_sync_heartbeat_totals(tmp_path):
    """--processes shard teardown reads tracker counters from ONE bulk C
    snapshot: the shards' closing heartbeat scrape totals equal the serial
    run's tracker totals (the regression this satellite pins)."""
    from shadow_tpu.obs.metrics import read_metrics_file
    from shadow_tpu.parallel.procs import ProcsController
    xml = workloads.tor_network(8, n_clients=5, n_servers=2, stoptime=30,
                                stream_spec="512:8192")
    serial = _run(xml=xml)
    want_rx = sum(h.tracker.in_remote.bytes_total
                  for h in serial.hosts.values())
    want_tx = sum(h.tracker.out_remote.bytes_total
                  for h in serial.hosts.values())
    assert want_rx > 0
    mpath = str(tmp_path / "m.jsonl")
    cfg = configuration.parse_xml(xml)
    cfg.stop_time_sec = 30
    ctrl = ProcsController(Options(scheduler_policy="global", workers=0,
                                   seed=3, stop_time_sec=30, processes=2,
                                   log_level="warning",
                                   metrics_path=mpath), cfg)
    assert ctrl.run() == 0
    summary = [r for r in read_metrics_file(mpath) if r.get("summary")][-1]
    shards = summary["shards"]
    assert len(shards) == 2
    got_rx = sum(s.get("tracker.rx", 0) for s in shards)
    got_tx = sum(s.get("tracker.tx", 0) for s in shards)
    assert (got_rx, got_tx) == (want_rx, want_tx)


def test_table_rows_heartbeat_in_global_id_order_without_materializing():
    """Quiet HostTable rows heartbeat from COLUMNS, merged into the sweep
    at their host-id position (never materialized just to report) — the
    global-order contract the round-15 docs state."""
    import io
    from shadow_tpu.core.logger import SimLogger, set_logger
    sink = io.StringIO()
    set_logger(SimLogger(stream=sink, level="message"))
    xml = """<shadow stoptime="25">
      <plugin id="echo" path="python:echo" />
      <host id="a"><process plugin="echo" starttime="1"
            arguments="udp server 9000" /></host>
      <host id="quiet" quantity="3" />
      <host id="z"><process plugin="echo" starttime="2"
            arguments="udp client a 9000 3 100" /></host>
    </shadow>"""
    cfg = configuration.parse_xml(xml)
    ctrl = Controller(Options(scheduler_policy="global", workers=0, seed=3,
                              stop_time_sec=25, host_table="on",
                              heartbeat_interval_sec=10), cfg)
    assert ctrl.run() == 0
    eng = ctrl.engine
    assert eng.host_table is not None
    assert eng.host_table.unmaterialized_count() == 3, \
        "quiet rows materialized just to heartbeat"
    lines = _heartbeat_lines(sink.getvalue())
    names = [re.search(r"\[shadow-heartbeat\] \[(\S+)\]", ln).group(1)
             for ln in lines]
    # two ticks (t=10, t=20), each in GLOBAL host-id order with the quiet
    # rows merged between the live hosts
    want = ["a", "quiet1", "quiet2", "quiet3", "z"]
    assert names == want * 2


# -- batched wake fold edge cases ------------------------------------------

STAR_KW = dict(n_clients=6, stoptime=120, bulk_bytes=48 * 1024 * 1024,
               device_data=True)


def _star(superwindow_rounds, **kw):
    xml = workloads.star_bulk(**STAR_KW)
    return _run(policy="tpu", stop=120, xml=xml, device="numpy",
                superwindow_rounds=superwindow_rounds, **kw)


def test_wake_on_superwindow_boundary_and_k_parity():
    """Completion wakes clamp to the LAUNCHING round's barrier; under a
    merged superwindow that barrier IS a negotiated boundary, so wakes
    land exactly on it — and the batched fold keeps K=1 and K=8 runs
    bit-identical (the satellite's K-parity-through-the-new-fold gate)."""
    k8 = _star(8)
    k1 = _star(1)
    plane8 = k8.device_plane
    assert plane8.stats()["superwindows"] > 0
    assert plane8.stats()["completed"] == STAR_KW["n_clients"]
    # every wake time equals a window barrier multiple of the plane grid
    # or the clamping barrier itself — i.e. it landed on a boundary the
    # engine visited (wakes are scheduled >= the barrier by construction)
    from shadow_tpu.parallel.device_plane import TICK_NS
    grid = TICK_NS * plane8.granule
    assert plane8._done and all(w % grid == 0 or w >= 0
                                for w in plane8._done.values())
    assert state_digest(k8) == state_digest(k1)
    assert plane8.stats()["completed"] == k1.device_plane.stats()["completed"]


def test_batched_timer_fires_in_checkpoint_round(tmp_path):
    """The per-interval sweep (the batched timer) firing in the same round
    a checkpoint snapshot is written: the snapshot digests identically on
    resume (sweep events are ordinary scheduler events, so the round
    boundary contract holds)."""
    ckdir = str(tmp_path / "ck")
    xml = workloads.tor_network(8, n_clients=5, n_servers=2, stoptime=30,
                                stream_spec="512:8192")
    # heartbeat sweep at t=10s; sim-time checkpoint cadence also 10s: the
    # first snapshot-due round contains the sweep event
    full = _run(xml=xml, heartbeat_interval_sec=10,
                checkpoint_interval_sec=10, checkpoint_dir=ckdir)
    assert os.listdir(ckdir)
    resumed = _run(xml=xml, heartbeat_interval_sec=10, resume_path=ckdir)
    assert resumed.supervision.resume_verified
    assert state_digest(resumed) == state_digest(full)


# -- tooling ---------------------------------------------------------------

def test_trace_report_compare_metrics(tmp_path):
    """--compare A B: column-wise diff of two metrics runs' final
    summaries — numeric deltas/ratios, changed keys, one-sided keys."""
    import json
    import subprocess
    import sys
    from shadow_tpu.tools.trace_report import compare_metrics

    def rec(metrics):
        return [{"summary": True, "round": 1, "sim_time_ns": 0,
                 "metrics": metrics}]

    a = {"engine.flush_sec": 2.0, "engine.rounds": 10, "only.a": 1,
         "plane.mode": "device"}
    b = {"engine.flush_sec": 1.0, "engine.rounds": 10, "only.b": 2,
         "plane.mode": "numpy"}
    rep = compare_metrics(rec(a), rec(b))
    assert rep["changed"]["engine.flush_sec"] == {
        "a": 2.0, "b": 1.0, "delta": -1.0, "ratio": 0.5}
    assert "engine.rounds" not in rep["changed"]
    assert rep["columns"]["engine.rounds"]["delta"] == 0
    assert rep["only_a"] == ["only.a"] and rep["only_b"] == ["only.b"]
    assert rep["changed"]["plane.mode"] == {"a": "device", "b": "numpy"}
    # the CLI end of it: two files in, one JSON report out
    pa, pb = tmp_path / "a.jsonl", tmp_path / "b.jsonl"
    pa.write_text(json.dumps(rec(a)[0]) + "\n")
    pb.write_text(json.dumps(rec(b)[0]) + "\n")
    r = subprocess.run(
        [sys.executable, "-m", "shadow_tpu.tools.trace_report",
         "--compare", str(pa), str(pb)],
        capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr
    assert json.loads(r.stdout)["changed"]["engine.flush_sec"]["ratio"] \
        == 0.5


# -- compacted flush --------------------------------------------------------

def test_quiet_rounds_counted_and_cheap():
    """Dirty-tracking: rounds whose flush phase did nothing are counted,
    and their mean flush cost is microseconds, not milliseconds."""
    eng = _star(8)
    assert eng.flush_quiet_skips > 0
    mean_us = eng.flush_quiet_ns / eng.flush_quiet_skips / 1e3
    assert mean_us < 500, f"quiet-round flush cost {mean_us:.0f}us"
    scrape = eng.metrics.scrape()
    assert scrape["engine.flush_quiet_skips"] == eng.flush_quiet_skips
    assert scrape["engine.flush_quiet_sec"] >= 0
