"""Pluggable TCP congestion control (descriptor/tcp_cong.py): each
algorithm completes a lossy bulk transfer, runs deterministically, and
actually changes behavior (the --tcp-congestion-control knob is live).
Reference: tcp_cong.h vtable + --tcp-congestion-control option."""

import textwrap

import pytest

from shadow_tpu.core import configuration
from shadow_tpu.core.checkpoint import state_digest
from shadow_tpu.core.controller import Controller
from shadow_tpu.core.options import Options

LOSSY = textwrap.dedent("""\
    <graphml xmlns="http://graphml.graphdrawing.org/xmlns">
      <key id="lat" for="edge" attr.name="latency" attr.type="double"/>
      <key id="loss" for="edge" attr.name="packetloss" attr.type="double"/>
      <key id="nip" for="node" attr.name="ip" attr.type="string"/>
      <graph edgedefault="undirected">
        <node id="a"><data key="nip">11.0.0.1</data></node>
        <node id="b"><data key="nip">11.0.0.2</data></node>
        <edge source="a" target="b">
          <data key="lat">30.0</data><data key="loss">0.02</data>
        </edge>
        <edge source="a" target="a"><data key="lat">1.0</data></edge>
        <edge source="b" target="b"><data key="lat">1.0</data></edge>
      </graph>
    </graphml>
""")

XML = textwrap.dedent(f"""\
    <shadow stoptime="120">
      <topology><![CDATA[{LOSSY}]]></topology>
      <plugin id="tgen" path="python:tgen" />
      <host id="server" iphint="11.0.0.1" bandwidthdown="20480" bandwidthup="20480">
        <process plugin="tgen" starttime="1" arguments="server 80" />
      </host>
      <host id="client" iphint="11.0.0.2" bandwidthdown="20480" bandwidthup="20480">
        <process plugin="tgen" starttime="2"
                 arguments="client server 80 1024:409600" />
      </host>
    </shadow>
""")


def _run(cc: str):
    cfg = configuration.parse_xml(XML)
    ctrl = Controller(Options(scheduler_policy="global", workers=0,
                              stop_time_sec=cfg.stop_time_sec,
                              tcp_congestion_control=cc), cfg)
    rc = ctrl.run()
    assert rc == 0, cc
    # stream spec is up:down — the 400kB payload flows server -> client
    client = ctrl.engine.host_by_name("client")
    assert client.tracker.in_remote.bytes_data > 400_000, cc
    # the lossy link must actually bite, or this test proves nothing
    server = ctrl.engine.host_by_name("server")
    assert server.tracker.out_remote.packets_retrans > 0, cc
    return state_digest(ctrl.engine)


@pytest.mark.parametrize("cc", ["reno", "aimd", "cubic"])
def test_lossy_bulk_completes_and_is_deterministic(cc):
    assert _run(cc) == _run(cc)


def test_congestion_knob_changes_behavior():
    digests = {cc: _run(cc) for cc in ("reno", "aimd", "cubic")}
    assert len(set(digests.values())) == 3, \
        f"congestion algorithms produced identical runs: {digests}"
