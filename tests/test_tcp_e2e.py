"""TCP stack tests: the reference's loopback/lossless/lossy matrix
(src/test/tcp/*.test.shadow.config.xml) adapted to the rebuilt stack, plus
retransmit-tally unit tests (native C++ lib vs pure-Python parity)."""

import textwrap

import pytest

from shadow_tpu.core import configuration
from shadow_tpu.core.controller import Controller
from shadow_tpu.core.options import Options
from shadow_tpu.descriptor.retransmit_tally import (PyTally, native_available,
                                                    make_tally)

LOSSY_GRAPHML = textwrap.dedent("""\
    <?xml version="1.0" encoding="UTF-8"?>
    <graphml xmlns="http://graphml.graphdrawing.org/xmlns">
      <key id="d0" for="node" attr.name="ip" attr.type="string"/>
      <key id="d5" for="edge" attr.name="latency" attr.type="double"/>
      <key id="d6" for="edge" attr.name="packetloss" attr.type="double"/>
      <graph edgedefault="undirected">
        <node id="v0"><data key="d0">10.0.0.1</data></node>
        <node id="v1"><data key="d0">10.0.0.2</data></node>
        <edge source="v0" target="v1">
          <data key="d5">10.0</data><data key="d6">{loss}</data>
        </edge>
        <edge source="v0" target="v0"><data key="d5">1.0</data></edge>
        <edge source="v1" target="v1"><data key="d5">1.0</data></edge>
      </graph>
    </graphml>
""")


def two_host_xml(client_args, loss=0.0, stop=120, server_args="tcp server 8000",
                 plugin="echo"):
    topo = LOSSY_GRAPHML.format(loss=loss) if loss >= 0 else None
    topo_el = f"<topology><![CDATA[{topo}]]></topology>" if topo else ""
    return textwrap.dedent(f"""\
        <shadow stoptime="{stop}">
          {topo_el}
          <plugin id="app" path="python:{plugin}" />
          <host id="server" bandwidthdown="10240" bandwidthup="10240" iphint="10.0.0.1">
            <process plugin="app" starttime="1" arguments="{server_args}" />
          </host>
          <host id="client" bandwidthdown="10240" bandwidthup="10240" iphint="10.0.0.2">
            <process plugin="app" starttime="2" arguments="{client_args}" />
          </host>
        </shadow>
    """)


def run_sim(xml, policy="global", workers=0, stop=120, seed=42):
    cfg = configuration.parse_xml(xml)
    cfg.stop_time_sec = stop
    opts = Options(scheduler_policy=policy, workers=workers,
                   stop_time_sec=stop, seed=seed)
    ctrl = Controller(opts, cfg)
    rc = ctrl.run()
    return rc, ctrl


def client_proc(ctrl):
    return ctrl.engine.host_by_name("client").processes[0]


# ---------------------------------------------------------------------------
# handshake + echo matrix
# ---------------------------------------------------------------------------

def test_tcp_echo_lossless():
    rc, ctrl = run_sim(two_host_xml("tcp client server 8000 5 2048"))
    assert rc == 0
    p = client_proc(ctrl)
    assert p.exited and p.exit_code == 0


def test_tcp_echo_lossy():
    """10% loss: retransmit/SACK machinery must still deliver everything."""
    rc, ctrl = run_sim(two_host_xml("tcp client server 8000 5 2048", loss=0.1,
                                    stop=300), stop=300)
    assert rc == 0
    p = client_proc(ctrl)
    assert p.exited and p.exit_code == 0


def test_tcp_echo_loopback():
    xml = textwrap.dedent("""\
        <shadow stoptime="60">
          <plugin id="echo" path="python:echo" />
          <host id="box" bandwidthdown="10240" bandwidthup="10240">
            <process plugin="echo" starttime="1" arguments="tcp server 8000" />
            <process plugin="echo" starttime="2"
                     arguments="tcp client localhost 8000 5 2048" />
          </host>
        </shadow>
    """)
    rc, ctrl = run_sim(xml, stop=60)
    assert rc == 0
    box = ctrl.engine.host_by_name("box")
    assert box.processes[1].exit_code == 0


def test_tcp_bulk_transfer_lossless():
    """Bulk download exercises cwnd growth + flow control (256 KiB)."""
    rc, ctrl = run_sim(two_host_xml(
        "client server 80 2", server_args="server 80 262144",
        plugin="filetransfer", stop=300), stop=300)
    assert rc == 0
    p = client_proc(ctrl)
    assert p.exited and p.exit_code == 0


def test_tcp_bulk_transfer_lossy():
    """64 KiB through 5% loss: SACK-driven recovery, no livelock."""
    rc, ctrl = run_sim(two_host_xml(
        "client server 80 1", server_args="server 80 65536",
        plugin="filetransfer", loss=0.05, stop=600), stop=600)
    assert rc == 0
    p = client_proc(ctrl)
    assert p.exited and p.exit_code == 0
    # loss actually happened and was repaired
    server = ctrl.engine.host_by_name("server")
    assert server.tracker.out_remote.packets_retrans > 0


def test_tcp_lossy_deterministic():
    xml = two_host_xml("tcp client server 8000 3 4096", loss=0.1, stop=300)
    rc1, c1 = run_sim(xml, stop=300)
    rc2, c2 = run_sim(xml, stop=300)
    assert rc1 == rc2 == 0
    assert c1.engine.events_executed == c2.engine.events_executed
    assert c1.engine.rounds_executed == c2.engine.rounds_executed


def test_tcp_parallel_host_policy():
    rc, ctrl = run_sim(two_host_xml("tcp client server 8000 5 2048"),
                       policy="host", workers=2)
    assert rc == 0
    assert client_proc(ctrl).exit_code == 0


# ---------------------------------------------------------------------------
# epoll-driven (nonblocking) server — reference tcp-nonblocking-epoll tests
# ---------------------------------------------------------------------------

def _register_epoll_echo():
    from shadow_tpu.apps.registry import register, _APPS  # noqa

    if "epoll_echo" in _APPS:
        return

    @register("epoll_echo")
    def epoll_echo(api, args):
        port = int(args[0]) if args else 8000
        lfd = api.socket("tcp")
        api.bind(lfd, ("0.0.0.0", port))
        api.listen(lfd)
        epfd = api.epoll_create()
        api.epoll_ctl(epfd, "add", lfd, 1)  # EPOLLIN-ish: readable
        conns = set()
        while True:
            events = yield from api.epoll_wait(epfd)
            for fd, _ev in events:
                if fd == lfd:
                    cfd, _peer = yield from api.accept(lfd)
                    conns.add(cfd)
                    api.epoll_ctl(epfd, "add", cfd, 1)
                else:
                    data = api.try_recvfrom(fd)
                    if data is None:
                        continue
                    buf = data[0]
                    if not buf:
                        api.epoll_ctl(epfd, "del", fd)
                        api.close(fd)
                        conns.discard(fd)
                        continue
                    yield from api.send(fd, buf)


def test_tcp_epoll_server():
    _register_epoll_echo()
    xml = textwrap.dedent("""\
        <shadow stoptime="120">
          <plugin id="srv" path="python:epoll_echo" />
          <plugin id="cli" path="python:echo" />
          <host id="server" bandwidthdown="10240" bandwidthup="10240">
            <process plugin="srv" starttime="1" arguments="8000" />
          </host>
          <host id="client" bandwidthdown="10240" bandwidthup="10240">
            <process plugin="cli" starttime="2"
                     arguments="tcp client server 8000 4 1024" />
          </host>
        </shadow>
    """)
    rc, ctrl = run_sim(xml)
    assert rc == 0
    assert client_proc(ctrl).exit_code == 0


# ---------------------------------------------------------------------------
# retransmit tally: native/python parity + semantics
# ---------------------------------------------------------------------------

OPS = [
    ("mark_sacked", 100, 200),
    ("mark_sacked", 300, 400),
    ("mark_retransmitted", 0, 50),
    ("update_lost", 0, 500, 3),
    ("mark_sacked", 450, 500),
    ("update_lost", 0, 500, 4),
    ("advance_una", 250),
]


def apply_ops(t):
    for op, *args in OPS:
        getattr(t, op)(*args)
    return t


def test_pytally_semantics():
    t = apply_ops(PyTally())
    # after una=250: sacked keeps [300,400)+[450,500); lost covers the
    # unsacked/unretransmitted gaps above una
    assert t.total_sacked() == 150
    lost = t.lost_ranges()
    assert (250, 300) in lost and (400, 450) in lost
    assert t.is_sacked(310, 390)
    assert not t.is_sacked(200, 310)
    assert t.highest_sacked() == 500


@pytest.mark.skipif(not native_available(), reason="native tally not built")
def test_native_tally_matches_python():
    py = apply_ops(PyTally())
    nat = apply_ops(make_tally())
    assert type(nat).__name__ == "NativeTally"
    assert nat.lost_ranges() == py.lost_ranges()
    assert nat.total_sacked() == py.total_sacked()
    assert nat.total_lost() == py.total_lost()
    assert nat.highest_sacked() == py.highest_sacked()
    nat.close()


def test_tally_sack_clears_lost():
    t = make_tally()
    t.mark_lost(0, 100)
    t.mark_sacked(25, 75)
    lost = t.lost_ranges()
    assert (0, 25) in lost and (75, 100) in lost and len(lost) == 2
    t.close()
