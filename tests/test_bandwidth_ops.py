"""Device token-bucket admission (ops/bandwidth.py) vs the event-driven
host implementation: bit-for-bit parity.

The oracle drives the same TokenBucket math the CPU policies use
(host/network_interface.py) as an explicit event loop — refill at every
1 ms tick, FIFO whole-packet drain, capacity cap across idle gaps — and
the kernel's one-scan answer must match it exactly for every packet.
"""

import numpy as np

from shadow_tpu.core import defs
from shadow_tpu.ops.bandwidth import REFILL_NS, BandwidthKernel, bucket_params


def oracle_admit(dst_rows, sizes, arrive, tokens0, refill, capacity):
    """Event-driven FIFO drain per host, ticks at absolute 1 ms boundaries."""
    admits = np.zeros(len(dst_rows), dtype=np.int64)
    order = np.lexsort((np.arange(len(dst_rows)), arrive, dst_rows))
    state = {}   # dst -> [tick, tokens, last_admit]
    for i in order:
        d = int(dst_rows[i])
        a = int(arrive[i])
        size = int(sizes[i])
        ref = max(int(refill[d]), 1)
        cap = int(capacity[d])
        if d not in state:
            state[d] = [a // REFILL_NS, int(tokens0[d]), 0]
        tick, tok, last = state[d]
        start = max(a, last)
        # refill ticks elapsed while idle (capped at capacity)
        stick = start // REFILL_NS
        tok = min(cap, tok + ref * (stick - tick))
        tick = stick
        while tok < size:            # wait tick by tick (the refill task)
            tick += 1
            tok = min(cap, tok + ref)
        admit = max(start, tick * REFILL_NS)
        tok -= size
        state[d] = [tick, tok, admit]
        admits[i] = admit
    return admits


def _random_case(rng, n_hosts=8, n_pkts=400, span_ns=20 * REFILL_NS):
    dst = rng.integers(0, n_hosts, size=n_pkts).astype(np.int32)
    sizes = rng.integers(60, defs.CONFIG_MTU + 1, size=n_pkts).astype(np.int64)
    arrive = rng.integers(10 * REFILL_NS, 10 * REFILL_NS + span_ns,
                          size=n_pkts).astype(np.int64)
    rates = rng.integers(80, 2000, size=n_hosts).astype(np.int64)  # KiB/s
    refill, capacity = bucket_params(rates)
    tokens0 = rng.integers(0, capacity + 1, size=n_hosts).astype(np.int64)
    return dst, sizes, arrive, tokens0, refill, capacity, rates


def test_kernel_matches_event_driven_oracle():
    rng = np.random.default_rng(17)
    for trial in range(5):
        dst, sizes, arrive, tokens0, refill, capacity, rates = \
            _random_case(rng)
        kern = BandwidthKernel(rates)
        got = kern.admit(dst, sizes, arrive, tokens0)
        want = oracle_admit(dst, sizes, arrive, tokens0, refill, capacity)
        assert np.array_equal(got, want), f"trial {trial} diverged"


def test_capacity_cap_binds_across_idle_gaps():
    """A long idle gap must not accumulate tokens past capacity: the burst
    after the gap is throttled exactly as the capped bucket dictates."""
    rates = np.array([100], dtype=np.int64)           # 100 KiB/s -> small cap
    refill, capacity = bucket_params(rates)
    # burst of 20 MTU packets after a 1-second idle gap
    n = 20
    dst = np.zeros(n, dtype=np.int32)
    sizes = np.full(n, defs.CONFIG_MTU, dtype=np.int64)
    arrive = np.full(n, 2 * 10**9, dtype=np.int64)
    tokens0 = capacity.copy()                          # full at first arrival
    kern = BandwidthKernel(rates)
    got = kern.admit(dst, sizes, arrive, tokens0)
    want = oracle_admit(dst, sizes, arrive, tokens0, refill, capacity)
    assert np.array_equal(got, want)
    # with an uncapped bucket the whole burst would pass at t=2s; the cap
    # forces most of it to wait for refill ticks
    assert (got > arrive).sum() > n // 2


def test_saturated_host_spreads_over_ticks():
    """Sustained overload: admissions advance one refill's worth per tick."""
    rates = np.array([1000], dtype=np.int64)
    refill, capacity = bucket_params(rates)
    n = 50
    dst = np.zeros(n, dtype=np.int32)
    sizes = np.full(n, defs.CONFIG_MTU, dtype=np.int64)
    arrive = np.full(n, 10**9, dtype=np.int64)
    tokens0 = np.zeros(1, dtype=np.int64)
    kern = BandwidthKernel(rates)
    got = kern.admit(dst, sizes, arrive, tokens0)
    want = oracle_admit(dst, sizes, arrive, tokens0, refill, capacity)
    assert np.array_equal(got, want)
    assert np.all(np.diff(np.sort(got)) >= 0)
    assert got.max() > got.min()   # genuinely spread over multiple ticks
