"""simrace (shadow_tpu/analysis/simrace.py): the concurrency &
shard-protocol static-analysis pass, ISSUE 5's tentpole.

Fixture pairs (fire + suppress) for every SIM1xx rule and the protocol
checker (including the deliberately desynced send/recv pair the ISSUE
requires), the lock/alias/collection identity model, the cross-tool
pragma-ownership semantics (simlint ignores SIM1xx pragmas, simrace
ignores SIM00x pragmas — each judges staleness only for rules it runs),
the ``--diff BASE`` incremental mode, the JSON schema and CLI — and THE
GATE: simrace over all of shadow_tpu/ must report ZERO unsuppressed
findings, so every lock-order edge, thread-sharing seam and protocol tag
added by a future PR is proven (or justified in-code) forever.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import textwrap

import pytest

from shadow_tpu.analysis.simlint import (Config, lint_source, load_config)
from shadow_tpu.analysis.simrace import race_paths, race_sources

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _race(src: str, relpath: str = "shadow_tpu/fake/mod.py",
          config: Config = None):
    return race_sources({relpath: textwrap.dedent(src)}, config)


def _rules_of(findings):
    return sorted({f.rule for f in findings if not f.suppressed})


# ---------------------------------------------------------------------------
# SIM101 — lock-order inversion


_SIM101_FIXTURE = """
    import threading

    class S:
        def __init__(self):
            self.alock = threading.Lock()
            self.block = threading.Lock()

        def one(self):
            with self.alock:
                with self.block:{P1}
                    pass

        def two(self):
            with self.block:
                with self.alock:{P2}
                    pass
"""


def test_sim101_fires_on_inversion():
    out = _race(_SIM101_FIXTURE.replace("{P1}", "").replace("{P2}", ""))
    assert _rules_of(out) == ["SIM101"]
    assert len([f for f in out if f.rule == "SIM101"]) == 2
    assert "opposite order" in out[0].message


def test_sim101_suppressible_with_reason():
    src = _SIM101_FIXTURE.replace(
        "{P1}", "  # simlint: disable=SIM101 -- fixture justification"
    ).replace(
        "{P2}", "  # simlint: disable=SIM101 -- fixture justification")
    out = _race(src)
    assert _rules_of(out) == []
    assert sorted(f.rule for f in out if f.suppressed) == ["SIM101"] * 2


def test_sim101_quiet_on_consistent_order_and_collections():
    # consistent nesting is fine; two members of ONE lock collection are
    # unordered peers, not an inversion
    out = _race("""
        import threading

        class S:
            def __init__(self):
                self.alock = threading.Lock()
                self.block = threading.Lock()
                self._host_locks = {}
                for i in range(4):
                    self._host_locks[i] = threading.Lock()

            def one(self):
                with self.alock:
                    with self.block:
                        pass

            def two(self, a, b):
                with self.alock:
                    with self.block:
                        pass
                with self._host_locks[a]:
                    with self._host_locks[b]:
                        pass
    """)
    assert out == []


def test_sim101_sees_through_alias_and_calls():
    # an inversion completed by a helper CALLED under a lock, with one
    # lock reached through a local alias
    out = _race("""
        import threading

        class S:
            def __init__(self):
                self.alock = threading.Lock()
                self.blocks = {}
                self.blocks[0] = threading.Lock()

            def _inner(self):
                lk = self.blocks.get(0)
                lk.acquire()
                lk.release()

            def one(self):
                with self.alock:
                    self._inner()

            def two(self):
                with self.blocks[0]:
                    with self.alock:
                        pass
    """)
    assert _rules_of(out) == ["SIM101"]


# ---------------------------------------------------------------------------
# SIM102 — unsynchronized thread-shared state


_SIM102_FIXTURE = """
    import threading

    def guarded_collect(handle):
        box = {}

        def _work():
            box["out"] = handle{PRAGMA}

        th = threading.Thread(target=_work, daemon=True)
        th.start()
        th.join(5.0)
        return box.get("out")
"""


def test_sim102_fires_on_unlocked_result_box():
    out = _race(_SIM102_FIXTURE.replace("{PRAGMA}", ""))
    assert _rules_of(out) == ["SIM102"]
    assert "`box`" in out[0].message and "_work" in out[0].message


def test_sim102_suppressible_with_reason():
    out = _race(_SIM102_FIXTURE.replace(
        "{PRAGMA}", "  # simlint: disable=SIM102 -- joined before read"))
    assert _rules_of(out) == []
    supp = [f for f in out if f.suppressed]
    assert [f.rule for f in supp] == ["SIM102"]
    assert supp[0].reason == "joined before read"


def test_sim102_quiet_when_both_sides_locked():
    out = _race("""
        import threading

        def guarded_collect(handle):
            box = {}
            lk = threading.Lock()

            def _work():
                with lk:
                    box["out"] = handle

            th = threading.Thread(target=_work, daemon=True)
            th.start()
            th.join(5.0)
            with lk:
                return box.get("out")
    """)
    assert out == []


def test_sim102_ignores_prestart_setup_and_thread_locals():
    # accesses BEFORE Thread(...) are ordered by start(); names local to
    # the target are its own business
    out = _race("""
        import threading

        def spawn(n):
            jobs = [n]
            jobs.append(n + 1)

            def _work():
                mine = []
                mine.append(1)
                return jobs[0]

            th = threading.Thread(target=_work)
            th.start()
            th.join()
    """)
    assert out == []


def test_sim102_method_target_self_attr():
    out = _race("""
        import threading

        class W:
            def __init__(self):
                self.results = []
                self._t = None

            def start(self):
                self._t = threading.Thread(target=self._work)
                self._t.start()

            def _work(self):
                self.results.append(1)

            def harvest(self):
                return list(self.results)
    """)
    assert _rules_of(out) == ["SIM102"]


# ---------------------------------------------------------------------------
# SIM103 — blocking under a lock


_SIM103_FIXTURE = """
    import threading

    class Exchange:
        def __init__(self):
            self._lock = threading.Lock()

        def take(self, conn):
            with self._lock:
                return conn.recv(){PRAGMA}
"""


def test_sim103_fires_on_recv_under_lock():
    out = _race(_SIM103_FIXTURE.replace("{PRAGMA}", ""))
    assert _rules_of(out) == ["SIM103"]
    assert ".recv()" in out[0].message


def test_sim103_suppressible_with_reason():
    out = _race(_SIM103_FIXTURE.replace(
        "{PRAGMA}",
        "  # simlint: disable=SIM103 -- peer replies within one poll"))
    assert _rules_of(out) == []
    assert [f.rule for f in out if f.suppressed] == ["SIM103"]


def test_sim103_fires_on_sleep_and_unbounded_join_under_lock():
    out = _race("""
        import threading
        import time as _wt

        class S:
            def __init__(self):
                self._lock = threading.Lock()

            def f(self, th):
                with self._lock:
                    _wt.sleep(1.0)
                    th.join()
    """)
    assert [f.rule for f in out] == ["SIM103", "SIM103"]


def test_sim103_quiet_outside_lock_and_condition_wait():
    out = _race("""
        import threading
        import time as _wt

        class Latch:
            def __init__(self):
                self._cond = threading.Condition()
                self._count = 1

            def await_(self):
                with self._cond:
                    while self._count > 0:
                        self._cond.wait()

        def poll(conn, th):
            data = conn.recv()
            th.join(timeout=5.0)
            return data
    """)
    assert out == []


# ---------------------------------------------------------------------------
# SIM110 — shard-protocol checker


_PROTOCOL_CLEAN = """
    import multiprocessing as mp

    def _child(conn, options):
        conn.send(("ready", 1, 2))
        while True:
            msg = conn.recv()
            kind = msg[0]
            if kind == "stop":
                break
            if kind == "collect":
                conn.send(("hosts", {}))
                continue
            ws, we = msg[1], msg[2]
            conn.send(("out", []))
            inbox = conn.recv()[1]
            conn.send(("min", ws, 0))
        conn.send(("final", {}))

    def run(options, n):
        ctx = mp.get_context("spawn")
        conns = []
        for sid in range(n):
            pa, ch = ctx.Pipe()
            p = ctx.Process(target=_child, args=(ch, options))
            p.start()
            conns.append(pa)
        readies = [c.recv() for c in conns]
        while True:
            if options.done:
                break
            for c in conns:
                c.send(("run", 0, 1))
            outs = [c.recv()[1] for c in conns]
            for c in conns:
                c.send(("in", []))
            mins = [c.recv() for c in conns]
            if options.checkpoint:
                for c in conns:
                    c.send(("collect",))
                hosts = [c.recv()[1] for c in conns]
        for c in conns:
            c.send(("stop",))
        finals = [c.recv()[1] for c in conns]
        return finals
"""


def test_sim110_clean_protocol_passes():
    assert _race(_PROTOCOL_CLEAN) == []


_PROTOCOL_UNHANDLED = """
    import multiprocessing as mp

    def _child(conn):
        while True:
            msg = conn.recv()
            kind = msg[0]
            if kind == "stop":
                break
            if kind == "run":
                conn.send(("out", msg[1]))
                continue
            raise ValueError(msg)
        conn.send(("final", 1))

    def run(options):
        ctx = mp.get_context("spawn")
        pa, ch = ctx.Pipe()
        p = ctx.Process(target=_child, args=(ch,))
        p.start()
        while True:
            if options.done:
                break
            pa.send(("run", 0))
            out = pa.recv()
            pa.send(("prefetch", 0)){PRAGMA}
        pa.send(("stop",))
        final = pa.recv()
        return final
"""


def test_sim110_unhandled_tag_fires_and_suppresses():
    # the child dispatches exhaustively (unknown tag raises): a parent
    # tag with no child branch is a missing handler
    out = _race(_PROTOCOL_UNHANDLED.replace("{PRAGMA}", ""))
    assert "SIM110" in _rules_of(out)
    assert any('"prefetch"' in f.message and "no handler" in f.message
               for f in out)
    sup = _race(_PROTOCOL_UNHANDLED.replace(
        "{PRAGMA}",
        "  # simlint: disable=SIM110 -- fixture justification"))
    assert not any('"prefetch"' in f.message
                   for f in sup if not f.suppressed)


def test_sim110_desynced_round_trip_fails():
    """The ISSUE's required fixture: a deliberately desynced send/recv
    pair — the parent expects one more reply than the child sends —
    must fail with a mutual-wait finding."""
    out = _race("""
        import multiprocessing as mp

        def _child(conn):
            msg = conn.recv()
            conn.send(("ack", 1))
            msg2 = conn.recv()
            conn.send(("done", 1))

        def run():
            ctx = mp.get_context("spawn")
            pa, ch = ctx.Pipe()
            p = ctx.Process(target=_child, args=(ch,))
            p.start()
            pa.send(("cfg", 1))
            first = pa.recv()
            second = pa.recv()
            return first, second
    """)
    assert _rules_of(out) == ["SIM110"]
    assert any("mutual wait" in f.message for f in out)


def test_sim110_arity_mismatch_fires():
    out = _race("""
        import multiprocessing as mp

        def _child(conn):
            msg = conn.recv()
            ws, we = msg[1], msg[2]
            conn.send(("out", ws))

        def run():
            ctx = mp.get_context("spawn")
            pa, ch = ctx.Pipe()
            p = ctx.Process(target=_child, args=(ch,))
            p.start()
            pa.send(("run", 5))
            out = pa.recv()
            return out
    """)
    assert _rules_of(out) == ["SIM110"]
    assert any("arity" in f.message for f in out)


def test_sim110_stale_handler_is_drift():
    # the child matches a tag the parent never sends: drift the checker
    # reports even though nothing hangs
    out = _race("""
        import multiprocessing as mp

        def _child(conn):
            while True:
                msg = conn.recv()
                if msg[0] == "stop":
                    break
                if msg[0] == "rewind":
                    conn.send(("ok", 1))
                    continue
            conn.send(("final", 1))

        def run():
            ctx = mp.get_context("spawn")
            pa, ch = ctx.Pipe()
            p = ctx.Process(target=_child, args=(ch,))
            p.start()
            pa.send(("stop",))
            final = pa.recv()
            return final
    """)
    assert _rules_of(out) == ["SIM110"]
    assert any("rewind" in f.message and "never" in f.message
               for f in out)


def test_sim110_else_body_enters_the_automaton():
    # a dispatch chain's else is the unknown-tag path: a SEND there must
    # register (no false stale-handler), a RAISE there must make unknown
    # tags "unhandled" — neither may be silently dropped
    sending_else = """
        import multiprocessing as mp

        def _child(conn):
            while True:
                msg = conn.recv()
                if msg[0] == "stop":
                    break
                else:
                    conn.send(("echo", msg))
            conn.send(("final", 1))

        def run(options):
            ctx = mp.get_context("spawn")
            pa, ch = ctx.Pipe()
            p = ctx.Process(target=_child, args=(ch,))
            p.start()
            while True:
                if options.done:
                    break
                pa.send(("work", 1))
                if pa.recv()[0] == "echo":
                    continue
            pa.send(("stop",))
            final = pa.recv()
            return final
    """
    out = _race(sending_else)
    assert not any("echo" in f.message and "never sends" in f.message
                   for f in out), "else-body send was dropped"
    raising_else = """
        import multiprocessing as mp

        def _child(conn):
            while True:
                msg = conn.recv()
                if msg[0] == "stop":
                    break
                elif msg[0] == "work":
                    conn.send(("done", 1))
                else:
                    raise ValueError(msg)
            conn.send(("final", 1))

        def run(options):
            ctx = mp.get_context("spawn")
            pa, ch = ctx.Pipe()
            p = ctx.Process(target=_child, args=(ch,))
            p.start()
            while True:
                if options.done:
                    break
                pa.send(("work", 1))
                done = pa.recv()
                pa.send(("mystery", 1))
            pa.send(("stop",))
            final = pa.recv()
            return final
    """
    out = _race(raising_else)
    assert any('"mystery"' in f.message and "no handler" in f.message
               for f in out), "raising else did not mark unknown tags"


def test_sim110_payload_binding_is_not_the_message():
    # `x = conn.recv()[1]` binds the PAYLOAD: its subscripts must not be
    # charged against the message arity
    out = _race("""
        import multiprocessing as mp

        def _child(conn):
            payload = conn.recv()[1]
            v = payload[5]
            conn.send(("out", v))

        def run():
            ctx = mp.get_context("spawn")
            pa, ch = ctx.Pipe()
            p = ctx.Process(target=_child, args=(ch,))
            p.start()
            pa.send(("run", [0, 1, 2, 3, 4, 5]))
            out = pa.recv()
            return out
    """)
    assert not any("arity" in f.message for f in out)


_PROTOCOL_HEALED = """
    import multiprocessing as mp

    def _child(conn):
        try:
            conn.send(("ready", 1))
            while True:
                msg = conn.recv()
                kind = msg[0]
                if kind == "stop":
                    break{EXTRA}
                conn.send(("out", []))
            conn.send(("final", 1))
        except Exception as e:
            conn.send(("error", str(e)))

    def _recv_watch(conn, proc):
        while True:
            if conn.poll(0.5):
                msg = conn.recv()
                if msg[0] == "error":
                    raise RuntimeError(msg[1])
                return msg
            if not proc.is_alive():
                raise RuntimeError("dead")

    class Ctl:
        def _spawn(self, sid):
            pa, ch = mp.get_context("spawn").Pipe()
            p = mp.get_context("spawn").Process(target=_child,
                                                args=(ch,))
            p.start()
            self.conns[sid] = pa
            self.procs[sid] = p

        def _send(self, sid, msg):
            self.conns[sid].send(msg)

        def _recv(self, sid):
            return _recv_watch(self.conns[sid], self.procs[sid])

        def run(self, n):
            for sid in range(n):
                self._spawn(sid)
            readies = [self._recv(sid) for sid in range(n)]
            sent = [False] * n
            outs = {}
            while True:
                if self.done:
                    break
                for sid in range(n):
                    if not sent[sid]:
                        self._send(sid, ("run", 0, 1)){DRIFT}
                        sent[sid] = True
                for sid in range(n):
                    if sid not in outs:
                        outs[sid] = self._recv(sid)[1]
            for sid in range(n):
                self._send(sid, ("stop",))
            finals = [self._recv(sid)[1] for sid in range(n)]
            return finals
"""


def test_sim110_healed_controller_shape_is_clean():
    """The self-healing controller idiom must model-check clean: the
    spawn lives in a protocol-silent helper (root hoists to the caller
    that drives the conversation), sends route through a `_send`
    wrapper (literal payload bound by parameter position), the recv
    helper returns from inside its watchdog loop (Return is a function
    exit, not a loop backedge), and crash-retry guards (`if not
    sent[sid]: send; sent[sid] = True`) are happy-path-unconditional."""
    out = _race(_PROTOCOL_HEALED.replace("{EXTRA}", "")
                .replace("{DRIFT}", ""))
    assert out == [], "\n".join(f.render() for f in out)


def test_sim110_wrapper_sends_still_carry_drift():
    """The wrapper is seen THROUGH, not skipped: a tag the parent only
    ever sends via `self._send(...)` that the child matches but never
    receives a send for (or vice versa) still registers.  Here the
    child explicitly matches a tag the parent never sends."""
    out = _race(_PROTOCOL_HEALED
                .replace("{EXTRA}", "\n                if kind == "
                         "\"reload\":\n                    continue")
                .replace("{DRIFT}", ""))
    assert "SIM110" in _rules_of(out)
    assert any('"reload"' in f.message and "never" in f.message
               for f in out)


def test_sim110_real_procs_protocol_is_clean():
    """The production shard protocol itself must model-check clean —
    this is the per-module view of what the package gate enforces."""
    from shadow_tpu.analysis.protocol import ShardProtocolRule
    from shadow_tpu.analysis.simlint import ModuleContext
    path = os.path.join(REPO, "shadow_tpu", "parallel", "procs.py")
    with open(path, encoding="utf-8") as f:
        ctx = ModuleContext("shadow_tpu/parallel/procs.py", f.read())
    rule = ShardProtocolRule()
    findings = rule.check_module(ctx, "ProcsController.run", "_shard_main")
    assert findings == [], "\n".join(f.render() for f in findings)


# ---------------------------------------------------------------------------
# cross-tool pragma ownership


def test_simlint_ignores_simrace_pragmas_and_vice_versa():
    # a SIM102 pragma is not simlint's business: neither a suppression
    # nor a stale-pragma SIM000 there — and the reverse for simrace
    src = """
        import threading

        def guarded(handle):
            box = {}

            def _work():
                box["out"] = handle  # simlint: disable=SIM102 -- joined

            th = threading.Thread(target=_work)
            th.start()
            th.join(1.0)
            return box.get("out")
    """
    assert lint_source(textwrap.dedent(src)) == []        # simlint: silent
    out = _race(src)
    assert _rules_of(out) == []                           # simrace: used
    assert [f.rule for f in out if f.suppressed] == ["SIM102"]
    # reverse: a SIM005 pragma on a real SIM005 finding is invisible to
    # simrace (no stale SIM000), owned by simlint
    src2 = """
        import time as _wt

        def stall():
            _wt.sleep(1.0)  # simlint: disable=SIM005 -- fault harness
    """
    assert _race(src2) == []
    assert _rules_of(lint_source(textwrap.dedent(src2))) == []


def test_stale_simrace_pragma_is_sim000():
    out = _race("""
        x = 1  # simlint: disable=SIM103 -- nothing here anymore
    """)
    assert _rules_of(out) == ["SIM000"]
    assert "matched no finding" in out[0].message


def test_unknown_rule_pragma_flagged_by_simrace_too():
    out = _race("""
        x = 1  # simlint: disable=SIM999 -- no such rule
    """)
    assert _rules_of(out) == ["SIM000"]


# ---------------------------------------------------------------------------
# allowlist + unparsable files


def test_allowlist_exempts_by_rule_and_path():
    cfg = Config(allow={"SIM103": ["shadow_tpu/legacy/*"]})
    src = """
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()

            def f(self, conn):
                with self._lock:
                    return conn.recv()
    """
    assert _race(src, "shadow_tpu/legacy/old.py", cfg) == []
    assert _rules_of(_race(src, "shadow_tpu/core/hot.py", cfg)) \
        == ["SIM103"]


def test_unparsable_file_is_a_finding_not_a_crash():
    out = race_sources({"shadow_tpu/bad.py": "def f(:\n"})
    assert [f.rule for f in out] == ["SIM000"]
    assert "parse" in out[0].message


# ---------------------------------------------------------------------------
# --diff mode (shared with simlint) + make lint wiring


def _git(cwd, *args):
    return subprocess.run(
        ["git", "-c", "user.email=t@t", "-c", "user.name=t"] + list(args),
        cwd=cwd, capture_output=True, text=True, timeout=60)


def test_diff_mode_lints_only_changed_files(tmp_path):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "clean.py").write_text("import time\nx = time.monotonic()\n")
    (pkg / "other.py").write_text("y = 1\n")
    assert _git(tmp_path, "init", "-q").returncode == 0
    assert _git(tmp_path, "add", "-A").returncode == 0
    assert _git(tmp_path, "commit", "-qm", "base").returncode == 0
    # change only other.py (introducing a finding in BOTH files' terms:
    # clean.py already has one, but it is NOT part of the diff)
    (pkg / "other.py").write_text("import time\ny = time.monotonic()\n")
    full = subprocess.run(
        [sys.executable, "-m", "shadow_tpu.analysis.simlint",
         str(pkg), "--json"],
        capture_output=True, text=True, cwd=REPO, timeout=120)
    doc = json.loads(full.stdout)
    assert doc["summary"]["findings"] == 2
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    diffed = subprocess.run(
        [sys.executable, "-m", "shadow_tpu.analysis.simlint",
         str(pkg), "--json", "--diff", "HEAD",
         "--config", str(tmp_path / "pyproject.toml")],
        capture_output=True, text=True, cwd=tmp_path, env=env,
        timeout=120)
    doc = json.loads(diffed.stdout)
    assert doc["summary"]["findings"] == 1
    (f,) = doc["findings"]
    assert f["path"].endswith("other.py")


def test_diff_mode_rebases_paths_when_root_is_nested(tmp_path):
    # pyproject/config root nested inside the git toplevel: `git diff`
    # prints toplevel-relative paths, which must be re-based onto the
    # root before intersecting with the lint set
    sub = tmp_path / "sub"
    (sub / "pkg").mkdir(parents=True)
    (sub / "pkg" / "mod.py").write_text("x = 1\n")
    (tmp_path / "outside.py").write_text("y = 1\n")
    assert _git(tmp_path, "init", "-q").returncode == 0
    assert _git(tmp_path, "add", "-A").returncode == 0
    assert _git(tmp_path, "commit", "-qm", "base").returncode == 0
    (sub / "pkg" / "mod.py").write_text("import time\nx = time.time()\n")
    (tmp_path / "outside.py").write_text("import time\ny = time.time()\n")
    from shadow_tpu.analysis.simlint import changed_py_files
    changed = changed_py_files("HEAD", str(sub))
    assert "pkg/mod.py" in changed
    assert not any(p.startswith("outside") or p.startswith("sub/")
                   for p in changed)


def test_diff_mode_bad_ref_is_usage_error(tmp_path):
    run = subprocess.run(
        [sys.executable, "-m", "shadow_tpu.analysis.simrace",
         "shadow_tpu", "--diff", "no-such-ref-xyz"],
        capture_output=True, text=True, cwd=REPO, timeout=120)
    assert run.returncode == 2
    assert "--diff" in run.stderr


def test_make_lint_target_exists():
    with open(os.path.join(REPO, "Makefile"), encoding="utf-8") as f:
        text = f.read()
    assert "lint:" in text and "simrace" in text and "simlint" in text


# ---------------------------------------------------------------------------
# JSON schema + CLI round trip


def test_json_schema_and_cli_roundtrip(tmp_path):
    mod = tmp_path / "mod.py"
    mod.write_text(textwrap.dedent("""
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()

            def ok(self, conn):
                with self._lock:
                    x = conn.recv_bytes()  # simlint: disable=SIM103 -- t
                return x

            def bad(self, conn):
                with self._lock:
                    return conn.recv()
    """))
    run = subprocess.run(
        [sys.executable, "-m", "shadow_tpu.analysis.simrace",
         str(mod), "--json"],
        capture_output=True, text=True, cwd=REPO, timeout=120)
    assert run.returncode == 1, run.stderr
    doc = json.loads(run.stdout)
    assert doc["version"] == 1 and doc["tool"] == "simrace"
    assert doc["files"] == 1
    assert doc["summary"]["findings"] == 1
    assert doc["summary"]["suppressed"] == 1
    assert doc["summary"]["by_rule"] == {"SIM103": 1}
    (f,) = doc["findings"]
    assert set(f) == {"rule", "severity", "path", "line", "col", "message"}
    assert f["rule"] == "SIM103" and f["severity"] == "warning"


def test_cli_exit_codes(tmp_path):
    clean = tmp_path / "clean.py"
    clean.write_text("x = 1\n")
    ok = subprocess.run(
        [sys.executable, "-m", "shadow_tpu.analysis.simrace", str(clean)],
        capture_output=True, text=True, cwd=REPO, timeout=120)
    assert ok.returncode == 0
    missing = subprocess.run(
        [sys.executable, "-m", "shadow_tpu.analysis.simrace",
         str(tmp_path / "nope.py")],
        capture_output=True, text=True, cwd=REPO, timeout=120)
    assert missing.returncode == 2
    rules = subprocess.run(
        [sys.executable, "-m", "shadow_tpu.analysis.simrace",
         "--list-rules"],
        capture_output=True, text=True, cwd=REPO, timeout=120)
    assert rules.returncode == 0
    for rid in ("SIM101", "SIM102", "SIM103", "SIM110"):
        assert rid in rules.stdout


# ---------------------------------------------------------------------------
# THE GATE: zero unsuppressed findings over the whole package


def test_gate_zero_findings_over_shadow_tpu():
    """Every concurrency violation in shadow_tpu/ is fixed or justified.

    The package-wide analog of simlint's gate: a future PR adding a lock
    edge that completes an inversion, a helper thread sharing unlocked
    state, a blocking call under a lock, or a shard-protocol tag without
    a peer handler fails HERE with the file:line, and the only ways out
    are to fix it or to justify it with a reasoned pragma in the diff."""
    result = race_paths([os.path.join(REPO, "shadow_tpu")],
                        load_config(os.path.join(REPO, "pyproject.toml")))
    assert result.files > 50, "package discovery looks broken"
    pretty = "\n".join(f.render() for f in result.unsuppressed)
    assert not result.unsuppressed, (
        f"simrace found unsuppressed violations:\n{pretty}\n"
        "fix them, or justify with "
        "`# simlint: disable=<RULE> -- <why>`")
    for f in result.suppressed:
        assert f.reason, f"reasonless suppression survived: {f.render()}"


def test_gate_cli_matches_api():
    run = subprocess.run(
        [sys.executable, "-m", "shadow_tpu.analysis.simrace",
         "shadow_tpu", "--json"],
        capture_output=True, text=True, cwd=REPO, timeout=300)
    assert run.returncode == 0, run.stdout + run.stderr
    doc = json.loads(run.stdout)
    assert doc["findings"] == []
