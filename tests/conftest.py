"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

Must run before any jax import — pytest imports conftest first.  Benchmarks
(bench.py) do NOT go through here and use the real TPU.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"  # override any axon/tpu default
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if "PALLAS_AXON_POOL_IPS" in os.environ:
    # an accelerator plugin was registered at interpreter start; a dead
    # device tunnel would hang the whole suite at the first jax use, so
    # scrub it (gated on the trigger var: normal dev runs skip the jax
    # import cost entirely)
    from shadow_tpu.utils.cpu_only import force_cpu_backend

    force_cpu_backend()
    # spawned children (parallel/procs.py shards, pool helpers) re-run
    # sitecustomize; make sure they inherit the cpu pin rather than
    # re-trigger accelerator registration mid-test
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
