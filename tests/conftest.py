"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

Must run before any jax import — pytest imports conftest first.  Benchmarks
(bench.py) do NOT go through here and use the real TPU.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"  # override any axon/tpu default
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
